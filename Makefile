# Convenience targets over the CI script and benchmark suite.
# The analog of the reference's `bazel test //...` entry point
# (/root/reference/.bazelci/presubmit.yml); ci.sh holds the tier logic.

.PHONY: lint test slow smoke device ci bench headline watch measure

lint:            ## static analysis: AST-enforced repo invariants (tools/dpflint)
	./ci.sh lint

test:            ## fast tier: dpflint + default pytest suite (CPU, virtual 8-device mesh)
	./ci.sh fast

slow:            ## weekly tier: full suite incl. --runslow parametrizations
	./ci.sh slow

smoke:           ## application smokes: experiments CLI + both demos
	./ci.sh smoke

device:          ## on-chip differential checks (requires a reachable TPU)
	./ci.sh device

ci: test smoke   ## what presubmit runs

bench:           ## full benchmark suite -> benchmarks/results.json
	python benchmarks/run_all.py

headline:        ## the driver's headline metric (one JSON line)
	python bench.py

watch:           ## probe the TPU tunnel; fire the measurement session in the first window
	bash tools/tpu_watch.sh

measure:         ## the scripted TPU measurement session (tunnel must be up)
	bash tools/tpu_measure.sh
