"""Headline benchmark: full-domain DPF evaluation throughput.

Config (BASELINE.json headline): single-hierarchy DPF, log-domain 20, uint64
values, 1024-key batch, full-domain evaluation on one TPU chip. Metric is
evaluations/second = keys x domain points / wall time, measured the way
BM_EvaluateRegularDpf measures full expansions
(/root/reference/dpf/distributed_point_function_benchmark.cc:29-82).

Baseline derivation (BASELINE.md / SURVEY.md §6): the reference's
single-thread AES-NI full-domain expansion sustains ~40M level-AES ops/s; a
full-domain expansion of 2^20 leaves costs ~2*2^20 tree-AES + 2^20 value-AES
≈ 3*2^20 AES, i.e. ~13M leaf evaluations/s/core. vs_baseline is measured
against that 13e6 evals/s anchor.

Robustness contract (this script must NEVER crash without output): exactly
one JSON line is always printed to stdout --
  {"metric": ..., "value": N, "unit": "evals/s", "vs_baseline": N, ...}
with an "error" field when something went wrong. The TPU backend is probed
in a subprocess with a timeout first; if unreachable, the benchmark falls
back to a CPU run on a reduced config (value is then a real CPU measurement,
flagged by "platform": "cpu"). Platform selection happens *in-process* via
jax.config -- env-var platform forcing deadlocks under this image's
sitecustomize.
"""

import calendar
import faulthandler
import json
import os
import re
import subprocess
import sys
import threading
import time
import traceback


def _start_watchdog() -> None:
    """A hung device call is diagnosable: dump all thread stacks to stderr
    periodically so a stuck run shows where it is waiting.

    The interval scales with the ACTIVE subprocess budget (the parent
    exports BENCH_WATCHDOG_BUDGET = the attempt timeout it will kill this
    child at; standalone runs fall back to BENCH_TPU_TIMEOUT): the old
    fixed dump_traceback_later(600) fired twice inside a 900 s budget and
    its bare "Timeout (0:10:00)!" headers read as failures in the logs
    (BENCH_r04.json tail). Dumps are banner-prefixed as "periodic
    watchdog, not a timeout".

    The dump itself stays on faulthandler.dump_traceback_later — its C
    watchdog thread needs no GIL, so stacks still appear when a native
    device call hangs WHILE holding the GIL (the exact failure this
    diagnostic exists for). The banner rides a best-effort Python thread
    that wakes just before each dump; when the GIL is wedged the banner
    is missing but the startup notice below still explains the bare
    "Timeout" headers."""
    try:
        budget = float(os.environ.get("BENCH_WATCHDOG_BUDGET", "") or 0)
    except ValueError:
        budget = 0.0
    if budget <= 0:
        try:
            budget = float(os.environ.get("BENCH_TPU_TIMEOUT", 1500))
        except ValueError:
            budget = 1500.0
    interval = max(600.0, 0.75 * budget)
    print(
        f"# [watchdog] periodic stack dumps every {interval:.0f}s (scaled "
        f"to the {budget:.0f}s subprocess budget); any 'Timeout "
        "(h:mm:ss)!' stack dump below is the periodic watchdog, NOT a "
        "timeout — the run continues",
        file=sys.stderr,
        flush=True,
    )
    faulthandler.dump_traceback_later(interval, repeat=True, file=sys.stderr)

    def banner():
        n = 0
        while True:
            # Wake ~2 s before each C-side dump so the banner precedes it.
            time.sleep(max(1.0, interval - 2.0) if n == 0 else interval)
            n += 1
            print(
                f"# [watchdog] periodic stack dump #{n} due in ~2s — "
                "periodic watchdog, NOT a timeout; the run continues "
                f"(subprocess budget {budget:.0f}s)",
                file=sys.stderr,
                flush=True,
            )

    threading.Thread(target=banner, daemon=True, name="bench-watchdog").start()


_start_watchdog()

# Process start, for in-child budget accounting (the pipeline A/B skips
# itself when the remaining killable-subprocess budget could not absorb a
# second timed pass).
_START_TIME = time.time()

import numpy as np

BASELINE_EVALS_PER_SEC = 13e6

LOG_DOMAIN = int(os.environ.get("BENCH_LOG_DOMAIN", 20))
NUM_KEYS = int(os.environ.get("BENCH_KEYS", 1024))
# Device chunk. mode="fold": sized to HBM (the [chunk, domain, lpe] value
# buffer lives inside one program) — 128 keys at the default log-domain 20,
# the measured optimum. Other modes emit full values, where the tunnel's
# ~117 MB output threshold binds instead (14 keys at log-domain 20).
_FOLD_CHUNK = max(1, (128 << 20) >> LOG_DOMAIN)
_VALUES_CHUNK = max(1, (14 << 20) >> LOG_DOMAIN)
KEY_CHUNK = int(
    os.environ.get(
        "BENCH_KEY_CHUNK",
        _FOLD_CHUNK
        if os.environ.get("BENCH_MODE", "fold") in ("fold", "megakernel")
        else _VALUES_CHUNK,
    )
)
# Host-engine chunk (CPU fallback/comparison runs): independent of the
# device knob so CPU numbers stay comparable across device-side retuning.
CPU_KEY_CHUNK = int(os.environ.get("BENCH_CPU_KEY_CHUNK", 64))
# Device execution strategy: "fold" (default; ONE program per chunk that
# materializes every value in HBM behind an optimization_barrier and
# XOR-folds it in-program — output is a tiny [chunk, lpe], so the tunnel's
# large-output miscompute threshold never binds and chunks scale to 128+),
# "megakernel" (ISSUE 3: ONE pallas_call per chunk expanding every level
# inside VMEM slabs with the fold accumulated in-kernel — no per-level HBM
# round trips and no value buffer at all; A/B against fold via
# BENCH_MODE=megakernel / tools/tpu_measure.sh headline_megakernel),
# "fused" (per-chunk program emitting full values, 14-key output cap),
# "levels" (per-level dispatch) or "walk" (root-to-leaf walk per lane).
# Measured on the v5e tunnel 2026-07-31 (PERF.md): fold 63.8 M evals/s
# verified at 128-key chunks vs fused 58.2 M at the cap vs walk 19.0 M vs
# levels unverifiable — the device compute ceiling is ~60 M evals/s here
# regardless of dispatch count; fold's win is correctness at any size.
# Host-oracle verification below catches any drift and falls back.
MODE = os.environ.get("BENCH_MODE", "fold")
# CPU fallback config (native AES-NI host engine, ~45 s; shrinks further
# when the native library is unavailable and the numpy oracle must run).
CPU_LOG_DOMAIN = int(os.environ.get("BENCH_CPU_LOG_DOMAIN", 20))
CPU_NUM_KEYS = int(os.environ.get("BENCH_CPU_KEYS", 1024))
CPU_NUM_KEYS_NO_NATIVE = int(os.environ.get("BENCH_CPU_KEYS_NO_NATIVE", 4))
PROBE_TIMEOUT = float(os.environ.get("BENCH_PROBE_TIMEOUT", 120))
PROBE_ATTEMPTS = int(os.environ.get("BENCH_PROBE_ATTEMPTS", 3))
# Where the tunnel watcher (tools/tpu_watch.sh) keeps its probe journal and
# state word; overridable so the dry tests can point at fixtures.
WATCH_DIR = os.environ.get(
    "BENCH_WATCH_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools"),
)


def _log(msg: str) -> None:
    print(f"# {msg}", file=sys.stderr, flush=True)


def _watcher_hint():
    """Reads the tunnel watcher's journal to size this run's device-attempt
    budget (VERDICT r4 #2: the round-4 bench burned 25 minutes probing a
    link whose journal, 20 feet away, showed 65 consecutive failures).

    Returns one of:
      "claimed" — a measurement session holds the TPU claim right now
                  (state word "measuring"): skip probing, arbitrate via
                  the claim lock instead;
      "up"      — the most recent probe (or a completed session) within
                  the journal window answered: skip the probe, spend the
                  full device budget;
      "dead"    — >= BENCH_WATCH_DEAD_MIN probes in the window, ALL
                  failed: clamp the probe to one short attempt and the
                  device subprocess to BENCH_TPU_TIMEOUT_DEAD;
      None      — no watcher / stale journal: configured budgets.

    The journal is advisory — the device attempt itself remains
    unconditional (round-2 lesson); only its *budget* changes.
    """
    if os.environ.get("BENCH_WATCHER_JOURNAL", "1") != "1":
        return None
    now = time.time()
    window = float(os.environ.get("BENCH_WATCH_WINDOW", 1800))
    state_path = os.path.join(WATCH_DIR, "tpu_watch.state")
    try:
        state = open(state_path).read().strip()
    except OSError:
        state = ""
    if state == "measuring":
        if os.environ.get("TPU_CLAIM_HELD") == "1":
            # WE are (inside) the measurement session holding the claim —
            # the tunnel answered minutes ago; go straight to the device
            # attempt at full budget instead of re-probing.
            return "up"
        return "claimed"
    if state == "done":
        try:
            if now - os.path.getmtime(state_path) < window:
                return "up"
        except OSError:
            pass
    try:
        with open(os.path.join(WATCH_DIR, "tpu_watch.log")) as f:
            lines = f.readlines()[-400:]
    except OSError:
        return None
    pat = re.compile(
        r"(\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2})Z attempt=\d+ "
        r"(PROBE OK|probe down|probe skipped)"
    )
    recent = []
    for ln in lines:
        m = pat.match(ln)
        if not m:
            continue
        ts = calendar.timegm(time.strptime(m.group(1), "%Y-%m-%dT%H:%M:%S"))
        if now - ts <= window and m.group(2) != "probe skipped":
            recent.append(m.group(2))
    if recent and recent[-1] == "PROBE OK":
        return "up"
    dead_min = int(os.environ.get("BENCH_WATCH_DEAD_MIN", 3))
    if len(recent) >= dead_min and all(k == "probe down" for k in recent):
        return "dead"
    return None


def _metric(log_domain: int, num_keys: int) -> str:
    return (
        "full-domain DPF evaluations/sec (keys x domain points), "
        f"log_domain={log_domain}, {num_keys}-key batch, uint64"
    )


def _bench_keys(dpf, log_domain: int, num_keys: int, seed: int = 7):
    """The benchmark's key batch — ONE definition so the CPU fallback
    measures exactly the workload the TPU path measures. `seed` varies
    the batch for passes that must not replay identical inputs (the
    2026-07-31 distinct-inputs correction: server-side result caching on
    this tunnel can fake repeat-call timings, PERF.md)."""
    rng = np.random.default_rng(seed)
    alphas = [int(x) for x in rng.integers(0, 1 << log_domain, size=num_keys)]
    betas = [int(x) for x in rng.integers(1, 1 << 63, size=num_keys)]
    t0 = time.time()
    keys, _ = dpf.generate_keys_batch(alphas, [betas])
    _log(f"keygen: {time.time() - t0:.2f}s for {num_keys} keys")
    return keys


def _result(log_domain: int, num_keys: int, evals_per_sec: float, platform: str) -> dict:
    return {
        "metric": _metric(log_domain, num_keys),
        "value": round(evals_per_sec),
        "unit": "evals/s",
        "vs_baseline": round(evals_per_sec / BASELINE_EVALS_PER_SEC, 2),
        "platform": platform,
    }


def _probe_default_backend_retrying(timeout: float, attempts: int):
    """Retried backend probe: a transient tunnel stall at snapshot time must
    not erase the round's TPU evidence (it did in round 2 — BENCH_r02.json
    recorded the CPU fallback off ONE failed probe). Each retry raises the
    timeout (t, 1.5t, 2t, ...); the probe is an optimization, not a gate —
    the caller attempts the device run even when every probe fails."""
    for i in range(max(1, attempts)):
        t = timeout * (1 + 0.5 * i)
        platform = _probe_default_backend(t)
        if platform is not None:
            return platform
        if i + 1 < attempts:
            _log(f"probe attempt {i + 1}/{attempts} failed; retrying")
    return None


def _probe_default_backend(timeout: float):
    """Checks in a subprocess (killable on hang) that the default JAX
    backend initializes. Returns its platform name or None. Same
    process-group kill as _run_device_subprocess: the tunnel runtime may
    spawn helpers that would keep the pipes open past the child's death."""
    code = "import jax; print(jax.default_backend())"
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        start_new_session=True,
    )
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        import signal

        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        try:
            proc.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            pass
        _log(f"backend probe timed out after {timeout:.0f}s")
        return None
    if proc.returncode != 0:
        _log(f"backend probe failed rc={proc.returncode}: {stderr.strip()[-400:]}")
        return None
    return stdout.strip().splitlines()[-1] if stdout.strip() else None


def _init_jax(platform):
    """In-process platform selection + persistent compilation cache."""
    import jax

    if platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
    try:
        cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # cache is an optimization, never fatal
        _log(f"compilation cache unavailable: {e!r}")
    return jax


def _run(
    platform: str, log_domain: int, num_keys: int, key_chunk: int, reps: int = 1
) -> dict:
    jax = _init_jax(platform)
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from distributed_point_functions_tpu.core.dpf import DistributedPointFunction
    from distributed_point_functions_tpu.core.params import DpfParameters
    from distributed_point_functions_tpu.core.value_types import Int
    from distributed_point_functions_tpu.ops import evaluator

    backend = jax.default_backend()
    _log(f"platform: {backend}, devices: {jax.devices()}")

    if backend == "cpu" and platform == "default":
        # Probe-failure device attempt that resolved to a CPU backend: NOT
        # a device measurement. Error out so the parent falls back on ITS
        # side of the killable window — accepting this run would label CPU
        # numbers as device-verified and run the big CPU config under
        # BENCH_TPU_TIMEOUT's kill.
        result = _result(log_domain, num_keys, 0, "cpu")
        result["error"] = (
            "default backend resolved to cpu in the device-attempt child"
        )
        return result
    if backend == "cpu":
        # On a CPU-only host the honest engine is the native AES-NI host
        # path (the XLA bitslice exists for the TPU's sake and would measure
        # portability overhead, not the framework — PERF.md).
        return _run_cpu_host_engine(log_domain, num_keys, key_chunk, reps=reps)

    dpf = DistributedPointFunction.create(DpfParameters(log_domain, Int(64)))
    keys = _bench_keys(dpf, log_domain, num_keys)

    import jax.numpy as jnp

    # The timed quantity is device-resident full-domain evaluation: every
    # output value is materialized in HBM (where on-device consumers — PIR
    # inner products, histogram aggregation — read it), with an XOR fold per
    # chunk both forcing materialization and standing in for that consumer.
    # Pulling 8 GB of outputs to the host over this chip's tunnel runs at
    # ~5 MB/s and would measure the link, not the framework (PERF.md).
    def run_once(key_subset, chunk, verbose=False, pipeline=None):
        folds = []
        total_valid = 0
        if MODE in ("fold", "megakernel"):
            gen = evaluator.full_domain_fold_chunks(
                dpf, key_subset, key_chunk=chunk, pipeline=pipeline,
                mode=MODE,
            )
        else:
            gen = (
                (valid, jnp.bitwise_xor.reduce(out, axis=1))
                for valid, out in evaluator.full_domain_evaluate_chunks(
                    dpf, key_subset, key_chunk=chunk, mode=MODE,
                    pipeline=pipeline,
                )
            )
        for valid, fold in gen:
            total_valid += valid
            folds.append(fold)  # [chunk, lpe]
            if verbose:
                jax.block_until_ready(folds[-1])
                _log(f"chunk {len(folds)} done ({time.time() - t0:.1f}s)")
        # Pull every fold to the host ([chunk, lpe] each — tiny): the timed
        # quantity must include real execution. block_until_ready alone has
        # proven unreliable through this image's TPU tunnel (PERF.md:
        # "Trust, but verify").
        folds = [np.asarray(f) for f in folds]
        assert total_valid == len(key_subset), (total_valid, len(key_subset))
        return folds

    t0 = time.time()
    run_once(keys[:key_chunk], key_chunk, verbose=True)
    _log(f"warmup (compile + first chunk): {time.time() - t0:.1f}s")

    from distributed_point_functions_tpu.utils import profiling, telemetry

    t0 = time.time()
    # Telemetry capture around the PRIMARY timed pass (ISSUE 6): the
    # record gains the measured chunk dispatch count, per-stage busy
    # times, the library-computed pipeline_occupancy, and dispatch-
    # latency percentiles — the cost-model router's inputs — at zero
    # added device programs (host-side perf_counter arithmetic only;
    # pinned by tests/test_dispatch_audit.py).
    with profiling.trace(), telemetry.capture() as tel:
        # set DPF_TPU_PROFILE_DIR to capture a Perfetto trace
        folds = run_once(keys, key_chunk)
    elapsed = time.time() - t0
    tel_snap = tel.snapshot()
    for line in telemetry.summary(tel_snap).splitlines():
        _log(line)

    total_evals = num_keys * (1 << log_domain)
    evals_per_sec = total_evals / elapsed
    _log(f"{total_evals} evals in {elapsed:.2f}s on {backend} (device-resident)")

    # Pipeline on/off A/B (ISSUE 2): the primary number above runs at the
    # platform default (pipelined executor ON for device backends); a
    # second timed pass with the executor forced OFF quantifies how much
    # wall clock the chunk overlap actually hides on this link. Same
    # compiled programs, same keys — only the execution schedule differs.
    # The A/B is context, never the measurement: it only runs when the
    # remaining killable-subprocess budget (BENCH_TPU_TIMEOUT kills this
    # child from the parent) can absorb a sync pass at 2x the pipelined
    # time with slack — otherwise the watchdog would kill the child before
    # it prints, losing the PRIMARY verified number along with the A/B.
    sync_elapsed = None
    if os.environ.get("BENCH_PIPELINE_AB", "1") == "1":
        budget = float(os.environ.get("BENCH_TPU_TIMEOUT", 1500))
        spent = time.time() - _START_TIME
        if spent + 2 * elapsed > 0.7 * budget:
            _log(
                f"pipeline A/B skipped: {spent:.0f}s spent of {budget:.0f}s "
                f"budget; a ~{2 * elapsed:.0f}s sync pass could cost the "
                "primary record"
            )
        else:
            try:
                # DISTINCT inputs for the second pass (fresh seed): replaying
                # the identical key batch is the repeat-call pattern whose
                # timings this tunnel's server-side caching has faked before
                # (PERF.md 2026-07-31 correction). Keygen runs outside the
                # timed window; same count/domain = same workload shape.
                keys_sync = _bench_keys(dpf, log_domain, num_keys, seed=13)
                t0 = time.time()
                run_once(keys_sync, key_chunk, pipeline=False)
                sync_elapsed = time.time() - t0
                _log(
                    f"pipeline A/B: sync {sync_elapsed:.2f}s vs pipelined "
                    f"{elapsed:.2f}s (overlap {sync_elapsed / elapsed:.2f}x)"
                )
            except Exception as e:
                _log(f"pipeline A/B unavailable: {e!r}")

    # Verify the device outputs against the native host oracle on a sample
    # of keys — the whole number is worthless if the chip (or the tunnel
    # runtime) mis-executed the program, and that HAS been observed on this
    # image (upper-lane corruption at 64-key multi-level batches, PERF.md).
    from distributed_point_functions_tpu.core.host_eval import (
        full_domain_evaluate_host,
    )

    fold_rows = np.concatenate(folds, axis=0)[:num_keys]
    sample = list(range(0, num_keys, max(1, num_keys // 8)))[:8]
    host_vals = full_domain_evaluate_host(dpf, [keys[i] for i in sample])
    host_folds = np.bitwise_xor.reduce(host_vals, axis=1)
    got = fold_rows[sample]
    got64 = got[:, 0].astype(np.uint64) | (got[:, 1].astype(np.uint64) << np.uint64(32))
    n_ok = int((got64 == host_folds).sum())
    verified = n_ok == len(sample)
    _log(f"device-vs-host verification: {n_ok}/{len(sample)} sampled keys match")
    result = _result(log_domain, num_keys, evals_per_sec, backend)
    result["verified_keys"] = f"{n_ok}/{len(sample)}"
    result.update(telemetry.bench_fields(tel_snap))
    if sync_elapsed is not None:
        # pipeline_overlap = sync wall-clock / pipelined wall-clock: > 1
        # means the executor hides real latency; ~1 means this link's
        # dispatch already overlapped (or the run is compute-bound).
        result["pipeline_overlap"] = round(sync_elapsed / elapsed, 3)
        result["sync_evals_per_sec"] = round(total_evals / sync_elapsed)
    if verified:
        # Roofline accounting (VERDICT r4 #4): relate the measured rate to
        # what this chip's VPU can do on the bitsliced AES circuit. Trace-
        # only arithmetic — no extra device programs.
        try:
            from distributed_point_functions_tpu.utils.roofline import (
                hbm_fields,
                mfu_fields,
            )

            result.update(mfu_fields(evals_per_sec, log_domain))
            # HBM-bandwidth roofline next to the VPU one (ISSUE 3): which
            # wall this record sits against, per the strategy's traffic
            # model (megakernel leaves ~nothing on HBM; the doubling
            # strategies round-trip planes + values per level). "walk"
            # (every leaf lane walks its root-to-leaf path) uses the
            # point-walk traffic model (ISSUE 4): per-level plane round
            # trips at full width, one leaf capture.
            if MODE in ("levels", "fused", "fold", "megakernel"):
                result.update(
                    hbm_fields(evals_per_sec, log_domain, strategy=MODE)
                )
            elif MODE == "walk":
                from distributed_point_functions_tpu.utils.roofline import (
                    walk_hbm_fields,
                )

                # The model is per WALK (lane): the full-domain walk runs
                # hierarchy_to_tree[-1] tree levels and each lane yields
                # keep elements (2 for Int(64)), so convert the
                # element-eval rate to walks/s — same units as the
                # evaluate_at/dcf walk records (bench_evaluate_at.py).
                tree_levels = dpf.validator.hierarchy_to_tree[-1]
                keep = 1 << (log_domain - tree_levels)
                result.update(
                    walk_hbm_fields(
                        evals_per_sec / keep, tree_levels, "walk",
                        captures=1,
                    )
                )
            _log(
                f"roofline: mfu_estimate={result.get('mfu_estimate')} "
                f"binding_wall={result.get('binding_wall')} "
                f"({result.get('mfu_detail', '')})"
            )
        except Exception as e:
            _log(f"mfu estimate unavailable: {e!r}")
    if not verified:
        # Report the failure and quarantine the meaningless rate; the CPU
        # fallback is the PARENT's job — running it here, inside the
        # killable device subprocess, could blow BENCH_TPU_TIMEOUT and
        # discard this diagnosis along with it.
        result["value"] = 0
        result["vs_baseline"] = 0
        result["device_unverified_evals_per_sec"] = round(evals_per_sec)
        result["error"] = (
            "device outputs FAILED host-oracle verification on sampled "
            "keys; the quarantined rate measures a miscomputing program"
        )
        _log(result["error"])
    return result


def _latest_onchip_headline():
    """Most recent dated device-platform full_domain_headline record from
    benchmarks/results.json, reduced to its load-bearing fields — attached
    to CPU-fallback output as context (never as the measurement)."""
    try:
        path = os.environ.get("BENCH_RESULTS_PATH") or os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "benchmarks",
            "results.json",
        )
        with open(path) as f:
            records = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    best = None
    for r in records:
        if not isinstance(r, dict) or "error" in r:
            continue
        platform = r.get("platform") or ""
        # The PRIMARY headline slot only (plus its cross-platform rename,
        # run_all's "<bench>@<platform>") — RECORD_SUFFIX A/B variants
        # (e.g. the fused last-hash headline) are not "the" headline.
        if r.get("bench") not in (
            "full_domain_headline",
            f"full_domain_headline@{platform}",
        ):
            continue
        if platform.startswith("cpu") or not platform:
            continue
        if best is None or str(r.get("date", "")) > str(best.get("date", "")):
            best = r
    if best is None:
        return None
    out = {
        k: best[k]
        for k in ("bench", "value", "unit", "platform", "date", "caveat")
        if k in best
    }
    config = best.get("config")
    vs = (
        config.get("vs_baseline") if isinstance(config, dict) else None
    ) or best.get("vs_baseline")
    if vs is not None:
        out["vs_baseline"] = vs
    return out


def _run_cpu_host_engine(
    log_domain: int, num_keys: int, key_chunk: int, reps: int = 1
) -> dict:
    """CPU fallback: the vectorized native-AES host engine (core/host_eval).

    `reps` > 1 measures the workload that many times and reports the BEST
    rate (VERDICT r4 weak #7: the shared-vCPU box's tenant load makes one
    cold rep vary 1.5-2x between rounds — 48.8 vs 69.2 M evals/s for the
    identical engine; best-of-N recovers the machine's actual capability
    and the per-rep rates are kept in the record for variance visibility).
    """
    from distributed_point_functions_tpu import native
    from distributed_point_functions_tpu.core.dpf import DistributedPointFunction
    from distributed_point_functions_tpu.core.host_eval import (
        full_domain_evaluate_host,
    )
    from distributed_point_functions_tpu.core.params import DpfParameters
    from distributed_point_functions_tpu.core.value_types import Int

    if not native.available():
        # Pure-numpy AES is ~95x slower; shrink so the bench still
        # finishes, and never repeat it — one rep is already minutes.
        num_keys = min(num_keys, CPU_NUM_KEYS_NO_NATIVE)
        reps = 1
        _log(f"native AES-NI engine unavailable; numpy oracle, {num_keys} keys")
    dpf = DistributedPointFunction.create(DpfParameters(log_domain, Int(64)))
    keys = _bench_keys(dpf, log_domain, num_keys)
    # Evaluate in key blocks and fold each block into a checksum — the
    # consumer-in-the-loop shape the TPU bench uses (outputs materialized,
    # then reduced); retaining all 8 GB instead just measures page faults.
    block = int(os.environ.get("BENCH_CPU_BLOCK", 64))
    total_evals = num_keys * (1 << log_domain)
    rates = []
    for rep in range(max(1, reps)):
        t0 = time.time()
        folds = []
        for i in range(0, num_keys, block):
            out = full_domain_evaluate_host(
                dpf, keys[i : i + block], key_chunk=key_chunk
            )
            folds.append(np.bitwise_xor.reduce(out, axis=1))
        elapsed = time.time() - t0
        assert sum(f.shape[0] for f in folds) == num_keys
        rates.append(total_evals / elapsed)
        _log(
            f"rep {rep + 1}/{reps}: {total_evals} evals in {elapsed:.2f}s "
            "on the host engine"
        )
    result = _result(log_domain, num_keys, max(rates), "cpu-host-engine")
    if len(rates) > 1:
        result["cpu_rep_evals_per_sec"] = [round(r) for r in rates]
    return result


def _run_device_subprocess(platform: str, timeout: float):
    """Runs the device benchmark in a KILLABLE subprocess.

    The axon tunnel has been observed hanging not just at backend init (the
    probe covers that) but at arbitrary points mid-run — an in-process hang
    would eat the driver's whole time budget and lose the round's artifact
    (the round-1 failure mode). The child runs `_run(platform, ...)` and
    prints one JSON line; on timeout its whole process GROUP is killed
    (the TPU runtime may spawn helpers that would otherwise keep the pipes
    open) and the caller falls back to the CPU engine. Returns the parsed
    result dict or None.
    """
    env = dict(os.environ)
    env["BENCH_INNER"] = "1"
    env["BENCH_PLATFORM"] = platform
    # The child's periodic-stack-dump watchdog scales to the budget this
    # parent will actually kill it at (see _start_watchdog).
    env["BENCH_WATCHDOG_BUDGET"] = str(timeout)
    # The parent holds the TPU claim across this attempt; the child (and
    # anything it spawns) must not re-acquire it against its own parent.
    env["TPU_CLAIM_HELD"] = "1"
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=env,
        start_new_session=True,  # own process group: killpg reaps helpers
    )
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired as e:
        import signal

        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        try:
            stdout, stderr = proc.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            stdout, stderr = "", ""
        # Keep the child's diagnostics (faulthandler stacks, progress logs)
        # — they are the only record of WHERE the device run hung.
        partial = stderr or (
            e.stderr.decode("utf-8", "replace")
            if isinstance(e.stderr, bytes)
            else (e.stderr or "")
        )
        sys.stderr.write(partial[-4000:])
        _log(f"device benchmark subprocess timed out after {timeout:.0f}s")
        return None
    sys.stderr.write((stderr or "")[-4000:])
    if proc.returncode != 0:
        _log(f"device benchmark subprocess rc={proc.returncode}")
        return None
    line = (stdout or "").strip().splitlines()[-1] if (stdout or "").strip() else ""
    try:
        parsed = json.loads(line)
    except (json.JSONDecodeError, ValueError):
        _log(f"device benchmark subprocess bad output: {line[:200]}")
        return None
    # Error results are returned too: they may carry diagnostics worth
    # merging into the fallback record (e.g. the quarantined unverified
    # device rate).
    return parsed if isinstance(parsed, dict) else None


def _run_cpu_comparison_subprocess(timeout: float):
    """Runs the full-size host-engine comparison in a killable subprocess.

    Returns the parsed result dict, or None when it failed, timed out, or
    was skipped (rc=3: native AES-NI library unavailable — the numpy
    oracle would measure a different, shrunken workload)."""
    env = dict(os.environ)
    env["BENCH_INNER"] = "1"
    env["BENCH_PLATFORM"] = "cpu"
    env["BENCH_COMPARE"] = "1"
    env["BENCH_WATCHDOG_BUDGET"] = str(timeout)
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=env,
        start_new_session=True,
    )
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        import signal

        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        try:
            proc.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            pass
        _log(f"host-engine comparison timed out after {timeout:.0f}s; skipped")
        return None
    sys.stderr.write((stderr or "")[-2000:])
    if proc.returncode != 0:
        _log(f"host-engine comparison skipped/failed rc={proc.returncode}")
        return None
    line = (stdout or "").strip().splitlines()[-1] if (stdout or "").strip() else ""
    try:
        parsed = json.loads(line)
    except (json.JSONDecodeError, ValueError):
        return None
    if not isinstance(parsed, dict) or "error" in parsed:
        return None
    return parsed


def main() -> None:
    result = _result(LOG_DOMAIN, NUM_KEYS, 0, "none")
    inner = os.environ.get("BENCH_INNER") == "1"
    cpu_cfg = (CPU_LOG_DOMAIN, CPU_NUM_KEYS, min(CPU_KEY_CHUNK, CPU_NUM_KEYS))
    fallback_reps = int(os.environ.get("BENCH_CPU_REPS", 3))
    try:
        platform = os.environ.get("BENCH_PLATFORM")
        # Watcher-journal budget sizing (VERDICT r4 #2): the probe/device
        # budgets shrink when the watcher has just seen the tunnel
        # continuously dead, and grow to full when it has just seen it up.
        # Children skip this — the parent already sized their budgets.
        hint = _watcher_hint() if (platform is None and not inner) else None
        probe_timeout, probe_attempts = PROBE_TIMEOUT, PROBE_ATTEMPTS
        device_cap = None
        if hint == "dead":
            probe_timeout = min(
                probe_timeout, float(os.environ.get("BENCH_PROBE_TIMEOUT_DEAD", 60))
            )
            probe_attempts = 1
            device_cap = float(os.environ.get("BENCH_TPU_TIMEOUT_DEAD", 300))
            _log(
                "watcher journal: tunnel continuously down in the recent "
                f"window — one short probe, device budget {device_cap:.0f}s "
                "(the attempt itself stays unconditional)"
            )
        elif hint == "up":
            _log("watcher journal: tunnel answered recently — skipping the probe")
        elif hint == "claimed":
            _log(
                "watcher state 'measuring': a measurement session holds the "
                "TPU claim; arbitrating via tools/tpu_claim.lock"
            )
        if inner and platform == "cpu" and os.environ.get("BENCH_COMPARE") == "1":
            # Comparison child: the host engine on the DEVICE config, only
            # meaningful on the native AES-NI engine (rc=3 = skipped).
            from distributed_point_functions_tpu import native

            if not native.available():
                _log("native engine unavailable; comparison skipped")
                sys.exit(3)
            result = _run_cpu_host_engine(
                LOG_DOMAIN, NUM_KEYS, min(CPU_KEY_CHUNK, NUM_KEYS)
            )
            print(json.dumps(result), flush=True)
            return
        if inner and platform != "cpu":
            # Child: device attempt ONLY — fallback is the parent's job
            # (a child-side CPU rerun would just burn the kill timeout).
            try:
                result = _run(platform, LOG_DOMAIN, NUM_KEYS, KEY_CHUNK)
            except Exception as e:
                result["error"] = f"{type(e).__name__}: {e}"
                _log("device run failed:\n" + traceback.format_exc())
            print(json.dumps(result), flush=True)
            return
        if platform != "cpu":
            # Parent: device attempt in a killable subprocess; every CPU
            # run happens HERE, outside the killable window, so a slow
            # comparison can never discard a verified device measurement.
            # The probe and the device attempt run while HOLDING the shared
            # TPU claim (tools/tpu_claim.py): only one process may touch
            # the tunnel, and the watcher's measurement session or its
            # probes must not race this run (VERDICT r4 weak #3).
            sys.path.insert(
                0,
                os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools"),
            )
            from tpu_claim import ClaimUnavailable, hold

            claim_wait = float(
                os.environ.get(
                    "BENCH_CLAIM_WAIT", 600.0 if hint == "claimed" else 90.0
                )
            )
            parsed = None
            claim_failed = None
            try:
                with hold("bench.py", timeout=claim_wait):
                    if platform is None:
                        if hint == "up":
                            # Watcher just saw the tunnel answer: go
                            # straight to the device attempt, full budget.
                            platform = "default"
                        else:
                            platform = _probe_default_backend_retrying(
                                probe_timeout, probe_attempts
                            )
                            if platform is None:
                                # The probe is an optimization, not a gate:
                                # still attempt the device run inside the
                                # killable subprocess.
                                _log(
                                    "backend probe never answered; attempting "
                                    "the device run anyway (killable subprocess)"
                                )
                                platform = "default"
                    configured = float(os.environ.get("BENCH_TPU_TIMEOUT", 1500))
                    if platform != "default" or hint == "up":
                        attempt_timeout = configured
                    else:
                        # Unprobed attempt most likely hangs at backend
                        # init — never exceed an explicitly configured
                        # device budget.
                        attempt_timeout = float(
                            os.environ.get(
                                "BENCH_TPU_TIMEOUT_UNPROBED",
                                min(900.0, configured),
                            )
                        )
                    if device_cap is not None:
                        attempt_timeout = min(attempt_timeout, device_cap)
                    parsed = _run_device_subprocess(platform, attempt_timeout)
            except ClaimUnavailable as e:
                claim_failed = str(e)
                _log(
                    f"TPU claim unavailable after {claim_wait:.0f}s ({e}); "
                    "CPU host-engine fallback — the holder's on-chip records "
                    "land in benchmarks/results.json"
                )
            if parsed is not None and "error" not in parsed:
                result = parsed
                # The framework also ships the native AES-NI host engine
                # for this exact workload (no JAX, no TPU-claim
                # contention); report whichever engine is faster on this
                # box, keeping the other in a side field. On this image
                # the verified device rate is capped by the tunnel's
                # ~16M-leaf miscompute threshold + ~66 ms dispatch
                # latency (PERF.md), so the 1-core VAES engine can win.
                # The comparison runs in its own KILLABLE subprocess with
                # a bounded timeout: a stalled host run must never cost
                # the already-verified device measurement. It is skipped
                # entirely when the native library is absent — the numpy
                # oracle would measure a shrunken different workload under
                # a field name claiming the native engine.
                cpu = _run_cpu_comparison_subprocess(
                    float(os.environ.get("BENCH_CPU_TIMEOUT", 300))
                )
                if cpu is not None:
                    if cpu["value"] > result["value"]:
                        cpu["device_verified_evals_per_sec"] = result["value"]
                        cpu["device_verified_keys"] = result.get("verified_keys")
                        result = cpu
                    else:
                        result["cpu_host_engine_evals_per_sec"] = cpu["value"]
            else:
                if claim_failed is None:
                    _log("device attempt failed; CPU host-engine fallback")
                result = _run("cpu", *cpu_cfg, reps=fallback_reps)
                if claim_failed is not None:
                    result["note"] = f"device attempt skipped: {claim_failed}"
                onchip = _latest_onchip_headline()
                if onchip is not None:
                    # Context, clearly labeled as a PAST record: if the
                    # watcher-fired session captured an on-chip headline
                    # earlier in the round and the tunnel died again before
                    # this run, the driver artifact should still point at
                    # that evidence (benchmarks/results.json holds it).
                    result["last_onchip_headline_record"] = onchip
                if isinstance(parsed, dict):
                    for f in (
                        "device_unverified_evals_per_sec",
                        "verified_keys",
                    ):
                        if f in parsed:
                            result.setdefault(
                                "device_verified_keys"
                                if f == "verified_keys"
                                else f,
                                parsed[f],
                            )
        else:
            result = _run("cpu", *cpu_cfg, reps=fallback_reps)
    except Exception as e:
        result["error"] = (
            f"{type(e).__name__}: {e} (all attempts failed; metric string "
            "describes the intended TPU config, not a completed run)"
        )
        _log("benchmark failed:\n" + traceback.format_exc())
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
