"""Headline benchmark: full-domain DPF evaluation throughput.

Config (BASELINE.json headline): single-hierarchy DPF, log-domain 20, uint64
values, 1024-key batch, full-domain evaluation on one TPU chip. Metric is
evaluations/second = keys x domain points / wall time.

Baseline derivation (BASELINE.md / SURVEY.md §6): the reference's
single-thread AES-NI full-domain expansion sustains ~40M level-AES ops/s; a
full-domain expansion of 2^20 leaves costs ~2*2^20 tree-AES + 2^20 value-AES
≈ 3*2^20 AES, i.e. ~13M leaf evaluations/s/core. vs_baseline is measured
against that 13e6 evals/s anchor.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": "evals/s", "vs_baseline": N}
"""

import json
import os
import sys
import time

import numpy as np

BASELINE_EVALS_PER_SEC = 13e6

LOG_DOMAIN = int(os.environ.get("BENCH_LOG_DOMAIN", 20))
NUM_KEYS = int(os.environ.get("BENCH_KEYS", 1024))
KEY_CHUNK = int(os.environ.get("BENCH_KEY_CHUNK", 64))


def main() -> None:
    import jax

    sys.path.insert(0, ".")
    from distributed_point_functions_tpu.core.dpf import DistributedPointFunction
    from distributed_point_functions_tpu.core.params import DpfParameters
    from distributed_point_functions_tpu.core.value_types import Int
    from distributed_point_functions_tpu.ops import evaluator

    platform = jax.default_backend()
    print(f"# platform: {platform}, devices: {len(jax.devices())}", file=sys.stderr)

    dpf = DistributedPointFunction.create(DpfParameters(LOG_DOMAIN, Int(64)))
    rng = np.random.default_rng(7)
    print("# generating keys...", file=sys.stderr)
    t0 = time.time()
    keys = []
    for i in range(NUM_KEYS):
        alpha = int(rng.integers(0, 1 << LOG_DOMAIN))
        beta = int(rng.integers(1, 1 << 63))
        ka, _ = dpf.generate_keys(alpha, beta)
        keys.append(ka)
    print(f"# keygen: {time.time() - t0:.1f}s for {NUM_KEYS} keys", file=sys.stderr)

    # Warmup/compile on the first chunk.
    t0 = time.time()
    evaluator.full_domain_evaluate(dpf, keys[:KEY_CHUNK], key_chunk=KEY_CHUNK)
    print(f"# warmup (compile + first chunk): {time.time() - t0:.1f}s", file=sys.stderr)

    t0 = time.time()
    out = evaluator.full_domain_evaluate(dpf, keys, key_chunk=KEY_CHUNK)
    elapsed = time.time() - t0
    assert out.shape[0] == NUM_KEYS

    total_evals = NUM_KEYS * (1 << LOG_DOMAIN)
    evals_per_sec = total_evals / elapsed
    print(
        f"# {total_evals} evals in {elapsed:.2f}s on {platform}", file=sys.stderr
    )
    print(
        json.dumps(
            {
                "metric": (
                    "full-domain DPF evaluations/sec (keys x domain points), "
                    f"log_domain={LOG_DOMAIN}, {NUM_KEYS}-key batch, uint64"
                ),
                "value": round(evals_per_sec),
                "unit": "evals/s",
                "vs_baseline": round(evals_per_sec / BASELINE_EVALS_PER_SEC, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
