"""BASELINE config 4: DistributedComparisonFunction batch evaluation —
log-domain 24, 512 keys.

The reference evaluates one x per call in O(n^2) AES
(/root/reference/dcf/distributed_comparison_function_benchmark.cc:24-54 and
.h:83-107); this framework's fused walk does all levels in one O(n) scan,
vmapped over keys x points (dcf/batch.py).
"""

import os

import numpy as np

from common import Timer, log, run_bench


def bench(jax, smoke):
    from distributed_point_functions_tpu.core.value_types import Int
    from distributed_point_functions_tpu.dcf.dcf import (
        DistributedComparisonFunction,
    )
    from distributed_point_functions_tpu.dcf import batch as dcf_batch

    log_domain = int(os.environ.get("BENCH_LOG_DOMAIN", 10 if smoke else 24))
    num_keys = int(os.environ.get("BENCH_KEYS", 8 if smoke else 512))
    num_points = int(os.environ.get("BENCH_POINTS", 32 if smoke else 512))
    reps = int(os.environ.get("BENCH_REPS", 2 if smoke else 5))
    # Default to the native host engine: at 512x512 it measured 1.21 M
    # comparisons/s vs 648 K for the device scan on v5e — per-point work is
    # too small to amortize the device's walk program; the device engine
    # still wins for XOR groups/128-bit values and huge point batches.
    engine = os.environ.get("BENCH_DCF_ENGINE", "host")

    dcf = DistributedComparisonFunction.create(log_domain, Int(64))
    rng = np.random.default_rng(11)
    alphas = [int(a) for a in rng.integers(0, 1 << log_domain, size=num_keys)]
    betas = [int(b) for b in rng.integers(1, 1 << 62, size=num_keys)]
    with Timer() as tk:
        keys, _ = dcf.generate_keys_batch(alphas, betas)
    log(f"keygen: {tk.elapsed:.2f}s for {num_keys} DCF keys (batched)")
    xs = [int(x) for x in rng.integers(0, 1 << log_domain, size=num_points)]

    from distributed_point_functions_tpu import native

    if engine == "host" and not native.available():
        engine = "device"
    run = (
        dcf_batch.batch_evaluate_host if engine == "host"
        else dcf_batch.batch_evaluate
    )
    log(f"engine: {engine}")
    # Distinct point sets per rep + host-pulled outputs: on the device
    # engine, identical repeated programs time as ~0 through this image's
    # tunnel (server-side result caching, PERF.md); harmless on the host.
    xs_sets = [
        [int(x) for x in rng.integers(0, 1 << log_domain, size=num_points)]
        for _ in range(reps)
    ]
    def timed_pull(out):
        """Timing pull: host engine results are host arrays already; the
        device engine's [K, P, lpe] output (2 MB at 512x512) folds to
        [lpe] in a follow-on device program so the timed region measures
        the evaluation, not the ~5 MB/s tunnel link (PERF.md)."""
        if engine == "host":
            return np.asarray(out)
        import jax.numpy as jnp

        return np.asarray(jnp.sum(out, axis=(0, 1)))

    with Timer() as warm:
        out = np.asarray(run(dcf, keys, xs))  # full pull: shape check only
    assert out.shape[:2] == (num_keys, num_points)
    log(f"warmup (compile + run): {warm.elapsed:.1f}s")
    if engine != "host":
        timed_pull(run(dcf, keys, xs))  # warm the fold program
    with Timer() as t:
        for xs_i in xs_sets:
            timed_pull(run(dcf, keys, xs_i))
    evals = num_keys * num_points * reps
    device_rate = None
    if engine == "host" and jax.default_backend() != "cpu":
        # Keep the device scan kernel under benchmark coverage even though
        # the host engine is the headline for this shape. Distinct points
        # per rep: identical repeats time as ~0 through this tunnel.
        import jax.numpy as jnp

        def dev_fold(points):
            return np.asarray(
                jnp.sum(dcf_batch.batch_evaluate(dcf, keys, points), axis=(0, 1))
            )

        xs2 = [int(x) for x in rng.integers(0, 1 << log_domain, size=num_points)]
        with Timer() as wd:
            dev_fold(xs)
        log(f"device engine warmup: {wd.elapsed:.1f}s")
        with Timer() as td:
            dev_fold(xs2)
        device_rate = round(num_keys * num_points / td.elapsed)
        log(f"device engine: {device_rate} comparisons/s")
    return {
        "bench": "dcf_batch",
        "metric": (
            f"DCF BatchEvaluate, {num_keys} keys x {num_points} points, "
            f"log_domain={log_domain}, uint64"
        ),
        "value": round(evals / t.elapsed),
        "unit": "comparisons/s",
        "config": {
            "log_domain": log_domain,
            "num_keys": num_keys,
            "num_points": num_points,
            "engine": engine,
            **(
                {"device_engine_comparisons_per_s": device_rate}
                if device_rate
                else {}
            ),
        },
        **({"platform": "cpu"} if engine == "host" else {}),
    }


if __name__ == "__main__":
    run_bench("dcf_batch", bench)
