"""BASELINE config 4: DistributedComparisonFunction batch evaluation —
log-domain 24, 512 keys.

The reference evaluates one x per call in O(n^2) AES
(/root/reference/dcf/distributed_comparison_function_benchmark.cc:24-54 and
.h:83-107); this framework's fused walk does all levels in one O(n) scan,
vmapped over keys x points (dcf/batch.py).
"""

import os

import numpy as np

from common import Timer, log, run_bench


def bench(jax, smoke):
    from distributed_point_functions_tpu.core.value_types import Int
    from distributed_point_functions_tpu.dcf.dcf import (
        DistributedComparisonFunction,
    )
    from distributed_point_functions_tpu.dcf import batch as dcf_batch

    log_domain = int(os.environ.get("BENCH_LOG_DOMAIN", 10 if smoke else 24))
    num_keys = int(os.environ.get("BENCH_KEYS", 8 if smoke else 512))
    num_points = int(os.environ.get("BENCH_POINTS", 32 if smoke else 512))
    reps = int(os.environ.get("BENCH_REPS", 2 if smoke else 5))
    # Default to the native host engine: at 512x512 it measured 1.21 M
    # comparisons/s vs 648 K for the device scan on v5e — per-point work is
    # too small to amortize the device's walk program; the device engine
    # still wins for XOR groups/128-bit values and huge point batches.
    engine = os.environ.get("BENCH_DCF_ENGINE", "host")
    # "walk" = the shipped per-level device walk; "walkkernel" = the
    # single-program walk megakernel (ISSUE 4). walkkernel is a device
    # strategy, so it forces engine=device (tools/tpu_measure.sh
    # dcf_walkkernel stage records the A/B in its own results.json slot).
    mode = os.environ.get("BENCH_DCF_MODE", "walk")
    if mode == "walkkernel":
        engine = "device"

    dcf = DistributedComparisonFunction.create(log_domain, Int(64))
    rng = np.random.default_rng(11)
    alphas = [int(a) for a in rng.integers(0, 1 << log_domain, size=num_keys)]
    betas = [int(b) for b in rng.integers(1, 1 << 62, size=num_keys)]
    with Timer() as tk:
        keys, _ = dcf.generate_keys_batch(alphas, betas)
    log(f"keygen: {tk.elapsed:.2f}s for {num_keys} DCF keys (batched)")
    xs = [int(x) for x in rng.integers(0, 1 << log_domain, size=num_points)]

    from distributed_point_functions_tpu import native

    if engine == "host" and not native.available():
        engine = "device"
    if engine == "host":
        run = dcf_batch.batch_evaluate_host
    else:
        import functools

        run = functools.partial(dcf_batch.batch_evaluate, mode=mode)
    log(f"engine: {engine} mode: {mode}")
    # Distinct point sets per rep + host-pulled outputs: on the device
    # engine, identical repeated programs time as ~0 through this image's
    # tunnel (server-side result caching, PERF.md); harmless on the host.
    xs_sets = [
        [int(x) for x in rng.integers(0, 1 << log_domain, size=num_points)]
        for _ in range(reps)
    ]
    def timed_pull(out):
        """Timing pull: host engine results are host arrays already; the
        device engine's [K, P, lpe] output (2 MB at 512x512) folds to
        [lpe] in a follow-on device program so the timed region measures
        the evaluation, not the ~5 MB/s tunnel link (PERF.md)."""
        if engine == "host":
            return np.asarray(out)
        import jax.numpy as jnp

        return np.asarray(jnp.sum(out, axis=(0, 1)))

    with Timer() as warm:
        out = np.asarray(run(dcf, keys, xs))  # full pull: shape check only
    assert out.shape[:2] == (num_keys, num_points)
    log(f"warmup (compile + run): {warm.elapsed:.1f}s")
    # Host-oracle spot verification of THE warmed output (4 keys x 8
    # points vs the reference-parity per-point path): the `verified` flag
    # is what lets run_bench_stage.py SUPERSEDES retire a beaten record —
    # an unverified walkkernel number must never supersede anything.
    sample_k = list(range(0, num_keys, max(1, num_keys // 4)))[:4]
    ok = True
    for i in sample_k:
        want = np.array(
            [dcf.evaluate(keys[i], x) for x in xs[:8]], dtype=np.uint64
        )
        if engine == "host":
            got = out[i, :8].astype(np.uint64)
        else:
            from distributed_point_functions_tpu.ops import evaluator

            got = (
                evaluator.values_to_numpy(out[i : i + 1, :8], 64)[0]
                .astype(np.uint64)
            )
        if not np.array_equal(got, want):
            ok = False
    log(
        f"host-oracle spot verification ({len(sample_k)} keys x 8 pts): "
        f"{'OK' if ok else 'MISMATCH'}"
    )
    if engine != "host":
        timed_pull(run(dcf, keys, xs))  # warm the fold program
    # Telemetry capture around the timed loop (ISSUE 6): device-engine
    # records gain dispatch_count / stage times / pipeline_occupancy as
    # provenance fields; the host engine dispatches nothing and gains
    # nothing.
    from distributed_point_functions_tpu.utils import telemetry

    with telemetry.capture() as tel, Timer() as t:
        for xs_i in xs_sets:
            timed_pull(run(dcf, keys, xs_i))
    telemetry_fields = telemetry.bench_fields(tel.snapshot())
    evals = num_keys * num_points * reps
    device_rate = None
    if engine == "host" and jax.default_backend() != "cpu":
        # Keep the device scan kernel under benchmark coverage even though
        # the host engine is the headline for this shape. Distinct points
        # per rep: identical repeats time as ~0 through this tunnel.
        import jax.numpy as jnp

        def dev_fold(points):
            return np.asarray(
                jnp.sum(dcf_batch.batch_evaluate(dcf, keys, points), axis=(0, 1))
            )

        xs2 = [int(x) for x in rng.integers(0, 1 << log_domain, size=num_points)]
        with Timer() as wd:
            dev_fold(xs)
        log(f"device engine warmup: {wd.elapsed:.1f}s")
        with Timer() as td:
            dev_fold(xs2)
        device_rate = round(num_keys * num_points / td.elapsed)
        log(f"device engine: {device_rate} comparisons/s")
    walk_fields = {}
    if engine == "device":
        # Walk traffic model next to the measured rate (per-level walk vs
        # the in-register walk megakernel). The DCF walk runs T tree
        # levels (T = hierarchy_to_tree[-1], log_domain - 2 for Int(64):
        # points are x >> 1 and blocks hold two elements) with a capture
        # at each of the T+1 depths.
        from distributed_point_functions_tpu.utils import roofline

        T = dcf.dpf.validator.hierarchy_to_tree[-1]
        walk_fields = roofline.walk_hbm_fields(
            evals / t.elapsed, T, mode, lpe=2, captures=T + 1,
        )
    return {
        **({} if ok else {
            "error": "device output failed host-oracle spot verification"
        }),
        "bench": "dcf_batch",
        "metric": (
            f"DCF BatchEvaluate, {num_keys} keys x {num_points} points, "
            f"log_domain={log_domain}, uint64"
            + (f", mode={mode}" if engine == "device" else "")
        ),
        "value": round(evals / t.elapsed),
        "unit": "comparisons/s",
        "verified": bool(ok),
        "config": {
            "log_domain": log_domain,
            "num_keys": num_keys,
            "num_points": num_points,
            "engine": engine,
            **({"mode": mode} if engine == "device" else {}),
            **walk_fields,
            **telemetry_fields,
            **(
                {"device_engine_comparisons_per_s": device_rate}
                if device_rate
                else {}
            ),
        },
        **({"platform": "cpu"} if engine == "host" else {}),
    }


if __name__ == "__main__":
    run_bench("dcf_batch", bench)
