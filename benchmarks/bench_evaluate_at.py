"""BASELINE config 2: batched EvaluateAt — 1024 keys x 4096 points each,
log-domain 32, uint64 output.

Methodology of BM_BatchEvaluation
(/root/reference/dpf/distributed_point_function_benchmark.cc:345-402), which
loops EvaluateAt over keys one at a time on CPU; here all keys x points run
as one vmapped device program.
"""

import os

import numpy as np

from common import Timer, log, run_bench


def bench(jax, smoke):
    from distributed_point_functions_tpu.core.dpf import DistributedPointFunction
    from distributed_point_functions_tpu.core.params import DpfParameters
    from distributed_point_functions_tpu.core.value_types import Int
    from distributed_point_functions_tpu.ops import evaluator

    log_domain = int(os.environ.get("BENCH_LOG_DOMAIN", 16 if smoke else 32))
    num_keys = int(os.environ.get("BENCH_KEYS", 16 if smoke else 1024))
    num_points = int(os.environ.get("BENCH_POINTS", 256 if smoke else 4096))
    reps = int(os.environ.get("BENCH_REPS", 2 if smoke else 5))

    dpf = DistributedPointFunction.create(DpfParameters(log_domain, Int(64)))
    rng = np.random.default_rng(5)
    alphas = [int(x) for x in rng.integers(0, 1 << log_domain, size=num_keys)]
    betas = [int(x) for x in rng.integers(1, 1 << 62, size=num_keys)]
    with Timer() as tk:
        keys, _ = dpf.generate_keys_batch(alphas, [betas])
    log(f"keygen: {tk.elapsed:.2f}s for {num_keys} keys")
    points = [int(x) for x in rng.integers(0, 1 << log_domain, size=num_points)]

    def run():
        # device-resident outputs + tiny fold PULLED to the host — block_
        # until_ready alone is not trustworthy timing through this image's
        # tunnel (PERF.md "Platform findings").
        out = evaluator.evaluate_at_batch(dpf, keys, points, device_output=True)
        import jax.numpy as jnp

        return np.asarray(jnp.bitwise_xor.reduce(out, axis=1))

    with Timer() as warm:
        fold = run()
    assert fold.shape[0] == num_keys
    log(f"warmup (compile + run): {warm.elapsed:.1f}s")
    with Timer() as t:
        for _ in range(reps):
            run()
    evals = num_keys * num_points * reps

    # Secondary: the native host engine on the same workload, for the
    # engine-choice record (PERF.md) — the device wins this shape.
    host_rate = None
    from distributed_point_functions_tpu import native

    if native.available():
        from distributed_point_functions_tpu.core.host_eval import (
            evaluate_at_host,
        )

        pts_arr = np.asarray(points, dtype=np.uint64)
        evaluate_at_host(dpf, keys, pts_arr)  # warm (dlopen, KeyBatch prep)
        with Timer() as th:
            for _ in range(reps):
                evaluate_at_host(dpf, keys, pts_arr)
        host_rate = round(num_keys * num_points * reps / th.elapsed)
        log(f"host engine: {host_rate} point-evals/s")
    return {
        "bench": "evaluate_at",
        "metric": (
            f"batched EvaluateAt, {num_keys} keys x {num_points} points, "
            f"log_domain={log_domain}, uint64"
        ),
        "value": round(evals / t.elapsed),
        "unit": "point-evals/s",
        "config": {
            "log_domain": log_domain,
            "num_keys": num_keys,
            "num_points": num_points,
            **(
                {"host_engine_point_evals_per_s": host_rate}
                if host_rate
                else {}
            ),
        },
    }


if __name__ == "__main__":
    run_bench("evaluate_at", bench)
