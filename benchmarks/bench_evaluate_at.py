"""BASELINE config 2: batched EvaluateAt — 1024 keys x 4096 points each,
log-domain 32, uint64 output.

Methodology of BM_BatchEvaluation
(/root/reference/dpf/distributed_point_function_benchmark.cc:345-402), which
loops EvaluateAt over keys one at a time on CPU; here all keys x points run
as one vmapped device program.
"""

import os

import numpy as np

from common import Timer, log, run_bench


def bench(jax, smoke):
    from distributed_point_functions_tpu.core.dpf import DistributedPointFunction
    from distributed_point_functions_tpu.core.params import DpfParameters
    from distributed_point_functions_tpu.core.value_types import Int
    from distributed_point_functions_tpu.ops import evaluator

    log_domain = int(os.environ.get("BENCH_LOG_DOMAIN", 16 if smoke else 32))
    num_keys = int(os.environ.get("BENCH_KEYS", 16 if smoke else 1024))
    num_points = int(os.environ.get("BENCH_POINTS", 256 if smoke else 4096))
    reps = int(os.environ.get("BENCH_REPS", 2 if smoke else 5))
    # "walk" = the shipped per-level walk; "walkkernel" = the single-program
    # walk megakernel (ISSUE 4; tools/tpu_measure.sh evaluate_at_walkkernel
    # stage records the A/B in its own results.json slot).
    mode = os.environ.get("BENCH_EVALAT_MODE", "walk")

    dpf = DistributedPointFunction.create(DpfParameters(log_domain, Int(64)))
    rng = np.random.default_rng(5)
    alphas = [int(x) for x in rng.integers(0, 1 << log_domain, size=num_keys)]
    betas = [int(x) for x in rng.integers(1, 1 << 62, size=num_keys)]
    with Timer() as tk:
        keys, _ = dpf.generate_keys_batch(alphas, [betas])
    log(f"keygen: {tk.elapsed:.2f}s for {num_keys} keys")
    # Distinct point sets per rep: identical repeated programs time as ~0
    # through this image's tunnel (server-side result caching, PERF.md).
    point_sets = [
        [int(x) for x in rng.integers(0, 1 << log_domain, size=num_points)]
        for _ in range(reps + 1)
    ]

    def run(points):
        # device-resident outputs + tiny fold PULLED to the host — block_
        # until_ready alone is not trustworthy timing through this image's
        # tunnel (PERF.md "Platform findings").
        out = evaluator.evaluate_at_batch(
            dpf, keys, points, device_output=True, mode=mode
        )
        import jax.numpy as jnp

        return np.asarray(jnp.bitwise_xor.reduce(out, axis=1))

    with Timer() as warm:
        fold = run(point_sets[0])
    assert fold.shape[0] == num_keys
    log(f"warmup (compile + run): {warm.elapsed:.1f}s")
    # Verify THE warmup fold itself on sampled keys: the host oracle
    # (native engine, or the reference path without it) recomputes those
    # keys over the full warmup point set and must reproduce fold[i] —
    # attesting the actual benchmarked program, not a separate small one.
    sample = list(range(0, num_keys, max(1, num_keys // 4)))[:4]
    from distributed_point_functions_tpu import native

    if native.available():
        from distributed_point_functions_tpu.core.host_eval import (
            evaluate_at_host,
        )

        host_vals = evaluate_at_host(
            dpf,
            [keys[i] for i in sample],
            np.asarray(point_sets[0], dtype=np.uint64),
        )
    else:
        host_vals = np.asarray(
            [dpf.evaluate_at(keys[i], 0, point_sets[0][:64]) for i in sample],
            dtype=np.uint64,
        )
    if host_vals.shape[1] == len(point_sets[0]):
        want = np.bitwise_xor.reduce(host_vals.astype(np.uint64), axis=1)
        got = fold[sample]  # uint32[len(sample), 2] limb folds
        got64 = got[:, 0].astype(np.uint64) | (
            got[:, 1].astype(np.uint64) << np.uint64(32)
        )
        ok = bool((got64 == want).all())
    else:  # numpy-oracle fallback verified only a point subset
        dev = evaluator.values_to_numpy(
            evaluator.evaluate_at_batch(
                dpf, [keys[i] for i in sample], point_sets[0][:64], mode=mode
            ),
            64,
        ).astype(np.uint64)
        ok = bool((dev == host_vals).all())
    log(f"device-vs-host verification ({len(sample)} keys): "
        f"{'OK' if ok else 'MISMATCH'}")
    # Telemetry capture around the timed loop (ISSUE 6): the record gains
    # the measured chunk dispatch count, per-stage times and
    # pipeline_occupancy as provenance fields (not a schema break).
    from distributed_point_functions_tpu.utils import telemetry

    with telemetry.capture() as tel, Timer() as t:
        for points in point_sets[1:]:
            run(points)
    telemetry_fields = telemetry.bench_fields(tel.snapshot())
    evals = num_keys * num_points * reps

    # Secondary: the native host engine on the same workload, for the
    # engine-choice record (PERF.md) — the device wins this shape.
    host_rate = None
    from distributed_point_functions_tpu import native

    if native.available():
        from distributed_point_functions_tpu.core.host_eval import (
            evaluate_at_host,
        )

        pts_arr = np.asarray(point_sets[0], dtype=np.uint64)
        evaluate_at_host(dpf, keys, pts_arr)  # warm (dlopen, KeyBatch prep)
        with Timer() as th:
            for _ in range(reps):
                evaluate_at_host(dpf, keys, pts_arr)
        host_rate = round(num_keys * num_points * reps / th.elapsed)
        log(f"host engine: {host_rate} point-evals/s")
    result_extra = {} if ok else {
        "error": "device output failed host-oracle spot verification"
    }
    # Walk traffic model next to the measured rate (per-level walk vs the
    # in-register walk megakernel), the point-walk twin of the headline's
    # hbm roofline fields. The walk runs TREE levels (log_domain - 1 for
    # Int(64): two elements per block), not log_domain.
    from distributed_point_functions_tpu.utils import roofline

    tree_levels = dpf.validator.hierarchy_to_tree[-1]
    walk_fields = roofline.walk_hbm_fields(
        evals / t.elapsed, tree_levels, mode, lpe=2, captures=1
    )
    return {
        **result_extra,
        "bench": "evaluate_at",
        "metric": (
            f"batched EvaluateAt, {num_keys} keys x {num_points} points, "
            f"log_domain={log_domain}, uint64, mode={mode}"
        ),
        "value": round(evals / t.elapsed),
        "unit": "point-evals/s",
        "verified": bool(ok),
        "config": {
            "log_domain": log_domain,
            "num_keys": num_keys,
            "num_points": num_points,
            "mode": mode,
            **walk_fields,
            **telemetry_fields,
            **(
                {"host_engine_point_evals_per_s": host_rate}
                if host_rate
                else {}
            ),
        },
    }


if __name__ == "__main__":
    run_bench("evaluate_at", bench)
