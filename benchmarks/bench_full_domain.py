"""BASELINE config 1: single-hierarchy DPF full-domain evaluation.

log-domain 20, XorWrapper<uint128>, 1 key — the shape of
BM_EvaluateRegularDpf (/root/reference/dpf/distributed_point_function_benchmark.cc:29-82)
at its largest type. Values are materialized device-resident and XOR-folded
(see PERF.md for why host transfer is not part of the metric).
"""

import os

import numpy as np

from common import Timer, log, run_bench


def bench(jax, smoke):
    import jax.numpy as jnp

    from distributed_point_functions_tpu.core.dpf import DistributedPointFunction
    from distributed_point_functions_tpu.core.params import DpfParameters
    from distributed_point_functions_tpu.core.value_types import XorWrapper
    from distributed_point_functions_tpu.ops import evaluator

    log_domain = int(os.environ.get("BENCH_LOG_DOMAIN", 12 if smoke else 20))
    reps = int(os.environ.get("BENCH_REPS", 2 if smoke else 5))
    dpf = DistributedPointFunction.create(
        DpfParameters(log_domain, XorWrapper(128))
    )
    key, _ = dpf.generate_keys(123, 1 << 100)

    def run():
        for _, out in evaluator.full_domain_evaluate_chunks(dpf, [key]):
            fold = jnp.bitwise_xor.reduce(out, axis=1)
        jax.block_until_ready(fold)

    with Timer() as warm:
        run()
    log(f"warmup (compile + run): {warm.elapsed:.1f}s")
    with Timer() as t:
        for _ in range(reps):
            run()
    evals = (1 << log_domain) * reps
    return {
        "bench": "full_domain",
        "metric": f"full-domain eval, log_domain={log_domain}, XorWrapper<u128>, 1 key",
        "value": round(evals / t.elapsed),
        "unit": "evals/s",
        "config": {"log_domain": log_domain, "value_type": "XorWrapper<u128>"},
        "seconds_per_expansion": t.elapsed / reps,
    }


if __name__ == "__main__":
    run_bench("full_domain", bench)
