"""BASELINE config 1: single-hierarchy DPF full-domain evaluation.

log-domain 20, XorWrapper<uint128>, 1 key — the shape of
BM_EvaluateRegularDpf (/root/reference/dpf/distributed_point_function_benchmark.cc:29-82)
at its largest type. Values are materialized device-resident and XOR-folded
(see PERF.md for why host transfer is not part of the metric).
"""

import os

import numpy as np

from common import Timer, log, run_bench


def bench(jax, smoke):
    import jax.numpy as jnp

    from distributed_point_functions_tpu.core.dpf import DistributedPointFunction
    from distributed_point_functions_tpu.core.params import DpfParameters
    from distributed_point_functions_tpu.core.value_types import XorWrapper
    from distributed_point_functions_tpu.ops import evaluator

    log_domain = int(os.environ.get("BENCH_LOG_DOMAIN", 12 if smoke else 20))
    reps = int(os.environ.get("BENCH_REPS", 2 if smoke else 5))
    # "fold": the in-program consumer shape (values materialized behind a
    # barrier and XOR-folded in-program; Mosaic row kernels on TPU) —
    # matches the headline bench's execution shape at 1 key.
    mode = os.environ.get("BENCH_MODE", "fold")
    dpf = DistributedPointFunction.create(
        DpfParameters(log_domain, XorWrapper(128))
    )
    # One key per rep: identical repeated programs time as ~0 through this
    # image's tunnel (server-side result caching, PERF.md) — every timed
    # iteration must compute something new, and its fold must reach the
    # host inside the timed region.
    rng = np.random.default_rng(123)
    alphas = [int(a) for a in rng.integers(0, 1 << log_domain, size=reps + 1)]
    keys, _ = dpf.generate_keys_batch(alphas, [[1 << 100] * (reps + 1)])

    def run(key):
        folds = []
        if mode == "fold":
            for _, fold in evaluator.full_domain_fold_chunks(dpf, [key]):
                folds.append(fold)
        else:
            for _, out in evaluator.full_domain_evaluate_chunks(
                dpf, [key], mode=mode
            ):
                folds.append(jnp.bitwise_xor.reduce(out, axis=1))
        return np.asarray(folds[-1])

    with Timer() as warm:
        fold0 = run(keys[0])
    log(f"warmup (compile + run): {warm.elapsed:.1f}s")
    # Host-oracle check of the warmup key: a rate from a miscomputing
    # program is worthless (PERF.md "Platform findings").
    from distributed_point_functions_tpu.core.host_eval import (
        full_domain_evaluate_host,
    )

    host = full_domain_evaluate_host(dpf, [keys[0]])
    want = np.bitwise_xor.reduce(host, axis=1)
    verified = (np.asarray(fold0[0]) == want[0]).all()
    log(f"device-vs-host verification: {'OK' if verified else 'MISMATCH'}")

    with Timer() as t:
        for key in keys[1:]:
            run(key)
    evals = (1 << log_domain) * reps
    result = {
        "bench": "full_domain",
        "metric": f"full-domain eval, log_domain={log_domain}, XorWrapper<u128>, 1 key",
        "value": round(evals / t.elapsed),
        "unit": "evals/s",
        "config": {
            "log_domain": log_domain,
            "value_type": "XorWrapper<u128>",
            "mode": mode,
        },
        "seconds_per_expansion": t.elapsed / reps,
        "verified": bool(verified),
    }
    if not verified:
        result["error"] = "device output failed host-oracle verification"
    return result


if __name__ == "__main__":
    run_bench("full_domain", bench)
