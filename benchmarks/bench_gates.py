"""FSS gate family benchmark (ISSUE 9): DReLU + spline(ReLU) + the wide
sigmoid/tanh activations through the shared framework at production
batch shapes.

Each gate evaluation is ONE fused batched-DCF pass of
(num_components keys) x (num_sites * batch points) — the record's
headline is gate evaluations/s, and the config carries the
DCF-invocations-per-gate-eval accounting (components x sites: the walks
the program actually runs, including the uniform-program-family waste
PERF.md's "FSS gate family" table documents), the serialized
key_bytes_per_gate, and the walk roofline fields. Host-oracle spot
verification (gate.eval, exact Python ints) gates the `verified` flag —
an unverified device number must never SUPERSEDE a stored record (the
bench_dcf pattern, tools/run_bench_stage.py).

Knobs: BENCH_GATES_GATE (drelu|relu|sigmoid|tanh, default all),
BENCH_LOG_GROUP (16), BENCH_GATE_BATCH (2048),
BENCH_GATES_PAYLOAD (vector|scalar — the spline component-key codec
A/B, ISSUE 18: vector packs all coefficients into ONE tuple-payload DCF
key, scalar flattens to one Int(128) key per shifted coefficient; both
arms record the same fields so stored records compare directly),
BENCH_GATES_ENGINE (host when the native engine is available, else
device), BENCH_GATES_MODE (walk|walkkernel — a device strategy, forces
engine=device like bench_dcf's BENCH_DCF_MODE).
"""

import os

import numpy as np

from common import Timer, log, run_bench


def _one_gate(jax, gate_name, gate, log_group, batch, reps, engine, mode, rng):
    from distributed_point_functions_tpu.utils import roofline, telemetry

    from distributed_point_functions_tpu.protos import serialization as ser

    n = gate.n
    r_in = int(rng.integers(0, n))
    r_outs = [int(r) for r in rng.integers(0, n, size=gate.num_outputs)]
    with Timer() as tk:
        k0, _ = gate.gen(r_in, r_outs)
    key_bytes = len(
        ser.serialize_gate_key(k0, gate.dcf.dpf.validator.parameters)
    )
    log(
        f"{gate_name}: keygen {tk.elapsed:.2f}s "
        f"({gate.num_components} component DCF keys, {key_bytes}B on the wire)"
    )
    kwargs = {} if engine == "host" else {"mode": mode}
    xs_sets = [
        [int(x) for x in rng.integers(0, n, size=batch)] for _ in range(reps)
    ]
    with Timer() as warm:
        out = gate.batch_eval(k0, xs_sets[0], engine=engine, **kwargs)
    assert out.shape == (batch, gate.num_outputs)
    log(f"{gate_name}: warmup (compile + run) {warm.elapsed:.1f}s")
    # Host-oracle spot verification of the warmed output: exact-int
    # per-point gate.eval on a handful of inputs.
    ok = True
    for xi in range(0, batch, max(1, batch // 4))[:4] if batch else []:
        want = gate.eval(k0, xs_sets[0][xi])
        if [int(v) for v in out[xi]] != [int(v) for v in want]:
            ok = False
    log(f"{gate_name}: host-oracle spot verification: {'OK' if ok else 'MISMATCH'}")
    # Distinct input sets per rep + the result already host-side: identical
    # repeated device programs time as ~0 through this image's tunnel.
    with telemetry.capture() as tel, Timer() as t:
        for xs_i in xs_sets:
            gate.batch_eval(k0, xs_i, engine=engine, **kwargs)
    telemetry_fields = telemetry.bench_fields(tel.snapshot())
    gate_evals = batch * reps
    dcf_walks_per_eval = gate.num_components * gate.num_sites
    fields = {
        "log_group_size": log_group,
        "batch": batch,
        "engine": engine,
        **({"mode": mode} if engine != "host" else {}),
        "num_components": gate.num_components,
        "num_sites": gate.num_sites,
        "payload": getattr(gate, "payload", "scalar"),
        # Serialized dealer->server key size: the vector codec's other
        # headline axis (ONE packed tuple key vs m(d+1) scalar keys).
        "key_bytes_per_gate": key_bytes,
        # The fused pass walks every component at every site: the DCF
        # invocations one gate evaluation costs (PERF.md "FSS gate family").
        "dcf_invocations_per_gate_eval": dcf_walks_per_eval,
        "dcf_walks_per_sec": round(gate_evals * dcf_walks_per_eval / t.elapsed),
        **telemetry_fields,
    }
    if engine != "host":
        # Walk traffic model at the DCF-walk rate, same fields as
        # bench_dcf's device records. lpe follows the component value
        # type: Int(128) scalars carry 4 limbs, a vector gate's Int(w)
        # tuple elements carry w/32.
        vt = gate.dcf.dpf.validator.parameters[-1].value_type
        lpe = max((ser._uniform_tuple_bits(vt) or 128) // 32, 1)
        T = gate.dcf.dpf.validator.hierarchy_to_tree[-1]
        fields.update(
            roofline.walk_hbm_fields(
                gate_evals * dcf_walks_per_eval / t.elapsed,
                T, mode, lpe=lpe, captures=T + 1,
            )
        )
    return {
        **({} if ok else {
            "error": "device output failed host-oracle spot verification"
        }),
        "bench": f"gates_{gate_name}",
        "metric": (
            f"{gate_name} gate batch_eval, batch {batch}, "
            f"log_group={log_group}"
            + (f", mode={mode}" if engine != "host" else "")
        ),
        "value": round(gate_evals / t.elapsed, 1),
        "unit": "gate evals/s",
        "verified": bool(ok),
        "config": fields,
        **({"platform": "cpu"} if engine == "host" else {}),
    }


def bench(jax, smoke):
    from distributed_point_functions_tpu import native
    from distributed_point_functions_tpu.gates import (
        DReluGate,
        ReluGate,
        SigmoidGate,
        TanhGate,
    )

    log_group = int(os.environ.get("BENCH_LOG_GROUP", 8 if smoke else 16))
    batch = int(os.environ.get("BENCH_GATE_BATCH", 64 if smoke else 2048))
    reps = int(os.environ.get("BENCH_REPS", 2 if smoke else 5))
    which = os.environ.get("BENCH_GATES_GATE", "")
    # The component-key codec A/B arm (ISSUE 18). DReLU is a single
    # 1-payload DCF either way — only the spline gates change layout.
    payload = os.environ.get("BENCH_GATES_PAYLOAD", "vector")
    # Host engine default when available (the DCF engine-table winner at
    # point-walk shapes); walkkernel/walk are device strategies.
    engine = os.environ.get(
        "BENCH_GATES_ENGINE", "host" if native.available() else "device"
    )
    mode = os.environ.get("BENCH_GATES_MODE", "walk")
    if mode == "walkkernel":
        engine = "device"
    if engine == "host" and not native.available():
        engine = "device"
    log(f"engine: {engine} mode: {mode} payload: {payload}")
    rng = np.random.default_rng(0x9A7E)

    # The activations' +/-6.0 input range must fit the signed fixed-point
    # domain: 6 * 2^frac_bits < 2^(log_group - 1).
    frac_bits = min(5, log_group - 4)
    results = []
    gates_to_run = [
        ("drelu", DReluGate.create(log_group)),
        ("relu", ReluGate.create(log_group, payload=payload)),
        ("sigmoid", SigmoidGate.create(log_group, frac_bits=frac_bits,
                                       payload=payload)),
        ("tanh", TanhGate.create(log_group, frac_bits=frac_bits,
                                 payload=payload)),
    ]
    for name, gate in gates_to_run:
        if which and name != which:
            continue
        results.append(
            _one_gate(
                jax, name, gate, log_group, batch, reps, engine, mode, rng
            )
        )
    # One JSON line per run (the common.py contract): the primary record
    # is the ReLU (the spline workhorse); the other gates' records ride
    # in config unless a single gate was requested.
    if len(results) == 1:
        return results[0]
    primary = next(
        r for r in results if r["bench"] == "gates_relu"
    )
    for r in results:
        if r is primary:
            continue
        primary["config"][r["bench"].removeprefix("gates_")] = {
            "value": r["value"],
            "unit": r["unit"],
            "verified": r["verified"],
            **r["config"],
        }
    primary["verified"] = all(r["verified"] for r in results)
    return primary


if __name__ == "__main__":
    run_bench("gates", bench)
