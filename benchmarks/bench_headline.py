"""Wrapper: runs the repo-root bench.py (the driver's headline benchmark)
and re-emits its JSON line as a suite record, so `run_all.py` stores the
headline in benchmarks/results.json through the same merge as every other
bench — the headline claim and the machine-readable record can no longer
drift apart (round-2 verdict: results.json held a stale pre-Pallas number
while the README claimed the Pallas rate).
"""

import json
import os
import subprocess
import sys
import time


def main():
    from common import run_killable

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # Killable process group (common.run_killable). Note the device
    # grandchild is session-separated too, so bench.py's INTERNAL killable
    # windows (probe/device/comparison subprocess timeouts, which sum well
    # under this backstop) are what actually guarantee the TPU claim is
    # released; the killpg covers bench.py itself plus any non-sessioned
    # children if it wedges outside those windows.
    stdout, stderr, timed_out = run_killable(
        [sys.executable, os.path.join(root, "bench.py")],
        timeout=float(os.environ.get("BENCH_HEADLINE_TIMEOUT", 3300)),
    )
    if timed_out:
        sys.stderr.write((stderr or "")[-4000:])
        print(json.dumps({"bench": "full_domain_headline", "error": "timeout",
                  "date": time.strftime("%Y-%m-%d")}))
        return
    sys.stderr.write((stderr or "")[-4000:])
    if not (stdout or "").strip():
        # Hard-killed child (OOM / SIGKILL / interpreter crash): no JSON
        # printed. Parsing "{}" here would store a null record that reads
        # as a measurement — emit an explicit error instead (r3 review).
        print(json.dumps({
            "bench": "full_domain_headline",
            "error": "bench.py produced no output (killed or crashed)",
            "date": time.strftime("%Y-%m-%d"),
        }))
        return
    line = stdout.strip().splitlines()[-1]
    try:
        d = json.loads(line)
    except json.JSONDecodeError:
        print(json.dumps({
            "bench": "full_domain_headline",
            "error": f"bad output: {line[:200]}",
            "date": time.strftime("%Y-%m-%d"),
        }))
        return
    rec = {
        "bench": "full_domain_headline",
        "metric": d.pop("metric", None),
        "value": d.pop("value", None),
        "unit": d.pop("unit", None),
        "platform": d.pop("platform", None),
    }
    if "error" in d:
        # Surface in-band bench.py failures at the top level: a value-0
        # record with the error buried in config would read as a
        # measurement to every results.json consumer.
        rec["error"] = d["error"]
    rec["config"] = d  # vs_baseline, verification fields, etc.
    # Same dating discipline as common.run_bench (every record is dated).
    rec.setdefault("date", time.strftime("%Y-%m-%d"))
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
