"""Heavy-hitters bit-wise hierarchy: one hierarchy level per bit, 10,000
uniform nonzeros discovered level by level.

Mirrors BM_HeavyHitters
(/root/reference/dpf/distributed_point_function_benchmark.cc:306-340): a
`num_levels`-parameter incremental DPF with log_domain_size i+1 at level i,
uint64 values, alpha=42, beta=23, and the unique prefixes of 10k uniform
final-level nonzeros evaluated at EVERY bit via the batched hierarchical
context (the prefix-set EvaluateNext access pattern, not full expansions).
The reference sweeps num_levels over 16..128; here the sweep is one run
(BENCH_HH_LEVELS) with 128 as the TPU default, and the prefix bookkeeping
exercises both the uint64 and the vectorized-U128 index regimes.
"""

import os

import numpy as np

from common import Timer, log, run_bench


def _uniform_prefixes(num_levels, num_nonzeros, rng):
    """prefixes[i] = sorted unique i+1-bit prefixes of the final nonzeros
    (GenerateUniformPrefixes, distributed_point_function_benchmark.cc:268-303)."""
    from distributed_point_functions_tpu.core import uint128

    if num_levels <= 63:
        finals = sorted(
            {int(x) for x in rng.integers(0, 1 << num_levels, size=num_nonzeros)}
        )
    else:  # uniform over the full width, composed from 32-bit draws
        nwords = -(-num_levels // 32)
        words = rng.integers(0, 1 << 32, size=(num_nonzeros, nwords), dtype=np.uint64)
        mask = (1 << num_levels) - 1
        finals = sorted(
            {
                sum(int(w) << (32 * j) for j, w in enumerate(row)) & mask
                for row in words
            }
        )
    out = []
    for i in range(num_levels):
        shift = num_levels - (i + 1)
        p = sorted({f >> shift for f in finals})
        lds = i + 1
        if lds >= 64:
            out.append(np.unique(uint128.u128_array(p)))
        else:
            out.append(np.array(p, dtype=np.uint64))
    return out


def bench(jax, smoke):
    from distributed_point_functions_tpu.core.dpf import DistributedPointFunction
    from distributed_point_functions_tpu.core.params import DpfParameters
    from distributed_point_functions_tpu.core.value_types import Int
    from distributed_point_functions_tpu.ops import hierarchical

    num_levels = int(os.environ.get("BENCH_HH_LEVELS", 16 if smoke else 128))
    num_nonzeros = int(os.environ.get("BENCH_HH_NONZEROS", 10000))
    # Default to the native host engine on every platform: at 10k prefixes
    # x 1 key the workload is ~128 level advances of ~1 MB expansions and
    # the per-level device path is dispatch-bound (measured 11.45 s/key on
    # v5e vs ~0.22-0.26 s/key host). BENCH_HH_ENGINE=device runs the fused
    # grouped advance (hierarchical.evaluate_levels_fused — the prefix
    # sets are known upfront in this workload, so BENCH_HH_GROUP level
    # advances fuse into each program); BENCH_HH_ENGINE=device-levels
    # keeps the per-level path for comparison. BENCH_HH_MODE picks the
    # device advance strategy — "fused" (the grouped advance) or
    # "hierkernel" (the single-program prefix-window megakernel, ISSUE 5:
    # ceil(levels/group) pallas_calls for the whole hierarchy) — so the
    # fused vs hierkernel A/B shares this one harness; hierkernel is a
    # device strategy, so it forces engine=device (the bench_dcf
    # BENCH_DCF_MODE pattern; tools/tpu_measure.sh's
    # heavy_hitters_hierkernel stage records the A/B in its own
    # results.json slot).
    engine = os.environ.get("BENCH_HH_ENGINE", "host")
    group = int(os.environ.get("BENCH_HH_GROUP", 16))
    mode = os.environ.get("BENCH_HH_MODE", "fused")
    if mode == "hierkernel":
        engine = "device"

    def make_workload(lv):
        p_lv = [DpfParameters(i + 1, Int(64)) for i in range(lv)]
        d_lv = DistributedPointFunction.create_incremental(p_lv)
        k_lv, _ = d_lv.generate_keys_incremental(42 % (1 << lv), [23] * lv)
        pre = _uniform_prefixes(lv, num_nonzeros, np.random.default_rng(7))
        return d_lv, k_lv, pre

    def run_once(d_lv, k_lv, pre, lv):
        ctx = hierarchical.BatchedContext.create(d_lv, [k_lv])
        if engine == "device":
            plan = [
                (level, () if level == 0 else pre[level - 1])
                for level in range(lv)
            ]
            outs = hierarchical.evaluate_levels_fused(
                ctx, plan, group=group, device_output=True, mode=mode
            )
            jax.block_until_ready(outs[-1])
            return outs[-1]
        out = None
        for level in range(lv):
            out = hierarchical.evaluate_until_batch(
                ctx,
                level,
                () if level == 0 else pre[level - 1],
                device_output=True,
                engine="device" if engine == "device-levels" else engine,
            )
        if engine != "host":
            jax.block_until_ready(out)
        return out

    dpf, key, prefixes = make_workload(num_levels)
    log(
        f"{num_levels} levels, {len(prefixes[-1])} unique nonzeros, "
        f"engine={engine}"
        + (f", mode={mode}" if engine == "device" else "")
    )
    with Timer() as warm:
        first = run_once(dpf, key, prefixes, num_levels)
    log(f"warmup (compile + run): {warm.elapsed:.1f}s")
    verified = False
    if engine != "host":
        # Host-oracle verification of THE warmed output (the full final
        # level vs the native host engine, cheap: ~0.25 s/key) — this
        # tunnel has miscomputed silently before, so a device rate
        # without an oracle check is not evidence (PERF.md). The
        # `verified` flag is what lets run_bench_stage.py SUPERSEDES
        # retire a beaten record — an unverified device number must
        # never supersede anything (the bench_dcf pattern).
        ctx_h = hierarchical.BatchedContext.create(dpf, [key])
        for level in range(num_levels):
            want = hierarchical.evaluate_until_batch(
                ctx_h,
                level,
                () if level == 0 else prefixes[level - 1],
                engine="host",
            )
        got = np.asarray(first)
        got64 = (
            got[..., 0].astype(np.uint64)
            | (got[..., 1].astype(np.uint64) << np.uint64(32))
        )
        verified = bool(np.array_equal(got64, np.asarray(want)))
        log(
            "final-level host-oracle verification: "
            f"{'OK' if verified else 'MISMATCH'}"
        )
    # Telemetry capture around the timed pass (ISSUE 6): hierkernel-mode
    # records gain the measured window dispatch count, per-stage busy
    # times and pipeline_occupancy (provenance fields; host-engine runs
    # dispatch nothing through the executor and gain nothing).
    from distributed_point_functions_tpu.utils import telemetry

    with telemetry.capture() as tel, Timer() as t:
        run_once(dpf, key, prefixes, num_levels)
    telemetry_fields = telemetry.bench_fields(tel.snapshot())

    prepared_stats = {}
    if engine == "device":
        # Aggregation-server shape: ONE global prefix plan replayed over
        # many client key batches — tables composed and uploaded once
        # (hierarchical.prepare_levels_fused), only key material per call.
        plan = [
            (level, () if level == 0 else prefixes[level - 1])
            for level in range(num_levels)
        ]
        ctx0 = hierarchical.BatchedContext.create(dpf, [key])
        with Timer() as tp:
            prepared = hierarchical.prepare_levels_fused(
                ctx0, plan, group, mode=mode
            )
        def run_prepared():
            c = hierarchical.BatchedContext.create(dpf, [key])
            outs = hierarchical.evaluate_levels_fused(
                c, prepared, device_output=True
            )
            jax.block_until_ready(outs[-1])
            return outs[-1]
        got_p = np.asarray(run_prepared())
        if not np.array_equal(got_p, np.asarray(first)):
            raise RuntimeError("prepared-plan outputs diverge from the plain path")
        with Timer() as t2:
            run_prepared()
        prepared_stats = {
            "prepare_seconds": round(tp.elapsed, 4),
            "prepared_s_per_key": round(t2.elapsed, 4),
        }
        log(f"prepared plan: {prepared_stats} (outputs verified vs plain path)")

    # The reference sweeps Range(16, 128); on the cheap host engine emit
    # the whole sweep so regenerated results keep it (device sweeps would
    # compile ~levels programs — single level only there). Every entry is
    # a warmed second run, same as the headline number.
    sweep = {}
    if engine == "host" and not smoke and "BENCH_HH_LEVELS" not in os.environ:
        for lv in (16, 32, 64):
            w = make_workload(lv)
            run_once(*w, lv)
            with Timer() as ts:
                run_once(*w, lv)
            sweep[str(lv)] = round(ts.elapsed, 4)
        sweep[str(num_levels)] = round(t.elapsed, 4)
        log(f"level sweep: {sweep}")

    if engine == "host":
        verification_fields = {
            "verification": (
                "n/a: the host engine IS the oracle device records verify "
                "against (reference-parity path, tested by the suite)"
            )
        }
    else:
        verification_fields = {
            "verified": verified,
            **(
                {}
                if verified
                else {
                    "error": (
                        "device final-level outputs failed host-oracle "
                        "verification"
                    )
                }
            ),
        }
    hier_fields = {}
    if engine == "device":
        # Hierarchical traffic model next to the measured rate (per-level
        # fused round trips vs the in-register prefix windows).
        from distributed_point_functions_tpu.utils import roofline

        prefix_levels = sum(len(p) for p in prefixes)
        hier_fields = roofline.hier_hbm_fields(
            prefix_levels / t.elapsed, mode, lpe=2, keep=2, group=group,
        )
    return {
        # Engine-distinct slots: the fused device record must not clobber
        # (or be clobbered by) the host-engine record on the same platform
        # (VERDICT r3 #4: the fused-path proof needs its own dated entry).
        "bench": (
            "heavy_hitters" if engine == "host" else f"heavy_hitters_{engine}"
        ),
        **verification_fields,
        "metric": (
            f"bit-wise hierarchy, {num_levels} levels, "
            f"{num_nonzeros} uniform nonzeros, 1 key"
            + (f", mode={mode}" if engine == "device" else "")
        ),
        "value": round(t.elapsed, 4),
        "unit": "s/key/iteration",
        "config": {
            "num_levels": num_levels,
            "num_nonzeros": num_nonzeros,
            "engine": engine,
            **({"mode": mode, "group": group} if engine == "device" else {}),
            **hier_fields,
            **telemetry_fields,
            **prepared_stats,
            **({"seconds_by_levels": sweep} if sweep else {}),
        },
        **({"platform": "cpu"} if engine == "host" else {}),
    }


if __name__ == "__main__":
    run_bench("heavy_hitters", bench)
