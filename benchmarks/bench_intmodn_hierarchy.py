"""BASELINE config 3: incremental 8-level hierarchy (heavy-hitters prefix
tree), IntModN<uint64> output, 256 keys.

Times the device-path expansion at every hierarchy level (the heavy-hitters
access pattern evaluates each level once, pruning between levels — the
per-level full expansions measured here are its compute kernel; cf.
BM_HeavyHitters, /root/reference/dpf/distributed_point_function_benchmark.cc:308-340).
The deepest level (log-domain 24) dominates; outputs stay device-resident
(IntModN mod-N reduction runs on device via the value codec).
"""

import os

import numpy as np

from common import Timer, log, run_bench

MOD64 = (1 << 64) - 59


def bench(jax, smoke):
    import jax.numpy as jnp

    from distributed_point_functions_tpu.core.dpf import DistributedPointFunction
    from distributed_point_functions_tpu.core.params import DpfParameters
    from distributed_point_functions_tpu.core.value_types import IntModN
    from distributed_point_functions_tpu.ops import evaluator

    num_keys = int(os.environ.get("BENCH_KEYS", 8 if smoke else 256))
    max_lds = int(os.environ.get("BENCH_MAX_LOG_DOMAIN", 10 if smoke else 24))
    # 4 keys/chunk at log-domain 24: the IntModN codec's finalize program
    # pads its [chunk, N, epb, lpe] temporaries ~2.5x on TPU; 16-key chunks
    # exceed v5e HBM (20G padded vs 15.75G available).
    key_chunk = int(os.environ.get("BENCH_KEY_CHUNK", 8 if smoke else 4))
    num_levels = 8
    step = max(max_lds // num_levels, 1)
    domains = [step * (i + 1) for i in range(num_levels)]

    vt = IntModN(64, MOD64)
    params = [DpfParameters(d, vt) for d in domains]
    dpf = DistributedPointFunction.create_incremental(params)
    rng = np.random.default_rng(3)
    # Two independent key sets: warmup compiles + runs on the first, the
    # timed pass runs on the second — identical repeated programs time as
    # ~0 through this image's tunnel (server-side result caching, PERF.md).
    key_sets = []
    with Timer() as tk:
        for _ in range(2):
            alphas = [
                int(x) for x in rng.integers(0, 1 << domains[-1], size=num_keys)
            ]
            betas = [
                [int(x) % MOD64 for x in rng.integers(1, 1 << 63, size=num_keys)]
                for _ in range(num_levels)
            ]
            ks, _ = dpf.generate_keys_batch(alphas, betas)
            key_sets.append(ks)
    log(f"keygen: {tk.elapsed:.2f}s for 2x{num_keys} keys x {num_levels} levels")

    # Per-level slab plans: the deep levels (2^21, 2^24) exceed the
    # tunnel's safe program size even at 4-key chunks — without slabbing
    # their outputs are silently corrupt (PERF.md threshold bisect).
    plans = [
        evaluator.plan_slabs(dpf, key_chunk, hierarchy_level=lv)
        for lv in range(num_levels)
    ]

    def run_level(ks, level):
        h, slab = plans[level]
        folds = []
        for _, out in evaluator.full_domain_evaluate_chunks(
            dpf, ks, hierarchy_level=level, key_chunk=key_chunk,
            mode="fused", host_levels=h, lane_slab=slab,
        ):
            folds.append(jnp.bitwise_xor.reduce(out, axis=1))
        return np.asarray(folds[-1])  # pulled: timing must include execution

    with Timer() as warm:
        for level in range(num_levels):
            run_level(key_sets[0], level)
    log(f"warmup all {num_levels} levels (compile + run): {warm.elapsed:.1f}s")

    # Host-oracle verification at the deepest level the reference host
    # path can afford (domain <= 2^15), with a FORCED small lane_slab so
    # the check exercises the same multi-piece slab slicing/concatenation
    # machinery the timed deep levels rely on — at this domain plan_slabs
    # itself would return no slabbing and the slab branch would go
    # unvalidated (a rate from a miscomputing program is worthless, PERF.md).
    ver_level = max(
        (lv for lv, d in enumerate(domains) if d <= 15), default=0
    )
    ver_stop = dpf.validator.hierarchy_to_tree[ver_level]
    ver_h = min(ver_stop, 7)  # >= 64 host lanes -> slab 32 gives >= 2 pieces
    pieces = [
        np.asarray(out)[0]
        for _, out in evaluator.full_domain_evaluate_chunks(
            dpf, key_sets[0][:1], hierarchy_level=ver_level, key_chunk=1,
            mode="fused", host_levels=ver_h, lane_slab=32,
        )
    ]
    log(f"verification pieces: {len(pieces)}")
    v_out = np.concatenate(pieces, axis=0)
    from distributed_point_functions_tpu.ops import value_codec

    spec = value_codec.build_spec(vt, dpf.validator.blocks_needed[ver_level])
    got = value_codec.values_to_host((v_out,), spec)
    ctx = dpf.create_evaluation_context(key_sets[0][0])
    want = dpf.evaluate_until(ver_level, [], ctx)
    verified = got == want
    log(f"device-vs-host verification (level {ver_level}, "
        f"2^{domains[ver_level]}): {'OK' if verified else 'MISMATCH'}")

    with Timer() as t:
        for level in range(num_levels):
            run_level(key_sets[1], level)
    evals = num_keys * sum(1 << d for d in domains)
    result_extra = {} if verified else {
        "error": "device output failed host-oracle verification"
    }
    return {
        **result_extra,
        "bench": "intmodn_hierarchy",
        "metric": (
            f"{num_levels}-level IntModN<u64> hierarchy, {num_keys} keys, "
            f"domains {domains}"
        ),
        "value": round(evals / t.elapsed),
        "unit": "evals/s",
        "verified": bool(verified),
        "config": {"domains": domains, "num_keys": num_keys, "modulus": MOD64},
        "seconds_all_levels": t.elapsed,
    }


if __name__ == "__main__":
    run_bench("intmodn_hierarchy", bench)
