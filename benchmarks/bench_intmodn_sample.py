"""IntModN statistical sampling throughput.

Mirrors BM_Sample (/root/reference/dpf/int_mod_n_benchmark.cc:28-46):
IntModN<uint32, 2^32-5> with the security-padded leftover-entropy chain,
security parameter 40 + log2(n), ONE sample per block (the reference's
BM_Sample draws 5 chained samples per call, so its per-call figures are
~5x one-sample figures — compare rates per sample, not per call).
Measures both engines:

* host: the python host sampler (core/value_types.IntModN.sample_and_update
  — the wire-exact path used by keygen/value correction on the host),
* device: the vectorized codec chain (ops/value_codec._sample_chain) over a
  batch of blocks on the default backend.
"""

import os
import secrets

import numpy as np

from common import Timer, log, run_bench

MOD = (1 << 32) - 5


def bench(jax, smoke):
    import jax.numpy as jnp

    from distributed_point_functions_tpu.core.value_types import IntModN
    from distributed_point_functions_tpu.ops import value_codec

    # 2^18 blocks/dispatch on real backends: with the in-program fold the
    # output is bytes, so the batch size only has to amortize dispatch
    # latency (streams are device-resident before the timed loop).
    n_blocks = int(os.environ.get("BENCH_SAMPLE_BLOCKS", 1 << (10 if smoke else 18)))
    vt = IntModN(32, MOD)

    # Host sampler: one block + chained bytes per call, one sample out.
    sec = 40 + np.log2(n_blocks)
    bytes_needed = vt.bits_needed(sec) // 8
    blocks = [secrets.token_bytes(bytes_needed) for _ in range(256)]
    with Timer() as th:
        for b in blocks:
            block = int.from_bytes(b[:16], "little")
            vt.sample_and_update(True, block, b[16:])
    host_rate = 256 / th.elapsed  # samples/s
    log(f"host sampler: {host_rate:.0f} blocks/s")

    # Device chain: the codec consumes a hash stream [lanes, 4*bn] and emits
    # mod-N values per lane; blocks_needed from the security accounting.
    bn = -(-vt.bits_needed(sec) // 128)
    spec = value_codec.build_spec(vt, blocks_needed=bn)
    rng = np.random.default_rng(5)
    reps = int(os.environ.get("BENCH_REPS", 10))
    # Distinct streams per rep (identical repeated programs time as ~0
    # through this image's tunnel), and an IN-PROGRAM consumer fold so the
    # host pull is tiny — pulling all n_blocks sample limbs would measure
    # the ~MB/s host link, not the sampler (the 503 K samples/s r2 device
    # record was exactly that).
    streams = [
        jnp.asarray(
            rng.integers(
                0, 2**32, size=(n_blocks, 4 * spec.blocks_needed),
                dtype=np.uint32,
            )
        )
        for _ in range(reps + 1)
    ]

    @jax.jit
    def fn(s):
        samples = value_codec._sample_chain(s, spec)
        samples = jax.lax.optimization_barrier(samples)
        return tuple(jnp.bitwise_xor.reduce(o, axis=0) for o in samples)

    jax.block_until_ready(fn(streams[0]))  # warmup (compile)
    # Verify the device chain against the wire-exact host sampler on a few
    # lanes (the fold itself is a plain XOR reduce; what needs attesting is
    # the mod-N chain the rate claims to measure).
    n_verify = min(64, n_blocks)
    small = np.asarray(streams[0])[:n_verify]
    dev_small = [
        np.asarray(o)
        for o in jax.jit(lambda s: value_codec._sample_chain(s, spec))(
            jnp.asarray(small)
        )
    ]
    for lane in range(0, n_verify, max(1, n_verify // 4)):
        b = small[lane].tobytes()
        block = int.from_bytes(b[:16], "little")
        want, _, _ = vt.sample_and_update(False, block, b[16:])
        got = int(dev_small[0][lane, 0])
        if got != want:
            # Not an assert: python -O would strip it and the bench would
            # report an unverified rate as verified (ADVICE r3).
            raise RuntimeError(
                f"device sample chain mismatch at lane {lane}: "
                f"got {got}, want {want}"
            )
    log("device chain verified against the host sampler on 4 lanes")
    with Timer() as t:
        for i in range(reps):
            out = [np.asarray(o) for o in fn(streams[1 + i])]
    rate = reps * n_blocks / t.elapsed
    return {
        "bench": "intmodn_sample",
        # The chain is oracle-checked against the wire-exact host sampler
        # above (raises on mismatch), so the rate is a verified one.
        "verified": True,
        "metric": (
            f"IntModN<u32, 2^32-5> sampling, {n_blocks} blocks "
            f"(device codec chain, 1 sample/block; host sampler "
            f"{host_rate:.0f} samples/s)"
        ),
        "value": round(rate),
        "unit": "samples/s",
        "config": {"modulus": MOD, "n_blocks": n_blocks},
    }


if __name__ == "__main__":
    run_bench("intmodn_sample", bench)
