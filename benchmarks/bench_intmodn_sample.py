"""IntModN statistical sampling throughput.

Mirrors BM_Sample (/root/reference/dpf/int_mod_n_benchmark.cc:28-46):
IntModN<uint32, 2^32-5> with the security-padded leftover-entropy chain,
security parameter 40 + log2(n), ONE sample per block (the reference's
BM_Sample draws 5 chained samples per call, so its per-call figures are
~5x one-sample figures — compare rates per sample, not per call).
Measures both engines:

* host: the python host sampler (core/value_types.IntModN.sample_and_update
  — the wire-exact path used by keygen/value correction on the host),
* device: the vectorized codec chain (ops/value_codec._sample_chain) over a
  batch of blocks on the default backend.
"""

import os
import secrets

import numpy as np

from common import Timer, log, run_bench

MOD = (1 << 32) - 5


def bench(jax, smoke):
    import jax.numpy as jnp

    from distributed_point_functions_tpu.core.value_types import IntModN
    from distributed_point_functions_tpu.ops import value_codec

    n_blocks = int(os.environ.get("BENCH_SAMPLE_BLOCKS", 1 << (10 if smoke else 16)))
    vt = IntModN(32, MOD)

    # Host sampler: one block + chained bytes per call, one sample out.
    sec = 40 + np.log2(n_blocks)
    bytes_needed = vt.bits_needed(sec) // 8
    blocks = [secrets.token_bytes(bytes_needed) for _ in range(256)]
    with Timer() as th:
        for b in blocks:
            block = int.from_bytes(b[:16], "little")
            vt.sample_and_update(True, block, b[16:])
    host_rate = 256 / th.elapsed  # samples/s
    log(f"host sampler: {host_rate:.0f} blocks/s")

    # Device chain: the codec consumes a hash stream [lanes, 4*bn] and emits
    # mod-N values per lane; blocks_needed from the security accounting.
    bn = -(-vt.bits_needed(sec) // 128)
    spec = value_codec.build_spec(vt, blocks_needed=bn)
    rng = np.random.default_rng(5)
    stream = jnp.asarray(
        rng.integers(0, 2**32, size=(n_blocks, 4 * spec.blocks_needed), dtype=np.uint32)
    )
    fn = jax.jit(lambda s: value_codec._sample_chain(s, spec))
    jax.block_until_ready(fn(stream))
    reps = int(os.environ.get("BENCH_REPS", 10))
    with Timer() as t:
        for _ in range(reps):
            out = fn(stream)
            out = [np.asarray(o) for o in out]  # host pull: honest timing
    rate = reps * n_blocks / t.elapsed
    return {
        "bench": "intmodn_sample",
        "metric": (
            f"IntModN<u32, 2^32-5> sampling, {n_blocks} blocks "
            f"(device codec chain, 1 sample/block; host sampler "
            f"{host_rate:.0f} samples/s)"
        ),
        "value": round(rate),
        "unit": "samples/s",
        "config": {"modulus": MOD, "n_blocks": n_blocks},
    }


if __name__ == "__main__":
    run_bench("intmodn_sample", bench)
