"""The ISRG two-level example hierarchy (Prio heavy-hitters sizing):
level 0 = full 2^12 expansion, level 1 = 32 random 12-bit prefixes into a
2^25 domain, uint32 values.

Mirrors BM_IsrgExampleHierarchy
(/root/reference/dpf/distributed_point_function_benchmark.cc:182-222): per
iteration a FRESH context advances through both hierarchy levels. Here the
advance runs the batched hierarchical path (one BatchedContext, device or
native-host engine); keys are generated once outside the loop, as in the
reference.
"""

import os

import numpy as np

from common import Timer, log, run_bench


def bench(jax, smoke):
    from distributed_point_functions_tpu.core.dpf import DistributedPointFunction
    from distributed_point_functions_tpu.core.params import DpfParameters
    from distributed_point_functions_tpu.core.value_types import Int
    from distributed_point_functions_tpu.ops import hierarchical

    lds0, lds1 = (8, 18) if smoke else (12, 25)
    num_nonzeros = 32
    reps = int(os.environ.get("BENCH_REPS", 2 if smoke else 5))
    engine = os.environ.get("BENCH_ISRG_ENGINE", "host")

    params = [DpfParameters(lds0, Int(32)), DpfParameters(lds1, Int(32))]
    dpf = DistributedPointFunction.create_incremental(params)
    rng = np.random.default_rng(13)
    # One key per rep: identical repeated programs time as ~0 through this
    # image's tunnel (server-side result caching, PERF.md) — every timed
    # iteration must compute something new.
    keys = [
        dpf.generate_keys_incremental(int(a), [1, 1])[0]
        for a in rng.integers(0, 1 << lds1, size=reps + 1)
    ]
    ka = keys[0]
    prefixes = np.unique(
        rng.integers(0, 1 << lds0, size=num_nonzeros).astype(np.uint64)
    )

    from distributed_point_functions_tpu import native

    if engine == "host" and not native.available():
        engine = "device"
    log(f"engine: {engine}, levels ({lds0}, {lds1}), {len(prefixes)} prefixes")

    def run_once(key):
        ctx = hierarchical.BatchedContext.create(dpf, [key])
        out0 = hierarchical.evaluate_until_batch(
            ctx, 0, device_output=(engine != "host"), engine=engine
        )
        out1 = hierarchical.evaluate_until_batch(
            ctx, 1, [int(p) for p in prefixes],
            device_output=(engine != "host"), engine=engine,
        )
        if engine != "host":
            # Tiny fold pulled to the host: block_until_ready alone is not
            # trustworthy timing through this tunnel, and a full pull of
            # the 2^25-slice outputs would measure the ~5 MB/s link.
            import jax.numpy as jnp

            np.asarray(jnp.bitwise_xor.reduce(out1, axis=1))
        return out0, out1

    with Timer() as warm:
        out0, out1 = run_once(ka)
    n_out = (1 << lds0) + len(prefixes) * (1 << (lds1 - lds0))
    log(f"warmup (compile + run): {warm.elapsed:.1f}s, {n_out} outputs/iter")
    with Timer() as t:
        for key in keys[1:]:
            run_once(key)
    per_iter = t.elapsed / reps

    return {
        "bench": "isrg_example_hierarchy",
        "metric": (
            f"ISRG 2-level example: 2^{lds0} full + {len(prefixes)} prefixes "
            f"-> 2^{lds1}, uint32, 1 key"
        ),
        "value": round(per_iter, 5),
        "unit": "s/iteration",
        "config": {
            "log_domain_sizes": [lds0, lds1],
            "num_nonzeros": int(len(prefixes)),
            "outputs_per_iteration": n_out,
            "engine": engine,
            "reps": reps,
        },
        **({"platform": "cpu"} if engine == "host" else {}),
    }


if __name__ == "__main__":
    run_bench("isrg_example_hierarchy", bench)
