"""Key-generation throughput: scalar dealer loop vs the batched paths.

Methodology of BM_KeyGeneration
(/root/reference/dpf/distributed_point_function_benchmark.cc:228-260):
single-level DPFs across tree depths. The primary record is the batched
level-major path at BENCH_KEYGEN_MODE ("numpy-threaded" = the
thread-parallel host dealer, the production default; "numpy" = the
single-thread vectorized host batch; "jax"/"pallas"/"megakernel" = the
device circuits of ops/keygen_batch.py — device strategies,
staged-for-tunnel), A/B'd against the scalar per-key loop (the
reference's shape) on a sampled prefix, plus a batch-size sweep at the
headline depth. Host modes also run a BENCH_KEYGEN_THREADS worker sweep
(default "1,2,4,0"; 0 = all cores) at the deepest depth, each point
paired with the roofline host-thread model's predicted speedup.

The `verified` flag — spot keys byte-compared (serialized) against the
scalar oracle from the same seeds — is what lets run_bench_stage.py's
SUPERSEDES retire a beaten record; an unverified device-mode number
must never supersede anything.
"""

import os
import time

import numpy as np

from common import Timer, log, run_bench


def bench(jax, smoke):
    from distributed_point_functions_tpu.core.dpf import DistributedPointFunction
    from distributed_point_functions_tpu.core.params import DpfParameters
    from distributed_point_functions_tpu.core.value_types import Int
    from distributed_point_functions_tpu.ops import keygen_batch
    from distributed_point_functions_tpu.protos import serialization

    num_keys = int(os.environ.get("BENCH_KEYS", 64 if smoke else 1024))
    depths = [20, 64, 128]
    mode = os.environ.get("BENCH_KEYGEN_MODE", "numpy-threaded")
    # The scalar-loop A/B arm samples this many keys and extrapolates —
    # the loop is the ~1 ms/key reference shape being beaten.
    scalar_sample = min(
        num_keys,
        int(os.environ.get("BENCH_SCALAR_SAMPLE", 8 if smoke else 64)),
    )
    sweep = [64, 256, 1024] if not smoke else [16, 64]

    rng = np.random.default_rng(23)
    per_depth = {}
    scalar_per_depth = {}
    verified = True
    for depth in depths:
        dpf = DistributedPointFunction.create(DpfParameters(depth, Int(64)))
        alphas = [
            int.from_bytes(rng.bytes(16), "little") % (1 << depth)
            for _ in range(num_keys)
        ]
        betas = [int(x) for x in rng.integers(1, 1 << 62, size=num_keys)]
        seeds = rng.integers(0, 2**32, size=(num_keys, 2, 4), dtype=np.uint32)
        # warm at the FULL batch shape: the device modes compile one
        # program per (2K, want_value) signature, and a narrower warm
        # batch would leave the timed pass paying the compile.
        keygen_batch.generate_keys_batch(
            dpf, alphas, [betas], mode=mode, seeds=seeds
        )
        with Timer() as t:
            keys_0, keys_1 = keygen_batch.generate_keys_batch(
                dpf, alphas, [betas], mode=mode, seeds=seeds
            )
        per_depth[depth] = round(num_keys / t.elapsed)
        # scalar A/B arm: sampled prefix, same seeds.
        t0 = time.perf_counter()
        scalar_keys = [
            dpf.generate_keys(
                alphas[i], betas[i],
                seeds=(
                    int.from_bytes(seeds[i, 0].tobytes(), "little"),
                    int.from_bytes(seeds[i, 1].tobytes(), "little"),
                ),
            )
            for i in range(scalar_sample)
        ]
        scalar_per_depth[depth] = round(
            scalar_sample / (time.perf_counter() - t0)
        )
        # Host-oracle verification: the sampled scalar keys must match
        # the batched output byte for byte, both parties.
        params = dpf.validator.parameters
        for i, (want_0, want_1) in enumerate(scalar_keys):
            for got, want in ((keys_0[i], want_0), (keys_1[i], want_1)):
                if serialization.serialize_dpf_key(
                    got, params
                ) != serialization.serialize_dpf_key(want, params):
                    verified = False
        log(
            f"depth {depth}: {per_depth[depth]} keys/s batched[{mode}] vs "
            f"{scalar_per_depth[depth]} keys/s scalar "
            f"({per_depth[depth] / max(1, scalar_per_depth[depth]):.1f}x, "
            f"{scalar_sample} keys byte-checked)"
        )

    # Host-thread sweep (ISSUE 19) at the deepest depth — the shape where
    # per-key work is largest and thread-parallel sharding of the dealer
    # pays most. Each point carries the roofline model's prediction so
    # measured-vs-modeled scaling lands in the record (on a 1-core box
    # every point degenerates to the single-thread rate by design: the
    # pool is sized min(threads, cores)).
    threads_sweep = {}
    threads_model = {}
    if mode in ("numpy", "numpy-threaded"):
        from distributed_point_functions_tpu.utils import roofline

        deep = depths[-1]
        dpf = DistributedPointFunction.create(DpfParameters(deep, Int(64)))
        alphas = [
            int.from_bytes(rng.bytes(16), "little") % (1 << deep)
            for _ in range(num_keys)
        ]
        betas = [int(x) for x in rng.integers(1, 1 << 62, size=num_keys)]
        seeds = rng.integers(0, 2**32, size=(num_keys, 2, 4), dtype=np.uint32)
        spec = os.environ.get("BENCH_KEYGEN_THREADS", "1,2,4,0")
        for raw in spec.split(","):
            t_n = int(raw)
            label = "all" if t_n == 0 else str(t_n)
            eff = t_n if t_n else (os.cpu_count() or 1)
            keygen_batch.generate_keys_batch(
                dpf, alphas, [betas], mode="numpy-threaded", seeds=seeds,
                threads=eff,
            )
            with Timer() as t:
                keygen_batch.generate_keys_batch(
                    dpf, alphas, [betas], mode="numpy-threaded", seeds=seeds,
                    threads=eff,
                )
            threads_sweep[label] = round(num_keys / t.elapsed)
            threads_model[label] = round(roofline.host_thread_speedup(eff), 2)
        log(f"thread sweep depth {deep} [numpy-threaded]: " + ", ".join(
            f"{k}: {v} keys/s (model {threads_model[k]}x)"
            for k, v in threads_sweep.items()
        ))

    # Batch-size sweep at the headline depth: where amortization lands.
    sweep_rates = {}
    dpf = DistributedPointFunction.create(DpfParameters(20, Int(64)))
    for k in sweep:
        alphas = [int(x) for x in rng.integers(0, 1 << 20, size=k)]
        betas = [int(x) for x in rng.integers(1, 1 << 62, size=k)]
        keygen_batch.generate_keys_batch(dpf, alphas, [betas], mode=mode)
        with Timer() as t:
            keygen_batch.generate_keys_batch(dpf, alphas, [betas], mode=mode)
        sweep_rates[k] = round(k / t.elapsed)
    log(f"batch sweep depth 20 [{mode}]: " + ", ".join(
        f"{k}: {v} keys/s" for k, v in sweep_rates.items()
    ))
    if not verified:
        log("VERIFICATION FAILED: batched keys differ from the scalar oracle")

    return {
        "bench": "keygen",
        "metric": f"batched key generation [{mode}], {num_keys} keys, depth 20",
        "value": per_depth[20],
        "unit": "keys/s",
        "verified": verified,
        "config": {
            "num_keys": num_keys,
            "mode": mode,
            "keys_per_s_by_depth": per_depth,
            "scalar_keys_per_s_by_depth": scalar_per_depth,
            "scalar_sample": scalar_sample,
            "speedup_vs_scalar_depth20": round(
                per_depth[20] / max(1, scalar_per_depth[20]), 1
            ),
            "speedup_vs_scalar_depth128": round(
                per_depth[128] / max(1, scalar_per_depth[128]), 1
            ),
            "batch_sweep_keys_per_s": sweep_rates,
            "threads_keys_per_s_depth128": threads_sweep,
            "threads_model_speedup": threads_model,
            "host_threads_default": keygen_batch.keygen_threads(),
            "host_cores": os.cpu_count(),
        },
    }


if __name__ == "__main__":
    run_bench("keygen", bench)
