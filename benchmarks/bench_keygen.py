"""Key-generation throughput (host, batched level-major numpy AES).

Methodology of BM_KeyGeneration
(/root/reference/dpf/distributed_point_function_benchmark.cc:228-260):
single-level DPFs across tree depths. Keygen stays on CPU by design
(SURVEY.md north star) — sequential in depth, vectorized across the batch.
"""

import os

import numpy as np

from common import Timer, log, run_bench


def bench(jax, smoke):
    from distributed_point_functions_tpu.core.dpf import DistributedPointFunction
    from distributed_point_functions_tpu.core.params import DpfParameters
    from distributed_point_functions_tpu.core.value_types import Int

    num_keys = int(os.environ.get("BENCH_KEYS", 64 if smoke else 1024))
    depths = [20, 64, 128]
    rng = np.random.default_rng(23)
    per_depth = {}
    for depth in depths:
        dpf = DistributedPointFunction.create(DpfParameters(depth, Int(64)))
        alphas = [
            int.from_bytes(rng.bytes(16), "little") % (1 << depth)
            for _ in range(num_keys)
        ]
        betas = [int(x) for x in rng.integers(1, 1 << 62, size=num_keys)]
        with Timer() as t:
            dpf.generate_keys_batch(alphas, [betas])
        per_depth[depth] = round(num_keys / t.elapsed)
        log(f"depth {depth}: {per_depth[depth]} keys/s")
    return {
        "bench": "keygen",
        "metric": f"batched key generation, {num_keys} keys, depth 20",
        "value": per_depth[20],
        "unit": "keys/s",
        "config": {"num_keys": num_keys, "keys_per_s_by_depth": per_depth},
    }


if __name__ == "__main__":
    run_bench("keygen", bench)
