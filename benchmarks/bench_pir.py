"""BASELINE config 5: two-server PIR — full-domain eval + XOR inner-product
reduction, 2^24-entry database x 64 concurrent queries.

On multi-device platforms the database and evaluation tree shard over the
'domain' mesh axis and queries over 'keys' (parallel/sharded.py, XLA
collectives over ICI); on one chip the same program runs on a 1x1 mesh.
Queries run in chunks sized to HBM.
"""

import os

# Opt in to the virtual 8-device CPU platform for the sharded smoke path
# (must be set before common.init_jax creates the backend). Other benches
# stay on the 1-device client — the multi-device CPU client slows
# single-device programs ~13x on this image (see common.init_jax).
os.environ.setdefault("BENCH_MESH", "1")

import numpy as np

from common import Timer, log, run_bench


def bench(jax, smoke):
    from distributed_point_functions_tpu.core.dpf import DistributedPointFunction
    from distributed_point_functions_tpu.core.params import DpfParameters
    from distributed_point_functions_tpu.core.value_types import XorWrapper
    from distributed_point_functions_tpu.parallel import sharded

    log_domain = int(os.environ.get("BENCH_LOG_DOMAIN", 14 if smoke else 24))
    num_queries = int(os.environ.get("BENCH_QUERIES", 8 if smoke else 64))
    key_chunk = int(os.environ.get("BENCH_KEY_CHUNK", 8))
    n_dev = len(jax.devices())
    # BENCH_PIR_MESH=KxD selects the pod-scale sharded-megakernel path
    # (ISSUE 17): the megakernel-order DB rows shard over 'domain', the
    # query batch over 'keys', one shard_map program per key chunk.
    mesh_spec = os.environ.get("BENCH_PIR_MESH", "")
    pir_mesh = None
    if mesh_spec:
        try:
            k_s, d_s = (int(p) for p in mesh_spec.lower().split("x"))
        except ValueError:
            raise SystemExit(
                f"BENCH_PIR_MESH must be KxD (e.g. 2x4), got {mesh_spec!r}"
            )
        pir_mesh = sharded.make_mesh(k_s, d_s)
        mesh = pir_mesh
    elif smoke and n_dev >= 8:
        mesh = sharded.make_mesh(2, 4)
    else:
        mesh = sharded.make_mesh(1, n_dev)
    log(f"mesh: keys={mesh.shape['keys']} x domain={mesh.shape['domain']}")

    dpf = DistributedPointFunction.create(
        DpfParameters(log_domain, XorWrapper(128))
    )
    rng = np.random.default_rng(17)
    reps = int(os.environ.get("BENCH_REPS", 2))
    # Distinct query batch per rep (identical repeated programs time as ~0
    # through this image's tunnel, PERF.md); both parties' keys for the
    # warmup batch so the responses can be verified end-to-end.
    beta = (1 << 128) - 1  # all-ones: responses XOR to DB[target]
    batches, targets0 = [], None
    with Timer() as tk:
        for r in range(reps + 1):
            targets = [
                int(x) for x in rng.integers(0, 1 << log_domain, size=num_queries)
            ]
            ka, kb = dpf.generate_keys_batch(targets, [[beta] * num_queries])
            if r == 0:
                targets0, keys_b = targets, kb
            batches.append(ka)
    log(f"keygen: {tk.elapsed:.2f}s for {(reps + 1) * num_queries} queries")
    keys = batches[0]
    db = rng.integers(0, 2**32, size=(1 << log_domain, 4), dtype=np.uint32)

    single_chip = mesh.shape["keys"] == 1 and mesh.shape["domain"] == 1
    # Measured 2026-07-31 at 2^24 x 64 queries, all verified 64/64:
    # with the Mosaic row kernels, "fold" (in-program inner product)
    # reaches ~21.3 q/s / 5.7 GB/s of DB scanned vs 5.2 q/s for the slabbed
    # "fused" value-emission shape (and 3.2/1.7 q/s respectively on the
    # XLA bitslice, where HBM pressure made slabbed fused win).
    mode = os.environ.get("BENCH_PIR_MODE", "fold")
    if pir_mesh is not None:
        mode = "megakernel"  # the only mode the sharded path dispatches
    # The DB is the server's static state: permute/upload once at setup
    # (prepare_pir_database) — per-query upload would measure the host
    # link, not the query engine. Each mode consumes its own row order:
    # "megakernel" takes the in-kernel streaming layout (ISSUE 3).
    db_order = {
        "walk": "natural", "fused": "natural", "megakernel": "megakernel",
    }.get(mode, "lane")
    import jax.numpy as jnp

    with Timer() as tdb:
        if pir_mesh is not None:
            # Shard-direct upload: each device gets its own megakernel-order
            # row slab at prepare time (no post-hoc resharding of the DB).
            db_dev = sharded.prepare_pir_database(
                dpf, db, order="megakernel", mesh=pir_mesh
            )
        elif single_chip:
            db_dev = sharded.prepare_pir_database(dpf, db, order=db_order)
        else:
            db_dev = jnp.asarray(db)
        jax.block_until_ready(
            db_dev.lane_db if (single_chip or pir_mesh is not None) else db_dev
        )
    log(f"db setup (permute + upload): {tdb.elapsed:.1f}s")

    def run(qkeys):
        if pir_mesh is not None:
            return sharded.pir_query_batch_chunked(
                dpf, qkeys, db_dev, key_chunk=key_chunk,
                mode="megakernel", mesh=pir_mesh,
            )
        if single_chip:
            # One device: the chunked bulk path — no shard_map needed.
            return sharded.pir_query_batch_chunked(
                dpf, qkeys, db_dev, key_chunk=key_chunk, mode=mode
            )
        outs = []
        for start in range(0, num_queries, key_chunk):
            outs.append(
                sharded.pir_query_batch(
                    dpf, qkeys[start : start + key_chunk], db_dev, mesh
                )
            )
        return np.concatenate(outs, axis=0)

    with Timer() as warm:
        out = run(keys)
    assert out.shape == (num_queries, 4)
    log(f"warmup (compile + run): {warm.elapsed:.1f}s")
    # End-to-end verification of the warmup batch: server B's responses
    # XOR server A's must reconstruct the target records.
    out_b = run(keys_b)
    recovered = np.asarray(out) ^ np.asarray(out_b)
    n_ok = sum(
        1
        for i, tgt in enumerate(targets0)
        if (recovered[i] == db[tgt]).all()
    )
    verified = n_ok == num_queries
    log(f"two-server reconstruction: {n_ok}/{num_queries} records OK")
    with Timer() as t:
        for qkeys in batches[1:]:
            run(qkeys)
    queries = num_queries * reps
    scanned = queries * (1 << log_domain)
    result_extra = {} if verified else {
        "error": "two-server reconstruction failed on the warmup batch"
    }
    roofline_fields = {}
    if pir_mesh is not None:
        # Per-shard AND aggregate HBM roofline for the sharded record: the
        # per-eval byte model is mesh-invariant (each DB row is read on
        # exactly one 'domain' shard), the ceilings scale with chip count.
        from distributed_point_functions_tpu.utils import roofline

        n_chips = pir_mesh.shape["keys"] * pir_mesh.shape["domain"]
        roofline_fields = roofline.hbm_fields(
            scanned / t.elapsed,
            log_domain,
            strategy="megakernel",
            lpe=db.shape[1],
            pir=True,
            n_chips=n_chips,
        )
    return {
        **result_extra,
        "bench": "pir",
        "metric": (
            f"two-server PIR, 2^{log_domain} x 128-bit DB, "
            f"{num_queries} concurrent queries"
        ),
        "value": round(queries / t.elapsed, 2),
        "unit": "queries/s",
        "verified": bool(verified),
        "config": {
            "log_domain": log_domain,
            "num_queries": num_queries,
            "mesh": dict(mesh.shape),
            **({"mode": mode} if (pir_mesh is not None or mode != "fold") else {}),
            **roofline_fields,
        },
        "db_bytes_scanned_per_s": round(scanned * 16 / t.elapsed),
    }


if __name__ == "__main__":
    run_bench("pir", bench)
