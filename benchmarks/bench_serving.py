"""Serving front door A/B (ISSUE 8): continuous batching vs naive
per-request dispatch under a seeded Poisson stream of small mixed
requests.

The load generator draws ``BENCH_SERVING_REQUESTS`` requests (seeded —
the schedule replays exactly) with exponential inter-arrival gaps and a
mixed op distribution (full-domain expansions, point batches, DCF
comparisons — each request a few keys/points, the shape the engine table
says loses to dispatch latency when served one at a time). Both arms
serve the identical schedule:

* **naive** — one direct entry-point call per request, in arrival order
  (service starts at max(arrival, previous completion): an ideal
  zero-overhead sequential server).
* **frontdoor** — requests submitted to ``serving.FrontDoor`` at their
  arrival times; the continuous batcher aggregates compatible requests
  into merged batches executed through the supervisor.

On CPU the ~66 ms device dispatch latency does not exist, so the
``chunk_delay`` fault-injection stage supplies it
(``BENCH_SERVING_DELAY_MS`` per chunk launch AND finalize — the
test_pipeline overlap-proxy pattern); on a real device the bench runs
undelayed and measures the genuine tunnel latency. Both arms are forced
onto the device engine class so the A/B isolates the batcher (routing
quality is covered by CHECK_MODE=router and the router decision mix this
record also carries).

Record: throughput speedup (the headline value), per-arm req/s, p50/p95
request latency, the batch-width histogram, and the router's decision mix
from a separate auto-routed pass.

Fleet mode (ISSUE 14, ``BENCH_SERVING_MODE=fleet``): the same seeded
mixed-op idea at the FLEET tier — one party's replica pool
(serving/fleet.py) behind the frame-aware FleetProxy on loopback, driven
by ``BENCH_SERVING_THREADS`` concurrent clients. Arms: 1 replica vs
``BENCH_SERVING_REPLICAS`` (default 3) replicas serving the identical
seeded schedule; the fleet arm SIGKILLs + restarts one replica mid-run
(failover rides the client retry budget — the error count must stay 0).
Each replica is its own process, so the single-replica arm is capped by
one batcher worker + one GIL; the record's headline is the aggregate
throughput ratio. A second, in-process measurement records the Orca
fairness A/B: a 10:1 flood of per-key gate batches vs a minority op,
minority p95 under ``fair=True`` vs the FIFO baseline vs uncontended.
"""

import os

import numpy as np

from common import Timer, log, run_bench


def _make_requests(serving, rng, n, dpf, dcf, keys_fd, keys_dcf):
    """The seeded mixed-request schedule: (arrival_offset_s, Request)."""
    mean_gap = float(os.environ.get("BENCH_SERVING_GAP_MS", 5.0)) / 1e3
    gaps = rng.exponential(mean_gap, size=n)
    arrivals = np.cumsum(gaps) - gaps[0]
    lds = dcf.log_domain_size
    reqs = []
    for i in range(n):
        kind = rng.integers(0, 3)
        if kind == 0:
            reqs.append(
                serving.Request.full_domain(dpf, [keys_fd[i % len(keys_fd)]])
            )
        elif kind == 1:
            pts = [int(x) for x in rng.integers(0, dpf_domain(dpf), size=8)]
            reqs.append(
                serving.Request.evaluate_at(
                    dpf, [keys_fd[i % len(keys_fd)]], pts
                )
            )
        else:
            xs = [int(x) for x in rng.integers(0, 1 << lds, size=8)]
            reqs.append(
                serving.Request.dcf(dcf, [keys_dcf[i % len(keys_dcf)]], xs)
            )
    return list(zip(arrivals.tolist(), reqs))


def dpf_domain(dpf):
    return 1 << dpf.validator.parameters[-1].log_domain_size


def _naive_serve(schedule, evaluator, key_chunk, pipeline):
    """Sequential per-request dispatch: service begins at
    max(arrival, previous completion); returns (wall, latencies)."""
    import time

    t0 = time.perf_counter()
    latencies = []
    for arrival, req in schedule:
        now = time.perf_counter() - t0
        if now < arrival:
            time.sleep(arrival - now)
            now = arrival
        if req.op == "full_domain":
            evaluator.full_domain_evaluate(
                req.obj, list(req.keys), key_chunk=key_chunk,
                pipeline=pipeline,
            )
        elif req.op == "evaluate_at":
            evaluator.evaluate_at_batch(
                req.obj, list(req.keys), list(req.points),
                pipeline=pipeline,
            )
        else:
            req.obj.batch_evaluate(
                list(req.keys), list(req.points), pipeline=pipeline
            )
        latencies.append((time.perf_counter() - t0) - arrival)
    return time.perf_counter() - t0, latencies


def _frontdoor_serve(serving, schedule, **door_kwargs):
    import time

    with serving.FrontDoor(**door_kwargs) as door:
        t0 = time.perf_counter()
        futures = []
        for arrival, req in schedule:
            now = time.perf_counter() - t0
            if now < arrival:
                time.sleep(arrival - now)
            futures.append(door.submit(req))
        for f in futures:
            f.result(timeout=600)
        wall = time.perf_counter() - t0
    latencies = [
        (f.completed_at - t0_abs)
        for f, t0_abs in zip(
            futures, [t0 + a for a, _ in schedule]
        )
    ]
    return wall, latencies, futures


def _pcts(latencies):
    v = np.sort(np.asarray(latencies))
    return (
        round(float(v[len(v) // 2]) * 1e3, 2),
        round(float(v[min(len(v) - 1, int(len(v) * 0.95))]) * 1e3, 2),
    )


def _fleet_workload(rng):
    """One party's seeded mixed-op request set for the fleet arms, as
    PRE-ENCODED (op, payload) pairs — encoding is client-side work that
    would otherwise bound the (single-process) load generator before the
    replicas saturate. Server-side-heavy mix (the mic gate's exact-int
    host eval over a 16-bit group dominates) so replica scaling, not
    wire overhead, is what the A/B measures."""
    from distributed_point_functions_tpu.core.dpf import (
        DistributedPointFunction,
    )
    from distributed_point_functions_tpu.core.params import DpfParameters
    from distributed_point_functions_tpu.core.value_types import Int
    from distributed_point_functions_tpu.dcf.dcf import (
        DistributedComparisonFunction,
    )
    from distributed_point_functions_tpu.gates.mic import (
        MultipleIntervalContainmentGate,
    )
    from distributed_point_functions_tpu.serving import wire

    params = [DpfParameters(10, Int(64))]
    dpf = DistributedPointFunction.create(params[0])
    alphas = [int(a) for a in rng.integers(0, 1 << 10, size=8)]
    keys, _ = dpf.generate_keys_batch(alphas, [[7] * 8])
    dcf = DistributedComparisonFunction.create(16, Int(64))
    dkeys = [dcf.generate_keys(int(rng.integers(0, 1 << 16)), 99)[0]
             for _ in range(4)]
    intervals = [(2, 1000), (2000, 9000), (20000, 40000)]
    gate = MultipleIntervalContainmentGate.create(16, intervals)
    gkeys = [gate.gen(int(rng.integers(0, 1 << 16)), [3, 7, 11])[0]
             for _ in range(6)]

    def _eval_at(i):
        pts = [int(x) for x in rng.integers(0, 1 << 10, size=8)]
        return ("evaluate_at", wire.encode_evaluate_at(
            params, [keys[i % len(keys)]], pts))

    def _dcf(i):
        xs = [int(x) for x in rng.integers(0, 1 << 16, size=24)]
        return ("dcf", wire.encode_dcf(
            16, Int(64), [dkeys[i % len(dkeys)]], xs))

    def _mic(i):
        xs = [int(x) for x in rng.integers(0, 1 << 16, size=32)]
        return ("mic", wire.encode_mic(
            16, intervals, gkeys[i % len(gkeys)], xs))

    # 3:1:1 mic-dominated — ~2 ms of exact-int server work per average
    # request, an order over the load generator's per-call cost.
    kinds = (_mic, _mic, _mic, _dcf, _eval_at)
    return [kinds[int(rng.integers(0, len(kinds)))](i) for i in range(2048)]


def _drive_fleet(serving, port, calls, n, threads_n, on_progress=None):
    """n pre-encoded calls spread over threads_n serial clients against
    `port`; returns (wall, latencies, errors)."""
    import threading
    import time

    per = n // threads_n
    lock = threading.Lock()
    latencies, errors, done = [], [], [0]

    def _worker(t):
        cli = serving.DpfClient("127.0.0.1", port)
        try:
            for i in range(per):
                op, payload = calls[(t * per + i) % len(calls)]
                t0 = time.perf_counter()
                try:
                    cli.call(op, payload, deadline=120.0)
                except Exception as exc:  # noqa: BLE001 — counted, not fatal
                    with lock:
                        errors.append(f"{type(exc).__name__}: {exc}")
                    continue
                dt = time.perf_counter() - t0
                with lock:
                    latencies.append(dt)
                    done[0] += 1
                    if on_progress is not None:
                        on_progress(done[0])
        finally:
            cli.close()

    t0 = time.perf_counter()
    workers = [threading.Thread(target=_worker, args=(t,), daemon=True)
               for t in range(threads_n)]
    for w in workers:
        w.start()
    for w in workers:
        w.join(timeout=900)
    return time.perf_counter() - t0, latencies, errors


def _bench_fairness(serving, rng):
    """The Orca fairness A/B, in-process: a 10:1 flood of per-key gate
    batches (12 distinct keys = 12 compatibility queues per scan) vs a
    minority evaluate_at stream. Records the minority op's p95 under
    fair round-robin ordering vs the FIFO baseline vs uncontended."""
    import time

    from distributed_point_functions_tpu.core.dpf import (
        DistributedPointFunction,
    )
    from distributed_point_functions_tpu.core.params import DpfParameters
    from distributed_point_functions_tpu.core.value_types import Int
    from distributed_point_functions_tpu.gates.mic import (
        MultipleIntervalContainmentGate,
    )

    params = DpfParameters(8, Int(64))
    dpf = DistributedPointFunction.create(params)
    mkey, _ = dpf.generate_keys(3, 5)
    intervals = [(2, 1000), (2000, 9000), (20000, 40000)]
    gate = MultipleIntervalContainmentGate.create(16, intervals)
    gkeys = [gate.gen(int(rng.integers(0, 1 << 16)), [3, 7, 11])[0]
             for _ in range(16)]
    rounds = int(os.environ.get("BENCH_SERVING_FAIR_ROUNDS", 25))

    def _minority():
        return serving.Request.evaluate_at(dpf, [mkey], [1, 2, 3, 4])

    def _run(fair, flood):
        minority_lat = []
        with serving.FrontDoor(
            engine="host", max_wait_ms=2.0, width_target=64, fair=fair,
        ) as door:
            futures = []
            for r in range(rounds):
                if flood:
                    for j in range(10):
                        xs = [int(x) for x in rng.integers(0, 1 << 16,
                                                          size=8)]
                        gk = gkeys[(r * 10 + j) % len(gkeys)]
                        futures.append(door.submit(
                            serving.Request.mic(gate, gk, xs)
                        ))
                fut = door.submit(_minority())
                futures.append(fut)
                minority_lat.append(fut)
                time.sleep(0.002)
            for f in futures:
                f.result(timeout=300)
        lats = sorted(f.latency_seconds for f in minority_lat)
        return lats[min(len(lats) - 1, int(len(lats) * 0.95))] * 1e3

    # Warm the per-process host caches (crypto objects, params
    # signatures, the host oracle's value tables) OUT of the timed arms —
    # the uncontended arm runs first and must not read as cold-start.
    _run(fair=True, flood=False)
    p95_u = _run(fair=True, flood=False)
    p95_fair = _run(fair=True, flood=True)
    p95_fifo = _run(fair=False, flood=True)
    return {
        "rounds": rounds,
        "flood_ratio": 10,
        "uncontended_p95_ms": round(p95_u, 2),
        "fair_p95_ms": round(p95_fair, 2),
        "fifo_p95_ms": round(p95_fifo, 2),
        "fair_factor_vs_uncontended": round(p95_fair / max(p95_u, 1e-9), 2),
        "fifo_factor_vs_uncontended": round(p95_fifo / max(p95_u, 1e-9), 2),
    }


def _bench_fleet(jax, smoke):
    """BENCH_SERVING_MODE=fleet: 1-replica vs N-replica aggregate
    throughput behind the FleetProxy, with a mid-run kill/restart on the
    fleet arm, plus the in-process fairness A/B."""
    import time

    from distributed_point_functions_tpu import serving
    from distributed_point_functions_tpu.serving import fleet as fleet_mod

    replicas = int(os.environ.get("BENCH_SERVING_REPLICAS", 3))
    n = int(os.environ.get("BENCH_SERVING_REQUESTS", 480 if smoke else 2400))
    threads_n = int(os.environ.get("BENCH_SERVING_THREADS", 16))
    rng = np.random.default_rng(int(os.environ.get("BENCH_SEED", 17)))
    calls = _fleet_workload(rng)
    server_args = ["--engine", "host", "--max-wait-ms", "2"]

    arms = {}
    for label, count in (("single", 1), ("fleet", replicas)):
        pool = fleet_mod.ReplicaPool(replicas=count, server_args=server_args)
        proxy = None
        try:
            with Timer() as tup:
                pool.start()
                proxy = serving.FleetProxy(pool.endpoints).start()
                probe = serving.DpfClient("127.0.0.1", proxy.port)
                probe.wait_ready(timeout=180)
                probe.close()
            log(f"{label}: {count} replica(s) up in {tup.elapsed:.1f}s")
            # warm: every op family once per client-thread count
            _drive_fleet(serving, proxy.port, calls, threads_n * 4,
                         threads_n)
            killer = {}
            if label == "fleet":
                # mid-run chaos: SIGKILL the hottest replica at ~1/3 of
                # the run, restart it (same port) — failover must ride
                # the client retry budget with zero errors.
                def _maybe_kill(done, _state={"fired": False}):
                    if _state["fired"] or done < n // 3:
                        return
                    _state["fired"] = True

                    def _chaos():
                        st = proxy._stats()
                        routed = {
                            r["endpoint"]: r["routed"]
                            for r in st["fleet"]["replicas"]
                        }
                        victim = max(
                            range(count),
                            key=lambda i: routed.get(
                                f"127.0.0.1:{pool.ports[i]}", 0),
                        )
                        log(f"fleet: SIGKILL replica {victim} mid-run")
                        pool.kill(victim)
                        time.sleep(0.3)
                        pool.restart(victim)
                        log(f"fleet: replica {victim} restarted")

                    import threading

                    th = threading.Thread(target=_chaos, daemon=True)
                    th.start()
                    killer["thread"] = th

                on_progress = _maybe_kill
            else:
                on_progress = None
            wall, lats, errors = _drive_fleet(
                serving, proxy.port, calls, n, threads_n,
                on_progress=on_progress,
            )
            if killer.get("thread") is not None:
                killer["thread"].join(timeout=120)
            if not lats:
                # Surface the recorded failures instead of dying on an
                # empty-percentile IndexError (which would also discard
                # the other arm's results).
                raise RuntimeError(
                    f"{label} arm served 0 of {n} requests; "
                    f"errors: {errors[:3]}"
                )
            p50, p95 = _pcts(lats)
            stats = proxy._stats()
            arms[label] = {
                "replicas": count,
                "req_per_sec": round(len(lats) / wall, 1),
                "served": len(lats),
                "errors": len(errors),
                "error_samples": errors[:3],
                "latency_ms": {"p50": p50, "p95": p95},
                "fleet_counters": stats["fleet"]["counters"],
            }
            log(f"{label}: {len(lats)}/{n} in {wall:.1f}s "
                f"({len(lats) / wall:.0f} req/s), p95 {p95} ms, "
                f"errors {len(errors)}")
        finally:
            if proxy is not None:
                proxy.stop()
            pool.stop()

    fairness = _bench_fairness(serving, rng)
    log(f"fairness: {fairness}")
    speedup = (
        arms["fleet"]["req_per_sec"] / max(arms["single"]["req_per_sec"], 1e-9)
    )
    return {
        "bench": "serving",
        "metric": "fleet_aggregate_throughput_vs_single_replica",
        "value": round(speedup, 3),
        "unit": "x",
        "config": {
            "mode": "fleet",
            "requests": n,
            "threads": threads_n,
            "arms": arms,
            "fairness": fairness,
        },
    }


def _drive_timed(serving, port, calls, threads_n, duration):
    """threads_n serial clients hammering pre-encoded calls against
    `port` until `duration` elapses; returns (latencies, errors)."""
    import threading
    import time

    lock = threading.Lock()
    latencies, errors = [], []
    t_stop = time.perf_counter() + duration

    def _worker(t):
        cli = serving.DpfClient("127.0.0.1", port)
        try:
            i = 0
            while time.perf_counter() < t_stop:
                op, payload = calls[(t * 997 + i) % len(calls)]
                i += 1
                t0 = time.perf_counter()
                try:
                    cli.call(op, payload, deadline=120.0)
                except Exception as exc:  # noqa: BLE001 — counted, not fatal
                    with lock:
                        errors.append(f"{type(exc).__name__}: {exc}")
                    continue
                dt = time.perf_counter() - t0
                with lock:
                    latencies.append(dt)
        finally:
            cli.close()

    workers = [threading.Thread(target=_worker, args=(t,), daemon=True)
               for t in range(threads_n)]
    for w in workers:
        w.start()
    for w in workers:
        w.join(timeout=900)
    return latencies, errors


def _bench_tenant_qos(serving, rng):
    """The multi-tenant admission-quota A/B, in-process: a flood tenant
    submitting 10 per-key gate batches per round vs a minority tenant's
    evaluate_at stream, all arms under FIFO flush ordering so quotas —
    not fair rotation — are what the A/B isolates. Records the minority
    tenant's p95 uncontended, under the unquota'd flood, and under a
    flood bounded by its admission quota (the flood sheds ONLY itself:
    over-quota submits fail fast with RESOURCE_EXHAUSTED)."""
    import time

    from distributed_point_functions_tpu.core.dpf import (
        DistributedPointFunction,
    )
    from distributed_point_functions_tpu.core.params import DpfParameters
    from distributed_point_functions_tpu.core.value_types import Int
    from distributed_point_functions_tpu.gates.mic import (
        MultipleIntervalContainmentGate,
    )
    from distributed_point_functions_tpu.utils.errors import (
        ResourceExhaustedError,
    )

    params = DpfParameters(8, Int(64))
    dpf = DistributedPointFunction.create(params)
    mkey, _ = dpf.generate_keys(3, 5)
    intervals = [(2, 1000), (2000, 9000), (20000, 40000)]
    gate = MultipleIntervalContainmentGate.create(16, intervals)
    gkeys = [gate.gen(int(rng.integers(0, 1 << 16)), [3, 7, 11])[0]
             for _ in range(16)]
    rounds = int(os.environ.get("BENCH_SERVING_FAIR_ROUNDS", 25))

    def _run(flood, quota):
        minority_lat, shed = [], [0]
        kwargs = {}
        if quota:
            kwargs["tenant_quotas"] = {"flood": quota}
        with serving.FrontDoor(
            engine="host", max_wait_ms=2.0, width_target=64, fair=False,
            **kwargs,
        ) as door:
            futures = []
            for r in range(rounds):
                if flood:
                    for j in range(10):
                        xs = [int(x) for x in rng.integers(0, 1 << 16,
                                                          size=8)]
                        gk = gkeys[(r * 10 + j) % len(gkeys)]
                        req = serving.Request.mic(gate, gk, xs).with_tenant(
                            "flood"
                        )
                        try:
                            futures.append(door.submit(req))
                        except ResourceExhaustedError:
                            shed[0] += 1  # the flood sheds only itself
                fut = door.submit(
                    serving.Request.evaluate_at(
                        dpf, [mkey], [1, 2, 3, 4]
                    ).with_tenant("minority")
                )
                futures.append(fut)
                minority_lat.append(fut)
                time.sleep(0.002)
            for f in futures:
                f.result(timeout=300)
        lats = sorted(f.latency_seconds for f in minority_lat)
        p95 = lats[min(len(lats) - 1, int(len(lats) * 0.95))] * 1e3
        return p95, shed[0]

    # Warm the host caches out of the timed arms (the fairness-bench
    # pattern: the uncontended arm must not read as cold-start).
    quota = int(os.environ.get("BENCH_SERVING_FLOOD_QUOTA", 2))
    _run(flood=False, quota=0)
    p95_u, _ = _run(flood=False, quota=0)
    p95_fifo, _ = _run(flood=True, quota=0)
    p95_quota, shed = _run(flood=True, quota=quota)
    return {
        "rounds": rounds,
        "flood_ratio": 10,
        "flood_quota": quota,
        "uncontended_p95_ms": round(p95_u, 2),
        "flood_fifo_p95_ms": round(p95_fifo, 2),
        "flood_quota_p95_ms": round(p95_quota, 2),
        "flood_shed": shed,
        "fifo_factor_vs_uncontended": round(p95_fifo / max(p95_u, 1e-9), 2),
        "quota_factor_vs_uncontended": round(
            p95_quota / max(p95_u, 1e-9), 2
        ),
    }


def _bench_autoscale(jax, smoke):
    """BENCH_SERVING_MODE=autoscale (ISSUE 20): the diurnal elasticity
    A/B. A seeded 4x day/night client swing (night -> day -> night ->
    idle tail) is served by two arms over the IDENTICAL phase schedule:

    * **static** — a fleet held at peak size for the whole run (the
      capacity-planning baseline: provisioned for the day, idle all
      night);
    * **autoscale** — one replica plus the AutoScaler on the proxy's
      stats/health signal (min 1, max = the same peak).

    The headline is replica-seconds (integrated live-replica count over
    the schedule) autoscaled vs static-peak, with the p95 ratio as the
    latency-cost guard. A second, in-process measurement records the
    tenant-quota QoS A/B (10:1 flood, minority p95 quota'd vs FIFO)."""
    import threading
    import time

    from distributed_point_functions_tpu import serving
    from distributed_point_functions_tpu.serving import fleet as fleet_mod

    peak = int(os.environ.get("BENCH_SERVING_REPLICAS", 3))
    lo = max(1, int(os.environ.get("BENCH_SERVING_THREADS", 8)) // 4)
    hi = lo * 4  # the 4x diurnal swing
    # A 24-beat diurnal cycle (1 beat ~ 1 hour, day peak = 6 beats): the
    # peak is a MINORITY of the cycle — the whole reason static-peak
    # provisioning wastes replica-seconds.
    scale_t = 0.5 if smoke else 1.0
    phases = [
        ("night", lo, 6.0 * scale_t),
        ("day", hi, 6.0 * scale_t),
        ("night", lo, 6.0 * scale_t),
        ("idle", 0, 6.0 * scale_t),
    ]
    rng = np.random.default_rng(int(os.environ.get("BENCH_SEED", 17)))
    calls = _fleet_workload(rng)
    server_args = ["--engine", "host", "--max-wait-ms", "2"]

    def _run_arm(autoscale):
        label = "autoscale" if autoscale else "static"
        pool = fleet_mod.ReplicaPool(
            replicas=1 if autoscale else peak, server_args=server_args,
        )
        proxy = scaler = sampler = None
        stop_sampling = threading.Event()
        sample = {"rs": 0.0, "peak": 0, "floor": peak + 1}
        try:
            pool.start()
            proxy = serving.FleetProxy(
                pool.endpoints, probe_interval=0.25,
            ).start()
            probe = serving.DpfClient("127.0.0.1", proxy.port)
            probe.wait_ready(timeout=180)
            probe.close()
            if autoscale:
                scaler = serving.AutoScaler(
                    proxy, pool, plane="eval", min_replicas=1,
                    max_replicas=peak, interval=0.2, up_backlog=3.0,
                    down_backlog=1.25, sustain=2, cooldown=1.0,
                    drain_timeout=10.0,
                )
                scaler.start()
            # warm every op family out of the measured window
            _drive_fleet(serving, proxy.port, calls, 8, 4)

            def _sampler():
                prev = time.perf_counter()
                while not stop_sampling.is_set():
                    time.sleep(0.05)
                    now = time.perf_counter()
                    live = len(pool.running_indices())
                    sample["rs"] += live * (now - prev)
                    sample["peak"] = max(sample["peak"], live)
                    sample["floor"] = min(sample["floor"], live)
                    prev = now

            sampler = threading.Thread(target=_sampler, daemon=True)
            sampler.start()
            lats, errs, per_phase = [], [], []
            for name, threads_n, duration in phases:
                if threads_n == 0:
                    time.sleep(duration)
                    per_phase.append({"phase": name, "served": 0})
                    continue
                pl, pe = _drive_timed(
                    serving, proxy.port, calls, threads_n, duration
                )
                lats += pl
                errs += pe
                p50, p95 = _pcts(pl) if pl else (None, None)
                per_phase.append({
                    "phase": name, "threads": threads_n,
                    "served": len(pl), "p95_ms": p95,
                })
                log(f"{label}/{name}: {len(pl)} served at {threads_n} "
                    f"threads, p95 {p95} ms, replicas now "
                    f"{len(pool.running_indices())}")
            stop_sampling.set()
            sampler.join(timeout=10)
            if not lats:
                raise RuntimeError(
                    f"{label} arm served 0 requests; errors: {errs[:3]}"
                )
            p50, p95 = _pcts(lats)
            arm = {
                "replicas_peak_observed": sample["peak"],
                "replicas_floor_observed": sample["floor"],
                "replica_seconds": round(sample["rs"], 1),
                "served": len(lats),
                "errors": len(errs),
                "error_samples": errs[:3],
                "latency_ms": {"p50": p50, "p95": p95},
                "phases": per_phase,
            }
            if scaler is not None:
                arm["scaler"] = scaler.stats()
            log(f"{label}: {len(lats)} served, p95 {p95} ms, "
                f"{arm['replica_seconds']} replica-seconds "
                f"(floor {sample['floor']}, peak {sample['peak']})")
            return arm
        finally:
            stop_sampling.set()
            if scaler is not None:
                scaler.stop()
            if proxy is not None:
                proxy.stop()
            pool.stop()

    arms = {"static": _run_arm(False), "autoscale": _run_arm(True)}
    tenant_qos = _bench_tenant_qos(serving, rng)
    log(f"tenant QoS: {tenant_qos}")
    rs_ratio = arms["autoscale"]["replica_seconds"] / max(
        arms["static"]["replica_seconds"], 1e-9
    )
    p95_ratio = arms["autoscale"]["latency_ms"]["p95"] / max(
        arms["static"]["latency_ms"]["p95"], 1e-9
    )
    return {
        "bench": "serving",
        "metric": "autoscale_replica_seconds_vs_static_peak",
        "value": round(rs_ratio, 3),
        "unit": "x",
        "config": {
            "mode": "autoscale",
            "peak_replicas": peak,
            "diurnal_threads": [lo, hi],
            "phases": [
                {"phase": n, "threads": t, "seconds": d}
                for n, t, d in phases
            ],
            "p95_ratio_vs_static": round(p95_ratio, 3),
            "arms": arms,
            "tenant_qos": tenant_qos,
        },
    }


def bench(jax, smoke):
    mode = os.environ.get("BENCH_SERVING_MODE", "ab")
    if mode == "fleet":
        return _bench_fleet(jax, smoke)
    if mode == "autoscale":
        return _bench_autoscale(jax, smoke)
    from distributed_point_functions_tpu import serving
    from distributed_point_functions_tpu.core.dpf import (
        DistributedPointFunction,
    )
    from distributed_point_functions_tpu.core.params import DpfParameters
    from distributed_point_functions_tpu.core.value_types import Int
    from distributed_point_functions_tpu.dcf.dcf import (
        DistributedComparisonFunction,
    )
    from distributed_point_functions_tpu.ops import evaluator, supervisor
    from distributed_point_functions_tpu.utils import faultinject, telemetry

    n = int(os.environ.get("BENCH_SERVING_REQUESTS", 64 if smoke else 200))
    lds = int(os.environ.get("BENCH_SERVING_LOG_DOMAIN", 6 if smoke else 14))
    # Serving-realistic chunking: merged full-domain batches dispatch
    # ceil(K/32) programs vs one per request — the amortization itself.
    # (key_chunk=2 would make full-domain dispatches scale with keys and
    # cancel the merge win; it exists only for test-suite shape reuse.)
    key_chunk = int(os.environ.get("BENCH_SERVING_KEY_CHUNK", 32))
    width = int(os.environ.get("BENCH_SERVING_WIDTH", 64))
    max_wait_ms = float(os.environ.get("BENCH_SERVING_WAIT_MS", 10.0))
    # CPU proxy: injected per-chunk dispatch latency (launch + finalize
    # each). 0 on device — the tunnel supplies the real thing.
    delay_ms = float(
        os.environ.get(
            "BENCH_SERVING_DELAY_MS",
            12.0 if jax.default_backend() == "cpu" else 0.0,
        )
    )
    pool = 32  # distinct key pool the schedule cycles through
    rng = np.random.default_rng(int(os.environ.get("BENCH_SEED", 17)))

    dpf = DistributedPointFunction.create(DpfParameters(lds, Int(64)))
    dcf = DistributedComparisonFunction.create(lds, Int(64))
    with Timer() as tk:
        alphas = [int(x) for x in rng.integers(0, 1 << lds, size=pool)]
        betas = [[int(x) for x in rng.integers(1, 1000, size=pool)]]
        keys_fd, _ = dpf.generate_keys_batch(alphas, betas)
        keys_dcf = [
            dcf.generate_keys(int(rng.integers(0, 1 << lds)), 4242)[0]
            for _ in range(4)
        ]
    log(f"keygen: {tk.elapsed:.2f}s ({pool} DPF + 4 DCF keys)")

    schedule = _make_requests(serving, rng, n, dpf, dcf, keys_fd, keys_dcf)
    mix = {}
    for _, r in schedule:
        mix[r.op] = mix.get(r.op, 0) + 1
    log(f"schedule: {n} requests, op mix {mix}")

    def delay_plan():
        d = delay_ms / 1e3
        return faultinject.FaultPlan(
            stage="chunk_delay", delay_launch=d, delay_finalize=d
        )

    def with_delay(fn):
        if delay_ms <= 0:
            return fn()
        with faultinject.inject(delay_plan()):
            return fn()

    # Warm BOTH arms by replaying the full schedule once, UNDER the same
    # injected delays but untimed: XLA compiles of every program family
    # an arm will touch and the supervisor's one-time probe caches must
    # never read as dispatch latency (the walkkernel-budget lesson; on
    # hardware the .jax_cache plays this role). The warm pass keeps the
    # delays so the batcher's flush timing — and therefore the bucketed
    # merged-batch shapes the timed pass will compile against — matches.
    with Timer() as tw:
        with_delay(
            lambda: _naive_serve(
                _replay(schedule), evaluator, key_chunk, None
            )
        )
        # Two front-door replays: batch composition (and therefore the
        # bucketed shapes) depends on queue timing, so shapes that only
        # appear once the queues run deep compile during the FIRST warm
        # replay; the second confirms the steady state a long-running
        # server sits in.
        for _ in range(2):
            with_delay(
                lambda: _frontdoor_serve(
                    serving, _replay(schedule), engine="device",
                    max_wait_ms=max_wait_ms, width_target=width,
                    key_chunk=key_chunk, pipeline=True,
                )
            )
    log(f"warm pass (both arms, compiles + probe caches): {tw.elapsed:.2f}s")

    naive_sched = _replay(schedule)
    naive_wall, naive_lat = with_delay(
        lambda: _naive_serve(naive_sched, evaluator, key_chunk, None)
    )
    log(f"naive: {naive_wall:.2f}s ({n / naive_wall:.1f} req/s)")

    door_sched = _replay(schedule)
    with telemetry.capture() as tel:
        door_wall, door_lat, futures = with_delay(
            lambda: _frontdoor_serve(
                serving, door_sched, engine="device",
                max_wait_ms=max_wait_ms, width_target=width,
                key_chunk=key_chunk, pipeline=True,
            )
        )
    snap = tel.snapshot()
    log(f"frontdoor: {door_wall:.2f}s ({n / door_wall:.1f} req/s)")

    # Router decision mix: replay the schedule once through engine="auto"
    # (undelayed, after the timed arms) so the record shows what the
    # cost model would pick live.
    with telemetry.capture() as tel_auto:
        _frontdoor_serve(
            serving, [(0.0, r) for _, r in _replay(schedule)],
            engine="auto", max_wait_ms=max_wait_ms, width_target=width,
            key_chunk=key_chunk,
        )
    decisions = {}
    for d in tel_auto.decision_records(source="router"):
        label = d["data"].get("choice", "?")
        decisions[label] = decisions.get(label, 0) + 1

    speedup = naive_wall / door_wall if door_wall > 0 else 0.0
    p50_n, p95_n = _pcts(naive_lat)
    p50_d, p95_d = _pcts(door_lat)
    widths = snap["histograms"].get("serving.batch_width", {})
    return {
        "bench": "serving",
        "metric": "frontdoor_speedup_vs_naive",
        "value": round(speedup, 3),
        "unit": "x",
        "config": {
            "requests": n,
            "log_domain": lds,
            "key_chunk": key_chunk,
            "width_target": width,
            "max_wait_ms": max_wait_ms,
            "injected_delay_ms": delay_ms,
            "op_mix": mix,
            "naive_req_per_sec": round(n / naive_wall, 2),
            "frontdoor_req_per_sec": round(n / door_wall, 2),
            "naive_latency_ms": {"p50": p50_n, "p95": p95_n},
            "frontdoor_latency_ms": {"p50": p50_d, "p95": p95_d},
            "batch_width": {
                k: widths.get(k) for k in ("count", "p50", "max") if widths
            },
            "router_decision_mix": decisions,
            "batches": int(
                sum(
                    v
                    for k, v in snap["counters"].items()
                    if k.startswith("serving.batches")
                )
            ),
        },
        **telemetry.bench_fields(snap),
    }


def _replay(schedule):
    """Clones the schedule with fresh futures (a Request's future is
    single-shot; warm passes, timed arms and the decision-mix pass each
    re-serve the identical work)."""
    import dataclasses

    from distributed_point_functions_tpu.serving.batcher import ServedFuture

    return [
        (arrival, dataclasses.replace(r, future=ServedFuture()))
        for arrival, r in schedule
    ]


if __name__ == "__main__":
    run_bench("serving", bench)
