"""Streaming heavy-hitters ingestion rate (ISSUE 15).

The write-heavy tier's throughput question: how many client keys per
second can the two-server pair ACCEPT — journal-fsync'd, deduped,
windowed — and how fast do closed windows publish behind the ingest
front? By design the system is **keygen-bound**: every uploaded key is a
client-side incremental DPF keygen (fed here through the ISSUE 19
threaded batched dealer, `host_generate_keys_batch` — the feed-rate
ceiling for any client fleet), so the serving-side interesting numbers
are the ingest ack rate (the fsync + dedup + window accounting path)
and the publish lag.

Arms, one seeded run on loopback (two in-process servers; the leader
drives the advance against the follower over the real wire):

* ``ingest`` — keys/s acknowledged across ``BENCH_STREAM_THREADS``
  concurrent uploading clients (keys pre-generated through the threaded
  dealer; the measured feed rate lands in
  ``client_keygen_keys_per_sec``);
* ``publish`` — wall from the final flush to every window published
  (the level-by-level advance + peer exchange for the whole backlog);
* ``failover`` (ISSUE 16) — the leader is stopped WITHOUT releasing its
  lease (the crash shape), the ex-leader restarts as a demoted
  follower, and the wall from the kill to (a) the follower's lease
  promotion and (b) the first post-flip publish of the backlog window
  is measured against the lease TTL.

CPU-only (the host-engine advance is the production default; the
hierkernel arm stays staged-for-tunnel behind the stream's mode knob).
"""

import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

from common import Timer, log, run_bench


def smoke_shrink(smoke: bool) -> bool:
    """CPU smoke runs shrink the batch count; the record is tagged by
    run_bench either way."""
    return smoke


def _bench_failover(serving, dpf, bits, bpl, n_levels, lease_ttl):
    """Measures the ISSUE 16 failover path: leader crash (lease NOT
    released), ex-leader restarted as a demoted follower on the same
    port + journals, the follower promoted by lease expiry, and the
    backlog window published under the new epoch. Returns
    (promote_wall_s, first_post_flip_publish_wall_s), both from the
    kill."""
    cfg = serving.StreamConfig.bitwise(
        "flip", bits, bpl, threshold=8, window_keys=16,
        max_pending_windows=1 << 30,
    )
    tmp = tempfile.mkdtemp(prefix="dpf-bench-failover-")
    lease_dir = os.path.join(tmp, "lease")
    policy = serving.RetryPolicy(
        attempts=8, base_backoff=0.05, max_backoff=0.5,
        connect_attempts=80, connect_backoff=0.1, seed=0,
    )

    f_stream = serving.HeavyHitterStream(
        cfg, os.path.join(tmp, "p1"), role="follower",
        lease_dir=lease_dir, lease_ttl=lease_ttl, owner="bench-p1",
    )
    f_srv = serving.DpfServer(engine="host", max_wait_ms=1.0)
    f_srv.register_stream(f_stream)
    f_srv.start()
    l_srv = serving.DpfServer(engine="host", max_wait_ms=1.0)
    l_stream = serving.HeavyHitterStream(
        cfg, os.path.join(tmp, "p0"), peer=("127.0.0.1", f_srv.port),
        lease_dir=lease_dir, lease_ttl=lease_ttl, owner="bench-p0",
    )
    l_srv.register_stream(l_stream)
    l_srv.start()
    # The follower's promotion legs need the leader's endpoint, which
    # only exists now (both sides of an in-process pair cannot name
    # each other before either binds). start() is re-entrant: with the
    # peer known it starts the advance worker a promoted follower
    # drives.
    f_stream.peer = ("127.0.0.1", l_srv.port)
    f_stream.start()

    def _keys(vals):
        k0s, k1s = [], []
        for v in vals:
            k0, k1 = dpf.generate_keys_incremental(int(v), [1] * n_levels)
            k0s.append(k0)
            k1s.append(k1)
        return k0s, k1s

    endpoints = [("127.0.0.1", l_srv.port), ("127.0.0.1", f_srv.port)]
    client = serving.TwoServerClient(endpoints, policy=policy)
    client.wait_ready(timeout=60)
    rng = np.random.default_rng(16)
    # Warm window: the full publish path is live before the kill.
    client.hh_ingest("flip", cfg.parameters, _keys([1] * 9), "warm",
                     flush=True, deadline=60.0)
    deadline = time.perf_counter() + 60
    while time.perf_counter() < deadline:
        if client.clients[1].hh_snapshot("flip", deadline=10.0)["published"]:
            break
        time.sleep(0.02)
    else:
        raise RuntimeError("failover arm: warm window never published")
    # The backlog: 12 of 16 window keys — the window stays OPEN, so the
    # dying leader cannot publish it early; the post-flip flush closes
    # it under the new leader.
    for i in range(3):
        vals = [int(v) for v in rng.integers(0, 1 << bits, size=4)]
        client.hh_ingest("flip", cfg.parameters, _keys(vals), f"flip-{i}",
                         deadline=60.0)

    t_kill = time.perf_counter()
    l_stream.release_on_stop = False  # the crash shape: lease left held
    l_srv.stop()

    promote_s = None
    deadline = time.perf_counter() + 60
    while time.perf_counter() < deadline:
        if f_stream.role == "leader":
            promote_s = time.perf_counter() - t_kill
            break
        time.sleep(0.005)
    if promote_s is None:
        raise RuntimeError("failover arm: follower never promoted")

    # The ex-leader returns on the same port + journals once the flip
    # is decided (restarting inside the expiry window would race the
    # follower for the lease); boot arbitration finds the promoted
    # leader's live lease and demotes it to follower.
    l_srv2 = serving.DpfServer(engine="host", max_wait_ms=1.0,
                               port=endpoints[0][1])
    l_srv2.register_stream(serving.HeavyHitterStream(
        cfg, os.path.join(tmp, "p0"), peer=("127.0.0.1", f_srv.port),
        lease_dir=lease_dir, lease_ttl=lease_ttl, owner="bench-p0r",
    ))
    l_srv2.start()

    flip_publish_s = None
    fin = serving.TwoServerClient(endpoints, policy=policy)
    deadline = time.perf_counter() + 120
    while time.perf_counter() < deadline:
        try:
            fin.hh_ingest("flip", cfg.parameters, ([], []), "",
                          flush=True, deadline=30.0)
            snap = fin.clients[1].hh_snapshot("flip", deadline=10.0)
        except Exception:  # noqa: BLE001 — restart settling
            time.sleep(0.02)
            continue
        done = {b for w in snap["published"] for b in w["batch_ids"]}
        if "flip-0" in done:
            flip_publish_s = time.perf_counter() - t_kill
            break
        time.sleep(0.005)
    fin.close()
    client.close()
    f_srv.stop()
    l_srv2.stop()
    if flip_publish_s is None:
        raise RuntimeError(
            "failover arm: backlog window never published post-flip"
        )
    return promote_s, flip_publish_s


def bench_streaming(jax, smoke):
    del jax
    from distributed_point_functions_tpu import serving
    from distributed_point_functions_tpu.core.dpf import (
        DistributedPointFunction,
    )
    from distributed_point_functions_tpu.ops import keygen_batch

    n_threads = int(os.environ.get("BENCH_STREAM_THREADS", 4))
    n_batches = int(os.environ.get(
        "BENCH_STREAM_BATCHES", 10 if smoke_shrink(smoke) else 40
    ))
    keys_per_batch = int(os.environ.get("BENCH_STREAM_BATCH_KEYS", 4))
    bits, bpl = 16, 2
    window_keys = int(os.environ.get("BENCH_STREAM_WINDOW", 64))

    cfg = serving.StreamConfig.bitwise(
        "bench", bits, bpl, threshold=8, window_keys=window_keys,
        max_pending_windows=1 << 30,  # measure raw rates, not the shed
    )
    dpf = DistributedPointFunction.create_incremental(list(cfg.parameters))
    n_levels = len(cfg.parameters)

    tmp = tempfile.mkdtemp(prefix="dpf-bench-stream-")
    follower = serving.DpfServer(engine="host", max_wait_ms=1.0)
    follower.register_stream(
        serving.HeavyHitterStream(cfg, os.path.join(tmp, "p1"))
    )
    follower.start()
    leader = serving.DpfServer(engine="host", max_wait_ms=1.0)
    leader.register_stream(serving.HeavyHitterStream(
        cfg, os.path.join(tmp, "p0"), peer=("127.0.0.1", follower.port),
    ))
    leader.start()
    policy = serving.RetryPolicy(
        attempts=8, base_backoff=0.05, max_backoff=0.5, seed=0,
    )

    rng = np.random.default_rng(20260804)
    hot = [int(v) for v in rng.integers(0, 1 << bits, size=4)]
    log(f"pre-generating {n_threads * n_batches} batches x "
        f"{keys_per_batch} keys (client keygen, the PR 13-bound side)")
    t0 = time.perf_counter()
    schedule = {}
    for t in range(n_threads):
        for i in range(n_batches):
            pool = hot * 4 + [
                int(v) for v in rng.integers(0, 1 << bits, size=4)
            ]
            vals = [
                pool[j]
                for j in rng.integers(0, len(pool), size=keys_per_batch)
            ]
            k0s, k1s = keygen_batch.host_generate_keys_batch(
                dpf, vals, [[1] * len(vals)] * n_levels
            )
            schedule[f"t{t}-b{i}"] = (k0s, k1s)
    keygen_wall = time.perf_counter() - t0
    total_keys = n_threads * n_batches * keys_per_batch
    log(f"client keygen: {total_keys} keys in {keygen_wall:.2f}s "
        f"({total_keys / keygen_wall:.0f} keys/s threaded batched dealer)")

    endpoints = [("127.0.0.1", leader.port), ("127.0.0.1", follower.port)]
    warm = serving.TwoServerClient(endpoints, policy=policy)
    warm.wait_ready(timeout=60)
    warm.close()

    def _worker(t_index):
        client = serving.TwoServerClient(endpoints, policy=policy)
        try:
            for i in range(n_batches):
                bid = f"t{t_index}-b{i}"
                client.hh_ingest(
                    "bench", cfg.parameters, schedule[bid], bid,
                    deadline=60.0,
                )
        finally:
            client.close()

    with Timer() as t_ingest:
        workers = [
            threading.Thread(target=_worker, args=(t,), daemon=True)
            for t in range(n_threads)
        ]
        for w in workers:
            w.start()
        for w in workers:
            w.join()

    fin = serving.TwoServerClient(endpoints, policy=policy)
    with Timer() as t_publish:
        fin.hh_ingest("bench", cfg.parameters, ([], []), "", flush=True,
                      deadline=60.0)
        deadline = time.perf_counter() + 300
        snap = None
        while time.perf_counter() < deadline:
            snap = fin.clients[0].hh_snapshot("bench", deadline=10.0)
            done = {b for w in snap["published"] for b in w["batch_ids"]}
            if len(done) == len(schedule) and snap["pending_windows"] == 0:
                break
            time.sleep(0.05)
    stats = snap["stats"]
    fin.close()
    leader.stop()
    follower.stop()
    assert stats["accepted_keys"] == total_keys, "lost keys"

    ingest_rate = total_keys / t_ingest.elapsed
    log(f"ingest: {total_keys} keys ({len(schedule)} batches, "
        f"{n_threads} clients) acked in {t_ingest.elapsed:.2f}s = "
        f"{ingest_rate:.0f} keys/s; publish drain {t_publish.elapsed:.2f}s "
        f"for {stats['windows_published']} windows")

    # ---- failover arm (ISSUE 16): leader kill -> lease flip ----------
    lease_ttl = float(os.environ.get("BENCH_STREAM_LEASE_TTL", 0.5))
    promote_s, flip_publish_s = _bench_failover(
        serving, dpf, bits, bpl, n_levels, lease_ttl
    )
    log(f"failover: lease ttl={lease_ttl:.2f}s, follower promoted "
        f"{promote_s:.2f}s after the kill, first post-flip publish at "
        f"{flip_publish_s:.2f}s (full backlog window: reconcile + "
        "restart + advance)")
    return {
        "bench": "streaming_ingest",
        "value": round(ingest_rate, 1),
        "bits": bits,
        "bits_per_level": bpl,
        "levels": n_levels,
        "window_keys": window_keys,
        "threads": n_threads,
        "total_keys": total_keys,
        "batches": len(schedule),
        "client_keygen_keys_per_sec": total_keys / keygen_wall,
        "ingest_keys_per_sec": ingest_rate,
        "ingest_wall_s": t_ingest.elapsed,
        "publish_drain_s": t_publish.elapsed,
        "windows_published": stats["windows_published"],
        "journals_rotated": stats["journals_rotated"],
        "failover_lease_ttl_s": lease_ttl,
        "failover_promote_s": round(promote_s, 3),
        "failover_first_publish_s": round(flip_publish_s, 3),
        "engine": "host",
        "notes": (
            "write path is journal-fsync-per-batch by contract; the "
            "system feed rate is keygen-bound by design (client keys "
            "fed through the ISSUE 19 threaded batched dealer — see "
            "client_keygen_keys_per_sec)"
        ),
    }


if __name__ == "__main__":
    run_bench("streaming_ingest", bench_streaming)
