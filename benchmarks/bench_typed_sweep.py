"""Typed full-domain evaluation sweep — BM_EvaluateRegularDpf across value
types (/root/reference/dpf/distributed_point_function_benchmark.cc:29-82:
log_domain 12..24 x {u8..u128, Tuple, IntModN}).

The headline/full_domain benches cover u64 and XorWrapper<u128>; this script
covers the remaining typed configs as a sweep: one value type per invocation
(BENCH_TYPED_TYPE in {u8, u32, tuple_u32_u64, intmodn_u64}), log_domain
16/18/20 on the device engine, plus the native host engine column for the
scalar Int types (the host bulk engine is scalar-only by design). Correctness
before rates: scalar types verify against the host engine bit-for-bit; codec
types verify the share-sum property over the full domain from a second key
(the product-level criterion — beta at alpha, zero elsewhere).
"""

import os

import numpy as np

from common import Timer, log, run_bench

TYPES = ("u8", "u32", "tuple_u32_u64", "intmodn_u64")
MOD_N = (1 << 64) - 59


def _make_type(name):
    from distributed_point_functions_tpu.core.value_types import (
        Int,
        IntModN,
        TupleType,
    )

    return {
        "u8": lambda: Int(8),
        "u32": lambda: Int(32),
        "tuple_u32_u64": lambda: TupleType(Int(32), Int(64)),
        "intmodn_u64": lambda: IntModN(64, MOD_N),
    }[name]()


def _betas(name, rng, count):
    if name == "tuple_u32_u64":
        return [[(7, 9)] * count]
    if name == "intmodn_u64":
        return [
            [
                int(b)
                for b in rng.integers(1, MOD_N, size=count, dtype=np.uint64)
            ]
        ]
    bits = 8 if name == "u8" else 32
    return [[int(b) for b in rng.integers(1, 1 << bits, size=count)]]


def _device_values(dpf, key, jnp, evaluator):
    """Full-domain device evaluation; returns per-component host arrays
    (verification path — full pull, NOT used inside timed regions)."""
    outs = []
    for valid, out in evaluator.full_domain_evaluate_chunks(dpf, [key]):
        comps = out if isinstance(out, tuple) else (out,)
        outs.append(tuple(np.asarray(c)[:valid] for c in comps))
    return tuple(
        np.concatenate([o[c] for o in outs], axis=0)
        for c in range(len(outs[0]))
    )


def _device_fold(dpf, key, jnp, evaluator, scalar):
    """Timed-region form: values stay device-resident and only tiny folds
    reach the host. Pulling full 2^20-element outputs would time the host
    link, not the device (~5 MB/s through this image's tunnel — the
    round-2 headline mistake; PERF.md). Scalar types ride the library's
    fused fold (full_domain_fold_chunks: expansion + fold in ONE program
    per key chunk — the shipping consumer shape); codec types have no
    fused fold, so each chunk takes one extra reduction dispatch (the
    chunk output is materialized device-side, jnp.sum is a follow-on
    program). Distinct keys per rep keep the tunnel's server-side result
    cache out of the timing."""
    if scalar:
        try:
            folds = []
            for valid, f in evaluator.full_domain_fold_chunks(dpf, [key]):
                folds.append(np.asarray(f)[:valid])  # key-chunk slices
            return (np.concatenate(folds, axis=0),)
        except NotImplementedError:
            # Trees shallower than the fold path's floor (smoke domains):
            # XOR-fold the evaluate path's chunks instead, matching the
            # fold program's semantics.
            folds = []
            for valid, out in evaluator.full_domain_evaluate_chunks(dpf, [key]):
                folds.append(
                    np.asarray(
                        jnp.bitwise_xor.reduce(out, axis=1)
                    )[:valid]
                )
            return (np.concatenate(folds, axis=0),)
    folds = None
    for valid, out in evaluator.full_domain_evaluate_chunks(dpf, [key]):
        comps = out if isinstance(out, tuple) else (out,)
        sums = tuple(jnp.sum(c, axis=(0, 1)) for c in comps)
        folds = sums if folds is None else tuple(
            f + s for f, s in zip(folds, sums)
        )
    return tuple(np.asarray(f) for f in folds)


def _limbs_to_int(arr):
    """uint32[K, n, lpe] -> object/uint64 integer array."""
    arr = np.asarray(arr)
    if arr.ndim == 2:
        return arr.astype(np.uint64)
    acc = arr[..., 0].astype(object)
    for limb in range(1, arr.shape[-1]):
        acc = acc + (arr[..., limb].astype(object) << (32 * limb))
    return acc


def bench(jax, smoke):
    import jax.numpy as jnp

    from distributed_point_functions_tpu.core.dpf import DistributedPointFunction
    from distributed_point_functions_tpu.core.host_eval import (
        full_domain_evaluate_host,
    )
    from distributed_point_functions_tpu.core.params import DpfParameters
    from distributed_point_functions_tpu.ops import evaluator

    type_name = os.environ.get("BENCH_TYPED_TYPE", "u32")
    if type_name not in TYPES:
        raise ValueError(f"BENCH_TYPED_TYPE must be one of {TYPES}")
    domains = (
        [int(d) for d in os.environ["BENCH_TYPED_DOMAINS"].split(",")]
        if "BENCH_TYPED_DOMAINS" in os.environ
        else ([10] if smoke else [16, 18, 20])
    )
    reps = int(os.environ.get("BENCH_REPS", 2 if smoke else 3))
    scalar = type_name in ("u8", "u32")
    rng = np.random.default_rng(0x7E57)

    per_domain = {}
    verified_all = True
    for lds in domains:
        vt = _make_type(type_name)
        dpf = DistributedPointFunction.create(DpfParameters(lds, vt))
        count = reps + 2  # warmup key + share-sum partner + reps
        alphas = [int(a) for a in rng.integers(0, 1 << lds, size=count)]
        betas = _betas(type_name, rng, count)
        keys_a, keys_b = dpf.generate_keys_batch(alphas, betas)

        with Timer() as warm:
            got = _device_values(dpf, keys_a[0], jnp, evaluator)
        log(f"{type_name} 2^{lds}: warmup (compile + run) {warm.elapsed:.1f}s")

        # --- Correctness gate ---
        if scalar:
            host = full_domain_evaluate_host(dpf, [keys_a[0]])
            bits = 8 if type_name == "u8" else 32
            mask = np.uint64((1 << bits) - 1)
            dev = _limbs_to_int(got[0][..., 0] if got[0].ndim == 3 else got[0])
            ok = np.array_equal(dev & mask, host & mask)
            # The TIMED path is the fused fold program — a different kernel
            # than the evaluate path checked above; verify it too (its XOR
            # fold must equal the host values' XOR fold).
            fold_dev = _device_fold(dpf, keys_a[0], jnp, evaluator, scalar)[0]
            host_fold = np.bitwise_xor.reduce(host, axis=1)
            ok = ok and np.array_equal(
                fold_dev[:, 0].astype(np.uint64) & mask, host_fold & mask
            )
        else:
            other = _device_values(dpf, keys_b[0], jnp, evaluator)
            if type_name == "tuple_u32_u64":
                masks = (1 << 32) - 1, (1 << 64) - 1
                want = (7, 9)
                ok = True
                for c, (m, w) in enumerate(zip(masks, want)):
                    tot = (_limbs_to_int(got[c]) + _limbs_to_int(other[c]))[0]
                    # dtype=object: values exceed int64 and numpy would
                    # silently coerce a plain list of big ints to float64.
                    tot = np.array([int(t) & m for t in tot.ravel()], dtype=object)
                    exp = np.zeros(1 << lds, dtype=object)
                    exp[alphas[0]] = w
                    ok = ok and np.array_equal(tot, exp)
            else:  # intmodn: (a + b) mod N == beta at alpha, 0 elsewhere
                tot = (_limbs_to_int(got[0]) + _limbs_to_int(other[0]))[0]
                tot = np.array(
                    [int(t) % MOD_N for t in tot.ravel()], dtype=object
                )
                nz = np.nonzero(tot)[0]
                ok = (
                    len(nz) == 1
                    and nz[0] == alphas[0]
                    and int(tot[alphas[0]]) == betas[0][0]
                )
        if not ok:
            verified_all = False
            log(f"{type_name} 2^{lds}: VERIFICATION FAILED")

        # --- Device rate (warmed, distinct keys per rep, fold pulls) ---
        _device_fold(dpf, keys_a[1], jnp, evaluator, scalar)  # warm fold
        with Timer() as t:
            for key in keys_a[2 : 2 + reps]:
                _device_fold(dpf, key, jnp, evaluator, scalar)
        dev_rate = (1 << lds) * reps / t.elapsed

        entry = {"device_evals_per_s": round(dev_rate)}
        if scalar:
            full_domain_evaluate_host(dpf, [keys_a[1]])  # warm native path
            with Timer() as th:
                for key in keys_a[2 : 2 + reps]:
                    full_domain_evaluate_host(dpf, [key])
            host_rate = (1 << lds) * reps / th.elapsed
            entry["host_evals_per_s"] = round(host_rate)
            entry["winner"] = "device" if dev_rate > host_rate else "host"
        else:
            entry["host_evals_per_s"] = None
            entry["winner"] = "device (host bulk engine is scalar-only)"
        per_domain[str(lds)] = entry
        log(f"{type_name} 2^{lds}: {entry}")

    top = per_domain[str(domains[-1])]
    return {
        "bench": f"typed_full_domain_{type_name}",
        "metric": (
            f"full-domain eval sweep, {type_name}, log_domain "
            f"{'/'.join(map(str, domains))}, device vs host engines"
        ),
        "value": top["device_evals_per_s"],
        "unit": "evals/s",
        "verified": bool(verified_all),
        "config": {
            "value_type": type_name,
            "reps": reps,
            "by_log_domain": per_domain,
        },
        **({} if verified_all else {"error": "verification failed"}),
    }


if __name__ == "__main__":
    # Per-variant fallback name: error records from two env variants must
    # not collide on one results.json merge slot.
    run_bench(
        f"typed_full_domain_{os.environ.get('BENCH_TYPED_TYPE', 'u32')}",
        bench,
    )
