"""Shared harness for the benchmark suite.

Every bench script prints exactly ONE JSON line to stdout:
  {"bench": ..., "metric": ..., "value": N, "unit": ..., "platform": ...,
   "config": {...}, "error": ...?}

Mirrors the robustness contract of the headline bench.py: the default
backend is probed in a subprocess (killable on hang); on failure the bench
runs on CPU with a reduced config. Platform forcing happens in-process via
jax.config (env-var forcing deadlocks under this image's sitecustomize).

Methodology matches the reference's google-benchmark suites
(/root/reference/dpf/distributed_point_function_benchmark.cc:29-402,
/root/reference/dcf/distributed_comparison_function_benchmark.cc:24-54):
time the evaluation loop only; key generation and (for the TPU) program
compilation are set-up, reported to stderr.
"""

import json
import os
import subprocess
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PROBE_TIMEOUT = float(os.environ.get("BENCH_PROBE_TIMEOUT", 180))


def log(msg: str) -> None:
    print(f"# {msg}", file=sys.stderr, flush=True)


def run_killable(cmd, timeout: float, env=None):
    """Runs `cmd` in its OWN process group; on timeout the whole group is
    SIGKILLed (the TPU runtime spawns helpers that keep pipes open past a
    plain child kill). Returns (stdout, stderr, timed_out).

    The same pattern lives inline in the repo-root bench.py (probe /
    device / comparison subprocesses) — bench.py is deliberately stdlib-
    standalone for the driver and cannot import this package; keep the two
    in sync when changing kill/reap behavior."""
    import signal

    proc = subprocess.Popen(
        cmd,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=env,
        start_new_session=True,
    )
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
        return stdout, stderr, False
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        try:
            stdout, stderr = proc.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            stdout, stderr = "", ""
        return stdout, stderr, True


def probe_default_backend(timeout: float = PROBE_TIMEOUT):
    code = "import jax; print(jax.default_backend())"
    try:
        r = subprocess.run(
            [sys.executable, "-c", code], timeout=timeout, capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired:
        log(f"backend probe timed out after {timeout:.0f}s")
        return None
    if r.returncode != 0:
        log(f"backend probe failed rc={r.returncode}: {r.stderr.strip()[-300:]}")
        return None
    return r.stdout.strip().splitlines()[-1] if r.stdout.strip() else None


def init_jax(platform=None):
    """Platform selection + persistent compilation cache. Returns jax."""
    if platform is None:
        platform = os.environ.get("BENCH_PLATFORM") or probe_default_backend()
        if platform is None:
            log("default backend unreachable; using CPU")
            platform = "cpu"
    if platform == "cpu" and os.environ.get("BENCH_MESH") == "1":
        # Virtual 8-device mesh for sharded smoke runs (bench_pir sets
        # BENCH_MESH at import). OPT-IN only: the multi-device CPU client
        # slows single-device XLA programs ~13x on this 1-vCPU image
        # (measured r4: fused heavy-hitters warm 0.96 s on 1 device vs
        # 12.7 s under the forced 8-device platform), so benches that don't
        # shard must never pay it. XLA_FLAGS is read at first backend init,
        # which hasn't happened yet in this process.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    import jax

    if platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
    try:
        cache_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".jax_cache",
        )
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:
        log(f"compilation cache unavailable: {e!r}")
    return jax


def emit(result: dict) -> None:
    print(json.dumps(result), flush=True)


def run_bench(name: str, fn) -> None:
    """Runs fn() -> result dict, emitting exactly one JSON line, always.

    fn receives the initialized jax module and a bool `smoke` (True when on
    CPU — scripts should shrink their configs).
    """
    result = {"bench": name, "value": 0}
    try:
        jax = init_jax()
        platform = jax.default_backend()
        log(f"platform: {platform}, devices: {jax.devices()}")
        # Smoke (reduced configs) when no accelerator is attached, unless
        # BENCH_FULL=1 deliberately records full-size host-engine numbers
        # on a CPU-only box. Smoke results are tagged so the run_all merge
        # never lets them replace a full-size record (round 3: a CPU smoke
        # sweep silently clobbered the TPU-day full-size host records).
        smoke = platform == "cpu" and os.environ.get("BENCH_FULL") != "1"
        try:
            result = fn(jax, smoke)
        except Exception:
            log("bench failed:\n" + traceback.format_exc())
            if platform != "cpu" and os.environ.get("BENCH_PLATFORM") != "cpu":
                # Backends cannot be re-selected after initialization in
                # this process — retry the whole script in a fresh CPU-forced
                # subprocess (with a timeout, in case the failure was a hang).
                log("retrying in a CPU-forced subprocess")
                env = dict(os.environ, BENCH_PLATFORM="cpu")
                r = subprocess.run(
                    [sys.executable, sys.argv[0]],
                    env=env,
                    capture_output=True,
                    text=True,
                    timeout=float(os.environ.get("BENCH_CPU_TIMEOUT", 1800)),
                )
                sys.stderr.write(r.stderr)
                line = (
                    r.stdout.strip().splitlines()[-1] if r.stdout.strip() else "{}"
                )
                print(line, flush=True)
                return
            raise
        result.setdefault("bench", name)
        # A bench that runs on an engine other than the default backend
        # (e.g. the native host engine while a TPU is attached) sets its
        # own platform; only fill it in when absent.
        result.setdefault("platform", jax.default_backend())
        # Every record carries its measurement date (VERDICT r3 #6: undated
        # entries from the caching-illusion era were indistinguishable from
        # trusted ones).
        result.setdefault("date", time.strftime("%Y-%m-%d"))
        if smoke:
            result["smoke"] = True
    except Exception as e:
        result["error"] = f"{type(e).__name__}: {e}"
    emit(result)


class Timer:
    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.perf_counter() - self.t0
