"""Microbenchmarks separating dispatch / compute / transfer on the TPU.

Answers the round-2 perf questions (VERDICT 'What's weak' #3): where do the
headline bench's seconds actually go — per-dispatch tunnel latency, bitsliced
AES compute, the leaf-order gather, or device->host transfers?

Run:  python benchmarks/micro_tpu.py            (real chip)
      JAX_PLATFORMS=cpu python benchmarks/micro_tpu.py   (smoke)

HONESTY: through this image's tunnel, repeating one input returns
server-cached results at ~0 cost — that methodology produced the wildly
inflated 2026-07-29 table PERF.md now strikes through (dispatch "0.21 ms"
vs the honest 65.7 ms; AES "5.8 G blocks/s" vs honest tens of M).
`timeit` now pulls a tiny device-side checksum per call and accepts
`variants` (distinct inputs per iteration), but THE CALL SITES IN THIS
FILE STILL PASS SINGLE INPUTS: treat every number it prints as a LOWER
BOUND on a caching backend. The authoritative measurements live in
`benchmarks/*.py` and `bench.py`, which implement the full
distinct-inputs + host-verified methodology.
"""

import functools
import sys
import time

import numpy as np

sys.path.insert(0, ".")

import os

import jax

# Env-var platform forcing alone is too late under this image's
# sitecustomize (jax may already be imported pointing at the TPU) — the
# config update is what actually switches the platform.
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp

from distributed_point_functions_tpu.ops import aes_jax, backend_jax


def timeit(fn, *args, n=5, warmup=1, variants=None):
    """Honest wall time per call: rotates over `variants` distinct input
    tuples and pulls a checksum of every output to the host inside the
    timed region — identical repeated programs time as ~0 through this
    image's tunnel (server-side result caching), and bare
    block_until_ready has returned early on it. Without `variants` it
    falls back to repeating the single `args`: such timings remain
    SUSPECT on caching backends (the pull fetches real bytes but the
    server may skip recomputation) — treat them as lower bounds only."""
    inputs = list(variants) if variants else [args]
    if len(inputs) > 1 and n > len(inputs):
        print(
            f"# timeit: n={n} > {len(inputs)} variants — repeats may be "
            "served from a result cache",
            file=sys.stderr,
        )
    for _ in range(warmup):
        jax.block_until_ready(fn(*inputs[0]))
    t0 = time.perf_counter()
    out = None
    for i in range(n):
        out = fn(*inputs[i % len(inputs)])
        # Pin each result with a TINY pull (8 words of the first leaf,
        # sliced device-side) — a full-array pull would measure the MB/s
        # host link, not the op.
        leaf = jax.tree_util.tree_leaves(out)[0]
        np.asarray(jnp.ravel(leaf)[:8])
    dt = (time.perf_counter() - t0) / n
    return dt, out


def main():
    print(f"# backend: {jax.default_backend()}, {jax.devices()}", file=sys.stderr)
    rng = np.random.default_rng(0)

    # --- 1. dispatch latency: trivial jitted op, small array ----------------
    tiny = jnp.asarray(np.arange(32, dtype=np.uint32))
    f_tiny = jax.jit(lambda x: x + 1)
    dt, _ = timeit(f_tiny, tiny, n=20)
    print(f"dispatch_latency_small_jit: {dt*1e3:.2f} ms")

    # --- 2. pure AES throughput: scan of hash_planes inside ONE jit ---------
    # planes [128, W]; W words = 32W blocks per application.
    for w in (1024, 4096, 16384):
        planes = jnp.asarray(
            rng.integers(0, 2**32, size=(128, w), dtype=np.uint32)
        )
        iters = 16

        @jax.jit
        def aes_loop(p):
            def body(c, _):
                h = backend_jax.hash_value_planes(c)
                return h, None

            out, _ = jax.lax.scan(body, p, None, length=iters)
            return out

        dt, _ = timeit(aes_loop, planes, n=3)
        blocks = 32 * w * iters
        print(
            f"aes_throughput W={w}: {blocks/dt/1e6:.1f} M blocks/s "
            f"({dt*1e3:.1f} ms for {iters} iters)"
        )

    # --- 3. expand_one_level: one dispatch at headline shapes ----------------
    for k, w in ((64, 8192),):
        planes = jnp.asarray(
            rng.integers(0, 2**32, size=(k, 128, w), dtype=np.uint32)
        )
        control = jnp.asarray(rng.integers(0, 2**32, size=(k, w), dtype=np.uint32))
        cw = jnp.asarray(rng.integers(0, 2**32, size=(k, 128), dtype=np.uint32))
        cc = jnp.asarray(rng.integers(0, 2**32, size=(k,), dtype=np.uint32))

        @jax.jit
        def one_level(p, c, cwp, l, r):
            return jax.vmap(backend_jax.expand_one_level)(p, c, cwp, l, r)

        dt, _ = timeit(one_level, planes, control, cw, cc, cc, n=3)
        blocks = 2 * 32 * w * k
        print(
            f"expand_one_level K={k} W={w}: {dt*1e3:.1f} ms/dispatch "
            f"({blocks/dt/1e6:.1f} M child blocks/s)"
        )

    # --- 4. fused multi-level expansion in ONE jit ---------------------------
    levels = 6

    @functools.partial(jax.jit, static_argnames=("levels",))
    def fused_expand(p, c, cws, ccls, ccrs, levels):
        def step(i, p, c):
            return backend_jax.expand_one_level(p, c, cws[i], ccls[i], ccrs[i])

        for i in range(levels):
            p, c = step(i, p, c)
        return p, c

    k, w0 = 8, 512
    planes = jnp.asarray(rng.integers(0, 2**32, size=(128, w0), dtype=np.uint32))
    control = jnp.asarray(rng.integers(0, 2**32, size=(w0,), dtype=np.uint32))
    cws = jnp.asarray(rng.integers(0, 2**32, size=(levels, 128), dtype=np.uint32))
    ccs = jnp.asarray(rng.integers(0, 2**32, size=(levels,), dtype=np.uint32))
    t0 = time.perf_counter()
    fused = functools.partial(fused_expand, levels=levels)
    jax.block_until_ready(fused(planes, control, cws, ccs, ccs))
    compile_s = time.perf_counter() - t0
    dt, _ = timeit(fused, planes, control, cws, ccs, ccs, n=3)
    blocks = 32 * w0 * (2 ** (levels + 1) - 2)
    print(
        f"fused_expand levels={levels} W0={w0}: {dt*1e3:.1f} ms/dispatch, "
        f"compile {compile_s:.1f}s, {blocks/dt/1e6:.1f} M child blocks/s"
    )

    # --- 4b. Pallas expansion kernel vs the XLA bitslice ---------------------
    # (the measured Pallas-vs-XLA decision of PERF.md / SURVEY §7 step 3)
    try:
        from distributed_point_functions_tpu.ops import aes_pallas

        w = 8192
        planes = jnp.asarray(
            rng.integers(0, 2**32, size=(128, w), dtype=np.uint32)
        )
        control = jnp.asarray(rng.integers(0, 2**32, size=(w,), dtype=np.uint32))
        cwp = jnp.asarray(rng.integers(0, 2**32, size=(128,), dtype=np.uint32))
        ccl = jnp.uint32(0xFFFFFFFF)
        ccr = jnp.uint32(0)
        xla_fn = jax.jit(backend_jax.expand_one_level)
        dt_xla, _ = timeit(xla_fn, planes, control, cwp, ccl, ccr, n=5)
        interp = jax.default_backend() != "tpu"
        pallas_fn = lambda *a: aes_pallas.expand_one_level_pallas(
            *a, interpret=interp
        )
        dt_pal, _ = timeit(pallas_fn, planes, control, cwp, ccl, ccr, n=5)
        blocks = 2 * 32 * w
        print(
            f"expand_one_level W={w}: XLA {dt_xla*1e3:.2f} ms "
            f"({blocks/dt_xla/1e6:.0f} M blk/s) vs Pallas {dt_pal*1e3:.2f} ms "
            f"({blocks/dt_pal/1e6:.0f} M blk/s)"
            + (" [interpreter]" if interp else "")
        )
    except Exception as e:
        print(f"pallas comparison failed: {type(e).__name__}: {e}")

    # --- 5. device->host transfer bandwidth ----------------------------------
    big = jnp.asarray(rng.integers(0, 2**32, size=(64, 1 << 19, 2), dtype=np.uint32))
    jax.block_until_ready(big)
    t0 = time.perf_counter()
    _ = np.asarray(big)
    dt = time.perf_counter() - t0
    mb = big.size * 4 / 1e6
    print(f"device_to_host: {mb:.0f} MB in {dt:.2f}s = {mb/dt:.0f} MB/s")

    # --- 6. leaf-order gather cost at headline shape -------------------------
    # The gather's HLO copy pads ~64x on TPU (observed 15.75 GB of padding
    # for a 256 MB array -> RESOURCE_EXHAUSTED) — a failure here is itself
    # a finding, not a reason to lose the earlier sections' output.
    try:
        order = jnp.asarray(np.random.permutation(1 << 19))

        @jax.jit
        def gathered(x, o):
            return x[:, o]

        dt, _ = timeit(gathered, big, order, n=3)
        print(f"gather [64, 2^19, 2]: {dt*1e3:.1f} ms")
    except Exception as e:
        print(f"gather benchmark failed: {type(e).__name__}: {str(e)[:200]}")


if __name__ == "__main__":
    main()
