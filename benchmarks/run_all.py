"""Runs the whole benchmark suite, one subprocess per bench (each owns the
TPU claim in turn), collecting JSON lines into benchmarks/results.json."""

import json
import os
import subprocess
import sys

# Entries are either a script name or (script, extra_env). BENCH_ONLY
# matches the script name (all env variants of it run).
BENCHES = [
    "bench_headline.py",
    "bench_keygen.py",
    "bench_full_domain.py",
    "bench_isrg.py",
    "bench_evaluate_at.py",
    "bench_intmodn_hierarchy.py",
    "bench_dcf.py",
    "bench_pir.py",
    "bench_heavy_hitters.py",
    # The fused grouped-advance engine (its own slot: heavy_hitters_device).
    ("bench_heavy_hitters.py", {"BENCH_HH_ENGINE": "device"}),
    "bench_intmodn_sample.py",
    # Typed full-domain sweep (BM_EvaluateRegularDpf's type axis) — one
    # record per value type.
    ("bench_typed_sweep.py", {"BENCH_TYPED_TYPE": "u8"}),
    ("bench_typed_sweep.py", {"BENCH_TYPED_TYPE": "u32"}),
    ("bench_typed_sweep.py", {"BENCH_TYPED_TYPE": "tuple_u32_u64"}),
    ("bench_typed_sweep.py", {"BENCH_TYPED_TYPE": "intmodn_u64"}),
]


def main():
    here = os.path.dirname(os.path.abspath(__file__))
    results = []
    for entry in BENCHES:
        script, extra_env = entry if isinstance(entry, tuple) else (entry, {})
        if os.environ.get("BENCH_ONLY") and script != os.environ["BENCH_ONLY"]:
            continue
        label = script + (f" {extra_env}" if extra_env else "")
        print(f"# running {label}", file=sys.stderr, flush=True)
        try:
            r = subprocess.run(
                [sys.executable, os.path.join(here, script)],
                cwd=here,
                capture_output=True,
                text=True,
                timeout=float(os.environ.get("BENCH_TIMEOUT", 3600)),
                env={**os.environ, **extra_env},
            )
        except subprocess.TimeoutExpired as e:
            sys.stderr.write((e.stderr or b"").decode("utf-8", "replace") if isinstance(e.stderr, bytes) else (e.stderr or ""))
            # Error records carry the full variant label: two failing env
            # variants of one script must not collide on a merge slot.
            results.append({"bench": label, "error": "timeout"})
            print(json.dumps(results[-1]), flush=True)
            continue
        sys.stderr.write(r.stderr)
        line = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else "{}"
        try:
            results.append(json.loads(line))
        except json.JSONDecodeError:
            results.append({"bench": label, "error": f"bad output: {line[:200]}"})
        print(line, flush=True)
    merge_records(results, os.path.join(here, "results.json"))


def merge_records(results, out):
    """Merge fresh bench records into results.json (also used by
    tools/run_bench_stage.py for per-stage resumable measurement sessions).

    A fresh entry replaces a stored one only when bench name AND platform
    match — a CPU smoke run must never clobber a TPU-day recording (or
    vice versa); mismatched-platform reruns are stored under
    "<bench>@<platform>". Hand-recorded entries (distinct bench names)
    always survive.
    """

    def slot(e):
        return (e.get("bench"), e.get("platform"))

    try:
        with open(out) as f:
            stored = json.load(f)
    except Exception:
        stored = []
    stored_by_slot = {slot(e): e for e in stored}

    def full_size_stored(name, platform):
        # Check the plain slot AND the '@platform'-suffixed slot the rename
        # branch below may have stored a cross-platform rerun under — a
        # smoke record passing the plain check would otherwise be renamed
        # onto (and delete) the full-size suffixed record.
        for key in ((name, platform), (f"{name}@{platform}", platform)):
            e = stored_by_slot.get(key)
            if e is not None and not e.get("smoke"):
                return True
        return False

    # A smoke record (reduced config; tagged by common.run_bench) must
    # never replace a full-size record.
    kept = []
    for r in results:
        if r.get("smoke") and full_size_stored(r.get("bench"), r.get("platform")):
            print(
                f"# skipped smoke record for {r.get('bench')} "
                "(full-size record exists)",
                file=sys.stderr,
            )
            continue
        kept.append(r)
    results = kept
    fresh = {}
    for r in results:
        fresh[slot(r)] = r
    merged = []
    for e in stored:
        if slot(e) not in fresh:
            merged.append(e)
    for r in results:
        name = r.get("bench")
        same_name_other_platform = any(
            e.get("bench") == name and e.get("platform") != r.get("platform")
            for e in stored
        )
        if same_name_other_platform and slot(r) not in {slot(e) for e in stored}:
            r = dict(r)
            r["bench"] = f"{name}@{r.get('platform')}"
            # replace a previous suffixed record of the same platform
            merged = [e for e in merged if e.get("bench") != r["bench"]]
        merged.append(r)
    with open(out, "w") as f:
        json.dump(merged, f, indent=2)
    print(f"# wrote {out}", file=sys.stderr)


if __name__ == "__main__":
    main()
