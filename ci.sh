#!/usr/bin/env bash
# CI entry point — the analog of the reference's pinned test matrix
# (/root/reference/.bazelci/presubmit.yml). Tiers:
#
#   ./ci.sh            fast tier: dpflint (seconds, fail-fast before the
#                      pytest spend) + the default pytest suite
#                      (slow-marked compile-heavy tests excluded),
#                      CPU-only.
#   ./ci.sh lint       static analysis only: tools/dpflint — AST-enforced
#                      repo invariants (Mosaic op-surface, replay parity,
#                      error taxonomy, env/lock/compile-budget
#                      discipline). Pure stdlib ast; never imports jax.
#   ./ci.sh slow       weekly tier: the full suite including --runslow.
#   ./ci.sh smoke      application smokes: experiments CLI + both demos
#                      on reduced configs.
#   ./ci.sh device     hardware tier: on-chip differential checks
#                      (tools/check_device.py) — requires a reachable TPU.
#   ./ci.sh faults     integrity tier: the runtime-integrity /
#                      fault-injection suite (tests marked 'faults'),
#                      forced onto XLA:CPU.
#   ./ci.sh multichip  mesh tier: the full __graft_entry__ dryrun on a
#                      forced 8-device CPU platform — sharded PIR,
#                      sharded expansion, key-sharded fused hierarchy,
#                      and the bounded real-circuit sharded-megakernel
#                      PIR regime (ISSUE 17; replay engine, zero pallas
#                      configs).
#   ./ci.sh all        lint + fast + smoke.
#
# Every tier exits nonzero on the first failure. Tests force a virtual
# 8-device CPU platform themselves (tests/conftest.py); the smokes force
# CPU here so they never contend for the single-process TPU claim.
set -euo pipefail
cd "$(dirname "$0")"

tier="${1:-fast}"

run_lint() {
  # ISSUE 11: AST-enforced repo invariants. Runs at the top of the fast
  # tier so an invariant violation fails in seconds instead of after the
  # ~800 s pytest spend. JAX_PLATFORMS pinned out of uniformity with the
  # other tiers; dpflint itself never imports jax (pure stdlib ast —
  # tests/test_lint.py pins that).
  JAX_PLATFORMS=cpu python -m tools.dpflint
}

run_fast() {
  # The fast tier includes the pipelined-executor suite
  # (tests/test_pipeline.py, ISSUE 2), the interpret-mode megakernel
  # suite (tests/test_megakernel.py, ISSUE 3), the interpret
  # walk-kernel suite (tests/test_walkkernel.py + the MIC replay
  # differential in tests/test_mic_gate.py, ISSUE 4) and the hierkernel
  # suite (tests/test_hierkernel.py, ISSUE 5 — ONE compiled interpret
  # config on a shape-uniform window plan, every equivalence variant
  # sharing it per the ~40-115 s/config compile budget; eager
  # real-circuit coverage goes through the replays, never pallas_call)
  # and the telemetry-bus suite (tests/test_telemetry.py, ISSUE 6 —
  # spans/counters/decisions on the XLA paths only, no new pallas
  # configs) and the serving-front-door suite (tests/test_serving.py,
  # ISSUE 8 — router pins, batcher units, six-op bit-exact e2e and the
  # 2x throughput A/B, built strictly on the lds-6 chunk-2 XLA program
  # family test_pipeline already compiles: ZERO new pallas interpret
  # configs, per the walkkernel compile-budget lesson) and the FSS
  # gate-family suite (tests/test_gates_framework.py, ISSUE 9 — the
  # family-parameterized mod-N edge matrix + wire/robust/serving
  # plumbing, every gate's batch_eval reusing the already-compiled
  # fused-DCF walk program families: again ZERO new pallas configs;
  # kernel-path coverage stays with the MIC walkkernel differentials
  # in test_mic_gate.py, which the whole family flattens onto) and the
  # vector-payload gate codec suite (tests/test_gate_payload.py,
  # ISSUE 18 — vector-vs-scalar-vs-plaintext edge matrix, packed-wire
  # and golden pins, the >=8x key-bytes/walks acceptance; device
  # coverage rides the cheap log_group=6 ReLU shape on the SAME
  # tuple-capture program family the walk engine already compiles:
  # ZERO new pallas configs); pytest
  # collects them with the rest of tests/ — no
  # separate invocation, which would run them twice. JAX_PLATFORMS=cpu
  # is pinned explicitly (belt to conftest.py's in-process suspenders)
  # so the tier can never contend for the single-process TPU claim.
  JAX_PLATFORMS=cpu python -m pytest tests/ -q -x
}

run_slow() {
  python -m pytest tests/ -q -x --runslow
}

run_smoke() {
  # Experiments CLI on the committed 4k-row smoke fixture (full-size
  # fixtures regenerate deterministically: gen_data.py seeds its RNG from
  # the fixture parameters).
  ( cd experiments \
    && python synthetic_data_benchmarks.py \
         --input data/20_4096_4096_0.1.csv --log_domain_size 20 \
         --platform cpu --engine auto --max_expansion_factor 4 \
         --num_iterations 1 )
  python examples/pir_demo.py --log_domain 12 --platform cpu
  # ISSUE 10: the same query through the REAL two-server RPC stack
  # (serving/server.py + serving/client.py) on loopback.
  python examples/pir_demo.py --log_domain 12 --platform cpu --serve
  python examples/heavy_hitters_demo.py
  # ISSUE 15: the streaming deployment shape — a real two-server pair
  # on loopback, batched hh_ingest uploads into rolling windows,
  # continuous publishes checked per window against the batch oracle.
  HH_CLIENTS=48 python examples/heavy_hitters_demo.py --serve
}

run_device() {
  # Full differential set: headline shapes + every r3/r4 device path
  # (DCF Mosaic walk, EvaluateAt Pallas walk, fused hierarchy, prepared
  # replay, 1x1 shard_map PIR).
  CHECK_EXTRAS=all python tools/check_device.py
}

run_multichip() {
  # ISSUE 17: the multi-device regression gate. __graft_entry__ forces a
  # virtual 8-device CPU platform itself (_force_cpu_mesh) and runs all
  # four sharded regimes, including the bounded sharded-megakernel PIR
  # dryrun: the real AES circuit pins the per-shard decomposition via
  # EAGER megakernel replays (the ~27K-eqn row graph cannot compile
  # through any jitted program on XLA-CPU in CI time), and the jitted
  # shard_map machinery runs with a cheap lane-local stand-in, full mesh
  # vs the 1x1 degenerate mesh — zero pallas interpret configs either
  # way. JAX_PLATFORMS=cpu pinned here too so the tier can never contend
  # for the TPU claim.
  JAX_PLATFORMS=cpu python __graft_entry__.py
}

run_faults() {
  # Runtime-integrity / fault-injection suite (ISSUE 1): every injected
  # fault class must be detected by sentinel verification and recovered by
  # the Pallas->JAX->numpy fallback chain. Forced onto XLA:CPU so the tier
  # never contends for the TPU claim and detection is exercised against a
  # known-good backend. ISSUE 7 adds the supervisor suite
  # (tests/test_supervisor.py, collected by the marker) plus a short
  # deterministic chaos-soak pass: seeded fault schedules (corruption,
  # OOM, unavailable, device_hang) across all six bulk entry points,
  # asserting bit-exact recovery and telemetry completeness (<60 s,
  # zero Pallas configs on CPU).
  JAX_PLATFORMS=cpu python -m pytest tests/ -q -x -m faults
  JAX_PLATFORMS=cpu python tools/chaos_soak.py --rounds 2 --seed 7
  # ISSUE 10: the socket chaos soak — two real server subprocesses on
  # loopback, party 0 behind the library fleet proxy (single-replica
  # degenerate case since ISSUE 14), a mixed two-server workload driven
  # through serving/client.py with seeded wire faults (conn_reset /
  # garbage_frame / slow_server / mid-batch server_kill + journal
  # resume). Bounded rounds, loopback only, XLA:CPU, zero new pallas
  # configs.
  JAX_PLATFORMS=cpu python tools/chaos_soak.py --wire --seed 7 \
    --wire-requests 60 --wire-faults 6
  # ISSUE 14: the fleet soak — 2 replicas per party behind FleetProxy,
  # seeded mixed-op load, the hottest party-0 replica SIGKILLed and
  # restarted mid-run. Asserts bit-exact shares, zero caller-visible
  # failures (client retry budgets absorb the failover), and affinity
  # resumption on the restarted replica. Bounded (<60 s), loopback,
  # XLA:CPU, host engine — zero pallas configs.
  JAX_PLATFORMS=cpu python tools/chaos_soak.py --fleet --replicas 2 \
    --fleet-requests 120 --fleet-threads 4 --seed 7
  # ISSUE 20: the elastic-fleet soak — party 0 starts at ONE replica with
  # a live AutoScaler on its FleetProxy; a client flood drives the
  # backlog signal over threshold, the seed replica is SIGKILLed DURING
  # the resulting scale event (newcomer spawned, not yet admitted), and
  # the lull after the flood drains the fleet back down gracefully.
  # Asserts bit-exact shares with ZERO caller-visible errors through
  # flood + mid-scale kill + drain, >= 1 scale-up and >= 1 retirement in
  # the proxy counters, and the killed seed probing back alive. Bounded
  # (<30 s), loopback, XLA:CPU, host engine — zero pallas configs.
  JAX_PLATFORMS=cpu python tools/chaos_soak.py --fleet-scale \
    --fleet-threads 4 --seed 7
  # ISSUE 15: the streaming heavy-hitters soak — two server
  # subprocesses (party 0 the aggregation leader via --stream-peer), a
  # seeded client fleet uploading key batches into rolling window
  # generations, the FOLLOWER SIGKILLed mid-window and restarted on the
  # same port + journal dir. Asserts per-window published prefixes +
  # counts EXACTLY equal the batch oracle (exactly-once membership: no
  # lost, no double-counted keys), journal reload across the kill,
  # >= 1 retry carried by the client budget, and the backpressure path
  # (RESOURCE_EXHAUSTED refused, retried to success). ISSUE 16 rides the
  # same flag with two more arms: the LEADER SIGKILLed (the follower
  # promotes itself by lease within ~TTL, a superseded-epoch zombie leg
  # is refused FAILED_PRECONDITION, seeded beta!=1 poison batches are
  # quarantined on both parties) and a fleet-sheltered stream (the
  # owning replica over a shared --stream-journal-root SIGKILLed, the
  # survivor re-homes by ownership lease, exactly-once intact). Bounded,
  # loopback, XLA:CPU, host-engine advance — zero pallas configs.
  JAX_PLATFORMS=cpu python tools/chaos_soak.py --stream --seed 7 \
    --stream-batches 12 --stream-threads 3
}

case "$tier" in
  lint) run_lint ;;
  fast) run_lint; run_fast ;;
  slow) run_slow ;;
  smoke) run_smoke ;;
  device) run_device ;;
  faults) run_faults ;;
  multichip) run_multichip ;;
  all) run_lint; run_fast; run_smoke ;;
  *) echo "unknown tier: $tier (lint|fast|slow|smoke|device|faults|multichip|all)" >&2; exit 2 ;;
esac
echo "ci: tier '$tier' passed"
