"""distributed_point_functions_tpu: a TPU-native function-secret-sharing
framework.

From-scratch JAX/XLA/Pallas re-design of Google's distributed_point_functions
C++ library: incremental Distributed Point Functions (DPF), Distributed
Comparison Functions (DCF), and FSS gates, over the same value-type system and
a byte-compatible key format. Key generation runs on the CPU host; key
evaluation (the fixed-key AES-128 PRG tree expansion) runs on TPU as bitsliced
vector/Pallas kernels driven by `jax.lax.scan`, with `jax.sharding` for
multi-chip full-domain expansion and PIR-style reductions.
"""

from .core.dpf import DistributedPointFunction, NumpyBackend
from .core.keys import CorrectionWord, DpfKey, EvaluationContext, PartialEvaluation
from .core.params import DpfParameters, ParameterValidator
from .core.value_types import Int, IntModN, TupleType, ValueType, XorWrapper
from .utils.errors import (
    DataCorruptionError,
    DataLossError,
    DpfError,
    FailedPreconditionError,
    InternalError,
    InvalidArgumentError,
    ResourceExhaustedError,
    UnavailableError,
    UnimplementedError,
)

__all__ = [
    "DistributedPointFunction",
    "NumpyBackend",
    "DpfParameters",
    "ParameterValidator",
    "DpfKey",
    "CorrectionWord",
    "EvaluationContext",
    "PartialEvaluation",
    "ValueType",
    "Int",
    "IntModN",
    "TupleType",
    "XorWrapper",
    "DpfError",
    "InvalidArgumentError",
    "FailedPreconditionError",
    "UnimplementedError",
    "InternalError",
    "DataLossError",
    "DataCorruptionError",
    "UnavailableError",
    "ResourceExhaustedError",
]

__version__ = "0.1.0"
