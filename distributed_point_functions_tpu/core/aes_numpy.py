"""Pure-numpy AES-128 (ECB over independent blocks) and the fixed-key MMO hash.

This is the host-side *oracle*: key generation uses it directly (a handful of
blocks per tree level), and every JAX/Pallas kernel is differentially tested
against it — the same strategy the reference uses for its SIMD kernels
(/root/reference/dpf/internal/aes_128_fixed_key_hash_hwy_test.cc).

All tables are generated programmatically from GF(2^8) arithmetic so the
implementation is correct by construction (verified against the reference's
pinned hash outputs in tests/test_aes.py).

Block layout: each 128-bit block is 16 bytes in little-endian order of the
underlying uint128 (see core/uint128.py). AES itself is byte-oriented, so this
only matters at the integer<->bytes boundary.
"""

from __future__ import annotations

import functools

import numpy as np

from . import uint128

# ---------------------------------------------------------------------------
# GF(2^8) arithmetic and table generation
# ---------------------------------------------------------------------------

_AES_POLY = 0x11B  # x^8 + x^4 + x^3 + x + 1


def _gf_mul(a: int, b: int) -> int:
    out = 0
    while b:
        if b & 1:
            out ^= a
        a <<= 1
        if a & 0x100:
            a ^= _AES_POLY
        b >>= 1
    return out


@functools.lru_cache(maxsize=None)
def _make_sbox() -> np.ndarray:
    # Multiplicative inverse table via exp/log over generator 3.
    exp = [0] * 256
    log = [0] * 256
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x = _gf_mul(x, 3)
    inv = [0] * 256
    for i in range(1, 256):
        inv[i] = exp[(255 - log[i]) % 255]
    # Affine transform: b'_i = b_i ^ b_{i+4} ^ b_{i+5} ^ b_{i+6} ^ b_{i+7} ^ c_i
    sbox = np.zeros(256, dtype=np.uint8)
    for v in range(256):
        b = inv[v]
        res = 0
        for i in range(8):
            bit = (
                (b >> i)
                ^ (b >> ((i + 4) % 8))
                ^ (b >> ((i + 5) % 8))
                ^ (b >> ((i + 6) % 8))
                ^ (b >> ((i + 7) % 8))
                ^ (0x63 >> i)
            ) & 1
            res |= bit << i
        sbox[v] = res
    return sbox


SBOX = _make_sbox()
_XTIME = np.array([_gf_mul(v, 2) for v in range(256)], dtype=np.uint8)
_RCON = [0x01, 0x02, 0x04, 0x08, 0x10, 0x20, 0x40, 0x80, 0x1B, 0x36]

# ShiftRows permutation on byte index j = row + 4*col (column-major state, as
# in the AES spec): output[row, col] = input[row, (col + row) % 4].
_SHIFT_ROWS = np.array(
    [(row + 4 * ((col + row) % 4)) for col in range(4) for row in range(4)],
    dtype=np.int64,
)


def expand_key(key_bytes: bytes) -> np.ndarray:
    """AES-128 key schedule -> uint8[11, 16] round keys."""
    assert len(key_bytes) == 16
    words = [list(key_bytes[4 * i : 4 * i + 4]) for i in range(4)]
    for i in range(4, 44):
        temp = list(words[i - 1])
        if i % 4 == 0:
            temp = temp[1:] + temp[:1]  # RotWord
            temp = [int(SBOX[t]) for t in temp]  # SubWord
            temp[0] ^= _RCON[i // 4 - 1]
        words.append([a ^ b for a, b in zip(words[i - 4], temp)])
    rks = np.array(words, dtype=np.uint8).reshape(11, 16)
    return rks


def encrypt_blocks(blocks: np.ndarray, round_keys: np.ndarray) -> np.ndarray:
    """AES-128 encryption of uint8[N, 16] blocks (vectorized over N)."""
    state = np.asarray(blocks, dtype=np.uint8).copy()
    assert state.ndim == 2 and state.shape[1] == 16
    state ^= round_keys[0]
    for rnd in range(1, 11):
        state = SBOX[state]
        state = state[:, _SHIFT_ROWS]
        if rnd < 10:
            # MixColumns on column-major state: bytes [4c, 4c+1, 4c+2, 4c+3].
            s = state.reshape(-1, 4, 4)  # [N, col, row]
            t = s[:, :, 0] ^ s[:, :, 1] ^ s[:, :, 2] ^ s[:, :, 3]
            new = np.empty_like(s)
            for r in range(4):
                new[:, :, r] = s[:, :, r] ^ t ^ _XTIME[s[:, :, r] ^ s[:, :, (r + 1) % 4]]
            state = new.reshape(-1, 16)
        state ^= round_keys[rnd]
    return state


class Aes128FixedKeyHash:
    """Circular-correlation-robust MMO hash: H(x) = AES_k(sigma(x)) ^ sigma(x).

    Numpy equivalent of the reference's Aes128FixedKeyHash
    (/root/reference/dpf/aes_128_fixed_key_hash.h:39-69). Operates on uint32
    limb arrays of shape [N, 4] (see core/uint128.py for the layout).
    """

    def __init__(self, key: int):
        self.key = key
        self._round_keys = expand_key(uint128.to_bytes(key))

    def evaluate_limbs(self, in_limbs: np.ndarray) -> np.ndarray:
        """uint32[N, 4] -> uint32[N, 4]."""
        x = np.ascontiguousarray(np.asarray(in_limbs, dtype=np.uint32))
        n = x.shape[0]
        if n == 0:
            return x.copy()
        # AES-NI native engine when present (bit-exact; see native/). The
        # numpy key schedule is byte-identical to the native one
        # (tests/test_native.py) so it feeds the FFI directly.
        from .. import native

        if native.available():
            return native.mmo_hash_limbs(self._round_keys, x)
        # sigma on limbs: out = (hi ^ lo, hi); limbs 0,1 = lo, limbs 2,3 = hi.
        sig = np.empty_like(x)
        sig[:, 0] = x[:, 2]
        sig[:, 1] = x[:, 3]
        sig[:, 2] = x[:, 2] ^ x[:, 0]
        sig[:, 3] = x[:, 3] ^ x[:, 1]
        enc = encrypt_blocks(sig.view(np.uint8).reshape(n, 16), self._round_keys)
        out = np.ascontiguousarray(enc).view(np.uint32).reshape(n, 4) ^ sig
        return out

    def evaluate(self, xs) -> list:
        """List of 128-bit ints -> list of 128-bit ints."""
        limbs = uint128.array_to_limbs(xs)
        return uint128.limbs_to_array(self.evaluate_limbs(limbs))

    def evaluate_one(self, x: int) -> int:
        return self.evaluate([x])[0]
