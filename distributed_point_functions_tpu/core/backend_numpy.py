"""Vectorized numpy evaluation backend.

This is the CPU twin of the JAX/TPU backend in ops/: the same three hot
primitives the reference implements in its Highway SIMD kernel
(/root/reference/dpf/internal/evaluate_prg_hwy.cc) and in ExpandSeeds /
HashExpandedSeeds (/root/reference/dpf/distributed_point_function.cc:271-349,
500-524), expressed as vectorized numpy over uint32[N, 4] limb arrays. It
serves as (a) the differential-test oracle for every TPU kernel, and (b) a
working CPU backend for small workloads.

Seed layout: uint32[N, 4], little-endian limbs (see core/uint128.py).
Control bits: bool[N]. Paths: uint32[N, 4] limbs of the tree index.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from . import constants
from .aes_numpy import Aes128FixedKeyHash

_PRG_LEFT = Aes128FixedKeyHash(constants.PRG_KEY_LEFT)
_PRG_RIGHT = Aes128FixedKeyHash(constants.PRG_KEY_RIGHT)
_PRG_VALUE = Aes128FixedKeyHash(constants.PRG_KEY_VALUE)


def _native_prg():
    """Returns the native module iff the AES-NI engine is loadable, else
    None. The three hot primitives below run entirely inside the native
    library when it is present (one FFI call per walk/expansion instead of
    one per AES batch); DPF_TPU_NO_NATIVE=1 keeps them on the pure-numpy
    oracle, which is the differential baseline (tests/test_native.py)."""
    from .. import native

    return native if native.available() else None


def get_bit(limbs: np.ndarray, bit_index: int) -> np.ndarray:
    """bool[N]: bit `bit_index` of each uint128 in uint32[N, 4]."""
    return ((limbs[:, bit_index // 32] >> np.uint32(bit_index % 32)) & 1).astype(bool)


def evaluate_seeds(
    seeds: np.ndarray,
    control_bits: np.ndarray,
    paths: np.ndarray,
    correction_seeds: np.ndarray,
    correction_controls_left: np.ndarray,
    correction_controls_right: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray]:
    """Walks all seeds down `num_levels` tree levels along `paths`.

    Semantics of dpf_internal::EvaluateSeeds (scalar fallback at
    evaluate_prg_hwy.cc:415-491): per level, pick the left/right PRG by the
    path bit, XOR the correction seed where the control bit is set, then pull
    the new control bit out of the seed's lowest bit and correct it.

    Args:
      seeds: uint32[N, 4]. control_bits: bool[N]. paths: uint32[N, 4].
      correction_seeds: uint32[L, 4];
      correction_controls_{left,right}: bool[L].
    Returns: (uint32[N, 4] seeds, bool[N] control bits).
    """
    native = _native_prg()
    if native is not None and len(seeds):
        return native.evaluate_seeds(
            _PRG_LEFT._round_keys,
            _PRG_RIGHT._round_keys,
            seeds,
            control_bits,
            paths,
            correction_seeds,
            correction_controls_left,
            correction_controls_right,
        )
    return _evaluate_seeds_numpy(
        seeds,
        control_bits,
        paths,
        correction_seeds,
        correction_controls_left,
        correction_controls_right,
    )


def _evaluate_seeds_numpy(
    seeds,
    control_bits,
    paths,
    correction_seeds,
    correction_controls_left,
    correction_controls_right,
) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized-numpy walk (the native kernel's differential oracle)."""
    seeds = np.array(seeds, dtype=np.uint32)
    control = np.asarray(control_bits, dtype=bool).copy()
    num_levels = len(correction_seeds)
    for level in range(num_levels):
        bit_index = num_levels - level - 1
        path_bits = get_bit(paths, bit_index) if bit_index < 128 else np.zeros(
            len(seeds), dtype=bool
        )
        left = _PRG_LEFT.evaluate_limbs(seeds)
        right = _PRG_RIGHT.evaluate_limbs(seeds)
        seeds = np.where(path_bits[:, None], right, left)
        seeds ^= np.where(control[:, None], correction_seeds[level][None, :], 0).astype(
            np.uint32
        )
        new_control = (seeds[:, 0] & 1).astype(bool)
        seeds[:, 0] &= np.uint32(0xFFFFFFFE)
        cc = np.where(
            path_bits,
            bool(correction_controls_right[level]),
            bool(correction_controls_left[level]),
        )
        control = new_control ^ (control & cc)
    return seeds, control


def expand_seeds(
    seeds: np.ndarray,
    control_bits: np.ndarray,
    correction_seeds: np.ndarray,
    correction_controls_left: np.ndarray,
    correction_controls_right: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray]:
    """Full doubling expansion over len(correction_seeds) levels.

    Semantics of DistributedPointFunction::ExpandSeeds
    (distributed_point_function.cc:271-349): each level hashes every seed with
    both PRGs, applies the seed/control corrections, and interleaves children
    as [left_0, right_0, left_1, right_1, ...].
    """
    native = _native_prg()
    if native is not None and len(seeds):
        return native.expand_forest(
            _PRG_LEFT._round_keys,
            _PRG_RIGHT._round_keys,
            seeds,
            control_bits,
            correction_seeds,
            correction_controls_left,
            correction_controls_right,
            len(correction_seeds),
        )
    return _expand_seeds_numpy(
        seeds,
        control_bits,
        correction_seeds,
        correction_controls_left,
        correction_controls_right,
    )


def _expand_seeds_numpy(
    seeds,
    control_bits,
    correction_seeds,
    correction_controls_left,
    correction_controls_right,
) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized-numpy doubling expansion (the native kernel's oracle)."""
    seeds = np.array(seeds, dtype=np.uint32)
    control = np.asarray(control_bits, dtype=bool).copy()
    num_levels = len(correction_seeds)
    for level in range(num_levels):
        n = seeds.shape[0]
        left = _PRG_LEFT.evaluate_limbs(seeds)
        right = _PRG_RIGHT.evaluate_limbs(seeds)
        correction = np.where(
            control[:, None], correction_seeds[level][None, :], 0
        ).astype(np.uint32)
        left ^= correction
        right ^= correction
        children = np.stack([left, right], axis=1).reshape(2 * n, 4)
        child_control = (children[:, 0] & 1).astype(bool)
        children[:, 0] &= np.uint32(0xFFFFFFFE)
        cc = np.stack(
            [
                control & bool(correction_controls_left[level]),
                control & bool(correction_controls_right[level]),
            ],
            axis=1,
        ).reshape(2 * n)
        control = child_control ^ cc
        seeds = children
    return seeds, control


def hash_expanded_seeds(seeds: np.ndarray, blocks_needed: int) -> np.ndarray:
    """Value-PRG hash of seeds[i] + j for j < blocks_needed.

    Semantics of DistributedPointFunction::HashExpandedSeeds
    (distributed_point_function.cc:500-524). Returns uint32[N, blocks_needed, 4].
    """
    seeds = np.asarray(seeds, dtype=np.uint32)
    native = _native_prg()
    if native is not None and seeds.shape[0] and blocks_needed:
        return native.value_hash(_PRG_VALUE._round_keys, seeds, blocks_needed)
    return _hash_expanded_seeds_numpy(seeds, blocks_needed)


def _hash_expanded_seeds_numpy(seeds: np.ndarray, blocks_needed: int) -> np.ndarray:
    """Numpy value-PRG hash (the native kernel's differential oracle)."""
    seeds = np.asarray(seeds, dtype=np.uint32)
    n = seeds.shape[0]
    inputs = np.repeat(seeds[:, None, :], blocks_needed, axis=1)  # [N, bn, 4]
    # uint128 addition of the small constant j, with carry propagation.
    for j in range(blocks_needed):
        carry = np.uint32(j)
        for limb in range(4):
            old = inputs[:, j, limb].copy()
            inputs[:, j, limb] += carry
            carry = (inputs[:, j, limb] < old).astype(np.uint32)
            if not carry.any():
                break
    hashed = _PRG_VALUE.evaluate_limbs(inputs.reshape(n * blocks_needed, 4))
    return hashed.reshape(n, blocks_needed, 4)
