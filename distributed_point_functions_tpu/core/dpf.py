"""DistributedPointFunction: the core (incremental) DPF engine.

Python/TPU re-implementation of the reference's DistributedPointFunction class
(/root/reference/dpf/distributed_point_function.{h,cc}):

* key generation on the host (core/keygen.py),
* evaluation through a pluggable backend — numpy (oracle/CPU) or JAX
  (jit/Pallas on TPU) — supplying the three data-parallel primitives
  `evaluate_seeds`, `expand_seeds`, `hash_expanded_seeds`,
* hierarchy bookkeeping, prefix dedup, value correction, and the
  EvaluationContext checkpoint/resume protocol on the host.

Unlike the C++ template API (EvaluateUntil<T> etc.), output types are fully
determined by the DpfParameters, so methods simply return host values of the
corresponding Python type. Batched/vectorized device outputs for the
performance path are provided by ops/ (see ops/evaluator.py).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.errors import InvalidArgumentError
from . import backend_numpy, uint128
from .keygen import KeyGenerator
from .keys import DpfKey, EvaluationContext, PartialEvaluation
from .params import DpfParameters, ParameterValidator
from .uint128 import MASK128
from .value_types import ValueType


@dataclasses.dataclass
class _Expansion:
    """Seeds and control bits of a (partial) expansion; limb layout."""

    seeds: np.ndarray  # uint32[N, 4]
    control_bits: np.ndarray  # bool[N]


def _correction_word_arrays(correction_words) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    seeds = np.zeros((len(correction_words), 4), dtype=np.uint32)
    ccl = np.zeros(len(correction_words), dtype=bool)
    ccr = np.zeros(len(correction_words), dtype=bool)
    for i, cw in enumerate(correction_words):
        seeds[i] = uint128.to_limbs(cw.seed)
        ccl[i] = cw.control_left
        ccr[i] = cw.control_right
    return seeds, ccl, ccr


class NumpyBackend:
    """Evaluation primitives on CPU via vectorized numpy (the oracle)."""

    name = "numpy"

    evaluate_seeds = staticmethod(backend_numpy.evaluate_seeds)
    expand_seeds = staticmethod(backend_numpy.expand_seeds)
    hash_expanded_seeds = staticmethod(backend_numpy.hash_expanded_seeds)


class DistributedPointFunction:
    """An (incremental) distributed point function over given parameters."""

    def __init__(self, parameters: Sequence[DpfParameters], backend=None):
        self._validator = ParameterValidator(parameters)
        self._keygen = KeyGenerator(self._validator)
        if backend is None:
            backend = NumpyBackend()
        self._backend = backend

    # ------------------------------------------------------------------
    # Construction
    # ------------------------------------------------------------------

    @classmethod
    def create(cls, parameters: DpfParameters, backend=None) -> "DistributedPointFunction":
        return cls([parameters], backend=backend)

    @classmethod
    def create_incremental(
        cls, parameters: Sequence[DpfParameters], backend=None
    ) -> "DistributedPointFunction":
        return cls(parameters, backend=backend)

    @property
    def parameters(self) -> List[DpfParameters]:
        return self._validator.parameters

    @property
    def validator(self) -> ParameterValidator:
        return self._validator

    # ------------------------------------------------------------------
    # Key generation (host)
    # ------------------------------------------------------------------

    def generate_keys(self, alpha: int, beta, seeds=None) -> Tuple[DpfKey, DpfKey]:
        return self.generate_keys_incremental(alpha, [beta], seeds=seeds)

    def generate_keys_batch(self, alphas, betas, seeds=None, prg=None):
        """K key pairs at once; one vectorized AES call per tree level.

        `betas` is per hierarchy level, scalar or length-K. See
        KeyGenerator.generate_keys_batch. `prg` overrides the AES
        provider (core/keygen.KeygenPrg; ops/keygen_batch.py supplies
        device-backed providers — byte-identical keys by construction).
        """
        return self._keygen.generate_keys_batch(
            alphas, betas, seeds=seeds, prg=prg
        )

    def generate_keys_incremental(
        self, alpha: int, betas: Sequence, seeds=None
    ) -> Tuple[DpfKey, DpfKey]:
        return self._keygen.generate_keys_incremental(alpha, betas, seeds=seeds)

    def create_evaluation_context(self, key: DpfKey) -> EvaluationContext:
        self._validator.validate_key(key)
        return EvaluationContext(
            parameters=list(self._validator.parameters),
            key=key,
            previous_hierarchy_level=-1,
        )

    # ------------------------------------------------------------------
    # Internal helpers
    # ------------------------------------------------------------------

    def _domain_to_tree_index(self, domain_index: int, hierarchy_level: int) -> int:
        return self._validator.domain_to_tree_index(domain_index, hierarchy_level)

    def _domain_to_block_index(self, domain_index: int, hierarchy_level: int) -> int:
        return self._validator.domain_to_block_index(domain_index, hierarchy_level)

    def _evaluate_seeds_arrays(
        self,
        expansion: _Expansion,
        paths: Sequence[int],
        correction_words,
    ) -> _Expansion:
        if not correction_words:
            return expansion
        cs, ccl, ccr = _correction_word_arrays(correction_words)
        paths_limbs = uint128.array_to_limbs(paths)
        seeds, control = self._backend.evaluate_seeds(
            expansion.seeds, expansion.control_bits, paths_limbs, cs, ccl, ccr
        )
        return _Expansion(np.asarray(seeds), np.asarray(control))

    def _compute_partial_evaluations(
        self,
        prefixes: Sequence[int],
        hierarchy_level: int,
        update_ctx: bool,
        ctx: EvaluationContext,
    ) -> _Expansion:
        """Mirrors DistributedPointFunction::ComputePartialEvaluations
        (distributed_point_function.cc:351-453)."""
        num_prefixes = len(prefixes)
        start_level = self._validator.hierarchy_to_tree[ctx.partial_evaluations_level]
        stop_level = self._validator.hierarchy_to_tree[hierarchy_level]

        if ctx.partial_evaluations and start_level <= stop_level:
            previous: Dict[int, Tuple[int, bool]] = {}
            for element in ctx.partial_evaluations:
                value = (element.seed, bool(element.control_bit))
                existing = previous.setdefault(element.prefix, value)
                if existing != value:
                    raise InvalidArgumentError(
                        "Duplicate prefix in `ctx.partial_evaluations()` with "
                        "mismatching seed or control bit"
                    )
            seeds = np.zeros((num_prefixes, 4), dtype=np.uint32)
            control = np.zeros(num_prefixes, dtype=bool)
            shift = stop_level - start_level
            for i, prefix in enumerate(prefixes):
                previous_prefix = prefix >> shift if shift < 128 else 0
                if previous_prefix not in previous:
                    raise InvalidArgumentError(
                        "Prefix not present in ctx.partial_evaluations at hierarchy "
                        f"level {hierarchy_level}"
                    )
                seed, control_bit = previous[previous_prefix]
                seeds[i] = uint128.to_limbs(seed)
                control[i] = control_bit
        else:
            seeds = np.tile(uint128.to_limbs(ctx.key.seed), (num_prefixes, 1))
            control = np.full(num_prefixes, bool(ctx.key.party), dtype=bool)
            start_level = 0

        expansion = self._evaluate_seeds_arrays(
            _Expansion(seeds, control),
            prefixes,
            ctx.key.correction_words[start_level:stop_level],
        )

        ctx.partial_evaluations = []
        if update_ctx:
            seed_ints = uint128.limbs_to_array(expansion.seeds)
            for i, prefix in enumerate(prefixes):
                ctx.partial_evaluations.append(
                    PartialEvaluation(
                        prefix=prefix,
                        seed=seed_ints[i],
                        control_bit=bool(expansion.control_bits[i]),
                    )
                )
        ctx.partial_evaluations_level = hierarchy_level
        return expansion

    def _expand_and_update_context(
        self,
        hierarchy_level: int,
        tree_indices: Sequence[int],
        ctx: EvaluationContext,
    ) -> _Expansion:
        """Mirrors ExpandAndUpdateContext (distributed_point_function.cc:455-498)."""
        v = self._validator
        if len(tree_indices) == 0:
            selected = _Expansion(
                seeds=uint128.to_limbs(ctx.key.seed)[None, :].copy(),
                control_bits=np.array([bool(ctx.key.party)]),
            )
            start_level = 0
        else:
            update_ctx = hierarchy_level < len(v.parameters) - 1
            selected = self._compute_partial_evaluations(
                tree_indices, ctx.previous_hierarchy_level, update_ctx, ctx
            )
            start_level = v.hierarchy_to_tree[ctx.previous_hierarchy_level]

        stop_level = v.hierarchy_to_tree[hierarchy_level]
        correction_words = ctx.key.correction_words[start_level:stop_level]
        if correction_words:
            cs, ccl, ccr = _correction_word_arrays(correction_words)
            seeds, control = self._backend.expand_seeds(
                selected.seeds, selected.control_bits, cs, ccl, ccr
            )
            expansion = _Expansion(np.asarray(seeds), np.asarray(control))
        else:
            expansion = selected
        ctx.previous_hierarchy_level = hierarchy_level
        return expansion

    def _get_value_correction(self, key: DpfKey, hierarchy_level: int) -> list:
        v = self._validator
        if hierarchy_level < len(v.parameters) - 1:
            return key.correction_words[
                v.hierarchy_to_tree[hierarchy_level]
            ].value_correction
        return key.last_level_value_correction

    # ------------------------------------------------------------------
    # Hierarchical evaluation (EvaluateUntil / EvaluateNext)
    # ------------------------------------------------------------------

    def evaluate_next(self, prefixes: Sequence[int], ctx: EvaluationContext) -> list:
        if ctx.previous_hierarchy_level < 0 and prefixes:
            raise InvalidArgumentError(
                "`prefixes` must be empty if and only if this is the first call with "
                "`ctx`."
            )
        return self.evaluate_until(ctx.previous_hierarchy_level + 1, prefixes, ctx)

    def evaluate_until(
        self, hierarchy_level: int, prefixes: Sequence[int], ctx: EvaluationContext
    ) -> list:
        """Mirrors EvaluateUntil<T> (distributed_point_function.h:641-837).

        Returns a flat list of host values: for each prefix (or the whole
        domain on the first call), the expansion at `hierarchy_level`.
        """
        v = self._validator
        v.validate_evaluation_context(ctx)
        if hierarchy_level < 0 or hierarchy_level >= len(v.parameters):
            raise InvalidArgumentError(
                "`hierarchy_level` must be non-negative and less than "
                "parameters_.size()"
            )
        if hierarchy_level <= ctx.previous_hierarchy_level:
            raise InvalidArgumentError(
                "`hierarchy_level` must be greater than `ctx.previous_hierarchy_level`"
            )
        if (ctx.previous_hierarchy_level < 0) != (len(prefixes) == 0):
            raise InvalidArgumentError(
                "`prefixes` must be empty if and only if this is the first call with "
                "`ctx`."
            )
        previous_hierarchy_level = ctx.previous_hierarchy_level
        previous_log_domain_size = 0
        if prefixes:
            previous_log_domain_size = v.parameters[
                previous_hierarchy_level
            ].log_domain_size
            for prefix in prefixes:
                if prefix < 0 or (
                    previous_log_domain_size < 128
                    and prefix >= (1 << previous_log_domain_size)
                ):
                    raise InvalidArgumentError(
                        f"Index {prefix} out of range for hierarchy level "
                        f"{previous_hierarchy_level}"
                    )
        log_domain_size = v.parameters[hierarchy_level].log_domain_size
        if log_domain_size - previous_log_domain_size > 62:
            raise InvalidArgumentError(
                "Output size would be larger than 2**62. Please evaluate fewer "
                "hierarchy levels at once."
            )

        # Deduplicate prefixes into unique tree indices; remember, per prefix,
        # the tree index position and the block index, so results can be
        # reassembled in input order (distributed_point_function.h:698-742).
        tree_indices: List[int] = []
        tree_indices_inverse: Dict[int, int] = {}
        prefix_map: List[Tuple[int, int]] = []
        for prefix in prefixes:
            tree_index = self._domain_to_tree_index(prefix, previous_hierarchy_level)
            block_index = self._domain_to_block_index(prefix, previous_hierarchy_level)
            if tree_index not in tree_indices_inverse:
                tree_indices_inverse[tree_index] = len(tree_indices)
                tree_indices.append(tree_index)
            prefix_map.append((tree_indices_inverse[tree_index], block_index))

        expansion = self._expand_and_update_context(hierarchy_level, tree_indices, ctx)
        expansion_size = len(expansion.control_bits)

        blocks_needed = v.blocks_needed[hierarchy_level]
        hashed = self._backend.hash_expanded_seeds(expansion.seeds, blocks_needed)
        hashed = np.asarray(hashed)

        value_type = v.parameters[hierarchy_level].value_type
        correction_ints = self._check_correction(
            self._get_value_correction(ctx.key, hierarchy_level), value_type
        )

        corrected_epb = 1 << (
            log_domain_size - v.hierarchy_to_tree[hierarchy_level]
        )
        party = ctx.key.party
        corrected = self._correct_expansion(
            hashed,
            expansion.control_bits,
            correction_ints,
            corrected_epb,
            party,
            value_type,
        )

        outputs_per_prefix = 1 << (log_domain_size - previous_log_domain_size)
        if not prefixes:
            return corrected
        blocks_per_tree_prefix = expansion_size // len(tree_indices)
        result = []
        for tree_pos, block_index in prefix_map:
            start = (
                tree_pos * blocks_per_tree_prefix * corrected_epb
                + block_index * outputs_per_prefix
            )
            result.extend(corrected[start : start + outputs_per_prefix])
        return result

    # ------------------------------------------------------------------
    # Batched point evaluation (EvaluateAt)
    # ------------------------------------------------------------------

    def evaluate_at(
        self,
        key: DpfKey,
        hierarchy_level: int,
        evaluation_points: Sequence[int],
        ctx: Optional[EvaluationContext] = None,
    ) -> list:
        """Mirrors EvaluateAt/EvaluateAtImpl (distributed_point_function.h:839-1010)."""
        v = self._validator
        if ctx is not None and ctx.key is not key:
            raise InvalidArgumentError(
                "`key` and `ctx.key()` must refer to the same object"
            )
        if hierarchy_level < 0:
            raise InvalidArgumentError("`hierarchy_level` must be non-negative")
        if hierarchy_level >= len(v.parameters):
            raise InvalidArgumentError(
                "`hierarchy_level` must be less than the number of parameters passed "
                "at construction"
            )
        log_domain_size = v.parameters[hierarchy_level].log_domain_size
        max_point = MASK128 if log_domain_size >= 128 else (1 << log_domain_size) - 1
        for i, point in enumerate(evaluation_points):
            if point < 0 or point > max_point:
                raise InvalidArgumentError(
                    f"`evaluation_points[{i}]` larger than the domain size at "
                    f"hierarchy level {hierarchy_level}"
                )
        v.validate_key(key)
        num_points = len(evaluation_points)
        if num_points == 0:
            return []

        value_type = v.parameters[hierarchy_level].value_type
        correction_ints = self._check_correction(
            self._get_value_correction(key, hierarchy_level), value_type
        )
        elements_per_block = value_type.elements_per_block()

        if elements_per_block > 1:
            tree_indices = [
                self._domain_to_tree_index(p, hierarchy_level)
                for p in evaluation_points
            ]
        else:
            tree_indices = list(evaluation_points)

        stop_level = v.hierarchy_to_tree[hierarchy_level]
        if ctx is None:
            selected = _Expansion(
                seeds=np.tile(uint128.to_limbs(key.seed), (num_points, 1)),
                control_bits=np.full(num_points, bool(key.party), dtype=bool),
            )
            start_level = 0
        else:
            selected = self._compute_partial_evaluations(
                tree_indices, hierarchy_level, True, ctx
            )
            start_level = stop_level

        expansion = self._evaluate_seeds_arrays(
            selected, tree_indices, key.correction_words[start_level:stop_level]
        )

        blocks_needed = v.blocks_needed[hierarchy_level]
        hashed = np.asarray(
            self._backend.hash_expanded_seeds(expansion.seeds, blocks_needed)
        )

        party = key.party
        result = []
        for i in range(num_points):
            data = hashed[i].tobytes()
            elements = value_type.bytes_to_block_values(data)
            block_index = (
                self._domain_to_block_index(evaluation_points[i], hierarchy_level)
                if elements_per_block > 1
                else 0
            )
            value = elements[block_index]
            if expansion.control_bits[i]:
                value = value_type.add(value, correction_ints[block_index])
            if party == 1:
                value = value_type.neg(value)
            result.append(value)

        if ctx is not None:
            ctx.previous_hierarchy_level = hierarchy_level
        return result

    # ------------------------------------------------------------------
    # Value correction helpers
    # ------------------------------------------------------------------

    def _check_correction(self, correction_values: list, value_type: ValueType) -> list:
        epb = value_type.elements_per_block()
        if len(correction_values) != epb:
            raise InvalidArgumentError(
                f"values.size() (= {len(correction_values)}) does not match "
                f"ElementsPerBlock<T>() (= {epb})"
            )
        return correction_values

    def _correct_expansion(
        self,
        hashed: np.ndarray,
        control_bits: np.ndarray,
        correction_ints: list,
        corrected_epb: int,
        party: int,
        value_type: ValueType,
    ) -> list:
        out = []
        n = hashed.shape[0]
        for i in range(n):
            data = hashed[i].tobytes()
            elements = value_type.bytes_to_block_values(data)
            for j in range(corrected_epb):
                value = elements[j]
                if control_bits[i]:
                    value = value_type.add(value, correction_ints[j])
                if party == 1:
                    value = value_type.neg(value)
                out.append(value)
        return out
