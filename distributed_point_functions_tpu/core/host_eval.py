"""Vectorized host full-domain evaluation on the native AES engine.

The reference's evaluation runs on CPU with AES-NI; this module is that
engine's counterpart for hosts without an accelerator (and the bench's CPU
fallback): the whole doubling expansion and value correction as batched
numpy over the native AES library (native/dpf_native.cc) — no Python
per-element loops, no XLA. The TPU path (ops/evaluator.py) remains the
flagship; results are bit-identical.

Scope: scalar Int/XorWrapper value types (the benchmark configs); other
types evaluate through ops/evaluator.py or the host reference path.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..utils.errors import InvalidArgumentError
from . import backend_numpy
from .dpf import DistributedPointFunction
from .keys import DpfKey
from .value_types import Int, XorWrapper


def _split_elements_np(blocks: np.ndarray, bits: int) -> np.ndarray:
    """uint32[..., 4] -> uint32/uint64[..., epb] elements; bits <= 64 only
    (the 128-bit case keeps limb rows and is handled by the caller)."""
    assert bits <= 64, bits
    if bits == 64:
        v = blocks.view(np.uint64) if blocks.flags["C_CONTIGUOUS"] else np.ascontiguousarray(blocks).view(np.uint64)
        return v.reshape(blocks.shape[:-1] + (2,))
    if bits == 32:
        return blocks
    per_limb = 32 // bits
    mask = np.uint32((1 << bits) - 1)
    shifts = (np.arange(per_limb, dtype=np.uint32) * np.uint32(bits))
    vals = (blocks[..., :, None] >> shifts) & mask
    return vals.reshape(blocks.shape[:-1] + (128 // bits,))


def full_domain_evaluate_host(
    dpf: DistributedPointFunction,
    keys: Sequence[DpfKey],
    hierarchy_level: int = -1,
    key_chunk: int = 32,
) -> np.ndarray:
    """Full-domain evaluation of a key batch, entirely on the host.

    Returns uint64[K, domain] for Int/XorWrapper up to 64 bits and
    uint32[K, domain, 4] limb rows for 128-bit types. Bit-identical to
    ops/evaluator.full_domain_evaluate.
    """
    from ..ops import evaluator  # KeyBatch reuse (host-side preparation)

    v = dpf.validator
    if hierarchy_level < 0:
        hierarchy_level = v.num_hierarchy_levels - 1
    value_type = v.parameters[hierarchy_level].value_type
    if not isinstance(value_type, (Int, XorWrapper)):
        raise InvalidArgumentError(
            "full_domain_evaluate_host supports Int/XorWrapper outputs; use "
            "ops/evaluator or the host reference path for other types"
        )
    bits = value_type.bitsize
    xor_group = isinstance(value_type, XorWrapper)
    lds = v.parameters[hierarchy_level].log_domain_size
    domain = 1 << lds

    batch = evaluator.KeyBatch.from_keys(dpf, keys, hierarchy_level)
    stop_level = batch.num_levels
    keep_per_block = 1 << (lds - stop_level)
    num_keys = len(keys)
    out = (
        np.empty((num_keys, domain), dtype=np.uint64)
        if bits <= 64
        else np.empty((num_keys, domain, 4), dtype=np.uint32)
    )
    vc = batch.value_corrections  # uint32[K, epb, 4]

    from .. import native

    if native.available():
        # Fully fused native evaluation: expansion to the last level, then
        # ONE streaming pass doing final level + value hash + correction
        # (the engine is DRAM-bound; the fused tail removes two full-size
        # read+write passes over the leaf arrays).
        rkl = np.asarray(backend_numpy._PRG_LEFT._round_keys, dtype=np.uint8)
        rkr = np.asarray(backend_numpy._PRG_RIGHT._round_keys, dtype=np.uint8)
        rkv = np.asarray(backend_numpy._PRG_VALUE._round_keys, dtype=np.uint8)
        vc_wide = pack_vc_wide(vc)  # [K, epb, 2]
        ctl0 = np.array([batch.party & 1], dtype=np.uint8)
        for j in range(num_keys):
            # 2^stop * keep == domain exactly for power-of-2 bitsizes, so
            # native-width rows stream in place (sub-32-bit elements into
            # the uint64 rows take one upcast copy inside the helper).
            fused_forest_values_into(
                out[j], rkl, rkr, rkv,
                batch.seeds[j : j + 1], ctl0,
                batch.cw_seeds[j], batch.cw_left[j], batch.cw_right[j],
                batch.party, stop_level,
                vc_wide[j], bits, xor_group, keep_per_block,
            )
        return out

    for start in range(0, num_keys, key_chunk):
        idx = np.arange(start, min(start + key_chunk, num_keys))
        kb = batch.take(idx)
        k = idx.shape[0]
        control0 = np.full(k, bool(kb.party), dtype=bool)
        # Vectorized doubling expansion on the numpy oracle.
        seeds, control = evaluator._host_expand(
            kb.seeds, control0, kb, stop_level
        )  # [k, 2^stop, 4], [k, 2^stop]
        n_blocks = seeds.shape[1]
        hashed = backend_numpy._PRG_VALUE.evaluate_limbs(
            seeds.reshape(k * n_blocks, 4)
        ).reshape(k, n_blocks, 4)
        vals = correct_scalar_blocks(
            hashed, control, vc[idx], bits, xor_group, kb.party, keep_per_block
        )
        out[idx] = vals[:, :domain]
    return out



def values_to_limbs(vals: np.ndarray, bits: int) -> np.ndarray:
    """Host-engine values -> the device evaluators' uint32[..., lpe] limb
    layout (lpe = max(bits // 32, 1)).

    The inverse of ops/evaluator.values_to_numpy for this module's return
    types (uint64 rows up to 64 bits, uint32[..., 4] limb rows at 128) —
    the comparison format of the runtime integrity layer's host oracle
    (utils/integrity.py verifies device limb outputs against it).
    """
    vals = np.asarray(vals)
    if bits == 128:
        return vals  # already uint32[..., 4] limb rows
    if bits <= 32:
        return (vals & np.uint64(0xFFFFFFFF)).astype(np.uint32)[..., None]
    return np.stack(
        [
            (vals & np.uint64(0xFFFFFFFF)).astype(np.uint32),
            (vals >> np.uint64(32)).astype(np.uint32),
        ],
        axis=-1,
    )


def pack_vc_wide(vc: np.ndarray) -> np.ndarray:
    """uint32[..., 4] correction limb rows -> uint64[..., 2] (lo, hi) pairs
    (the native fused kernels' correction layout)."""
    return np.stack(
        [
            vc[..., 0].astype(np.uint64)
            | (vc[..., 1].astype(np.uint64) << np.uint64(32)),
            vc[..., 2].astype(np.uint64)
            | (vc[..., 3].astype(np.uint64) << np.uint64(32)),
        ],
        axis=-1,
    )


def fused_forest_values_into(
    out_row: np.ndarray,
    rkl, rkr, rkv,
    seeds: np.ndarray,  # uint32[N, 4] roots
    control: np.ndarray,  # uint8[N]
    cw, cl, cr,
    party: int,
    levels: int,
    vc_wide_row: np.ndarray,  # uint64[epb, 2]
    bits: int,
    xor_group: bool,
    keep_per_block: int,
) -> None:
    """One key's fused native forest evaluation into `out_row`.

    Owns the native kernel's calling convention in ONE place for both host
    engines (full-domain and hierarchical). Streams directly into the row
    when it is C-contiguous at the kernel's exact byte size (native element
    width rows — e.g. the hierarchical engine's uint32 rows for 32-bit
    values, uint64 for 64-bit, uint32[..., 4] for 128-bit); otherwise one
    width-view copy (e.g. the full-domain engine's uint64 rows for sub-64
    widths, per its documented return type).
    """
    from .. import native

    n_bytes = (seeds.shape[0] << levels) * keep_per_block * (bits // 8)
    if out_row.flags["C_CONTIGUOUS"] and out_row.nbytes == n_bytes:
        native.expand_forest_values(
            rkl, rkr, rkv, seeds, control, cw, cl, cr, party, levels,
            vc_wide_row, bits, xor_group, keep_per_block, out=out_row,
        )
        return
    raw = native.expand_forest_values(
        rkl, rkr, rkv, seeds, control, cw, cl, cr, party, levels,
        vc_wide_row, bits, xor_group, keep_per_block,
    )
    if bits == 128:  # limb rows
        out_row[...] = raw.view(np.uint32).reshape(out_row.shape)
        return
    width = {8: np.uint8, 16: np.uint16, 32: np.uint32, 64: np.uint64}[bits]
    out_row[...] = raw.view(width).reshape(out_row.shape)


def correct_scalar_blocks(
    hashed: np.ndarray,  # uint32[k, n, 4] value-hash blocks
    control: np.ndarray,  # bool[k, n]
    vc: np.ndarray,  # uint32[k, epb, 4] value corrections (one limb row/elem)
    bits: int,
    xor_group: bool,
    party: int,
    keep_per_block: int,
) -> np.ndarray:
    """Vectorized value correction + party negation over hash blocks.

    The correction loop of EvaluateUntil
    (/root/reference/dpf/distributed_point_function.h:776-808): split each
    block into elements, apply the group op where the control bit is set,
    negate for party 1, and keep the first `keep_per_block` elements per
    block. Returns the native element width — uint32[k, n * keep_per_block]
    for bits <= 32, uint64[...] for bits == 64, uint32[k, ..., 4] limb rows
    for bits == 128 (a uint64 up-cast here would add a full-size copy to
    every bulk path for nothing).
    """
    k = hashed.shape[0]
    if bits == 128:
        corr = vc[:, None, :, :]  # [k, 1, epb, 4]
        elems = hashed[:, :, None, :]  # [k, blocks, 1, 4]
        ctrl = control[:, :, None, None]
        if xor_group:
            vals = elems ^ np.where(ctrl, corr, np.uint32(0))
        else:
            c = np.where(ctrl, corr, np.uint32(0))
            vals = _add128(elems, c)
            if party == 1:
                vals = _neg128(vals)
        return vals[:, :, :keep_per_block].reshape(k, -1, 4)

    elems = _split_elements_np(hashed, bits)  # [k, blocks, epb]
    if bits <= 32:
        corr = (vc[:, :, 0] & np.uint32((1 << bits) - 1))[:, None, :]
    else:  # 64
        corr = (
            vc[:, :, 0].astype(np.uint64)
            | (vc[:, :, 1].astype(np.uint64) << np.uint64(32))
        )[:, None, :]
    ctrl = np.broadcast_to(control[:, :, None], elems.shape)
    edt = elems.dtype
    corr_b = np.broadcast_to(corr.astype(edt), elems.shape)
    # In-place masked group op on the hash buffer view — one pass, no
    # temporary correction array.
    vals = np.ascontiguousarray(elems)
    op = np.bitwise_xor if xor_group else np.add
    op(vals, corr_b, where=ctrl, out=vals)
    if bits < 32:
        vals &= edt.type((1 << bits) - 1)
    if party == 1 and not xor_group:
        sview = vals.view(np.int64 if edt == np.uint64 else np.int32)
        np.negative(sview, out=sview)
        if bits < edt.itemsize * 8:
            vals &= edt.type((1 << bits) - 1)
    return vals[:, :, :keep_per_block].reshape(k, -1)


def _points_to_limb_arrays(points, lds: int, log2_epb: int):
    """points -> (paths uint32[P, 4] of tree indices, block_idx int64[P]).

    Vectorized uint64 fast path when tree indices fit 64 bits; python-int
    limb split otherwise (DomainToTreeIndex/DomainToBlockIndex,
    /root/reference/dpf/distributed_point_function.cc:206-221).
    """
    from . import uint128

    num = len(points)
    paths = np.zeros((num, 4), dtype=np.uint32)
    if isinstance(points, np.ndarray) and points.dtype == uint128.U128:
        block = uint128.u128_and_low(points, log2_epb).astype(np.int64)
        paths = uint128.u128_to_limb_rows(uint128.u128_rshift(points, log2_epb))
        return paths, block
    if lds - log2_epb <= 64 and lds <= 64:
        arr = np.asarray(points, dtype=np.uint64)
        tree = arr >> np.uint64(log2_epb)
        block = (arr & np.uint64((1 << log2_epb) - 1)).astype(np.int64)
        paths[:, 0] = (tree & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        paths[:, 1] = (tree >> np.uint64(32)).astype(np.uint32)
        return paths, block
    block = np.empty(num, dtype=np.int64)
    mask = (1 << log2_epb) - 1
    for i, p in enumerate(points):
        p = int(p)
        block[i] = p & mask
        t = p >> log2_epb
        paths[i, 0] = t & 0xFFFFFFFF
        paths[i, 1] = (t >> 32) & 0xFFFFFFFF
        paths[i, 2] = (t >> 64) & 0xFFFFFFFF
        paths[i, 3] = (t >> 96) & 0xFFFFFFFF
    return paths, block


def evaluate_at_host(
    dpf: DistributedPointFunction,
    keys: Sequence[DpfKey],
    points,
    hierarchy_level: int = -1,
) -> np.ndarray:
    """Batched EvaluateAt of K keys x P points, entirely on the host.

    The vectorized native-engine analog of EvaluateAtImpl
    (/root/reference/dpf/distributed_point_function.h:839-1010) for scalar
    Int/XorWrapper outputs: one native tree walk per key over all points,
    one value-hash pass, vectorized correction. Returns uint64[K, P]
    (uint32[K, P, 4] limb rows for 128-bit types). Bit-identical to
    dpf.evaluate_at / ops.evaluator.evaluate_at_batch.
    """
    from ..ops import evaluator

    v = dpf.validator
    if hierarchy_level < 0:
        hierarchy_level = v.num_hierarchy_levels - 1
    value_type = v.parameters[hierarchy_level].value_type
    if not isinstance(value_type, (Int, XorWrapper)):
        raise InvalidArgumentError(
            "evaluate_at_host supports Int/XorWrapper outputs; use "
            "dpf.evaluate_at or ops/evaluator for other types"
        )
    bits = value_type.bitsize
    xor_group = isinstance(value_type, XorWrapper)
    lds = v.parameters[hierarchy_level].log_domain_size
    epb = value_type.elements_per_block()
    log2_epb = epb.bit_length() - 1
    blocks_needed = v.blocks_needed[hierarchy_level]

    batch = evaluator.KeyBatch.from_keys(dpf, keys, hierarchy_level)
    num_keys = len(keys)
    num_points = len(points)
    paths, block_idx = _points_to_limb_arrays(points, lds, log2_epb)

    out = (
        np.empty((num_keys, num_points), dtype=np.uint64)
        if bits <= 64
        else np.empty((num_keys, num_points, 4), dtype=np.uint32)
    )
    ctl0 = np.full(num_points, bool(batch.party), dtype=bool)
    for j in range(num_keys):
        seeds0 = np.broadcast_to(batch.seeds[j], (num_points, 4))
        seeds, control = backend_numpy.evaluate_seeds(
            seeds0,
            ctl0,
            paths,
            batch.cw_seeds[j],
            batch.cw_left[j],
            batch.cw_right[j],
        )
        hashed = backend_numpy.hash_expanded_seeds(seeds, blocks_needed)
        vc = batch.value_corrections[j : j + 1]  # [1, epb, 4]
        if bits == 128:
            vals = correct_scalar_blocks(
                hashed[None, :, 0, :], control[None, :], vc, bits, xor_group,
                batch.party, 1,
            )
            out[j] = vals[0]
            continue
        # Split the hash block into elements and keep only each point's
        # block_index element, correcting with that element's correction.
        elems = _split_elements_np(hashed[:, 0, :], bits)  # [P, epb]
        sel = np.take_along_axis(elems, block_idx[:, None], axis=1)[:, 0]
        if bits <= 32:
            corr_e = vc[0, :, 0] & np.uint32((1 << bits) - 1)
        else:
            corr_e = vc[0, :, 0].astype(np.uint64) | (
                vc[0, :, 1].astype(np.uint64) << np.uint64(32)
            )
        corr = corr_e[block_idx].astype(sel.dtype)
        op = np.bitwise_xor if xor_group else np.add
        vals = np.where(control, op(sel, corr), sel)
        if bits < 32:
            vals &= vals.dtype.type((1 << bits) - 1)
        if batch.party == 1 and not xor_group:
            vals = (-vals.astype(np.int64)).astype(np.uint64)
            if bits < 64:
                vals &= np.uint64((1 << bits) - 1)
        out[j] = vals.astype(np.uint64, copy=False)
    return out


def _add128(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Limb-wise 128-bit addition on uint32[..., 4]."""
    out = np.empty(np.broadcast_shapes(a.shape, b.shape), dtype=np.uint32)
    carry = np.zeros(out.shape[:-1], dtype=np.uint64)
    for l in range(4):
        t = a[..., l].astype(np.uint64) + b[..., l].astype(np.uint64) + carry
        out[..., l] = t.astype(np.uint32)
        carry = t >> np.uint64(32)
    return out


def _neg128(a: np.ndarray) -> np.ndarray:
    """Two's-complement negation on uint32[..., 4]."""
    inv = ~a
    one = np.zeros_like(a)
    one[..., 0] = 1
    return _add128(inv, one)
