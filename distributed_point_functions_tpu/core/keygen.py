"""DPF key generation (host / CPU).

Faithful re-implementation of DistributedPointFunction::GenerateKeysIncremental
and GenerateNext (/root/reference/dpf/distributed_point_function.cc:619-687,
103-204), which follow Fig. 11 of the Incremental DPF paper
(https://arxiv.org/pdf/2012.14884.pdf). Key generation is sequential in tree
depth with only 4-6 AES blocks per level, so it stays on the CPU (SURVEY.md
north star); evaluation is what runs on TPU.

Keys produced here are bit-exact with the reference implementation given the
same random seeds, so they can be exchanged with C++ evaluators.
"""

from __future__ import annotations

import gc
import secrets
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..utils.errors import InvalidArgumentError
from . import constants, uint128
from .aes_numpy import Aes128FixedKeyHash
from .keys import CorrectionWord, DpfKey
from .params import ParameterValidator
from .uint128 import MASK128
from .value_types import Int, XorWrapper, compute_value_correction


def _extract_and_clear_lowest_bit(x: int) -> Tuple[int, int]:
    """Returns (bit, x with bit 0 cleared); mirrors
    dpf_internal::ExtractAndClearLowestBit
    (/root/reference/dpf/internal/evaluate_prg_hwy.h:31-35)."""
    return x & 1, x & ~1


# ---------------------------------------------------------------------------
# The batched-keygen PRG seam
# ---------------------------------------------------------------------------


class KeygenPrg:
    """The PRG provider of the batched keygen level loop.

    ``generate_keys_batch`` is pure level-major algebra around three AES
    fixed-key hashes; this seam is the ONLY place those hashes run, so a
    provider that computes the same circuits elsewhere (the batched
    plane-space XLA / Pallas row circuits of ops/keygen_batch.py) yields
    byte-identical keys by construction — the correction-word algebra is
    literally the same code.
    """

    def __init__(
        self,
        left: Aes128FixedKeyHash,
        right: Aes128FixedKeyHash,
        value: Aes128FixedKeyHash,
    ):
        self._left = left
        self._right = right
        self._value = value

    def expand(self, flat: np.ndarray, want_value: bool):
        """Expands parent seeds under both branch PRGs.

        Args:
          flat: uint32[N, 4] parent seed limb rows (N = 2K, party-pairwise).
          want_value: also hash `flat` under the value PRG — the value-
            correction inputs for a blocks_needed==1 output level are
            exactly the parent seeds (seed + j for j < 1), so a fused
            provider can serve all three hashes from one dispatch.
        Returns: (left, right, value_or_None), each uint32[N, 4] raw hash
        outputs (control bit still in bit 0 of limb 0).
        """
        left = self._left.evaluate_limbs(flat)
        right = self._right.evaluate_limbs(flat)
        value = self._value.evaluate_limbs(flat) if want_value else None
        return left, right, value

    def value_hash(self, inputs: np.ndarray) -> np.ndarray:
        """Value-PRG hash of uint32[M, 4] blocks (the blocks_needed > 1
        output-level inputs and the final-level correction)."""
        return self._value.evaluate_limbs(inputs)


def _value_hash_inputs(seeds_l: np.ndarray, blocks_needed: int) -> np.ndarray:
    """Builds the value-PRG inputs seeds[i, party] + j for j < blocks_needed
    (uint128 limb addition), vectorized: uint32[K*2*blocks_needed, 4]."""
    inputs = np.repeat(
        seeds_l[:, :, None, :], blocks_needed, axis=2
    ).astype(np.uint64)  # widen to u64 for carry math
    offs = np.arange(blocks_needed, dtype=np.uint64)
    inputs[..., 0] += offs[None, None, :]
    for limb in range(3):
        carry = inputs[..., limb] >> 32
        inputs[..., limb] &= 0xFFFFFFFF
        inputs[..., limb + 1] += carry
    inputs[..., 3] &= 0xFFFFFFFF
    return inputs.astype(np.uint32).reshape(-1, 4)


def batch_level_step(
    left: np.ndarray,  # uint32[K, 2, 4] raw left-PRG outputs per party
    right: np.ndarray,  # uint32[K, 2, 4] raw right-PRG outputs per party
    control: np.ndarray,  # bool[K, 2] current control bits
    current_bit: np.ndarray,  # int64[K] alpha bit at this level
):
    """One Fig.-11 level of correction-word algebra on expanded planes
    (lines 5-12), vectorized over keys. The level-step seam shared by the
    host batched path and the device paths (ops/keygen_batch.py): both
    compute `left`/`right` with their own AES engine and feed the SAME
    algebra, so correction words are byte-identical by construction.

    Returns (new_seeds uint32[K, 2, 4], new_control bool[K, 2],
    seed_correction uint32[K, 4], control_correction bool[K, 2])."""
    k = left.shape[0]
    exp = np.stack([left, right], axis=1).astype(np.uint32, copy=False)  # [K, br, party, 4]
    exp_bits = (exp[..., 0] & 1).astype(bool)  # [K, branch, party]
    exp[..., 0] &= np.uint32(0xFFFFFFFE)

    keep = current_bit  # [K]
    lose = 1 - keep
    rows = np.arange(k)
    lose_seeds = exp[rows, lose]  # [K, party, 4]
    seed_correction = lose_seeds[:, 0] ^ lose_seeds[:, 1]  # [K, 4]
    # control_correction[:, branch] (lines 9-10)
    cc = np.empty((k, 2), dtype=bool)
    cc[:, 0] = exp_bits[:, 0, 0] ^ exp_bits[:, 0, 1] ^ (current_bit == 1) ^ True
    cc[:, 1] = exp_bits[:, 1, 0] ^ exp_bits[:, 1, 1] ^ (current_bit == 1)

    keep_seeds = exp[rows, keep]  # [K, party, 4]
    corr = np.where(control[:, :, None], seed_correction[:, None, :], 0)
    new_seeds = (keep_seeds ^ corr).astype(np.uint32)
    keep_cc = cc[rows, keep]  # [K]
    new_control = exp_bits[rows, keep] ^ (control & keep_cc[:, None])
    return new_seeds, new_control, seed_correction, cc


def assemble_batch_keys(
    out_keys: Tuple[List[DpfKey], List[DpfKey]],
    level_records: Sequence[Tuple[np.ndarray, np.ndarray, Optional[List[list]]]],
    last_cw: List[list],
) -> None:
    """Appends all correction words + the final value correction to K
    pre-seeded key pairs from level-major arrays.

    ``level_records`` is one tuple per tree level (the
    :func:`batch_level_step` outputs): seed_correction uint32[K, 4],
    control_correction bool[K, 2], and the level's typed value
    corrections (None off output levels). The limb->int conversion runs
    ONCE vectorized over all levels — the per-key/per-level
    ``from_limbs`` + keyword-argument construction loop this replaces
    was ~85% of a depth-128 host keygen pass (AES itself is 2-3%
    behind the native engine). Both the host batched path and the
    device/megakernel dealers assemble through here, so the wire form
    cannot drift between them."""
    k = len(out_keys[0])
    # A deep batch materializes hundreds of thousands of acyclic
    # containers (CorrectionWord + its value list, per key per level per
    # party); every gen-0 threshold trip rescans the survivors, which
    # doubled depth-128 assembly time. Pause collection for the bounded
    # allocation burst — nothing built here can form a cycle.
    gc_was_enabled = gc.isenabled()
    if gc_was_enabled:
        gc.disable()
    try:
        _assemble_batch_keys(out_keys, level_records, last_cw, k)
    finally:
        if gc_was_enabled:
            gc.enable()


def _assemble_batch_keys(out_keys, level_records, last_cw, k) -> None:
    if level_records:
        sc_ints = uint128.limb_rows_to_ints(
            np.stack([rec[0] for rec in level_records]).reshape(-1, 4)
        )
        cc_flat = np.stack([rec[1] for rec in level_records]).reshape(-1, 2)
        cls = cc_flat[:, 0].tolist()
        crs = cc_flat[:, 1].tolist()

        for party in range(2):
            keys_p = out_keys[party]
            # Level-major stream of value-correction lists, one FRESH
            # list per correction word (the scalar oracle gives each
            # party its own list — shared lists would alias mutations
            # across parties).
            vc_flat: list = []
            for rec in level_records:
                vcs = rec[2]
                if vcs is None:
                    vc_flat += [[] for _ in range(k)]
                else:
                    vc_flat += [list(vc) for vc in vcs]
            all_cws = list(map(CorrectionWord, sc_ints, cls, crs, vc_flat))
            for i in range(k):
                # Level-major layout: level l of key i sits at l*k + i, so
                # the stride slice is this key's per-level sequence.
                keys_p[i].correction_words += all_cws[i::k]
    for i in range(k):
        out_keys[0][i].last_level_value_correction = list(last_cw[i])
        out_keys[1][i].last_level_value_correction = list(last_cw[i])


#: numpy view dtypes for the vectorized value-correction fast path.
_DIRECT_DTYPES = {8: "<u1", 16: "<u2", 32: "<u4", 64: "<u8"}


def normalize_beta_cols(
    betas: Sequence, k: int, num_levels: Optional[int] = None
) -> List[list]:
    """Per-level beta columns for a K-key batch: each level is a scalar
    (broadcast over keys) or a length-K sequence. THE broadcast rule —
    every layer that accepts batched betas (this module, the robust
    wrapper, the serving request, the wire codec, the two-server client)
    normalizes through here so they cannot diverge on which inputs they
    accept."""
    if num_levels is not None and len(betas) != num_levels:
        raise InvalidArgumentError(
            "`beta` has to have the same size as `parameters` passed at "
            "construction"
        )
    cols: List[list] = []
    for level, b in enumerate(betas):
        col = list(b) if isinstance(b, (list, tuple, np.ndarray)) else [b] * k
        if len(col) != k:
            raise InvalidArgumentError(
                f"betas[{level}] must be a scalar or have one value per key"
            )
        cols.append(col)
    return cols


class KeyGenerator:
    """Generates incremental DPF keys for a validated parameter set."""

    def __init__(self, validator: ParameterValidator):
        self._v = validator
        self._prg_left = Aes128FixedKeyHash(constants.PRG_KEY_LEFT)
        self._prg_right = Aes128FixedKeyHash(constants.PRG_KEY_RIGHT)
        self._prg_value = Aes128FixedKeyHash(constants.PRG_KEY_VALUE)

    # -- helpers -----------------------------------------------------------

    def _domain_to_block_index(self, domain_index: int, hierarchy_level: int) -> int:
        return self._v.domain_to_block_index(domain_index, hierarchy_level)

    def _compute_value_correction(
        self, hierarchy_level: int, seeds: List[int], alpha: int, beta, invert: bool
    ) -> list:
        """Mirrors DistributedPointFunction::ComputeValueCorrection
        (distributed_point_function.cc:63-99): hash seeds[i]+j for
        j < blocks_needed under the value PRG, then form correction shares."""
        blocks_needed = self._v.blocks_needed[hierarchy_level]
        inputs = [(seeds[0] + j) & MASK128 for j in range(blocks_needed)]
        inputs += [(seeds[1] + j) & MASK128 for j in range(blocks_needed)]
        hashed = self._prg_value.evaluate(inputs)
        seed_a = b"".join(uint128.to_bytes(h) for h in hashed[:blocks_needed])
        seed_b = b"".join(uint128.to_bytes(h) for h in hashed[blocks_needed:])
        index_in_block = self._domain_to_block_index(alpha, hierarchy_level)
        value_type = self._v.parameters[hierarchy_level].value_type
        return compute_value_correction(
            value_type, seed_a, seed_b, index_in_block, beta, invert
        )

    # -- key generation ----------------------------------------------------

    def generate_keys_incremental(
        self,
        alpha: int,
        betas: Sequence,
        seeds: Optional[Tuple[int, int]] = None,
    ) -> Tuple[DpfKey, DpfKey]:
        """Generates a key pair. `seeds` overrides the CSPRNG (tests only)."""
        v = self._v
        if len(betas) != v.num_hierarchy_levels:
            raise InvalidArgumentError(
                "`beta` has to have the same size as `parameters` passed at "
                "construction"
            )
        for i, beta in enumerate(betas):
            v.validate_value(beta, i)
        last_log_domain_size = v.parameters[-1].log_domain_size
        if alpha < 0 or (
            last_log_domain_size < 128 and alpha >= (1 << last_log_domain_size)
        ):
            raise InvalidArgumentError(
                "`alpha` must be smaller than the output domain size"
            )

        if seeds is None:
            seeds = (
                uint128.from_bytes(secrets.token_bytes(16)),
                uint128.from_bytes(secrets.token_bytes(16)),
            )
        seeds = [seeds[0] & MASK128, seeds[1] & MASK128]
        control_bits = [0, 1]
        keys = (
            DpfKey(seed=seeds[0], correction_words=[], party=0),
            DpfKey(seed=seeds[1], correction_words=[], party=1),
        )

        for tree_level in range(1, v.tree_levels_needed):
            self._generate_next(tree_level, alpha, betas, seeds, control_bits, keys)

        last_cw = self._compute_value_correction(
            v.num_hierarchy_levels - 1, seeds, alpha, betas[-1], bool(control_bits[1])
        )
        keys[0].last_level_value_correction = list(last_cw)
        keys[1].last_level_value_correction = list(last_cw)
        return keys

    # -- batched key generation -------------------------------------------

    def generate_keys_batch(
        self,
        alphas: Sequence[int],
        betas: Sequence[Sequence],
        seeds: Optional[np.ndarray] = None,
        prg: Optional[KeygenPrg] = None,
    ) -> Tuple[List[DpfKey], List[DpfKey]]:
        """Generates K key pairs at once, level-major.

        Semantics are identical to `generate_keys_incremental` run K times
        (same Fig.-11 algebra, same AES calls), but the per-level PRG
        expansion is one vectorized numpy AES call over all 2K seeds instead
        of 2K two-block calls — this is what makes 1024-key benchmark setup
        take seconds instead of minutes.

        Args:
          alphas: K domain indices.
          betas: per hierarchy level, either a scalar (broadcast over keys) or
            a length-K sequence of values.
          seeds: optional uint32[K, 2, 4] CSPRNG override (tests only).
          prg: the AES provider (:class:`KeygenPrg`; None = this
            generator's host hashes). ops/keygen_batch.py passes providers
            that run the same circuits on the batched device kernels —
            everything outside the provider is shared, so keys are
            byte-identical across providers by construction.
        Returns: (keys of party 0, keys of party 1), each a length-K list.
        """
        v = self._v
        k = len(alphas)
        beta_cols = normalize_beta_cols(betas, k, v.num_hierarchy_levels)
        for level, col in enumerate(beta_cols):
            for val in col:
                v.validate_value(val, level)
        last_log_domain_size = v.parameters[-1].log_domain_size
        alphas = [int(a) for a in alphas]
        for alpha in alphas:
            if alpha < 0 or (
                last_log_domain_size < 128 and alpha >= (1 << last_log_domain_size)
            ):
                raise InvalidArgumentError(
                    "`alpha` must be smaller than the output domain size"
                )

        if seeds is None:
            raw = secrets.token_bytes(16 * 2 * k)
            seeds_l = np.frombuffer(raw, dtype=np.uint32).reshape(k, 2, 4).copy()
        else:
            seeds_l = np.array(seeds, dtype=np.uint32).reshape(k, 2, 4)
        if prg is None:
            prg = KeygenPrg(self._prg_left, self._prg_right, self._prg_value)
        control = np.zeros((k, 2), dtype=bool)
        control[:, 1] = True
        alpha_limbs = uint128.u128_to_limb_rows(uint128.u128_array(alphas))

        seed_ints = uint128.limb_rows_to_ints(seeds_l.reshape(-1, 4))
        out_keys: Tuple[List[DpfKey], List[DpfKey]] = (
            [DpfKey(seed=seed_ints[2 * i], correction_words=[], party=0)
             for i in range(k)],
            [DpfKey(seed=seed_ints[2 * i + 1], correction_words=[], party=1)
             for i in range(k)],
        )
        level_records: List[Tuple[np.ndarray, np.ndarray, Optional[List[list]]]] = []

        for tree_level in range(1, v.tree_levels_needed):
            # Value correction for the previous level if it is an output
            # level: its PRG inputs are derived from the seeds BEFORE this
            # level's expansion, so both hashes can share one provider call
            # when blocks_needed == 1 (the inputs ARE the seeds).
            hierarchy_level = v.tree_to_hierarchy.get(tree_level - 1)
            blocks_needed = (
                v.blocks_needed[hierarchy_level]
                if hierarchy_level is not None
                else 0
            )

            # Expand all 2K seeds under both PRGs (Fig. 11 line 5).
            flat = seeds_l.reshape(2 * k, 4)
            left, right, value_hashed = prg.expand(
                flat, want_value=blocks_needed == 1
            )
            value_corrections: Optional[List[list]] = None
            if hierarchy_level is not None:
                if value_hashed is not None:
                    hashed = value_hashed.reshape(k, 2, 1, 4)
                else:
                    hashed = prg.value_hash(
                        _value_hash_inputs(seeds_l, blocks_needed)
                    ).reshape(k, 2, blocks_needed, 4)
                value_corrections = self._value_corrections_from_hashed(
                    hierarchy_level, hashed, control, alphas,
                    beta_cols[hierarchy_level],
                )

            bit_index = last_log_domain_size - tree_level
            if bit_index < 128:
                current_bit = (
                    (alpha_limbs[:, bit_index // 32] >> (bit_index % 32)) & 1
                ).astype(np.int64)  # [K]
            else:
                current_bit = np.zeros(k, dtype=np.int64)

            seeds_l, control, seed_correction, cc = batch_level_step(
                left.reshape(k, 2, 4), right.reshape(k, 2, 4),
                control, current_bit,
            )

            level_records.append((seed_correction, cc, value_corrections))

        last_level = v.num_hierarchy_levels - 1
        blocks_needed = v.blocks_needed[last_level]
        hashed = prg.value_hash(
            _value_hash_inputs(seeds_l, blocks_needed)
        ).reshape(k, 2, blocks_needed, 4)
        last_cw = self._value_corrections_from_hashed(
            last_level, hashed, control, alphas, beta_cols[-1]
        )
        assemble_batch_keys(out_keys, level_records, last_cw)
        return out_keys

    def _value_corrections_from_hashed(
        self,
        hierarchy_level: int,
        hashed: np.ndarray,  # uint32[K, 2, blocks_needed, 4] value-PRG outputs
        control: np.ndarray,  # bool[K, 2]
        alphas: Sequence[int],
        beta_col: Sequence,
    ) -> List[list]:
        """Typed value corrections for all K keys from the hashed blocks.

        Scalar Int/XorWrapper types up to 64 bits take a fully vectorized
        numpy path (the per-key ``compute_value_correction`` calls were
        the dominant host cost of a <=64-bit keygen pass — the same
        host-prep-not-AES waste class PERF.md's eval-prep record
        documents); wider and sampled types (u128, IntModN, tuples) keep
        the exact-Python-int path."""
        v = self._v
        k = hashed.shape[0]
        shift = (
            v.parameters[-1].log_domain_size
            - v.parameters[hierarchy_level].log_domain_size
        )
        value_type = v.parameters[hierarchy_level].value_type

        direct = (
            isinstance(value_type, (Int, XorWrapper))
            and value_type.bitsize <= 64
        )
        if direct:
            # index_in_block = (alpha >> shift) & (epb - 1): low bits only,
            # so the U128 limb forms cover every domain width vectorized.
            prefixes = uint128.u128_rshift(
                uint128.u128_array(alphas), min(shift, 128)
            )
            idx = uint128.u128_and_low(
                prefixes, min(64, v.block_index_bits(hierarchy_level))
            ).astype(np.int64)
            bits = value_type.bitsize
            vals = (
                np.ascontiguousarray(hashed[:, :, 0, :])
                .view(_DIRECT_DTYPES[bits])
                .reshape(k, 2, 128 // bits)
            )
            a = vals[:, 0]
            b = vals[:, 1].copy()
            beta_arr = np.array(
                [int(x) for x in beta_col], dtype=np.uint64
            ).astype(a.dtype)
            rows = np.arange(k)
            if isinstance(value_type, XorWrapper):
                b[rows, idx] ^= beta_arr
                corr = b ^ a  # XOR group: sub == add, neg == identity
            else:
                b[rows, idx] += beta_arr
                corr = b - a  # mod 2^bits via natural uint wraparound
                invert = control[:, 1]
                corr[invert] = (-corr[invert].astype(a.dtype)).astype(a.dtype)
            return corr.tolist()

        hashed_bytes = np.ascontiguousarray(hashed).view(np.uint8)
        out = []
        for i in range(k):
            alpha_prefix = alphas[i] >> shift if shift < 128 else 0
            index_in_block = v.domain_to_block_index(alpha_prefix, hierarchy_level)
            out.append(
                compute_value_correction(
                    value_type,
                    hashed_bytes[i, 0].tobytes(),
                    hashed_bytes[i, 1].tobytes(),
                    index_in_block,
                    beta_col[i],
                    bool(control[i, 1]),
                )
            )
        return out

    def _generate_next(
        self,
        tree_level: int,
        alpha: int,
        betas: Sequence,
        seeds: List[int],
        control_bits: List[int],
        keys: Tuple[DpfKey, DpfKey],
    ) -> None:
        """One level of correction-word generation (Fig. 11 lines 5-15)."""
        v = self._v
        # Value correction for the previous tree level, if it is an output
        # level ("PRG evaluation optimization", paper Appendix C.2).
        value_correction: list = []
        if (tree_level - 1) in v.tree_to_hierarchy:
            hierarchy_level = v.tree_to_hierarchy[tree_level - 1]
            shift = (
                v.parameters[-1].log_domain_size
                - v.parameters[hierarchy_level].log_domain_size
            )
            alpha_prefix = alpha >> shift if shift < 128 else 0
            value_correction = self._compute_value_correction(
                hierarchy_level, seeds, alpha_prefix,
                betas[hierarchy_level], bool(control_bits[1]),
            )

        # Expand both parties' seeds with both PRGs (line 5).
        left = self._prg_left.evaluate(seeds)
        right = self._prg_right.evaluate(seeds)
        expanded_seeds = [[left[0], left[1]], [right[0], right[1]]]  # [branch][party]
        expanded_control_bits = [[0, 0], [0, 0]]
        for branch in range(2):
            for party in range(2):
                bit, cleared = _extract_and_clear_lowest_bit(expanded_seeds[branch][party])
                expanded_control_bits[branch][party] = bit
                expanded_seeds[branch][party] = cleared

        # Keep/lose branch from the current bit of alpha (lines 6-8).
        bit_index = v.parameters[-1].log_domain_size - tree_level
        current_bit = int(bit_index < 128 and (alpha >> bit_index) & 1)
        keep, lose = current_bit, 1 - current_bit

        # Seed and control-bit correction words (lines 9-10).
        seed_correction = expanded_seeds[lose][0] ^ expanded_seeds[lose][1]
        control_correction = [
            expanded_control_bits[0][0] ^ expanded_control_bits[0][1] ^ current_bit ^ 1,
            expanded_control_bits[1][0] ^ expanded_control_bits[1][1] ^ current_bit,
        ]

        # Update seeds with the *previous* level's control bits (line 12; the
        # corrected seed feeds the next level directly, which is safe because
        # value correction uses an independent AES key).
        for party in range(2):
            new_seed = expanded_seeds[keep][party]
            if control_bits[party]:
                new_seed ^= seed_correction
            seeds[party] = new_seed

        # Update control bits (line 11).
        for party in range(2):
            control_bits[party] = expanded_control_bits[keep][party] ^ (
                control_bits[party] & control_correction[keep]
            )

        cw = CorrectionWord(
            seed=seed_correction,
            control_left=bool(control_correction[0]),
            control_right=bool(control_correction[1]),
            value_correction=list(value_correction),
        )
        keys[0].correction_words.append(cw)
        keys[1].correction_words.append(
            CorrectionWord(
                seed=cw.seed,
                control_left=cw.control_left,
                control_right=cw.control_right,
                value_correction=list(value_correction),
            )
        )
