"""DPF key generation (host / CPU).

Faithful re-implementation of DistributedPointFunction::GenerateKeysIncremental
and GenerateNext (/root/reference/dpf/distributed_point_function.cc:619-687,
103-204), which follow Fig. 11 of the Incremental DPF paper
(https://arxiv.org/pdf/2012.14884.pdf). Key generation is sequential in tree
depth with only 4-6 AES blocks per level, so it stays on the CPU (SURVEY.md
north star); evaluation is what runs on TPU.

Keys produced here are bit-exact with the reference implementation given the
same random seeds, so they can be exchanged with C++ evaluators.
"""

from __future__ import annotations

import secrets
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..utils.errors import InvalidArgumentError
from . import constants, uint128
from .aes_numpy import Aes128FixedKeyHash
from .keys import CorrectionWord, DpfKey
from .params import ParameterValidator
from .uint128 import MASK128
from .value_types import compute_value_correction


def _extract_and_clear_lowest_bit(x: int) -> Tuple[int, int]:
    """Returns (bit, x with bit 0 cleared); mirrors
    dpf_internal::ExtractAndClearLowestBit
    (/root/reference/dpf/internal/evaluate_prg_hwy.h:31-35)."""
    return x & 1, x & ~1


class KeyGenerator:
    """Generates incremental DPF keys for a validated parameter set."""

    def __init__(self, validator: ParameterValidator):
        self._v = validator
        self._prg_left = Aes128FixedKeyHash(constants.PRG_KEY_LEFT)
        self._prg_right = Aes128FixedKeyHash(constants.PRG_KEY_RIGHT)
        self._prg_value = Aes128FixedKeyHash(constants.PRG_KEY_VALUE)

    # -- helpers -----------------------------------------------------------

    def _domain_to_block_index(self, domain_index: int, hierarchy_level: int) -> int:
        return self._v.domain_to_block_index(domain_index, hierarchy_level)

    def _compute_value_correction(
        self, hierarchy_level: int, seeds: List[int], alpha: int, beta, invert: bool
    ) -> list:
        """Mirrors DistributedPointFunction::ComputeValueCorrection
        (distributed_point_function.cc:63-99): hash seeds[i]+j for
        j < blocks_needed under the value PRG, then form correction shares."""
        blocks_needed = self._v.blocks_needed[hierarchy_level]
        inputs = [(seeds[0] + j) & MASK128 for j in range(blocks_needed)]
        inputs += [(seeds[1] + j) & MASK128 for j in range(blocks_needed)]
        hashed = self._prg_value.evaluate(inputs)
        seed_a = b"".join(uint128.to_bytes(h) for h in hashed[:blocks_needed])
        seed_b = b"".join(uint128.to_bytes(h) for h in hashed[blocks_needed:])
        index_in_block = self._domain_to_block_index(alpha, hierarchy_level)
        value_type = self._v.parameters[hierarchy_level].value_type
        return compute_value_correction(
            value_type, seed_a, seed_b, index_in_block, beta, invert
        )

    # -- key generation ----------------------------------------------------

    def generate_keys_incremental(
        self,
        alpha: int,
        betas: Sequence,
        seeds: Optional[Tuple[int, int]] = None,
    ) -> Tuple[DpfKey, DpfKey]:
        """Generates a key pair. `seeds` overrides the CSPRNG (tests only)."""
        v = self._v
        if len(betas) != v.num_hierarchy_levels:
            raise InvalidArgumentError(
                "`beta` has to have the same size as `parameters` passed at "
                "construction"
            )
        for i, beta in enumerate(betas):
            v.validate_value(beta, i)
        last_log_domain_size = v.parameters[-1].log_domain_size
        if alpha < 0 or (
            last_log_domain_size < 128 and alpha >= (1 << last_log_domain_size)
        ):
            raise InvalidArgumentError(
                "`alpha` must be smaller than the output domain size"
            )

        if seeds is None:
            seeds = (
                uint128.from_bytes(secrets.token_bytes(16)),
                uint128.from_bytes(secrets.token_bytes(16)),
            )
        seeds = [seeds[0] & MASK128, seeds[1] & MASK128]
        control_bits = [0, 1]
        keys = (
            DpfKey(seed=seeds[0], correction_words=[], party=0),
            DpfKey(seed=seeds[1], correction_words=[], party=1),
        )

        for tree_level in range(1, v.tree_levels_needed):
            self._generate_next(tree_level, alpha, betas, seeds, control_bits, keys)

        last_cw = self._compute_value_correction(
            v.num_hierarchy_levels - 1, seeds, alpha, betas[-1], bool(control_bits[1])
        )
        keys[0].last_level_value_correction = list(last_cw)
        keys[1].last_level_value_correction = list(last_cw)
        return keys

    # -- batched key generation -------------------------------------------

    def generate_keys_batch(
        self,
        alphas: Sequence[int],
        betas: Sequence[Sequence],
        seeds: Optional[np.ndarray] = None,
    ) -> Tuple[List[DpfKey], List[DpfKey]]:
        """Generates K key pairs at once, level-major.

        Semantics are identical to `generate_keys_incremental` run K times
        (same Fig.-11 algebra, same AES calls), but the per-level PRG
        expansion is one vectorized numpy AES call over all 2K seeds instead
        of 2K two-block calls — this is what makes 1024-key benchmark setup
        take seconds instead of minutes.

        Args:
          alphas: K domain indices.
          betas: per hierarchy level, either a scalar (broadcast over keys) or
            a length-K sequence of values.
          seeds: optional uint32[K, 2, 4] CSPRNG override (tests only).
        Returns: (keys of party 0, keys of party 1), each a length-K list.
        """
        v = self._v
        k = len(alphas)
        if len(betas) != v.num_hierarchy_levels:
            raise InvalidArgumentError(
                "`beta` has to have the same size as `parameters` passed at "
                "construction"
            )
        beta_cols: List[list] = []
        for level, b in enumerate(betas):
            col = list(b) if isinstance(b, (list, tuple, np.ndarray)) else [b] * k
            if len(col) != k:
                raise InvalidArgumentError(
                    f"betas[{level}] must be a scalar or have one value per key"
                )
            for val in col:
                v.validate_value(val, level)
            beta_cols.append(col)
        last_log_domain_size = v.parameters[-1].log_domain_size
        alphas = [int(a) for a in alphas]
        for alpha in alphas:
            if alpha < 0 or (
                last_log_domain_size < 128 and alpha >= (1 << last_log_domain_size)
            ):
                raise InvalidArgumentError(
                    "`alpha` must be smaller than the output domain size"
                )

        if seeds is None:
            raw = secrets.token_bytes(16 * 2 * k)
            seeds_l = np.frombuffer(raw, dtype=np.uint32).reshape(k, 2, 4).copy()
        else:
            seeds_l = np.array(seeds, dtype=np.uint32).reshape(k, 2, 4)
        control = np.zeros((k, 2), dtype=bool)
        control[:, 1] = True
        alpha_limbs = uint128.array_to_limbs(alphas)  # uint32[K, 4]

        out_keys: Tuple[List[DpfKey], List[DpfKey]] = (
            [DpfKey(seed=uint128.from_limbs(seeds_l[i, 0]), correction_words=[], party=0)
             for i in range(k)],
            [DpfKey(seed=uint128.from_limbs(seeds_l[i, 1]), correction_words=[], party=1)
             for i in range(k)],
        )

        for tree_level in range(1, v.tree_levels_needed):
            # Value correction for the previous level if it is an output level.
            value_corrections: Optional[List[list]] = None
            if (tree_level - 1) in v.tree_to_hierarchy:
                hierarchy_level = v.tree_to_hierarchy[tree_level - 1]
                value_corrections = self._batch_value_correction(
                    hierarchy_level, seeds_l, control, alphas,
                    beta_cols[hierarchy_level],
                )

            # Expand all 2K seeds under both PRGs (Fig. 11 line 5).
            flat = seeds_l.reshape(2 * k, 4)
            left = self._prg_left.evaluate_limbs(flat).reshape(k, 2, 4)
            right = self._prg_right.evaluate_limbs(flat).reshape(k, 2, 4)
            exp = np.stack([left, right], axis=1)  # [K, branch, party, 4]
            exp_bits = (exp[..., 0] & 1).astype(bool)  # [K, branch, party]
            exp[..., 0] &= np.uint32(0xFFFFFFFE)

            bit_index = last_log_domain_size - tree_level
            if bit_index < 128:
                current_bit = (
                    (alpha_limbs[:, bit_index // 32] >> (bit_index % 32)) & 1
                ).astype(np.int64)  # [K]
            else:
                current_bit = np.zeros(k, dtype=np.int64)
            keep = current_bit  # [K]
            lose = 1 - keep

            rows = np.arange(k)
            lose_seeds = exp[rows, lose]  # [K, party, 4]
            seed_correction = lose_seeds[:, 0] ^ lose_seeds[:, 1]  # [K, 4]
            # control_correction[:, branch] (lines 9-10)
            cc = np.empty((k, 2), dtype=bool)
            cc[:, 0] = exp_bits[:, 0, 0] ^ exp_bits[:, 0, 1] ^ (current_bit == 1) ^ True
            cc[:, 1] = exp_bits[:, 1, 0] ^ exp_bits[:, 1, 1] ^ (current_bit == 1)

            keep_seeds = exp[rows, keep]  # [K, party, 4]
            corr = np.where(control[:, :, None], seed_correction[:, None, :], 0)
            seeds_l = (keep_seeds ^ corr).astype(np.uint32)
            keep_cc = cc[rows, keep]  # [K]
            control = exp_bits[rows, keep] ^ (control & keep_cc[:, None])

            for i in range(k):
                vc = value_corrections[i] if value_corrections is not None else []
                sc = uint128.from_limbs(seed_correction[i])
                for party in range(2):
                    out_keys[party][i].correction_words.append(
                        CorrectionWord(
                            seed=sc,
                            control_left=bool(cc[i, 0]),
                            control_right=bool(cc[i, 1]),
                            value_correction=list(vc),
                        )
                    )

        last_cw = self._batch_value_correction(
            v.num_hierarchy_levels - 1, seeds_l, control, alphas, beta_cols[-1]
        )
        for i in range(k):
            out_keys[0][i].last_level_value_correction = list(last_cw[i])
            out_keys[1][i].last_level_value_correction = list(last_cw[i])
        return out_keys

    def _batch_value_correction(
        self,
        hierarchy_level: int,
        seeds_l: np.ndarray,  # uint32[K, 2, 4]
        control: np.ndarray,  # bool[K, 2]
        alphas: Sequence[int],
        beta_col: Sequence,
    ) -> List[list]:
        """Value corrections for all K keys with one batched value-PRG call."""
        v = self._v
        k = seeds_l.shape[0]
        blocks_needed = v.blocks_needed[hierarchy_level]
        # inputs[i, party, j] = seeds[i, party] + j  (uint128 limb addition)
        inputs = np.repeat(seeds_l[:, :, None, :], blocks_needed, axis=2).astype(
            np.uint64
        )  # widen to u64 for carry math
        offs = np.arange(blocks_needed, dtype=np.uint64)
        inputs[..., 0] += offs[None, None, :]
        for limb in range(3):
            carry = inputs[..., limb] >> 32
            inputs[..., limb] &= 0xFFFFFFFF
            inputs[..., limb + 1] += carry
        inputs[..., 3] &= 0xFFFFFFFF
        hashed = self._prg_value.evaluate_limbs(
            inputs.astype(np.uint32).reshape(k * 2 * blocks_needed, 4)
        ).reshape(k, 2, blocks_needed, 4)
        hashed_bytes = np.ascontiguousarray(hashed).view(np.uint8)

        shift = (
            v.parameters[-1].log_domain_size
            - v.parameters[hierarchy_level].log_domain_size
        )
        value_type = v.parameters[hierarchy_level].value_type
        out = []
        for i in range(k):
            alpha_prefix = alphas[i] >> shift if shift < 128 else 0
            index_in_block = v.domain_to_block_index(alpha_prefix, hierarchy_level)
            out.append(
                compute_value_correction(
                    value_type,
                    hashed_bytes[i, 0].tobytes(),
                    hashed_bytes[i, 1].tobytes(),
                    index_in_block,
                    beta_col[i],
                    bool(control[i, 1]),
                )
            )
        return out

    def _generate_next(
        self,
        tree_level: int,
        alpha: int,
        betas: Sequence,
        seeds: List[int],
        control_bits: List[int],
        keys: Tuple[DpfKey, DpfKey],
    ) -> None:
        """One level of correction-word generation (Fig. 11 lines 5-15)."""
        v = self._v
        # Value correction for the previous tree level, if it is an output
        # level ("PRG evaluation optimization", paper Appendix C.2).
        value_correction: list = []
        if (tree_level - 1) in v.tree_to_hierarchy:
            hierarchy_level = v.tree_to_hierarchy[tree_level - 1]
            shift = (
                v.parameters[-1].log_domain_size
                - v.parameters[hierarchy_level].log_domain_size
            )
            alpha_prefix = alpha >> shift if shift < 128 else 0
            value_correction = self._compute_value_correction(
                hierarchy_level, seeds, alpha_prefix,
                betas[hierarchy_level], bool(control_bits[1]),
            )

        # Expand both parties' seeds with both PRGs (line 5).
        left = self._prg_left.evaluate(seeds)
        right = self._prg_right.evaluate(seeds)
        expanded_seeds = [[left[0], left[1]], [right[0], right[1]]]  # [branch][party]
        expanded_control_bits = [[0, 0], [0, 0]]
        for branch in range(2):
            for party in range(2):
                bit, cleared = _extract_and_clear_lowest_bit(expanded_seeds[branch][party])
                expanded_control_bits[branch][party] = bit
                expanded_seeds[branch][party] = cleared

        # Keep/lose branch from the current bit of alpha (lines 6-8).
        bit_index = v.parameters[-1].log_domain_size - tree_level
        current_bit = int(bit_index < 128 and (alpha >> bit_index) & 1)
        keep, lose = current_bit, 1 - current_bit

        # Seed and control-bit correction words (lines 9-10).
        seed_correction = expanded_seeds[lose][0] ^ expanded_seeds[lose][1]
        control_correction = [
            expanded_control_bits[0][0] ^ expanded_control_bits[0][1] ^ current_bit ^ 1,
            expanded_control_bits[1][0] ^ expanded_control_bits[1][1] ^ current_bit,
        ]

        # Update seeds with the *previous* level's control bits (line 12; the
        # corrected seed feeds the next level directly, which is safe because
        # value correction uses an independent AES key).
        for party in range(2):
            new_seed = expanded_seeds[keep][party]
            if control_bits[party]:
                new_seed ^= seed_correction
            seeds[party] = new_seed

        # Update control bits (line 11).
        for party in range(2):
            control_bits[party] = expanded_control_bits[keep][party] ^ (
                control_bits[party] & control_correction[keep]
            )

        cw = CorrectionWord(
            seed=seed_correction,
            control_left=bool(control_correction[0]),
            control_right=bool(control_correction[1]),
            value_correction=list(value_correction),
        )
        keys[0].correction_words.append(cw)
        keys[1].correction_words.append(
            CorrectionWord(
                seed=cw.seed,
                control_left=cw.control_left,
                control_right=cw.control_right,
                value_correction=list(value_correction),
            )
        )
