"""DPF key generation (host / CPU).

Faithful re-implementation of DistributedPointFunction::GenerateKeysIncremental
and GenerateNext (/root/reference/dpf/distributed_point_function.cc:619-687,
103-204), which follow Fig. 11 of the Incremental DPF paper
(https://arxiv.org/pdf/2012.14884.pdf). Key generation is sequential in tree
depth with only 4-6 AES blocks per level, so it stays on the CPU (SURVEY.md
north star); evaluation is what runs on TPU.

Keys produced here are bit-exact with the reference implementation given the
same random seeds, so they can be exchanged with C++ evaluators.
"""

from __future__ import annotations

import secrets
from typing import List, Optional, Sequence, Tuple

from ..utils.errors import InvalidArgumentError
from . import constants, uint128
from .aes_numpy import Aes128FixedKeyHash
from .keys import CorrectionWord, DpfKey
from .params import ParameterValidator
from .uint128 import MASK128
from .value_types import compute_value_correction


def _extract_and_clear_lowest_bit(x: int) -> Tuple[int, int]:
    """Returns (bit, x with bit 0 cleared); mirrors
    dpf_internal::ExtractAndClearLowestBit
    (/root/reference/dpf/internal/evaluate_prg_hwy.h:31-35)."""
    return x & 1, x & ~1


class KeyGenerator:
    """Generates incremental DPF keys for a validated parameter set."""

    def __init__(self, validator: ParameterValidator):
        self._v = validator
        self._prg_left = Aes128FixedKeyHash(constants.PRG_KEY_LEFT)
        self._prg_right = Aes128FixedKeyHash(constants.PRG_KEY_RIGHT)
        self._prg_value = Aes128FixedKeyHash(constants.PRG_KEY_VALUE)

    # -- helpers -----------------------------------------------------------

    def _domain_to_block_index(self, domain_index: int, hierarchy_level: int) -> int:
        return self._v.domain_to_block_index(domain_index, hierarchy_level)

    def _compute_value_correction(
        self, hierarchy_level: int, seeds: List[int], alpha: int, beta, invert: bool
    ) -> list:
        """Mirrors DistributedPointFunction::ComputeValueCorrection
        (distributed_point_function.cc:63-99): hash seeds[i]+j for
        j < blocks_needed under the value PRG, then form correction shares."""
        blocks_needed = self._v.blocks_needed[hierarchy_level]
        inputs = [(seeds[0] + j) & MASK128 for j in range(blocks_needed)]
        inputs += [(seeds[1] + j) & MASK128 for j in range(blocks_needed)]
        hashed = self._prg_value.evaluate(inputs)
        seed_a = b"".join(uint128.to_bytes(h) for h in hashed[:blocks_needed])
        seed_b = b"".join(uint128.to_bytes(h) for h in hashed[blocks_needed:])
        index_in_block = self._domain_to_block_index(alpha, hierarchy_level)
        value_type = self._v.parameters[hierarchy_level].value_type
        return compute_value_correction(
            value_type, seed_a, seed_b, index_in_block, beta, invert
        )

    # -- key generation ----------------------------------------------------

    def generate_keys_incremental(
        self,
        alpha: int,
        betas: Sequence,
        seeds: Optional[Tuple[int, int]] = None,
    ) -> Tuple[DpfKey, DpfKey]:
        """Generates a key pair. `seeds` overrides the CSPRNG (tests only)."""
        v = self._v
        if len(betas) != v.num_hierarchy_levels:
            raise InvalidArgumentError(
                "`beta` has to have the same size as `parameters` passed at "
                "construction"
            )
        for i, beta in enumerate(betas):
            v.validate_value(beta, i)
        last_log_domain_size = v.parameters[-1].log_domain_size
        if alpha < 0 or (
            last_log_domain_size < 128 and alpha >= (1 << last_log_domain_size)
        ):
            raise InvalidArgumentError(
                "`alpha` must be smaller than the output domain size"
            )

        if seeds is None:
            seeds = (
                uint128.from_bytes(secrets.token_bytes(16)),
                uint128.from_bytes(secrets.token_bytes(16)),
            )
        seeds = [seeds[0] & MASK128, seeds[1] & MASK128]
        control_bits = [0, 1]
        keys = (
            DpfKey(seed=seeds[0], correction_words=[], party=0),
            DpfKey(seed=seeds[1], correction_words=[], party=1),
        )

        for tree_level in range(1, v.tree_levels_needed):
            self._generate_next(tree_level, alpha, betas, seeds, control_bits, keys)

        last_cw = self._compute_value_correction(
            v.num_hierarchy_levels - 1, seeds, alpha, betas[-1], bool(control_bits[1])
        )
        keys[0].last_level_value_correction = list(last_cw)
        keys[1].last_level_value_correction = list(last_cw)
        return keys

    def _generate_next(
        self,
        tree_level: int,
        alpha: int,
        betas: Sequence,
        seeds: List[int],
        control_bits: List[int],
        keys: Tuple[DpfKey, DpfKey],
    ) -> None:
        """One level of correction-word generation (Fig. 11 lines 5-15)."""
        v = self._v
        # Value correction for the previous tree level, if it is an output
        # level ("PRG evaluation optimization", paper Appendix C.2).
        value_correction: list = []
        if (tree_level - 1) in v.tree_to_hierarchy:
            hierarchy_level = v.tree_to_hierarchy[tree_level - 1]
            shift = (
                v.parameters[-1].log_domain_size
                - v.parameters[hierarchy_level].log_domain_size
            )
            alpha_prefix = alpha >> shift if shift < 128 else 0
            value_correction = self._compute_value_correction(
                hierarchy_level, seeds, alpha_prefix,
                betas[hierarchy_level], bool(control_bits[1]),
            )

        # Expand both parties' seeds with both PRGs (line 5).
        left = self._prg_left.evaluate(seeds)
        right = self._prg_right.evaluate(seeds)
        expanded_seeds = [[left[0], left[1]], [right[0], right[1]]]  # [branch][party]
        expanded_control_bits = [[0, 0], [0, 0]]
        for branch in range(2):
            for party in range(2):
                bit, cleared = _extract_and_clear_lowest_bit(expanded_seeds[branch][party])
                expanded_control_bits[branch][party] = bit
                expanded_seeds[branch][party] = cleared

        # Keep/lose branch from the current bit of alpha (lines 6-8).
        bit_index = v.parameters[-1].log_domain_size - tree_level
        current_bit = int(bit_index < 128 and (alpha >> bit_index) & 1)
        keep, lose = current_bit, 1 - current_bit

        # Seed and control-bit correction words (lines 9-10).
        seed_correction = expanded_seeds[lose][0] ^ expanded_seeds[lose][1]
        control_correction = [
            expanded_control_bits[0][0] ^ expanded_control_bits[0][1] ^ current_bit ^ 1,
            expanded_control_bits[1][0] ^ expanded_control_bits[1][1] ^ current_bit,
        ]

        # Update seeds with the *previous* level's control bits (line 12; the
        # corrected seed feeds the next level directly, which is safe because
        # value correction uses an independent AES key).
        for party in range(2):
            new_seed = expanded_seeds[keep][party]
            if control_bits[party]:
                new_seed ^= seed_correction
            seeds[party] = new_seed

        # Update control bits (line 11).
        for party in range(2):
            control_bits[party] = expanded_control_bits[keep][party] ^ (
                control_bits[party] & control_correction[keep]
            )

        cw = CorrectionWord(
            seed=seed_correction,
            control_left=bool(control_correction[0]),
            control_right=bool(control_correction[1]),
            value_correction=list(value_correction),
        )
        keys[0].correction_words.append(cw)
        keys[1].correction_words.append(
            CorrectionWord(
                seed=cw.seed,
                control_left=cw.control_left,
                control_right=cw.control_right,
                value_correction=list(value_correction),
            )
        )
