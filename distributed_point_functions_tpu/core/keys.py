"""Key and evaluation-context data structures.

Host dataclasses mirroring the reference's wire-format messages
(/root/reference/dpf/distributed_point_function.proto:108-171). 128-bit
quantities are Python ints; value corrections are host values typed by the
corresponding hierarchy level's ValueType. Conversion to/from the
byte-compatible protobuf wire format lives in protos/serialization.py.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional

from .params import DpfParameters


@dataclasses.dataclass
class CorrectionWord:
    """Per-tree-level correction: seed XOR word, control-bit corrections, and
    (on output levels) the value correction for the *previous* tree layer."""

    seed: int
    control_left: bool
    control_right: bool
    value_correction: List[Any] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class DpfKey:
    """One party's DPF key."""

    seed: int
    correction_words: List[CorrectionWord]
    party: int
    last_level_value_correction: List[Any] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class PartialEvaluation:
    """Saved (prefix -> seed, control bit) state between hierarchy levels."""

    prefix: int
    seed: int
    control_bit: bool


@dataclasses.dataclass
class EvaluationContext:
    """State of a partially evaluated incremental DPF. Serializable and
    resumable between hierarchy levels — this is the framework's
    checkpoint/resume mechanism (SURVEY.md section 5)."""

    parameters: List[DpfParameters]
    key: DpfKey
    previous_hierarchy_level: int = -1
    partial_evaluations: List[PartialEvaluation] = dataclasses.field(default_factory=list)
    partial_evaluations_level: int = 0
