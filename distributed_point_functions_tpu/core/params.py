"""DPF parameters and structural validation.

Python equivalent of dpf_internal::ProtoValidator
(/root/reference/dpf/internal/proto_validator.{h,cc}): validates parameter
lists, keys and evaluation contexts, and computes the hierarchy<->tree level
maps plus the evaluation-tree height (block packing shrinks the tree by up to
7 - log2(bits) levels; see proto_validator.cc:111-137).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence

from ..utils.errors import InvalidArgumentError
from . import keys as keys_mod
from .value_types import ValueType

DEFAULT_SECURITY_PARAMETER = 40.0
SECURITY_PARAMETER_EPSILON = 0.0001


@dataclasses.dataclass(frozen=True)
class DpfParameters:
    """Parameters of one hierarchy level.

    Mirrors the DpfParameters proto message
    (/root/reference/dpf/distributed_point_function.proto:92-105).
    security_parameter == 0 selects the default 40 + log_domain_size.
    """

    log_domain_size: int
    value_type: ValueType
    security_parameter: float = 0.0


def default_security_parameter(p: DpfParameters) -> float:
    return DEFAULT_SECURITY_PARAMETER + p.log_domain_size


def _almost_equal(a: float, b: float) -> bool:
    return abs(a - b) <= SECURITY_PARAMETER_EPSILON


def parameters_are_equal(lhs: DpfParameters, rhs: DpfParameters) -> bool:
    if lhs.log_domain_size != rhs.log_domain_size:
        return False
    if not (
        _almost_equal(lhs.security_parameter, rhs.security_parameter)
        or (
            lhs.security_parameter == 0
            and _almost_equal(rhs.security_parameter, default_security_parameter(rhs))
        )
        or (
            rhs.security_parameter == 0
            and _almost_equal(lhs.security_parameter, default_security_parameter(lhs))
        )
    ):
        return False
    return lhs.value_type == rhs.value_type


def validate_parameters(parameters: Sequence[DpfParameters]) -> None:
    """Mirrors ProtoValidator::ValidateParameters (proto_validator.cc:144-187)."""
    if not parameters:
        raise InvalidArgumentError("`parameters` must not be empty")
    previous_log_domain_size = 0
    for i, p in enumerate(parameters):
        if p.log_domain_size < 0:
            raise InvalidArgumentError("`log_domain_size` must be non-negative")
        if p.log_domain_size > 128:
            raise InvalidArgumentError("`log_domain_size` must be <= 128")
        if i > 0 and p.log_domain_size <= previous_log_domain_size:
            raise InvalidArgumentError(
                "`log_domain_size` fields must be in ascending order in `parameters`"
            )
        previous_log_domain_size = p.log_domain_size
        if p.value_type is None:
            raise InvalidArgumentError("`value_type` is required")
        p.value_type.validate()
        if math.isnan(p.security_parameter):
            raise InvalidArgumentError("`security_parameter` must not be NaN")
        if p.security_parameter < 0 or p.security_parameter > 128:
            raise InvalidArgumentError("`security_parameter` must be in [0, 128]")


class ParameterValidator:
    """Validated parameters plus derived tree structure."""

    def __init__(self, parameters: Sequence[DpfParameters]):
        validate_parameters(parameters)
        # Apply the security-parameter default.
        resolved: List[DpfParameters] = []
        for p in parameters:
            sp = p.security_parameter
            if sp == 0:
                sp = default_security_parameter(p)
            resolved.append(dataclasses.replace(p, security_parameter=sp))
        self.parameters: List[DpfParameters] = resolved

        # Map hierarchy levels to tree levels: a single AES block holds up to
        # 2^7 bits, so hierarchy levels with small elements sit above the leaf
        # layer of the tree (proto_validator.cc:117-137).
        tree_to_hierarchy: Dict[int, int] = {}
        hierarchy_to_tree: List[int] = [0] * len(resolved)
        tree_levels_needed = 0
        self.blocks_needed: List[int] = []
        for i, p in enumerate(resolved):
            bits_needed = p.value_type.bits_needed(p.security_parameter)
            self.blocks_needed.append((bits_needed + 127) // 128)
            log_bits_needed = math.ceil(math.log2(bits_needed))
            tree_level = max(
                tree_levels_needed,
                p.log_domain_size - 7 + min(log_bits_needed, 7),
            )
            tree_to_hierarchy[tree_level] = i
            hierarchy_to_tree[i] = tree_level
            tree_levels_needed = max(tree_levels_needed, tree_level + 1)
        self.tree_to_hierarchy = tree_to_hierarchy
        self.hierarchy_to_tree = hierarchy_to_tree
        self.tree_levels_needed = tree_levels_needed

    @property
    def num_hierarchy_levels(self) -> int:
        return len(self.parameters)

    def block_index_bits(self, hierarchy_level: int) -> int:
        """Bits of a domain index below the tree (block packing)."""
        return (
            self.parameters[hierarchy_level].log_domain_size
            - self.hierarchy_to_tree[hierarchy_level]
        )

    def domain_to_tree_index(self, domain_index: int, hierarchy_level: int) -> int:
        """Mirrors DomainToTreeIndex (distributed_point_function.cc:206-213)."""
        return domain_index >> self.block_index_bits(hierarchy_level)

    def domain_to_block_index(self, domain_index: int, hierarchy_level: int) -> int:
        """Mirrors DomainToBlockIndex (distributed_point_function.cc:215-221)."""
        return domain_index & ((1 << self.block_index_bits(hierarchy_level)) - 1)

    def validate_value(self, value, hierarchy_level: int) -> None:
        self.parameters[hierarchy_level].value_type.validate_value(value)

    def validate_key(self, key: "keys_mod.DpfKey") -> None:
        """Mirrors ProtoValidator::ValidateDpfKey (proto_validator.cc:189-220)."""
        if key.seed is None:
            raise InvalidArgumentError("key.seed must be present")
        if not key.last_level_value_correction:
            raise InvalidArgumentError("key.last_level_value_correction must be present")
        if len(key.correction_words) != self.tree_levels_needed - 1:
            raise InvalidArgumentError(
                f"Malformed DpfKey: expected {self.tree_levels_needed - 1} "
                f"correction words, but got {len(key.correction_words)}"
            )
        for i, tree_level in enumerate(self.hierarchy_to_tree):
            if tree_level == self.tree_levels_needed - 1:
                continue  # stored in last_level_value_correction
            if not key.correction_words[tree_level].value_correction:
                raise InvalidArgumentError(
                    f"Malformed DpfKey: expected correction_words[{tree_level}] to "
                    f"contain the value correction of hierarchy level {i}"
                )

    def validate_evaluation_context(self, ctx: "keys_mod.EvaluationContext") -> None:
        """Mirrors ProtoValidator::ValidateEvaluationContext
        (proto_validator.cc:222-251)."""
        if len(ctx.parameters) != len(self.parameters):
            raise InvalidArgumentError("Number of parameters in `ctx` doesn't match")
        for i, (mine, theirs) in enumerate(zip(self.parameters, ctx.parameters)):
            if not parameters_are_equal(mine, theirs):
                raise InvalidArgumentError(f"Parameter {i} in `ctx` doesn't match")
        if ctx.key is None:
            raise InvalidArgumentError("ctx.key must be present")
        self.validate_key(ctx.key)
        if ctx.previous_hierarchy_level >= len(self.parameters) - 1:
            raise InvalidArgumentError("This context has already been fully evaluated")
        if ctx.partial_evaluations and (
            ctx.partial_evaluations_level > ctx.previous_hierarchy_level
        ):
            raise InvalidArgumentError(
                "ctx.partial_evaluations_level must be less than or equal to "
                "ctx.previous_hierarchy_level"
            )
