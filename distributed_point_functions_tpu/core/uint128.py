"""Host-side 128-bit integer helpers.

The whole framework represents 128-bit AES blocks / DPF seeds in two ways:

* On the host (key generation, proto (de)serialization): Python ``int`` in
  ``[0, 2**128)`` or numpy arrays of shape ``[..., 16]`` (uint8, little-endian
  bytes) / ``[..., 4]`` (uint32 limbs, little-endian limb order).
* On device (JAX): ``uint32[..., 4]`` limb arrays, limb 0 = bits 0..31.

The little-endian layout matches the reference C++ library, which hands the
in-memory representation of an ``absl::uint128`` (x86, little-endian) directly
to AES (see /root/reference/dpf/aes_128_fixed_key_hash.cc:38-44,70-73). Keeping
the same byte order makes keys and hash outputs byte-compatible.
"""

from __future__ import annotations

import numpy as np

MASK64 = (1 << 64) - 1
MASK128 = (1 << 128) - 1


def make_uint128(high: int, low: int) -> int:
    """Equivalent of absl::MakeUint128: (high << 64) | low."""
    return ((high & MASK64) << 64) | (low & MASK64)


def high64(x: int) -> int:
    return (x >> 64) & MASK64


def low64(x: int) -> int:
    return x & MASK64


def to_bytes(x: int) -> bytes:
    """128-bit int -> 16 little-endian bytes (the AES-facing layout)."""
    return (int(x) & MASK128).to_bytes(16, "little")


def from_bytes(b: bytes) -> int:
    return int.from_bytes(b, "little")


def to_limbs(x: int) -> np.ndarray:
    """128-bit int -> uint32[4] little-endian limbs."""
    return np.frombuffer(to_bytes(x), dtype=np.uint32).copy()


def from_limbs(limbs: np.ndarray) -> int:
    limbs = np.asarray(limbs, dtype=np.uint32)
    assert limbs.shape[-1] == 4, limbs.shape
    return from_bytes(limbs.tobytes())


def array_to_limbs(xs) -> np.ndarray:
    """Iterable of 128-bit ints -> uint32[N, 4]."""
    xs = list(xs)
    out = np.empty((len(xs), 4), dtype=np.uint32)
    for i, x in enumerate(xs):
        out[i] = to_limbs(x)
    return out


def limbs_to_array(limbs: np.ndarray) -> list:
    """uint32[N, 4] -> list of 128-bit Python ints."""
    limbs = np.ascontiguousarray(np.asarray(limbs, dtype=np.uint32))
    assert limbs.shape[-1] == 4
    flat = limbs.reshape(-1, 4)
    return [from_bytes(flat[i].tobytes()) for i in range(flat.shape[0])]


def sigma(x: int) -> int:
    """The MMO orthomorphism sigma(x) = (high ^ low, high).

    Mirrors /root/reference/dpf/aes_128_fixed_key_hash.cc:63-67.
    """
    hi, lo = high64(x), low64(x)
    return make_uint128(hi ^ lo, hi)
