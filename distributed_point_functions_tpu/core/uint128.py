"""Host-side 128-bit integer helpers.

The whole framework represents 128-bit AES blocks / DPF seeds in two ways:

* On the host (key generation, proto (de)serialization): Python ``int`` in
  ``[0, 2**128)`` or numpy arrays of shape ``[..., 16]`` (uint8, little-endian
  bytes) / ``[..., 4]`` (uint32 limbs, little-endian limb order).
* On device (JAX): ``uint32[..., 4]`` limb arrays, limb 0 = bits 0..31.

The little-endian layout matches the reference C++ library, which hands the
in-memory representation of an ``absl::uint128`` (x86, little-endian) directly
to AES (see /root/reference/dpf/aes_128_fixed_key_hash.cc:38-44,70-73). Keeping
the same byte order makes keys and hash outputs byte-compatible.
"""

from __future__ import annotations

import numpy as np

MASK64 = (1 << 64) - 1
MASK128 = (1 << 128) - 1


def make_uint128(high: int, low: int) -> int:
    """Equivalent of absl::MakeUint128: (high << 64) | low."""
    return ((high & MASK64) << 64) | (low & MASK64)


def high64(x: int) -> int:
    return (x >> 64) & MASK64


def low64(x: int) -> int:
    return x & MASK64


def to_bytes(x: int) -> bytes:
    """128-bit int -> 16 little-endian bytes (the AES-facing layout)."""
    return (int(x) & MASK128).to_bytes(16, "little")


def from_bytes(b: bytes) -> int:
    return int.from_bytes(b, "little")


def to_limbs(x: int) -> np.ndarray:
    """128-bit int -> uint32[4] little-endian limbs."""
    return np.frombuffer(to_bytes(x), dtype=np.uint32).copy()


def from_limbs(limbs: np.ndarray) -> int:
    limbs = np.asarray(limbs, dtype=np.uint32)
    assert limbs.shape[-1] == 4, limbs.shape
    return from_bytes(limbs.tobytes())


def limb_rows_to_ints(rows: np.ndarray) -> list:
    """uint32[N, 4] little-endian limb rows -> list of N Python ints.

    Two vectorized u64 combines + one ``tolist`` per half instead of
    per-row ``tobytes``/``from_bytes`` — the per-row form dominated
    depth-128 batched keygen assembly (one conversion per key per tree
    level)."""
    r = np.asarray(rows, dtype=np.uint64).reshape(-1, 4)
    lo = (r[:, 0] | (r[:, 1] << np.uint64(32))).tolist()
    hi = (r[:, 2] | (r[:, 3] << np.uint64(32))).tolist()
    return [(h << 64) | l for h, l in zip(hi, lo)]


def array_to_limbs(xs) -> np.ndarray:
    """Iterable of 128-bit ints -> uint32[N, 4]."""
    xs = list(xs)
    out = np.empty((len(xs), 4), dtype=np.uint32)
    for i, x in enumerate(xs):
        out[i] = to_limbs(x)
    return out


def limbs_to_array(limbs: np.ndarray) -> list:
    """uint32[N, 4] -> list of 128-bit Python ints."""
    limbs = np.ascontiguousarray(np.asarray(limbs, dtype=np.uint32))
    assert limbs.shape[-1] == 4
    flat = limbs.reshape(-1, 4)
    return [from_bytes(flat[i].tobytes()) for i in range(flat.shape[0])]


def sigma(x: int) -> int:
    """The MMO orthomorphism sigma(x) = (high ^ low, high).

    Mirrors /root/reference/dpf/aes_128_fixed_key_hash.cc:63-67.
    """
    hi, lo = high64(x), low64(x)
    return make_uint128(hi ^ lo, hi)


# ---------------------------------------------------------------------------
# Vectorized uint128 arrays (structured hi/lo dtype)
# ---------------------------------------------------------------------------
#
# Bulk host paths (hierarchical prefix bookkeeping over 2^64..2^128 domains,
# 128-bit point batches) need millions of 128-bit values with numpy-speed
# compare/sort/searchsorted/shift — python-int object arrays are 30-100x too
# slow there. U128 is a two-field structured dtype ordered (hi, lo), so
# numpy's lexicographic structured comparison IS the numeric order and
# np.unique / np.sort / np.searchsorted work unchanged.

U128 = np.dtype([("hi", "<u8"), ("lo", "<u8")])


def u128_array(xs) -> np.ndarray:
    """Iterable of Python ints (or uint64 array) -> U128[N]."""
    if isinstance(xs, np.ndarray) and xs.dtype == U128:
        return xs
    if isinstance(xs, np.ndarray) and xs.dtype != object:
        out = np.zeros(xs.shape[0], dtype=U128)
        out["lo"] = xs.astype(np.uint64)
        return out
    xs = [int(x) for x in xs]
    out = np.empty(len(xs), dtype=U128)
    out["hi"] = np.array([x >> 64 for x in xs], dtype=np.uint64)
    out["lo"] = np.array([x & MASK64 for x in xs], dtype=np.uint64)
    return out


def u128_to_ints(a: np.ndarray) -> list:
    """U128[N] -> list of Python ints."""
    hi = a["hi"].tolist()
    lo = a["lo"].tolist()
    return [(h << 64) | l for h, l in zip(hi, lo)]


def u128_rshift(a: np.ndarray, k: int) -> np.ndarray:
    out = np.empty(a.shape, dtype=U128)
    if k == 0:
        out["hi"], out["lo"] = a["hi"], a["lo"]
    elif k >= 128:
        out["hi"] = 0
        out["lo"] = 0
    elif k >= 64:
        out["hi"] = 0
        out["lo"] = a["hi"] >> np.uint64(k - 64)
    else:
        out["lo"] = (a["lo"] >> np.uint64(k)) | (a["hi"] << np.uint64(64 - k))
        out["hi"] = a["hi"] >> np.uint64(k)
    return out


def u128_lshift(a: np.ndarray, k: int) -> np.ndarray:
    out = np.empty(a.shape, dtype=U128)
    if k == 0:
        out["hi"], out["lo"] = a["hi"], a["lo"]
    elif k >= 128:
        out["hi"] = 0
        out["lo"] = 0
    elif k >= 64:
        out["hi"] = a["lo"] << np.uint64(k - 64)
        out["lo"] = 0
    else:
        out["hi"] = (a["hi"] << np.uint64(k)) | (a["lo"] >> np.uint64(64 - k))
        out["lo"] = a["lo"] << np.uint64(k)
    return out


def u128_add_u64(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """U128[N] + uint64[N] (element-wise, mod 2^128)."""
    out = np.empty(a.shape, dtype=U128)
    b = np.asarray(b, dtype=np.uint64)
    out["lo"] = a["lo"] + b
    out["hi"] = a["hi"] + (out["lo"] < b)
    return out


def u128_and_low(a: np.ndarray, k: int) -> np.ndarray:
    """a & ((1 << k) - 1) as uint64 (requires k <= 64)."""
    assert k <= 64, k
    if k == 64:
        return a["lo"].copy()
    return a["lo"] & np.uint64((1 << k) - 1)


def u128_to_limb_rows(a: np.ndarray) -> np.ndarray:
    """U128[N] -> uint32[N, 4] little-endian limb rows (the AES layout)."""
    out = np.empty((a.shape[0], 4), dtype=np.uint32)
    out[:, 0] = (a["lo"] & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    out[:, 1] = (a["lo"] >> np.uint64(32)).astype(np.uint32)
    out[:, 2] = (a["hi"] & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    out[:, 3] = (a["hi"] >> np.uint64(32)).astype(np.uint32)
    return out


def u128_gt(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Element-wise a > b for U128 arrays (numpy has no void-dtype ufunc
    loop for ordered comparisons; ==/!=, sort, unique and searchsorted all
    work natively on the structured dtype)."""
    return (a["hi"] > b["hi"]) | ((a["hi"] == b["hi"]) & (a["lo"] > b["lo"]))


def u128_searchsorted(haystack: np.ndarray, needles: np.ndarray) -> np.ndarray:
    """np.searchsorted(haystack, needles, 'left') for U128 arrays.

    numpy's structured-dtype searchsorted goes through python-level void
    comparisons (~20x slower than a uint64 search — it dominated the 2^128
    hierarchical profile). This splits into two uint64 phases: binary search
    on the hi words, then rank-by-lo within each equal-hi run, vectorized
    either as a bounded scan (short runs: the deep-level case, runs of 1-2)
    or per-run uint64 searchsorted (few long runs: the just-past-64-bit
    case). Both arrays must be sorted; needles additionally sorted so runs
    are contiguous.
    """
    n_hay, n_needle = haystack.shape[0], needles.shape[0]
    out = np.zeros(n_needle, dtype=np.int64)
    if n_hay == 0 or n_needle == 0:
        return out
    hay_hi, hay_lo = haystack["hi"], haystack["lo"]
    ndl_hi, ndl_lo = needles["hi"], needles["lo"]
    if not hay_hi.any() and not ndl_hi.any():
        return np.searchsorted(hay_lo, ndl_lo).astype(np.int64)
    left = np.searchsorted(hay_hi, ndl_hi, "left").astype(np.int64)
    right = np.searchsorted(hay_hi, ndl_hi, "right").astype(np.int64)
    # Rank-by-lo within each equal-hi run: one vectorized binary search per
    # needle over its own [left, right) window — ceil(log2(max run)) passes
    # over the whole needle array, no per-run Python loops in any regime.
    lo_b, hi_b = left, right.copy()
    while True:
        active = lo_b < hi_b
        if not active.any():
            break
        mid = (lo_b + hi_b) >> 1
        at = np.minimum(mid, n_hay - 1)
        go_right = active & (hay_lo[at] < ndl_lo)
        lo_b = np.where(go_right, mid + 1, lo_b)
        hi_b = np.where(active & ~go_right, mid, hi_b)
    return lo_b
