"""The DPF output value-type system (host side).

Re-implements the semantics of the reference's ValueTypeHelper trait family
(/root/reference/dpf/internal/value_type_helpers.h:42-651, .cc:60-130) as a
small class hierarchy:

* ``Int(bitsize)``       — unsigned integer mod 2^bitsize (additive group)
* ``XorWrapper(bitsize)``— same bits, but the group operation is XOR
* ``IntModN(base_bitsize, modulus)`` — Z_N with statistical sampling
* ``TupleType(e_0, ..., e_k)`` — product group, elementwise ops

Host values are plain Python ``int``s (for the three scalar types) and tuples
of those (for ``TupleType``). All byte conversions are little-endian to stay
byte-compatible with the reference (x86 memory layout of absl::uint128).

Device-side lowering of these types lives in ops/value_codec.py; this module
is the source of truth for bit layouts, sampling semantics, and the host
value-correction computation used during key generation.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Tuple as PyTuple

from ..utils.errors import InvalidArgumentError, UnimplementedError

# Statistical-security accounting for IntModN sampling, mirroring
# /root/reference/dpf/int_mod_n.cc:21-76.


def int_mod_n_security_level(num_samples: int, modulus: int) -> float:
    return 128 + 3 - (
        math.log2(modulus) + math.log2(num_samples) + math.log2(num_samples + 1)
    )


def int_mod_n_num_bytes_required(
    num_samples: int, base_integer_bitsize: int, modulus: int, security_parameter: float
) -> int:
    if num_samples <= 0:
        raise InvalidArgumentError("num_samples must be positive")
    if base_integer_bitsize <= 0:
        raise InvalidArgumentError("base_integer_bitsize must be positive")
    if base_integer_bitsize > 128:
        raise InvalidArgumentError("base_integer_bitsize must be at most 128")
    if base_integer_bitsize < 128 and (1 << base_integer_bitsize) < modulus:
        raise InvalidArgumentError(
            f"kModulus {modulus} out of range for base_integer_bitsize = "
            f"{base_integer_bitsize}"
        )
    sigma = int_mod_n_security_level(num_samples, modulus)
    if security_parameter > sigma:
        raise InvalidArgumentError(
            f"For num_samples = {num_samples} and kModulus = {modulus} this "
            f"approach can only provide {sigma:f} bits of statistical security."
        )
    base_integer_bytes = (base_integer_bitsize + 7) // 8
    # Sampling starts from one full 128-bit block; see SampleFromBytes.
    return 16 + base_integer_bytes * (num_samples - 1)


class ValueType:
    """Base class. Subclasses implement layout, sampling, and group ops."""

    # --- structural properties -------------------------------------------

    def can_convert_directly(self) -> bool:
        raise NotImplementedError

    def total_bit_size(self) -> int:
        """Bit size for directly-convertible types."""
        raise NotImplementedError

    def elements_per_block(self) -> int:
        """How many values of this type pack into one 128-bit block.

        Mirrors dpf_internal::ElementsPerBlock
        (/root/reference/dpf/internal/value_type_helpers.h:506-520).
        """
        if self.can_convert_directly() and self.total_bit_size() <= 128:
            return 128 // self.total_bit_size()
        return 1

    def bits_needed(self, security_parameter: float) -> int:
        """Pseudorandom bits needed for one uniform element.

        Mirrors dpf_internal::BitsNeeded
        (/root/reference/dpf/internal/value_type_helpers.cc:60-130).
        """
        raise NotImplementedError

    def validate(self) -> None:
        """Raises InvalidArgumentError if the type itself is malformed."""
        raise NotImplementedError

    def canonical(self) -> tuple:
        """Hashable canonical form; the registry key (host equivalent of the
        reference's deterministic ValueType serialization)."""
        raise NotImplementedError

    # --- value handling ---------------------------------------------------

    def validate_value(self, value) -> None:
        raise NotImplementedError

    def zero(self):
        raise NotImplementedError

    def add(self, a, b):
        raise NotImplementedError

    def sub(self, a, b):
        raise NotImplementedError

    def neg(self, a):
        return self.sub(self.zero(), a)

    # --- byte conversions -------------------------------------------------

    def directly_from_bytes(self, data: bytes):
        raise NotImplementedError

    def sample_and_update(self, update: bool, block: int, remaining: bytes):
        """Returns (value, new_block, new_remaining).

        Mirrors ValueTypeHelper<T>::SampleAndUpdateBytes.
        """
        raise NotImplementedError

    def from_bytes(self, data: bytes):
        """Value from a pseudorandom byte string (direct or sampled).

        Mirrors dpf_internal::FromBytes
        (/root/reference/dpf/internal/value_type_helpers.h:526-538).
        """
        if self.can_convert_directly():
            return self.directly_from_bytes(data)
        block = int.from_bytes(data[:16], "little")
        value, _, _ = self.sample_and_update(False, block, data[16:])
        return value

    def bytes_to_block_values(self, data: bytes) -> list:
        """One 128-bit block's worth of bytes -> elements_per_block values.

        Mirrors dpf_internal::ConvertBytesToArrayOf
        (/root/reference/dpf/internal/value_type_helpers.h:569-589).
        """
        n = self.elements_per_block()
        if not self.can_convert_directly():
            return [self.from_bytes(data)]
        size = (self.total_bit_size() + 7) // 8
        return [self.directly_from_bytes(data[i * size : (i + 1) * size]) for i in range(n)]

    def __eq__(self, other):
        return isinstance(other, ValueType) and self.canonical() == other.canonical()

    def __hash__(self):
        return hash(self.canonical())

    def __repr__(self):
        return str(self.canonical())


def _check_bitsize(bitsize: int) -> None:
    # Mirrors ValidateIntegerType (/root/reference/dpf/internal/proto_validator.cc:58-71).
    # Additionally requires bitsize >= 8: the reference only registers value
    # correction for uint8..uint128 (distributed_point_function.cc:597-610), so
    # sub-byte types can never produce keys there either; accepting them here
    # would break the byte-granular block layout (and with it privacy).
    if bitsize < 1:
        raise InvalidArgumentError("`bitsize` must be positive")
    if bitsize > 128:
        raise InvalidArgumentError("`bitsize` must be less than or equal to 128")
    if bitsize & (bitsize - 1):
        raise InvalidArgumentError("`bitsize` must be a power of 2")
    if bitsize < 8:
        raise InvalidArgumentError("`bitsize` must be at least 8")


@dataclasses.dataclass(frozen=True, eq=False)
class Int(ValueType):
    """Unsigned integer mod 2^bitsize; bitsize in {8,16,32,64,128}."""

    bitsize: int

    def can_convert_directly(self):
        return True

    def total_bit_size(self):
        return self.bitsize

    def bits_needed(self, security_parameter):
        return self.bitsize

    def validate(self):
        _check_bitsize(self.bitsize)

    def canonical(self):
        return ("int", self.bitsize)

    @property
    def _mask(self):
        return (1 << self.bitsize) - 1

    def validate_value(self, value):
        if not isinstance(value, int) or value < 0:
            raise InvalidArgumentError("Expected non-negative integer value")
        if value > self._mask:
            raise InvalidArgumentError(
                f"Value (= {value}) too large for ValueType with bitsize = {self.bitsize}"
            )

    def zero(self):
        return 0

    def add(self, a, b):
        return (a + b) & self._mask

    def sub(self, a, b):
        return (a - b) & self._mask

    def directly_from_bytes(self, data):
        size = (self.bitsize + 7) // 8
        return int.from_bytes(data[:size], "little")

    def sample_and_update(self, update, block, remaining):
        result = block & self._mask
        if update:
            size = self.bitsize // 8
            block &= ~self._mask
            block |= int.from_bytes(remaining[:size], "little")
            remaining = remaining[size:]
        return result, block, remaining


@dataclasses.dataclass(frozen=True, eq=False)
class XorWrapper(ValueType):
    """Group where +/- are bitwise XOR (e.g. XOR-shared PIR outputs)."""

    bitsize: int

    def can_convert_directly(self):
        return True

    def total_bit_size(self):
        return self.bitsize

    def bits_needed(self, security_parameter):
        return self.bitsize

    def validate(self):
        _check_bitsize(self.bitsize)

    def canonical(self):
        return ("xor", self.bitsize)

    def validate_value(self, value):
        Int(self.bitsize).validate_value(value)

    def zero(self):
        return 0

    def add(self, a, b):
        return a ^ b

    def sub(self, a, b):
        return a ^ b

    def neg(self, a):
        return a

    def directly_from_bytes(self, data):
        size = (self.bitsize + 7) // 8
        return int.from_bytes(data[:size], "little")

    def sample_and_update(self, update, block, remaining):
        return Int(self.bitsize).sample_and_update(update, block, remaining)


@dataclasses.dataclass(frozen=True, eq=False)
class IntModN(ValueType):
    """Z_modulus over a base integer of base_bitsize bits.

    Sampling follows IntModNImpl::UnsafeSampleFromBytes
    (/root/reference/dpf/int_mod_n.h:154-177): take the running 128-bit block
    mod N; to refill, divide the block by N, shift left by the base integer
    size and OR in fresh bytes.
    """

    base_bitsize: int
    modulus: int

    def can_convert_directly(self):
        return False

    def bits_needed(self, security_parameter):
        return 8 * int_mod_n_num_bytes_required(
            1, self.base_bitsize, self.modulus, security_parameter
        )

    def validate(self):
        _check_bitsize(self.base_bitsize)
        if self.modulus < 1:
            raise InvalidArgumentError("modulus must be positive")
        if self.base_bitsize < 128 and self.modulus > (1 << self.base_bitsize):
            raise InvalidArgumentError(
                f"Value (= {self.modulus}) too large for ValueType with bitsize = "
                f"{self.base_bitsize}"
            )

    def canonical(self):
        return ("modn", self.base_bitsize, self.modulus)

    def validate_value(self, value):
        if not isinstance(value, int) or value < 0:
            raise InvalidArgumentError("Expected non-negative integer value")
        if value >= self.modulus:
            raise InvalidArgumentError(
                f"Value (= {value}) is too large for modulus (= {self.modulus})"
            )

    def zero(self):
        return 0

    def add(self, a, b):
        return (a + b) % self.modulus

    def sub(self, a, b):
        return (a - b) % self.modulus

    def sample_and_update(self, update, block, remaining):
        quotient, remainder = divmod(block, self.modulus)
        result = remainder
        if update:
            size = self.base_bitsize // 8
            if self.base_bitsize < 128:
                block = (quotient << self.base_bitsize) & ((1 << 128) - 1)
            else:
                block = 0
            block |= int.from_bytes(remaining[:size], "little")
            remaining = remaining[size:]
        return result, block, remaining


@dataclasses.dataclass(frozen=True, eq=False, init=False)
class TupleType(ValueType):
    """Product of up to arbitrary element types; elementwise group ops."""

    elements: PyTuple[ValueType, ...]

    def __init__(self, *elements: ValueType):
        if len(elements) == 1 and isinstance(elements[0], (tuple, list)):
            elements = tuple(elements[0])
        object.__setattr__(self, "elements", tuple(elements))

    def can_convert_directly(self):
        return all(e.can_convert_directly() for e in self.elements)

    def total_bit_size(self):
        return sum(e.total_bit_size() for e in self.elements)

    def bits_needed(self, security_parameter):
        # Mirrors BitsNeeded for tuples
        # (/root/reference/dpf/internal/value_type_helpers.cc:64-115),
        # including its quirk of iterating over the *first* `num_other`
        # elements when computing the non-IntModN contribution.
        int_mod_n_elements = [e for e in self.elements if isinstance(e, IntModN)]
        num_mod_n = len(int_mod_n_elements)
        num_other = len(self.elements) - num_mod_n
        if num_mod_n > 1:
            first = int_mod_n_elements[0]
            if any(e != first for e in int_mod_n_elements):
                raise UnimplementedError(
                    "All elements of type IntModN in a tuple must be the same"
                )
        bits_other = 0
        if num_other > 0:
            per_element_sp = security_parameter + math.log2(num_other)
            for i in range(num_other):
                bits_other += self.elements[i].bits_needed(per_element_sp)
        bits_mod_n = 0
        if num_mod_n > 0:
            first = int_mod_n_elements[0]
            bits_mod_n = 8 * int_mod_n_num_bytes_required(
                num_mod_n, first.base_bitsize, first.modulus, security_parameter
            )
        return bits_mod_n + bits_other

    def validate(self):
        for e in self.elements:
            e.validate()

    def canonical(self):
        return ("tuple",) + tuple(e.canonical() for e in self.elements)

    def validate_value(self, value):
        if not isinstance(value, tuple):
            raise InvalidArgumentError("Expected tuple value")
        if len(value) != len(self.elements):
            raise InvalidArgumentError(
                f"Expected tuple value of size {len(self.elements)} but got size "
                f"{len(value)}"
            )
        for v, e in zip(value, self.elements):
            e.validate_value(v)

    def zero(self):
        return tuple(e.zero() for e in self.elements)

    def add(self, a, b):
        return tuple(e.add(x, y) for e, x, y in zip(self.elements, a, b))

    def sub(self, a, b):
        return tuple(e.sub(x, y) for e, x, y in zip(self.elements, a, b))

    def neg(self, a):
        return tuple(e.neg(x) for e, x in zip(self.elements, a))

    def directly_from_bytes(self, data):
        out = []
        offset = 0
        for e in self.elements:
            size = (e.total_bit_size() + 7) // 8
            out.append(e.directly_from_bytes(data[offset : offset + size]))
            offset += size
        return tuple(out)

    def sample_and_update(self, update, block, remaining):
        out = []
        n = len(self.elements)
        for i, e in enumerate(self.elements):
            # Update after every element except (when update=False) the last.
            update_i = update or (i + 1 < n)
            value, block, remaining = e.sample_and_update(update_i, block, remaining)
            out.append(value)
        return tuple(out), block, remaining


def compute_value_correction(
    value_type: ValueType,
    seed_a: bytes,
    seed_b: bytes,
    block_index: int,
    beta,
    invert: bool,
) -> list:
    """Value-correction words so party shares sum to beta at block_index.

    Mirrors dpf_internal::ComputeValueCorrectionFor
    (/root/reference/dpf/internal/value_type_helpers.h:597-631). Returns
    elements_per_block host values.
    """
    ints_a = value_type.bytes_to_block_values(seed_a)
    ints_b = value_type.bytes_to_block_values(seed_b)
    ints_b[block_index] = value_type.add(ints_b[block_index], beta)
    out = []
    for a, b in zip(ints_a, ints_b):
        c = value_type.sub(b, a)
        if invert:
            c = value_type.neg(c)
        out.append(c)
    return out
