"""Fused batched DCF evaluation on device.

The reference evaluates a DCF by calling EvaluateAt once per domain bit, each
call re-walking the tree from the root — O(n^2) AES per point
(/root/reference/dcf/distributed_comparison_function.h:83-107; noted in
SURVEY.md §3.4). This kernel makes the pass O(n): ONE ``lax.scan`` walks the
point's root-to-leaf path, and at every output depth captures the current
seed, value-hashes it, selects the addressed block element, applies that
hierarchy level's value correction, and mask-accumulates it iff the point's
bit at that level is 0. vmapped over keys; evaluation points are shared
across the key batch.

Depth bookkeeping (hierarchy level i -> tree depth t_i = hierarchy_to_tree[i])
follows the incremental-DPF packing rules (core/params.py); depths that carry
no output level get a zero accumulate mask and their hash is wasted work —
at most a few early levels.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import uint128
from ..ops import aes_jax, backend_jax, evaluator
from ..utils import errors, faultinject, integrity
from ..utils import telemetry as _tm


def _capture_tables(dcf, xs_padded: np.ndarray, num_points: int):
    """Host precompute of per-depth capture parameters.

    Returns (acc_mask float? no — uint32[T+1, P], block_sel int32[T+1, P],
    depth_to_hierarchy list[T+1] with -1 for no capture).
    """
    v = dcf.dpf.validator
    n = dcf.log_domain_size
    T = v.hierarchy_to_tree[v.num_hierarchy_levels - 1]
    p_pad = xs_padded.shape[0]
    acc_mask = np.zeros((T + 1, p_pad), dtype=np.uint32)
    block_sel = np.zeros((T + 1, p_pad), dtype=np.int32)
    depth_to_hierarchy = [-1] * (T + 1)
    for i in range(v.num_hierarchy_levels):
        d = v.hierarchy_to_tree[i]
        depth_to_hierarchy[d] = i
        bits_d = i - d  # block-index bits at this level
        for j in range(num_points):
            x = int(xs_padded[j])
            prefix = x >> (n - i)
            block_sel[d, j] = prefix & ((1 << bits_d) - 1)
            bit = (x >> (n - 1 - i)) & 1
            acc_mask[d, j] = 0 if bit else 1
    return acc_mask, block_sel, depth_to_hierarchy


def _value_corrections_all(dcf, keys, depth_to_hierarchy, n_elems=1) -> np.ndarray:
    """uint32[K, T+1, E, 4]: per-key value-correction limbs by tree depth.

    E = elements_per_block for scalar payloads; for uniform tuple payloads
    (`n_elems` > 1) each level's single tuple correction flattens to its
    n_elems member limbs — row e carries element e, matching slot e % epb
    of value-hash block e // epb in the packed capture stream (the
    `_correction_limbs` pass downstream slices each row to the element
    width's limbs)."""
    v = dcf.dpf.validator
    epb = dcf.value_type.elements_per_block()
    k = len(keys)
    T = len(depth_to_hierarchy) - 1
    rows = n_elems if n_elems > 1 else epb
    vc = np.zeros((k, T + 1, rows, 4), dtype=np.uint32)
    for ki, key in enumerate(keys):
        dpf_key = key.key
        for d, i in enumerate(depth_to_hierarchy):
            if i < 0:
                continue
            if i == v.num_hierarchy_levels - 1:
                corrections = dpf_key.last_level_value_correction
            else:
                corrections = dpf_key.correction_words[d].value_correction
            for j, c in enumerate(corrections):
                if isinstance(c, tuple):
                    for e, ce in enumerate(c):
                        vc[ki, d, e] = uint128.to_limbs(int(ce))
                else:
                    vc[ki, d, j] = uint128.to_limbs(int(c))
    return vc


def _capture(
    planes, control, vc_d, block_sel_d, acc_mask_d, bits, xor_group, n_elems=1
):
    """Hash + select + correct + mask one depth; returns [P_pad, lpe]
    (scalar) or [P_pad, n_elems, lpe] (uniform tuple payload).

    Tuple payloads pack densely into ceil(n_elems * bits / 128) value-hash
    blocks (every tree depth is a hierarchy level, block index always 0):
    the capture widens to the hash(seed + j) stream, splits each block into
    its 128 // bits elements, and keeps the first n_elems — only the tail
    grows, the walk above is untouched."""
    ctrl = backend_jax.unpack_mask_device(control)  # uint32[P_pad] 0/1
    if n_elems > 1:
        # ONE lane-concatenated AES pass hashes seed+j for every block
        # (separate hash calls would put nb full AES graphs in the scan
        # body and explode XLA compile time).
        nb = -(-(n_elems * bits) // 128)
        seeds = aes_jax.unpack_from_planes(planes)
        p_pad = seeds.shape[0]
        blocks = backend_jax._hash_expanded_blocks_jit(seeds, nb)
        sel = blocks.transpose(1, 0, 2)  # [P_pad, nb, 4]
        elems = evaluator._split_elements(sel, bits)  # [P_pad, nb, epb, lpe]
        lpe = elems.shape[-1]
        sel = elems.reshape(p_pad, -1, lpe)[:, :n_elems]
        gated = vc_d[None, :, :] * ctrl[:, None, None]
        if xor_group:
            value = sel ^ gated
        else:
            value = evaluator._limb_add(sel, gated, bits)
        return value * acc_mask_d[:, None, None]
    hashed = backend_jax.hash_value_planes(planes)
    blocks = aes_jax.unpack_from_planes(hashed)
    elems = evaluator._split_elements(blocks, bits)  # [P_pad, epb, lpe]
    p_pad = elems.shape[0]
    sel = elems[jnp.arange(p_pad), block_sel_d]  # [P_pad, lpe]
    corr = vc_d[block_sel_d]  # [P_pad, lpe]
    gated = corr * ctrl[:, None]
    if xor_group:
        value = sel ^ gated
        return value * acc_mask_d[:, None]  # mask: 0 or 1
    value = evaluator._limb_add(sel, gated, bits)
    return value * acc_mask_d[:, None]


def _accumulate(acc, value, bits, xor_group):
    if xor_group:
        return acc ^ value
    return evaluator._limb_add(acc, value, bits)


def _dcf_walk_one_key(
    seeds,  # uint32[P_pad, 4] root seed broadcast
    control,  # uint32[W]
    path_masks,  # uint32[T, W]
    cw_planes,  # uint32[T, 128]
    ccl,  # uint32[T]
    ccr,  # uint32[T]
    vc,  # uint32[T+1, epb, lpe] / uint32[T+1, n_elems, lpe] for tuples
    block_sel,  # int32[T+1, P_pad]
    acc_mask,  # uint32[T+1, P_pad]
    bits: int,
    party: int,
    xor_group: bool,
    n_elems: int = 1,
):
    rk_left = backend_jax._rk("left")
    rk_diff = backend_jax._rk("lr_diff")
    planes = aes_jax.pack_to_planes(seeds)
    p_pad = seeds.shape[0]
    lpe = vc.shape[-1]
    if n_elems > 1:
        acc0 = jnp.zeros((p_pad, n_elems, lpe), dtype=jnp.uint32)
    else:
        acc0 = jnp.zeros((p_pad, lpe), dtype=jnp.uint32)

    def body(carry, xs):
        planes, control, acc = carry
        path_mask, cw, l, r, vc_d, bs_d, am_d = xs
        value = _capture(
            planes, control, vc_d, bs_d, am_d, bits, xor_group, n_elems
        )
        acc = _accumulate(acc, value, bits, xor_group)
        h = aes_jax.hash_planes(planes, rk_left, rk_diff, path_mask)
        h = h ^ (cw[:, None] & control[None, :])
        new_control = h[0]
        h = h.at[0].set(jnp.zeros_like(h[0]))
        cc = (l & ~path_mask) | (r & path_mask)
        return (h, new_control ^ (control & cc), acc), None

    (planes, control, acc), _ = jax.lax.scan(
        body,
        (planes, control, acc0),
        (path_masks, cw_planes, ccl, ccr, vc[:-1], block_sel[:-1], acc_mask[:-1]),
    )
    value = _capture(
        planes, control, vc[-1], block_sel[-1], acc_mask[-1], bits, xor_group,
        n_elems,
    )
    acc = _accumulate(acc, value, bits, xor_group)
    if party == 1 and not xor_group:
        acc = evaluator._limb_neg(acc, bits)
    return acc


@functools.partial(
    jax.jit, static_argnames=("bits", "party", "xor_group", "n_elems")
)
def _dcf_batch_jit(
    seeds, control, path_masks, cw_planes, ccl, ccr, vc, block_sel, acc_mask,
    bits, party, xor_group, n_elems=1,
):
    fn = functools.partial(
        _dcf_walk_one_key, bits=bits, party=party, xor_group=xor_group,
        n_elems=n_elems,
    )
    return jax.vmap(fn, in_axes=(0, None, None, 0, 0, 0, 0, None, None))(
        seeds, control, path_masks, cw_planes, ccl, ccr, vc, block_sel, acc_mask
    )


def _hash_blocks_batched(planes, use_pallas, interpret):
    """uint32[K, 128, W] packed seeds -> uint32[K, P_pad, 4] value hashes."""
    if use_pallas and planes.shape[2] >= 256:
        from ..ops import aes_pallas

        hashed = aes_pallas.hash_value_planes_pallas_batched(
            planes, interpret=interpret
        )
    else:
        hashed = jax.vmap(backend_jax.hash_value_planes)(planes)
    return jax.vmap(aes_jax.unpack_from_planes)(hashed)


def _capture_batched(
    planes,  # uint32[K, 128, W]
    ctrl,  # uint32[K, W]
    vc_d,  # uint32[K, epb, lpe] / uint32[K, n_elems, lpe] for tuples
    block_sel_d,  # int32[P_pad] (shared across keys)
    acc_mask_d,  # uint32[P_pad]
    bits: int,
    xor_group: bool,
    use_pallas: bool,
    interpret: bool,
    n_elems: int = 1,
):
    """Key-batched `_capture`: hash + select + correct + mask one depth."""
    ctrlb = jax.vmap(backend_jax.unpack_mask_device)(ctrl)  # [K, P_pad]
    if n_elems > 1:
        # Tuple payload: elements pack densely into nb value-hash blocks
        # (hash(seed + j), j < nb). All blocks' inputs concatenate along
        # the lane axis into ONE hash program per depth — same Mosaic
        # kernel family, wider W.
        nb = -(-(n_elems * bits) // 128)
        seeds = jax.vmap(aes_jax.unpack_from_planes)(planes)  # [K, P_pad, 4]
        k, p_pad = seeds.shape[0], seeds.shape[1]
        flat = seeds.reshape(k * p_pad, 4)
        inputs = jnp.concatenate(
            [
                seeds
                if j == 0
                else backend_jax._add_small_constant(
                    flat, np.uint32(j)
                ).reshape(k, p_pad, 4)
                for j in range(nb)
            ],
            axis=1,
        )  # [K, nb * P_pad, 4]
        big = jax.vmap(aes_jax.pack_to_planes)(inputs)
        blocks = _hash_blocks_batched(big, use_pallas, interpret)
        sel = blocks.reshape(k, nb, p_pad, 4).transpose(0, 2, 1, 3)
        elems = evaluator._split_elements(sel, bits)  # [K, P, nb, epb, lpe]
        lpe = elems.shape[-1]
        sel = elems.reshape(k, p_pad, -1, lpe)[:, :, :n_elems]
        gated = vc_d[:, None] * ctrlb[..., None, None]
        if xor_group:
            value = sel ^ gated
        else:
            value = evaluator._limb_add(sel, gated, bits)
        return value * acc_mask_d[None, :, None, None]
    blocks = _hash_blocks_batched(planes, use_pallas, interpret)  # [K, P_pad, 4]
    elems = evaluator._split_elements(blocks, bits)  # [K, P_pad, epb, lpe]
    p_pad = elems.shape[1]
    sel = elems[:, jnp.arange(p_pad), block_sel_d]  # [K, P_pad, lpe]
    corr = vc_d[:, block_sel_d]  # [K, P_pad, lpe]
    gated = corr * ctrlb[..., None]
    if xor_group:
        value = sel ^ gated
    else:
        value = evaluator._limb_add(sel, gated, bits)
    return value * acc_mask_d[None, :, None]


def _dcf_key_tile(k: int, p_pad: int) -> int:
    """Key tile for the Mosaic walk: DCF point batches are often narrow
    (W = P/32 lane words), so tile enough keys together to fill the
    (8, 128) vregs — bounded by the key count itself. Prefers a divisor
    of k (the walk zero-pads k up to a tile multiple and walks the dead
    keys at every level; a large-enough exact divisor keeps the vregs
    filled with zero padding — r3 review)."""
    w = max(1, p_pad // 32)
    cap = max(1, min(k, max(8, min(64, 1024 // w))))
    for t in range(cap, 0, -1):
        if k % t == 0:
            if t >= max(1, cap // 2):
                return t
            break  # only tiny divisors exist; bounded padding beats them
    return cap


@functools.partial(
    jax.jit,
    static_argnames=(
        "bits", "party", "xor_group", "key_tile", "interpret", "n_elems"
    ),
)
def _dcf_batch_pallas_jit(
    seeds,  # uint32[K, P_pad, 4] root seed broadcast
    control_mask,  # uint32[W] (shared initial control)
    path_masks,  # uint32[T, W]
    cw_planes,  # uint32[K, T, 128]
    ccl,  # uint32[K, T]
    ccr,  # uint32[K, T]
    vc,  # uint32[K, T+1, epb, lpe] / uint32[K, T+1, n_elems, lpe]
    block_sel,  # int32[T+1, P_pad]
    acc_mask,  # uint32[T+1, P_pad]
    bits: int,
    party: int,
    xor_group: bool,
    key_tile: int,
    interpret: bool = False,
    n_elems: int = 1,
):
    """Mosaic-kernel variant of `_dcf_batch_jit`: the same O(n) fused walk,
    but each tree level runs the batched Pallas walk kernel
    (aes_pallas.walk_levels_pallas_batched, one level per call) with the
    per-depth capture (value hash + block select + correction +
    mask-accumulate) interleaved between levels — the structure
    `evaluate_at_batch` uses, extended with the DCF's per-level consumer.
    Covers BASELINE config 4 (dcf/distributed_comparison_function_benchmark.cc:24-54)
    on the device path."""
    from ..ops import aes_pallas

    planes = jax.vmap(aes_jax.pack_to_planes)(seeds)  # [K, 128, W]
    k = planes.shape[0]
    ctrl = jnp.broadcast_to(control_mask[None], (k,) + control_mask.shape)
    T = path_masks.shape[0]
    lpe = vc.shape[-1]
    p_pad = block_sel.shape[1]
    if n_elems > 1:
        acc = jnp.zeros((k, p_pad, n_elems, lpe), jnp.uint32)
    else:
        acc = jnp.zeros((k, p_pad, lpe), jnp.uint32)
    for d in range(T + 1):
        value = _capture_batched(
            planes, ctrl, vc[:, d], block_sel[d], acc_mask[d],
            bits, xor_group, use_pallas=True, interpret=interpret,
            n_elems=n_elems,
        )
        acc = _accumulate(acc, value, bits, xor_group)
        if d < T:
            planes, ctrl = aes_pallas.walk_levels_pallas_batched(
                planes, ctrl,
                path_masks[d : d + 1],
                cw_planes[:, d : d + 1],
                ccl[:, d : d + 1],
                ccr[:, d : d + 1],
                key_tile=key_tile,
                interpret=interpret,
            )
    if party == 1 and not xor_group:
        acc = evaluator._limb_neg(acc, bits)
    return acc


def _prep_points(dcf, keys: Sequence, xs: Sequence[int], p_pad: int):
    """Shared host precompute for the batched evaluators: point validation,
    correction-word batch, per-point tree paths, capture tables."""
    v = dcf.dpf.validator
    n = dcf.log_domain_size
    num_points = len(xs)
    for x in xs:
        if x < 0 or (n < 128 and int(x) >= (1 << n)):
            raise errors.InvalidArgumentError(
                f"evaluation point {x} outside the domain"
            )
    batch = evaluator.KeyBatch.from_keys(dcf.dpf, [k.key for k in keys])
    xs_padded = np.zeros(p_pad, dtype=object)
    for j, x in enumerate(xs):
        xs_padded[j] = int(x)
    # Tree path of each point: the final hierarchy level's tree index.
    last = v.num_hierarchy_levels - 1
    paths = uint128.array_to_limbs(
        [v.domain_to_tree_index(int(x) >> 1, last) for x in xs_padded]
    )
    acc_mask, block_sel, depth_to_hierarchy = _capture_tables(
        dcf, xs_padded, num_points
    )
    return batch, paths, acc_mask, block_sel, depth_to_hierarchy


@_tm.traced("dcf.batch_evaluate")
def batch_evaluate(
    dcf, keys: Sequence, xs: Sequence[int], use_pallas=None, interpret=False,
    key_chunk=None, pipeline=None, mode=None,
) -> np.ndarray:
    """Evaluates every DCF key at every point x. Returns uint32[K, P, lpe]
    for scalar value types, uint32[K, P, n_elems, 4] for uniform tuple
    payloads (the vector gate codec: elements pack densely into value-hash
    blocks of the same seed, so only the capture tail widens — walk work is
    unchanged; narrow elements accumulate at their own limb width and
    zero-pad to 4 limbs on the way out).

    `use_pallas` (default: on for real TPU backends, see
    evaluator._pallas_default) routes the per-level tree walk through the
    batched Mosaic kernels instead of the XLA bitslice scan.

    `key_chunk` (None = the whole key batch in ONE program, the historical
    shape) splits the key axis into chunks driven through the pipelined
    executor (ops/pipeline.py, `pipeline` = None for the DPF_TPU_PIPELINE
    env / platform default): chunk N+1's per-key table upload and dispatch
    overlap chunk N's walk program and chunk N-1's D2H pull.

    `mode` selects the walk strategy (None = "walkkernel" when the
    DPF_TPU_WALKKERNEL env is truthy, else "walk"). "walk" is the shipped
    shape above (XLA scan or per-level Mosaic walk per `use_pallas`).
    "walkkernel" runs the walk megakernel
    (aes_pallas.walk_megakernel_pallas_batched): ONE pallas_call per key
    chunk walking all T tree levels in-register, with every depth's value
    capture — hash, block-element select, correction, accumulate-iff-bit-0
    mask, and the additive/XOR accumulation itself (party 1 negated once
    at the end) — executed in-kernel; only the [K, P, lpe] result leaves
    the device program. Scalar 32-bit-multiple widths only (an explicit
    mode="walkkernel" on sub-word values raises; the env default quietly
    keeps "walk"); off-TPU it runs through the Pallas interpreter."""
    from ..ops import pipeline as _pl

    bits, xor_group, n_elems = evaluator._payload_kind(dcf.value_type)
    num_points = len(xs)
    k = len(keys)

    v = dcf.dpf.validator
    mode = evaluator._resolve_walk_mode(
        mode, n_elems == 1, bits,
        v.hierarchy_to_tree[v.num_hierarchy_levels - 1],
        use_pallas,
        op="dcf.batch_evaluate",
    )
    if mode == "walkkernel":
        return _batch_evaluate_walkkernel(
            dcf, keys, xs, bits, xor_group,
            key_chunk=key_chunk, pipeline=pipeline, interpret=interpret,
        )

    p_pad = max(32, -(-num_points // 32) * 32)
    batch, paths, acc_mask, block_sel, depth_to_hierarchy = _prep_points(
        dcf, keys, xs, p_pad
    )
    T = batch.num_levels
    path_masks = backend_jax._path_bit_masks(paths, T, p_pad)
    vc_full = _value_corrections_all(dcf, keys, depth_to_hierarchy, n_elems)
    vc = np.ascontiguousarray(
        evaluator._correction_limbs(
            vc_full.reshape(k * (T + 1), -1, 4), bits
        ).reshape(k, T + 1, -1, max(bits // 32, 1))
    )

    control0 = aes_jax.pack_bit_mask(np.full(p_pad, bool(batch.party), dtype=bool))
    explicit_pallas = use_pallas is True
    if use_pallas is None:
        use_pallas = evaluator._pallas_default()
    if p_pad // 32 < 8 and not interpret and not explicit_pallas:
        # Narrow point batches (< 256 points -> < 8 lane words) would hand
        # the walk kernel near-degenerate blocks; the XLA scan driver is
        # the right engine there (r3 review). Only the platform DEFAULT is
        # downgraded — an explicit use_pallas=True (e.g. CHECK_PALLAS=1
        # verifying the Mosaic driver) must actually run the kernel it
        # claims to verify (ADVICE r3).
        if use_pallas:
            # Structured note (ISSUE 4 satellite): device A/B runs must be
            # able to tell "kernel lost" from "kernel never ran" — a
            # silent downgrade made narrow-batch Pallas A/Bs read as
            # kernel measurements when they were really the XLA scan.
            integrity.emit_event(
                "engine-downgrade",
                f"dcf.batch_evaluate: narrow point batch ({num_points} "
                f"points -> {p_pad // 32} lane words < 8) auto-downgraded "
                "from the Pallas walk to the XLA scan; pass "
                "use_pallas=True to force the kernel",
                "pallas",
                num_points=num_points,
                lane_words=p_pad // 32,
                downgraded_to="jax",
            )
        use_pallas = False

    pipe = _pl.resolve(pipeline)
    fib = evaluator._fi_backend(use_pallas)
    ck = k if key_chunk is None else max(1, key_chunk)
    # Point-shared tables upload once, outside the per-chunk loop.
    path_masks_dev = jnp.asarray(path_masks)
    control0_dev = jnp.asarray(control0)
    block_sel_dev = jnp.asarray(block_sel)
    acc_mask_dev = jnp.asarray(acc_mask)

    def _chunk_thunk(idx, valid):
        # Single chunk covering the whole batch (the historical default):
        # skip the identity fancy-index copies of every per-key table.
        whole = valid == k and idx.shape[0] == k
        kb = batch if whole else batch.take(idx)
        vc_c = vc if whole else vc[idx]
        kk = kb.seeds.shape[0]
        cw_planes, ccl, ccr = kb.device_cw_arrays()
        seeds = np.broadcast_to(kb.seeds[:, None, :], (kk, p_pad, 4)).copy()
        if use_pallas:
            out = _dcf_batch_pallas_jit(
                jnp.asarray(seeds),
                control0_dev,
                path_masks_dev,
                jnp.asarray(cw_planes),
                jnp.asarray(ccl),
                jnp.asarray(ccr),
                jnp.asarray(vc_c),
                block_sel_dev,
                acc_mask_dev,
                bits=bits,
                party=batch.party,
                xor_group=xor_group,
                key_tile=_dcf_key_tile(kk, p_pad),
                interpret=interpret,
                n_elems=n_elems,
            )
        else:
            out = _dcf_batch_jit(
                jnp.asarray(seeds),
                control0_dev,
                path_masks_dev,
                jnp.asarray(cw_planes),
                jnp.asarray(ccl),
                jnp.asarray(ccr),
                jnp.asarray(vc_c),
                block_sel_dev,
                acc_mask_dev,
                bits=bits,
                party=batch.party,
                xor_group=xor_group,
                n_elems=n_elems,
            )
        return valid, out

    pieces = list(
        _pl.map_chunks(
            (
                functools.partial(_chunk_thunk, idx, valid)
                for idx, valid in _pl.chunk_indices(k, ck)
            ),
            lambda item: np.asarray(item[1])[: item[0], :num_points],
            pipe,
            backend=fib,
            op="dcf.batch_evaluate",
        )
    )
    out = pieces[0] if len(pieces) == 1 else np.concatenate(pieces, axis=0)
    if n_elems > 1 and out.shape[-1] < 4:
        # Uniform [K, P, n_elems, 4] contract regardless of element width:
        # narrow elements walked at lpe < 4 limbs zero-extend host-side.
        pad = [(0, 0)] * (out.ndim - 1) + [(0, 4 - out.shape[-1])]
        out = np.pad(np.asarray(out), pad)
    # Output-corruption seam for the runtime integrity layer (ISSUE 7):
    # DCF has no sentinel-probe hook, so the supervisor's host-oracle spot
    # check is what detects device-side corruption — this is where the
    # chaos harness injects it. No-op (one truthiness check) unarmed.
    return faultinject.corrupt_output(out, backend=fib)


def _batch_evaluate_walkkernel(
    dcf, keys: Sequence, xs: Sequence[int], bits: int, xor_group: bool,
    key_chunk=None, pipeline=None, interpret=False,
) -> np.ndarray:
    """mode="walkkernel" body of `batch_evaluate`: one walk-megakernel
    program per key chunk. Host prep mirrors the per-level path, but the
    capture tables become packed per-(depth, element) select bitmasks with
    the accumulate mask pre-ANDed in — in-kernel, block-element selection
    and the "accumulate iff the point's bit is 0" gate are one AND."""
    import jax
    import jax.numpy as jnp

    from ..ops import pipeline as _pl

    num_points = len(xs)
    k = len(keys)
    v = dcf.dpf.validator
    T = v.hierarchy_to_tree[v.num_hierarchy_levels - 1]
    lpe = max(bits // 32, 1)
    epb = dcf.value_type.elements_per_block()
    plan = evaluator.plan_walkkernel(num_points, T, lpe, captures=True)
    p_pad = plan.padded_words * 32
    batch, paths, acc_mask, block_sel, depth_to_hierarchy = _prep_points(
        dcf, keys, xs, p_pad
    )
    path_masks = backend_jax._path_bit_masks(paths, T, p_pad)
    captures = tuple(i >= 0 for i in depth_to_hierarchy)
    vc_full = _value_corrections_all(dcf, keys, depth_to_hierarchy)
    # Correction rows flattened to (depth, element): row d*epb + e.
    vc = np.ascontiguousarray(
        evaluator._correction_limbs(
            vc_full.reshape(k * (T + 1), -1, 4), bits
        ).reshape(k, (T + 1) * epb, lpe)
    )
    # Select bitmask rows: bit j of row d*epb+e = [point j addresses
    # element e at depth d] AND [depth d's accumulate mask] — padded
    # points (and non-capture depths) select nothing and contribute zero.
    sel_bool = np.zeros((T + 1, epb, p_pad), dtype=bool)
    pts = np.arange(num_points)
    for d in range(T + 1):
        if captures[d]:
            sel_bool[d, block_sel[d, :num_points], pts] = acc_mask[
                d, :num_points
            ].astype(bool)
    sel_bits = aes_jax.pack_bit_mask(sel_bool.reshape((T + 1) * epb, p_pad))

    pipe = _pl.resolve(pipeline)
    ck = k if key_chunk is None else max(1, key_chunk)
    pieces = list(
        _pl.map_chunks(
            evaluator._walk_megakernel_thunks(
                batch, k, ck, vc,
                jnp.asarray(path_masks),
                jnp.asarray(sel_bits),
                plan, bits, batch.party, xor_group, epb,
                captures=captures,
                interpret=interpret or jax.default_backend() != "tpu",
            ),
            lambda item: np.asarray(item[1])[: item[0], :num_points],
            pipe,
            backend="pallas",
            op="dcf.batch_evaluate",
        )
    )
    out = pieces[0] if len(pieces) == 1 else np.concatenate(pieces, axis=0)
    return faultinject.corrupt_output(out, backend="pallas")


def batch_evaluate_host(dcf, keys: Sequence, xs: Sequence[int]) -> np.ndarray:
    """Host-engine fused batched DCF evaluation (native AES-NI).

    The same O(n) one-walk-per-point pass as `batch_evaluate`, executed in
    native/dpf_native.cc — one FFI call per key. Covers every Int/XorWrapper
    width: additive Int up to 64 bits on the packed u64 kernel
    (`dpf_dcf_evaluate_u64`), 128-bit and XOR-group values on the two-word
    kernel (`dpf_dcf_evaluate_wide`); IntModN outputs use the per-point host
    path (DistributedComparisonFunction.evaluate). Returns uint64[K, P] shares for
    bits <= 64, uint64[K, P, 2] (lo, hi) for 128-bit values — bit-identical
    to the device path. Uniform tuple payloads (the vector gate codec) run
    the same fused walk through the backend_numpy seed primitives (native
    AES-NI when built, numpy otherwise) and return uint64[K, P, n_elems, 2].
    """
    from .. import native
    from ..core import backend_numpy

    bits, xor_group, n_elems = evaluator._payload_kind(dcf.value_type)
    if n_elems > 1:
        return _batch_evaluate_host_tuple(
            dcf, keys, xs, bits, xor_group, n_elems
        )
    if not native.available():
        raise errors.UnavailableError(
            "native AES-NI engine unavailable on this host; use the device "
            "path (engine='device') or build native/dpf_native.cc"
        )
    num_points = len(xs)
    k = len(keys)
    batch, paths, acc_mask, block_sel, depth_to_hierarchy = _prep_points(
        dcf, keys, xs, num_points
    )
    capture = np.array([i >= 0 for i in depth_to_hierarchy], dtype=np.uint8)
    vc_limbs = _value_corrections_all(dcf, keys, depth_to_hierarchy)
    rkl = np.asarray(backend_numpy._PRG_LEFT._round_keys)
    rkr = np.asarray(backend_numpy._PRG_RIGHT._round_keys)
    rkv = np.asarray(backend_numpy._PRG_VALUE._round_keys)
    am = acc_mask[:, :num_points].astype(np.uint8)
    bs = block_sel[:, :num_points]
    if not xor_group and bits <= 64:
        # uint64 view of the per-element corrections (low two limbs).
        vc64 = (
            vc_limbs[..., 0].astype(np.uint64)
            | (vc_limbs[..., 1].astype(np.uint64) << np.uint64(32))
        )  # [K, T+1, epb]
        out = np.empty((k, num_points), dtype=np.uint64)
        for j in range(k):
            out[j] = native.dcf_evaluate_u64(
                rkl, rkr, rkv,
                batch.seeds[j], batch.party,
                batch.cw_seeds[j], batch.cw_left[j], batch.cw_right[j],
                vc64[j], capture, am, bs, paths, bits,
            )
        return out
    # Wide kernel: (lo, hi) uint64 pairs.
    vc_wide = np.stack(
        [
            vc_limbs[..., 0].astype(np.uint64)
            | (vc_limbs[..., 1].astype(np.uint64) << np.uint64(32)),
            vc_limbs[..., 2].astype(np.uint64)
            | (vc_limbs[..., 3].astype(np.uint64) << np.uint64(32)),
        ],
        axis=-1,
    )  # [K, T+1, epb, 2]
    out = np.empty((k, num_points, 2), dtype=np.uint64)
    for j in range(k):
        out[j] = native.dcf_evaluate_wide(
            rkl, rkr, rkv,
            batch.seeds[j], batch.party,
            batch.cw_seeds[j], batch.cw_left[j], batch.cw_right[j],
            vc_wide[j], capture, am, bs, paths, bits, xor_group,
        )
    return out if bits > 64 else out[..., 0]


def _batch_evaluate_host_tuple(
    dcf, keys: Sequence, xs: Sequence[int], bits: int, xor_group: bool,
    n_elems: int,
) -> np.ndarray:
    """Host fused DCF walk for uniform tuple payloads.

    Same O(n) pass as the scalar host kernels, built from the per-level
    backend_numpy primitives (native AES-NI when built, numpy fallback
    otherwise): one `evaluate_seeds` call per tree level with the level's
    path bit in the LSB, a `hash_expanded_seeds(seeds, nb)` capture at
    every output depth with the packed blocks split into their 128 // bits
    elements, and element-wise mod-2^bits accumulation (uint64 lanes for
    32/64-bit elements, limb adds for 128). Returns uint64[K, P, n_elems, 2]
    (lo, hi; hi == 0 for elements <= 64 bits) shares."""
    from ..core import backend_numpy, host_eval

    num_points = len(xs)
    k = len(keys)
    batch, paths, acc_mask, block_sel, depth_to_hierarchy = _prep_points(
        dcf, keys, xs, num_points
    )
    T = batch.num_levels
    nb = -(-(n_elems * bits) // 128)
    vc_limbs = _value_corrections_all(dcf, keys, depth_to_hierarchy, n_elems)
    # Per-depth path-bit arrays: `evaluate_seeds` reads bit L-1-level of its
    # paths argument relative to the call's own correction count, so a
    # one-level call reads the LSB — stage depth d's bit (T-1-d of the full
    # path) there.
    path_bits = np.zeros((T, num_points, 4), dtype=np.uint32)
    for d in range(T):
        idx = T - 1 - d
        path_bits[d, :, 0] = (paths[:, idx // 32] >> np.uint32(idx % 32)) & 1
    narrow = bits <= 64
    if narrow:
        # uint64-lane arithmetic: elements and corrections both < 2^bits.
        mask_w = np.uint64((1 << bits) - 1)
        vc64 = vc_limbs[..., 0].astype(np.uint64) | (
            vc_limbs[..., 1].astype(np.uint64) << np.uint64(32)
        )  # [K, T+1, n_elems]
        acc64 = np.zeros((k, num_points, n_elems), dtype=np.uint64)
    else:
        acc = np.zeros((k, num_points, n_elems, 4), dtype=np.uint32)

    def _elements(hashed):
        # uint32[P, nb, 4] packed blocks -> uint64[P, n_elems] (bits <= 64).
        flat = hashed.reshape(num_points, nb * 4).astype(np.uint64)
        if bits == 32:
            return flat[:, :n_elems]
        return (flat[:, 0::2] | (flat[:, 1::2] << np.uint64(32)))[
            :, :n_elems
        ]

    for ki in range(k):
        seeds = np.broadcast_to(
            batch.seeds[ki][None, :], (num_points, 4)
        ).copy()
        control = np.full(num_points, bool(batch.party), dtype=bool)
        for d in range(T + 1):
            if depth_to_hierarchy[d] >= 0:
                hashed = backend_numpy.hash_expanded_seeds(seeds, nb)
                if narrow:
                    els = _elements(hashed)
                    gated = vc64[ki, d][None] * control.astype(np.uint64)[
                        :, None
                    ]
                    if xor_group:
                        value = els ^ gated
                    else:
                        value = (els + gated) & mask_w
                    value = value * acc_mask[d, :num_points, None].astype(
                        np.uint64
                    )
                    if xor_group:
                        acc64[ki] ^= value
                    else:
                        acc64[ki] = (acc64[ki] + value) & mask_w
                else:
                    gated = (
                        vc_limbs[ki, d][None]
                        * control.astype(np.uint32)[:, None, None]
                    )
                    if xor_group:
                        value = hashed ^ gated
                    else:
                        value = host_eval._add128(hashed, gated)
                    value = value * acc_mask[d, :num_points, None, None]
                    if xor_group:
                        acc[ki] ^= value
                    else:
                        acc[ki] = host_eval._add128(acc[ki], value)
            if d < T:
                seeds, control = backend_numpy.evaluate_seeds(
                    seeds, control, path_bits[d],
                    batch.cw_seeds[ki, d : d + 1],
                    batch.cw_left[ki, d : d + 1],
                    batch.cw_right[ki, d : d + 1],
                )
        if batch.party == 1 and not xor_group:
            if narrow:
                acc64[ki] = (np.uint64(0) - acc64[ki]) & mask_w
            else:
                acc[ki] = host_eval._neg128(acc[ki])
    out = np.zeros((k, num_points, n_elems, 2), dtype=np.uint64)
    if narrow:
        out[..., 0] = acc64
        return out
    out[..., 0] = acc[..., 0].astype(np.uint64) | (
        acc[..., 1].astype(np.uint64) << np.uint64(32)
    )
    out[..., 1] = acc[..., 2].astype(np.uint64) | (
        acc[..., 3].astype(np.uint64) << np.uint64(32)
    )
    return out
