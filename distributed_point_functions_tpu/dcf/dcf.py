"""Distributed Comparison Function (DCF): secret shares of f(x) = beta iff
x < alpha.

Host API re-designed from the reference's DistributedComparisonFunction
(/root/reference/dcf/distributed_comparison_function.{h,cc}):

* Construction builds an *incremental DPF* with one hierarchy level per
  domain bit (log_domain_size i at level i) over the same value type
  (.cc:56-62).
* ``generate_keys(alpha, beta)``: level i's beta is `beta` where bit
  (n-1-i) of alpha is 1 and 0 where it is 0, and the DPF point is
  ``alpha >> 1`` — the last bit is encoded entirely in the last beta
  (.cc:78-100).
* ``evaluate(key, x)``: sum of the DPF evaluations of x's i-bit prefixes
  over exactly the levels where bit (n-1-i) of x is 0 (.h:83-107).

Why this computes [x < alpha]: walking the tree along x, the first level i
where x and alpha diverge contributes beta iff alpha's bit is 1 there
(x's prefix equals alpha's prefix and x's next bit is 0 < alpha's 1); all
other levels contribute shares of 0.

``evaluate`` mirrors the reference's one-EvaluateAt-per-level control flow
(O(n^2) AES per point) and works for every value type. The TPU fast path is
``batch_evaluate`` (dcf/batch.py): ONE fused root-to-leaf scan per point that
captures all n per-level values in a single pass (O(n) AES), vmapped over
keys — the reference has no batched equivalent at all.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..core.dpf import DistributedPointFunction
from ..core.keys import DpfKey
from ..core.params import DpfParameters
from ..core.value_types import ValueType
from ..utils import envflags
from ..utils.errors import InvalidArgumentError


@dataclasses.dataclass
class DcfKey:
    """One party's DCF key: a wrapped incremental DPF key.

    Mirrors the DcfKey proto (/root/reference/dcf/distributed_comparison_function.proto:25-28).
    """

    key: DpfKey


class DistributedComparisonFunction:
    """A DCF over a 2^log_domain_size domain with a given output value type."""

    def __init__(self, log_domain_size: int, value_type: ValueType, dpf):
        self.log_domain_size = log_domain_size
        self.value_type = value_type
        self._dpf = dpf

    @classmethod
    def create(
        cls, log_domain_size: int, value_type: ValueType, backend=None
    ) -> "DistributedComparisonFunction":
        if log_domain_size < 1:
            raise InvalidArgumentError("A DCF must have log_domain_size >= 1")
        parameters = [
            DpfParameters(i, value_type) for i in range(log_domain_size)
        ]
        dpf = DistributedPointFunction.create_incremental(parameters, backend=backend)
        return cls(log_domain_size, value_type, dpf)

    @property
    def dpf(self) -> DistributedPointFunction:
        return self._dpf

    def generate_keys(
        self, alpha: int, beta, seeds: Optional[Tuple[int, int]] = None
    ) -> Tuple[DcfKey, DcfKey]:
        n = self.log_domain_size
        if alpha < 0 or (n < 128 and alpha >= (1 << n)):
            raise InvalidArgumentError(
                "`alpha` must be smaller than the output domain size"
            )
        betas = []
        for i in range(n):
            current_bit = (alpha >> (n - i - 1)) & 1
            betas.append(beta if current_bit else self.value_type.zero())
        key_a, key_b = self._dpf.generate_keys_incremental(
            alpha >> 1, betas, seeds=seeds
        )
        return DcfKey(key_a), DcfKey(key_b)

    def generate_keys_batch(
        self, alphas: Sequence[int], betas, seeds=None, mode: Optional[str] = None
    ) -> Tuple[List[DcfKey], List[DcfKey]]:
        """K DCF key pairs at once through the level-major batched DPF
        keygen (one vectorized AES call per tree level across all keys).

        `betas` is one value (broadcast) or a length-K sequence. A value
        that is itself valid for the output type (e.g. a tuple for a
        TupleType DCF) is always treated as the broadcast form.

        `mode` selects the keygen engine ("numpy" / "jax" / "pallas";
        None = the host batched path unless DPF_TPU_KEYGEN overrides) —
        all modes are byte-identical, see ops/keygen_batch.py.
        """
        n = self.log_domain_size
        k = len(alphas)
        try:
            self.value_type.validate_value(betas)
            betas = [betas] * k
        except Exception:
            betas = list(betas) if hasattr(betas, "__len__") else [betas] * k
        if len(betas) != k:
            raise InvalidArgumentError(
                "`betas` must be a single value or one per alpha"
            )
        zero = self.value_type.zero()
        for alpha in alphas:
            if alpha < 0 or (n < 128 and alpha >= (1 << n)):
                raise InvalidArgumentError(
                    "`alpha` must be smaller than the output domain size"
                )
        per_level = [
            [
                betas[j] if (alphas[j] >> (n - i - 1)) & 1 else zero
                for j in range(k)
            ]
            for i in range(n)
        ]
        shifted = [a >> 1 for a in alphas]
        if mode is None and not envflags.env_str("DPF_TPU_KEYGEN", None):
            # The pure host path stays import-light (no jax): servers and
            # benches that never touch a device mode pay nothing for it.
            # Routed through the threaded host dealer (ISSUE 19, itself
            # jax-free) so gate dealers ride DPF_TPU_KEYGEN_THREADS; all
            # seeds are drawn before the pool fans out, so DCF keys stay
            # byte-identical at any thread count.
            from ..ops import keygen_batch

            keys_a, keys_b = keygen_batch.host_generate_keys_batch(
                self._dpf, shifted, per_level, seeds=seeds
            )
        else:
            from ..ops import keygen_batch

            keys_a, keys_b = keygen_batch.generate_keys_batch(
                self._dpf, shifted, per_level, mode=mode, seeds=seeds
            )
        return [DcfKey(x) for x in keys_a], [DcfKey(x) for x in keys_b]

    def evaluate(self, key: DcfKey, x: int):
        """Reference-parity single-point evaluation (host, any value type)."""
        n = self.log_domain_size
        if x < 0 or (n < 128 and x >= (1 << n)):
            raise InvalidArgumentError("`x` must be smaller than the domain size")
        result = self.value_type.zero()
        for i in range(n):
            prefix = x >> (n - i)  # the i-bit prefix of x (Python shifts are exact)
            evaluation = self._dpf.evaluate_at(key.key, i, [prefix])
            current_bit = (x >> (n - i - 1)) & 1
            if current_bit == 0:
                result = self.value_type.add(result, evaluation[0])
        return result

    def batch_evaluate(
        self, keys: Sequence[DcfKey], xs: Sequence[int], engine: str = "device",
        **device_kwargs,
    ) -> np.ndarray:
        """Fused evaluation of every key at every point (one tree walk per
        point instead of the reference's walk-per-bit).

        engine="device" returns uint32[K, P, lpe] limb values;
        engine="host" runs the native AES-NI kernels and returns uint64[K, P]
        (bits <= 64) or uint64[K, P, 2] (lo, hi) pairs (see dcf/batch.py).
        `device_kwargs` pass through to `batch.batch_evaluate` (mode=,
        use_pallas=, key_chunk=, pipeline=, interpret= — e.g.
        mode="walkkernel" for the single-program walk megakernel); they
        have no host-engine meaning, so engine="host" rejects them.
        """
        from . import batch

        if engine == "host":
            if device_kwargs:
                raise InvalidArgumentError(
                    "engine='host' takes no device kwargs, got "
                    f"{sorted(device_kwargs)}"
                )
            return batch.batch_evaluate_host(self, keys, xs)
        if engine != "device":
            raise InvalidArgumentError(
                f"engine must be 'device' or 'host', got {engine!r}"
            )
        return batch.batch_evaluate(self, keys, xs, **device_kwargs)
