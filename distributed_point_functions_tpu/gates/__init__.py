"""FSS gate family: DCF-derived two-party gates over masked inputs,
every one compiled onto the batched DCF walk through the shared
framework (gates/framework.py — ONE fused batched-DCF pass per gate
batch, walk or walkkernel mode).

* :class:`MultipleIntervalContainmentGate` — m interval predicates
  (BCG+ Fig. 14), the founding gate.
* :class:`DReluGate` / :class:`ReluGate` — the secure-ML activation pair
  (comparison gate; ReLU as the fixed two-piece spline).
* :class:`SplineGate` — piecewise-polynomial evaluation, the fixed-point
  math workhorse (vector-codec payload by default: ONE tuple-payload DCF
  key per gate instead of m(d+1) scalar keys).
* :class:`SigmoidGate` / :class:`TanhGate` — wide (8-16 piece, degree-1)
  fixed-point activation splines on the vector codec.
* :class:`BitDecompositionGate` — arithmetic-to-boolean share conversion.
"""

from .bitdecomp import BitDecompositionGate  # noqa: F401
from .framework import (  # noqa: F401
    GateKey,
    GatePlan,
    MaskedGate,
    bundle_eval,
)
from .mic import MicKey, MultipleIntervalContainmentGate  # noqa: F401
from .prng import BasicRng, CounterRng, SecurePrng  # noqa: F401
from .relu import DReluGate, ReluGate  # noqa: F401
from .spline import SigmoidGate, SplineGate, TanhGate  # noqa: F401
