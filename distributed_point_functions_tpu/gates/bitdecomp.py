"""Bit-decomposition FSS gate (BCG+ eprint 2020/1392 §4.3 flavor):
boolean (mod-2) additive shares of every bit of x_real from one masked
input — the arithmetic-to-boolean share conversion of mixed-mode secure
computation.

Construction (validated exhaustively in tests/test_gates_framework.py):
bit j of x_real depends only on ``y_j = x_real mod 2^(j+1)``, and
``bit_j = 1  iff  y_j in [2^j, 2^(j+1) - 1]`` — interval containment in
the subgroup Z_{2^(j+1)}. The subgroup's masked input is public:
``m_j = x mod 2^(j+1)`` (since 2^(j+1) divides N), its mask is
``u_j = r_in mod 2^(j+1)``, and a DCF threshold ``alpha_j = u_j - 1 mod
2^(j+1)`` < 2^(j+1) evaluated at subgroup points < 2^(j+1) is exact on
the shared FULL-domain DCF (a comparison is a comparison) — so all n
per-bit component keys ride ONE DCF object, and the whole decomposition
is ONE fused batched-DCF pass in the MIC program family: n component
keys x 2n sites per input. Reducing each subgroup share mod 2 (2 divides
every subgroup order) yields the boolean output shares; reconstruction
is ``(s0 + s1) mod 2 = bit_j XOR'd with r_out_j``.

Key layout (``GateKey.mask_shares``): ``[z_j share mod 2]`` per bit,
``z_j = wrap_count_j + r_out_j mod 2``. Output masks are bits
(r_out_j in {0, 1}).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from ..utils.errors import InvalidArgumentError
from . import framework


class BitDecompositionGate(framework.MaskedGate):
    """Boolean shares of the log_group_size bits of x_real."""

    def __init__(self, log_group_size: int, dcf):
        super().__init__(log_group_size, dcf, num_outputs=log_group_size)

    @classmethod
    def create(cls, log_group_size: int) -> "BitDecompositionGate":
        return cls(log_group_size, cls._create_dcf(log_group_size))

    # -- framework contract ------------------------------------------------
    @property
    def num_components(self) -> int:
        return self.log_group_size

    @property
    def num_sites(self) -> int:
        return 2 * self.log_group_size

    def _subgroup(self, j: int) -> Tuple[int, int, int]:
        """(n_j, p_j, q_j): subgroup order and the bit-j interval."""
        n_j = 1 << (j + 1)
        return n_j, 1 << j, n_j - 1

    def _component_specs(self, r_in: int) -> List[Tuple[int, int]]:
        specs = []
        for j in range(self.log_group_size):
            n_j, _, _ = self._subgroup(j)
            specs.append((framework.ic_alpha(n_j, r_in % n_j), 1))
        return specs

    def _mask_values(self, r_in: int, r_outs: Sequence[int]) -> List[int]:
        zs = []
        for j in range(self.log_group_size):
            n_j, p, q = self._subgroup(j)
            c = framework.ic_wrap_count(n_j, r_in % n_j, p, q)
            zs.append((c + r_outs[j]) % 2)
        return zs

    def _mask_moduli(self) -> List[int]:
        return [2] * self.log_group_size

    def _validate_r_out(self, r: int) -> bool:
        return r in (0, 1)

    def _points(self, x: int) -> List[int]:
        pts: List[int] = []
        for j in range(self.log_group_size):
            n_j, p, q = self._subgroup(j)
            pts.extend(framework.ic_points(n_j, x % n_j, p, q))
        return pts

    def _combine_one(
        self, party: int, shares: Sequence[int], x: int, vals: np.ndarray
    ) -> List[int]:
        out = []
        for j in range(self.log_group_size):
            n_j, p, q = self._subgroup(j)
            pub = framework.ic_public_term(n_j, x % n_j, p, q)
            # The subgroup identity holds mod n_j; 2 | n_j, so reducing
            # every term mod 2 keeps it exact — ic_share over Z_2.
            out.append(
                framework.ic_share(
                    2, pub, party,
                    int(vals[j, 2 * j]) % 2, int(vals[j, 2 * j + 1]) % 2,
                    shares[j],
                )
            )
        return out

    @staticmethod
    def reconstruct_bits(
        shares_0: Sequence[int], shares_1: Sequence[int],
        r_outs: Sequence[int],
    ) -> List[int]:
        """Client-side recombination: (s0 + s1 - r_out) mod 2 per bit."""
        return [
            (int(a) + int(b) - int(r)) % 2
            for a, b, r in zip(shares_0, shares_1, r_outs)
        ]
