"""FSS gate framework: masked-input gates compiled onto the batched DCF walk.

The reference's gate layer stops at one hand-built gate (MIC,
multiple_interval_containment.cc); this module turns its structure into a
*framework* so every new DCF-derived gate — comparison/DReLU, splines,
bit decomposition (BCG+ eprint 2020/1392; the gates-as-preprocessed-dealer
model of BGI eprint 2018/707) — is a capture-plan over the existing
batched-DCF machinery rather than a new 1k-LoC kernel body.

The shared structure (BCG+ §4, all built on Lemma 1/Fig. 14's interval
containment): a dealer knows an input mask ``r_in``; the parties hold the
public masked input ``x = x_real + r_in mod N`` and per-party key
material; the gate output is an additive sharing (mod N, or mod 2 for
boolean outputs) of ``f(x_real)`` plus an output mask. Every gate here
decomposes into three dealer-computable ingredients:

* **Component DCF keys** — one or more DCF key pairs at
  ``alpha = r_in' - 1`` with a payload ``beta`` the dealer picks
  (:meth:`MaskedGate._component_specs`). Payloads come in two layouts:
  scalar ``Int(128)`` (one component key per payload element — the
  original program family the MIC gate compiles) and the vector codec
  (BCG+'s native spline form: ONE component key whose value type is
  ``TupleType`` over all payload elements, ``payload_elems`` > 1). A
  vector key rides the same fused-DCF walk — only the value-capture
  tail widens (dcf/batch.py) — so key bytes, dealer work, and walk
  count all drop ``payload_elems``× while the combine algebra sees the
  identical coefficient-row matrix either way.
* **Mask shares** — additive shares of dealer-computed correction values
  (the interval wrap counts of BCG+ Lemma 1, payload shares, output
  masks), split by the gate's :class:`~.prng.SecurePrng`.
* **A site/combine plan** — per masked input, which DCF evaluation
  points are needed (:meth:`MaskedGate._points`) and how the evaluated
  (component x site) value matrix linearly combines with the mask shares
  and public comparisons into output shares
  (:meth:`MaskedGate._combine_one`).

:class:`GatePlan` is the flatten/evaluate path every gate shares: the
(inputs x sites) grid flattens into ONE fused batched-DCF pass
(``dcf.batch_evaluate`` — all component keys x all flattened points, one
device program per key chunk in walk mode, the whole gate in one
walk-megakernel program under ``mode="walkkernel"``), exactly the way
gates/mic.py did by hand before this framework existed. The robust and
serving layers reuse the same plan (ops/supervisor.gate_batch_eval_robust,
serving "gate" requests), so there is one flatten/evaluate path in the
repo, not four.

Everything dealer-side is exact Python-int arithmetic mod N (N | 2^128,
so reducing the DCF's mod-2^128 shares mod N is exact — the same
argument gates/mic.py documents).
"""

from __future__ import annotations

import abc
import dataclasses
import secrets
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..core import uint128
from ..dcf.dcf import DcfKey
from ..utils import telemetry as _tm
from ..utils.errors import InvalidArgumentError
from .prng import BasicRng, SecurePrng

# ---------------------------------------------------------------------------
# Interval-containment algebra (BCG+ Lemma 1 / Fig. 14), shared by every gate
# ---------------------------------------------------------------------------


def ic_points(n: int, x: int, p: int, q: int) -> Tuple[int, int]:
    """The two DCF evaluation points of one interval-containment instance
    over Z_n: the masked input's comparisons against p and q' = q+1."""
    q_prime = (q + 1) % n
    return (x + n - 1 - p) % n, (x + n - 1 - q_prime) % n


def ic_alpha(n: int, r_in: int) -> int:
    """The component DCF's evaluation threshold: r_in - 1 mod n."""
    return (n - 1 + r_in) % n


def ic_wrap_count(n: int, r_in: int, p: int, q: int) -> int:
    """The dealer's mask-wraparound correction count for interval [p, q]
    under input mask r_in (the bracketed term of gates/mic.py's ``z``,
    BCG+ Lemma 2): an integer in {-1, 0, 1, 2, 3}."""
    q_prime = (q + 1) % n
    alpha_p = (p + r_in) % n
    alpha_q = (q + r_in) % n
    alpha_q_prime = (q + 1 + r_in) % n
    return (
        (1 if alpha_p > alpha_q else 0)
        - (1 if alpha_p > p else 0)
        + (1 if alpha_q_prime > q_prime else 0)
        + (1 if alpha_q == n - 1 else 0)
    )


def ic_public_term(n: int, x: int, p: int, q: int) -> int:
    """The public comparison term both parties can compute from the
    masked input: 1{x > p} - 1{x > q'}. Multiplied by each party's share
    of the payload (for payload 1, party 0 holds 0 and party 1 holds 1 —
    the ``party_term`` of gates/mic.py)."""
    q_prime = (q + 1) % n
    return (1 if x > p else 0) - (1 if x > q_prime else 0)


def ic_share(
    n: int, pub: int, w_share: int, s_p: int, s_q_prime: int, z_share: int
) -> int:
    """One interval-containment output share: for payload w, reconstructs
    to ``w * 1{x_real in [p, q]}`` across the two parties. ``pub`` is
    :func:`ic_public_term`, ``w_share`` this party's additive share of
    the payload, ``s_p``/``s_q_prime`` its DCF value shares at the two
    :func:`ic_points` (already reduced mod n), ``z_share`` its share of
    ``wrap_count * w`` (+ any output mask)."""
    return (pub * w_share - s_p + s_q_prime + z_share) % n


def resolve_payload(payload: Optional[str] = None) -> str:
    """Resolve a gate's payload layout: an explicit "scalar"/"vector"
    wins, else the DPF_TPU_GATE_PAYLOAD env (default "vector" — the
    BCG+-native codec; "scalar" keeps the PR 9 flattening as the
    selectable oracle path)."""
    from ..utils import envflags

    if payload is None:
        payload = envflags.env_str("DPF_TPU_GATE_PAYLOAD", "vector") or "vector"
    if payload not in ("scalar", "vector"):
        raise InvalidArgumentError(
            f'payload must be "scalar" or "vector", got {payload!r}'
        )
    return payload


def split_share(value: int, modulus: int, prng: SecurePrng) -> Tuple[int, int]:
    """Additive 2-sharing of ``value`` mod ``modulus`` (party-0 share
    drawn from the prng — one rand128 per split, the draw order golden
    key tests pin)."""
    s0 = prng.rand128() % modulus
    return s0, (value - s0) % modulus


# ---------------------------------------------------------------------------
# Keys
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class GateKey:
    """One party's generic gate key: component DCF keys + the gate's
    mask-share vector (layout owned by the gate class; see
    protos/serialization.serialize_gate_key for the wire form)."""

    dcf_keys: List[DcfKey]
    mask_shares: List[int]

    @property
    def party(self) -> int:
        return self.dcf_keys[0].key.party


# ---------------------------------------------------------------------------
# The flatten/evaluate path (ONE fused batched-DCF pass per gate batch)
# ---------------------------------------------------------------------------


def _values_as_ints(evals, engine: str) -> np.ndarray:
    """Normalize a batched-DCF result to an object ndarray of Python ints
    [K, P] (scalar payloads) or [K, P, t] (vector payloads): host engine
    returns uint64 (lo, hi) pairs for the gates' Int(128) payloads, the
    device engine uint32 limb vectors."""
    from ..ops import evaluator

    evals = np.asarray(evals)
    if engine == "host":
        if evals.dtype == np.uint64 and evals.ndim >= 3 and evals.shape[-1] == 2:
            # uint64[K, P, 2] / uint64[K, P, t, 2] (lo, hi)
            return evals[..., 0].astype(object) | (
                evals[..., 1].astype(object) << 64
            )
        return evals.astype(object)
    return evaluator.values_to_numpy(evals, 128)


def _flatten_payload(values: np.ndarray) -> np.ndarray:
    """Vector-payload [K, P, t] int matrices -> the logical [K*t, P]
    coefficient-row matrix the combine algebra consumes (key-major, the
    scalar component-key order); scalar [K, P] passes through."""
    if values.ndim == 3:
        k, p, t = values.shape
        return values.transpose(0, 2, 1).reshape(k * t, p)
    return values


@dataclasses.dataclass
class GatePlan:
    """The flattened (inputs x DCF-evaluation-sites) layout of one gate
    batch — the object that compiles a gate onto the batched DCF walk.

    ``points`` is the flat evaluation-point list: input ``xi``'s
    ``num_sites`` points occupy ``points[xi * num_sites : (xi + 1) *
    num_sites]``. :meth:`evaluate` runs them against ALL component keys
    in ONE ``dcf.batch_evaluate`` pass (the fused walk — one device
    program per key chunk in walk mode, one walk-megakernel program under
    ``mode="walkkernel"``); :meth:`combine` reduces the resulting
    (component x site) matrix mod N and hands each input's slice to the
    gate's linear combine. The waste of evaluating every component at
    every site (components only read their own interval's sites) is the
    price of staying inside one uniform program family; the per-gate
    accounting lives in PERF.md's "FSS gate family" table.
    """

    gate: "MaskedGate"
    xs: List[int]
    points: List[int]

    @classmethod
    def build(cls, gate: "MaskedGate", xs: Sequence[int]) -> "GatePlan":
        gate._check_masked_inputs(xs)
        xs = [int(x) for x in xs]
        points: List[int] = []
        for x in xs:
            pts = gate._points(x)
            if len(pts) != gate.num_sites:
                raise InvalidArgumentError(
                    f"{type(gate).__name__}._points returned {len(pts)} "
                    f"sites, declared num_sites={gate.num_sites}"
                )
            points.extend(pts)
        return cls(gate=gate, xs=xs, points=points)

    def evaluate(
        self, dcf_keys: Sequence[DcfKey], engine: str = "device",
        **device_kwargs,
    ) -> np.ndarray:
        """ONE fused batched-DCF pass over all components x all sites;
        returns object ints [num_components, len(points)]."""
        evals = self.gate.dcf.batch_evaluate(
            list(dcf_keys), self.points, engine=engine, **device_kwargs
        )
        return _values_as_ints(evals, engine)

    def combine(self, key, values: np.ndarray) -> np.ndarray:
        """Per-input linear combine of the evaluated site matrix: returns
        an object ndarray [len(xs), num_outputs] of share values."""
        gate = self.gate
        n = gate.n
        s = gate.num_sites
        dcf_keys, shares = gate._key_parts(key)
        party = dcf_keys[0].key.party
        values = _flatten_payload(np.asarray(values, dtype=object))
        out = np.zeros((len(self.xs), gate.num_outputs), dtype=object)
        for xi, x in enumerate(self.xs):
            vals = values[:, s * xi : s * (xi + 1)] % n
            out[xi] = gate._combine_one(party, shares, x, vals)
        return out


# ---------------------------------------------------------------------------
# Gate base class
# ---------------------------------------------------------------------------


class MaskedGate(abc.ABC):
    """A two-party FSS gate over Z_N (N = 2^log_group_size) with masked
    input, evaluated through one fused batched-DCF pass.

    Subclasses declare the dealer algebra (component DCF specs, mask
    values) and the eval plan (sites, combine); ``gen`` / ``eval`` /
    ``batch_eval`` are the shared templates. Component DCFs ride
    ``Int(128)`` payloads over a 2^log_group_size domain — the program
    family gates/mic.py established — or, for vector-codec gates
    (``payload_elems`` > 1), one ``TupleType`` key carrying every
    coefficient through the same walk.
    """

    def __init__(self, log_group_size: int, dcf, num_outputs: int):
        self.log_group_size = log_group_size
        self._dcf = dcf
        self.num_outputs = num_outputs

    # -- shared construction ----------------------------------------------
    @staticmethod
    def _create_dcf(log_group_size: int, num_elements: int = 1):
        """The gate's component DCF: ``Int(128)`` for scalar payloads, a
        uniform ``TupleType(Int(w) x num_elements)`` for the vector codec
        with w the narrowest whole-limb width holding Z_N (32, 64, or
        128 — N | 2^w keeps the masked-wire algebra exact while the
        per-level value corrections shrink 128/w x). ``num_elements == 1``
        ALWAYS yields the plain scalar ``Int(128)`` DCF — a 1-element
        vector gate therefore degenerates to the scalar program and wire
        format exactly (the byte-identity pin)."""
        from ..core.value_types import Int, TupleType
        from ..dcf.dcf import DistributedComparisonFunction

        if log_group_size < 1 or log_group_size > 127:
            raise InvalidArgumentError(
                "log_group_size should be in > 0 and < 128"
            )
        if num_elements < 1:
            raise InvalidArgumentError("num_elements must be >= 1")
        if num_elements == 1:
            vt = Int(128)
        else:
            width = 32 if log_group_size <= 32 else (
                64 if log_group_size <= 64 else 128
            )
            vt = TupleType(*([Int(width)] * num_elements))
        return DistributedComparisonFunction.create(log_group_size, vt)

    @property
    def n(self) -> int:
        return 1 << self.log_group_size

    @property
    def dcf(self):
        """The shared component DCF (its DPF drives the fused walk)."""
        return self._dcf

    @property
    def payload_elems(self) -> int:
        """Tuple elements per component DCF key: 1 for scalar payloads,
        the coefficient count for vector-codec gates. The combine algebra
        always consumes ``num_components * payload_elems`` coefficient
        rows, whichever layout carried them."""
        return 1

    # -- subclass contract -------------------------------------------------
    @property
    @abc.abstractmethod
    def num_components(self) -> int:
        """Component DCF keys per party key (static: key size)."""

    @property
    @abc.abstractmethod
    def num_sites(self) -> int:
        """DCF evaluation points per masked input (static: plan shape)."""

    @abc.abstractmethod
    def _component_specs(self, r_in: int) -> List[Tuple[int, int]]:
        """Dealer: per component key, its (alpha, beta) DCF parameters."""

    @abc.abstractmethod
    def _mask_values(self, r_in: int, r_outs: Sequence[int]) -> List[int]:
        """Dealer: the plaintext correction/mask values to split."""

    @abc.abstractmethod
    def _points(self, x: int) -> List[int]:
        """The ``num_sites`` DCF evaluation points for masked input x."""

    @abc.abstractmethod
    def _combine_one(
        self, party: int, shares: Sequence[int], x: int, vals: np.ndarray
    ) -> List[int]:
        """Party's output shares from its mask shares + the reduced
        (component x site) value matrix for one input."""

    def _mask_moduli(self) -> List[int]:
        """Modulus per mask value (default: the group order; boolean
        outputs override with 2s)."""
        return [self.n] * len(self._mask_values(0, [0] * self.num_outputs))

    def config_signature(self) -> tuple:
        """The gate's public configuration beyond (class, log_group_size)
        — the identity serving compatibility queues key on
        (serving/batcher.py): two requests merge into one batch only if
        their gates agree on it. A subclass whose constructor takes any
        public parameter (intervals, coefficients, a shift amount, ...)
        MUST override and return it all, else differently-configured
        instances of the same class + key material would merge and the
        whole batch would be evaluated under one request's config."""
        return ()

    def _make_key(self, dcf_keys: List[DcfKey], shares: List[int]):
        return GateKey(dcf_keys, shares)

    def _key_parts(self, key) -> Tuple[List[DcfKey], List[int]]:
        return key.dcf_keys, key.mask_shares

    def _validate_r_out(self, r: int) -> bool:
        return 0 <= r < self.n

    # -- templates ---------------------------------------------------------
    def _check_masked_inputs(self, xs: Sequence[int]) -> None:
        """Input validation shared by batch_eval and the supervisor's
        robust wrapper (ops/supervisor.gate_batch_eval_robust)."""
        n = self.n
        for x in xs:
            if not 0 <= x < n:
                raise InvalidArgumentError(
                    "Masked input should be between 0 and 2^log_group_size"
                )

    def _check_masks(self, r_in: int, r_outs: Sequence[int]) -> None:
        if len(r_outs) != self.num_outputs:
            raise InvalidArgumentError(
                "Count of output masks should be equal to the number of "
                "gate outputs"
            )
        if not 0 <= r_in < self.n:
            raise InvalidArgumentError(
                "Input mask should be between 0 and 2^log_group_size"
            )
        for r in r_outs:
            if not self._validate_r_out(int(r)):
                raise InvalidArgumentError(
                    "Output mask outside the gate's output group"
                )

    def _normalize_dcf_seeds(self, num_components: int, dcf_seeds):
        """None / one pair (one-component gates) / one pair per component
        -> a list of Optional[(s0, s1)] of length num_components."""
        if dcf_seeds is None:
            return [None] * num_components
        if (
            num_components == 1
            and len(dcf_seeds) == 2
            and not hasattr(dcf_seeds[0], "__len__")
        ):
            return [tuple(dcf_seeds)]
        seeds_list = [tuple(s) for s in dcf_seeds]
        if len(seeds_list) != num_components:
            raise InvalidArgumentError(
                f"dcf_seeds must carry one (s0, s1) pair per component "
                f"({num_components}), got {len(seeds_list)}"
            )
        return seeds_list

    def _batch_component_keys(
        self, specs, seeds_list, keygen_mode: Optional[str]
    ) -> Tuple[List[DcfKey], List[DcfKey]]:
        """ALL component DCF key pairs in ONE level-major batched keygen
        pass (ops/keygen_batch.py via dcf.generate_keys_batch) — the
        dealer analog of the fused evaluation pass. Byte-identical to the
        per-component scalar loop given the same seeds; entries with no
        pinned seed draw theirs from the CSPRNG here (the scalar path
        drew inside `generate_keys`, same distribution)."""
        seeds_arr = np.empty((len(specs), 2, 4), dtype=np.uint32)
        for i, sd in enumerate(seeds_list):
            if sd is None:
                seeds_arr[i] = np.frombuffer(
                    secrets.token_bytes(32), dtype=np.uint32
                ).reshape(2, 4)
            else:
                seeds_arr[i, 0] = uint128.to_limbs(sd[0])
                seeds_arr[i, 1] = uint128.to_limbs(sd[1])
        return self._dcf.generate_keys_batch(
            [alpha for alpha, _ in specs],
            [beta for _, beta in specs],
            seeds=seeds_arr,
            mode=keygen_mode,
        )

    def gen(
        self,
        r_in: int,
        r_outs: Sequence[int],
        prng: Optional[SecurePrng] = None,
        dcf_seeds=None,
        keygen_mode: Optional[str] = None,
    ):
        """Dealer keygen for masks ``r_in`` / ``r_outs``: component DCF
        key pairs + additively split mask values. ``prng`` supplies the
        share randomness (one rand128 per mask value, in
        ``_mask_values`` order — the draw order golden-key tests pin);
        ``dcf_seeds`` optionally pins the component DCF keygen seeds (a
        single (s0, s1) pair for one-component gates, else one pair per
        component) — together they make ``gen`` fully deterministic.

        All component keys are seeded through ONE batched level-major
        keygen pass (ISSUE 13); ``keygen_mode`` selects its engine (any
        of ops/keygen_batch.KEYGEN_MODES; None = the threaded host
        dealer unless DPF_TPU_KEYGEN overrides, so gate dealers ride
        DPF_TPU_KEYGEN_THREADS) — every mode produces byte-identical
        keys."""
        if prng is None:
            prng = BasicRng()
        self._check_masks(r_in, r_outs)
        specs = self._component_specs(r_in)
        seeds_list = self._normalize_dcf_seeds(len(specs), dcf_seeds)
        keys_0, keys_1 = self._batch_component_keys(
            specs, seeds_list, keygen_mode
        )
        shares_0, shares_1 = self._split_mask_shares(r_in, r_outs, prng)
        return self._make_key(keys_0, shares_0), self._make_key(keys_1, shares_1)

    def _split_mask_shares(
        self, r_in: int, r_outs: Sequence[int], prng: SecurePrng
    ) -> Tuple[List[int], List[int]]:
        """Dealer mask-value splitting (one rand128 per value, in
        `_mask_values` order — the draw order golden-key tests pin);
        shared by `gen` and `gen_bundle` so the sequence exists once."""
        values = self._mask_values(int(r_in), [int(r) for r in r_outs])
        moduli = self._mask_moduli()
        shares_0: List[int] = []
        shares_1: List[int] = []
        for v, mod in zip(values, moduli):
            s0, s1 = split_share(int(v), mod, prng)
            shares_0.append(s0)
            shares_1.append(s1)
        return shares_0, shares_1

    def gen_bundle(
        self,
        r_ins: Sequence[int],
        r_outs_seq: Sequence[Sequence[int]],
        prng: Optional[SecurePrng] = None,
        dcf_seeds=None,
        keygen_mode: Optional[str] = None,
    ):
        """Dealer keygen for a whole bundle: B independent (r_in, r_outs)
        mask sets — the secure-ML layer / streaming-dealer shape — with
        ALL B x num_components component DCF keys seeded in ONE batched
        level-major keygen pass instead of B scalar gens. Bit-identical
        to ``[gen(r_ins[b], r_outs_seq[b]) for b]`` given the same
        ``prng`` and per-element ``dcf_seeds``: component key material
        comes from the CSPRNG (never ``prng``), and the mask-share draws
        happen in bundle order.

        ``dcf_seeds``: None, or one per bundle element, each in ``gen``'s
        ``dcf_seeds`` form. Returns (keys_0, keys_1), each a length-B
        list of this gate's party keys (``bundle_eval``'s input shape)."""
        if prng is None:
            prng = BasicRng()
        b_count = len(r_ins)
        if len(r_outs_seq) != b_count:
            raise InvalidArgumentError(
                f"gen_bundle needs one r_outs per r_in, got {len(r_outs_seq)} "
                f"for {b_count}"
            )
        if dcf_seeds is not None and len(dcf_seeds) != b_count:
            raise InvalidArgumentError(
                f"dcf_seeds must carry one entry per bundle element "
                f"({b_count}), got {len(dcf_seeds)}"
            )
        all_specs = []
        all_seeds = []
        for b in range(b_count):
            self._check_masks(int(r_ins[b]), r_outs_seq[b])
            specs = self._component_specs(int(r_ins[b]))
            all_specs.extend(specs)
            all_seeds.extend(
                self._normalize_dcf_seeds(
                    len(specs),
                    None if dcf_seeds is None else dcf_seeds[b],
                )
            )
        flat_0, flat_1 = self._batch_component_keys(
            all_specs, all_seeds, keygen_mode
        )
        c = self.num_components
        keys_0, keys_1 = [], []
        for b in range(b_count):
            shares_0, shares_1 = self._split_mask_shares(
                r_ins[b], r_outs_seq[b], prng
            )
            keys_0.append(
                self._make_key(flat_0[b * c : (b + 1) * c], shares_0)
            )
            keys_1.append(
                self._make_key(flat_1[b * c : (b + 1) * c], shares_1)
            )
        return keys_0, keys_1

    def eval(self, key, x: int) -> List[int]:
        """Host per-point evaluation (reference-parity DCF walks): this
        party's output shares for one masked input."""
        self._check_masked_inputs([x])
        n = self.n
        dcf_keys, shares = self._key_parts(key)
        pts = self._points(int(x))
        t = self.payload_elems
        vals = np.zeros((self.num_components * t, self.num_sites), dtype=object)
        for c, dk in enumerate(dcf_keys):
            for s, pt in enumerate(pts):
                v = self._dcf.evaluate(dk, pt)
                if isinstance(v, tuple):  # vector payload: t rows per key
                    for e, ve in enumerate(v):
                        vals[c * t + e, s] = int(ve) % n
                else:
                    vals[c, s] = v % n
        return self._combine_one(dcf_keys[0].key.party, shares, int(x), vals)

    @_tm.traced("gate.batch_eval")
    def batch_eval(
        self, key, xs: Sequence[int], engine: str = "device",
        **device_kwargs,
    ) -> np.ndarray:
        """Fused evaluation of a batch of masked inputs: ONE batched-DCF
        pass over (num_components keys) x (num_sites * len(xs) points),
        on the device (engine="device") or the native AES-NI host engine
        (engine="host"; the gates' Int(128) payloads ride the two-word
        wide kernel). ``device_kwargs`` pass through to the DCF device
        path (notably ``mode="walkkernel"``: the whole gate evaluation
        becomes ONE walk-megakernel program). Returns an object ndarray
        [len(xs), num_outputs] of share values."""
        plan = GatePlan.build(self, xs)
        dcf_keys, _ = self._key_parts(key)
        values = plan.evaluate(dcf_keys, engine=engine, **device_kwargs)
        return plan.combine(key, values)


def bundle_eval(
    gate: MaskedGate,
    keys: Sequence,
    xs: Sequence[int],
    engine: str = "device",
    **device_kwargs,
) -> np.ndarray:
    """Evaluates key ``b`` on input ``xs[b]`` for a whole bundle in ONE
    fused batched-DCF pass — the secure-ML inference shape (one
    independent mask and key pair per activation, one device program for
    the layer; examples/secure_relu_demo.py). All keys must come from
    ``gate``'s dealer (same party, same component DCF).

    The pass evaluates every bundled component key at every bundled
    input's sites and the combine slices each key's own block — a
    len(keys)-factor compute waste that buys ONE uniform program instead
    of len(keys) dispatches (PERF.md "FSS gate family"). Returns
    [len(keys), num_outputs] share values."""
    if len(keys) != len(xs):
        raise InvalidArgumentError(
            f"bundle_eval needs one key per input, got {len(keys)} keys "
            f"for {len(xs)} inputs"
        )
    if not keys:
        return np.zeros((0, gate.num_outputs), dtype=object)
    plan = GatePlan.build(gate, xs)
    c = gate.num_components
    s = gate.num_sites
    all_dcf: List[DcfKey] = []
    party0: Optional[int] = None
    for b, key in enumerate(keys):
        dcf_keys, _ = gate._key_parts(key)
        if len(dcf_keys) != c:
            raise InvalidArgumentError(
                f"bundle key {b} has {len(dcf_keys)} component DCF keys, "
                f"the gate declares {c}"
            )
        if party0 is None:
            party0 = dcf_keys[0].key.party
        elif dcf_keys[0].key.party != party0:
            raise InvalidArgumentError(
                f"bundle key {b} belongs to party "
                f"{dcf_keys[0].key.party}, key 0 to party {party0} — a "
                "bundle is ONE party's keys (mixing parties would "
                "reconstruct garbage, not raise)"
            )
        all_dcf.extend(dcf_keys)
    values = _flatten_payload(plan.evaluate(all_dcf, engine=engine, **device_kwargs))
    n = gate.n
    party = all_dcf[0].key.party
    rows = c * gate.payload_elems
    out = np.zeros((len(keys), gate.num_outputs), dtype=object)
    for b, (key, x) in enumerate(zip(keys, plan.xs)):
        _, shares = gate._key_parts(key)
        vals = values[b * rows : (b + 1) * rows, b * s : (b + 1) * s] % n
        out[b] = gate._combine_one(party, shares, x, vals)
    return out
