"""Multiple Interval Containment FSS gate.

Re-design of the reference's MultipleIntervalContainmentGate
(/root/reference/dcf/fss_gates/multiple_interval_containment.{h,cc}),
following BCG+ (eprint 2020/1392) Fig. 14: for m public intervals [p_i, q_i]
and a masked input x = x_real + r_in, the two parties obtain additive shares
(mod N = 2^log_group_size) of [x_real in [p_i, q_i]] for every i.

* ``gen(r_in, r_outs[])`` (.cc:104-204): one DCF key pair at
  alpha = r_in - 1 mod N with beta = 1, plus per interval an additively
  shared correction term z derived from the mask wraparounds (Lemma 1-2).
* ``eval(key, x)`` (.cc:206-275): per interval two DCF evaluations at
  x - 1 - p_i and x - 1 - q_i' (q' = q+1), plus mask arithmetic mod N.

All mod-N arithmetic is exact on Python ints; since N divides 2^128 the
reference's wrap-then-reduce uint128 arithmetic agrees with reducing the
integer expression directly.

Since ISSUE 9 the gate is the founding member of the gate *framework*
(gates/framework.py): its wraparound algebra lives in the shared
interval-containment helpers (``ic_points`` / ``ic_wrap_count`` /
``ic_public_term`` / ``ic_share``), and ``gen`` / ``eval`` /
``batch_eval`` are the framework templates — ``batch_eval`` flattens
(points x intervals x {p, q'}) through the shared :class:`GatePlan` into
ONE fused batched-DCF pass (dcf/batch.py; the reference walks the DCF
tree 2m times per input from the root, each walk itself O(n^2) AES).
``MicKey`` keeps its reference-proto shape (one DCF key + the per-interval
mask shares) for wire compatibility.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence, Tuple

import numpy as np

from ..dcf.dcf import DcfKey
from ..utils.errors import InvalidArgumentError
from . import framework


@dataclasses.dataclass
class MicKey:
    """One party's MIC key: DCF key + per-interval output mask share.

    Mirrors the MicKey proto
    (/root/reference/dcf/fss_gates/multiple_interval_containment.proto:36-44).
    """

    dcf_key: DcfKey
    output_mask_shares: List[int]


class MultipleIntervalContainmentGate(framework.MaskedGate):
    def __init__(self, log_group_size: int, intervals: List[Tuple[int, int]], dcf):
        super().__init__(log_group_size, dcf, num_outputs=len(intervals))
        self.intervals = intervals

    @classmethod
    def create(
        cls, log_group_size: int, intervals: Sequence[Tuple[int, int]]
    ) -> "MultipleIntervalContainmentGate":
        if log_group_size < 0 or log_group_size > 127:
            raise InvalidArgumentError("log_group_size should be in > 0 and < 128")
        n = 1 << log_group_size
        for p, q in intervals:
            if not (0 <= p < n and 0 <= q < n):
                raise InvalidArgumentError(
                    "Interval bounds should be between 0 and 2^log_group_size"
                )
            if p > q:
                raise InvalidArgumentError(
                    "Interval upper bounds should be >= lower bound"
                )
        dcf = cls._create_dcf(log_group_size)
        return cls(log_group_size, [(int(p), int(q)) for p, q in intervals], dcf)

    # -- framework contract ------------------------------------------------
    @property
    def num_components(self) -> int:
        return 1

    @property
    def num_sites(self) -> int:
        return 2 * len(self.intervals)

    def config_signature(self) -> tuple:
        return (tuple(self.intervals),)

    def _component_specs(self, r_in: int) -> List[Tuple[int, int]]:
        return [(framework.ic_alpha(self.n, r_in), 1)]

    def _mask_values(self, r_in: int, r_outs: Sequence[int]) -> List[int]:
        n = self.n
        return [
            (r_out + framework.ic_wrap_count(n, r_in, p, q)) % n
            for (p, q), r_out in zip(self.intervals, r_outs)
        ]

    def _points(self, x: int) -> List[int]:
        n = self.n
        pts: List[int] = []
        for p, q in self.intervals:
            pts.extend(framework.ic_points(n, x, p, q))
        return pts

    def _combine_one(
        self, party: int, shares: Sequence[int], x: int, vals: np.ndarray
    ) -> List[int]:
        n = self.n
        return [
            framework.ic_share(
                n,
                framework.ic_public_term(n, x, p, q),
                party,
                int(vals[0, 2 * i]),
                int(vals[0, 2 * i + 1]),
                shares[i],
            )
            for i, (p, q) in enumerate(self.intervals)
        ]

    def _make_key(self, dcf_keys: List[DcfKey], shares: List[int]) -> MicKey:
        return MicKey(dcf_keys[0], shares)

    def _key_parts(self, key: MicKey) -> Tuple[List[DcfKey], List[int]]:
        return [key.dcf_key], key.output_mask_shares

    # -- reference-shaped surface (kept for tests/serialization callers) ---
    def _eval_points(self, x: int) -> List[int]:
        """The 2m DCF evaluation points for one masked input."""
        return self._points(int(x))

    def _combine(self, key: MicKey, x: int, s_p: int, s_q_prime: int, i: int) -> int:
        n = self.n
        p, q = self.intervals[i]
        return framework.ic_share(
            n,
            framework.ic_public_term(n, x, p, q),
            key.dcf_key.key.party,
            s_p,
            s_q_prime,
            key.output_mask_shares[i],
        )

    def _combine_batch(
        self, key: MicKey, xs: Sequence[int], values
    ) -> np.ndarray:
        """mod-N combine of a flat (points x intervals x {p, q'}) DCF
        value vector back into per-(input, interval) shares — the
        single-component form of :meth:`GatePlan.combine`, kept for
        callers holding the flat one-key value layout."""
        plan = framework.GatePlan.build(self, xs)
        return plan.combine(key, np.asarray(values, dtype=object)[None, :])

    # gen / eval / batch_eval are the framework templates
    # (framework.MaskedGate): gen's draw order — one rand128 per interval
    # after the single DCF keygen — matches the pre-framework
    # implementation bit for bit (pinned by the golden-key test).
