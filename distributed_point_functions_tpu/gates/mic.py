"""Multiple Interval Containment FSS gate.

Re-design of the reference's MultipleIntervalContainmentGate
(/root/reference/dcf/fss_gates/multiple_interval_containment.{h,cc}),
following BCG+ (eprint 2020/1392) Fig. 14: for m public intervals [p_i, q_i]
and a masked input x = x_real + r_in, the two parties obtain additive shares
(mod N = 2^log_group_size) of [x_real in [p_i, q_i]] for every i.

* ``gen(r_in, r_out[])`` (.cc:104-204): one DCF key pair at
  alpha = r_in - 1 mod N with beta = 1, plus per interval an additively
  shared correction term z derived from the mask wraparounds (Lemma 1-2).
* ``eval(key, x)`` (.cc:206-275): per interval two DCF evaluations at
  x - 1 - p_i and x - 1 - q_i' (q' = q+1), plus mask arithmetic mod N.

All mod-N arithmetic is exact on Python ints; since N divides 2^128 the
reference's wrap-then-reduce uint128 arithmetic agrees with reducing the
integer expression directly.

TPU path: ``batch_eval`` flattens (points x intervals x {p, q'}) into ONE
fused batched DCF pass (dcf/batch.py) — the reference walks the DCF tree
2 * m times per input from the root, each walk itself O(n^2) AES; here the
whole gate evaluation is a single O(n)-depth scan over a packed lane batch.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..core.value_types import Int
from ..dcf.dcf import DcfKey, DistributedComparisonFunction
from ..ops import evaluator
from ..utils import telemetry as _tm
from ..utils.errors import InvalidArgumentError
from .prng import BasicRng, SecurePrng


@dataclasses.dataclass
class MicKey:
    """One party's MIC key: DCF key + per-interval output mask share.

    Mirrors the MicKey proto
    (/root/reference/dcf/fss_gates/multiple_interval_containment.proto:36-44).
    """

    dcf_key: DcfKey
    output_mask_shares: List[int]


class MultipleIntervalContainmentGate:
    def __init__(self, log_group_size: int, intervals: List[Tuple[int, int]], dcf):
        self.log_group_size = log_group_size
        self.intervals = intervals
        self._dcf = dcf

    @classmethod
    def create(
        cls, log_group_size: int, intervals: Sequence[Tuple[int, int]]
    ) -> "MultipleIntervalContainmentGate":
        if log_group_size < 0 or log_group_size > 127:
            raise InvalidArgumentError("log_group_size should be in > 0 and < 128")
        n = 1 << log_group_size
        for p, q in intervals:
            if not (0 <= p < n and 0 <= q < n):
                raise InvalidArgumentError(
                    "Interval bounds should be between 0 and 2^log_group_size"
                )
            if p > q:
                raise InvalidArgumentError(
                    "Interval upper bounds should be >= lower bound"
                )
        dcf = DistributedComparisonFunction.create(log_group_size, Int(128))
        return cls(log_group_size, [(int(p), int(q)) for p, q in intervals], dcf)

    @property
    def dcf(self) -> DistributedComparisonFunction:
        return self._dcf

    def gen(
        self,
        r_in: int,
        r_outs: Sequence[int],
        prng: Optional[SecurePrng] = None,
        dcf_seeds=None,
    ) -> Tuple[MicKey, MicKey]:
        """Key pair for masks r_in / r_outs. `prng` supplies the mask-share
        randomness (SecurePrng analog, prng.h:26-36); `dcf_seeds` optionally
        pins the inner DCF keygen seeds — together they make `gen` fully
        deterministic for golden-key tests."""
        if prng is None:
            prng = BasicRng()
        n = 1 << self.log_group_size
        if len(r_outs) != len(self.intervals):
            raise InvalidArgumentError(
                "Count of output masks should be equal to the number of intervals"
            )
        if not 0 <= r_in < n:
            raise InvalidArgumentError(
                "Input mask should be between 0 and 2^log_group_size"
            )
        for r in r_outs:
            if not 0 <= r < n:
                raise InvalidArgumentError(
                    "Output mask should be between 0 and 2^log_group_size"
                )

        gamma = (n - 1 + r_in) % n
        key_0, key_1 = self._dcf.generate_keys(gamma, 1, seeds=dcf_seeds)
        shares_0, shares_1 = [], []
        for (p, q), r_out in zip(self.intervals, r_outs):
            q_prime = (q + 1) % n
            alpha_p = (p + r_in) % n
            alpha_q = (q + r_in) % n
            alpha_q_prime = (q + 1 + r_in) % n
            z = (
                r_out
                + (1 if alpha_p > alpha_q else 0)
                - (1 if alpha_p > p else 0)
                + (1 if alpha_q_prime > q_prime else 0)
                + (1 if alpha_q == n - 1 else 0)
            ) % n
            z_0 = prng.rand128() % n
            z_1 = (z - z_0) % n
            shares_0.append(z_0)
            shares_1.append(z_1)
        return MicKey(key_0, shares_0), MicKey(key_1, shares_1)

    def _eval_points(self, x: int) -> List[int]:
        """The 2m DCF evaluation points for one masked input."""
        n = 1 << self.log_group_size
        points = []
        for p, q in self.intervals:
            q_prime = (q + 1) % n
            points.append((x + n - 1 - p) % n)
            points.append((x + n - 1 - q_prime) % n)
        return points

    def _check_masked_inputs(self, xs: Sequence[int]) -> None:
        """Input validation shared by batch_eval and the supervisor's
        robust wrapper (ops/supervisor.mic_batch_eval_robust)."""
        n = 1 << self.log_group_size
        for x in xs:
            if not 0 <= x < n:
                raise InvalidArgumentError(
                    "Masked input should be between 0 and 2^log_group_size"
                )

    def _combine_batch(
        self, key: MicKey, xs: Sequence[int], values
    ) -> np.ndarray:
        """mod-N combine of a flat (points x intervals x {p, q'}) DCF
        value vector back into per-(input, interval) shares — the single
        owner of the 2m-stride layout, shared by batch_eval and the
        robust wrapper so the point packing cannot drift between them."""
        n = 1 << self.log_group_size
        m = len(self.intervals)
        out = np.zeros((len(xs), m), dtype=object)
        for xi, x in enumerate(xs):
            for i in range(m):
                s_p = int(values[2 * m * xi + 2 * i]) % n
                s_q_prime = int(values[2 * m * xi + 2 * i + 1]) % n
                out[xi, i] = self._combine(key, int(x), s_p, s_q_prime, i)
        return out

    def _combine(self, key: MicKey, x: int, s_p: int, s_q_prime: int, i: int) -> int:
        n = 1 << self.log_group_size
        p, q = self.intervals[i]
        q_prime = (q + 1) % n
        party_term = 0
        if key.dcf_key.key.party:
            party_term = (1 if x > p else 0) - (1 if x > q_prime else 0)
        return (party_term - s_p + s_q_prime + key.output_mask_shares[i]) % n

    def eval(self, key: MicKey, x: int) -> List[int]:
        """Host evaluation: shares of [x - r_in in interval i] for each i."""
        n = 1 << self.log_group_size
        if not 0 <= x < n:
            raise InvalidArgumentError(
                "Masked input should be between 0 and 2^log_group_size"
            )
        points = self._eval_points(x)
        res = []
        for i in range(len(self.intervals)):
            s_p = self._dcf.evaluate(key.dcf_key, points[2 * i]) % n
            s_q_prime = self._dcf.evaluate(key.dcf_key, points[2 * i + 1]) % n
            res.append(self._combine(key, x, s_p, s_q_prime, i))
        return res

    @_tm.traced("mic.batch_eval")
    def batch_eval(
        self, key: MicKey, xs: Sequence[int], engine: str = "device",
        **device_kwargs,
    ) -> np.ndarray:
        """Fused evaluation of all intervals for a batch of masked inputs.

        One fused DCF pass over len(xs) * 2m lanes — on the device
        (engine="device") or the native AES-NI host engine (engine="host";
        the gate's Int(128) values ride the two-word wide kernel). Returns
        an object ndarray [len(xs), m] of share values mod N.
        `device_kwargs` pass through to the DCF device path (notably
        mode="walkkernel": the whole gate evaluation — every interval's
        two comparison walks — becomes ONE walk-megakernel program).
        """
        self._check_masked_inputs(xs)
        all_points: List[int] = []
        for x in xs:
            all_points.extend(self._eval_points(int(x)))
        evals = self._dcf.batch_evaluate(
            [key.dcf_key], all_points, engine=engine, **device_kwargs
        )
        if engine == "host":  # uint64[1, P, 2] (lo, hi) pairs
            values = (
                evals[0, :, 0].astype(object)
                | (evals[0, :, 1].astype(object) << 64)
            )
        else:
            values = evaluator.values_to_numpy(evals, 128)[0]  # [len(xs)*2m]
        return self._combine_batch(key, xs, values)
