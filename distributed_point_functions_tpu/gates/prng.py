"""Secure PRNG interface for gate key generation.

Analog of the reference's SecurePrng interface and BasicRng implementation
(/root/reference/dcf/fss_gates/prng/{prng.h:26-36,basic_rng.h:32-70}): gate
keygen draws its randomness through this interface so tests can inject a
deterministic stream and pin golden keys. Randomness never runs on the
device — mask sampling is host-side by design (SURVEY.md L5/"SecurePrng").
"""

from __future__ import annotations

import abc
import hashlib
import secrets


class SecurePrng(abc.ABC):
    """8/64/128-bit draws, mirroring SecurePrng's Rand8/Rand64/Rand128."""

    @abc.abstractmethod
    def rand8(self) -> int:
        ...

    @abc.abstractmethod
    def rand64(self) -> int:
        ...

    @abc.abstractmethod
    def rand128(self) -> int:
        ...


class BasicRng(SecurePrng):
    """OS CSPRNG (secrets.token_bytes), the reference's RAND_bytes analog."""

    def rand8(self) -> int:
        return secrets.token_bytes(1)[0]

    def rand64(self) -> int:
        return int.from_bytes(secrets.token_bytes(8), "little")

    def rand128(self) -> int:
        return int.from_bytes(secrets.token_bytes(16), "little")


class CounterRng(SecurePrng):
    """Deterministic SHA256-counter stream for tests and golden fixtures."""

    def __init__(self, seed: bytes = b""):
        self._seed = seed
        self._counter = 0

    def _draw(self, nbytes: int) -> bytes:
        out = hashlib.sha256(
            self._seed + self._counter.to_bytes(8, "little")
        ).digest()
        self._counter += 1
        return out[:nbytes]

    def rand8(self) -> int:
        return self._draw(1)[0]

    def rand64(self) -> int:
        return int.from_bytes(self._draw(8), "little")

    def rand128(self) -> int:
        return int.from_bytes(self._draw(16), "little")
