"""DReLU / ReLU FSS gates — the secure-ML activation pair (BCG+ eprint
2020/1392 §4.1/4.4; two's-complement signed convention over Z_N).

DReLU (the comparison gate): additive shares mod N of
``1{x_real >= 0}`` — with values in [0, N) read as two's-complement
signed, that is the single interval containment ``x_real in [0, N/2-1]``,
so the gate is one framework interval-containment instance: ONE component
DCF key with payload 1, two evaluation sites per input. The derivative of
ReLU, and the comparison primitive ``[a < b]`` via x_real = a - b.

ReLU: additive shares mod N of ``max(x_real, 0)`` (signed). Exactly the
two-piece degree-1 spline ``[0, N/2-1] -> X``, ``[N/2, N-1] -> 0``, so
:class:`ReluGate` is a :class:`~.spline.SplineGate` factory — the gate
the framework exists to make free. On the default vector payload: ONE
component key carrying all 4 coefficients, 4 sites per input, one fused
batched-DCF pass (``payload="scalar"`` keeps the 4-key oracle layout).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..utils.errors import InvalidArgumentError
from . import framework
from .spline import SplineGate


class DReluGate(framework.MaskedGate):
    """Shares of the ReLU derivative 1{x_real >= 0 (signed)}, + r_out."""

    def __init__(self, log_group_size: int, dcf):
        super().__init__(log_group_size, dcf, num_outputs=1)
        if log_group_size < 2:
            raise InvalidArgumentError(
                "DReLU needs log_group_size >= 2 (a sign bit and at least "
                "one magnitude bit)"
            )
        n = 1 << log_group_size
        #: the non-negative half of the signed range.
        self.interval: Tuple[int, int] = (0, n // 2 - 1)

    @classmethod
    def create(cls, log_group_size: int) -> "DReluGate":
        return cls(log_group_size, cls._create_dcf(log_group_size))

    # -- framework contract ------------------------------------------------
    @property
    def num_components(self) -> int:
        return 1

    @property
    def num_sites(self) -> int:
        return 2

    def _component_specs(self, r_in: int) -> List[Tuple[int, int]]:
        return [(framework.ic_alpha(self.n, r_in), 1)]

    def _mask_values(self, r_in: int, r_outs: Sequence[int]) -> List[int]:
        p, q = self.interval
        c = framework.ic_wrap_count(self.n, r_in, p, q)
        return [(r_outs[0] + c) % self.n]

    def _points(self, x: int) -> List[int]:
        p, q = self.interval
        return list(framework.ic_points(self.n, x, p, q))

    def _combine_one(
        self, party: int, shares: Sequence[int], x: int, vals: np.ndarray
    ) -> List[int]:
        p, q = self.interval
        pub = framework.ic_public_term(self.n, x, p, q)
        return [
            framework.ic_share(
                self.n, pub, party, int(vals[0, 0]), int(vals[0, 1]),
                shares[0],
            )
        ]


class ReluGate(SplineGate):
    """Shares of max(x_real, 0) (signed), + r_out: the fixed two-piece
    degree-1 spline. ``signed_lift``/``to_signed`` convert between the
    signed plaintext domain and the gate's Z_N representation."""

    @classmethod
    def create(
        cls, log_group_size: int, payload: Optional[str] = None
    ) -> "ReluGate":  # noqa: D417
        if log_group_size < 2:
            raise InvalidArgumentError(
                "ReLU needs log_group_size >= 2 (a sign bit and at least "
                "one magnitude bit)"
            )
        n = 1 << log_group_size
        return super().create(
            log_group_size,
            intervals=[(0, n // 2 - 1), (n // 2, n - 1)],
            coefficients=[[0, 1], [0, 0]],
            payload=payload,
        )

    # -- signed-domain helpers (demo/test convenience) ---------------------
    def signed_lift(self, v: int) -> int:
        """Signed integer in [-N/2, N/2) -> its Z_N representative."""
        n = self.n
        if not -(n // 2) <= v < n // 2:
            raise InvalidArgumentError(
                f"value {v} outside the signed range [-{n // 2}, {n // 2})"
            )
        return v % n

    def to_signed(self, v: int) -> int:
        """Z_N representative -> signed integer in [-N/2, N/2)."""
        n = self.n
        v = int(v) % n
        return v - n if v >= n // 2 else v
