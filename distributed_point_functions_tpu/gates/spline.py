"""Spline / piecewise-polynomial FSS gate (BCG+ eprint 2020/1392 §4).

For public intervals [p_i, q_i] and public polynomial coefficients
``a_{i,0..d}``, the parties obtain additive shares (mod N) of
``sum_{i : x_real in [p_i, q_i]} p_i(x_real)`` + r_out for the masked
input x = x_real + r_in — the fixed-point math workhorse (piecewise
approximations of sigmoid/tanh/reciprocal, and ReLU exactly).

Construction (validated exhaustively in tests/test_gates_framework.py):
the dealer expands each piece's *shifted* polynomial
``p_i^r(X) = p_i(X - r_in) mod N`` — evaluating it at the public masked
input x gives ``p_i(x_real)`` exactly — and must deliver shares of the
coefficient vector of the *active* piece. That is interval containment
with payload ``w_{i,j} = coeff_j(p_i^r)``: component DCF key (i, j)
carries ``beta = w_{i,j}`` at the shared threshold ``alpha = r_in - 1``,
and the MIC combine algebra, linear in the payload, reconstructs
``1{x_real in [p_i, q_i]} * w_{i,j}`` (the public comparison term is
multiplied by dealer-provided *shares* of w, since w depends on r_in).
Summing over i and evaluating at x yields the result.

BCG+ express the same gate as ONE DCF with a vector payload in
G^{m(d+1)}; this framework deliberately flattens the vector into
m(d+1) scalar Int(128) component keys instead, so the gate rides the
exact fused batched-DCF program family MIC compiles (walk and
walkkernel) — trading ~m(d+1)x key-tree material and an m-factor
evaluation waste (each component is evaluated at every interval's sites)
for zero new kernel shapes. PERF.md "FSS gate family" carries the
accounting.

Key layout (``GateKey.mask_shares``): ``[w shares (m*(d+1))] +
[z shares (m*(d+1), z_{i,j} = wrap_count_i * w_{i,j})] + [r_out share]``.
"""

from __future__ import annotations

from math import comb
from typing import List, Sequence, Tuple

import numpy as np

from ..utils.errors import InvalidArgumentError
from . import framework


class SplineGate(framework.MaskedGate):
    """Piecewise-polynomial evaluation over Z_{2^log_group_size}."""

    def __init__(self, log_group_size, intervals, coefficients, dcf):
        super().__init__(log_group_size, dcf, num_outputs=1)
        self.intervals = intervals
        self.coefficients = coefficients
        self.degree = len(coefficients[0]) - 1

    @classmethod
    def create(
        cls,
        log_group_size: int,
        intervals: Sequence[Tuple[int, int]],
        coefficients: Sequence[Sequence[int]],
    ) -> "SplineGate":
        """`coefficients[i][j]` is piece i's coefficient of X^j (mod N);
        all pieces must share one degree (pad with zeros). Intervals are
        validated in-range; they need not partition the domain — an
        uncovered x_real evaluates to 0, overlapping pieces sum."""
        dcf = cls._create_dcf(log_group_size)
        n = 1 << log_group_size
        if not intervals:
            raise InvalidArgumentError("A spline needs at least one interval")
        if len(coefficients) != len(intervals):
            raise InvalidArgumentError(
                "Count of coefficient vectors should be equal to the "
                "number of intervals"
            )
        d = len(coefficients[0]) - 1
        if d < 0:
            raise InvalidArgumentError("Coefficient vectors cannot be empty")
        for cs in coefficients:
            if len(cs) != d + 1:
                raise InvalidArgumentError(
                    "All pieces must share one polynomial degree "
                    "(zero-pad shorter coefficient vectors)"
                )
        for p, q in intervals:
            if not (0 <= p < n and 0 <= q < n):
                raise InvalidArgumentError(
                    "Interval bounds should be between 0 and 2^log_group_size"
                )
            if p > q:
                raise InvalidArgumentError(
                    "Interval upper bounds should be >= lower bound"
                )
        return cls(
            log_group_size,
            [(int(p), int(q)) for p, q in intervals],
            [[int(c) % n for c in cs] for cs in coefficients],
            dcf,
        )

    # -- framework contract ------------------------------------------------
    def config_signature(self) -> tuple:
        return (
            tuple(self.intervals),
            tuple(tuple(cs) for cs in self.coefficients),
        )

    @property
    def num_components(self) -> int:
        return len(self.intervals) * (self.degree + 1)

    @property
    def num_sites(self) -> int:
        return 2 * len(self.intervals)

    def _shifted_coefficients(self, r_in: int) -> List[List[int]]:
        """w_{i,j} = coeff_j of p_i(X - r_in) mod N (binomial expansion,
        exact Python ints)."""
        n = self.n
        out = []
        for cs in self.coefficients:
            w = [0] * (self.degree + 1)
            for k, a in enumerate(cs):
                for j in range(k + 1):
                    w[j] = (w[j] + a * comb(k, j) * pow(-r_in, k - j, n)) % n
            out.append(w)
        return out

    def _component_specs(self, r_in: int) -> List[Tuple[int, int]]:
        alpha = framework.ic_alpha(self.n, r_in)
        return [
            (alpha, w)
            for ws in self._shifted_coefficients(r_in)
            for w in ws
        ]

    def _mask_values(self, r_in: int, r_outs: Sequence[int]) -> List[int]:
        n = self.n
        shifted = self._shifted_coefficients(r_in)
        ws = [w for piece in shifted for w in piece]
        zs = []
        for i, (p, q) in enumerate(self.intervals):
            c = framework.ic_wrap_count(n, r_in, p, q)
            zs.extend((c * w) % n for w in shifted[i])
        return ws + zs + [r_outs[0] % n]

    def _points(self, x: int) -> List[int]:
        n = self.n
        pts: List[int] = []
        for p, q in self.intervals:
            pts.extend(framework.ic_points(n, x, p, q))
        return pts

    def _combine_one(
        self, party: int, shares: Sequence[int], x: int, vals: np.ndarray
    ) -> List[int]:
        n = self.n
        k = self.num_components
        w_sh = shares[:k]
        z_sh = shares[k : 2 * k]
        y = shares[2 * k]  # r_out share
        for i, (p, q) in enumerate(self.intervals):
            pub = framework.ic_public_term(n, x, p, q)
            for j in range(self.degree + 1):
                ci = i * (self.degree + 1) + j
                cshare = framework.ic_share(
                    n, pub, w_sh[ci],
                    int(vals[ci, 2 * i]), int(vals[ci, 2 * i + 1]),
                    z_sh[ci],
                )
                y = (y + cshare * pow(x, j, n)) % n
        return [y]
