"""Spline / piecewise-polynomial FSS gate (BCG+ eprint 2020/1392 §4).

For public intervals [p_i, q_i] and public polynomial coefficients
``a_{i,0..d}``, the parties obtain additive shares (mod N) of
``sum_{i : x_real in [p_i, q_i]} p_i(x_real)`` + r_out for the masked
input x = x_real + r_in — the fixed-point math workhorse (piecewise
approximations of sigmoid/tanh/reciprocal, and ReLU exactly).

Construction (validated exhaustively in tests/test_gates_framework.py):
the dealer expands each piece's *shifted* polynomial
``p_i^r(X) = p_i(X - r_in) mod N`` — evaluating it at the public masked
input x gives ``p_i(x_real)`` exactly — and must deliver shares of the
coefficient vector of the *active* piece. That is interval containment
with payload ``w_{i,j} = coeff_j(p_i^r)``: the coefficient's DCF payload
carries ``beta = w_{i,j}`` at the shared threshold ``alpha = r_in - 1``,
and the MIC combine algebra, linear in the payload, reconstructs
``1{x_real in [p_i, q_i]} * w_{i,j}`` (the public comparison term is
multiplied by dealer-provided *shares* of w, since w depends on r_in).
Summing over i and evaluating at x yields the result.

Payload layouts (``payload="vector"|"scalar"``, DPF_TPU_GATE_PAYLOAD
default "vector"): BCG+ express the gate as ONE DCF with a vector payload
in G^{m(d+1)} — every shifted coefficient shares the single threshold
``alpha = r_in - 1``, so one ``TupleType(Int(128) x m(d+1))`` key carries
them all and ONE fused walk per site captures the whole coefficient
vector (dcf/batch.py widens only the value-capture tail). Key material,
dealer keygen, and DCF walks per gate eval all drop m(d+1)x vs the
"scalar" layout, which flattens to m(d+1) scalar Int(128) component keys
(PR 9's recorded tradeoff — kept as the selectable oracle path; PERF.md
"FSS gate family" carries the before/after accounting).

Key layout (``GateKey.mask_shares``, identical in both payloads):
``[w shares (m*(d+1))] + [z shares (m*(d+1), z_{i,j} = wrap_count_i *
w_{i,j})] + [r_out share]``.

:class:`SigmoidGate` / :class:`TanhGate` are the wide-spline case the
vector codec exists for: 8-16 piece degree-1 chord approximations in
fixed point, one key instead of 16-32.
"""

from __future__ import annotations

import math
from math import comb
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..utils.errors import InvalidArgumentError
from . import framework


class SplineGate(framework.MaskedGate):
    """Piecewise-polynomial evaluation over Z_{2^log_group_size}."""

    def __init__(self, log_group_size, intervals, coefficients, dcf, payload):
        super().__init__(log_group_size, dcf, num_outputs=1)
        self.intervals = intervals
        self.coefficients = coefficients
        self.degree = len(coefficients[0]) - 1
        self.payload = payload

    @classmethod
    def create(
        cls,
        log_group_size: int,
        intervals: Sequence[Tuple[int, int]],
        coefficients: Sequence[Sequence[int]],
        payload: Optional[str] = None,
    ) -> "SplineGate":
        """`coefficients[i][j]` is piece i's coefficient of X^j (mod N);
        all pieces must share one degree (pad with zeros). Intervals are
        validated in-range; they need not partition the domain — an
        uncovered x_real evaluates to 0, overlapping pieces sum.
        ``payload`` picks the component-key layout (None = the
        DPF_TPU_GATE_PAYLOAD env, default "vector")."""
        payload = framework.resolve_payload(payload)
        n = 1 << log_group_size
        if not intervals:
            raise InvalidArgumentError("A spline needs at least one interval")
        if len(coefficients) != len(intervals):
            raise InvalidArgumentError(
                "Count of coefficient vectors should be equal to the "
                "number of intervals"
            )
        d = len(coefficients[0]) - 1
        if d < 0:
            raise InvalidArgumentError("Coefficient vectors cannot be empty")
        for cs in coefficients:
            if len(cs) != d + 1:
                raise InvalidArgumentError(
                    "All pieces must share one polynomial degree "
                    "(zero-pad shorter coefficient vectors)"
                )
        for p, q in intervals:
            if not (0 <= p < n and 0 <= q < n):
                raise InvalidArgumentError(
                    "Interval bounds should be between 0 and 2^log_group_size"
                )
            if p > q:
                raise InvalidArgumentError(
                    "Interval upper bounds should be >= lower bound"
                )
        num_coeffs = len(intervals) * (d + 1)
        dcf = cls._create_dcf(
            log_group_size, num_coeffs if payload == "vector" else 1
        )
        return cls(
            log_group_size,
            [(int(p), int(q)) for p, q in intervals],
            [[int(c) % n for c in cs] for cs in coefficients],
            dcf,
            payload,
        )

    # -- framework contract ------------------------------------------------
    def config_signature(self) -> tuple:
        # The payload token keeps scalar and vector requests for the same
        # spline in DIFFERENT serving compatibility queues: their DCF key
        # layouts (and so the fused pass shapes) are incompatible.
        return (
            tuple(self.intervals),
            tuple(tuple(cs) for cs in self.coefficients),
            self.payload,
        )

    @property
    def num_coeffs(self) -> int:
        """m*(d+1) shifted-polynomial coefficients — the combine algebra's
        row count, whichever payload layout carried them."""
        return len(self.intervals) * (self.degree + 1)

    @property
    def num_components(self) -> int:
        return 1 if self.payload == "vector" else self.num_coeffs

    @property
    def payload_elems(self) -> int:
        # A 1-coefficient vector gate degenerates to the scalar layout
        # (framework._create_dcf builds the plain Int(128) DCF for it), so
        # its keys stay byte-identical to scalar keys on the wire.
        if self.payload == "vector" and self.num_coeffs > 1:
            return self.num_coeffs
        return 1

    @property
    def num_sites(self) -> int:
        return 2 * len(self.intervals)

    def _shifted_coefficients(self, r_in: int) -> List[List[int]]:
        """w_{i,j} = coeff_j of p_i(X - r_in) mod N (binomial expansion,
        exact Python ints)."""
        n = self.n
        out = []
        for cs in self.coefficients:
            w = [0] * (self.degree + 1)
            for k, a in enumerate(cs):
                for j in range(k + 1):
                    w[j] = (w[j] + a * comb(k, j) * pow(-r_in, k - j, n)) % n
            out.append(w)
        return out

    def _component_specs(self, r_in: int) -> List[Tuple[int, int]]:
        alpha = framework.ic_alpha(self.n, r_in)
        ws = [w for piece in self._shifted_coefficients(r_in) for w in piece]
        if self.payload_elems > 1:
            return [(alpha, tuple(ws))]  # ONE key, all coefficients
        return [(alpha, w) for w in ws]

    def _mask_values(self, r_in: int, r_outs: Sequence[int]) -> List[int]:
        n = self.n
        shifted = self._shifted_coefficients(r_in)
        ws = [w for piece in shifted for w in piece]
        zs = []
        for i, (p, q) in enumerate(self.intervals):
            c = framework.ic_wrap_count(n, r_in, p, q)
            zs.extend((c * w) % n for w in shifted[i])
        return ws + zs + [r_outs[0] % n]

    def _points(self, x: int) -> List[int]:
        n = self.n
        pts: List[int] = []
        for p, q in self.intervals:
            pts.extend(framework.ic_points(n, x, p, q))
        return pts

    def _combine_one(
        self, party: int, shares: Sequence[int], x: int, vals: np.ndarray
    ) -> List[int]:
        n = self.n
        k = self.num_coeffs
        w_sh = shares[:k]
        z_sh = shares[k : 2 * k]
        y = shares[2 * k]  # r_out share
        for i, (p, q) in enumerate(self.intervals):
            pub = framework.ic_public_term(n, x, p, q)
            for j in range(self.degree + 1):
                ci = i * (self.degree + 1) + j
                cshare = framework.ic_share(
                    n, pub, w_sh[ci],
                    int(vals[ci, 2 * i]), int(vals[ci, 2 * i + 1]),
                    z_sh[ci],
                )
                y = (y + cshare * pow(x, j, n)) % n
        return [y]

    def plaintext(self, x_real: int) -> int:
        """The gate's exact plaintext function at a raw domain point: the
        sum of the active pieces' polynomials mod N — what a two-server
        reconstruction must equal bit-for-bit (the exact-int oracle the
        payload A/B tests and the supervisor spot checks compare
        against)."""
        n = self.n
        x = int(x_real) % n
        y = 0
        for (p, q), cs in zip(self.intervals, self.coefficients):
            if p <= x <= q:
                for j, c in enumerate(cs):
                    y = (y + c * pow(x, j, n)) % n
        return y


# ---------------------------------------------------------------------------
# Wide fixed-point activation splines (the vector codec's raison d'etre)
# ---------------------------------------------------------------------------


def _chord_pwl_gate(
    cls,
    fn,
    sat_lo: float,
    sat_hi: float,
    log_group_size: int,
    frac_bits: int,
    pieces: int,
    input_range: float,
    payload: Optional[str],
):
    """Degree-1 chord spline of a saturating real function over the signed
    fixed-point domain.

    Fixed-point contract: inputs are signed with ``frac_bits`` fractional
    bits (negative x_real rides the two's-complement point n - |x|);
    outputs carry ``2 * frac_bits`` fractional bits, because a degree-1
    piece over raw ints is ``c0 + c1 * x_raw`` with the slope quantized to
    ``c1 = round(slope * 2^frac_bits)`` — the standard pre-truncation FSS
    spline form (the truncation/ARS gate is the recorded follow-up,
    ROADMAP "private inference"). ``pieces`` counts total intervals: two
    saturation tails at ``fn(-inf)`` / ``fn(+inf)`` plus ``pieces - 2``
    uniform chords over [-input_range, input_range].

    The slope-intercept -> mod-N reduction is exact: for signed x0 with
    raw point x0 + n, ``c1 * (x0 + n) = c1 * x0 (mod n)``, so one signed
    intercept ``c0 = y0_fp - c1 * x0_fp mod n`` serves the whole chord.
    """
    if pieces < 4:
        raise InvalidArgumentError(
            "A saturating chord spline needs >= 4 pieces (2 tails + 2 chords)"
        )
    n = 1 << log_group_size
    half = n >> 1
    scale = 1 << frac_bits
    r_raw = int(round(input_range * scale))
    if not 0 < r_raw < half:
        raise InvalidArgumentError(
            "input_range must fit the signed fixed-point domain "
            f"(got {input_range} at {frac_bits} fractional bits in a "
            f"2^{log_group_size} group)"
        )
    interior = pieces - 2
    intervals: List[Tuple[int, int]] = []
    coefficients: List[List[int]] = []

    def add_chord(x0_fp: int, x1_fp: int) -> None:
        """One chord over signed raw [x0_fp, x1_fp): line through the
        endpoint samples, coefficients exact mod n."""
        y0 = int(round(fn(x0_fp / scale) * scale * scale))
        y1 = int(round(fn(x1_fp / scale) * scale * scale))
        c1 = int(round((y1 - y0) / ((x1_fp - x0_fp) * scale)))
        c0 = (y0 - c1 * x0_fp) % n
        lo, hi = x0_fp, x1_fp - 1
        if lo < 0 and hi >= 0:  # split the zero-crossing chord at the wrap
            intervals.append((0, hi))
            coefficients.append([c0, c1 % n])
            lo, hi = lo + n, n - 1
        elif lo < 0:
            lo, hi = lo + n, hi + n
        intervals.append((lo, hi))
        coefficients.append([c0, c1 % n])

    # Interior chords over [-r_raw, r_raw), uniform in raw units.
    bounds = [
        -r_raw + (2 * r_raw * i) // interior for i in range(interior + 1)
    ]
    for i in range(interior):
        if bounds[i + 1] > bounds[i]:
            add_chord(bounds[i], bounds[i + 1])
    # Saturation tails (constant pieces, degree-padded with a zero slope).
    sat_hi_fp = int(round(sat_hi * scale * scale)) % n
    sat_lo_fp = int(round(sat_lo * scale * scale)) % n
    intervals.append((r_raw, half - 1))
    coefficients.append([sat_hi_fp, 0])
    intervals.append((half, (n - r_raw - 1) % n))
    coefficients.append([sat_lo_fp, 0])
    gate = SplineGate.create.__func__(
        cls, log_group_size, intervals, coefficients, payload=payload
    )
    gate.frac_bits = frac_bits
    gate.input_range = input_range
    return gate


class SigmoidGate(SplineGate):
    """Wide degree-1 chord spline of the logistic sigmoid in fixed point —
    the ~16x vector-codec case (8 pieces x 2 coefficients = 16 scalar
    keys collapse to one). Inputs signed with ``frac_bits`` fractional
    bits; outputs carry ``2 * frac_bits`` (see ``_chord_pwl_gate``)."""

    @classmethod
    def create(  # noqa: D417 — pieces/frac_bits documented above
        cls,
        log_group_size: int,
        frac_bits: int = 5,
        pieces: int = 8,
        input_range: float = 6.0,
        payload: Optional[str] = None,
    ) -> "SigmoidGate":
        return _chord_pwl_gate(
            cls,
            lambda x: 1.0 / (1.0 + math.exp(-x)),
            0.0,
            1.0,
            log_group_size,
            frac_bits,
            pieces,
            input_range,
            payload,
        )


class TanhGate(SplineGate):
    """Wide degree-1 chord spline of tanh in fixed point; same contract
    as :class:`SigmoidGate` (negative outputs ride mod-N)."""

    @classmethod
    def create(  # noqa: D417
        cls,
        log_group_size: int,
        frac_bits: int = 5,
        pieces: int = 8,
        input_range: float = 4.0,
        payload: Optional[str] = None,
    ) -> "TanhGate":
        return _chord_pwl_gate(
            cls,
            math.tanh,
            -1.0,
            1.0,
            log_group_size,
            frac_bits,
            pieces,
            input_range,
            payload,
        )
