"""ctypes loader for the native AES-NI host engine (dpf_native.cc).

Builds the shared library on first import (g++, cached next to the source;
rebuilt when the source is newer) and exposes numpy-friendly wrappers. The
host layer (core/aes_numpy.py) transparently uses it when available; set
DPF_TPU_NO_NATIVE=1 to force the pure-numpy path (the differential-test
baseline). All functions are bit-exact with the numpy implementation — the
golden AES vectors and every share-sum test run identically either way.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "dpf_native.cc")
_LIB = os.path.join(_HERE, "libdpf_native.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    cmd = [
        "g++", "-O3", "-maes", "-mssse3", "-shared", "-fPIC", _SRC, "-o", _LIB,
    ]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        return r.returncode == 0
    except Exception:
        return False


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if os.environ.get("DPF_TPU_NO_NATIVE"):
            return None
        try:
            stale = (not os.path.exists(_LIB)) or (
                os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
            )
            if stale and not _build():
                return None
            lib = ctypes.CDLL(_LIB)
            if not lib.dpf_native_available():
                return None
            lib.dpf_expand_key.argtypes = [ctypes.c_char_p, ctypes.c_void_p]
            lib.dpf_mmo_hash.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_size_t,
            ]
            lib.dpf_mmo_hash_masked.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
            ]
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def available() -> bool:
    return _load() is not None


def expand_key(key_bytes: bytes) -> np.ndarray:
    """16-byte AES key -> uint8[11, 16] round keys."""
    lib = _load()
    assert lib is not None
    out = np.empty((11, 16), dtype=np.uint8)
    lib.dpf_expand_key(key_bytes, out.ctypes.data_as(ctypes.c_void_p))
    return out


def mmo_hash_limbs(round_keys: np.ndarray, in_limbs: np.ndarray) -> np.ndarray:
    """MMO hash of uint32[N, 4] blocks with uint8[11, 16] round keys."""
    lib = _load()
    assert lib is not None
    x = np.ascontiguousarray(in_limbs, dtype=np.uint32)
    out = np.empty_like(x)
    lib.dpf_mmo_hash(
        np.ascontiguousarray(round_keys).ctypes.data_as(ctypes.c_void_p),
        x.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p),
        x.shape[0],
    )
    return out


def mmo_hash_masked_limbs(
    rks_left: np.ndarray,
    rks_right: np.ndarray,
    in_limbs: np.ndarray,
    mask: np.ndarray,
) -> np.ndarray:
    """Per-block key-selected MMO hash (mask != 0 -> right key)."""
    lib = _load()
    assert lib is not None
    x = np.ascontiguousarray(in_limbs, dtype=np.uint32)
    m = np.ascontiguousarray(mask, dtype=np.uint8)
    out = np.empty_like(x)
    lib.dpf_mmo_hash_masked(
        np.ascontiguousarray(rks_left).ctypes.data_as(ctypes.c_void_p),
        np.ascontiguousarray(rks_right).ctypes.data_as(ctypes.c_void_p),
        x.ctypes.data_as(ctypes.c_void_p),
        m.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p),
        x.shape[0],
    )
    return out


def expand_tree(
    rks_left: np.ndarray,
    rks_right: np.ndarray,
    seed_limbs: np.ndarray,  # uint32[4]
    cw_seed_limbs: np.ndarray,  # uint32[L, 4]
    cw_left: np.ndarray,  # bool/uint8[L]
    cw_right: np.ndarray,  # bool/uint8[L]
    party: int,
    levels: int,
):
    """Full doubling expansion of one key in native code.

    Returns (seeds uint32[2^levels, 4], control uint8[2^levels]) in leaf
    order — bit-identical to the numpy oracle's level-by-level expansion.
    """
    lib = _load()
    assert lib is not None
    n = 1 << levels
    out_seeds = np.empty((n, 4), dtype=np.uint32)
    out_control = np.empty(n, dtype=np.uint8)
    scratch = np.empty((n, 4), dtype=np.uint32)
    if not hasattr(lib, "_expand_tree_typed"):
        lib.dpf_expand_tree.argtypes = [ctypes.c_void_p] * 6 + [
            ctypes.c_int, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ]
        lib._expand_tree_typed = True
    ptr = lambda a: np.ascontiguousarray(a).ctypes.data_as(ctypes.c_void_p)
    lib.dpf_expand_tree(
        ptr(rks_left),
        ptr(rks_right),
        ptr(np.ascontiguousarray(seed_limbs, dtype=np.uint32)),
        ptr(np.ascontiguousarray(cw_seed_limbs, dtype=np.uint32)),
        ptr(np.ascontiguousarray(cw_left, dtype=np.uint8)),
        ptr(np.ascontiguousarray(cw_right, dtype=np.uint8)),
        int(party),
        int(levels),
        out_seeds.ctypes.data_as(ctypes.c_void_p),
        out_control.ctypes.data_as(ctypes.c_void_p),
        scratch.ctypes.data_as(ctypes.c_void_p),
    )
    return out_seeds, out_control
