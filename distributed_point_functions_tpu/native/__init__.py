"""ctypes loader for the native AES-NI host engine (dpf_native.cc).

Builds the shared library on first import (g++, cached next to the source;
rebuilt when the source is newer) and exposes numpy-friendly wrappers. The
host layer (core/aes_numpy.py) transparently uses it when available; set
DPF_TPU_NO_NATIVE=1 to force the pure-numpy path (the differential-test
baseline). All functions are bit-exact with the numpy implementation — the
golden AES vectors and every share-sum test run identically either way.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from ..utils import envflags

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "dpf_native.cc")
_LIB = os.path.join(_HERE, "libdpf_native.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    cmd = [
        "g++", "-O3", "-maes", "-mssse3", "-pthread", "-shared", "-fPIC",
        _SRC, "-o", _LIB,
    ]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        return r.returncode == 0
    except Exception:
        return False


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        # Parse the flag BEFORE latching _tried: a strict-parse failure
        # must raise on every call, not raise once and then silently
        # disable the native engine forever.
        no_native = envflags.env_bool("DPF_TPU_NO_NATIVE", default=False)
        _tried = True
        if no_native:
            return None
        try:
            stale = (not os.path.exists(_LIB)) or (
                os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
            )
            if stale and not _build():
                return None
            lib = ctypes.CDLL(_LIB)
            if not lib.dpf_native_available():
                return None
            lib.dpf_expand_key.argtypes = [ctypes.c_char_p, ctypes.c_void_p]
            lib.dpf_mmo_hash.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_size_t,
            ]
            lib.dpf_mmo_hash_masked.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
            ]
            lib.dpf_evaluate_seeds.argtypes = [ctypes.c_void_p] * 8 + [
                ctypes.c_size_t, ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p,
            ]
            lib.dpf_expand_forest.argtypes = [ctypes.c_void_p] * 7 + [
                ctypes.c_size_t, ctypes.c_int,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ]
            lib.dpf_value_hash.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
                ctypes.c_int, ctypes.c_void_p,
            ]
            lib.dpf_dcf_evaluate_u64.argtypes = [ctypes.c_void_p] * 4 + [
                ctypes.c_int,
            ] + [ctypes.c_void_p] * 8 + [
                ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_size_t,
                ctypes.c_void_p,
            ]
            lib.dpf_dcf_evaluate_wide.argtypes = [ctypes.c_void_p] * 4 + [
                ctypes.c_int,
            ] + [ctypes.c_void_p] * 8 + [
                ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.c_size_t, ctypes.c_void_p,
            ]
            lib.dpf_finish_tree_values.argtypes = [ctypes.c_void_p] * 6 + [
                ctypes.c_uint8, ctypes.c_uint8, ctypes.c_int, ctypes.c_size_t,
                ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.c_void_p,
            ]
            lib.dpf_hash_correct_values.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int, ctypes.c_size_t, ctypes.c_void_p,
                ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_void_p,
            ]
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def available() -> bool:
    return _load() is not None


def expand_key(key_bytes: bytes) -> np.ndarray:
    """16-byte AES key -> uint8[11, 16] round keys."""
    lib = _load()
    assert lib is not None
    out = np.empty((11, 16), dtype=np.uint8)
    lib.dpf_expand_key(key_bytes, out.ctypes.data_as(ctypes.c_void_p))
    return out


def mmo_hash_limbs(round_keys: np.ndarray, in_limbs: np.ndarray) -> np.ndarray:
    """MMO hash of uint32[N, 4] blocks with uint8[11, 16] round keys."""
    lib = _load()
    assert lib is not None
    x = np.ascontiguousarray(in_limbs, dtype=np.uint32)
    out = np.empty_like(x)
    lib.dpf_mmo_hash(
        np.ascontiguousarray(round_keys).ctypes.data_as(ctypes.c_void_p),
        x.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p),
        x.shape[0],
    )
    return out


def mmo_hash_masked_limbs(
    rks_left: np.ndarray,
    rks_right: np.ndarray,
    in_limbs: np.ndarray,
    mask: np.ndarray,
) -> np.ndarray:
    """Per-block key-selected MMO hash (mask != 0 -> right key)."""
    lib = _load()
    assert lib is not None
    x = np.ascontiguousarray(in_limbs, dtype=np.uint32)
    m = np.ascontiguousarray(mask, dtype=np.uint8)
    out = np.empty_like(x)
    lib.dpf_mmo_hash_masked(
        np.ascontiguousarray(rks_left).ctypes.data_as(ctypes.c_void_p),
        np.ascontiguousarray(rks_right).ctypes.data_as(ctypes.c_void_p),
        x.ctypes.data_as(ctypes.c_void_p),
        m.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p),
        x.shape[0],
    )
    return out


def evaluate_seeds(
    rks_left: np.ndarray,
    rks_right: np.ndarray,
    seeds: np.ndarray,  # uint32[N, 4]
    control: np.ndarray,  # bool/uint8[N]
    paths: np.ndarray,  # uint32[N, 4]
    cw_seed_limbs: np.ndarray,  # uint32[L, 4]
    cw_left: np.ndarray,  # bool/uint8[L]
    cw_right: np.ndarray,  # bool/uint8[L]
):
    """Native batched point-evaluation walk (EvaluateSeeds).

    Returns (uint32[N, 4] seeds, bool[N] control) — bit-identical to
    core/backend_numpy.evaluate_seeds.
    """
    lib = _load()
    assert lib is not None
    x = np.ascontiguousarray(seeds, dtype=np.uint32)
    n = x.shape[0]
    levels = len(cw_seed_limbs)
    out_seeds = np.empty_like(x)
    out_control = np.empty(n, dtype=np.uint8)
    ptr = lambda a: np.ascontiguousarray(a).ctypes.data_as(ctypes.c_void_p)
    lib.dpf_evaluate_seeds(
        ptr(rks_left),
        ptr(rks_right),
        x.ctypes.data_as(ctypes.c_void_p),
        ptr(np.ascontiguousarray(control, dtype=np.uint8)),
        ptr(np.ascontiguousarray(paths, dtype=np.uint32)),
        ptr(np.ascontiguousarray(cw_seed_limbs, dtype=np.uint32)),
        ptr(np.ascontiguousarray(cw_left, dtype=np.uint8)),
        ptr(np.ascontiguousarray(cw_right, dtype=np.uint8)),
        n,
        levels,
        out_seeds.ctypes.data_as(ctypes.c_void_p),
        out_control.ctypes.data_as(ctypes.c_void_p),
    )
    return out_seeds, out_control.astype(bool)


def expand_forest(
    rks_left: np.ndarray,
    rks_right: np.ndarray,
    seeds: np.ndarray,  # uint32[N, 4] roots
    control: np.ndarray,  # bool/uint8[N]
    cw_seed_limbs: np.ndarray,  # uint32[L, 4]
    cw_left: np.ndarray,
    cw_right: np.ndarray,
    levels: int,
):
    """Doubling expansion of N roots by `levels` levels (ExpandSeeds).

    Returns (uint32[N << levels, 4], bool[N << levels]) in the interleaved
    per-level child order — bit-identical to backend_numpy.expand_seeds.
    """
    lib = _load()
    assert lib is not None
    x = np.ascontiguousarray(seeds, dtype=np.uint32)
    n = x.shape[0]
    total = n << levels
    out_seeds = np.empty((total, 4), dtype=np.uint32)
    out_control = np.empty(total, dtype=np.uint8)
    scratch = np.empty((total, 4), dtype=np.uint32)
    ptr = lambda a: np.ascontiguousarray(a).ctypes.data_as(ctypes.c_void_p)
    lib.dpf_expand_forest(
        ptr(rks_left),
        ptr(rks_right),
        x.ctypes.data_as(ctypes.c_void_p),
        ptr(np.ascontiguousarray(control, dtype=np.uint8)),
        ptr(np.ascontiguousarray(cw_seed_limbs, dtype=np.uint32)),
        ptr(np.ascontiguousarray(cw_left, dtype=np.uint8)),
        ptr(np.ascontiguousarray(cw_right, dtype=np.uint8)),
        n,
        int(levels),
        out_seeds.ctypes.data_as(ctypes.c_void_p),
        out_control.ctypes.data_as(ctypes.c_void_p),
        scratch.ctypes.data_as(ctypes.c_void_p),
    )
    return out_seeds, out_control.astype(bool)


def value_hash(round_keys: np.ndarray, in_limbs: np.ndarray, blocks_needed: int):
    """MMO hash of in[i] + j for j < blocks_needed (HashExpandedSeeds).

    Returns uint32[N, blocks_needed, 4].
    """
    lib = _load()
    assert lib is not None
    x = np.ascontiguousarray(in_limbs, dtype=np.uint32)
    n = x.shape[0]
    out = np.empty((n, blocks_needed, 4), dtype=np.uint32)
    lib.dpf_value_hash(
        np.ascontiguousarray(round_keys).ctypes.data_as(ctypes.c_void_p),
        x.ctypes.data_as(ctypes.c_void_p),
        n,
        int(blocks_needed),
        out.ctypes.data_as(ctypes.c_void_p),
    )
    return out


def dcf_evaluate_u64(
    rks_left: np.ndarray,
    rks_right: np.ndarray,
    rks_value: np.ndarray,
    seed_limbs: np.ndarray,  # uint32[4]
    party: int,
    cw_seed_limbs: np.ndarray,  # uint32[T, 4]
    cw_left: np.ndarray,  # bool/uint8[T]
    cw_right: np.ndarray,  # bool/uint8[T]
    vc: np.ndarray,  # uint64[T+1, epb] value corrections by depth
    capture: np.ndarray,  # bool/uint8[T+1]
    acc_mask: np.ndarray,  # uint8[T+1, P]
    block_sel: np.ndarray,  # int32[T+1, P]
    paths: np.ndarray,  # uint32[P, 4] tree indices
    value_bits: int,
) -> np.ndarray:
    """Fused batched DCF evaluation of one key (<= 64-bit additive values).

    One root-to-leaf walk per point with per-depth value captures — the
    native twin of dcf/batch.py's device kernel. Returns uint64[P] shares.
    """
    lib = _load()
    assert lib is not None
    vc = np.ascontiguousarray(vc, dtype=np.uint64)
    levels = len(cw_seed_limbs)
    n = paths.shape[0]
    out = np.empty(n, dtype=np.uint64)
    ptr = lambda a: np.ascontiguousarray(a).ctypes.data_as(ctypes.c_void_p)
    lib.dpf_dcf_evaluate_u64(
        ptr(rks_left),
        ptr(rks_right),
        ptr(rks_value),
        ptr(np.ascontiguousarray(seed_limbs, dtype=np.uint32)),
        int(party),
        ptr(np.ascontiguousarray(cw_seed_limbs, dtype=np.uint32)),
        ptr(np.ascontiguousarray(cw_left, dtype=np.uint8)),
        ptr(np.ascontiguousarray(cw_right, dtype=np.uint8)),
        vc.ctypes.data_as(ctypes.c_void_p),
        ptr(np.ascontiguousarray(capture, dtype=np.uint8)),
        ptr(np.ascontiguousarray(acc_mask, dtype=np.uint8)),
        ptr(np.ascontiguousarray(block_sel, dtype=np.int32)),
        ptr(np.ascontiguousarray(paths, dtype=np.uint32)),
        int(value_bits),
        int(vc.shape[1]),
        levels,
        n,
        out.ctypes.data_as(ctypes.c_void_p),
    )
    return out


def dcf_evaluate_wide(
    rks_left: np.ndarray,
    rks_right: np.ndarray,
    rks_value: np.ndarray,
    seed_limbs: np.ndarray,  # uint32[4]
    party: int,
    cw_seed_limbs: np.ndarray,  # uint32[T, 4]
    cw_left: np.ndarray,  # bool/uint8[T]
    cw_right: np.ndarray,  # bool/uint8[T]
    vc: np.ndarray,  # uint64[T+1, epb, 2] value corrections (lo, hi)
    capture: np.ndarray,  # bool/uint8[T+1]
    acc_mask: np.ndarray,  # uint8[T+1, P]
    block_sel: np.ndarray,  # int32[T+1, P]
    paths: np.ndarray,  # uint32[P, 4] tree indices
    value_bits: int,
    is_xor: bool,
) -> np.ndarray:
    """Fused batched DCF evaluation of one key — every scalar group.

    Generalization of `dcf_evaluate_u64` to 128-bit values and XOR groups;
    values travel as (lo, hi) uint64 pairs. Returns uint64[P, 2] shares.
    """
    lib = _load()
    assert lib is not None
    vc = np.ascontiguousarray(vc, dtype=np.uint64)
    levels = len(cw_seed_limbs)
    n = paths.shape[0]
    out = np.empty((n, 2), dtype=np.uint64)
    ptr = lambda a: np.ascontiguousarray(a).ctypes.data_as(ctypes.c_void_p)
    lib.dpf_dcf_evaluate_wide(
        ptr(rks_left),
        ptr(rks_right),
        ptr(rks_value),
        ptr(np.ascontiguousarray(seed_limbs, dtype=np.uint32)),
        int(party),
        ptr(np.ascontiguousarray(cw_seed_limbs, dtype=np.uint32)),
        ptr(np.ascontiguousarray(cw_left, dtype=np.uint8)),
        ptr(np.ascontiguousarray(cw_right, dtype=np.uint8)),
        vc.ctypes.data_as(ctypes.c_void_p),
        ptr(np.ascontiguousarray(capture, dtype=np.uint8)),
        ptr(np.ascontiguousarray(acc_mask, dtype=np.uint8)),
        ptr(np.ascontiguousarray(block_sel, dtype=np.int32)),
        ptr(np.ascontiguousarray(paths, dtype=np.uint32)),
        int(value_bits),
        1 if is_xor else 0,
        int(vc.shape[1]),
        levels,
        n,
        out.ctypes.data_as(ctypes.c_void_p),
    )
    return out


def expand_forest_values(
    rks_left: np.ndarray,
    rks_right: np.ndarray,
    rks_value: np.ndarray,
    seeds: np.ndarray,  # uint32[N, 4] roots
    control: np.ndarray,  # bool/uint8[N]
    cw_seed_limbs: np.ndarray,  # uint32[L, 4]
    cw_left: np.ndarray,
    cw_right: np.ndarray,
    party: int,
    levels: int,
    vc_wide: np.ndarray,  # uint64[epb, 2]
    value_bits: int,
    is_xor: bool,
    keep_per_block: int,
    out: np.ndarray = None,
) -> np.ndarray:
    """Fused forest evaluation: N prefix roots expand
    `levels` levels with the final level fused into the value hash +
    correction pass (root j's outputs land contiguously). For hierarchy
    tails where the expansion state is not needed afterwards.

    Returns uint8[(N << levels) * keep_per_block * value_bits/8] element
    bytes (or writes into a matching C-contiguous `out`).
    """
    lib = _load()
    assert lib is not None
    vc_wide = np.ascontiguousarray(vc_wide, dtype=np.uint64)
    n = seeds.shape[0]
    n_out_bytes = (n << levels) * keep_per_block * (value_bits // 8)
    if out is None:
        out = np.empty(n_out_bytes, dtype=np.uint8)
    else:
        assert out.flags["C_CONTIGUOUS"] and out.nbytes == n_out_bytes
        out = out.view(np.uint8).reshape(-1)
    ptr = lambda a: np.ascontiguousarray(a).ctypes.data_as(ctypes.c_void_p)
    if levels == 0:
        lib.dpf_hash_correct_values(
            ptr(rks_value),
            ptr(np.ascontiguousarray(seeds, dtype=np.uint32)),
            ptr(np.ascontiguousarray(control, dtype=np.uint8)),
            int(party),
            n,
            vc_wide.ctypes.data_as(ctypes.c_void_p),
            int(value_bits),
            1 if is_xor else 0,
            int(keep_per_block),
            out.ctypes.data_as(ctypes.c_void_p),
        )
        return out
    parents, ctl_parents = expand_forest(
        rks_left, rks_right, seeds,
        np.ascontiguousarray(control, dtype=np.uint8),
        cw_seed_limbs[: levels - 1], cw_left[: levels - 1],
        cw_right[: levels - 1], levels - 1,
    )
    last = levels - 1
    lib.dpf_finish_tree_values(
        ptr(rks_left),
        ptr(rks_right),
        ptr(rks_value),
        parents.ctypes.data_as(ctypes.c_void_p),
        np.ascontiguousarray(ctl_parents, dtype=np.uint8).ctypes.data_as(
            ctypes.c_void_p
        ),
        ptr(np.ascontiguousarray(cw_seed_limbs[last], dtype=np.uint32)),
        int(bool(cw_left[last])),
        int(bool(cw_right[last])),
        int(party),
        parents.shape[0],
        vc_wide.ctypes.data_as(ctypes.c_void_p),
        int(value_bits),
        1 if is_xor else 0,
        int(keep_per_block),
        out.ctypes.data_as(ctypes.c_void_p),
    )
    return out
