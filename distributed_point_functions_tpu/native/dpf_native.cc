// Native host engine: AES-NI batch kernels for the CPU side of the
// framework (key generation, host pre-expansion, the differential-test
// oracle). The TPU compute path is JAX/XLA (ops/); this library is the
// native runtime underneath the host layer, playing the role the
// OpenSSL/Highway kernels play in the reference
// (/root/reference/dpf/aes_128_fixed_key_hash.cc:27-85,
//  /root/reference/dpf/internal/aes_128_fixed_key_hash_hwy.h:62-229) —
// written from scratch against the AES-NI intrinsics, not ported.
//
// Build:  g++ -O3 -maes -mssse3 -pthread -shared -fPIC dpf_native.cc -o libdpf_native.so
// ABI: plain C, little-endian 16-byte blocks (the uint32[,4] limb layout).

#include <cstddef>
#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <memory>
#include <thread>
#include <vector>

#if defined(__AES__) && defined(__SSSE3__)
#include <immintrin.h>
#include <cpuid.h>
// VAES intrinsics + the target attribute need gcc >= 9 or clang;
// older toolchains still build the full 128-bit AES-NI engine.
#if defined(__x86_64__) && (defined(__clang__) || __GNUC__ >= 9)
#define DPF_HAVE_VAES 1
#endif
#include <wmmintrin.h>
#include <tmmintrin.h>

namespace {

// Host-side worker threads for the batch kernels. The reference library is
// single-threaded by design; every cross-implementation number in this
// repo was measured with the default of 1. DPF_TPU_THREADS=N opts in,
// DPF_TPU_THREADS=0 uses all hardware threads. Outputs are bit-identical
// at any thread count (work splits are by disjoint index ranges).
int num_threads() {
  static int n = [] {
    const char* env = std::getenv("DPF_TPU_THREADS");
    if (env == nullptr || *env == '\0') return 1;
    char* end = nullptr;
    long v = std::strtol(env, &end, 10);
    if (end == env || *end != '\0') return 1;  // non-numeric: stay at 1
    if (v == 0) v = static_cast<long>(std::thread::hardware_concurrency());
    return v < 1 ? 1 : static_cast<int>(v);
  }();
  return n;
}

// Runs fn(begin, end) over [0, total) split into `threads` contiguous
// ranges aligned to `align` (so SIMD groups never straddle a boundary).
template <typename Fn>
void parallel_ranges(size_t total, size_t align, const Fn& fn) {
  const int t = num_threads();
  if (t <= 1 || total <= align * 2) {
    fn(static_cast<size_t>(0), total);
    return;
  }
  const size_t groups = (total + align - 1) / align;
  const size_t per = (groups + t - 1) / t;
  std::vector<std::thread> workers;
  for (int i = 0; i < t; ++i) {
    const size_t a = static_cast<size_t>(i) * per * align;
    if (a >= total) break;
    size_t b = a + per * align;
    if (b > total) b = total;
    workers.emplace_back([&fn, a, b] { fn(a, b); });
  }
  for (auto& w : workers) w.join();
}

inline __m128i expand_step(__m128i key, __m128i keygened) {
  keygened = _mm_shuffle_epi32(keygened, _MM_SHUFFLE(3, 3, 3, 3));
  key = _mm_xor_si128(key, _mm_slli_si128(key, 4));
  key = _mm_xor_si128(key, _mm_slli_si128(key, 4));
  key = _mm_xor_si128(key, _mm_slli_si128(key, 4));
  return _mm_xor_si128(key, keygened);
}

// sigma(x): out.lo64 = x.hi64, out.hi64 = x.hi64 ^ x.lo64 — the linear
// orthomorphism of the MMO construction.
inline __m128i sigma(__m128i x) {
  __m128i hi_hi = _mm_shuffle_epi32(x, _MM_SHUFFLE(3, 2, 3, 2));
  __m128i zero_lo = _mm_slli_si128(x, 8);
  return _mm_xor_si128(hi_hi, zero_lo);
}

inline __m128i encrypt(__m128i block, const __m128i* rks) {
  block = _mm_xor_si128(block, rks[0]);
  for (int r = 1; r < 10; ++r) block = _mm_aesenc_si128(block, rks[r]);
  return _mm_aesenclast_si128(block, rks[10]);
}

inline void load_rks(const uint8_t* bytes, __m128i* rks) {
  for (int i = 0; i < 11; ++i)
    rks[i] = _mm_loadu_si128(reinterpret_cast<const __m128i*>(bytes + 16 * i));
}

// ---------------------------------------------------------------------------
// VAES / AVX-512 wide path: 4 AES blocks per 512-bit register, runtime
// dispatched (this image's CPUs have VAES; older hosts fall back to the
// 128-bit AES-NI path above). Outputs are bit-identical either way —
// the differential suites run with DPF_TPU_NO_VAES=1 to pin that.
// ---------------------------------------------------------------------------


// Shared output-element emitter for the fused value kernels: one hash
// block -> corrected, party-negated element bytes at dst.
inline void emit_corrected_elements(const uint64_t blk[2], uint8_t ctrl,
                                    const uint64_t* vc, int value_bits,
                                    int is_xor, int party, int keep,
                                    uint64_t lo_mask, uint64_t hi_mask,
                                    size_t elem_bytes, uint8_t* dst) {
  for (int e = 0; e < keep; ++e) {
    const int bit_off = e * value_bits;
    uint64_t v_lo = (blk[bit_off >> 6] >> (bit_off & 63)) & lo_mask;
    uint64_t v_hi = (value_bits > 64 ? blk[1] : 0) & hi_mask;
    const uint64_t* c = vc + 2 * e;
    if (is_xor) {
      if (ctrl) {
        v_lo ^= c[0];
        v_hi ^= c[1];
      }
    } else {
      if (ctrl) {
        const uint64_t s_lo = v_lo + c[0];
        v_hi = (v_hi + c[1] + (s_lo < v_lo ? 1 : 0)) & hi_mask;
        v_lo = s_lo & lo_mask;
      }
      if (party) {
        const uint64_t n_lo = (0 - v_lo) & lo_mask;
        v_hi = ((0 - v_hi) - (v_lo != 0 ? 1 : 0)) & hi_mask;
        v_lo = n_lo;
      }
    }
    uint8_t* d = dst + static_cast<size_t>(e) * elem_bytes;
    if (elem_bytes <= 8) {
      std::memcpy(d, &v_lo, elem_bytes);
    } else {
      std::memcpy(d, &v_lo, 8);
      std::memcpy(d + 8, &v_hi, 8);
    }
  }
}


// Whole-block vectorized correction for full-block outputs (keep == epb,
// bits <= 64): one lane-wise group op over the 16-byte hash block, wrap
// mod 2^bits automatic per lane.
inline __m128i correct_block_vec(__m128i h, uint8_t ctrl, __m128i vc_vec,
                                 int value_bits, int is_xor, int party) {
  const __m128i gated = ctrl ? vc_vec : _mm_setzero_si128();
  if (is_xor) return _mm_xor_si128(h, gated);
  __m128i v;
  switch (value_bits) {
    case 8:
      v = _mm_add_epi8(h, gated);
      if (party) v = _mm_sub_epi8(_mm_setzero_si128(), v);
      break;
    case 16:
      v = _mm_add_epi16(h, gated);
      if (party) v = _mm_sub_epi16(_mm_setzero_si128(), v);
      break;
    case 32:
      v = _mm_add_epi32(h, gated);
      if (party) v = _mm_sub_epi32(_mm_setzero_si128(), v);
      break;
    default:  // 64
      v = _mm_add_epi64(h, gated);
      if (party) v = _mm_sub_epi64(_mm_setzero_si128(), v);
      break;
  }
  return v;
}

inline bool use_vaes() {
#if !defined(DPF_HAVE_VAES)
  return false;  // toolchain lacks VAES intrinsics; 128-bit AES-NI path
#else
  static const bool on = [] {
    if (std::getenv("DPF_TPU_NO_VAES") != nullptr) return false;
    // __builtin_cpu_supports("vaes") only exists from gcc 11 — and a
    // toolchain that can compile the intrinsics (gcc >= 9) may still lack
    // the builtin, which used to abort the whole build and silently lose
    // the native engine to the ~95x-slower numpy path. Read the CPUID bit
    // (leaf 7, ECX bit 9) directly; AVX-512 state checks (which need
    // OSXSAVE/XCR0 handling) stay on the builtin, present since gcc 5.
    unsigned eax = 0, ebx = 0, ecx = 0, edx = 0;
    if (!__get_cpuid_count(7, 0, &eax, &ebx, &ecx, &edx)) return false;
    return __builtin_cpu_supports("avx512f") &&
           __builtin_cpu_supports("avx512bw") && ((ecx >> 9) & 1u) != 0;
  }();
  return on;
#endif
}

#if defined(DPF_HAVE_VAES)
#define DPF_VAES_TARGET __attribute__((target("avx512f,avx512bw,vaes")))

// sigma per 128-bit lane: out.lo64 = hi64, out.hi64 = hi64 ^ lo64.
DPF_VAES_TARGET inline __m512i sigma512(__m512i x) {
  __m512i hi_hi = _mm512_shuffle_epi32(x, _MM_PERM_DCDC);
  __m512i zero_lo = _mm512_bslli_epi128(x, 8);
  return _mm512_xor_si512(hi_hi, zero_lo);
}

// MMO hash of a 16-block-aligned range [begin, end): 16 blocks (4 regs) in
// flight per iteration.
DPF_VAES_TARGET void mmo_hash_vaes(const __m128i* rks, const uint8_t* in,
                                   uint8_t* out, size_t begin, size_t end) {
  __m512i rk[11];
  for (int i = 0; i < 11; ++i) rk[i] = _mm512_broadcast_i32x4(rks[i]);
  for (size_t i = begin; i + 16 <= end; i += 16) {
    __m512i s[4], b[4];
    for (int j = 0; j < 4; ++j) {
      __m512i x = _mm512_loadu_si512(in + 16 * (i + 4 * j));
      s[j] = sigma512(x);
      b[j] = _mm512_xor_si512(s[j], rk[0]);
    }
    for (int r = 1; r < 10; ++r)
      for (int j = 0; j < 4; ++j) b[j] = _mm512_aesenc_epi128(b[j], rk[r]);
    for (int j = 0; j < 4; ++j) {
      b[j] = _mm512_xor_si512(_mm512_aesenclast_epi128(b[j], rk[10]), s[j]);
      _mm512_storeu_si512(out + 16 * (i + 4 * j), b[j]);
    }
  }
}

// One doubling level over parents [begin, end) (4-aligned bulk): 4 parents
// = 8 child blocks (two 512-bit streams) per iteration; children
// interleaved [L0 R0 L1 R1 | L2 R2 L3 R3] by a qword cross-permute.
DPF_VAES_TARGET void expand_level_vaes(
    const __m128i* rl128, const __m128i* rr128, __m128i cw128, uint8_t ccl,
    uint8_t ccr, const uint8_t* cur, const uint8_t* ctl_cur, uint8_t* nxt,
    uint8_t* ctl_nxt, size_t begin, size_t end) {
  __m512i rl[11], rr[11];
  for (int i = 0; i < 11; ++i) {
    rl[i] = _mm512_broadcast_i32x4(rl128[i]);
    rr[i] = _mm512_broadcast_i32x4(rr128[i]);
  }
  const __m512i cw = _mm512_broadcast_i32x4(cw128);
  // Bit 0 of each 128-bit block = bit 0 of its even qword lane.
  const __m512i low_bit512 =
      _mm512_maskz_set1_epi64(static_cast<__mmask8>(0x55), 1);
  const __m512i idx0 = _mm512_setr_epi64(0, 1, 8, 9, 2, 3, 10, 11);
  const __m512i idx1 = _mm512_setr_epi64(4, 5, 12, 13, 6, 7, 14, 15);
  size_t i = begin;
  // 8 parents per iteration: 4 independent AES streams in flight (the AES
  // units need ~5 to hide latency; 2 streams leave them half idle).
  for (; i + 8 <= end; i += 8) {
    __m512i x0 = _mm512_loadu_si512(cur + 16 * i);
    __m512i x1 = _mm512_loadu_si512(cur + 16 * (i + 4));
    __m512i sg0 = sigma512(x0), sg1 = sigma512(x1);
    __m512i bl0 = _mm512_xor_si512(sg0, rl[0]);
    __m512i br0 = _mm512_xor_si512(sg0, rr[0]);
    __m512i bl1 = _mm512_xor_si512(sg1, rl[0]);
    __m512i br1 = _mm512_xor_si512(sg1, rr[0]);
    for (int r = 1; r < 10; ++r) {
      bl0 = _mm512_aesenc_epi128(bl0, rl[r]);
      br0 = _mm512_aesenc_epi128(br0, rr[r]);
      bl1 = _mm512_aesenc_epi128(bl1, rl[r]);
      br1 = _mm512_aesenc_epi128(br1, rr[r]);
    }
    bl0 = _mm512_xor_si512(_mm512_aesenclast_epi128(bl0, rl[10]), sg0);
    br0 = _mm512_xor_si512(_mm512_aesenclast_epi128(br0, rr[10]), sg0);
    bl1 = _mm512_xor_si512(_mm512_aesenclast_epi128(bl1, rl[10]), sg1);
    br1 = _mm512_xor_si512(_mm512_aesenclast_epi128(br1, rr[10]), sg1);
    for (int g = 0; g < 2; ++g) {
      const size_t p = i + 4 * g;
      __m512i bl = g ? bl1 : bl0, br = g ? br1 : br0;
      const uint8_t t0 = ctl_cur[p], t1 = ctl_cur[p + 1],
                    t2 = ctl_cur[p + 2], t3 = ctl_cur[p + 3];
      const __mmask8 tm = static_cast<__mmask8>(
          (t0 ? 0x03 : 0) | (t1 ? 0x0C : 0) | (t2 ? 0x30 : 0) |
          (t3 ? 0xC0 : 0));
      bl = _mm512_mask_xor_epi64(bl, tm, bl, cw);
      br = _mm512_mask_xor_epi64(br, tm, br, cw);
      const __mmask8 kl = _mm512_test_epi64_mask(bl, low_bit512);
      const __mmask8 kr = _mm512_test_epi64_mask(br, low_bit512);
      bl = _mm512_andnot_si512(low_bit512, bl);
      br = _mm512_andnot_si512(low_bit512, br);
      _mm512_storeu_si512(nxt + 16 * 2 * p,
                          _mm512_permutex2var_epi64(bl, idx0, br));
      _mm512_storeu_si512(nxt + 16 * (2 * p + 4),
                          _mm512_permutex2var_epi64(bl, idx1, br));
      const uint8_t ts[4] = {t0, t1, t2, t3};
      for (int j = 0; j < 4; ++j) {
        ctl_nxt[2 * (p + j)] = static_cast<uint8_t>(
            (((kl >> (2 * j)) & 1)) ^ (ts[j] & ccl));
        ctl_nxt[2 * (p + j) + 1] = static_cast<uint8_t>(
            (((kr >> (2 * j)) & 1)) ^ (ts[j] & ccr));
      }
    }
  }
  for (; i + 4 <= end; i += 4) {
    __m512i x = _mm512_loadu_si512(cur + 16 * i);
    __m512i sg = sigma512(x);
    __m512i bl = _mm512_xor_si512(sg, rl[0]);
    __m512i br = _mm512_xor_si512(sg, rr[0]);
    for (int r = 1; r < 10; ++r) {
      bl = _mm512_aesenc_epi128(bl, rl[r]);
      br = _mm512_aesenc_epi128(br, rr[r]);
    }
    bl = _mm512_xor_si512(_mm512_aesenclast_epi128(bl, rl[10]), sg);
    br = _mm512_xor_si512(_mm512_aesenclast_epi128(br, rr[10]), sg);
    const uint8_t t0 = ctl_cur[i], t1 = ctl_cur[i + 1], t2 = ctl_cur[i + 2],
                  t3 = ctl_cur[i + 3];
    const __mmask8 tm = static_cast<__mmask8>(
        (t0 ? 0x03 : 0) | (t1 ? 0x0C : 0) | (t2 ? 0x30 : 0) | (t3 ? 0xC0 : 0));
    bl = _mm512_mask_xor_epi64(bl, tm, bl, cw);
    br = _mm512_mask_xor_epi64(br, tm, br, cw);
    // Child control bits: LSB of each block (qword lanes 0,2,4,6).
    const __mmask8 kl = _mm512_test_epi64_mask(bl, low_bit512);
    const __mmask8 kr = _mm512_test_epi64_mask(br, low_bit512);
    bl = _mm512_andnot_si512(low_bit512, bl);
    br = _mm512_andnot_si512(low_bit512, br);
    _mm512_storeu_si512(nxt + 16 * 2 * i,
                        _mm512_permutex2var_epi64(bl, idx0, br));
    _mm512_storeu_si512(nxt + 16 * (2 * i + 4),
                        _mm512_permutex2var_epi64(bl, idx1, br));
    const uint8_t ts[4] = {t0, t1, t2, t3};
    for (int j = 0; j < 4; ++j) {
      ctl_nxt[2 * (i + j)] = static_cast<uint8_t>(
          (((kl >> (2 * j)) & 1)) ^ (ts[j] & ccl));
      ctl_nxt[2 * (i + j) + 1] = static_cast<uint8_t>(
          (((kr >> (2 * j)) & 1)) ^ (ts[j] & ccr));
    }
  }
}

// Fused final level + value hash + correction, VAES: 4 parents = two
// 512-bit walk streams + two 512-bit value-hash streams per iteration.
DPF_VAES_TARGET void finish_tree_values_vaes(
    const __m128i* rl128, const __m128i* rr128, const __m128i* rv128,
    const uint8_t* parents, const uint8_t* ctl_parents, __m128i cw128,
    uint8_t cw_ctl_left, uint8_t cw_ctl_right, int party, size_t begin,
    size_t end, const uint64_t* vc, int value_bits, int is_xor,
    int keep_per_block, uint64_t lo_mask, uint64_t hi_mask,
    size_t elem_bytes, size_t leaf_bytes, bool full_vec, __m128i vc_vec,
    uint8_t* out) {
  __m512i rl[11], rr[11], rv[11];
  for (int i = 0; i < 11; ++i) {
    rl[i] = _mm512_broadcast_i32x4(rl128[i]);
    rr[i] = _mm512_broadcast_i32x4(rr128[i]);
    rv[i] = _mm512_broadcast_i32x4(rv128[i]);
  }
  const __m512i cw = _mm512_broadcast_i32x4(cw128);
  const __m512i low_bit512 =
      _mm512_maskz_set1_epi64(static_cast<__mmask8>(0x55), 1);
  const __m512i idx0 = _mm512_setr_epi64(0, 1, 8, 9, 2, 3, 10, 11);
  const __m512i idx1 = _mm512_setr_epi64(4, 5, 12, 13, 6, 7, 14, 15);
  const __m512i vc512 = _mm512_broadcast_i32x4(vc_vec);
  alignas(64) uint64_t blk_l[8], blk_r[8];
  size_t i = begin;
  for (; i + 4 <= end; i += 4) {
    __m512i x = _mm512_loadu_si512(parents + 16 * i);
    __m512i sg = sigma512(x);
    __m512i bl = _mm512_xor_si512(sg, rl[0]);
    __m512i br = _mm512_xor_si512(sg, rr[0]);
    for (int r = 1; r < 10; ++r) {
      bl = _mm512_aesenc_epi128(bl, rl[r]);
      br = _mm512_aesenc_epi128(br, rr[r]);
    }
    bl = _mm512_xor_si512(_mm512_aesenclast_epi128(bl, rl[10]), sg);
    br = _mm512_xor_si512(_mm512_aesenclast_epi128(br, rr[10]), sg);
    const uint8_t t0 = ctl_parents[i], t1 = ctl_parents[i + 1],
                  t2 = ctl_parents[i + 2], t3 = ctl_parents[i + 3];
    const __mmask8 tm = static_cast<__mmask8>(
        (t0 ? 0x03 : 0) | (t1 ? 0x0C : 0) | (t2 ? 0x30 : 0) | (t3 ? 0xC0 : 0));
    bl = _mm512_mask_xor_epi64(bl, tm, bl, cw);
    br = _mm512_mask_xor_epi64(br, tm, br, cw);
    const __mmask8 kl = _mm512_test_epi64_mask(bl, low_bit512);
    const __mmask8 kr = _mm512_test_epi64_mask(br, low_bit512);
    bl = _mm512_andnot_si512(low_bit512, bl);
    br = _mm512_andnot_si512(low_bit512, br);
    const __m512i vgl = sigma512(bl), vgr = sigma512(br);
    __m512i hl = _mm512_xor_si512(vgl, rv[0]);
    __m512i hr = _mm512_xor_si512(vgr, rv[0]);
    for (int r = 1; r < 10; ++r) {
      hl = _mm512_aesenc_epi128(hl, rv[r]);
      hr = _mm512_aesenc_epi128(hr, rv[r]);
    }
    hl = _mm512_xor_si512(_mm512_aesenclast_epi128(hl, rv[10]), vgl);
    hr = _mm512_xor_si512(_mm512_aesenclast_epi128(hr, rv[10]), vgr);
    const uint8_t ts[4] = {t0, t1, t2, t3};
    uint8_t tl[4], tr[4];
    for (int j = 0; j < 4; ++j) {
      tl[j] = static_cast<uint8_t>((((kl >> (2 * j)) & 1)) ^
                                   (ts[j] & cw_ctl_left));
      tr[j] = static_cast<uint8_t>((((kr >> (2 * j)) & 1)) ^
                                   (ts[j] & cw_ctl_right));
    }
    if (full_vec) {
      // Lane-wise correction of all 8 children, gated per 128-bit child
      // block by its control bit (qword-granular masks), then one qword
      // cross-permute into leaf order and two direct 64-byte stores.
      const __mmask8 cml = static_cast<__mmask8>(
          (tl[0] ? 0x03 : 0) | (tl[1] ? 0x0C : 0) | (tl[2] ? 0x30 : 0) |
          (tl[3] ? 0xC0 : 0));
      const __mmask8 cmr = static_cast<__mmask8>(
          (tr[0] ? 0x03 : 0) | (tr[1] ? 0x0C : 0) | (tr[2] ? 0x30 : 0) |
          (tr[3] ? 0xC0 : 0));
      __m512i gl = _mm512_maskz_mov_epi64(cml, vc512);
      __m512i gr = _mm512_maskz_mov_epi64(cmr, vc512);
      __m512i vl, vr;
      if (is_xor) {
        vl = _mm512_xor_si512(hl, gl);
        vr = _mm512_xor_si512(hr, gr);
      } else {
        const __m512i z = _mm512_setzero_si512();
        switch (value_bits) {
          case 8:
            vl = _mm512_add_epi8(hl, gl);
            vr = _mm512_add_epi8(hr, gr);
            if (party) {
              vl = _mm512_sub_epi8(z, vl);
              vr = _mm512_sub_epi8(z, vr);
            }
            break;
          case 16:
            vl = _mm512_add_epi16(hl, gl);
            vr = _mm512_add_epi16(hr, gr);
            if (party) {
              vl = _mm512_sub_epi16(z, vl);
              vr = _mm512_sub_epi16(z, vr);
            }
            break;
          case 32:
            vl = _mm512_add_epi32(hl, gl);
            vr = _mm512_add_epi32(hr, gr);
            if (party) {
              vl = _mm512_sub_epi32(z, vl);
              vr = _mm512_sub_epi32(z, vr);
            }
            break;
          default:  // 64
            vl = _mm512_add_epi64(hl, gl);
            vr = _mm512_add_epi64(hr, gr);
            if (party) {
              vl = _mm512_sub_epi64(z, vl);
              vr = _mm512_sub_epi64(z, vr);
            }
            break;
        }
      }
      const size_t leaf = 2 * i;
      _mm512_storeu_si512(out + leaf * 16,
                          _mm512_permutex2var_epi64(vl, idx0, vr));
      _mm512_storeu_si512(out + (leaf + 4) * 16,
                          _mm512_permutex2var_epi64(vl, idx1, vr));
      continue;
    }
    _mm512_store_si512(blk_l, hl);
    _mm512_store_si512(blk_r, hr);
    for (int j = 0; j < 4; ++j) {
      const size_t leaf = 2 * (i + j);
      emit_corrected_elements(blk_l + 2 * j, tl[j], vc, value_bits, is_xor,
                              party, keep_per_block, lo_mask, hi_mask,
                              elem_bytes, out + leaf * leaf_bytes);
      emit_corrected_elements(blk_r + 2 * j, tr[j], vc, value_bits, is_xor,
                              party, keep_per_block, lo_mask, hi_mask,
                              elem_bytes, out + (leaf + 1) * leaf_bytes);
    }
  }
}

#else
inline void mmo_hash_vaes(const __m128i*, const uint8_t*, uint8_t*, size_t,
                          size_t) {}
inline void expand_level_vaes(const __m128i*, const __m128i*, __m128i,
                              uint8_t, uint8_t, const uint8_t*,
                              const uint8_t*, uint8_t*, uint8_t*, size_t,
                              size_t) {}
inline void finish_tree_values_vaes(const __m128i*, const __m128i*,
                                    const __m128i*, const uint8_t*,
                                    const uint8_t*, __m128i, uint8_t, uint8_t,
                                    int, size_t, size_t, const uint64_t*, int,
                                    int, int, uint64_t, uint64_t, size_t,
                                    size_t, bool, __m128i, uint8_t*) {}

#endif


#if defined(DPF_HAVE_VAES)
// VAES range of the point-evaluation walk: 8 seeds per iteration as two
// 512-bit groups; per-lane PRG key selection is one masked qword XOR per
// round (rk = rl ^ (rdiff & path_bit_mask)).
DPF_VAES_TARGET void evaluate_seeds_vaes_range(
    const __m128i* rl128, const __m128i* rdiff128, const uint8_t* seeds_in,
    const uint8_t* ctl_in, const uint8_t* paths, const uint8_t* cw_seeds,
    const uint8_t* cw_left, const uint8_t* cw_right, int levels,
    size_t begin, size_t end, uint8_t* seeds_out, uint8_t* ctl_out) {
  __m512i rl[11], rdiff[11];
  for (int i = 0; i < 11; ++i) {
    rl[i] = _mm512_broadcast_i32x4(rl128[i]);
    rdiff[i] = _mm512_broadcast_i32x4(rdiff128[i]);
  }
  const __m512i low_bit512 =
      _mm512_maskz_set1_epi64(static_cast<__mmask8>(0x55), 1);
  for (size_t i0 = begin; i0 + 8 <= end; i0 += 8) {
    __m512i s[2];
    s[0] = _mm512_loadu_si512(seeds_in + 16 * i0);
    s[1] = _mm512_loadu_si512(seeds_in + 16 * (i0 + 4));
    uint64_t path_lo[8], path_hi[8];
    uint8_t t[8];
    for (int j = 0; j < 8; ++j) {
      const uint64_t* p =
          reinterpret_cast<const uint64_t*>(paths + 16 * (i0 + j));
      path_lo[j] = p[0];
      path_hi[j] = p[1];
      t[j] = ctl_in[i0 + j];
    }
    for (int level = 0; level < levels; ++level) {
      const int bit_index = levels - 1 - level;
      const __m512i cw512 = _mm512_broadcast_i32x4(_mm_loadu_si128(
          reinterpret_cast<const __m128i*>(cw_seeds + 16 * level)));
      const uint8_t ccl = cw_left[level], ccr = cw_right[level];
      uint8_t bit[8];
      __mmask8 km[2], tm[2];
      for (int g = 0; g < 2; ++g) {
        uint8_t m = 0, tmg = 0;
        for (int j = 0; j < 4; ++j) {
          const int q = 4 * g + j;
          bit[q] = static_cast<uint8_t>(
              (bit_index >= 128)
                  ? 0
                  : (((bit_index < 64 ? path_lo[q] : path_hi[q]) >>
                      (bit_index & 63)) &
                     1));
          if (bit[q]) m |= static_cast<uint8_t>(0x03 << (2 * j));
          if (t[q]) tmg |= static_cast<uint8_t>(0x03 << (2 * j));
        }
        km[g] = m;
        tm[g] = tmg;
      }
      __m512i sg[2], b[2];
      for (int g = 0; g < 2; ++g) {
        sg[g] = sigma512(s[g]);
        b[g] = _mm512_xor_si512(
            sg[g], _mm512_mask_xor_epi64(rl[0], km[g], rl[0], rdiff[0]));
      }
      for (int r = 1; r < 10; ++r)
        for (int g = 0; g < 2; ++g)
          b[g] = _mm512_aesenc_epi128(
              b[g], _mm512_mask_xor_epi64(rl[r], km[g], rl[r], rdiff[r]));
      for (int g = 0; g < 2; ++g) {
        b[g] = _mm512_xor_si512(
            _mm512_aesenclast_epi128(
                b[g], _mm512_mask_xor_epi64(rl[10], km[g], rl[10], rdiff[10])),
            sg[g]);
        b[g] = _mm512_mask_xor_epi64(b[g], tm[g], b[g], cw512);
        const __mmask8 k8 = _mm512_test_epi64_mask(b[g], low_bit512);
        for (int j = 0; j < 4; ++j) {
          const int q = 4 * g + j;
          const uint8_t nt = static_cast<uint8_t>((k8 >> (2 * j)) & 1);
          t[q] = static_cast<uint8_t>(nt ^ (t[q] & (bit[q] ? ccr : ccl)));
        }
        s[g] = _mm512_andnot_si512(low_bit512, b[g]);
      }
    }
    _mm512_storeu_si512(seeds_out + 16 * i0, s[0]);
    _mm512_storeu_si512(seeds_out + 16 * (i0 + 4), s[1]);
    for (int j = 0; j < 8; ++j) ctl_out[i0 + j] = t[j];
  }
}
#endif  // DPF_HAVE_VAES

}  // namespace

extern "C" {

int dpf_native_available() { return 1; }

// 16-byte key -> 11 x 16-byte round keys.
void dpf_expand_key(const uint8_t* key, uint8_t* rks_out) {
  __m128i rks[11];
  rks[0] = _mm_loadu_si128(reinterpret_cast<const __m128i*>(key));
  rks[1] = expand_step(rks[0], _mm_aeskeygenassist_si128(rks[0], 0x01));
  rks[2] = expand_step(rks[1], _mm_aeskeygenassist_si128(rks[1], 0x02));
  rks[3] = expand_step(rks[2], _mm_aeskeygenassist_si128(rks[2], 0x04));
  rks[4] = expand_step(rks[3], _mm_aeskeygenassist_si128(rks[3], 0x08));
  rks[5] = expand_step(rks[4], _mm_aeskeygenassist_si128(rks[4], 0x10));
  rks[6] = expand_step(rks[5], _mm_aeskeygenassist_si128(rks[5], 0x20));
  rks[7] = expand_step(rks[6], _mm_aeskeygenassist_si128(rks[6], 0x40));
  rks[8] = expand_step(rks[7], _mm_aeskeygenassist_si128(rks[7], 0x80));
  rks[9] = expand_step(rks[8], _mm_aeskeygenassist_si128(rks[8], 0x1B));
  rks[10] = expand_step(rks[9], _mm_aeskeygenassist_si128(rks[9], 0x36));
  for (int i = 0; i < 11; ++i)
    _mm_storeu_si128(reinterpret_cast<__m128i*>(rks_out + 16 * i), rks[i]);
}

// MMO hash of n blocks: out[i] = AES_k(sigma(in[i])) ^ sigma(in[i]).
// 8-wide unrolled to keep the AES units' pipelines full (the same reason
// the reference batches 64 blocks through EVP and pipelines 4 vectors).
void dpf_mmo_hash(const uint8_t* rks_bytes, const uint8_t* in, uint8_t* out,
                  size_t n) {
  __m128i rks[11];
  load_rks(rks_bytes, rks);
  parallel_ranges(n, 16, [&](size_t begin, size_t end) {
  size_t i = begin;
  if (use_vaes() && end - i >= 16) {
    const size_t bulk = i + ((end - i) / 16) * 16;
    mmo_hash_vaes(rks, in, out, i, bulk);
    i = bulk;
  }
  for (; i + 8 <= end; i += 8) {
    __m128i s[8];
    for (int j = 0; j < 8; ++j)
      s[j] = sigma(_mm_loadu_si128(
          reinterpret_cast<const __m128i*>(in + 16 * (i + j))));
    __m128i b[8];
    for (int j = 0; j < 8; ++j) b[j] = _mm_xor_si128(s[j], rks[0]);
    for (int r = 1; r < 10; ++r)
      for (int j = 0; j < 8; ++j) b[j] = _mm_aesenc_si128(b[j], rks[r]);
    for (int j = 0; j < 8; ++j) {
      b[j] = _mm_xor_si128(_mm_aesenclast_si128(b[j], rks[10]), s[j]);
      _mm_storeu_si128(reinterpret_cast<__m128i*>(out + 16 * (i + j)), b[j]);
    }
  }
  for (; i < end; ++i) {
    __m128i s =
        sigma(_mm_loadu_si128(reinterpret_cast<const __m128i*>(in + 16 * i)));
    __m128i e = _mm_xor_si128(encrypt(s, rks), s);
    _mm_storeu_si128(reinterpret_cast<__m128i*>(out + 16 * i), e);
  }
  });
}

// Two-key MMO hash with per-block key selection (mask[i] != 0 -> right key):
// the evaluate-path primitive where each lane walks left or right.
void dpf_mmo_hash_masked(const uint8_t* rks_left, const uint8_t* rks_right,
                         const uint8_t* in, const uint8_t* mask, uint8_t* out,
                         size_t n) {
  __m128i rl[11], rr[11];
  load_rks(rks_left, rl);
  load_rks(rks_right, rr);
  // Per-block round keys via blend: rk = rl ^ ((rl ^ rr) & m).
  __m128i rdiff[11];
  for (int i = 0; i < 11; ++i) rdiff[i] = _mm_xor_si128(rl[i], rr[i]);
  for (size_t i = 0; i < n; ++i) {
    __m128i m = _mm_set1_epi8(mask[i] ? static_cast<char>(0xFF) : 0);
    __m128i s =
        sigma(_mm_loadu_si128(reinterpret_cast<const __m128i*>(in + 16 * i)));
    __m128i b = _mm_xor_si128(
        s, _mm_xor_si128(rl[0], _mm_and_si128(rdiff[0], m)));
    for (int r = 1; r < 10; ++r)
      b = _mm_aesenc_si128(
          b, _mm_xor_si128(rl[r], _mm_and_si128(rdiff[r], m)));
    b = _mm_aesenclast_si128(
        b, _mm_xor_si128(rl[10], _mm_and_si128(rdiff[10], m)));
    b = _mm_xor_si128(b, s);
    _mm_storeu_si128(reinterpret_cast<__m128i*>(out + 16 * i), b);
  }
}

// Batched point-evaluation walk: n seeds descend `levels` tree levels, each
// along its own 128-bit path (the EvaluateAt hot loop,
// /root/reference/dpf/internal/evaluate_prg_hwy.cc:205-304). Per level the
// PRG key is selected by the path bit (rk = rl ^ (rdiff & mask), the same
// per-lane blend the reference does in Highway registers), the correction
// seed is XORed where the control bit is set, and the new control bit is
// extracted from the seed LSB and corrected. Seeds stay in registers across
// all levels, 8 lanes pipelined to keep the AES units full.
//
//   seeds/paths: n x 16 bytes; ctl: n bytes (0/1), updated in place in the
//   output buffers; cw_seeds: levels x 16; cw_left/right: levels bytes.
//   Path bit for level l is bit (levels - 1 - l) of the path (bits >= 128
//   read as 0).
void dpf_evaluate_seeds(const uint8_t* rks_left, const uint8_t* rks_right,
                        const uint8_t* seeds_in, const uint8_t* ctl_in,
                        const uint8_t* paths, const uint8_t* cw_seeds,
                        const uint8_t* cw_left, const uint8_t* cw_right,
                        size_t n, int levels, uint8_t* seeds_out,
                        uint8_t* ctl_out) {
  __m128i rl[11], rdiff[11];
  load_rks(rks_left, rl);
  {
    __m128i rr[11];
    load_rks(rks_right, rr);
    for (int i = 0; i < 11; ++i) rdiff[i] = _mm_xor_si128(rl[i], rr[i]);
  }
  const __m128i low_bit = _mm_set_epi64x(0, 1);

  parallel_ranges(n, 8, [&](size_t begin, size_t end) {
  size_t i = begin;
#if defined(DPF_HAVE_VAES)
  if (use_vaes() && end - i >= 8) {
    const size_t bulk = i + ((end - i) / 8) * 8;
    evaluate_seeds_vaes_range(rl, rdiff, seeds_in, ctl_in, paths, cw_seeds,
                              cw_left, cw_right, levels, i, bulk, seeds_out,
                              ctl_out);
    i = bulk;
  }
#endif
  for (; i + 8 <= end; i += 8) {
    __m128i s[8];
    uint64_t path_lo[8], path_hi[8];
    uint8_t t[8];
    for (int j = 0; j < 8; ++j) {
      s[j] = _mm_loadu_si128(
          reinterpret_cast<const __m128i*>(seeds_in + 16 * (i + j)));
      const uint64_t* p =
          reinterpret_cast<const uint64_t*>(paths + 16 * (i + j));
      path_lo[j] = p[0];
      path_hi[j] = p[1];
      t[j] = ctl_in[i + j];
    }
    for (int level = 0; level < levels; ++level) {
      const int bit_index = levels - 1 - level;
      const __m128i cw = _mm_loadu_si128(
          reinterpret_cast<const __m128i*>(cw_seeds + 16 * level));
      const uint8_t ccl = cw_left[level], ccr = cw_right[level];
      __m128i m[8], sg[8], b[8];
      uint8_t bit[8];
      for (int j = 0; j < 8; ++j) {
        bit[j] =
            (bit_index >= 128)
                ? 0
                : static_cast<uint8_t>(
                      ((bit_index < 64 ? path_lo[j] : path_hi[j]) >>
                       (bit_index & 63)) &
                      1);
        m[j] = _mm_set1_epi8(bit[j] ? static_cast<char>(0xFF) : 0);
        sg[j] = sigma(s[j]);
        b[j] = _mm_xor_si128(
            sg[j], _mm_xor_si128(rl[0], _mm_and_si128(rdiff[0], m[j])));
      }
      for (int r = 1; r < 10; ++r)
        for (int j = 0; j < 8; ++j)
          b[j] = _mm_aesenc_si128(
              b[j], _mm_xor_si128(rl[r], _mm_and_si128(rdiff[r], m[j])));
      for (int j = 0; j < 8; ++j) {
        b[j] = _mm_xor_si128(
            _mm_aesenclast_si128(
                b[j], _mm_xor_si128(rl[10], _mm_and_si128(rdiff[10], m[j]))),
            sg[j]);
        if (t[j]) b[j] = _mm_xor_si128(b[j], cw);
        uint8_t nt = static_cast<uint8_t>(_mm_cvtsi128_si64(b[j]) & 1);
        t[j] = static_cast<uint8_t>(nt ^ (t[j] & (bit[j] ? ccr : ccl)));
        s[j] = _mm_andnot_si128(low_bit, b[j]);
      }
    }
    for (int j = 0; j < 8; ++j) {
      _mm_storeu_si128(reinterpret_cast<__m128i*>(seeds_out + 16 * (i + j)),
                       s[j]);
      ctl_out[i + j] = t[j];
    }
  }
  for (; i < end; ++i) {  // scalar tail
    __m128i s =
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(seeds_in + 16 * i));
    const uint64_t* p = reinterpret_cast<const uint64_t*>(paths + 16 * i);
    uint8_t t = ctl_in[i];
    for (int level = 0; level < levels; ++level) {
      const int bit_index = levels - 1 - level;
      const uint8_t bit =
          (bit_index >= 128)
              ? 0
              : static_cast<uint8_t>(
                    ((bit_index < 64 ? p[0] : p[1]) >> (bit_index & 63)) & 1);
      const __m128i m = _mm_set1_epi8(bit ? static_cast<char>(0xFF) : 0);
      const __m128i sg = sigma(s);
      __m128i b = _mm_xor_si128(
          sg, _mm_xor_si128(rl[0], _mm_and_si128(rdiff[0], m)));
      for (int r = 1; r < 10; ++r)
        b = _mm_aesenc_si128(
            b, _mm_xor_si128(rl[r], _mm_and_si128(rdiff[r], m)));
      b = _mm_xor_si128(
          _mm_aesenclast_si128(
              b, _mm_xor_si128(rl[10], _mm_and_si128(rdiff[10], m))),
          sg);
      if (t)
        b = _mm_xor_si128(b, _mm_loadu_si128(reinterpret_cast<const __m128i*>(
                                 cw_seeds + 16 * level)));
      uint8_t nt = static_cast<uint8_t>(_mm_cvtsi128_si64(b) & 1);
      t = static_cast<uint8_t>(nt ^ (t & (bit ? cw_right[level] : cw_left[level])));
      s = _mm_andnot_si128(low_bit, b);
    }
    _mm_storeu_si128(reinterpret_cast<__m128i*>(seeds_out + 16 * i), s);
    ctl_out[i] = t;
  }
  });
}

// Doubling expansion of a *forest*: n root seeds expand `levels` levels to
// n << levels leaves (root j's leaves land contiguously at
// [j << levels, (j+1) << levels)), sharing one set of correction words —
// the ExpandSeeds hot loop (distributed_point_function.cc:271-349) for a
// batch of prefix seeds inside one key. Children of node i go to 2i and
// 2i+1, so the per-level layout is bit-identical to the host oracle's
// interleaved [l0, r0, l1, r1, ...]. 4 parents (8 AES streams) pipelined.
void dpf_expand_forest(const uint8_t* rks_left, const uint8_t* rks_right,
                       const uint8_t* seeds0, const uint8_t* ctl0,
                       const uint8_t* cw_seeds, const uint8_t* cw_left,
                       const uint8_t* cw_right, size_t n, int levels,
                       uint8_t* out_seeds, uint8_t* out_control,
                       uint8_t* scratch) {
  __m128i rl[11], rr[11];
  load_rks(rks_left, rl);
  load_rks(rks_right, rr);
  const __m128i low_bit = _mm_set_epi64x(0, 1);

  // Seeds ping-pong between scratch and out_seeds so the final level lands
  // in out_seeds; control bits ping-pong between out_control and an
  // internal scratch (dual buffers keep every parent read disjoint from
  // every child write, which lets levels split across worker threads — the
  // old single-buffer reverse-walk trick serializes).
  uint8_t* cur = (levels % 2 == 0) ? out_seeds : scratch;
  uint8_t* nxt = (levels % 2 == 0) ? scratch : out_seeds;
  // The scratch only ever holds an intermediate level (the final level's
  // parity lands in out_control), so half the output size suffices;
  // new[] leaves it uninitialized — no memset of up-to-gigabyte buffers.
  const size_t scratch_ctl_size =
      levels > 0 ? (n << (levels - 1)) : n;
  std::unique_ptr<uint8_t[]> ctl_scratch(new uint8_t[scratch_ctl_size]);
  uint8_t* ctl_cur = (levels % 2 == 0) ? out_control : ctl_scratch.get();
  uint8_t* ctl_nxt = (levels % 2 == 0) ? ctl_scratch.get() : out_control;
  for (size_t i = 0; i < 16 * n; ++i) cur[i] = seeds0[i];
  for (size_t i = 0; i < n; ++i) ctl_cur[i] = ctl0[i];

  for (int level = 0; level < levels; ++level) {
    const size_t parents = n << level;
    const __m128i cw = _mm_loadu_si128(
        reinterpret_cast<const __m128i*>(cw_seeds + 16 * level));
    const uint8_t ccl = cw_left[level], ccr = cw_right[level];
    parallel_ranges(parents, 4, [&](size_t a, size_t bnd) {
      size_t i = a;
      if (use_vaes() && bnd - i >= 4) {
        const size_t bulk = i + ((bnd - i) / 4) * 4;
        expand_level_vaes(rl, rr, cw, ccl, ccr, cur, ctl_cur, nxt, ctl_nxt,
                          i, bulk);
        i = bulk;
      }
      for (; i + 4 <= bnd; i += 4) {
        __m128i sg[4], bl[4], br[4];
        uint8_t t[4];
        for (int j = 0; j < 4; ++j) {
          sg[j] = sigma(_mm_loadu_si128(
              reinterpret_cast<const __m128i*>(cur + 16 * (i + j))));
          t[j] = ctl_cur[i + j];
          bl[j] = _mm_xor_si128(sg[j], rl[0]);
          br[j] = _mm_xor_si128(sg[j], rr[0]);
        }
        for (int r = 1; r < 10; ++r)
          for (int j = 0; j < 4; ++j) {
            bl[j] = _mm_aesenc_si128(bl[j], rl[r]);
            br[j] = _mm_aesenc_si128(br[j], rr[r]);
          }
        for (int j = 0; j < 4; ++j) {
          const __m128i corr = t[j] ? cw : _mm_setzero_si128();
          bl[j] = _mm_xor_si128(
              _mm_xor_si128(_mm_aesenclast_si128(bl[j], rl[10]), sg[j]), corr);
          br[j] = _mm_xor_si128(
              _mm_xor_si128(_mm_aesenclast_si128(br[j], rr[10]), sg[j]), corr);
          const size_t c = 2 * (i + j);
          ctl_nxt[c] = static_cast<uint8_t>((_mm_cvtsi128_si64(bl[j]) & 1) ^
                                            (t[j] & ccl));
          ctl_nxt[c + 1] = static_cast<uint8_t>(
              (_mm_cvtsi128_si64(br[j]) & 1) ^ (t[j] & ccr));
          _mm_storeu_si128(reinterpret_cast<__m128i*>(nxt + 16 * c),
                           _mm_andnot_si128(low_bit, bl[j]));
          _mm_storeu_si128(reinterpret_cast<__m128i*>(nxt + 16 * (c + 1)),
                           _mm_andnot_si128(low_bit, br[j]));
        }
      }
      for (; i < bnd; ++i) {
        const __m128i sg = sigma(
            _mm_loadu_si128(reinterpret_cast<const __m128i*>(cur + 16 * i)));
        const uint8_t t = ctl_cur[i];
        const __m128i corr = t ? cw : _mm_setzero_si128();
        __m128i bl = _mm_xor_si128(sg, rl[0]);
        __m128i br = _mm_xor_si128(sg, rr[0]);
        for (int r = 1; r < 10; ++r) {
          bl = _mm_aesenc_si128(bl, rl[r]);
          br = _mm_aesenc_si128(br, rr[r]);
        }
        bl = _mm_xor_si128(
            _mm_xor_si128(_mm_aesenclast_si128(bl, rl[10]), sg), corr);
        br = _mm_xor_si128(
            _mm_xor_si128(_mm_aesenclast_si128(br, rr[10]), sg), corr);
        ctl_nxt[2 * i] =
            static_cast<uint8_t>((_mm_cvtsi128_si64(bl) & 1) ^ (t & ccl));
        ctl_nxt[2 * i + 1] =
            static_cast<uint8_t>((_mm_cvtsi128_si64(br) & 1) ^ (t & ccr));
        _mm_storeu_si128(reinterpret_cast<__m128i*>(nxt + 16 * (2 * i)),
                         _mm_andnot_si128(low_bit, bl));
        _mm_storeu_si128(reinterpret_cast<__m128i*>(nxt + 16 * (2 * i + 1)),
                         _mm_andnot_si128(low_bit, br));
      }
    });
    uint8_t* tmp = cur;
    cur = nxt;
    nxt = tmp;
    uint8_t* ctmp = ctl_cur;
    ctl_cur = ctl_nxt;
    ctl_nxt = ctmp;
  }
}

// Fused tail of full-domain evaluation of one key: expands the LAST tree
// level from the 2^(levels-1) parent seeds, value-hashes each child in the
// same register file, applies the value correction under the child control
// bit and the party negation, and writes ONLY the output element bytes.
// The separate passes it replaces (final expand writes 16 B/leaf, value
// hash reads+writes 32 B/leaf, numpy correction reads 16 B/leaf) made the
// host engine DRAM-bound; this pass streams 16 B/parent in and
// keep*bits/8 B/leaf out. Values travel as raw little-endian bytes —
// out[(leaf*keep + e) * bits/8 ...] — exactly the ConvertBytesToArrayOf
// layout (/root/reference/dpf/internal/value_type_helpers.h:506-520).
//
//   parents:      2^(levels-1) seeds (from dpf_expand_forest at levels-1)
//   vc:           epb x (lo, hi) uint64 value corrections
//   ctl_parents:  2^(levels-1) bytes
//   out:          2^levels * keep * (value_bits/8) bytes
void dpf_finish_tree_values(
    const uint8_t* rks_left, const uint8_t* rks_right, const uint8_t* rks_value,
    const uint8_t* parents, const uint8_t* ctl_parents, const uint8_t* cw_seed,
    uint8_t cw_ctl_left, uint8_t cw_ctl_right, int party, size_t n_parents,
    const uint64_t* vc, int value_bits, int is_xor, int keep_per_block,
    uint8_t* out) {
  __m128i rl[11], rr[11], rv[11];
  load_rks(rks_left, rl);
  load_rks(rks_right, rr);
  load_rks(rks_value, rv);
  const __m128i low_bit = _mm_set_epi64x(0, 1);
  const __m128i cw = _mm_loadu_si128(reinterpret_cast<const __m128i*>(cw_seed));
  const uint64_t lo_mask =
      value_bits >= 64 ? ~0ULL : ((1ULL << value_bits) - 1);
  const uint64_t hi_mask = value_bits >= 128 ? ~0ULL : 0;
  const size_t elem_bytes = static_cast<size_t>(value_bits) / 8;
  const size_t leaf_bytes = elem_bytes * keep_per_block;
  // Full-block outputs take the vectorized lane-wise correction + a direct
  // 16-byte store; partial blocks / 128-bit go through the scalar emitter.
  const bool full_vec =
      value_bits <= 64 && keep_per_block * value_bits == 128;
  __m128i vc_vec = _mm_setzero_si128();
  if (full_vec) {
    uint8_t tmp[16] = {0};
    for (int e = 0; e < keep_per_block; ++e)
      std::memcpy(tmp + e * elem_bytes, vc + 2 * e, elem_bytes);
    vc_vec = _mm_loadu_si128(reinterpret_cast<const __m128i*>(tmp));
  }

  // One child's hash block -> corrected output elements.
  auto emit = [&](const __m128i hashed, uint8_t ctrl, uint8_t* dst) {
    if (full_vec) {
      _mm_storeu_si128(
          reinterpret_cast<__m128i*>(dst),
          correct_block_vec(hashed, ctrl, vc_vec, value_bits, is_xor, party));
      return;
    }
    uint64_t blk[2];
    _mm_storeu_si128(reinterpret_cast<__m128i*>(blk), hashed);
    emit_corrected_elements(blk, ctrl, vc, value_bits, is_xor, party,
                            keep_per_block, lo_mask, hi_mask, elem_bytes,
                            dst);
  };

  parallel_ranges(n_parents, 4, [&](size_t begin, size_t end) {
    size_t i = begin;
    if (use_vaes() && end - i >= 4) {
      const size_t bulk = i + ((end - i) / 4) * 4;
      finish_tree_values_vaes(rl, rr, rv, parents, ctl_parents, cw,
                              cw_ctl_left, cw_ctl_right, party, i, bulk, vc,
                              value_bits, is_xor, keep_per_block, lo_mask,
                              hi_mask, elem_bytes, leaf_bytes, full_vec,
                              vc_vec, out);
      i = bulk;
    }
    for (; i + 4 <= end; i += 4) {
      // 8 walk-AES streams (4 parents x {left, right} children)...
      __m128i sg[4], bl[4], br[4];
      uint8_t t[4];
      for (int j = 0; j < 4; ++j) {
        sg[j] = sigma(_mm_loadu_si128(
            reinterpret_cast<const __m128i*>(parents + 16 * (i + j))));
        t[j] = ctl_parents[i + j];
        bl[j] = _mm_xor_si128(sg[j], rl[0]);
        br[j] = _mm_xor_si128(sg[j], rr[0]);
      }
      for (int r = 1; r < 10; ++r)
        for (int j = 0; j < 4; ++j) {
          bl[j] = _mm_aesenc_si128(bl[j], rl[r]);
          br[j] = _mm_aesenc_si128(br[j], rr[r]);
        }
      // ...then 8 value-AES streams over the children, same registers.
      __m128i cl[4], cr[4], vgl[4], vgr[4];
      uint8_t tl[4], tr[4];
      for (int j = 0; j < 4; ++j) {
        const __m128i corr = t[j] ? cw : _mm_setzero_si128();
        __m128i l = _mm_xor_si128(
            _mm_xor_si128(_mm_aesenclast_si128(bl[j], rl[10]), sg[j]), corr);
        __m128i r = _mm_xor_si128(
            _mm_xor_si128(_mm_aesenclast_si128(br[j], rr[10]), sg[j]), corr);
        tl[j] = static_cast<uint8_t>((_mm_cvtsi128_si64(l) & 1) ^
                                     (t[j] & cw_ctl_left));
        tr[j] = static_cast<uint8_t>((_mm_cvtsi128_si64(r) & 1) ^
                                     (t[j] & cw_ctl_right));
        l = _mm_andnot_si128(low_bit, l);
        r = _mm_andnot_si128(low_bit, r);
        vgl[j] = sigma(l);
        vgr[j] = sigma(r);
        cl[j] = _mm_xor_si128(vgl[j], rv[0]);
        cr[j] = _mm_xor_si128(vgr[j], rv[0]);
      }
      for (int r = 1; r < 10; ++r)
        for (int j = 0; j < 4; ++j) {
          cl[j] = _mm_aesenc_si128(cl[j], rv[r]);
          cr[j] = _mm_aesenc_si128(cr[j], rv[r]);
        }
      for (int j = 0; j < 4; ++j) {
        const __m128i hl =
            _mm_xor_si128(_mm_aesenclast_si128(cl[j], rv[10]), vgl[j]);
        const __m128i hr =
            _mm_xor_si128(_mm_aesenclast_si128(cr[j], rv[10]), vgr[j]);
        const size_t leaf = 2 * (i + j);
        emit(hl, tl[j], out + leaf * leaf_bytes);
        emit(hr, tr[j], out + (leaf + 1) * leaf_bytes);
      }
    }
    for (; i < end; ++i) {  // scalar tail
      const __m128i sg = sigma(_mm_loadu_si128(
          reinterpret_cast<const __m128i*>(parents + 16 * i)));
      const uint8_t t = ctl_parents[i];
      const __m128i corr = t ? cw : _mm_setzero_si128();
      __m128i bl = _mm_xor_si128(sg, rl[0]);
      __m128i br = _mm_xor_si128(sg, rr[0]);
      for (int r = 1; r < 10; ++r) {
        bl = _mm_aesenc_si128(bl, rl[r]);
        br = _mm_aesenc_si128(br, rr[r]);
      }
      bl = _mm_xor_si128(
          _mm_xor_si128(_mm_aesenclast_si128(bl, rl[10]), sg), corr);
      br = _mm_xor_si128(
          _mm_xor_si128(_mm_aesenclast_si128(br, rr[10]), sg), corr);
      const uint8_t tl = static_cast<uint8_t>((_mm_cvtsi128_si64(bl) & 1) ^
                                              (t & cw_ctl_left));
      const uint8_t tr = static_cast<uint8_t>((_mm_cvtsi128_si64(br) & 1) ^
                                              (t & cw_ctl_right));
      bl = _mm_andnot_si128(low_bit, bl);
      br = _mm_andnot_si128(low_bit, br);
      const __m128i vgl = sigma(bl), vgr = sigma(br);
      const __m128i hl = _mm_xor_si128(encrypt(vgl, rv), vgl);
      const __m128i hr = _mm_xor_si128(encrypt(vgr, rv), vgr);
      const size_t leaf = 2 * i;
      emit(hl, tl, out + leaf * leaf_bytes);
      emit(hr, tr, out + (leaf + 1) * leaf_bytes);
    }
  });
}

// Value hash + correction only (the levels == 0 shape of
// dpf_finish_tree_values: the seeds are already the leaves).
void dpf_hash_correct_values(
    const uint8_t* rks_value, const uint8_t* leaves, const uint8_t* ctl,
    int party, size_t n_leaves, const uint64_t* vc, int value_bits,
    int is_xor, int keep_per_block, uint8_t* out) {
  __m128i rv[11];
  load_rks(rks_value, rv);
  const uint64_t lo_mask =
      value_bits >= 64 ? ~0ULL : ((1ULL << value_bits) - 1);
  const uint64_t hi_mask = value_bits >= 128 ? ~0ULL : 0;
  const size_t elem_bytes = static_cast<size_t>(value_bits) / 8;
  const size_t leaf_bytes = elem_bytes * keep_per_block;
  parallel_ranges(n_leaves, 8, [&](size_t begin, size_t end) {
    for (size_t i = begin; i < end; ++i) {
      const __m128i sg = sigma(_mm_loadu_si128(
          reinterpret_cast<const __m128i*>(leaves + 16 * i)));
      const __m128i h = _mm_xor_si128(encrypt(sg, rv), sg);
      uint64_t blk[2];
      _mm_storeu_si128(reinterpret_cast<__m128i*>(blk), h);
      emit_corrected_elements(blk, ctl[i], vc, value_bits, is_xor, party,
                              keep_per_block, lo_mask, hi_mask, elem_bytes,
                              out + i * leaf_bytes);
    }
  });
}

// Fused batched DCF evaluation: each point walks the incremental DPF's
// tree ONCE; at every capturing depth d the current seed is value-hashed,
// the addressed element extracted, the value correction applied under the
// control bit, party-negated, and accumulated into the point's sum iff
// acc_mask says the point's bit at that level is 0 (f(x) = sum of prefix
// shares where bit_i(x) = 0,
// /root/reference/dcf/distributed_comparison_function.h:83-107 — but one
// walk total instead of one per bit). 4 points pipelined; value hash and
// walk AES interleave in the same registers.
//
// One templated walk, two accumulator policies: the descent/capture
// structure is shared and only "extract + correct + accumulate" differs
// (packed uint64 vs two-word (lo, hi) groups) — policies inline, so the
// generated code matches the previously hand-split kernels.
//
//   capture:   (T+1) bytes, 1 if a hierarchy level outputs at this depth
//   acc_mask:  (T+1) x P bytes (1 = accumulate)
//   block_sel: (T+1) x P int32 element index within the block
//   paths:     P x 16 bytes (tree index at the final depth)
}  // extern "C"

namespace {

// <= 64-bit additive Int: one uint64 accumulator per point.
struct DcfAccU64 {
  using Acc = uint64_t;
  const uint64_t* vc;  // [T+1, epb]
  uint64_t mask;
  int value_bits, epb, party;
  void init(Acc& a) const { a = 0; }
  void consume(Acc& a, const uint64_t blk[2], int depth, int32_t sel,
               uint8_t ctrl, uint8_t accumulate) const {
    const int bit_off = static_cast<int>(sel) * value_bits;
    uint64_t v = blk[bit_off >> 6] >> (bit_off & 63);
    v &= mask;
    if (ctrl) v = (v + vc[static_cast<size_t>(depth) * epb + sel]) & mask;
    if (party) v = (0 - v) & mask;
    if (accumulate) a = (a + v) & mask;
  }
  void store(uint64_t* out, size_t i, const Acc& a) const { out[i] = a; }
};

// Every scalar group up to 128 bits: (lo, hi) uint64 pair accumulators,
// additive (two-word carry/borrow) or XOR (no party negation).
struct DcfAccWide {
  struct Acc {
    uint64_t lo, hi;
  };
  const uint64_t* vc;  // [T+1, epb, 2]
  uint64_t lo_mask, hi_mask;
  int value_bits, epb, party, is_xor;
  void init(Acc& a) const { a.lo = a.hi = 0; }
  void consume(Acc& a, const uint64_t blk[2], int depth, int32_t sel,
               uint8_t ctrl, uint8_t accumulate) const {
    const int bit_off = static_cast<int>(sel) * value_bits;
    uint64_t v_lo = (blk[bit_off >> 6] >> (bit_off & 63)) & lo_mask;
    uint64_t v_hi = (value_bits > 64 ? blk[1] : 0) & hi_mask;
    const uint64_t* c = vc + (static_cast<size_t>(depth) * epb + sel) * 2;
    if (is_xor) {
      if (ctrl) {
        v_lo ^= c[0];
        v_hi ^= c[1];
      }
      if (accumulate) {
        a.lo ^= v_lo;
        a.hi ^= v_hi;
      }
      return;
    }
    if (ctrl) {
      const uint64_t s_lo = v_lo + c[0];
      v_hi = (v_hi + c[1] + (s_lo < v_lo ? 1 : 0)) & hi_mask;
      v_lo = s_lo & lo_mask;
    }
    if (party) {
      const uint64_t n_lo = (0 - v_lo) & lo_mask;
      v_hi = ((0 - v_hi) - (v_lo != 0 ? 1 : 0)) & hi_mask;
      v_lo = n_lo;
    }
    if (accumulate) {
      const uint64_t s_lo = a.lo + v_lo;
      a.hi = (a.hi + v_hi + (s_lo < a.lo ? 1 : 0)) & hi_mask;
      a.lo = s_lo & lo_mask;
    }
  }
  void store(uint64_t* out, size_t i, const Acc& a) const {
    out[i * 2] = a.lo;
    out[i * 2 + 1] = a.hi;
  }
};


#if defined(DPF_HAVE_VAES)
// VAES range of the fused DCF walk: 8 points per iteration as two 512-bit
// groups of 4; per-point PRG key selection is one masked qword XOR of the
// (rl, rl^rr) round-key pair per AES round. Captures hash in the same
// register file; element extract/correct/accumulate stays scalar via the
// policy (a few ops per point per depth — not the hot part).
template <typename Policy, typename OutT>
DPF_VAES_TARGET void dcf_walk_vaes_range(
    const __m128i* rl128, const __m128i* rdiff128, const __m128i* rv128,
    const uint8_t* seed0, int party, const uint8_t* cw_seeds,
    const uint8_t* cw_left, const uint8_t* cw_right, const uint8_t* capture,
    const uint8_t* acc_mask, const int32_t* block_sel, const uint8_t* paths,
    int levels, size_t stride, size_t begin, size_t end,
    const Policy& policy, OutT* out) {
  __m512i rl[11], rdiff[11], rv[11];
  for (int i = 0; i < 11; ++i) {
    rl[i] = _mm512_broadcast_i32x4(rl128[i]);
    rdiff[i] = _mm512_broadcast_i32x4(rdiff128[i]);
    rv[i] = _mm512_broadcast_i32x4(rv128[i]);
  }
  const __m512i low_bit512 =
      _mm512_maskz_set1_epi64(static_cast<__mmask8>(0x55), 1);
  const __m512i seed512 = _mm512_broadcast_i32x4(
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(seed0)));
  alignas(64) uint64_t blk[8];
  for (size_t i0 = begin; i0 + 8 <= end; i0 += 8) {
    __m512i s[2] = {seed512, seed512};
    uint64_t path_lo[8], path_hi[8];
    typename Policy::Acc acc[8];
    uint8_t t[8];
    for (int j = 0; j < 8; ++j) {
      policy.init(acc[j]);
      const uint64_t* p =
          reinterpret_cast<const uint64_t*>(paths + 16 * (i0 + j));
      path_lo[j] = p[0];
      path_hi[j] = p[1];
      t[j] = static_cast<uint8_t>(party & 1);
    }
    for (int depth = 0; depth <= levels; ++depth) {
      if (capture[depth]) {
        __m512i sg[2], b[2];
        for (int g = 0; g < 2; ++g) {
          sg[g] = sigma512(s[g]);
          b[g] = _mm512_xor_si512(sg[g], rv[0]);
        }
        for (int r = 1; r < 10; ++r)
          for (int g = 0; g < 2; ++g) b[g] = _mm512_aesenc_epi128(b[g], rv[r]);
        for (int g = 0; g < 2; ++g) {
          b[g] = _mm512_xor_si512(_mm512_aesenclast_epi128(b[g], rv[10]),
                                  sg[g]);
          _mm512_store_si512(blk, b[g]);
          for (int j = 0; j < 4; ++j) {
            const size_t pt = i0 + 4 * g + j;
            policy.consume(acc[4 * g + j], blk + 2 * j, depth,
                           block_sel[depth * stride + pt], t[4 * g + j],
                           acc_mask[depth * stride + pt]);
          }
        }
      }
      if (depth == levels) break;
      const int bit_index = levels - 1 - depth;
      const __m512i cw512 = _mm512_broadcast_i32x4(_mm_loadu_si128(
          reinterpret_cast<const __m128i*>(cw_seeds + 16 * depth)));
      const uint8_t ccl = cw_left[depth], ccr = cw_right[depth];
      uint8_t bit[8];
      __mmask8 km[2], tm[2];
      for (int g = 0; g < 2; ++g) {
        uint8_t m = 0, tmg = 0;
        for (int j = 0; j < 4; ++j) {
          const int q = 4 * g + j;
          bit[q] = static_cast<uint8_t>(
              ((bit_index < 64 ? path_lo[q] : path_hi[q]) >>
               (bit_index & 63)) &
              1);
          if (bit[q]) m |= static_cast<uint8_t>(0x03 << (2 * j));
          if (t[q]) tmg |= static_cast<uint8_t>(0x03 << (2 * j));
        }
        km[g] = m;
        tm[g] = tmg;
      }
      __m512i sg[2], b[2];
      for (int g = 0; g < 2; ++g) {
        sg[g] = sigma512(s[g]);
        b[g] = _mm512_xor_si512(
            sg[g], _mm512_mask_xor_epi64(rl[0], km[g], rl[0], rdiff[0]));
      }
      for (int r = 1; r < 10; ++r)
        for (int g = 0; g < 2; ++g)
          b[g] = _mm512_aesenc_epi128(
              b[g], _mm512_mask_xor_epi64(rl[r], km[g], rl[r], rdiff[r]));
      for (int g = 0; g < 2; ++g) {
        b[g] = _mm512_xor_si512(
            _mm512_aesenclast_epi128(
                b[g], _mm512_mask_xor_epi64(rl[10], km[g], rl[10], rdiff[10])),
            sg[g]);
        b[g] = _mm512_mask_xor_epi64(b[g], tm[g], b[g], cw512);
        const __mmask8 k8 = _mm512_test_epi64_mask(b[g], low_bit512);
        for (int j = 0; j < 4; ++j) {
          const int q = 4 * g + j;
          const uint8_t nt = static_cast<uint8_t>((k8 >> (2 * j)) & 1);
          t[q] = static_cast<uint8_t>(nt ^ (t[q] & (bit[q] ? ccr : ccl)));
        }
        s[g] = _mm512_andnot_si512(low_bit512, b[g]);
      }
    }
    for (int j = 0; j < 8; ++j) policy.store(out, i0 + j, acc[j]);
  }
}
#endif  // DPF_HAVE_VAES

template <typename Policy, typename OutT>
void dcf_walk_impl(const uint8_t* rks_left, const uint8_t* rks_right,
                   const uint8_t* rks_value, const uint8_t* seed0, int party,
                   const uint8_t* cw_seeds, const uint8_t* cw_left,
                   const uint8_t* cw_right, const uint8_t* capture,
                   const uint8_t* acc_mask, const int32_t* block_sel,
                   const uint8_t* paths, int levels, size_t n_points,
                   const Policy& policy, OutT* out) {
  __m128i rl[11], rdiff[11], rv[11];
  load_rks(rks_left, rl);
  {
    __m128i rr[11];
    load_rks(rks_right, rr);
    for (int i = 0; i < 11; ++i) rdiff[i] = _mm_xor_si128(rl[i], rr[i]);
  }
  load_rks(rks_value, rv);
  const __m128i low_bit = _mm_set_epi64x(0, 1);
  const size_t stride = n_points;  // row stride of acc_mask / block_sel

  parallel_ranges(n_points, 8, [&](size_t begin, size_t end) {
  size_t start = begin;
#if defined(DPF_HAVE_VAES)
  if (use_vaes() && end - start >= 8) {
    const size_t bulk = start + ((end - start) / 8) * 8;
    dcf_walk_vaes_range(rl, rdiff, rv, seed0, party, cw_seeds, cw_left,
                        cw_right, capture, acc_mask, block_sel, paths,
                        levels, stride, start, bulk, policy, out);
    start = bulk;
  }
#endif
  for (size_t i0 = start; i0 < end; i0 += 4) {
    const int lanes = static_cast<int>(end - i0 < 4 ? end - i0 : 4);
    __m128i s[4];
    uint64_t path_lo[4] = {0}, path_hi[4] = {0};
    typename Policy::Acc acc[4];
    uint8_t t[4] = {0};
    for (int j = 0; j < lanes; ++j) {
      policy.init(acc[j]);
      s[j] = _mm_loadu_si128(reinterpret_cast<const __m128i*>(seed0));
      const uint64_t* p =
          reinterpret_cast<const uint64_t*>(paths + 16 * (i0 + j));
      path_lo[j] = p[0];
      path_hi[j] = p[1];
      t[j] = static_cast<uint8_t>(party & 1);
    }
    for (int depth = 0; depth <= levels; ++depth) {
      if (capture[depth]) {
        // Value hash of the current seeds, element select, correction
        // under control bit, party negation, masked accumulate — the
        // group-specific part lives in the policy.
        __m128i b[4], sg[4];
        for (int j = 0; j < lanes; ++j) {
          sg[j] = sigma(s[j]);
          b[j] = _mm_xor_si128(sg[j], rv[0]);
        }
        for (int r = 1; r < 10; ++r)
          for (int j = 0; j < lanes; ++j) b[j] = _mm_aesenc_si128(b[j], rv[r]);
        for (int j = 0; j < lanes; ++j) {
          b[j] = _mm_xor_si128(_mm_aesenclast_si128(b[j], rv[10]), sg[j]);
          uint64_t blk[2];
          _mm_storeu_si128(reinterpret_cast<__m128i*>(blk), b[j]);
          policy.consume(acc[j], blk, depth,
                         block_sel[depth * stride + i0 + j], t[j],
                         acc_mask[depth * stride + i0 + j]);
        }
      }
      if (depth == levels) break;
      // Walk one level: select the child along the point's path bit.
      const int bit_index = levels - 1 - depth;
      const __m128i cw = _mm_loadu_si128(
          reinterpret_cast<const __m128i*>(cw_seeds + 16 * depth));
      const uint8_t ccl = cw_left[depth], ccr = cw_right[depth];
      __m128i m[4], sg[4], b[4];
      uint8_t bit[4];
      for (int j = 0; j < lanes; ++j) {
        bit[j] = static_cast<uint8_t>(
            ((bit_index < 64 ? path_lo[j] : path_hi[j]) >> (bit_index & 63)) &
            1);
        m[j] = _mm_set1_epi8(bit[j] ? static_cast<char>(0xFF) : 0);
        sg[j] = sigma(s[j]);
        b[j] = _mm_xor_si128(
            sg[j], _mm_xor_si128(rl[0], _mm_and_si128(rdiff[0], m[j])));
      }
      for (int r = 1; r < 10; ++r)
        for (int j = 0; j < lanes; ++j)
          b[j] = _mm_aesenc_si128(
              b[j], _mm_xor_si128(rl[r], _mm_and_si128(rdiff[r], m[j])));
      for (int j = 0; j < lanes; ++j) {
        b[j] = _mm_xor_si128(
            _mm_aesenclast_si128(
                b[j], _mm_xor_si128(rl[10], _mm_and_si128(rdiff[10], m[j]))),
            sg[j]);
        if (t[j]) b[j] = _mm_xor_si128(b[j], cw);
        uint8_t nt = static_cast<uint8_t>(_mm_cvtsi128_si64(b[j]) & 1);
        t[j] = static_cast<uint8_t>(nt ^ (t[j] & (bit[j] ? ccr : ccl)));
        s[j] = _mm_andnot_si128(low_bit, b[j]);
      }
    }
    for (int j = 0; j < lanes; ++j) policy.store(out, i0 + j, acc[j]);
  }
  });
}

}  // namespace

extern "C" {

// <= 64-bit additive outputs; vc: (T+1) x epb uint64; out: P uint64.
void dpf_dcf_evaluate_u64(
    const uint8_t* rks_left, const uint8_t* rks_right, const uint8_t* rks_value,
    const uint8_t* seed0, int party, const uint8_t* cw_seeds,
    const uint8_t* cw_left, const uint8_t* cw_right, const uint64_t* vc,
    const uint8_t* capture, const uint8_t* acc_mask, const int32_t* block_sel,
    const uint8_t* paths, int value_bits, int epb, int levels /* T */,
    size_t n_points, uint64_t* out) {
  DcfAccU64 policy;
  policy.vc = vc;
  policy.mask = value_bits >= 64 ? ~0ULL : ((1ULL << value_bits) - 1);
  policy.value_bits = value_bits;
  policy.epb = epb;
  policy.party = party;
  dcf_walk_impl(rks_left, rks_right, rks_value, seed0, party, cw_seeds,
                cw_left, cw_right, capture, acc_mask, block_sel, paths,
                levels, n_points, policy, out);
}

// Every scalar group up to 128 bits (additive Int or XOR); values and
// corrections travel as (lo, hi) uint64 pairs; out: P x 2 uint64.
void dpf_dcf_evaluate_wide(
    const uint8_t* rks_left, const uint8_t* rks_right, const uint8_t* rks_value,
    const uint8_t* seed0, int party, const uint8_t* cw_seeds,
    const uint8_t* cw_left, const uint8_t* cw_right, const uint64_t* vc,
    const uint8_t* capture, const uint8_t* acc_mask, const int32_t* block_sel,
    const uint8_t* paths, int value_bits, int is_xor, int epb,
    int levels /* T */, size_t n_points, uint64_t* out) {
  DcfAccWide policy;
  policy.vc = vc;
  policy.lo_mask = value_bits >= 64 ? ~0ULL : ((1ULL << value_bits) - 1);
  policy.hi_mask =
      value_bits >= 128
          ? ~0ULL
          : (value_bits > 64 ? ((1ULL << (value_bits - 64)) - 1) : 0);
  policy.value_bits = value_bits;
  policy.epb = epb;
  policy.party = party;
  policy.is_xor = is_xor;
  dcf_walk_impl(rks_left, rks_right, rks_value, seed0, party, cw_seeds,
                cw_left, cw_right, capture, acc_mask, block_sel, paths,
                levels, n_points, policy, out);
}

// Value-PRG hash with block offsets: out[i*bn + j] = MMO(in[i] + j) for
// j < bn (HashExpandedSeeds, distributed_point_function.cc:500-524) — the
// uint128 + j addition and the hash in one native pass.
void dpf_value_hash(const uint8_t* rks_bytes, const uint8_t* in, size_t n,
                    int blocks_needed, uint8_t* out) {
  __m128i rks[11];
  load_rks(rks_bytes, rks);
  const size_t total = n * static_cast<size_t>(blocks_needed);
  parallel_ranges(total, 8, [&](size_t begin, size_t end) {
    __m128i s[8];
    size_t done = begin;
    while (done < end) {
      int lanes = 0;
      for (; lanes < 8 && done + lanes < end; ++lanes) {
        const size_t flat = done + lanes;
        const size_t i = flat / blocks_needed;
        const uint64_t j = static_cast<uint64_t>(flat % blocks_needed);
        const uint64_t* p = reinterpret_cast<const uint64_t*>(in + 16 * i);
        uint64_t lo = p[0] + j;
        uint64_t hi = p[1] + (lo < p[0] ? 1 : 0);
        s[lanes] = sigma(_mm_set_epi64x(static_cast<long long>(hi),
                                        static_cast<long long>(lo)));
      }
      __m128i b[8];
      for (int j = 0; j < lanes; ++j) b[j] = _mm_xor_si128(s[j], rks[0]);
      for (int r = 1; r < 10; ++r)
        for (int j = 0; j < lanes; ++j) b[j] = _mm_aesenc_si128(b[j], rks[r]);
      for (int j = 0; j < lanes; ++j) {
        b[j] = _mm_xor_si128(_mm_aesenclast_si128(b[j], rks[10]), s[j]);
        _mm_storeu_si128(
            reinterpret_cast<__m128i*>(out + 16 * (done + j)), b[j]);
      }
      done += lanes;
    }
  });
}

}  // extern "C"

#else  // no AES-NI at compile time

extern "C" {
int dpf_native_available() { return 0; }
void dpf_expand_key(const uint8_t*, uint8_t*) {}
void dpf_mmo_hash(const uint8_t*, const uint8_t*, uint8_t*, size_t) {}
void dpf_mmo_hash_masked(const uint8_t*, const uint8_t*, const uint8_t*,
                         const uint8_t*, uint8_t*, size_t) {}
void dpf_evaluate_seeds(const uint8_t*, const uint8_t*, const uint8_t*,
                        const uint8_t*, const uint8_t*, const uint8_t*,
                        const uint8_t*, const uint8_t*, size_t, int, uint8_t*,
                        uint8_t*) {}
void dpf_expand_forest(const uint8_t*, const uint8_t*, const uint8_t*,
                       const uint8_t*, const uint8_t*, const uint8_t*,
                       const uint8_t*, size_t, int, uint8_t*, uint8_t*,
                       uint8_t*) {}
void dpf_value_hash(const uint8_t*, const uint8_t*, size_t, int, uint8_t*) {}
void dpf_finish_tree_values(const uint8_t*, const uint8_t*, const uint8_t*,
                            const uint8_t*, const uint8_t*, const uint8_t*,
                            uint8_t, uint8_t, int, size_t, const uint64_t*,
                            int, int, int, uint8_t*) {}
void dpf_hash_correct_values(const uint8_t*, const uint8_t*, const uint8_t*,
                             int, size_t, const uint64_t*, int, int, int,
                             uint8_t*) {}
void dpf_dcf_evaluate_u64(const uint8_t*, const uint8_t*, const uint8_t*,
                          const uint8_t*, int, const uint8_t*, const uint8_t*,
                          const uint8_t*, const uint64_t*, const uint8_t*,
                          const uint8_t*, const int32_t*, const uint8_t*, int,
                          int, int, size_t, uint64_t*) {}
}

#endif
