// Native host engine: AES-NI batch kernels for the CPU side of the
// framework (key generation, host pre-expansion, the differential-test
// oracle). The TPU compute path is JAX/XLA (ops/); this library is the
// native runtime underneath the host layer, playing the role the
// OpenSSL/Highway kernels play in the reference
// (/root/reference/dpf/aes_128_fixed_key_hash.cc:27-85,
//  /root/reference/dpf/internal/aes_128_fixed_key_hash_hwy.h:62-229) —
// written from scratch against the AES-NI intrinsics, not ported.
//
// Build:  g++ -O3 -maes -mssse3 -shared -fPIC dpf_native.cc -o libdpf_native.so
// ABI: plain C, little-endian 16-byte blocks (the uint32[,4] limb layout).

#include <cstddef>
#include <cstdint>

#if defined(__AES__) && defined(__SSSE3__)
#include <wmmintrin.h>
#include <tmmintrin.h>

namespace {

inline __m128i expand_step(__m128i key, __m128i keygened) {
  keygened = _mm_shuffle_epi32(keygened, _MM_SHUFFLE(3, 3, 3, 3));
  key = _mm_xor_si128(key, _mm_slli_si128(key, 4));
  key = _mm_xor_si128(key, _mm_slli_si128(key, 4));
  key = _mm_xor_si128(key, _mm_slli_si128(key, 4));
  return _mm_xor_si128(key, keygened);
}

// sigma(x): out.lo64 = x.hi64, out.hi64 = x.hi64 ^ x.lo64 — the linear
// orthomorphism of the MMO construction.
inline __m128i sigma(__m128i x) {
  __m128i hi_hi = _mm_shuffle_epi32(x, _MM_SHUFFLE(3, 2, 3, 2));
  __m128i zero_lo = _mm_slli_si128(x, 8);
  return _mm_xor_si128(hi_hi, zero_lo);
}

inline __m128i encrypt(__m128i block, const __m128i* rks) {
  block = _mm_xor_si128(block, rks[0]);
  for (int r = 1; r < 10; ++r) block = _mm_aesenc_si128(block, rks[r]);
  return _mm_aesenclast_si128(block, rks[10]);
}

inline void load_rks(const uint8_t* bytes, __m128i* rks) {
  for (int i = 0; i < 11; ++i)
    rks[i] = _mm_loadu_si128(reinterpret_cast<const __m128i*>(bytes + 16 * i));
}

}  // namespace

extern "C" {

int dpf_native_available() { return 1; }

// 16-byte key -> 11 x 16-byte round keys.
void dpf_expand_key(const uint8_t* key, uint8_t* rks_out) {
  __m128i rks[11];
  rks[0] = _mm_loadu_si128(reinterpret_cast<const __m128i*>(key));
  rks[1] = expand_step(rks[0], _mm_aeskeygenassist_si128(rks[0], 0x01));
  rks[2] = expand_step(rks[1], _mm_aeskeygenassist_si128(rks[1], 0x02));
  rks[3] = expand_step(rks[2], _mm_aeskeygenassist_si128(rks[2], 0x04));
  rks[4] = expand_step(rks[3], _mm_aeskeygenassist_si128(rks[3], 0x08));
  rks[5] = expand_step(rks[4], _mm_aeskeygenassist_si128(rks[4], 0x10));
  rks[6] = expand_step(rks[5], _mm_aeskeygenassist_si128(rks[5], 0x20));
  rks[7] = expand_step(rks[6], _mm_aeskeygenassist_si128(rks[6], 0x40));
  rks[8] = expand_step(rks[7], _mm_aeskeygenassist_si128(rks[7], 0x80));
  rks[9] = expand_step(rks[8], _mm_aeskeygenassist_si128(rks[8], 0x1B));
  rks[10] = expand_step(rks[9], _mm_aeskeygenassist_si128(rks[9], 0x36));
  for (int i = 0; i < 11; ++i)
    _mm_storeu_si128(reinterpret_cast<__m128i*>(rks_out + 16 * i), rks[i]);
}

// MMO hash of n blocks: out[i] = AES_k(sigma(in[i])) ^ sigma(in[i]).
// 8-wide unrolled to keep the AES units' pipelines full (the same reason
// the reference batches 64 blocks through EVP and pipelines 4 vectors).
void dpf_mmo_hash(const uint8_t* rks_bytes, const uint8_t* in, uint8_t* out,
                  size_t n) {
  __m128i rks[11];
  load_rks(rks_bytes, rks);
  size_t i = 0;
  for (; i + 8 <= n; i += 8) {
    __m128i s[8];
    for (int j = 0; j < 8; ++j)
      s[j] = sigma(_mm_loadu_si128(
          reinterpret_cast<const __m128i*>(in + 16 * (i + j))));
    __m128i b[8];
    for (int j = 0; j < 8; ++j) b[j] = _mm_xor_si128(s[j], rks[0]);
    for (int r = 1; r < 10; ++r)
      for (int j = 0; j < 8; ++j) b[j] = _mm_aesenc_si128(b[j], rks[r]);
    for (int j = 0; j < 8; ++j) {
      b[j] = _mm_xor_si128(_mm_aesenclast_si128(b[j], rks[10]), s[j]);
      _mm_storeu_si128(reinterpret_cast<__m128i*>(out + 16 * (i + j)), b[j]);
    }
  }
  for (; i < n; ++i) {
    __m128i s =
        sigma(_mm_loadu_si128(reinterpret_cast<const __m128i*>(in + 16 * i)));
    __m128i e = _mm_xor_si128(encrypt(s, rks), s);
    _mm_storeu_si128(reinterpret_cast<__m128i*>(out + 16 * i), e);
  }
}

// Two-key MMO hash with per-block key selection (mask[i] != 0 -> right key):
// the evaluate-path primitive where each lane walks left or right.
void dpf_mmo_hash_masked(const uint8_t* rks_left, const uint8_t* rks_right,
                         const uint8_t* in, const uint8_t* mask, uint8_t* out,
                         size_t n) {
  __m128i rl[11], rr[11];
  load_rks(rks_left, rl);
  load_rks(rks_right, rr);
  // Per-block round keys via blend: rk = rl ^ ((rl ^ rr) & m).
  __m128i rdiff[11];
  for (int i = 0; i < 11; ++i) rdiff[i] = _mm_xor_si128(rl[i], rr[i]);
  for (size_t i = 0; i < n; ++i) {
    __m128i m = _mm_set1_epi8(mask[i] ? static_cast<char>(0xFF) : 0);
    __m128i s =
        sigma(_mm_loadu_si128(reinterpret_cast<const __m128i*>(in + 16 * i)));
    __m128i b = _mm_xor_si128(
        s, _mm_xor_si128(rl[0], _mm_and_si128(rdiff[0], m)));
    for (int r = 1; r < 10; ++r)
      b = _mm_aesenc_si128(
          b, _mm_xor_si128(rl[r], _mm_and_si128(rdiff[r], m)));
    b = _mm_aesenclast_si128(
        b, _mm_xor_si128(rl[10], _mm_and_si128(rdiff[10], m)));
    b = _mm_xor_si128(b, s);
    _mm_storeu_si128(reinterpret_cast<__m128i*>(out + 16 * i), b);
  }
}

// Full doubling expansion of one key, all levels in native code: seeds/
// control ping-pong between two buffers; per level every parent hashes
// under both PRG keys (left child then right child, leaf order), XORs the
// correction seed where the parent's control bit is set, extracts and
// corrects the child control bits. The per-level layout matches the
// framework's host oracle (core/backend_numpy.py) bit for bit.
//
//   rks_left/right: 11x16-byte round keys of the two PRG keys
//   seed0:          16-byte root seed
//   cw_seeds:       levels x 16 bytes of correction seeds
//   cw_left/right:  levels bytes (0/1) of control corrections
//   party:          0/1 (initial control bit)
//   out_seeds:      (1 << levels) * 16 bytes, leaf order
//   out_control:    (1 << levels) bytes (0/1)
//   scratch:        (1 << levels) * 16 bytes working buffer
void dpf_expand_tree(const uint8_t* rks_left, const uint8_t* rks_right,
                     const uint8_t* seed0, const uint8_t* cw_seeds,
                     const uint8_t* cw_left, const uint8_t* cw_right,
                     int party, int levels, uint8_t* out_seeds,
                     uint8_t* out_control, uint8_t* scratch) {
  __m128i rl[11], rr[11];
  load_rks(rks_left, rl);
  load_rks(rks_right, rr);
  const __m128i low_bit = _mm_set_epi64x(0, 1);

  uint8_t* cur = scratch;
  uint8_t* nxt = out_seeds;
  // Control bits ping-pong in the out_control buffer's two halves is not
  // possible (it is only 2^levels bytes); keep a parallel scratch tail of
  // the seed buffers: control byte i of level l lives in cur_ctl[i].
  uint8_t* cur_ctl = out_control;          // reused across levels
  for (int i = 0; i < 16; ++i) cur[i] = seed0[i];
  cur_ctl[0] = static_cast<uint8_t>(party & 1);

  for (int level = 0; level < levels; ++level) {
    const size_t parents = static_cast<size_t>(1) << level;
    const __m128i cw =
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(cw_seeds + 16 * level));
    const uint8_t ccl = cw_left[level], ccr = cw_right[level];
    // Walk parents in reverse so children can be written into the same
    // control buffer without clobbering unread parents (child indices
    // 2i, 2i+1 are >= i).
    for (size_t i = parents; i-- > 0;) {
      const __m128i s =
          sigma(_mm_loadu_si128(reinterpret_cast<const __m128i*>(cur + 16 * i)));
      const uint8_t t = cur_ctl[i];
      const __m128i corr = t ? cw : _mm_setzero_si128();
      __m128i bl = _mm_xor_si128(s, rl[0]);
      __m128i br = _mm_xor_si128(s, rr[0]);
      for (int r = 1; r < 10; ++r) {
        bl = _mm_aesenc_si128(bl, rl[r]);
        br = _mm_aesenc_si128(br, rr[r]);
      }
      bl = _mm_xor_si128(_mm_aesenclast_si128(bl, rl[10]), s);
      br = _mm_xor_si128(_mm_aesenclast_si128(br, rr[10]), s);
      bl = _mm_xor_si128(bl, corr);
      br = _mm_xor_si128(br, corr);
      uint8_t ctl_l = static_cast<uint8_t>(
          (_mm_cvtsi128_si64(bl) & 1) ^ (t & ccl));
      uint8_t ctl_r = static_cast<uint8_t>(
          (_mm_cvtsi128_si64(br) & 1) ^ (t & ccr));
      bl = _mm_andnot_si128(low_bit, bl);
      br = _mm_andnot_si128(low_bit, br);
      _mm_storeu_si128(reinterpret_cast<__m128i*>(nxt + 16 * (2 * i)), bl);
      _mm_storeu_si128(reinterpret_cast<__m128i*>(nxt + 16 * (2 * i + 1)), br);
      cur_ctl[2 * i] = ctl_l;
      cur_ctl[2 * i + 1] = ctl_r;
    }
    uint8_t* t = cur;
    cur = nxt;
    nxt = t;
  }
  if (cur != out_seeds) {
    const size_t bytes = (static_cast<size_t>(1) << levels) * 16;
    for (size_t i = 0; i < bytes; ++i) out_seeds[i] = cur[i];
  }
}

}  // extern "C"

#else  // no AES-NI at compile time

extern "C" {
int dpf_native_available() { return 0; }
void dpf_expand_key(const uint8_t*, uint8_t*) {}
void dpf_mmo_hash(const uint8_t*, const uint8_t*, uint8_t*, size_t) {}
void dpf_mmo_hash_masked(const uint8_t*, const uint8_t*, const uint8_t*,
                         const uint8_t*, uint8_t*, size_t) {}
void dpf_expand_tree(const uint8_t*, const uint8_t*, const uint8_t*,
                     const uint8_t*, const uint8_t*, const uint8_t*, int, int,
                     uint8_t*, uint8_t*, uint8_t*) {}
}

#endif
