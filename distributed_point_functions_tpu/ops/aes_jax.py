"""Bitsliced AES-128 fixed-key hash in pure JAX — the TPU PRG primitive.

TPU has no AES instruction and table lookups do not vectorize on the VPU, so
AES is computed as boolean algebra on *bit-planes*: a batch of N 128-bit
blocks is transposed into 128 planes of N bits, each plane packed 32
lanes/word into ``uint32[W]`` (W = N/32). Every AES step is then XOR/AND on
uint32 vectors, which the VPU executes 8x128 lanes at a time — one vector op
processes 32 blocks per element. The S-box is the 113-gate Boyar-Peralta
circuit; ShiftRows is a static byte-plane permutation; MixColumns is a small
XOR network.

This replaces the reference's two AES paths — OpenSSL EVP
(/root/reference/dpf/aes_128_fixed_key_hash.cc) and the Highway SIMD
register implementation with per-lane key selection
(/root/reference/dpf/internal/aes_128_fixed_key_hash_hwy.h:62-229) — with a
single data layout that also keeps the DPF level loop (correction XOR,
control-bit extraction, left/right key choice by path bit) in plane space, so
an entire tree walk never leaves the packed representation.

Per-lane key selection (the reference's `HashOneWithKeyMask`) costs only two
extra vector ops per differing round-key bit: round keys are 0/~0 plane
constants, so ``rk = rk_left ^ (diff & lane_mask)``.

Everything here is differentially tested against the numpy oracle
(core/aes_numpy.py), which in turn pins the reference's golden hash vectors.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core import uint128
from ..core.aes_numpy import expand_key

# ---------------------------------------------------------------------------
# Packing: uint32[N, 4] limbs <-> uint32[128, W] bit-planes (N = 32*W)
# ---------------------------------------------------------------------------

_TSHIFTS = (16, 8, 4, 2, 1)
_TMASKS = (0x0000FFFF, 0x00FF00FF, 0x0F0F0F0F, 0x33333333, 0x55555555)


def _bit_transpose32(a: jnp.ndarray) -> jnp.ndarray:
    """Transpose 32x32 bit matrices: out[..., j] bit i == in[..., i] bit j.

    Masked-shift butterfly (5 stages); the word-order reversals adapt the
    classic MSB-column algorithm to LSB-first bit indexing. Self-inverse.
    """
    lead = a.shape[:-1]
    a = a[..., ::-1]
    for j, m in zip(_TSHIFTS, _TMASKS):
        mm = jnp.uint32(m)
        g = a.reshape(lead + (32 // (2 * j), 2, j))
        a0 = g[..., 0, :]
        a1 = g[..., 1, :]
        t = (a0 ^ (a1 >> j)) & mm
        a0 = a0 ^ t
        a1 = a1 ^ (t << j)
        a = jnp.stack([a0, a1], axis=-2).reshape(lead + (32,))
    return a[..., ::-1]


def pack_to_planes(x: jnp.ndarray) -> jnp.ndarray:
    """uint32[N, 4] blocks -> uint32[128, W] planes; plane b, word w holds bit
    b of blocks 32w..32w+31 (block 32w+i in bit i). N must be a multiple of 32.
    """
    n = x.shape[0]
    assert n % 32 == 0, n
    w = n // 32
    rows = x.reshape(w, 32, 4).transpose(2, 0, 1)  # [limb, W, 32]
    t = _bit_transpose32(rows)  # [limb, W, 32]: word j holds bit j of rows
    return t.transpose(0, 2, 1).reshape(128, w)


def unpack_from_planes(planes: jnp.ndarray) -> jnp.ndarray:
    """uint32[128, W] planes -> uint32[32*W, 4] blocks (inverse of pack)."""
    w = planes.shape[1]
    t = planes.reshape(4, 32, w).transpose(0, 2, 1)  # [limb, W, 32]
    rows = _bit_transpose32(t)
    return rows.transpose(1, 2, 0).reshape(32 * w, 4)


def pack_bit_mask(bits: np.ndarray) -> np.ndarray:
    """Host-side: bool[..., N] -> uint32[..., N//32] lane masks (bit i of word
    w = element 32w+i), matching the pack_to_planes lane order."""
    bits = np.asarray(bits, dtype=bool)
    n = bits.shape[-1]
    assert n % 32 == 0, n
    w = bits.reshape(bits.shape[:-1] + (n // 32, 32)).astype(np.uint32)
    return (w << np.arange(32, dtype=np.uint32)).sum(axis=-1, dtype=np.uint32)


# ---------------------------------------------------------------------------
# Boyar-Peralta S-box circuit (113 gates), bit-plane operands
# ---------------------------------------------------------------------------


def _bp_sbox(u0, u1, u2, u3, u4, u5, u6, u7):
    """Forward AES S-box on 8 bit-planes; u0 is the MSB. Any uint dtype."""
    y14 = u3 ^ u5
    y13 = u0 ^ u6
    y9 = u0 ^ u3
    y8 = u0 ^ u5
    t0 = u1 ^ u2
    y1 = t0 ^ u7
    y4 = y1 ^ u3
    y12 = y13 ^ y14
    y2 = y1 ^ u0
    y5 = y1 ^ u6
    y3 = y5 ^ y8
    t1 = u4 ^ y12
    y15 = t1 ^ u5
    y20 = t1 ^ u1
    y6 = y15 ^ u7
    y10 = y15 ^ t0
    y11 = y20 ^ y9
    y7 = u7 ^ y11
    y17 = y10 ^ y11
    y19 = y10 ^ y8
    y16 = t0 ^ y11
    y21 = y13 ^ y16
    y18 = u0 ^ y16
    t2 = y12 & y15
    t3 = y3 & y6
    t4 = t3 ^ t2
    t5 = y4 & u7
    t6 = t5 ^ t2
    t7 = y13 & y16
    t8 = y5 & y1
    t9 = t8 ^ t7
    t10 = y2 & y7
    t11 = t10 ^ t7
    t12 = y9 & y11
    t13 = y14 & y17
    t14 = t13 ^ t12
    t15 = y8 & y10
    t16 = t15 ^ t12
    t17 = t4 ^ t14
    t18 = t6 ^ t16
    t19 = t9 ^ t14
    t20 = t11 ^ t16
    t21 = t17 ^ y20
    t22 = t18 ^ y19
    t23 = t19 ^ y21
    t24 = t20 ^ y18
    t25 = t21 ^ t22
    t26 = t21 & t23
    t27 = t24 ^ t26
    t28 = t25 & t27
    t29 = t28 ^ t22
    t30 = t23 ^ t24
    t31 = t22 ^ t26
    t32 = t31 & t30
    t33 = t32 ^ t24
    t34 = t23 ^ t33
    t35 = t27 ^ t33
    t36 = t24 & t35
    t37 = t36 ^ t34
    t38 = t27 ^ t36
    t39 = t29 & t38
    t40 = t25 ^ t39
    t41 = t40 ^ t37
    t42 = t29 ^ t33
    t43 = t29 ^ t40
    t44 = t33 ^ t37
    t45 = t42 ^ t41
    z0 = t44 & y15
    z1 = t37 & y6
    z2 = t33 & u7
    z3 = t43 & y16
    z4 = t40 & y1
    z5 = t29 & y7
    z6 = t42 & y11
    z7 = t45 & y17
    z8 = t41 & y10
    z9 = t44 & y12
    z10 = t37 & y3
    z11 = t33 & y4
    z12 = t43 & y13
    z13 = t40 & y5
    z14 = t29 & y2
    z15 = t42 & y9
    z16 = t45 & y14
    z17 = t41 & y8
    t46 = z15 ^ z16
    t47 = z10 ^ z11
    t48 = z5 ^ z13
    t49 = z9 ^ z10
    t50 = z2 ^ z12
    t51 = z2 ^ z5
    t52 = z7 ^ z8
    t53 = z0 ^ z3
    t54 = z6 ^ z7
    t55 = z16 ^ z17
    t56 = z12 ^ t48
    t57 = t50 ^ t53
    t58 = z4 ^ t46
    t59 = z3 ^ t54
    t60 = t46 ^ t57
    t61 = z14 ^ t57
    t62 = t52 ^ t58
    t63 = t49 ^ t58
    t64 = z4 ^ t59
    t65 = t61 ^ t62
    t66 = z1 ^ t63
    s0 = t59 ^ t63
    s6 = ~(t56 ^ t62)
    s7 = ~(t48 ^ t60)
    t67 = t64 ^ t65
    s3 = t53 ^ t66
    s4 = t51 ^ t66
    s5 = t47 ^ t65
    s1 = ~(t64 ^ s3)
    s2 = ~(t55 ^ t67)
    return s0, s1, s2, s3, s4, s5, s6, s7


def _sub_bytes(state: jnp.ndarray) -> jnp.ndarray:
    """S-box on state [16, 8, W] (byte-plane, bit index LSB-first)."""
    u = [state[:, 7 - i, :] for i in range(8)]  # u0 = MSB = bit 7
    s = _bp_sbox(*u)
    return jnp.stack([s[7 - k] for k in range(8)], axis=1)


# ShiftRows source index for output byte j (column-major state, byte j =
# row j%4, col j//4): out[row, col] = in[row, (col + row) % 4]. Mirrors the
# numpy oracle's table (core/aes_numpy.py).
_SHIFT_ROWS = tuple(
    (row + 4 * ((col + row) % 4)) for col in range(4) for row in range(4)
)


def _shift_rows(state: jnp.ndarray) -> jnp.ndarray:
    # Unrolled static gather (not a fancy-index with a constant array) so
    # the same circuit traces inside Pallas kernels, which reject captured
    # array constants; XLA folds both forms identically.
    return jnp.stack([state[i] for i in _SHIFT_ROWS], axis=0)


def _xtime(a: jnp.ndarray) -> jnp.ndarray:
    """GF(2^8) doubling on bit-planes [..., 8, W]: x<<1 ^ (0x1B if MSB)."""
    a7 = a[..., 7, :]
    return jnp.stack(
        [
            a7,
            a[..., 0, :] ^ a7,
            a[..., 1, :],
            a[..., 2, :] ^ a7,
            a[..., 3, :] ^ a7,
            a[..., 4, :],
            a[..., 5, :],
            a[..., 6, :],
        ],
        axis=-2,
    )


def _mix_columns(state: jnp.ndarray) -> jnp.ndarray:
    w = state.shape[-1]
    s = state.reshape(4, 4, 8, w)  # [col, row, bit, W]
    t = s[:, 0] ^ s[:, 1] ^ s[:, 2] ^ s[:, 3]  # [col, 8, W]
    rows = []
    for r in range(4):
        rows.append(s[:, r] ^ t ^ _xtime(s[:, r] ^ s[:, (r + 1) % 4]))
    return jnp.stack(rows, axis=1).reshape(16, 8, w)


# ---------------------------------------------------------------------------
# Round keys as plane constants
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def round_key_planes(key: int) -> np.ndarray:
    """AES-128 round keys -> uint32[11, 16, 8] of 0 / 0xFFFFFFFF plane masks."""
    rks = expand_key(uint128.to_bytes(key))  # uint8[11, 16]
    bits = (rks[:, :, None] >> np.arange(8, dtype=np.uint8)) & 1
    return (bits.astype(np.uint32) * np.uint32(0xFFFFFFFF)).astype(np.uint32)


# ---------------------------------------------------------------------------
# Core encryption + fixed-key hash, in plane space
# ---------------------------------------------------------------------------


def aes_encrypt_planes(state, rk_base, rk_diff=None, key_mask=None):
    """AES-128 over bit-planes.

    Args:
      state: uint32[16, 8, W] byte/bit planes of the plaintext blocks.
      rk_base: uint32[11, 16, 8] plane-constant round keys (0 / ~0).
      rk_diff: optional uint32[11, 16, 8]; when given with `key_mask`
        (uint32[W]), lanes with a set mask bit are encrypted under
        rk_base ^ rk_diff instead — the reference's per-lane key selection
        (aes_128_fixed_key_hash_hwy.h:88-107) for free in plane space.
    Returns: uint32[16, 8, W] ciphertext planes.
    """

    def ark(s, r):
        k = rk_base[r][:, :, None]
        if rk_diff is not None:
            k = k ^ (rk_diff[r][:, :, None] & key_mask[None, None, :])
        return s ^ k

    s = ark(state, 0)
    for r in range(1, 11):
        s = _sub_bytes(s)
        s = _shift_rows(s)
        if r < 10:
            s = _mix_columns(s)
        s = ark(s, r)
    return s


def sigma_planes(planes: jnp.ndarray) -> jnp.ndarray:
    """MMO orthomorphism sigma(x) = (high ^ low, high) on [128, W] planes."""
    lo, hi = planes[:64], planes[64:]
    return jnp.concatenate([hi, hi ^ lo], axis=0)


def hash_planes(planes, rk_base, rk_diff=None, key_mask=None):
    """Fixed-key MMO hash H(x) = AES_k(sigma(x)) ^ sigma(x) on [128, W] planes.

    Plane-space equivalent of Aes128FixedKeyHash::Evaluate
    (/root/reference/dpf/aes_128_fixed_key_hash.cc:47-85); with
    rk_diff/key_mask it is HashOneWithKeyMask
    (/root/reference/dpf/internal/aes_128_fixed_key_hash_hwy.h:88-107).
    """
    w = planes.shape[1]
    sig = sigma_planes(planes)
    enc = aes_encrypt_planes(sig.reshape(16, 8, w), rk_base, rk_diff, key_mask)
    return enc.reshape(128, w) ^ sig


# Convenience block-layout wrappers (pack -> op -> unpack), mostly for tests.


@functools.partial(jax.jit, static_argnames=("key",))
def encrypt_blocks_jax(x: jnp.ndarray, key: int) -> jnp.ndarray:
    """uint32[N, 4] -> AES-128_key(blocks), N % 32 == 0."""
    rk = jnp.asarray(round_key_planes(key))
    planes = pack_to_planes(x)
    out = aes_encrypt_planes(planes.reshape(16, 8, -1), rk)
    return unpack_from_planes(out.reshape(128, -1))


@functools.partial(jax.jit, static_argnames=("key",))
def hash_blocks_jax(x: jnp.ndarray, key: int) -> jnp.ndarray:
    """uint32[N, 4] -> H_key(blocks) (fixed-key MMO hash), N % 32 == 0."""
    rk = jnp.asarray(round_key_planes(key))
    return unpack_from_planes(hash_planes(pack_to_planes(x), rk))
