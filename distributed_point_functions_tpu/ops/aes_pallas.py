"""Pallas TPU kernel for the doubling-expansion hot op.

The XLA bitslice (ops/aes_jax.py + backend_jax.expand_one_level) already
saturates the chip far beyond the workload's AES demand (PERF.md), so this
kernel exists to *prove the decision with a measurement*, not because
profiles demanded it: `benchmarks/micro_tpu.py` times both paths
on hardware. The kernel fuses one tree level — per-lane dual-key bitsliced
AES, correction XOR, control-bit extraction — with all 128 bit-planes
resident in VMEM and a grid over (child, lane-block):

    grid = (2, W // block_w)
    out[128, 2W] = [left children | right children]  (expand_one_level's
    block-concatenated layout, same unpack permutation applies)

The AES circuit itself is the same jnp boolean algebra as the XLA path
(aes_jax.hash_planes) traced inside the kernel — one implementation, two
schedulers. Tested for bit-equality against expand_one_level in
interpreter mode (CPU) and compiled (TPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from . import aes_jax, backend_jax


def _divisor_block_w(w: int, block_w: int) -> int:
    """Largest divisor of `w` that is <= block_w. Expansion widths are
    slab*2^k (slab a multiple of 32), so this normally lands on a large
    block even when w is not a multiple of the default block — a
    caller-chosen lane_slab like 96 produces widths 3*2^k (ADVICE r2)."""
    bw = min(block_w, w)
    while bw > 1 and w % bw:
        bw -= 1
    return max(1, bw)


def _block_plan(w: int, block_w: int):
    """Returns (bw, wp): the kernel block width and the (possibly padded)
    lane-word width, wp % bw == 0. Prefers an exact large divisor of w
    (zero padding); when the best divisor is degenerate (prime-ish widths
    would get near-width-1 blocks — Mosaic lowering failure or a
    pathological grid), falls back to zero-padding w up to a multiple of a
    256-capped block. Padded lanes compute on zero seeds and are trimmed
    by the caller."""
    bw = _divisor_block_w(w, block_w)
    if bw == w or bw >= max(32, block_w // 8):
        # Exact divisor with a non-degenerate block (>= one packed word,
        # and not minuscule relative to the requested block): zero padding.
        return bw, w
    bw = min(block_w, 256)
    return bw, w + (-w) % bw


def _pad_lane_words(arrays, w: int, bw: int):
    """Zero-pads each array's trailing lane-word axis from w up to a
    multiple of bw. Returns (padded_arrays, padded_w)."""
    pad = (-w) % bw
    if pad == 0:
        return list(arrays), w
    out = []
    for a in arrays:
        cfg = [(0, 0)] * (a.ndim - 1) + [(0, pad)]
        out.append(jnp.pad(a, cfg))
    return out, w + pad


def _expand_kernel(
    planes_ref,  # uint32[128, bw]
    control_ref,  # uint32[1, bw]
    cw_ref,  # uint32[128, 1]
    cc_ref,  # uint32[1, 2]: (ccl, ccr)
    rk_ref,  # uint32[22, 128]: [rk_base | rk_diff], 16*8 planes per round
    out_planes_ref,  # uint32[128, bw]
    out_control_ref,  # uint32[1, bw]
):
    child = pl.program_id(0)  # 0 = left key, 1 = right key
    p = planes_ref[:, :]
    c = control_ref[0, :]
    w = p.shape[1]
    key_mask = jnp.broadcast_to(
        jnp.where(child == 0, jnp.uint32(0), jnp.uint32(0xFFFFFFFF)), (w,)
    )
    rks = rk_ref[:, :].reshape(22, 16, 8)
    h = aes_jax.hash_planes(p, rks[:11], rks[11:], key_mask)
    h = h ^ (cw_ref[:, 0][:, None] & c[None, :])
    cc = jnp.where(child == 0, cc_ref[0, 0], cc_ref[0, 1])
    new_control = h[0] ^ (c & cc)
    # Zero the LSB plane without h.at[0].set(...): scatter does not lower
    # in Pallas TPU kernels (observed NotImplementedError on v5e).
    row = jax.lax.broadcasted_iota(jnp.uint32, h.shape, 0)
    h = jnp.where(row == 0, jnp.uint32(0), h)
    out_planes_ref[:, :] = h
    out_control_ref[0, :] = new_control


@functools.partial(jax.jit, static_argnames=("block_w", "interpret"))
def expand_one_level_pallas(
    planes: jnp.ndarray,  # uint32[128, W]
    control: jnp.ndarray,  # uint32[W]
    cw_plane: jnp.ndarray,  # uint32[128]
    ccl_mask: jnp.ndarray,  # uint32 scalar mask
    ccr_mask: jnp.ndarray,  # uint32 scalar mask
    block_w: int = 512,
    interpret: bool = False,
):
    """Pallas twin of backend_jax.expand_one_level (same outputs/layout)."""
    w = planes.shape[1]
    bw, wp = _block_plan(w, block_w)
    if wp != w:
        # This legacy tensor-shaped kernel (micro-benchmarks only) has no
        # pad-and-trim plumbing; fail loudly rather than compile a
        # degenerate grid (r3 review). The batched row kernels pad.
        raise NotImplementedError(
            f"width {w} has no usable divisor block <= {block_w}; use "
            "expand_one_level_pallas_batched, which zero-pads arbitrary "
            "widths"
        )
    rks = np.concatenate(
        [backend_jax._rk_np("left"), backend_jax._rk_np("lr_diff")]
    ).reshape(22, 128)
    grid = (2, w // bw)
    out_planes, out_control = pl.pallas_call(
        _expand_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((128, 2 * w), jnp.uint32),
            jax.ShapeDtypeStruct((1, 2 * w), jnp.uint32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((128, bw), lambda i, j: (0, j)),
            pl.BlockSpec((1, bw), lambda i, j: (0, j)),
            pl.BlockSpec((128, 1), lambda i, j: (0, 0)),
            pl.BlockSpec((1, 2), lambda i, j: (0, 0)),
            pl.BlockSpec((22, 128), lambda i, j: (0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((128, bw), lambda i, j: (0, i * (w // bw) + j)),
            pl.BlockSpec((1, bw), lambda i, j: (0, i * (w // bw) + j)),
        ),
        interpret=interpret,
    )(
        planes,
        control[None, :],
        cw_plane[:, None],
        jnp.stack([ccl_mask, ccr_mask]).astype(jnp.uint32)[None, :],
        jnp.asarray(rks),
    )
    return out_planes, out_control[0]


# ---------------------------------------------------------------------------
# Row-based kernel: Mosaic-compatible form
# ---------------------------------------------------------------------------
#
# The tensor-shaped kernel above traces `hash_planes`, whose
# [128, w] <-> [16, 8, w] reshapes and stacks Mosaic rejects
# ("infer-vector-layout: unsupported shape cast" on the v5e remote
# compiler). This variant re-expresses the identical circuit as plain
# Python lists of 128 one-dimensional rows — only elementwise vector ops
# and static-index row loads/stores — and bakes the fixed PRG round keys
# in as TRACE-TIME constants (they are compile-time-known: XORs with a
# zero plane vanish from the traced circuit entirely, the plane-space
# analog of the reference's precomputed key schedule).


def _sbox_rows(byte_rows):
    """AES S-box on one byte's 8 bit-rows (LSB-first), via the shared
    Boyar–Peralta netlist (aes_jax._bp_sbox, MSB-first order)."""
    u = [byte_rows[7 - i] for i in range(8)]
    s = aes_jax._bp_sbox(*u)
    return [s[7 - k] for k in range(8)]


def _aes_rows(rows, rk_base, rk_diff, key_mask):
    """AES-128 on 128 bit-rows. rk_base/rk_diff: uint32[11, 16, 8] numpy
    0/~0 constants (rk_diff applies under key_mask — per-lane key select).
    """
    full = np.uint32(0xFFFFFFFF)

    def ark(rows, r):
        out = []
        for p in range(128):
            b, i = divmod(p, 8)
            row = rows[p]
            if rk_base[r, b, i]:
                row = row ^ full  # NOT: plane-constant key bit
            if rk_diff is not None and rk_diff[r, b, i]:
                row = row ^ key_mask
            out.append(row)
        return out

    s = ark(rows, 0)
    for r in range(1, 11):
        # SubBytes per byte
        s = [
            bit
            for b in range(16)
            for bit in _sbox_rows(s[8 * b : 8 * b + 8])
        ]
        # ShiftRows: byte permutation
        s = [s[8 * src + i] for src in aes_jax._SHIFT_ROWS for i in range(8)]
        if r < 10:
            # MixColumns on byte lists
            cols = [[s[8 * (4 * c + rr) : 8 * (4 * c + rr) + 8] for rr in range(4)] for c in range(4)]

            def xt(byte):  # GF(2^8) doubling on an 8-bit row list
                a7 = byte[7]
                return [
                    a7,
                    byte[0] ^ a7,
                    byte[1],
                    byte[2] ^ a7,
                    byte[3] ^ a7,
                    byte[4],
                    byte[5],
                    byte[6],
                ]

            out = []
            for c in range(4):
                t = [
                    cols[c][0][i] ^ cols[c][1][i] ^ cols[c][2][i] ^ cols[c][3][i]
                    for i in range(8)
                ]
                for rr in range(4):
                    nxt = cols[c][(rr + 1) % 4]
                    x2 = xt([cols[c][rr][i] ^ nxt[i] for i in range(8)])
                    out.append(
                        [cols[c][rr][i] ^ t[i] ^ x2[i] for i in range(8)]
                    )
            s = [bit for byte in out for bit in byte]
        s = ark(s, r)
    return s


@functools.partial(jax.jit, static_argnames=("block_w", "interpret"))
def expand_one_level_pallas_rows(
    planes: jnp.ndarray,  # uint32[128, W]
    control: jnp.ndarray,  # uint32[W]
    cw_plane: jnp.ndarray,  # uint32[128]
    ccl_mask: jnp.ndarray,
    ccr_mask: jnp.ndarray,
    block_w: int = 512,
    interpret: bool = False,
):
    """Row-based Pallas twin of backend_jax.expand_one_level (same
    outputs/layout as expand_one_level_pallas). Thin single-key view of the
    batched kernel — one implementation to keep in sync."""
    out_planes, out_control = expand_one_level_pallas_batched(
        planes[None],
        control[None],
        cw_plane[None],
        ccl_mask[None] if ccl_mask.ndim else ccl_mask.reshape(1),
        ccr_mask[None] if ccr_mask.ndim else ccr_mask.reshape(1),
        block_w=block_w,
        interpret=interpret,
    )
    return out_planes[0], out_control[0]


def _expand_child_rows(planes_ref, control_ref, cw_ref, cc_ref, rk_base, rk_diff):
    """Shared expand-child body for the batched row kernels: reads the refs,
    selects this grid step's child key by mask, runs the masked AES, applies
    seed/control corrections. Returns (h rows with h[0] zeroed, control)."""
    child = pl.program_id(0)
    c = control_ref[0, 0, :]
    w = c.shape[0]
    key_mask = jnp.broadcast_to(
        jnp.where(child == 0, jnp.uint32(0), jnp.uint32(0xFFFFFFFF)), (w,)
    )
    x = [planes_ref[0, p, :] for p in range(128)]
    sig = [x[64 + p] for p in range(64)] + [
        x[64 + p] ^ x[p] for p in range(64)
    ]
    enc = _aes_rows(sig, rk_base, rk_diff, key_mask)
    h = [enc[p] ^ sig[p] for p in range(128)]
    h = [h[p] ^ (cw_ref[0, p, 0] & c) for p in range(128)]
    cc = jnp.where(child == 0, cc_ref[0, 0, 0], cc_ref[0, 0, 1])
    new_control = h[0] ^ (c & cc)
    h[0] = jnp.zeros_like(h[0])
    return h, new_control


def _expand_kernel_rows_batched(rk_base, rk_diff):
    """Key-batched row kernel: grid (2, K, W//bw); per-key correction words
    and control-correction masks come from refs indexed by the key axis."""

    def kernel(
        planes_ref,  # uint32[1, 128, bw]
        control_ref,  # uint32[1, 1, bw]
        cw_ref,  # uint32[1, 128, 1]
        cc_ref,  # uint32[1, 1, 2]
        out_planes_ref,  # uint32[1, 128, bw]
        out_control_ref,  # uint32[1, 1, bw]
    ):
        h, new_control = _expand_child_rows(
            planes_ref, control_ref, cw_ref, cc_ref, rk_base, rk_diff
        )
        for p in range(128):
            out_planes_ref[0, p, :] = h[p]
        out_control_ref[0, 0, :] = new_control

    return kernel


@functools.partial(jax.jit, static_argnames=("block_w", "interpret"))
def expand_one_level_pallas_batched(
    planes: jnp.ndarray,  # uint32[K, 128, W]
    control: jnp.ndarray,  # uint32[K, W] lane-word control masks
    cw_plane: jnp.ndarray,  # uint32[K, 128]
    ccl_mask: jnp.ndarray,  # uint32[K]
    ccr_mask: jnp.ndarray,  # uint32[K]
    block_w: int = 2048,
    interpret: bool = False,
):
    """Batched row-kernel twin of vmap(backend_jax.expand_one_level):
    identical outputs/layout ([K, 128, 2W] with children block-concatenated
    along the lane-word axis)."""
    kernel = _expand_kernel_rows_batched(
        backend_jax._rk_np("left"), backend_jax._rk_np("lr_diff")
    )
    return _run_expand_blocked(
        kernel, planes, control, cw_plane, ccl_mask, ccr_mask,
        block_w, interpret,
    )


def _run_expand_blocked(
    kernel, planes, control, cw_plane, ccl_mask, ccr_mask, block_w, interpret
):
    """Shared pallas_call scaffolding for the child-doubling kernels
    (plain expand and fused expand+hash): block plan, lane padding, the
    (2, K, blocks) grid with children block-concatenated along the output
    lane axis, and the pad trim/re-concat. The kernel decides WHAT the
    per-child outputs are (planes or hashed planes)."""
    k, _, w = planes.shape
    bw, wp = _block_plan(w, block_w)
    if wp != w:
        (planes, control), _ = _pad_lane_words((planes, control), w, bw)
    nblk = wp // bw
    out_main, out_control = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((k, 128, 2 * wp), jnp.uint32),
            jax.ShapeDtypeStruct((k, 1, 2 * wp), jnp.uint32),
        ),
        grid=(2, k, nblk),
        in_specs=[
            pl.BlockSpec((1, 128, bw), lambda i, kk, j: (kk, 0, j)),
            pl.BlockSpec((1, 1, bw), lambda i, kk, j: (kk, 0, j)),
            pl.BlockSpec((1, 128, 1), lambda i, kk, j: (kk, 0, 0)),
            pl.BlockSpec((1, 1, 2), lambda i, kk, j: (kk, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec(
                (1, 128, bw), lambda i, kk, j: (kk, 0, i * nblk + j)
            ),
            pl.BlockSpec(
                (1, 1, bw), lambda i, kk, j: (kk, 0, i * nblk + j)
            ),
        ),
        interpret=interpret,
    )(
        planes,
        control[:, None, :],
        cw_plane[:, :, None],
        jnp.stack([ccl_mask, ccr_mask], axis=-1).astype(jnp.uint32)[:, None, :],
    )
    if wp != w:
        # Children live at [0:wp] / [wp:2wp]; re-concatenate the real lanes
        # so the caller sees the unpadded [left | right] layout.
        out_main = jnp.concatenate(
            [out_main[:, :, :w], out_main[:, :, wp : wp + w]], axis=2
        )
        out_control = jnp.concatenate(
            [out_control[:, :, :w], out_control[:, :, wp : wp + w]], axis=2
        )
    return out_main, out_control[:, 0, :]


def _expand_hash_kernel_rows_batched(rk_base, rk_diff, rk_value):
    """Fused LAST-level kernel: one doubling expansion child + its value
    hash in a single kernel, emitting only the hashed planes and the new
    control row. In the fold path the final level's child planes are read
    exactly once (by the value hash) and then discarded, so fusing removes
    a full HBM write+read of the widest planes — the single largest memory
    op of a doubling expansion (the last level is half of all lanes)."""

    def kernel(
        planes_ref,  # uint32[1, 128, bw]
        control_ref,  # uint32[1, 1, bw]
        cw_ref,  # uint32[1, 128, 1]
        cc_ref,  # uint32[1, 1, 2]
        out_hashed_ref,  # uint32[1, 128, bw]
        out_control_ref,  # uint32[1, 1, bw]
    ):
        h, new_control = _expand_child_rows(
            planes_ref, control_ref, cw_ref, cc_ref, rk_base, rk_diff
        )
        # Value hash of the child seed, chained in-register.
        sig2 = [h[64 + p] for p in range(64)] + [
            h[64 + p] ^ h[p] for p in range(64)
        ]
        enc2 = _aes_rows(sig2, rk_value, None, None)
        for p in range(128):
            out_hashed_ref[0, p, :] = enc2[p] ^ sig2[p]
        out_control_ref[0, 0, :] = new_control

    return kernel


@functools.partial(jax.jit, static_argnames=("block_w", "interpret"))
def expand_and_hash_last_level_pallas_batched(
    planes: jnp.ndarray,  # uint32[K, 128, W]
    control: jnp.ndarray,  # uint32[K, W]
    cw_plane: jnp.ndarray,  # uint32[K, 128]
    ccl_mask: jnp.ndarray,  # uint32[K]
    ccr_mask: jnp.ndarray,  # uint32[K]
    block_w: int = 2048,
    interpret: bool = False,
):
    """Fused twin of expand_one_level_pallas_batched followed by
    hash_value_planes_pallas_batched on its output: returns
    (hashed uint32[K, 128, 2W], control uint32[K, 2W]) — child planes are
    never materialized in HBM. Bit-identical to the two-kernel
    composition (the kernel body chains the same two circuits)."""
    kernel = _expand_hash_kernel_rows_batched(
        backend_jax._rk_np("left"),
        backend_jax._rk_np("lr_diff"),
        backend_jax._rk_np("value"),
    )
    return _run_expand_blocked(
        kernel, planes, control, cw_plane, ccl_mask, ccr_mask,
        block_w, interpret,
    )


def _value_hash_kernel_rows(rk_value):
    """Fixed-key value-PRG hash (no key select, no corrections)."""

    def kernel(planes_ref, out_ref):
        x = [planes_ref[0, p, :] for p in range(128)]
        sig = [x[64 + p] for p in range(64)] + [
            x[64 + p] ^ x[p] for p in range(64)
        ]
        enc = _aes_rows(sig, rk_value, None, None)
        for p in range(128):
            out_ref[0, p, :] = enc[p] ^ sig[p]

    return kernel


@functools.partial(jax.jit, static_argnames=("block_w", "interpret"))
def hash_value_planes_pallas_batched(
    planes: jnp.ndarray,  # uint32[K, 128, W]
    block_w: int = 2048,
    interpret: bool = False,
):
    """Batched row-kernel twin of vmap(backend_jax.hash_value_planes)."""
    k, _, w = planes.shape
    bw, wp = _block_plan(w, block_w)
    if wp != w:
        (planes,), _ = _pad_lane_words((planes,), w, bw)
    out = pl.pallas_call(
        _value_hash_kernel_rows(backend_jax._rk_np("value")),
        out_shape=jax.ShapeDtypeStruct((k, 128, wp), jnp.uint32),
        grid=(k, wp // bw),
        in_specs=[pl.BlockSpec((1, 128, bw), lambda kk, j: (kk, 0, j))],
        out_specs=pl.BlockSpec((1, 128, bw), lambda kk, j: (kk, 0, j)),
        interpret=interpret,
    )(planes)
    return out[:, :, :w] if wp != w else out


def _walk_level_kernel_tiled(rk_base, rk_diff):
    """One walk level for a TILE of kb keys: rows are (kb, bw) 2-D tiles so
    narrow point batches still fill the (8, 128) vregs. Per-lane key select
    comes from the level's path-bit mask (shared across keys); correction
    words / control corrections are per-key columns broadcast across lanes.
    Mirrors backend_jax.evaluate_seeds_planes's scan body."""

    def kernel(
        planes_ref,  # uint32[kb, 128, bw]
        control_ref,  # uint32[kb, 1, bw]
        mask_ref,  # uint32[1, 1, bw] path bits of this level
        cw_ref,  # uint32[kb, 128, 1]
        cc_ref,  # uint32[kb, 1, 2] (ccl, ccr) of this level
        out_planes_ref,  # uint32[kb, 128, bw]
        out_control_ref,  # uint32[kb, 1, bw]
    ):
        c = control_ref[:, 0, :]  # (kb, bw)
        key_mask = mask_ref[0, 0, :][None, :]  # (1, bw) broadcasts
        x = [planes_ref[:, p, :] for p in range(128)]
        sig = [x[64 + p] for p in range(64)] + [
            x[64 + p] ^ x[p] for p in range(64)
        ]
        enc = _aes_rows(sig, rk_base, rk_diff, key_mask)
        h = [enc[p] ^ sig[p] ^ (cw_ref[:, p, :] & c) for p in range(128)]
        l = cc_ref[:, 0, 0:1]  # (kb, 1)
        r = cc_ref[:, 0, 1:2]
        cc = (l & ~key_mask) | (r & key_mask)  # (kb, bw)
        new_control = h[0] ^ (c & cc)
        h[0] = jnp.zeros_like(h[0])
        for p in range(128):
            out_planes_ref[:, p, :] = h[p]
        out_control_ref[:, 0, :] = new_control

    return kernel


@functools.partial(
    jax.jit, static_argnames=("block_w", "key_tile", "interpret")
)
def walk_levels_pallas_batched(
    planes: jnp.ndarray,  # uint32[K, 128, W]
    control: jnp.ndarray,  # uint32[K, W]
    path_masks: jnp.ndarray,  # uint32[L, W] shared across keys
    cw_planes: jnp.ndarray,  # uint32[K, L, 128]
    ccl: jnp.ndarray,  # uint32[K, L]
    ccr: jnp.ndarray,  # uint32[K, L]
    block_w: int = 512,
    key_tile: int = 8,
    interpret: bool = False,
):
    """Batched Mosaic twin of vmap(backend_jax.evaluate_seeds_planes):
    walks every lane down all L levels (one pallas_call per level inside
    one jit program). Keys are padded to a multiple of key_tile; the
    lane-word axis takes _block_plan's route for arbitrary widths (point
    counts are arbitrary — e.g. P=20000 -> w=625): an exact divisor block
    when a large one exists, else zero-padding to a block multiple, with
    the pad trimmed on return (ADVICE r2)."""
    k, _, w = planes.shape
    levels = path_masks.shape[0]
    bw, wp_plan = _block_plan(w, block_w)
    (planes, control, path_masks), wp = _pad_lane_words(
        (planes, control, path_masks), w, bw
    )
    assert wp == wp_plan, (w, bw, wp, wp_plan)
    pad = (-k) % key_tile
    if pad:
        planes = jnp.concatenate(
            [planes, jnp.zeros((pad, 128, wp), jnp.uint32)], axis=0
        )
        control = jnp.concatenate(
            [control, jnp.zeros((pad, wp), jnp.uint32)], axis=0
        )
        cw_planes = jnp.concatenate(
            [cw_planes, jnp.zeros((pad,) + cw_planes.shape[1:], jnp.uint32)],
            axis=0,
        )
        ccl = jnp.concatenate([ccl, jnp.zeros((pad, levels), jnp.uint32)], axis=0)
        ccr = jnp.concatenate([ccr, jnp.zeros((pad, levels), jnp.uint32)], axis=0)
    kp = k + pad
    kernel = _walk_level_kernel_tiled(
        backend_jax._rk_np("left"), backend_jax._rk_np("lr_diff")
    )
    ctrl = control[:, None, :]
    cc = jnp.stack([ccl, ccr], axis=-1)  # [Kp, L, 2]
    for level in range(levels):
        planes, ctrl = pl.pallas_call(
            kernel,
            out_shape=(
                jax.ShapeDtypeStruct((kp, 128, wp), jnp.uint32),
                jax.ShapeDtypeStruct((kp, 1, wp), jnp.uint32),
            ),
            grid=(kp // key_tile, wp // bw),
            in_specs=[
                pl.BlockSpec((key_tile, 128, bw), lambda kk, j: (kk, 0, j)),
                pl.BlockSpec((key_tile, 1, bw), lambda kk, j: (kk, 0, j)),
                pl.BlockSpec((1, 1, bw), lambda kk, j: (0, 0, j)),
                pl.BlockSpec((key_tile, 128, 1), lambda kk, j: (kk, 0, 0)),
                pl.BlockSpec((key_tile, 1, 2), lambda kk, j: (kk, 0, 0)),
            ],
            out_specs=(
                pl.BlockSpec((key_tile, 128, bw), lambda kk, j: (kk, 0, j)),
                pl.BlockSpec((key_tile, 1, bw), lambda kk, j: (kk, 0, j)),
            ),
            interpret=interpret,
        )(
            planes,
            ctrl,
            path_masks[level][None, None, :],
            cw_planes[:, level, :, None],
            cc[:, level, :][:, None, :],
        )
    return planes[:k, :, :w], ctrl[:k, 0, :w]
