"""Pallas TPU kernel for the doubling-expansion hot op.

The XLA bitslice (ops/aes_jax.py + backend_jax.expand_one_level) already
saturates the chip far beyond the workload's AES demand (PERF.md), so this
kernel exists to *prove the decision with a measurement*, not because
profiles demanded it: `benchmarks/micro_tpu.py` times both paths
on hardware. The kernel fuses one tree level — per-lane dual-key bitsliced
AES, correction XOR, control-bit extraction — with all 128 bit-planes
resident in VMEM and a grid over (child, lane-block):

    grid = (2, W // block_w)
    out[128, 2W] = [left children | right children]  (expand_one_level's
    block-concatenated layout, same unpack permutation applies)

The AES circuit itself is the same jnp boolean algebra as the XLA path
(aes_jax.hash_planes) traced inside the kernel — one implementation, two
schedulers. Tested for bit-equality against expand_one_level in
interpreter mode (CPU) and compiled (TPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from . import aes_jax, backend_jax


def _expand_kernel(
    planes_ref,  # uint32[128, bw]
    control_ref,  # uint32[1, bw]
    cw_ref,  # uint32[128, 1]
    cc_ref,  # uint32[1, 2]: (ccl, ccr)
    rk_ref,  # uint32[22, 128]: [rk_base | rk_diff], 16*8 planes per round
    out_planes_ref,  # uint32[128, bw]
    out_control_ref,  # uint32[1, bw]
):
    child = pl.program_id(0)  # 0 = left key, 1 = right key
    p = planes_ref[:, :]
    c = control_ref[0, :]
    w = p.shape[1]
    key_mask = jnp.broadcast_to(
        jnp.where(child == 0, jnp.uint32(0), jnp.uint32(0xFFFFFFFF)), (w,)
    )
    rks = rk_ref[:, :].reshape(22, 16, 8)
    h = aes_jax.hash_planes(p, rks[:11], rks[11:], key_mask)
    h = h ^ (cw_ref[:, 0][:, None] & c[None, :])
    cc = jnp.where(child == 0, cc_ref[0, 0], cc_ref[0, 1])
    new_control = h[0] ^ (c & cc)
    # Zero the LSB plane without h.at[0].set(...): scatter does not lower
    # in Pallas TPU kernels (observed NotImplementedError on v5e).
    row = jax.lax.broadcasted_iota(jnp.uint32, h.shape, 0)
    h = jnp.where(row == 0, jnp.uint32(0), h)
    out_planes_ref[:, :] = h
    out_control_ref[0, :] = new_control


@functools.partial(jax.jit, static_argnames=("block_w", "interpret"))
def expand_one_level_pallas(
    planes: jnp.ndarray,  # uint32[128, W]
    control: jnp.ndarray,  # uint32[W]
    cw_plane: jnp.ndarray,  # uint32[128]
    ccl_mask: jnp.ndarray,  # uint32 scalar mask
    ccr_mask: jnp.ndarray,  # uint32 scalar mask
    block_w: int = 512,
    interpret: bool = False,
):
    """Pallas twin of backend_jax.expand_one_level (same outputs/layout)."""
    w = planes.shape[1]
    bw = min(block_w, w)
    assert w % bw == 0, (w, bw)
    rks = np.concatenate(
        [backend_jax._rk_np("left"), backend_jax._rk_np("lr_diff")]
    ).reshape(22, 128)
    grid = (2, w // bw)
    out_planes, out_control = pl.pallas_call(
        _expand_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((128, 2 * w), jnp.uint32),
            jax.ShapeDtypeStruct((1, 2 * w), jnp.uint32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((128, bw), lambda i, j: (0, j)),
            pl.BlockSpec((1, bw), lambda i, j: (0, j)),
            pl.BlockSpec((128, 1), lambda i, j: (0, 0)),
            pl.BlockSpec((1, 2), lambda i, j: (0, 0)),
            pl.BlockSpec((22, 128), lambda i, j: (0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((128, bw), lambda i, j: (0, i * (w // bw) + j)),
            pl.BlockSpec((1, bw), lambda i, j: (0, i * (w // bw) + j)),
        ),
        interpret=interpret,
    )(
        planes,
        control[None, :],
        cw_plane[:, None],
        jnp.stack([ccl_mask, ccr_mask]).astype(jnp.uint32)[None, :],
        jnp.asarray(rks),
    )
    return out_planes, out_control[0]
