"""Pallas TPU kernel for the doubling-expansion hot op.

The XLA bitslice (ops/aes_jax.py + backend_jax.expand_one_level) already
saturates the chip far beyond the workload's AES demand (PERF.md), so this
kernel exists to *prove the decision with a measurement*, not because
profiles demanded it: `benchmarks/micro_tpu.py` times both paths
on hardware. The kernel fuses one tree level — per-lane dual-key bitsliced
AES, correction XOR, control-bit extraction — with all 128 bit-planes
resident in VMEM and a grid over (child, lane-block):

    grid = (2, W // block_w)
    out[128, 2W] = [left children | right children]  (expand_one_level's
    block-concatenated layout, same unpack permutation applies)

The AES circuit itself is the same jnp boolean algebra as the XLA path
(aes_jax.hash_planes) traced inside the kernel — one implementation, two
schedulers. Tested for bit-equality against expand_one_level in
interpreter mode (CPU) and compiled (TPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import aes_jax, backend_jax, value_codec


def _divisor_block_w(w: int, block_w: int) -> int:
    """Largest divisor of `w` that is <= block_w. Expansion widths are
    slab*2^k (slab a multiple of 32), so this normally lands on a large
    block even when w is not a multiple of the default block — a
    caller-chosen lane_slab like 96 produces widths 3*2^k (ADVICE r2)."""
    bw = min(block_w, w)
    while bw > 1 and w % bw:
        bw -= 1
    return max(1, bw)


def _block_plan(w: int, block_w: int):
    """Returns (bw, wp): the kernel block width and the (possibly padded)
    lane-word width, wp % bw == 0. Prefers an exact large divisor of w
    (zero padding); when the best divisor is degenerate (prime-ish widths
    would get near-width-1 blocks — Mosaic lowering failure or a
    pathological grid), falls back to zero-padding w up to a multiple of a
    256-capped block. Padded lanes compute on zero seeds and are trimmed
    by the caller."""
    bw = _divisor_block_w(w, block_w)
    if bw == w or bw >= max(32, block_w // 8):
        # Exact divisor with a non-degenerate block (>= one packed word,
        # and not minuscule relative to the requested block): zero padding.
        return bw, w
    bw = min(block_w, 256)
    return bw, w + (-w) % bw


def _pad_lane_words(arrays, w: int, bw: int):
    """Zero-pads each array's trailing lane-word axis from w up to a
    multiple of bw. Returns (padded_arrays, padded_w)."""
    pad = (-w) % bw
    if pad == 0:
        return list(arrays), w
    out = []
    for a in arrays:
        cfg = [(0, 0)] * (a.ndim - 1) + [(0, pad)]
        out.append(jnp.pad(a, cfg))
    return out, w + pad


def _expand_kernel(
    planes_ref,  # uint32[128, bw]
    control_ref,  # uint32[1, bw]
    cw_ref,  # uint32[128, 1]
    cc_ref,  # uint32[1, 2]: (ccl, ccr)
    rk_ref,  # uint32[22, 128]: [rk_base | rk_diff], 16*8 planes per round
    out_planes_ref,  # uint32[128, bw]
    out_control_ref,  # uint32[1, bw]
):
    child = pl.program_id(0)  # 0 = left key, 1 = right key
    p = planes_ref[:, :]
    c = control_ref[0, :]
    w = p.shape[1]
    key_mask = jnp.broadcast_to(
        jnp.where(child == 0, jnp.uint32(0), jnp.uint32(0xFFFFFFFF)), (w,)
    )
    rks = rk_ref[:, :].reshape(22, 16, 8)
    h = aes_jax.hash_planes(p, rks[:11], rks[11:], key_mask)
    h = h ^ (cw_ref[:, 0][:, None] & c[None, :])
    cc = jnp.where(child == 0, cc_ref[0, 0], cc_ref[0, 1])
    new_control = h[0] ^ (c & cc)
    # Zero the LSB plane without h.at[0].set(...): scatter does not lower
    # in Pallas TPU kernels (observed NotImplementedError on v5e).
    row = jax.lax.broadcasted_iota(jnp.uint32, h.shape, 0)
    h = jnp.where(row == 0, jnp.uint32(0), h)
    out_planes_ref[:, :] = h
    out_control_ref[0, :] = new_control


@functools.partial(jax.jit, static_argnames=("block_w", "interpret"))
def expand_one_level_pallas(
    planes: jnp.ndarray,  # uint32[128, W]
    control: jnp.ndarray,  # uint32[W]
    cw_plane: jnp.ndarray,  # uint32[128]
    ccl_mask: jnp.ndarray,  # uint32 scalar mask
    ccr_mask: jnp.ndarray,  # uint32 scalar mask
    block_w: int = 512,
    interpret: bool = False,
):
    """Pallas twin of backend_jax.expand_one_level (same outputs/layout)."""
    w = planes.shape[1]
    bw, wp = _block_plan(w, block_w)
    if wp != w:
        # This legacy tensor-shaped kernel (micro-benchmarks only) has no
        # pad-and-trim plumbing; fail loudly rather than compile a
        # degenerate grid (r3 review). The batched row kernels pad.
        raise NotImplementedError(
            f"width {w} has no usable divisor block <= {block_w}; use "
            "expand_one_level_pallas_batched, which zero-pads arbitrary "
            "widths"
        )
    rks = np.concatenate(
        [backend_jax._rk_np("left"), backend_jax._rk_np("lr_diff")]
    ).reshape(22, 128)
    grid = (2, w // bw)
    out_planes, out_control = pl.pallas_call(
        _expand_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((128, 2 * w), jnp.uint32),
            jax.ShapeDtypeStruct((1, 2 * w), jnp.uint32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((128, bw), lambda i, j: (0, j)),
            pl.BlockSpec((1, bw), lambda i, j: (0, j)),
            pl.BlockSpec((128, 1), lambda i, j: (0, 0)),
            pl.BlockSpec((1, 2), lambda i, j: (0, 0)),
            pl.BlockSpec((22, 128), lambda i, j: (0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((128, bw), lambda i, j: (0, i * (w // bw) + j)),
            pl.BlockSpec((1, bw), lambda i, j: (0, i * (w // bw) + j)),
        ),
        interpret=interpret,
    )(
        planes,
        control[None, :],
        cw_plane[:, None],
        jnp.stack([ccl_mask, ccr_mask]).astype(jnp.uint32)[None, :],
        jnp.asarray(rks),
    )
    return out_planes, out_control[0]


# ---------------------------------------------------------------------------
# Row-based kernel: Mosaic-compatible form
# ---------------------------------------------------------------------------
#
# The tensor-shaped kernel above traces `hash_planes`, whose
# [128, w] <-> [16, 8, w] reshapes and stacks Mosaic rejects
# ("infer-vector-layout: unsupported shape cast" on the v5e remote
# compiler). This variant re-expresses the identical circuit as plain
# Python lists of 128 one-dimensional rows — only elementwise vector ops
# and static-index row loads/stores — and bakes the fixed PRG round keys
# in as TRACE-TIME constants (they are compile-time-known: XORs with a
# zero plane vanish from the traced circuit entirely, the plane-space
# analog of the reference's precomputed key schedule).


def _sbox_rows(byte_rows):
    """AES S-box on one byte's 8 bit-rows (LSB-first), via the shared
    Boyar–Peralta netlist (aes_jax._bp_sbox, MSB-first order)."""
    u = [byte_rows[7 - i] for i in range(8)]
    s = aes_jax._bp_sbox(*u)
    return [s[7 - k] for k in range(8)]


def _aes_rows(rows, rk_base, rk_diff, key_mask):
    """AES-128 on 128 bit-rows. rk_base/rk_diff: uint32[11, 16, 8] numpy
    0/~0 constants (rk_diff applies under key_mask — per-lane key select).
    """
    full = np.uint32(0xFFFFFFFF)

    def ark(rows, r):
        out = []
        for p in range(128):
            b, i = divmod(p, 8)
            row = rows[p]
            if rk_base[r, b, i]:
                row = row ^ full  # NOT: plane-constant key bit
            if rk_diff is not None and rk_diff[r, b, i]:
                row = row ^ key_mask
            out.append(row)
        return out

    s = ark(rows, 0)
    for r in range(1, 11):
        # SubBytes per byte
        s = [
            bit
            for b in range(16)
            for bit in _sbox_rows(s[8 * b : 8 * b + 8])
        ]
        # ShiftRows: byte permutation
        s = [s[8 * src + i] for src in aes_jax._SHIFT_ROWS for i in range(8)]
        if r < 10:
            # MixColumns on byte lists
            cols = [[s[8 * (4 * c + rr) : 8 * (4 * c + rr) + 8] for rr in range(4)] for c in range(4)]

            def xt(byte):  # GF(2^8) doubling on an 8-bit row list
                a7 = byte[7]
                return [
                    a7,
                    byte[0] ^ a7,
                    byte[1],
                    byte[2] ^ a7,
                    byte[3] ^ a7,
                    byte[4],
                    byte[5],
                    byte[6],
                ]

            out = []
            for c in range(4):
                t = [
                    cols[c][0][i] ^ cols[c][1][i] ^ cols[c][2][i] ^ cols[c][3][i]
                    for i in range(8)
                ]
                for rr in range(4):
                    nxt = cols[c][(rr + 1) % 4]
                    x2 = xt([cols[c][rr][i] ^ nxt[i] for i in range(8)])
                    out.append(
                        [cols[c][rr][i] ^ t[i] ^ x2[i] for i in range(8)]
                    )
            s = [bit for byte in out for bit in byte]
        s = ark(s, r)
    return s


@functools.partial(jax.jit, static_argnames=("block_w", "interpret"))
def expand_one_level_pallas_rows(
    planes: jnp.ndarray,  # uint32[128, W]
    control: jnp.ndarray,  # uint32[W]
    cw_plane: jnp.ndarray,  # uint32[128]
    ccl_mask: jnp.ndarray,
    ccr_mask: jnp.ndarray,
    block_w: int = 512,
    interpret: bool = False,
):
    """Row-based Pallas twin of backend_jax.expand_one_level (same
    outputs/layout as expand_one_level_pallas). Thin single-key view of the
    batched kernel — one implementation to keep in sync."""
    out_planes, out_control = expand_one_level_pallas_batched(
        planes[None],
        control[None],
        cw_plane[None],
        ccl_mask[None] if ccl_mask.ndim else ccl_mask.reshape(1),
        ccr_mask[None] if ccr_mask.ndim else ccr_mask.reshape(1),
        block_w=block_w,
        interpret=interpret,
    )
    return out_planes[0], out_control[0]


def _expand_child_rows(planes_ref, control_ref, cw_ref, cc_ref, rk_base, rk_diff):
    """Shared expand-child body for the batched row kernels: reads the refs,
    selects this grid step's child key by mask, runs the masked AES, applies
    seed/control corrections. Returns (h rows with h[0] zeroed, control)."""
    child = pl.program_id(0)
    c = control_ref[0, 0, :]
    w = c.shape[0]
    key_mask = jnp.broadcast_to(
        jnp.where(child == 0, jnp.uint32(0), jnp.uint32(0xFFFFFFFF)), (w,)
    )
    x = [planes_ref[0, p, :] for p in range(128)]
    sig = [x[64 + p] for p in range(64)] + [
        x[64 + p] ^ x[p] for p in range(64)
    ]
    enc = _aes_rows(sig, rk_base, rk_diff, key_mask)
    h = [enc[p] ^ sig[p] for p in range(128)]
    h = [h[p] ^ (cw_ref[0, p, 0] & c) for p in range(128)]
    cc = jnp.where(child == 0, cc_ref[0, 0, 0], cc_ref[0, 0, 1])
    new_control = h[0] ^ (c & cc)
    h[0] = jnp.zeros_like(h[0])
    return h, new_control


def _expand_kernel_rows_batched(rk_base, rk_diff):
    """Key-batched row kernel: grid (2, K, W//bw); per-key correction words
    and control-correction masks come from refs indexed by the key axis."""

    def kernel(
        planes_ref,  # uint32[1, 128, bw]
        control_ref,  # uint32[1, 1, bw]
        cw_ref,  # uint32[1, 128, 1]
        cc_ref,  # uint32[1, 1, 2]
        out_planes_ref,  # uint32[1, 128, bw]
        out_control_ref,  # uint32[1, 1, bw]
    ):
        h, new_control = _expand_child_rows(
            planes_ref, control_ref, cw_ref, cc_ref, rk_base, rk_diff
        )
        for p in range(128):
            out_planes_ref[0, p, :] = h[p]
        out_control_ref[0, 0, :] = new_control

    return kernel


@functools.partial(jax.jit, static_argnames=("block_w", "interpret"))
def expand_one_level_pallas_batched(
    planes: jnp.ndarray,  # uint32[K, 128, W]
    control: jnp.ndarray,  # uint32[K, W] lane-word control masks
    cw_plane: jnp.ndarray,  # uint32[K, 128]
    ccl_mask: jnp.ndarray,  # uint32[K]
    ccr_mask: jnp.ndarray,  # uint32[K]
    block_w: int = 2048,
    interpret: bool = False,
):
    """Batched row-kernel twin of vmap(backend_jax.expand_one_level):
    identical outputs/layout ([K, 128, 2W] with children block-concatenated
    along the lane-word axis)."""
    kernel = _expand_kernel_rows_batched(
        backend_jax._rk_np("left"), backend_jax._rk_np("lr_diff")
    )
    return _run_expand_blocked(
        kernel, planes, control, cw_plane, ccl_mask, ccr_mask,
        block_w, interpret,
    )


def _run_expand_blocked(
    kernel, planes, control, cw_plane, ccl_mask, ccr_mask, block_w, interpret
):
    """Shared pallas_call scaffolding for the child-doubling kernels
    (plain expand and fused expand+hash): block plan, lane padding, the
    (2, K, blocks) grid with children block-concatenated along the output
    lane axis, and the pad trim/re-concat. The kernel decides WHAT the
    per-child outputs are (planes or hashed planes)."""
    k, _, w = planes.shape
    bw, wp = _block_plan(w, block_w)
    if wp != w:
        (planes, control), _ = _pad_lane_words((planes, control), w, bw)
    nblk = wp // bw
    out_main, out_control = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((k, 128, 2 * wp), jnp.uint32),
            jax.ShapeDtypeStruct((k, 1, 2 * wp), jnp.uint32),
        ),
        grid=(2, k, nblk),
        in_specs=[
            pl.BlockSpec((1, 128, bw), lambda i, kk, j: (kk, 0, j)),
            pl.BlockSpec((1, 1, bw), lambda i, kk, j: (kk, 0, j)),
            pl.BlockSpec((1, 128, 1), lambda i, kk, j: (kk, 0, 0)),
            pl.BlockSpec((1, 1, 2), lambda i, kk, j: (kk, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec(
                (1, 128, bw), lambda i, kk, j: (kk, 0, i * nblk + j)
            ),
            pl.BlockSpec(
                (1, 1, bw), lambda i, kk, j: (kk, 0, i * nblk + j)
            ),
        ),
        interpret=interpret,
    )(
        planes,
        control[:, None, :],
        cw_plane[:, :, None],
        jnp.stack([ccl_mask, ccr_mask], axis=-1).astype(jnp.uint32)[:, None, :],
    )
    if wp != w:
        # Children live at [0:wp] / [wp:2wp]; re-concatenate the real lanes
        # so the caller sees the unpadded [left | right] layout.
        out_main = jnp.concatenate(
            [out_main[:, :, :w], out_main[:, :, wp : wp + w]], axis=2
        )
        out_control = jnp.concatenate(
            [out_control[:, :, :w], out_control[:, :, wp : wp + w]], axis=2
        )
    return out_main, out_control[:, 0, :]


def _expand_hash_kernel_rows_batched(rk_base, rk_diff, rk_value):
    """Fused LAST-level kernel: one doubling expansion child + its value
    hash in a single kernel, emitting only the hashed planes and the new
    control row. In the fold path the final level's child planes are read
    exactly once (by the value hash) and then discarded, so fusing removes
    a full HBM write+read of the widest planes — the single largest memory
    op of a doubling expansion (the last level is half of all lanes)."""

    def kernel(
        planes_ref,  # uint32[1, 128, bw]
        control_ref,  # uint32[1, 1, bw]
        cw_ref,  # uint32[1, 128, 1]
        cc_ref,  # uint32[1, 1, 2]
        out_hashed_ref,  # uint32[1, 128, bw]
        out_control_ref,  # uint32[1, 1, bw]
    ):
        h, new_control = _expand_child_rows(
            planes_ref, control_ref, cw_ref, cc_ref, rk_base, rk_diff
        )
        # Value hash of the child seed, chained in-register.
        sig2 = [h[64 + p] for p in range(64)] + [
            h[64 + p] ^ h[p] for p in range(64)
        ]
        enc2 = _aes_rows(sig2, rk_value, None, None)
        for p in range(128):
            out_hashed_ref[0, p, :] = enc2[p] ^ sig2[p]
        out_control_ref[0, 0, :] = new_control

    return kernel


@functools.partial(jax.jit, static_argnames=("block_w", "interpret"))
def expand_and_hash_last_level_pallas_batched(
    planes: jnp.ndarray,  # uint32[K, 128, W]
    control: jnp.ndarray,  # uint32[K, W]
    cw_plane: jnp.ndarray,  # uint32[K, 128]
    ccl_mask: jnp.ndarray,  # uint32[K]
    ccr_mask: jnp.ndarray,  # uint32[K]
    block_w: int = 2048,
    interpret: bool = False,
):
    """Fused twin of expand_one_level_pallas_batched followed by
    hash_value_planes_pallas_batched on its output: returns
    (hashed uint32[K, 128, 2W], control uint32[K, 2W]) — child planes are
    never materialized in HBM. Bit-identical to the two-kernel
    composition (the kernel body chains the same two circuits)."""
    kernel = _expand_hash_kernel_rows_batched(
        backend_jax._rk_np("left"),
        backend_jax._rk_np("lr_diff"),
        backend_jax._rk_np("value"),
    )
    return _run_expand_blocked(
        kernel, planes, control, cw_plane, ccl_mask, ccr_mask,
        block_w, interpret,
    )


def _value_hash_kernel_rows(rk_value):
    """Fixed-key value-PRG hash (no key select, no corrections)."""

    def kernel(planes_ref, out_ref):
        x = [planes_ref[0, p, :] for p in range(128)]
        sig = [x[64 + p] for p in range(64)] + [
            x[64 + p] ^ x[p] for p in range(64)
        ]
        enc = _aes_rows(sig, rk_value, None, None)
        for p in range(128):
            out_ref[0, p, :] = enc[p] ^ sig[p]

    return kernel


@functools.partial(jax.jit, static_argnames=("block_w", "interpret"))
def hash_value_planes_pallas_batched(
    planes: jnp.ndarray,  # uint32[K, 128, W]
    block_w: int = 2048,
    interpret: bool = False,
):
    """Batched row-kernel twin of vmap(backend_jax.hash_value_planes)."""
    k, _, w = planes.shape
    bw, wp = _block_plan(w, block_w)
    if wp != w:
        (planes,), _ = _pad_lane_words((planes,), w, bw)
    out = pl.pallas_call(
        _value_hash_kernel_rows(backend_jax._rk_np("value")),
        out_shape=jax.ShapeDtypeStruct((k, 128, wp), jnp.uint32),
        grid=(k, wp // bw),
        in_specs=[pl.BlockSpec((1, 128, bw), lambda kk, j: (kk, 0, j))],
        out_specs=pl.BlockSpec((1, 128, bw), lambda kk, j: (kk, 0, j)),
        interpret=interpret,
    )(planes)
    return out[:, :, :w] if wp != w else out


def _walk_level_kernel_tiled(rk_base, rk_diff):
    """One walk level for a TILE of kb keys: rows are (kb, bw) 2-D tiles so
    narrow point batches still fill the (8, 128) vregs. Per-lane key select
    comes from the level's path-bit mask (shared across keys); correction
    words / control corrections are per-key columns broadcast across lanes.
    Mirrors backend_jax.evaluate_seeds_planes's scan body."""

    def kernel(
        planes_ref,  # uint32[kb, 128, bw]
        control_ref,  # uint32[kb, 1, bw]
        mask_ref,  # uint32[1, 1, bw] path bits of this level
        cw_ref,  # uint32[kb, 128, 1]
        cc_ref,  # uint32[kb, 1, 2] (ccl, ccr) of this level
        out_planes_ref,  # uint32[kb, 128, bw]
        out_control_ref,  # uint32[kb, 1, bw]
    ):
        c = control_ref[:, 0, :]  # (kb, bw)
        key_mask = mask_ref[0, 0, :][None, :]  # (1, bw) broadcasts
        x = [planes_ref[:, p, :] for p in range(128)]
        sig = [x[64 + p] for p in range(64)] + [
            x[64 + p] ^ x[p] for p in range(64)
        ]
        enc = _aes_rows(sig, rk_base, rk_diff, key_mask)
        h = [enc[p] ^ sig[p] ^ (cw_ref[:, p, :] & c) for p in range(128)]
        l = cc_ref[:, 0, 0:1]  # (kb, 1)
        r = cc_ref[:, 0, 1:2]
        cc = (l & ~key_mask) | (r & key_mask)  # (kb, bw)
        new_control = h[0] ^ (c & cc)
        h[0] = jnp.zeros_like(h[0])
        for p in range(128):
            out_planes_ref[:, p, :] = h[p]
        out_control_ref[:, 0, :] = new_control

    return kernel


@functools.partial(
    jax.jit, static_argnames=("block_w", "key_tile", "interpret")
)
def walk_levels_pallas_batched(
    planes: jnp.ndarray,  # uint32[K, 128, W]
    control: jnp.ndarray,  # uint32[K, W]
    path_masks: jnp.ndarray,  # uint32[L, W] shared across keys
    cw_planes: jnp.ndarray,  # uint32[K, L, 128]
    ccl: jnp.ndarray,  # uint32[K, L]
    ccr: jnp.ndarray,  # uint32[K, L]
    block_w: int = 512,
    key_tile: int = 8,
    interpret: bool = False,
):
    """Batched Mosaic twin of vmap(backend_jax.evaluate_seeds_planes):
    walks every lane down all L levels (one pallas_call per level inside
    one jit program). Keys are padded to a multiple of key_tile; the
    lane-word axis takes _block_plan's route for arbitrary widths (point
    counts are arbitrary — e.g. P=20000 -> w=625): an exact divisor block
    when a large one exists, else zero-padding to a block multiple, with
    the pad trimmed on return (ADVICE r2)."""
    k, _, w = planes.shape
    levels = path_masks.shape[0]
    bw, wp_plan = _block_plan(w, block_w)
    (planes, control, path_masks), wp = _pad_lane_words(
        (planes, control, path_masks), w, bw
    )
    assert wp == wp_plan, (w, bw, wp, wp_plan)
    pad = (-k) % key_tile
    if pad:
        planes = jnp.concatenate(
            [planes, jnp.zeros((pad, 128, wp), jnp.uint32)], axis=0
        )
        control = jnp.concatenate(
            [control, jnp.zeros((pad, wp), jnp.uint32)], axis=0
        )
        cw_planes = jnp.concatenate(
            [cw_planes, jnp.zeros((pad,) + cw_planes.shape[1:], jnp.uint32)],
            axis=0,
        )
        ccl = jnp.concatenate([ccl, jnp.zeros((pad, levels), jnp.uint32)], axis=0)
        ccr = jnp.concatenate([ccr, jnp.zeros((pad, levels), jnp.uint32)], axis=0)
    kp = k + pad
    kernel = _walk_level_kernel_tiled(
        backend_jax._rk_np("left"), backend_jax._rk_np("lr_diff")
    )
    ctrl = control[:, None, :]
    cc = jnp.stack([ccl, ccr], axis=-1)  # [Kp, L, 2]
    for level in range(levels):
        planes, ctrl = pl.pallas_call(
            kernel,
            out_shape=(
                jax.ShapeDtypeStruct((kp, 128, wp), jnp.uint32),
                jax.ShapeDtypeStruct((kp, 1, wp), jnp.uint32),
            ),
            grid=(kp // key_tile, wp // bw),
            in_specs=[
                pl.BlockSpec((key_tile, 128, bw), lambda kk, j: (kk, 0, j)),
                pl.BlockSpec((key_tile, 1, bw), lambda kk, j: (kk, 0, j)),
                pl.BlockSpec((1, 1, bw), lambda kk, j: (0, 0, j)),
                pl.BlockSpec((key_tile, 128, 1), lambda kk, j: (kk, 0, 0)),
                pl.BlockSpec((key_tile, 1, 2), lambda kk, j: (kk, 0, 0)),
            ],
            out_specs=(
                pl.BlockSpec((key_tile, 128, bw), lambda kk, j: (kk, 0, j)),
                pl.BlockSpec((key_tile, 1, bw), lambda kk, j: (kk, 0, j)),
            ),
            interpret=interpret,
        )(
            planes,
            ctrl,
            path_masks[level][None, None, :],
            cw_planes[:, level, :, None],
            cc[:, level, :][:, None, :],
        )
    return planes[:k, :, :w], ctrl[:k, 0, :w]


# ---------------------------------------------------------------------------
# Multi-level slab megakernel: VMEM-resident tree slabs with in-kernel
# fold / PIR accumulate (ISSUE 3)
# ---------------------------------------------------------------------------
#
# The shipped Pallas path (above) still round-trips every doubling level's
# full plane set through HBM, and the fold path materializes a ~1 GB value
# buffer before the XOR fold / PIR inner product consumes it (PERF.md).
# This kernel keeps the whole subtree expansion resident in VMEM: one
# pallas_call whose grid is (keys, domain slabs); each grid step expands
# ALL remaining doubling levels of its slab in-register/VMEM (per-level
# correction words are small and stay resident for the whole call), runs
# the value hash, converts planes to u32 element limbs with an in-register
# 32x32 bit transpose, applies value correction in-kernel
# (value_codec.rows_correct_element), and accumulates the consumer — the
# XOR fold, optionally AND-masked against a database tile streamed from
# HBM per grid step (BlockSpec index map => Pallas double-buffers the DMA)
# — directly into the tiny [1, lpe, fold_w] output block. The leaves never
# touch HBM at all: the only HBM traffic is the level-h entry seeds, the
# correction-word tables, the (optional) DB stream, and the fold output.
#
# Structure per key (grid order is slab-inner, so j==0 runs first):
#   phase A (j == 0): expand entry planes (width w5) `levels_a` levels to
#     the mid state (width mid_words), park it in VMEM scratch — it
#     persists across the key's slab steps;
#   phase B (every j): slice slab j (slab_words) from the scratch, expand
#     `levels_b` more levels, value-hash, unpack, correct, fold.
#
# Both children of a level are produced by ONE AES instantiation: the
# parent rows are concatenated with themselves ([left slot | right slot])
# and the per-lane key select rides the rk_diff mask, so the traced
# circuit count stays at levels + 1 (value hash), not 2*levels. Lane order
# is the same block-concat recursion as expand_one_level, applied per
# phase/slab — evaluator.megakernel_leaf_map reproduces it on the host for
# the PIR database permutation; the XOR fold itself is order-invariant.
#
# NOTE on Mosaic portability: like the row kernels, the body uses only
# elementwise vector ops, static row loads/stores, scalar ref reads and
# static slices — plus 1-D `jnp.concatenate` (the child doubling) and
# `broadcasted_iota` (the child key mask), which interpret mode accepts;
# they are the first things to check when the tunnel compiles this for
# real (the [128,w]<->[16,8,w] reshape/stack rejection did NOT extend to
# 1-D concatenation in the Mosaic versions probed so far).


def _transpose32_rows(rows):
    """In-register 32x32 bit transpose over a list of 32 uint32 rows:
    out[j] word w bit i == in[i] word w bit j. Row-kernel twin of
    aes_jax._bit_transpose32 (same masked-shift butterfly, the 32-word
    axis realized as the Python list) — applied to hashed plane rows
    [32l, 32l+32) it yields limb-l value rows: out[j][w] = limb l of
    block 32w+j, i.e. the in-kernel form of aes_jax.unpack_from_planes."""
    a = list(rows[::-1])
    for j, m in zip(aes_jax._TSHIFTS, aes_jax._TMASKS):
        mm = jnp.uint32(m)
        out = [None] * 32
        for base in range(0, 32, 2 * j):
            for i in range(j):
                a0 = a[base + i]
                a1 = a[base + j + i]
                t = (a0 ^ (a1 >> jnp.uint32(j))) & mm
                out[base + i] = a0 ^ t
                out[base + j + i] = a1 ^ (t << jnp.uint32(j))
        a = out
    return a[::-1]


def _expand_rows_double(rows, c, cw_scalars, ccl, ccr, rk_base, rk_diff):
    """One doubling level with BOTH children in one AES instantiation:
    parent rows are concatenated with themselves ([left | right] block
    layout, the expand_one_level recursion) and the right half selects the
    right PRG key via the rk_diff mask. Returns (child rows of width 2w,
    child control row)."""
    w = rows[0].shape[0]
    x = [jnp.concatenate([r, r], axis=0) for r in rows]
    c2 = jnp.concatenate([c, c], axis=0)
    pos = jax.lax.broadcasted_iota(jnp.uint32, (1, 2 * w), 1)[0]
    key_mask = jnp.where(
        pos >= jnp.uint32(w), jnp.uint32(0xFFFFFFFF), jnp.uint32(0)
    )
    sig = [x[64 + p] for p in range(64)] + [x[64 + p] ^ x[p] for p in range(64)]
    enc = _aes_rows(sig, rk_base, rk_diff, key_mask)
    h = [enc[p] ^ sig[p] ^ (cw_scalars[p] & c2) for p in range(128)]
    cc = (ccl & ~key_mask) | (ccr & key_mask)
    new_c = h[0] ^ (c2 & cc)
    h[0] = jnp.zeros_like(h[0])
    return h, new_c


def _megakernel_slab_tail(
    rows, c, corr_scalars, db_slab, bits, party, xor_group, keep, rk_value
):
    """Shared phase-B tail: value hash, in-register unpack, correction,
    optional DB mask, XOR fold over rows/elements. `rows`/`c` are the
    leaf-level plane rows / control row of one slab; `db_slab` indexes
    like the kernel's db_ref block ([keep*lpe*32, final_words] rows).
    Returns the slab's lpe fold vectors (width = final slab words). Used
    verbatim by BOTH the kernel body and `megakernel_reference_rows`, so
    the interpret-mode plumbing tests and the eager real-circuit oracle
    replay exercise the same code."""
    lpe = bits // 32
    sig = [rows[64 + p] for p in range(64)] + [
        rows[64 + p] ^ rows[p] for p in range(64)
    ]
    enc = _aes_rows(sig, rk_value, None, None)
    h = [enc[p] ^ sig[p] for p in range(128)]
    vrows = [_transpose32_rows(h[32 * l : 32 * l + 32]) for l in range(4)]
    acc = [None] * lpe
    for i in range(32):
        # Control bit of block 32w+i is bit i of control word w.
        ctrl_mask = jnp.uint32(0) - ((c >> jnp.uint32(i)) & jnp.uint32(1))
        for e in range(keep):
            limbs = [vrows[e * lpe + l][i] for l in range(lpe)]
            corr = [corr_scalars(e, l) for l in range(lpe)]
            vals = value_codec.rows_correct_element(
                limbs, ctrl_mask, corr, bits, party, xor_group
            )
            if db_slab is not None:
                vals = [
                    vals[l] & db_slab((e * lpe + l) * 32 + i)
                    for l in range(lpe)
                ]
            for l in range(lpe):
                acc[l] = vals[l] if acc[l] is None else acc[l] ^ vals[l]
    return acc


def megakernel_reference_rows(
    planes,  # uint32[128, entry_words] one key's level-h seed planes
    control,  # uint32[entry_words]
    cw_planes,  # uint32[L, 128]
    ccl,  # uint32[L]
    ccr,  # uint32[L]
    corrections,  # uint32[epb, lpe]
    db_rows=None,  # uint32[keep*lpe*32, total_words]
    *,
    plan,
    bits: int,
    party: int,
    xor_group: bool,
    keep: int,
):
    """Pure-array replay of ONE key's megakernel computation — the same
    row functions (`_expand_rows_double`, `_aes_rows`,
    `_transpose32_rows`, `rows_correct_element` via the shared slab tail)
    on plain jnp arrays, no pallas_call. Two jobs (mirroring the
    test_rows_circuit / _CheapRows split the row kernels established):
    run eagerly (jax.disable_jit) with the REAL circuit it is bit-exact
    against the host oracle in CI time; run with the cheap `_aes_rows`
    stand-in it is the reference the interpret-mode pallas plumbing tests
    compare against. Returns the [lpe] fold limbs (fold over the whole
    domain, db-masked when given)."""
    lpe = bits // 32
    levels = plan.levels_a + plan.levels_b
    rows = [planes[p] for p in range(128)]
    c = control
    for lvl in range(plan.levels_a):
        rows, c = _expand_rows_double(
            rows, c,
            [cw_planes[lvl, p] for p in range(128)],
            ccl[lvl], ccr[lvl],
            backend_jax._rk_np("left"), backend_jax._rk_np("lr_diff"),
        )
    sw = plan.slab_words
    total = [None] * lpe
    for j in range(plan.num_slabs):
        srows = [r[j * sw : (j + 1) * sw] for r in rows]
        sc = c[j * sw : (j + 1) * sw]
        for lvl in range(plan.levels_a, levels):
            srows, sc = _expand_rows_double(
                srows, sc,
                [cw_planes[lvl, p] for p in range(128)],
                ccl[lvl], ccr[lvl],
                backend_jax._rk_np("left"), backend_jax._rk_np("lr_diff"),
            )
        db_slab = None
        if db_rows is not None:
            lo = j * plan.final_words
            db_slab = lambda r, lo=lo: db_rows[r, lo : lo + plan.final_words]
        acc = _megakernel_slab_tail(
            srows, sc,
            lambda e, l: corrections[e, l],
            db_slab, bits, party, xor_group, keep,
            backend_jax._rk_np("value"),
        )
        for l in range(lpe):
            total[l] = acc[l] if total[l] is None else total[l] ^ acc[l]
    out = []
    for l in range(lpe):
        v = total[l][0]
        for wd in range(1, total[l].shape[0]):
            v = v ^ total[l][wd]
        out.append(v)
    return jnp.stack(out)


def _megakernel_body(
    rk_base, rk_diff, rk_value, plan, bits, party, xor_group, keep, use_db
):
    """Builds the megakernel kernel fn for one (plan, value-kind) config."""
    lpe = bits // 32
    levels = plan.levels_a + plan.levels_b
    sw, w_f, fold_w = plan.slab_words, plan.final_words, plan.fold_words

    def kernel(planes_ref, ctrl_ref, cw_ref, cc_ref, corr_ref, *refs):
        if use_db:
            db_ref, out_ref, mid_planes, mid_ctrl = refs
        else:
            (out_ref, mid_planes, mid_ctrl) = refs
        j = pl.program_id(1)

        def _level(rows, c, lvl):
            return _expand_rows_double(
                rows,
                c,
                [cw_ref[0, lvl, p] for p in range(128)],
                cc_ref[0, lvl, 0],
                cc_ref[0, lvl, 1],
                rk_base,
                rk_diff,
            )

        # Phase A: entry -> mid state, parked in scratch for this key's
        # slab steps (grid is slab-inner, so j==0 runs before them all).
        @pl.when(j == 0)
        def _phase_a():
            rows = [planes_ref[0, p, :] for p in range(128)]
            c = ctrl_ref[0, 0, :]
            for lvl in range(plan.levels_a):
                rows, c = _level(rows, c, lvl)
            for p in range(128):
                mid_planes[p, :] = rows[p]
            mid_ctrl[0, :] = c

        # Phase B: slab j of the mid state -> leaves -> values -> fold
        # (value hash + in-register unpack + correction + accumulate live
        # in the shared `_megakernel_slab_tail`).
        off = j * sw
        rows = [mid_planes[p, pl.ds(off, sw)] for p in range(128)]
        c = mid_ctrl[0, pl.ds(off, sw)]
        for lvl in range(plan.levels_a, levels):
            rows, c = _level(rows, c, lvl)
        acc = _megakernel_slab_tail(
            rows,
            c,
            lambda e, l: corr_ref[0, e, l],
            (lambda r: db_ref[r, :]) if use_db else None,
            bits,
            party,
            xor_group,
            keep,
            rk_value,
        )
        # Width-reduce each limb accumulator from w_f to fold_w words so
        # the output block stays tiny at any slab size.
        red = []
        for l in range(lpe):
            r = acc[l][0:fold_w]
            for s in range(1, w_f // fold_w):
                r = r ^ acc[l][s * fold_w : (s + 1) * fold_w]
            red.append(r)

        @pl.when(j == 0)
        def _init():
            for l in range(lpe):
                out_ref[0, l, :] = red[l]

        @pl.when(j != 0)
        def _accumulate():
            for l in range(lpe):
                out_ref[0, l, :] = out_ref[0, l, :] ^ red[l]

    return kernel


def megakernel_fold_pallas_batched(
    planes: jnp.ndarray,  # uint32[K, 128, entry_words] level-h seed planes
    control: jnp.ndarray,  # uint32[K, entry_words] packed control masks
    cw_planes: jnp.ndarray,  # uint32[K, L, 128]
    ccl: jnp.ndarray,  # uint32[K, L]
    ccr: jnp.ndarray,  # uint32[K, L]
    corrections: jnp.ndarray,  # uint32[K, epb, lpe]
    db_rows=None,  # uint32[keep*lpe*32, total_words] megakernel-order DB
    *,
    plan,  # evaluator.MegakernelPlan (static)
    bits: int,
    party: int,
    xor_group: bool,
    keep: int,
    interpret: bool = False,
):
    """The slab megakernel: one pallas_call per key chunk expanding every
    device level in VMEM and XOR-folding the corrected values in-kernel
    (AND-masked against `db_rows` when given — the PIR inner product).
    Returns uint32[K, lpe, fold_w] per-key partial folds; XOR-reduce the
    last axis for the [K, lpe] answer (kept outside the kernel so the
    final cross-word reduction is one trivial XLA op)."""
    k = planes.shape[0]
    lpe = bits // 32
    levels = plan.levels_a + plan.levels_b
    assert cw_planes.shape[1] == levels, (cw_planes.shape, plan)
    kernel = _megakernel_body(
        backend_jax._rk_np("left"),
        backend_jax._rk_np("lr_diff"),
        backend_jax._rk_np("value"),
        plan,
        bits,
        party,
        xor_group,
        keep,
        db_rows is not None,
    )
    cc = jnp.stack([ccl, ccr], axis=-1).astype(jnp.uint32)  # [K, L, 2]
    in_specs = [
        pl.BlockSpec((1, 128, plan.entry_words), lambda kk, j: (kk, 0, 0)),
        pl.BlockSpec((1, 1, plan.entry_words), lambda kk, j: (kk, 0, 0)),
        pl.BlockSpec((1, levels, 128), lambda kk, j: (kk, 0, 0)),
        pl.BlockSpec((1, levels, 2), lambda kk, j: (kk, 0, 0)),
        pl.BlockSpec((1, corrections.shape[1], lpe), lambda kk, j: (kk, 0, 0)),
    ]
    args = [planes, control[:, None, :], cw_planes, cc, corrections]
    if db_rows is not None:
        # DB tile per slab, streamed from HBM: the index map advances with
        # j, so Pallas double-buffers the next slab's DMA behind this
        # slab's compute (the emit_pipeline behavior of blocked inputs).
        in_specs.append(
            pl.BlockSpec((keep * lpe * 32, plan.final_words), lambda kk, j: (0, j))
        )
        args.append(db_rows)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((k, lpe, plan.fold_words), jnp.uint32),
        grid=(k, plan.num_slabs),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, lpe, plan.fold_words), lambda kk, j: (kk, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((128, plan.mid_words), jnp.uint32),
            pltpu.VMEM((1, plan.mid_words), jnp.uint32),
        ],
        interpret=interpret,
    )(*args)


# ---------------------------------------------------------------------------
# Walk megakernel: single-program in-register tree walks for EvaluateAt,
# DCF BatchEvaluate and the walk-driven gates (ISSUE 4)
# ---------------------------------------------------------------------------
#
# The point-walk paths (evaluate_at_batch, dcf.batch_evaluate and MIC's
# batch_eval riding it) still run `walk_levels_pallas_batched` one
# pallas_call PER LEVEL: every one of the 20-128 tree levels pays a kernel
# boundary plus the full [K, P] seed-plane HBM round trip, on a link where
# each dispatch costs ~66 ms — exactly why the engine table's point-walk
# rows lose to one shared CPU core (PERF.md). This kernel is the walk twin
# of the slab megakernel above: ONE pallas_call per key chunk whose grid is
# (keys, point tiles); each grid step walks its tile of points down ALL
# tree levels in-register — the seed-plane rows and the control row live in
# VMEM/vregs for the whole level loop, never touching HBM between levels —
# with the per-level correction-word tables (small: levels x 128 plane
# masks + 2 control masks per key) VMEM-resident for the whole call.
#
# Per-lane child selection rides the rk_diff key mask exactly like the
# per-level walk kernel (lane = point, mask bit = that point's path bit at
# this level), so the traced circuit count stays levels + captures — one
# masked AES instantiation per level plus one value-hash instantiation per
# capture depth (EvaluateAt captures once at the leaves; DCF captures at
# every output depth and accumulates in-register).
#
# The capture tail reuses the megakernel's machinery: value hash, the
# in-register 32x32 bit transpose (`_transpose32_rows`) to element-limb
# rows, `value_codec.rows_correct_element` for the Int32/64/u128 codecs,
# and a packed-bit select mask per (depth, element) that folds the DCF
# accumulate mask in on the host — so block-element selection and the
# "accumulate iff the point's bit is 0" gate are a single AND. The DCF
# accumulate itself is `value_codec.rows_limb_add` (carry chain identical
# to the XLA scan's `_limb_add`), with the party-1 negation applied once
# after the last capture (`rows_limb_neg`).
#
# Output is [K, lpe*32, Wp] "value rows": row l*32+i at word w holds limb
# l of point 32w+i — the transpose back to [K, P, lpe] is one cheap XLA
# reshape/transpose in the same jit (evaluator._walk_megakernel_chunk_jit).
# Emitting rows keeps the kernel store pattern static (128 row writes max)
# and the output tiny: K * P * lpe * 4 bytes, no domain term anywhere.
#
# Mosaic portability: the body is the row kernels' op set (elementwise
# vector ops, static row loads/stores, scalar ref reads) plus the scalar
# broadcast of the per-key seed columns — NO 1-D concatenate, iota, or
# cross-grid-step scratch (each (key, tile) step is self-contained), so it
# sits strictly inside the op set the per-level walk kernel already proved
# on hardware; the watch-list items the slab megakernel added do not apply
# here.


def _walk_megakernel_core(
    rows,  # list of 128 uint32 rows: replicated root-seed planes
    c,  # uint32 row: initial control mask (party)
    path_row,  # path_row(lvl) -> uint32 row of this level's packed path bits
    cw_scalar,  # cw_scalar(lvl, p) -> uint32 scalar
    cc_scalar,  # cc_scalar(lvl, side) -> uint32 scalar (0=left, 1=right)
    corr_scalar,  # corr_scalar(row_idx, l) -> uint32 scalar
    sel_mask,  # sel_mask(row_idx, i) -> uint32 0/~0 row (select gate)
    *,
    levels: int,
    bits: int,
    party: int,
    xor_group: bool,
    keep: int,
    captures,  # None = EvaluateAt (one leaf capture); tuple[bool] = DCF
    rk_base,
    rk_diff,
    rk_value,
):
    """The whole walk+capture computation on indexable operands — used
    VERBATIM by the kernel body (reading refs) and by
    `walk_megakernel_reference_rows` (reading plain arrays), the same
    sharing contract `_megakernel_slab_tail` established: the interpret
    plumbing tests and the eager real-circuit oracle replay exercise this
    exact code. Returns vals[l][i]: uint32 row — limb l of points 32w+i.

    `corr_scalar`/`sel_mask` row indices: EvaluateAt indexes elements
    directly (row_idx = e in [0, keep)); DCF indexes (depth, element)
    flattened as d * keep + e, with the accumulate mask pre-ANDed into
    `sel_mask` on the host."""
    lpe = bits // 32

    def _level(rows, c, lvl):
        pmask = path_row(lvl)
        sig = [rows[64 + p] for p in range(64)] + [
            rows[64 + p] ^ rows[p] for p in range(64)
        ]
        enc = _aes_rows(sig, rk_base, rk_diff, pmask)
        h = [enc[p] ^ sig[p] ^ (cw_scalar(lvl, p) & c) for p in range(128)]
        cc = (cc_scalar(lvl, 0) & ~pmask) | (cc_scalar(lvl, 1) & pmask)
        new_c = h[0] ^ (c & cc)
        h[0] = jnp.zeros_like(h[0])
        return h, new_c

    def _capture(rows, c, base, cap_party):
        sig = [rows[64 + p] for p in range(64)] + [
            rows[64 + p] ^ rows[p] for p in range(64)
        ]
        enc = _aes_rows(sig, rk_value, None, None)
        h = [enc[p] ^ sig[p] for p in range(128)]
        vrows = [_transpose32_rows(h[32 * l : 32 * l + 32]) for l in range(4)]
        out = [[None] * 32 for _ in range(lpe)]
        for i in range(32):
            ctrl_mask = jnp.uint32(0) - ((c >> jnp.uint32(i)) & jnp.uint32(1))
            for e in range(keep):
                limbs = [vrows[e * lpe + l][i] for l in range(lpe)]
                corr = [corr_scalar(base + e, l) for l in range(lpe)]
                vals = value_codec.rows_correct_element(
                    limbs, ctrl_mask, corr, bits, cap_party, xor_group
                )
                sel = sel_mask(base + e, i)
                vals = [v & sel for v in vals]
                for l in range(lpe):
                    out[l][i] = (
                        vals[l] if out[l][i] is None else out[l][i] ^ vals[l]
                    )
        return out

    if captures is None:
        for lvl in range(levels):
            rows, c = _level(rows, c, lvl)
        return _capture(rows, c, 0, party)

    assert len(captures) == levels + 1, (len(captures), levels)
    acc = None
    for d in range(levels + 1):
        if captures[d]:
            # Per-depth corrections apply WITHOUT the party negation (the
            # XLA scan's shape); party 1 negates the accumulator once at
            # the end.
            vals = _capture(rows, c, d * keep, 0)
            if acc is None:
                acc = vals
            elif xor_group:
                acc = [
                    [acc[l][i] ^ vals[l][i] for i in range(32)]
                    for l in range(lpe)
                ]
            else:
                for i in range(32):
                    s = value_codec.rows_limb_add(
                        [acc[l][i] for l in range(lpe)],
                        [vals[l][i] for l in range(lpe)],
                        bits,
                    )
                    for l in range(lpe):
                        acc[l][i] = s[l]
        if d < levels:
            rows, c = _level(rows, c, d)
    if party == 1 and not xor_group:
        for i in range(32):
            s = value_codec.rows_limb_neg(
                [acc[l][i] for l in range(lpe)], bits
            )
            for l in range(lpe):
                acc[l][i] = s[l]
    return acc


def walk_megakernel_reference_rows(
    seed_planes,  # uint32[128] one key's root-seed plane masks (0/~0)
    path_masks,  # uint32[L, W] packed per-point path bits
    cw_planes,  # uint32[L, 128]
    ccl,  # uint32[L]
    ccr,  # uint32[L]
    corrections,  # uint32[n_rows, lpe] (EvaluateAt: n_rows=epb; DCF: (L+1)*epb)
    sel_bits,  # uint32[n_rows, W] packed per-point select bits
    *,
    bits: int,
    party: int,
    xor_group: bool,
    keep: int,
    captures=None,
):
    """Pure-array replay of ONE key's walk-megakernel computation — the
    same row functions on plain jnp arrays, no pallas_call (the
    `megakernel_reference_rows` twin for the walk paths). Two jobs: run
    eagerly (jax.disable_jit) with the REAL circuit it is bit-exact
    against the host oracle in CI time; run with the cheap `_aes_rows`
    stand-in it is the reference the interpret-mode pallas plumbing tests
    compare against. Tiling is pure lane slicing (every op is
    lane-local), so the untiled replay covers any plan. Returns
    uint32[lpe*32, W] value rows: row l*32+i word w = limb l of point
    32w+i."""
    w = path_masks.shape[1]
    levels = path_masks.shape[0]
    rows = [jnp.broadcast_to(seed_planes[p], (w,)) for p in range(128)]
    c = jnp.full(
        (w,), jnp.uint32(0xFFFFFFFF) if party else jnp.uint32(0), jnp.uint32
    )
    vals = _walk_megakernel_core(
        rows,
        c,
        lambda lvl: path_masks[lvl],
        lambda lvl, p: cw_planes[lvl, p],
        lambda lvl, side: (ccl, ccr)[side][lvl],
        lambda r, l: corrections[r, l],
        lambda r, i: jnp.uint32(0)
        - ((sel_bits[r] >> jnp.uint32(i)) & jnp.uint32(1)),
        levels=levels,
        bits=bits,
        party=party,
        xor_group=xor_group,
        keep=keep,
        captures=captures,
        rk_base=backend_jax._rk_np("left"),
        rk_diff=backend_jax._rk_np("lr_diff"),
        rk_value=backend_jax._rk_np("value"),
    )
    lpe = bits // 32
    return jnp.stack([vals[l][i] for l in range(lpe) for i in range(32)])


# ---------------------------------------------------------------------------
# Hierarchical megakernel: single-program prefix-window advances for the
# heavy-hitters path (ISSUE 5)
# ---------------------------------------------------------------------------
#
# The heavy-hitters hierarchical walk (hierarchical.evaluate_levels_fused)
# is the last workload where the device loses to one CPU core: the grouped
# fused advance already minimized the program count (~8 programs for the
# 128-level plan), so the residual gap is per-dispatch latency times the
# window count. This kernel is the walk-megakernel treatment of the
# hierarchy: ONE pallas_call per (key chunk x prefix window) advancing a
# whole window of W tree levels in-register.
#
# The data-dependent per-level prefix gathers — the reason ISSUE 4 could
# not cover this path — dissolve under one observation: every advance's
# prefix set is known on the host before the call (prepare_levels_fused
# composes the index tables today), so each level's "gather" can be
# compiled into the walk itself. Every (hierarchy level, expanded tree
# node) pair in the window gets its own LANE; the host composes, per lane,
# its window-entry ancestor (an outside-kernel XLA gather in the same jit)
# and its packed path bits from that ancestor — so the in-kernel walk is
# the walk kernel's lockstep level loop with per-lane path-bit key select,
# and the per-level prefix selection is packed one-hot select-mask rows
# (pre-ANDed on the host, padded to the window's max prefix width): each
# level's value capture is gated by a mask row that is hot exactly on that
# level's lanes, and the cross-level combine is a mask-AND-XOR placement
# (lanes are one-hot across capture slots) instead of a dynamic index.
#
# Per capture slot the tail runs in-kernel: value hash, the in-register
# 32x32 bit transpose, value_codec.rows_correct_element with the FULL
# per-level correction (party negation included — unlike the DCF form,
# each hierarchy level's output is a finished value, not a summand), and
# the masked XOR placement into per-(element, limb) accumulator rows. The
# kernel also exports the end-of-walk seed planes + control row: the last
# slot's lanes are exactly the final level's full child-block expansion in
# leaf order — the resumable BatchedContext state — and the next window's
# entry gather reads it (outside the kernel, in the next program).
#
# Mosaic portability: the body stays strictly inside the hardware-proven
# walk-kernel vocabulary — elementwise vector ops, static row
# loads/stores, scalar ref reads, per-lane masks; NO 1-D concatenate, no
# iota, no cross-grid-step scratch (each (key, tile) grid step is
# self-contained). Trace depth is levels + capture slots chained AES
# circuits per window (<= ~2*group), the same risk class the walk
# megakernel already carries on the watch-list.


def _hier_megakernel_core(
    rows,  # list of 128 uint32 rows: gathered window-entry seed planes
    c,  # uint32 row: gathered window-entry control mask
    path_row,  # path_row(lvl) -> uint32 row of this level's packed path bits
    cw_scalar,  # cw_scalar(lvl, p) -> uint32 scalar
    cc_scalar,  # cc_scalar(lvl, side) -> uint32 scalar (0=left, 1=right)
    corr_scalar,  # corr_scalar(row_idx, l) -> uint32 scalar
    sel_mask,  # sel_mask(row_idx, i) -> uint32 0/~0 row (slot-lane gate)
    *,
    levels: int,
    bits: int,
    party: int,
    xor_group: bool,
    keep: int,
    captures,  # tuple[levels + 1] of int: capture-slot index at each
    #            depth, -1 = no capture at that depth
    rk_base,
    rk_diff,
    rk_value,
):
    """The whole window computation on indexable operands — used VERBATIM
    by the kernel body (reading refs) and by
    `hier_megakernel_reference_rows` (reading plain arrays), the sharing
    contract `_megakernel_slab_tail` / `_walk_megakernel_core`
    established: the interpret plumbing tests and the eager real-circuit
    oracle replay exercise this exact code.

    Row indices of `corr_scalar`/`sel_mask`: slot s element e flattens to
    s * keep + e. Unlike the DCF walk core, every capture applies the
    FULL party correction (each hierarchy level emits finished values)
    and slots combine by masked XOR placement — each lane is hot in at
    most one slot, so the XOR is pure placement in any value group.

    Returns (acc, rows, c): acc[e][l][i] uint32 rows — limb l of element
    e of lanes 32w+i — plus the end-of-walk seed rows and control row
    (the exit state the next window / the resumable context gathers)."""
    lpe = bits // 32

    def _level(rows, c, lvl):
        pmask = path_row(lvl)
        sig = [rows[64 + p] for p in range(64)] + [
            rows[64 + p] ^ rows[p] for p in range(64)
        ]
        enc = _aes_rows(sig, rk_base, rk_diff, pmask)
        h = [enc[p] ^ sig[p] ^ (cw_scalar(lvl, p) & c) for p in range(128)]
        cc = (cc_scalar(lvl, 0) & ~pmask) | (cc_scalar(lvl, 1) & pmask)
        new_c = h[0] ^ (c & cc)
        h[0] = jnp.zeros_like(h[0])
        return h, new_c

    acc = [[[None] * 32 for _ in range(lpe)] for _ in range(keep)]

    def _capture(rows, c, slot):
        sig = [rows[64 + p] for p in range(64)] + [
            rows[64 + p] ^ rows[p] for p in range(64)
        ]
        enc = _aes_rows(sig, rk_value, None, None)
        h = [enc[p] ^ sig[p] for p in range(128)]
        vrows = [_transpose32_rows(h[32 * l : 32 * l + 32]) for l in range(4)]
        for i in range(32):
            ctrl_mask = jnp.uint32(0) - ((c >> jnp.uint32(i)) & jnp.uint32(1))
            for e in range(keep):
                limbs = [vrows[e * lpe + l][i] for l in range(lpe)]
                corr = [corr_scalar(slot * keep + e, l) for l in range(lpe)]
                vals = value_codec.rows_correct_element(
                    limbs, ctrl_mask, corr, bits, party, xor_group
                )
                sel = sel_mask(slot * keep + e, i)
                for l in range(lpe):
                    v = vals[l] & sel
                    acc[e][l][i] = (
                        v if acc[e][l][i] is None else acc[e][l][i] ^ v
                    )

    assert len(captures) == levels + 1, (len(captures), levels)
    assert any(s >= 0 for s in captures), captures
    for d in range(levels + 1):
        if captures[d] >= 0:
            _capture(rows, c, captures[d])
        if d < levels:
            rows, c = _level(rows, c, d)
    return acc, rows, c


def hier_megakernel_reference_rows(
    entry_planes,  # uint32[128, W] one key's gathered window-entry planes
    entry_control,  # uint32[W] packed entry control masks
    path_masks,  # uint32[L, W] packed per-lane path bits from the entry
    cw_planes,  # uint32[L, 128]
    ccl,  # uint32[L]
    ccr,  # uint32[L]
    corrections,  # uint32[n_rows, lpe] per-(slot, element) correction limbs
    sel_bits,  # uint32[n_rows, W] packed per-lane slot-membership bits
    *,
    bits: int,
    party: int,
    xor_group: bool,
    keep: int,
    captures,
):
    """Pure-array replay of ONE key's hier-megakernel window — the same
    row functions on plain jnp arrays, no pallas_call (the
    `walk_megakernel_reference_rows` twin for the hierarchical path). Two
    jobs: run eagerly (jax.disable_jit) with the REAL circuit it is
    bit-exact against the host oracle in CI time; run with the cheap
    `_aes_rows` stand-in it is the reference the interpret-mode pallas
    plumbing tests compare against. Returns (value rows
    uint32[keep*lpe*32, W] — row (e*lpe+l)*32+i word w = limb l of
    element e of lane 32w+i — exit seed planes uint32[128, W], exit
    control row uint32[W])."""
    levels = path_masks.shape[0]
    rows = [entry_planes[p] for p in range(128)]
    c = entry_control
    acc, xrows, xc = _hier_megakernel_core(
        rows,
        c,
        lambda lvl: path_masks[lvl],
        lambda lvl, p: cw_planes[lvl, p],
        lambda lvl, side: (ccl, ccr)[side][lvl],
        lambda r, l: corrections[r, l],
        lambda r, i: jnp.uint32(0)
        - ((sel_bits[r] >> jnp.uint32(i)) & jnp.uint32(1)),
        levels=levels,
        bits=bits,
        party=party,
        xor_group=xor_group,
        keep=keep,
        captures=captures,
        rk_base=backend_jax._rk_np("left"),
        rk_diff=backend_jax._rk_np("lr_diff"),
        rk_value=backend_jax._rk_np("value"),
    )
    lpe = bits // 32
    vals = jnp.stack(
        [acc[e][l][i] for e in range(keep) for l in range(lpe) for i in range(32)]
    )
    return vals, jnp.stack(xrows), xc


def _hier_megakernel_body(
    rk_base, rk_diff, rk_value, plan, bits, party, xor_group, keep, captures
):
    """Builds the hier-megakernel kernel fn for one (plan, window-shape)
    config. The body reads refs and delegates every computation to
    `_hier_megakernel_core` (shared with the replay)."""
    lpe = bits // 32

    def kernel(
        planes_ref,  # uint32[1, 128, tw] gathered entry planes
        ctrl_ref,  # uint32[1, 1, tw] entry control masks
        path_ref,  # uint32[L, tw]
        cw_ref,  # uint32[1, L, 128]
        cc_ref,  # uint32[1, L, 2]
        corr_ref,  # uint32[1, n_rows, lpe]
        sel_ref,  # uint32[n_rows, tw]
        out_ref,  # uint32[1, keep*lpe*32, tw] value rows
        xplanes_ref,  # uint32[1, 128, tw] exit seed planes
        xctrl_ref,  # uint32[1, 1, tw] exit control masks
    ):
        rows = [planes_ref[0, p, :] for p in range(128)]
        c = ctrl_ref[0, 0, :]
        acc, xrows, xc = _hier_megakernel_core(
            rows,
            c,
            lambda lvl: path_ref[lvl, :],
            lambda lvl, p: cw_ref[0, lvl, p],
            lambda lvl, side: cc_ref[0, lvl, side],
            lambda r, l: corr_ref[0, r, l],
            lambda r, i: jnp.uint32(0)
            - ((sel_ref[r, :] >> jnp.uint32(i)) & jnp.uint32(1)),
            levels=plan.levels,
            bits=bits,
            party=party,
            xor_group=xor_group,
            keep=keep,
            captures=captures,
            rk_base=rk_base,
            rk_diff=rk_diff,
            rk_value=rk_value,
        )
        for e in range(keep):
            for l in range(lpe):
                for i in range(32):
                    out_ref[0, (e * lpe + l) * 32 + i, :] = acc[e][l][i]
        for p in range(128):
            xplanes_ref[0, p, :] = xrows[p]
        xctrl_ref[0, 0, :] = xc

    return kernel


def hier_megakernel_pallas_batched(
    entry_planes: jnp.ndarray,  # uint32[K, 128, Wp] gathered entry planes
    entry_control: jnp.ndarray,  # uint32[K, Wp] packed entry control masks
    path_masks: jnp.ndarray,  # uint32[L, Wp] shared across keys
    cw_planes: jnp.ndarray,  # uint32[K, L, 128]
    ccl: jnp.ndarray,  # uint32[K, L]
    ccr: jnp.ndarray,  # uint32[K, L]
    corrections: jnp.ndarray,  # uint32[K, n_rows, lpe]
    sel_bits: jnp.ndarray,  # uint32[n_rows, Wp] packed slot-lane bits
    *,
    plan,  # evaluator.HierkernelPlan (static)
    bits: int,
    party: int,
    xor_group: bool,
    keep: int,
    captures,  # tuple[levels + 1] of slot index / -1 (static)
    interpret: bool = False,
):
    """The hierarchical megakernel: ONE pallas_call per (key chunk x
    prefix window), grid (keys, lane tiles). Each grid step walks its
    tile of (level, tree-node) lanes down the whole window in-register
    and captures every hierarchy level's values through the pre-ANDed
    select-mask rows. Returns (value rows uint32[K, keep*lpe*32, Wp],
    exit seed planes uint32[K, 128, Wp], exit control uint32[K, Wp]);
    the caller transposes/gathers per level in the same jit."""
    k = entry_planes.shape[0]
    lpe = bits // 32
    levels = plan.levels
    assert path_masks.shape == (levels, plan.padded_words), (
        path_masks.shape,
        plan,
    )
    assert sel_bits.shape[1] == plan.padded_words, (sel_bits.shape, plan)
    kernel = _hier_megakernel_body(
        backend_jax._rk_np("left"),
        backend_jax._rk_np("lr_diff"),
        backend_jax._rk_np("value"),
        plan,
        bits,
        party,
        xor_group,
        keep,
        captures,
    )
    cc = jnp.stack([ccl, ccr], axis=-1).astype(jnp.uint32)  # [K, L, 2]
    n_rows = corrections.shape[1]
    n_sel = sel_bits.shape[0]
    tw = plan.tile_words
    out, xplanes, xctrl = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((k, keep * lpe * 32, plan.padded_words), jnp.uint32),
            jax.ShapeDtypeStruct((k, 128, plan.padded_words), jnp.uint32),
            jax.ShapeDtypeStruct((k, 1, plan.padded_words), jnp.uint32),
        ),
        grid=(k, plan.num_tiles),
        in_specs=[
            pl.BlockSpec((1, 128, tw), lambda kk, j: (kk, 0, j)),
            pl.BlockSpec((1, 1, tw), lambda kk, j: (kk, 0, j)),
            pl.BlockSpec((levels, tw), lambda kk, j: (0, j)),
            pl.BlockSpec((1, levels, 128), lambda kk, j: (kk, 0, 0)),
            pl.BlockSpec((1, levels, 2), lambda kk, j: (kk, 0, 0)),
            pl.BlockSpec((1, n_rows, lpe), lambda kk, j: (kk, 0, 0)),
            pl.BlockSpec((n_sel, tw), lambda kk, j: (0, j)),
        ],
        out_specs=(
            pl.BlockSpec((1, keep * lpe * 32, tw), lambda kk, j: (kk, 0, j)),
            pl.BlockSpec((1, 128, tw), lambda kk, j: (kk, 0, j)),
            pl.BlockSpec((1, 1, tw), lambda kk, j: (kk, 0, j)),
        ),
        interpret=interpret,
    )(
        entry_planes,
        entry_control[:, None, :],
        path_masks,
        cw_planes,
        cc,
        corrections,
        sel_bits,
    )
    return out, xplanes, xctrl[:, 0, :]


def _walk_megakernel_body(
    rk_base, rk_diff, rk_value, plan, bits, party, xor_group, keep, captures
):
    """Builds the walk-megakernel kernel fn for one (plan, workload)
    config. The body reads refs and delegates every computation to
    `_walk_megakernel_core` (shared with the replay)."""
    lpe = bits // 32
    tw = plan.tile_words

    def kernel(seed_ref, path_ref, cw_ref, cc_ref, corr_ref, sel_ref, out_ref):
        rows = [jnp.broadcast_to(seed_ref[0, p], (tw,)) for p in range(128)]
        c = jnp.full(
            (tw,),
            jnp.uint32(0xFFFFFFFF) if party else jnp.uint32(0),
            jnp.uint32,
        )
        vals = _walk_megakernel_core(
            rows,
            c,
            lambda lvl: path_ref[lvl, :],
            lambda lvl, p: cw_ref[0, lvl, p],
            lambda lvl, side: cc_ref[0, lvl, side],
            lambda r, l: corr_ref[0, r, l],
            lambda r, i: jnp.uint32(0)
            - ((sel_ref[r, :] >> jnp.uint32(i)) & jnp.uint32(1)),
            levels=plan.levels,
            bits=bits,
            party=party,
            xor_group=xor_group,
            keep=keep,
            captures=captures,
            rk_base=rk_base,
            rk_diff=rk_diff,
            rk_value=rk_value,
        )
        for l in range(lpe):
            for i in range(32):
                out_ref[0, l * 32 + i, :] = vals[l][i]

    return kernel


def walk_megakernel_pallas_batched(
    seed_planes: jnp.ndarray,  # uint32[K, 128] root-seed plane masks
    path_masks: jnp.ndarray,  # uint32[L, Wp] shared across keys
    cw_planes: jnp.ndarray,  # uint32[K, L, 128]
    ccl: jnp.ndarray,  # uint32[K, L]
    ccr: jnp.ndarray,  # uint32[K, L]
    corrections: jnp.ndarray,  # uint32[K, n_rows, lpe]
    sel_bits: jnp.ndarray,  # uint32[n_rows, Wp] packed select bits
    *,
    plan,  # evaluator.WalkkernelPlan (static)
    bits: int,
    party: int,
    xor_group: bool,
    keep: int,
    captures=None,  # None = EvaluateAt; tuple[bool, L+1] = DCF depths
    interpret: bool = False,
):
    """The walk megakernel: ONE pallas_call per key chunk walking every
    tree level in-register, grid (keys, point tiles). Returns
    uint32[K, lpe*32, Wp] value rows (row l*32+i word w = limb l of point
    32w+i); the caller transposes to [K, P, lpe] in the same jit.

    EvaluateAt form (captures=None): walk all L levels, one leaf capture
    with the party correction applied per element; `sel_bits` row e
    selects the points whose addressed block element is e (all-ones when
    keep == 1). DCF form (captures = tuple of L+1 bools): capture at every
    flagged depth with corrections row d*keep+e, accumulate in-register
    (additive carry chain or XOR), negate once for party 1; `sel_bits`
    rows carry the block select AND the DCF accumulate mask, pre-combined
    on the host."""
    k = seed_planes.shape[0]
    lpe = bits // 32
    levels = plan.levels
    assert path_masks.shape == (levels, plan.padded_words), (
        path_masks.shape,
        plan,
    )
    assert sel_bits.shape[1] == plan.padded_words, (sel_bits.shape, plan)
    kernel = _walk_megakernel_body(
        backend_jax._rk_np("left"),
        backend_jax._rk_np("lr_diff"),
        backend_jax._rk_np("value"),
        plan,
        bits,
        party,
        xor_group,
        keep,
        captures,
    )
    cc = jnp.stack([ccl, ccr], axis=-1).astype(jnp.uint32)  # [K, L, 2]
    n_rows = corrections.shape[1]
    n_sel = sel_bits.shape[0]
    tw = plan.tile_words
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(
            (k, lpe * 32, plan.padded_words), jnp.uint32
        ),
        grid=(k, plan.num_tiles),
        in_specs=[
            pl.BlockSpec((1, 128), lambda kk, j: (kk, 0)),
            pl.BlockSpec((levels, tw), lambda kk, j: (0, j)),
            pl.BlockSpec((1, levels, 128), lambda kk, j: (kk, 0, 0)),
            pl.BlockSpec((1, levels, 2), lambda kk, j: (kk, 0, 0)),
            pl.BlockSpec((1, n_rows, lpe), lambda kk, j: (kk, 0, 0)),
            pl.BlockSpec((n_sel, tw), lambda kk, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((1, lpe * 32, tw), lambda kk, j: (kk, 0, j)),
        interpret=interpret,
    )(seed_planes, path_masks, cw_planes, cc, corrections, sel_bits)


# ---------------------------------------------------------------------------
# Keygen megakernel: single-program batched key generation (ISSUE 19)
# ---------------------------------------------------------------------------
#
# The jax/pallas keygen modes (ops/keygen_batch.py) pay exactly
# `tree_levels_needed` device programs per batch — one expand dispatch per
# level — so deep domains sit on the ~66 ms/dispatch floor regardless of
# batch size. This kernel runs the WHOLE Fig.-11 dealer loop as ONE
# pallas_call: both parties' seed planes and control rows stay resident in
# VMEM across all levels, and the correction-word algebra
# (core/keygen.batch_level_step, lines 5-12 of Fig. 11) is computed
# in-kernel from the expanded planes — the dealer holds BOTH parties'
# seeds, so every correction word is a pure function of rows already on
# chip.
#
# Lane layout: lanes are KEYS (bit j of word w = key 32w+j), the transpose
# of the walk kernels' point lanes. Seed planes come in as
# `aes_jax.pack_to_planes` of the interleaved 2K seed rows split by party;
# path bits as `aes_jax.pack_bit_mask` rows per level. With keys in lanes,
# every per-key quantity (control bit, path bit, control correction) is a
# packed row and the whole level step is elementwise row algebra:
#
#   lose_p  = left_p if path bit set else right_p     (keep = alpha bit)
#   sc      = lose_0 ^ lose_1                         (seed correction)
#   ccl     = ~(ebl_0 ^ ebl_1 ^ path)                 (control corrections)
#   ccr     =   ebr_0 ^ ebr_1 ^ path
#   rows_p ^= sc & c_p ; c_p = ebk_p ^ (c_p & keep_cc)
#
# with eb* the bit-0 rows extracted (and zeroed) from each branch hash,
# exactly `batch_level_step`'s exp_bits handling. Value captures land
# BEFORE the level step that consumes the same seeds (the
# blocks_needed == 1 fusion in `generate_keys_batch`: the value-PRG inputs
# ARE the parent seeds), plus the unconditional final capture after the
# last level; the host applies the typed beta algebra
# (`_value_corrections_from_hashed`) to the captured hash rows — value
# typing stays host-side, the kernel only moves AES.
#
# Outputs are row-major planes shared across the whole batch tile:
# correction-word planes [levels*128, Wp], control-correction rows
# [levels*2, Wp] (row 2l = ccl, 2l+1 = ccr), captured value-hash planes
# [slots*256, Wp] (slot s, party p, plane q at row s*256 + p*128 + q), and
# party-1 control rows at each capture [slots, Wp] (the only control the
# typed correction consumes). Grid is (key tiles,): each tile is
# self-contained — no cross-step scratch, no concatenate, no iota — so the
# body sits strictly inside the op set the walk megakernel already proved
# on hardware (6 masked-AES instantiations per level+capture: left/right
# per party plus value per party per capture).


def _keygen_megakernel_core(
    rows0,  # list of 128 uint32 rows: party-0 seed planes (keys in lanes)
    rows1,  # list of 128 uint32 rows: party-1 seed planes
    c0,  # uint32 row: party-0 control bits (starts all-zero)
    c1,  # uint32 row: party-1 control bits (starts all-one)
    path_row,  # path_row(lvl) -> uint32 row of this level's packed alpha bits
    *,
    levels: int,
    captures,  # tuple[bool, levels + 1]: value-capture depths (final True)
    rk_left,
    rk_right,
    rk_value,
):
    """The whole dealer loop on indexable operands — used VERBATIM by the
    kernel body (reading refs) and by `keygen_megakernel_reference_rows`
    (reading plain arrays), the `_walk_megakernel_core` sharing contract:
    interpret plumbing tests and the eager real-circuit oracle replay
    exercise this exact code. Returns flat row lists
    (cw_rows[levels*128], cc_rows[levels*2], vh_rows[slots*256],
    ctrl_rows[slots])."""
    cw_rows = []
    cc_rows = []
    vh_rows = []
    ctrl_rows = []

    def _branch_hashes(rows, pmask, notp):
        """Both branch hashes of one party's seeds; returns the bit-0 rows
        (exp_bits, pre-clear) plus the lose/keep child selected per lane by
        the path mask and the keep branch's exp-bit row."""
        sig = [rows[64 + p] for p in range(64)] + [
            rows[64 + p] ^ rows[p] for p in range(64)
        ]
        encl = _aes_rows(sig, rk_left, None, None)
        encr = _aes_rows(sig, rk_right, None, None)
        hl = [encl[p] ^ sig[p] for p in range(128)]
        hr = [encr[p] ^ sig[p] for p in range(128)]
        ebl = hl[0]
        ebr = hr[0]
        hl[0] = jnp.zeros_like(hl[0])
        hr[0] = jnp.zeros_like(hr[0])
        # keep = alpha bit: path bit 1 keeps right (loses left).
        lose = [(hl[p] & pmask) | (hr[p] & notp) for p in range(128)]
        keep = [(hr[p] & pmask) | (hl[p] & notp) for p in range(128)]
        ebk = (ebr & pmask) | (ebl & notp)
        return lose, keep, ebl, ebr, ebk

    def _capture(rows_a, rows_b, ctrl):
        for rows in (rows_a, rows_b):
            sig = [rows[64 + p] for p in range(64)] + [
                rows[64 + p] ^ rows[p] for p in range(64)
            ]
            enc = _aes_rows(sig, rk_value, None, None)
            # Raw value hash — bit 0 is value payload here, NOT a control
            # bit; no clearing (matches KeygenPrg.expand want_value).
            vh_rows.extend([enc[p] ^ sig[p] for p in range(128)])
        ctrl_rows.append(ctrl)

    for d in range(levels + 1):
        if captures[d]:
            _capture(rows0, rows1, c1)
        if d == levels:
            break
        pmask = path_row(d)
        notp = ~pmask
        lose0, keep0, ebl0, ebr0, ebk0 = _branch_hashes(rows0, pmask, notp)
        lose1, keep1, ebl1, ebr1, ebk1 = _branch_hashes(rows1, pmask, notp)
        sc = [lose0[p] ^ lose1[p] for p in range(128)]
        ccl = ~(ebl0 ^ ebl1 ^ pmask)
        ccr = ebr0 ^ ebr1 ^ pmask
        keep_cc = (ccr & pmask) | (ccl & notp)
        # Seed correction applies under the OLD control bit (Fig. 11 line
        # 11); compute both parties' new rows before updating controls.
        rows0 = [keep0[p] ^ (sc[p] & c0) for p in range(128)]
        rows1 = [keep1[p] ^ (sc[p] & c1) for p in range(128)]
        c0 = ebk0 ^ (c0 & keep_cc)
        c1 = ebk1 ^ (c1 & keep_cc)
        cw_rows.extend(sc)
        cc_rows.append(ccl)
        cc_rows.append(ccr)
    return cw_rows, cc_rows, vh_rows, ctrl_rows


def keygen_megakernel_reference_rows(
    planes0,  # uint32[128, W] party-0 seed planes (keys packed in lanes)
    planes1,  # uint32[128, W] party-1 seed planes
    path_masks,  # uint32[levels, W] packed per-key alpha bits
    *,
    captures,  # tuple[bool, levels + 1]
):
    """Pure-array replay of the keygen megakernel — the same row functions
    on plain jnp arrays, no pallas_call (the established reference twin).
    Run eagerly with the REAL circuit it is bit-exact against the host
    dealer; run with a cheap `_aes_rows` stand-in it anchors the
    interpret-mode plumbing tests. Returns (cw [levels*128, W],
    cc [levels*2, W], vh [slots*256, W], ctrl [slots, W])."""
    w = path_masks.shape[1]
    levels = path_masks.shape[0]
    rows0 = [planes0[p] for p in range(128)]
    rows1 = [planes1[p] for p in range(128)]
    c0 = jnp.zeros((w,), jnp.uint32)
    c1 = jnp.full((w,), jnp.uint32(0xFFFFFFFF), jnp.uint32)
    cw, cc, vh, ctrl = _keygen_megakernel_core(
        rows0,
        rows1,
        c0,
        c1,
        lambda lvl: path_masks[lvl],
        levels=levels,
        captures=captures,
        rk_left=backend_jax._rk_np("left"),
        rk_right=backend_jax._rk_np("right"),
        rk_value=backend_jax._rk_np("value"),
    )
    return (
        jnp.stack(cw),
        jnp.stack(cc),
        jnp.stack(vh),
        jnp.stack(ctrl),
    )


def _keygen_megakernel_body(rk_left, rk_right, rk_value, levels, captures, tw):
    """Builds the keygen-megakernel kernel fn for one (levels, captures,
    tile) config. The body reads refs and delegates every computation to
    `_keygen_megakernel_core` (shared with the replay)."""

    def kernel(
        planes0_ref,  # uint32[128, tw]
        planes1_ref,  # uint32[128, tw]
        path_ref,  # uint32[levels, tw]
        cw_ref,  # uint32[levels * 128, tw]
        cc_ref,  # uint32[levels * 2, tw]
        vh_ref,  # uint32[slots * 256, tw]
        ctrl_ref,  # uint32[slots, tw]
    ):
        rows0 = [planes0_ref[p, :] for p in range(128)]
        rows1 = [planes1_ref[p, :] for p in range(128)]
        c0 = jnp.zeros((tw,), jnp.uint32)
        c1 = jnp.full((tw,), jnp.uint32(0xFFFFFFFF), jnp.uint32)
        cw, cc, vh, ctrl = _keygen_megakernel_core(
            rows0,
            rows1,
            c0,
            c1,
            lambda lvl: path_ref[lvl, :],
            levels=levels,
            captures=captures,
            rk_left=rk_left,
            rk_right=rk_right,
            rk_value=rk_value,
        )
        for r in range(len(cw)):
            cw_ref[r, :] = cw[r]
        for r in range(len(cc)):
            cc_ref[r, :] = cc[r]
        for r in range(len(vh)):
            vh_ref[r, :] = vh[r]
        for r in range(len(ctrl)):
            ctrl_ref[r, :] = ctrl[r]

    return kernel


def keygen_megakernel_pallas_batched(
    planes0: jnp.ndarray,  # uint32[128, Wp] party-0 seed planes
    planes1: jnp.ndarray,  # uint32[128, Wp] party-1 seed planes
    path_masks: jnp.ndarray,  # uint32[levels, Wp] packed per-key alpha bits
    *,
    captures,  # tuple[bool, levels + 1]: value-capture depths
    block_w: int = 32,
    interpret: bool = False,
):
    """The keygen megakernel: ONE pallas_call per key batch running every
    tree level in VMEM, grid (key tiles,). `Wp` must be a multiple of
    `block_w` (the host pads the key batch). Returns
    (cw [levels*128, Wp], cc [levels*2, Wp], vh [slots*256, Wp],
    ctrl [slots, Wp]) — see the section comment for row layouts; the host
    (ops/keygen_batch._megakernel_generate) unpacks these into the SAME
    level-record stream the numpy dealer feeds `assemble_batch_keys`, so
    wire keys are byte-identical by construction."""
    levels = path_masks.shape[0]
    wp = planes0.shape[1]
    assert levels >= 1, "keygen megakernel needs at least one tree level"
    assert planes0.shape == (128, wp), planes0.shape
    assert planes1.shape == (128, wp), planes1.shape
    assert wp % block_w == 0, (wp, block_w)
    captures = tuple(bool(x) for x in captures)
    assert len(captures) == levels + 1, (len(captures), levels)
    assert captures[levels], "final level is always a value capture"
    slots = sum(1 for x in captures if x)
    kernel = _keygen_megakernel_body(
        backend_jax._rk_np("left"),
        backend_jax._rk_np("right"),
        backend_jax._rk_np("value"),
        levels,
        captures,
        block_w,
    )
    num_tiles = wp // block_w
    tw = block_w
    return pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((levels * 128, wp), jnp.uint32),
            jax.ShapeDtypeStruct((levels * 2, wp), jnp.uint32),
            jax.ShapeDtypeStruct((slots * 256, wp), jnp.uint32),
            jax.ShapeDtypeStruct((slots, wp), jnp.uint32),
        ),
        grid=(num_tiles,),
        in_specs=[
            pl.BlockSpec((128, tw), lambda j: (0, j)),
            pl.BlockSpec((128, tw), lambda j: (0, j)),
            pl.BlockSpec((levels, tw), lambda j: (0, j)),
        ],
        out_specs=(
            pl.BlockSpec((levels * 128, tw), lambda j: (0, j)),
            pl.BlockSpec((levels * 2, tw), lambda j: (0, j)),
            pl.BlockSpec((slots * 256, tw), lambda j: (0, j)),
            pl.BlockSpec((slots, tw), lambda j: (0, j)),
        ),
        interpret=interpret,
    )(planes0, planes1, path_masks)
