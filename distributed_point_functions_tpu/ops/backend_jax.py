"""JAX/TPU evaluation backend: the three DPF hot primitives in plane space.

TPU-native re-design of the reference's evaluation kernels:

* ``evaluate_seeds``  — dpf_internal::EvaluateSeeds
  (/root/reference/dpf/internal/evaluate_prg_hwy.cc:143-506): a
  ``jax.lax.scan`` over tree levels; per level one masked-key bitsliced AES
  hash + correction XOR + control-bit extraction, all on uint32 bit-planes.
* ``expand_seeds``    — DistributedPointFunction::ExpandSeeds
  (/root/reference/dpf/distributed_point_function.cc:271-349): per level both
  PRGs are applied to every lane and the lane axis doubles. Children are laid
  out block-concatenated ([all left | all right]) rather than interleaved —
  packed lanes make interleaving a bit-shuffle — and the resulting
  bit-reversal permutation is undone by a single gather at unpack time.
* ``hash_expanded_seeds`` — HashExpandedSeeds
  (/root/reference/dpf/distributed_point_function.cc:500-524): value-PRG hash
  of seed+j for j < blocks_needed.

The class `JaxBackend` exposes these with a numpy boundary (drop-in for
`NumpyBackend` in core/dpf.py); the `*_planes` functions are the pure device
path used by the batched evaluators (ops/evaluator.py) which never leave the
device between levels.

Lane padding: lane counts are padded up to a multiple of 32 (one packed
word); padded lanes compute garbage independently and are trimmed on unpack.
"""

from __future__ import annotations

import functools
import logging
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import constants
from ..utils import errors
from . import aes_jax

_FULL = np.uint32(0xFFFFFFFF)

_backend_logged = False


def log_backend_once() -> None:
    """One-time log of the active JAX backend and device kind — the analog of
    the reference's one-time SIMD-dispatch-mode log at Create
    (/root/reference/dpf/distributed_point_function.cc:569-571 via
    internal/get_hwy_mode.cc:30-41). Called from the evaluation entry points
    so it runs exactly when the first device computation is about to."""
    global _backend_logged
    if _backend_logged:
        return
    _backend_logged = True
    log = logging.getLogger("distributed_point_functions_tpu")
    try:
        devices = jax.devices()
        log.info(
            "DPF evaluation backend: %s, %d device(s), kind: %s",
            jax.default_backend(),
            len(devices),
            devices[0].device_kind if devices else "none",
        )
    except Exception as e:  # backend init failure is the caller's problem
        log.warning("JAX backend unavailable: %r", e)


def shard_map(fn, mesh, in_specs, out_specs):
    """`jax.shard_map` across installed jax versions.

    Newer jax exposes it at the top level (replication checking spelled
    `check_vma`); older releases (e.g. the 0.4.x on this image) only have
    `jax.experimental.shard_map` (spelled `check_rep`). Without the shim
    every sharded path dies at build time with AttributeError on the old
    runtime — a whole backend lost to an API rename."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
    )


@functools.lru_cache(maxsize=None)
def _rk_np(which: str) -> np.ndarray:
    left = aes_jax.round_key_planes(constants.PRG_KEY_LEFT)
    if which == "left":
        return left
    if which == "right":
        return aes_jax.round_key_planes(constants.PRG_KEY_RIGHT)
    if which == "value":
        return aes_jax.round_key_planes(constants.PRG_KEY_VALUE)
    if which == "lr_diff":
        return left ^ aes_jax.round_key_planes(constants.PRG_KEY_RIGHT)
    raise errors.InternalError(f"unknown PRG round-key table {which!r}")


def _rk(which: str) -> jnp.ndarray:
    # jnp conversion happens at the use site: inside a jit trace the numpy
    # array becomes an embedded constant (caching a jnp array here would
    # leak tracers through the lru_cache).
    return jnp.asarray(_rk_np(which))


def cw_seed_planes(correction_seeds: np.ndarray) -> np.ndarray:
    """uint32[..., 4] limb rows -> uint32[..., 128] plane-broadcast masks."""
    cs = np.asarray(correction_seeds, dtype=np.uint32)
    bits = (cs[..., :, None] >> np.arange(32, dtype=np.uint32)) & 1
    return (bits.reshape(cs.shape[:-1] + (128,)) * _FULL).astype(np.uint32)


def control_masks(flags: np.ndarray) -> np.ndarray:
    """bool[...] -> uint32[...] all-zeros/all-ones lane-broadcast masks."""
    return np.where(np.asarray(flags, dtype=bool), _FULL, np.uint32(0)).astype(
        np.uint32
    )


# ---------------------------------------------------------------------------
# Device cores (pure functions of device arrays; jitted by shape)
# ---------------------------------------------------------------------------


def evaluate_seeds_planes(planes, control, path_masks, cw_planes, ccl, ccr):
    """Walks every lane down L tree levels along its path — plane space.

    Args:
      planes: uint32[128, W] packed seeds. control: uint32[W] lane mask.
      path_masks: uint32[L, W] per-level packed path bits (bit set = right).
      cw_planes: uint32[L, 128] correction-seed plane masks.
      ccl, ccr: uint32[L] control-correction masks (0 / ~0).
    Returns: (uint32[128, W], uint32[W]).
    """
    rk_left = _rk("left")
    rk_diff = _rk("lr_diff")

    def body(carry, xs):
        p, c = carry
        path_mask, cw, l, r = xs
        h = aes_jax.hash_planes(p, rk_left, rk_diff, path_mask)
        h = h ^ (cw[:, None] & c[None, :])
        new_control = h[0]
        h = h.at[0].set(jnp.zeros_like(h[0]))
        cc = (l & ~path_mask) | (r & path_mask)
        return (h, new_control ^ (c & cc)), None

    (planes, control), _ = jax.lax.scan(
        body, (planes, control), (path_masks, cw_planes, ccl, ccr)
    )
    return planes, control


@jax.jit
def _evaluate_seeds_blocks_jit(seeds, control, path_masks, cw, ccl, ccr):
    """pack -> level scan -> unpack fused under one jit."""
    planes = aes_jax.pack_to_planes(seeds)
    out_planes, out_control = evaluate_seeds_planes(
        planes, control, path_masks, cw, ccl, ccr
    )
    return aes_jax.unpack_from_planes(out_planes), out_control


def expand_one_level(planes, control, cw_plane, ccl_mask, ccr_mask):
    """One doubling level: every lane hashed under both PRG keys.

    Implemented as ONE bitsliced AES at doubled width with per-lane key
    selection (left key for the first half, right for the second) — same
    arithmetic as hashing twice, but the program traces a single AES circuit,
    which halves compile time of unrolled expansions. Returns planes/control
    with the lane axis doubled, children block-concatenated:
    [left children | right children].
    """
    w = planes.shape[1]
    both = jnp.concatenate([planes, planes], axis=1)
    key_mask = jnp.concatenate(
        [jnp.zeros(w, jnp.uint32), jnp.full(w, _FULL, jnp.uint32)]
    )
    h = aes_jax.hash_planes(both, _rk("left"), _rk("lr_diff"), key_mask)
    corr = cw_plane[:, None] & control[None, :]
    h = h ^ jnp.concatenate([corr, corr], axis=1)
    cc = jnp.concatenate([control & ccl_mask, control & ccr_mask])
    new_control = h[0] ^ cc
    out = h.at[0].set(jnp.zeros_like(h[0]))
    return out, new_control


_expand_one_level_jit = jax.jit(expand_one_level)
_pack_jit = jax.jit(aes_jax.pack_to_planes)
_unpack_jit = jax.jit(aes_jax.unpack_from_planes)


def hash_value_planes(planes):
    """Value-PRG hash of packed seeds (the j=0 block)."""
    return aes_jax.hash_planes(planes, _rk("value"))


def hash_value_stream(planes, blocks_needed: int):
    """Value-PRG byte stream of packed seeds: hash(seed + j) for all
    j < blocks_needed, concatenated little-endian per lane.

    Device analog of HashExpandedSeeds
    (/root/reference/dpf/distributed_point_function.cc:500-524) feeding the
    value codec: returns uint32[lanes, 4 * blocks_needed] — the limb stream
    whose bytes equal the reference's per-seed hash buffer.
    """
    if blocks_needed == 1:
        return aes_jax.unpack_from_planes(hash_value_planes(planes))
    seeds = aes_jax.unpack_from_planes(planes)
    parts = [aes_jax.unpack_from_planes(hash_value_planes(planes))]
    for j in range(1, blocks_needed):
        s = _add_small_constant(seeds, np.uint32(j))
        h = hash_value_planes(aes_jax.pack_to_planes(s))
        parts.append(aes_jax.unpack_from_planes(h))
    return jnp.concatenate(parts, axis=-1)


@functools.partial(jax.jit, static_argnames=("blocks_needed",))
def _hash_expanded_blocks_jit(seeds, blocks_needed: int):
    """Value-PRG hash of seeds[i]+j for all j < blocks_needed, one batch.

    Returns uint32[blocks_needed, N, 4] (block-major so the per-j hashes stay
    contiguous lanes in plane space).
    """
    inputs = jnp.concatenate(
        [
            seeds if j == 0 else _add_small_constant(seeds, np.uint32(j))
            for j in range(blocks_needed)
        ],
        axis=0,
    )
    hashed = hash_value_planes(aes_jax.pack_to_planes(inputs))
    return aes_jax.unpack_from_planes(hashed).reshape(
        blocks_needed, seeds.shape[0], 4
    )


def _add_small_constant(limbs: jnp.ndarray, j) -> jnp.ndarray:
    """uint128 limb addition of a small scalar j, with carry propagation."""
    out0 = limbs[:, 0] + jnp.uint32(j)
    carry = (out0 < limbs[:, 0]).astype(jnp.uint32)
    out1 = limbs[:, 1] + carry
    carry = (out1 < limbs[:, 1]).astype(jnp.uint32)
    out2 = limbs[:, 2] + carry
    carry = (out2 < limbs[:, 2]).astype(jnp.uint32)
    out3 = limbs[:, 3] + carry
    return jnp.stack([out0, out1, out2, out3], axis=1)


# ---------------------------------------------------------------------------
# Expansion ordering
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def expansion_output_order(
    num_parents: int, padded_parents: int, levels: int
) -> np.ndarray:
    """int64[num_parents << levels] gather indices restoring leaf order after
    `levels` block-concatenated doublings of `num_parents` in-order lanes
    padded to `padded_parents` (padded lanes produce garbage children that
    are skipped). Computed by carrying each lane's leaf prefix through the
    concat schedule."""
    prefix = np.arange(padded_parents, dtype=np.int64)
    prefix[num_parents:] = -1
    for _ in range(levels):
        child = np.where(prefix >= 0, 2 * prefix, -1)
        prefix = np.concatenate([child, np.where(child >= 0, child + 1, -1)])
    order = np.empty(num_parents << levels, dtype=np.int64)
    valid = prefix >= 0
    order[prefix[valid]] = np.nonzero(valid)[0]
    return order


# ---------------------------------------------------------------------------
# Numpy-boundary backend (drop-in for core.dpf.NumpyBackend)
# ---------------------------------------------------------------------------


def _pad_lanes(seeds: np.ndarray, control_bits: np.ndarray, multiple: int = 32):
    n = seeds.shape[0]
    padded = -(-n // multiple) * multiple
    if padded != n:
        seeds = np.concatenate(
            [seeds, np.zeros((padded - n, 4), dtype=np.uint32)], axis=0
        )
        control_bits = np.concatenate(
            [control_bits, np.zeros(padded - n, dtype=bool)]
        )
    return seeds, control_bits, n


def _path_bit_masks(paths: np.ndarray, num_levels: int, padded: int) -> np.ndarray:
    """uint32[N, 4] tree indices -> uint32[L, padded//32] per-level lane masks.

    Level l selects bit (num_levels - 1 - l) of the path, as in the scalar
    reference (evaluate_prg_hwy.cc:441-449).
    """
    n = paths.shape[0]
    bits = np.zeros((num_levels, padded), dtype=bool)
    for level in range(num_levels):
        bit_index = num_levels - 1 - level
        if bit_index < 128:
            bits[level, :n] = (paths[:, bit_index // 32] >> (bit_index % 32)) & 1
    return aes_jax.pack_bit_mask(bits)


class JaxBackend:
    """Evaluation primitives on TPU/JAX (numpy in, numpy out)."""

    name = "jax"

    @staticmethod
    def evaluate_seeds(
        seeds: np.ndarray,
        control_bits: np.ndarray,
        paths: np.ndarray,
        correction_seeds: np.ndarray,
        correction_controls_left: np.ndarray,
        correction_controls_right: np.ndarray,
    ) -> Tuple[np.ndarray, np.ndarray]:
        num_levels = len(correction_seeds)
        n = seeds.shape[0]
        if num_levels == 0 or n == 0:
            return np.array(seeds, dtype=np.uint32), np.asarray(
                control_bits, dtype=bool
            ).copy()
        seeds_p, control_p, _ = _pad_lanes(
            np.asarray(seeds, np.uint32), np.asarray(control_bits, bool)
        )
        control = jnp.asarray(aes_jax.pack_bit_mask(control_p))
        path_masks = jnp.asarray(
            _path_bit_masks(np.asarray(paths, np.uint32), num_levels, seeds_p.shape[0])
        )
        cw = jnp.asarray(cw_seed_planes(correction_seeds))
        ccl = jnp.asarray(control_masks(correction_controls_left))
        ccr = jnp.asarray(control_masks(correction_controls_right))
        out_seeds, out_control = _evaluate_seeds_blocks_jit(
            jnp.asarray(seeds_p), control, path_masks, cw, ccl, ccr
        )
        out_bits = _unpack_mask(np.asarray(out_control), n)
        return np.asarray(out_seeds)[:n], out_bits

    @staticmethod
    def expand_seeds(
        seeds: np.ndarray,
        control_bits: np.ndarray,
        correction_seeds: np.ndarray,
        correction_controls_left: np.ndarray,
        correction_controls_right: np.ndarray,
    ) -> Tuple[np.ndarray, np.ndarray]:
        num_levels = len(correction_seeds)
        n = seeds.shape[0]
        if num_levels == 0 or n == 0:
            return np.array(seeds, dtype=np.uint32), np.asarray(
                control_bits, dtype=bool
            ).copy()
        seeds_p, control_p, _ = _pad_lanes(
            np.asarray(seeds, np.uint32), np.asarray(control_bits, bool)
        )
        padded = seeds_p.shape[0]
        planes = _pack_jit(jnp.asarray(seeds_p))
        control = jnp.asarray(aes_jax.pack_bit_mask(control_p))
        cw = cw_seed_planes(correction_seeds)
        ccl = control_masks(correction_controls_left)
        ccr = control_masks(correction_controls_right)
        for level in range(num_levels):
            planes, control = _expand_one_level_jit(
                planes,
                control,
                jnp.asarray(cw[level]),
                jnp.uint32(ccl[level]),
                jnp.uint32(ccr[level]),
            )
        out_seeds = np.asarray(_unpack_jit(planes))
        out_control = _unpack_mask(np.asarray(control), padded << num_levels)
        order = expansion_output_order(n, padded, num_levels)
        return out_seeds[order], out_control[order]

    @staticmethod
    def hash_expanded_seeds(seeds: np.ndarray, blocks_needed: int) -> np.ndarray:
        seeds = np.asarray(seeds, dtype=np.uint32)
        n = seeds.shape[0]
        if n == 0 or blocks_needed == 0:
            return np.zeros((n, blocks_needed, 4), dtype=np.uint32)
        seeds_p, _, _ = _pad_lanes(seeds, np.zeros(n, dtype=bool))
        hashed = _hash_expanded_blocks_jit(jnp.asarray(seeds_p), blocks_needed)
        return np.asarray(hashed).transpose(1, 0, 2)[:n]


def unpack_mask_device(mask_words: jnp.ndarray) -> jnp.ndarray:
    """uint32[W] lane masks -> uint32[32*W] of 0/1, device-side."""
    bits = (mask_words[:, None] >> jnp.arange(32, dtype=jnp.uint32)) & jnp.uint32(1)
    return bits.reshape(-1)


def _unpack_mask(mask_words: np.ndarray, n: int) -> np.ndarray:
    """uint32[W] lane masks -> bool[n]."""
    bits = (
        (mask_words[:, None] >> np.arange(32, dtype=np.uint32)) & 1
    ).astype(bool)
    return bits.reshape(-1)[:n]
