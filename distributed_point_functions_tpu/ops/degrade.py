"""Graceful backend degradation: Pallas -> pure-JAX -> numpy host engine.

A two-server FSS deployment must keep answering when a backend goes bad —
and on this image's hardware "bad" has meant *silently wrong*, not just
crashed (PERF.md "Platform findings"). This module wraps the bulk
evaluators (ops/evaluator.py) in a fallback chain driven by the runtime
integrity layer (utils/integrity.py):

  1. **Mosaic/Pallas row kernels** — the fast path on real TPUs.
  2. **Pure-JAX XLA bitslice** — same math, no Mosaic lowering; the level
     a Mosaic-specific miscompile degrades to.
  3. **Numpy/native host engine** (core/host_eval.py) — the oracle
     itself; slow but trusted, the level of last resort.

Since ISSUE 7 the chain walks **(mode, backend) rungs**, not flat
backends: the first rung of a call that would run a megakernel mode is
that kernel, and a Mosaic-specific miscompile degrades to the *still-
device* shipped shape (megakernel→fold, walkkernel→walk,
hierkernel→fused) before leaving the device at all. ``ops/supervisor.py``
builds the per-op chains and adds the journaled / deadline-bounded
wrappers for the remaining bulk entry points (DCF, MIC, hierarchical,
PIR); the flat-backend wrappers below keep their shape with rungs whose
mode component is None.

Per rung: transient failures (``UnavailableError`` — including dispatch-
deadline expiries from the supervisor's watchdog) retry with bounded
exponential backoff; ``ResourceExhaustedError`` halves the key-batch
chunk down to ``min_key_chunk`` before degrading; detected corruption
(``DataCorruptionError`` from sentinel verification) degrades
*immediately* — deterministic wrong answers do not get retried at the
level that produced them; a rung that cannot express the call
(``RungUnsupported``) is skipped with no retries. Every decision emits a
structured event through ``utils.integrity.emit_event`` (kinds "retry",
"chunk-halved", "degrade", "recovered") plus a telemetry
``decision(source="degrade")`` record per rung transition, so operators
can see a server running degraded; see README "Running degraded" for the
log-line format.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional, Sequence, Tuple

import numpy as np

from ..utils import faultinject, integrity
from ..utils import telemetry as _tm
from ..utils.errors import (
    DataCorruptionError,
    DataLossError,
    DpfError,
    InternalError,
    InvalidArgumentError,
    ResourceExhaustedError,
    UnavailableError,
)


@dataclasses.dataclass(frozen=True)
class DegradationPolicy:
    """Knobs of the fallback walk. The defaults suit a serving loop; tests
    zero the backoff."""

    max_retries: int = 2  # transient (UnavailableError) retries per level
    backoff_seconds: float = 0.05  # base of the exponential backoff
    min_key_chunk: int = 1  # floor of resource-exhaustion chunk halving
    verify: Optional[bool] = None  # sentinel verification (None = env default)
    #: Dispatch deadline in seconds for every device wait inside the chain
    #: (ops/supervisor.py watchdog). None = the DPF_TPU_DEADLINE env
    #: default; 0 disables even an env-armed deadline for this call.
    deadline_seconds: Optional[float] = None


DEFAULT_POLICY = DegradationPolicy()


class RungUnsupported(Exception):
    """Raised by an attempt_fn whose rung cannot express the call (e.g. an
    explicit kernel mode rejecting a plan shape): the chain skips straight
    to the next rung — no retries, no chunk halving — and records the
    degrade with reason "unsupported". Never escapes `_run_chain`."""

    def __init__(self, reason: str, cause: Optional[BaseException] = None):
        super().__init__(reason)
        self.reason = reason
        self.cause = cause


#: The flat fallback chain, fastest first. "pallas" is only present when
#: the platform default would use the Mosaic kernels (real TPUs or a
#: forced DPF_TPU_PALLAS=1); on CPU the chain starts at "jax".
BACKEND_LEVELS = ("pallas", "jax", "numpy")

#: A chain rung: (mode, backend). mode None = the entry point's shipped
#: default shape; backend "numpy" is the host oracle of last resort.
Rung = Tuple[Optional[str], str]


def fallback_chain() -> Tuple[str, ...]:
    from . import evaluator

    if evaluator._pallas_default():
        return BACKEND_LEVELS
    return BACKEND_LEVELS[1:]


def rung_label(rung: Rung) -> str:
    """Human/telemetry label of one rung: "jax", "walkkernel/pallas", …"""
    mode, backend = rung
    return backend if mode is None else f"{mode}/{backend}"


#: Taxonomy categories the chain may retry / degrade around. Everything
#: else propagates untouched from the first level that raises it:
#: InvalidArgumentError / FailedPreconditionError are the caller's bug,
#: and a library-raised InternalError (e.g. the host-oracle AES self-test
#: failing) means the oracle itself is broken — degrading to the numpy
#: level would serve answers from the very code whose self-test just
#: failed. XLA runtime INTERNAL errors are still degradable: they are not
#: DpfError instances, so classify_exception wraps them via the
#: string-matching branch below.
_DEGRADABLE = (
    DataCorruptionError,
    DataLossError,
    ResourceExhaustedError,
    UnavailableError,
)


def classify_exception(exc: BaseException) -> Optional[DpfError]:
    """Maps runtime/XLA exceptions onto the library's error taxonomy.

    Returns a taxonomy error (the exception itself if already a degradable
    one) or None for exceptions that should propagate unclassified
    (programming errors must not be silently 'degraded' around)."""
    if isinstance(exc, DpfError):
        return exc if isinstance(exc, _DEGRADABLE) else None
    text = f"{type(exc).__name__}: {exc}"
    upper = text.upper()
    if "RESOURCE_EXHAUSTED" in upper or "OUT OF MEMORY" in upper:
        err = ResourceExhaustedError(text)
    elif "UNAVAILABLE" in upper or "DEADLINE_EXCEEDED" in upper or "FAILED TO CONNECT" in upper:
        err = UnavailableError(text)
    elif (
        ("ABORTED" in upper or "CANCELLED" in upper)
        and "XLARUNTIMEERROR" in type(exc).__name__.upper()
    ):
        # jaxlib surfaces a killed/cancelled device computation (runtime
        # restart, preempted tunnel) as XlaRuntimeError ABORTED/CANCELLED;
        # untranslated it fell past the chain uncaught (ISSUE 7). They are
        # transient platform states: retry, then degrade.
        err = UnavailableError(text)
    elif "INTERNAL" in upper and "XLARUNTIMEERROR" in type(exc).__name__.upper():
        err = InternalError(text)
    elif "ONLY INTERPRET MODE IS SUPPORTED" in upper:
        # Pallas lowering on a non-Mosaic backend (jax raises a bare
        # ValueError): the rung's PLATFORM is absent, not broken — a
        # compiled-kernel entry mode on a CPU host must degrade down its
        # chain (e.g. keygen/megakernel → … → jax), not crash the call.
        err = UnavailableError(text)
    else:
        return None
    err.__cause__ = exc
    return err


def _host_full_domain_limbs(dpf, keys, hierarchy_level, key_chunk):
    from ..core import host_eval

    v = dpf.validator
    if hierarchy_level < 0:
        hierarchy_level = v.num_hierarchy_levels - 1
    bits, _ = _scalar_bits(dpf, hierarchy_level)
    raw = host_eval.full_domain_evaluate_host(
        dpf, keys, hierarchy_level, key_chunk=key_chunk
    )
    return host_eval.values_to_limbs(raw, bits)


def _host_evaluate_at_limbs(dpf, keys, points, hierarchy_level):
    from ..core import host_eval

    v = dpf.validator
    if hierarchy_level < 0:
        hierarchy_level = v.num_hierarchy_levels - 1
    bits, _ = _scalar_bits(dpf, hierarchy_level)
    raw = host_eval.evaluate_at_host(dpf, keys, points, hierarchy_level)
    return host_eval.values_to_limbs(raw, bits)


def _scalar_bits(dpf, hierarchy_level):
    from . import evaluator

    value_type = dpf.validator.parameters[hierarchy_level].value_type
    return evaluator._value_kind(value_type)


def _run_chain(
    op_name: str,
    policy: DegradationPolicy,
    attempt_fn,
    chain: Optional[Sequence] = None,
):
    """Walks the fallback chain for one logical operation.

    `attempt_fn(mode, backend, key_chunk)` performs the operation at one
    rung (sentinel- or spot-verified for device rungs) and returns the
    result; this driver owns retry / backoff / chunk-halving /
    degradation, the structured events, and the dispatch-deadline scope
    (``policy.deadline_seconds`` arms ops/supervisor.py's watchdog for
    every wait inside the attempt). Raises the last error when even the
    host engine fails.

    `chain` is a sequence of (mode, backend) rungs (bare backend strings
    are promoted to mode=None rungs); None = the flat platform chain —
    ops/supervisor.py composes the per-op mode-aware chains
    (megakernel→fold→jax→numpy, walkkernel→walk→jax→numpy,
    hierkernel→fused→jax→numpy).
    """
    from . import supervisor as _sv  # function-level: supervisor imports us

    rungs: Tuple[Rung, ...] = tuple(
        (None, r) if isinstance(r, str) else (r[0], r[1])
        for r in (fallback_chain() if chain is None else chain)
    )
    last_err: Optional[BaseException] = None
    degraded = False

    def _degrade_edge(level_idx, rung, err, reason=None):
        mode, backend = rung
        nxt = rungs[level_idx + 1]
        detail = (
            f"{op_name}: {rung_label(rung)!r} -> {rung_label(nxt)!r} "
            f"after {reason or type(err).__name__}"
        )
        if isinstance(err, DataCorruptionError) and err.pattern:
            detail += f" ({err.pattern})"
        integrity.emit_event(
            "degrade", detail, backend, op=op_name,
            error=type(err).__name__,
            **({"mode": mode} if mode else {}),
        )
        # Degradation IS an engine decision (ISSUE 6): record the rung
        # transition with a structured reason next to the explicit/
        # env-default resolutions.
        _tm.decision(
            op_name,
            rung_label(nxt),
            "degrade",
            reason=reason or type(err).__name__,
            from_backend=rung_label(rung),
        )

    with _sv.deadline_scope(policy.deadline_seconds):
        for level_idx, rung in enumerate(rungs):
            mode, backend = rung
            chunk = None  # resolved lazily by attempt_fn's default
            retries = 0
            while True:
                try:
                    faultinject.maybe_raise(
                        "device_call", backend=backend, mode=mode
                    )
                    result = attempt_fn(mode, backend, chunk)
                    if degraded:
                        integrity.emit_event(
                            "recovered",
                            f"{op_name} served by fallback rung "
                            f"{rung_label(rung)!r}",
                            backend,
                            op=op_name,
                        )
                        _tm.counter("degrade.recovered", op=op_name)
                    return result
                except RungUnsupported as exc:
                    # This rung cannot express the call at all: skip it
                    # without retries — the shipped shape one rung down
                    # can (the resolver-downgrade contract, made explicit
                    # for chains that pin kernel modes).
                    err = exc.cause or InvalidArgumentError(exc.reason)
                    last_err = err
                    if level_idx + 1 < len(rungs):
                        _degrade_edge(level_idx, rung, err, reason="unsupported")
                        degraded = True
                    break
                except Exception as exc:  # noqa: BLE001 — classified below
                    err = classify_exception(exc)
                    if err is None:
                        raise
                    if isinstance(err, ResourceExhaustedError):
                        new_chunk = _halve(chunk, policy, attempt_fn)
                        if new_chunk is not None:
                            integrity.emit_event(
                                "chunk-halved",
                                f"{op_name} on {rung_label(rung)!r}: resource "
                                f"exhausted, key chunk -> {new_chunk}",
                                backend,
                                op=op_name,
                                key_chunk=new_chunk,
                            )
                            _tm.counter("degrade.chunk_halvings", op=op_name)
                            chunk = new_chunk
                            continue
                    elif isinstance(err, UnavailableError):
                        if retries < policy.max_retries:
                            retries += 1
                            delay = policy.backoff_seconds * (2 ** (retries - 1))
                            integrity.emit_event(
                                "retry",
                                f"{op_name} on {rung_label(rung)!r} "
                                f"unavailable; retry "
                                f"{retries}/{policy.max_retries} after "
                                f"{delay:.3f}s",
                                backend,
                                op=op_name,
                                retry=retries,
                            )
                            _tm.counter("degrade.retries", op=op_name)
                            if delay > 0:
                                time.sleep(delay)
                            continue
                    # DataCorruptionError (and exhausted retries / chunk
                    # floor): degrade to the next rung.
                    last_err = err
                    if level_idx + 1 < len(rungs):
                        _degrade_edge(level_idx, rung, err)
                        degraded = True
                    break
    assert last_err is not None
    raise last_err


def _halve(chunk, policy: DegradationPolicy, attempt_fn) -> Optional[int]:
    """Next smaller chunk, or None when the floor is reached. `chunk` is
    None before the first failure; the operation's own default is exposed
    by attempt_fn.default_chunk."""
    current = chunk if chunk is not None else attempt_fn.default_chunk
    if current <= policy.min_key_chunk:
        return None
    return max(policy.min_key_chunk, current // 2)


def full_domain_evaluate_robust(
    dpf,
    keys: Sequence,
    hierarchy_level: int = -1,
    key_chunk: int = 32,
    host_levels: Optional[int] = None,
    policy: DegradationPolicy = DEFAULT_POLICY,
    pipeline: Optional[bool] = None,
) -> np.ndarray:
    """`evaluator.full_domain_evaluate` behind the integrity + degradation
    stack: sentinel-verified on device levels, bit-correct via the host
    engine when every device level fails. Scalar Int/XorWrapper outputs
    (the host oracle's scope). Returns uint32[K, domain, lpe] limbs.

    `pipeline` (None = DPF_TPU_PIPELINE env / platform default) runs the
    device levels through the pipelined chunk executor. The chain is
    pipeline-aware by construction: a corrupted chunk detected at the
    pull/verify stage drains every in-flight finalize inside the executor
    (ops/pipeline.consume) *before* the DataCorruptionError reaches this
    chain, so the degraded rerun at the next level never races a
    background pull and chunks already delivered to the caller stay
    valid. The numpy level of last resort has no device queue and always
    runs serially."""
    from . import evaluator

    _scalar_bits(dpf, hierarchy_level)  # raises early for codec types

    def attempt(mode: Optional[str], backend: str, chunk: Optional[int]):
        del mode  # the full-domain values path has one execution shape
        ck = chunk if chunk is not None else key_chunk
        if backend == "numpy":
            # The host engine IS the oracle: nothing meaningful to verify
            # it against, and the fault harness deliberately has no hook
            # here — injected faults model device-side corruption.
            return _host_full_domain_limbs(dpf, keys, hierarchy_level, ck)
        return evaluator.full_domain_evaluate(
            dpf,
            keys,
            hierarchy_level,
            key_chunk=ck,
            host_levels=host_levels,
            use_pallas=(backend == "pallas"),
            integrity=True if policy.verify is None else policy.verify,
            pipeline=pipeline,
        )

    attempt.default_chunk = key_chunk
    return _run_chain("full_domain_evaluate", policy, attempt)


def evaluate_at_robust(
    dpf,
    keys: Sequence,
    points: Sequence[int],
    hierarchy_level: int = -1,
    policy: DegradationPolicy = DEFAULT_POLICY,
    pipeline: Optional[bool] = None,
    mode: Optional[str] = None,
) -> np.ndarray:
    """`evaluator.evaluate_at_batch` behind the integrity + degradation
    stack. Scalar outputs; returns uint32[K, P, lpe] limbs. `pipeline`:
    see `full_domain_evaluate_robust` — the executor drains in-flight work
    before any error reaches this chain.

    The chain is mode-aware (ISSUE 7): when the resolved walk strategy is
    "walkkernel" (explicit `mode` or the DPF_TPU_WALKKERNEL env), the
    first rung is the walk megakernel and a Mosaic-specific failure
    degrades to the still-device per-level walk before leaving the device
    — walkkernel → walk/pallas → walk/jax → numpy."""
    from . import evaluator, supervisor

    _scalar_bits(dpf, hierarchy_level)
    chain = supervisor.walk_chain(
        dpf, hierarchy_level, mode, op="evaluate_at_batch"
    )

    def attempt(mode_r: Optional[str], backend: str, chunk: Optional[int]):
        if backend == "numpy":
            return _host_evaluate_at_limbs(dpf, keys, points, hierarchy_level)
        # evaluate_at_batch has no default chunking of its own (the K x P
        # program is one dispatch), so resource-exhaustion halving slices
        # the key batch here; each slice carries its own sentinel probe.
        ck = chunk if chunk is not None else len(keys)
        outs = [
            evaluator.evaluate_at_batch(
                dpf,
                keys[i : i + ck],
                points,
                hierarchy_level,
                use_pallas=(backend == "pallas"),
                integrity=True if policy.verify is None else policy.verify,
                pipeline=pipeline,
                mode=mode_r,
            )
            for i in range(0, len(keys), ck)
        ]
        return outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)

    attempt.default_chunk = len(keys) if keys else 1
    return _run_chain("evaluate_at_batch", policy, attempt, chain=chain)
