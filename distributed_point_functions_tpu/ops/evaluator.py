"""Batched device-native DPF evaluators — the performance path.

Where core/dpf.py walks the reference's control flow one host value at a
time (general value types, hierarchies, contexts), this module implements the
two benchmark-defining bulk operations as single fused jit programs that
never leave the device:

* ``full_domain_evaluate``  — EvaluateUntil's expansion
  (/root/reference/dpf/distributed_point_function.cc:271-349,500-524 +
  value correction at .h:744-836) for a whole *batch of keys*: host
  pre-expansion to one packed word, then unrolled doubling levels in
  bit-plane space, value hash, and u32-limb value correction, vmapped over
  the key axis. Output ordering is restored by one gather computed by
  simulating the lane layout (see ``_expansion_order``).
* ``evaluate_at_batch``     — EvaluateAt
  (/root/reference/dpf/distributed_point_function.h:839-1010) for
  keys x points: one ``lax.scan`` tree walk over all levels with per-lane
  key selection, vmapped over keys, sharing one set of evaluation points.

Value correction handles every value type on device: power-of-two integer
widths 8..128 (additive and XOR groups) on the scalar fast path, and
IntModN / Tuple outputs through the spec-driven codec (ops/value_codec.py):
mod-N reduction of the hash block in u32 limbs, struct-of-arrays tuples,
and the sequential sampling chain for tuples containing IntModN. Tuple
outputs are returned as a tuple of per-component limb arrays.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import backend_numpy, uint128
from ..core.dpf import DistributedPointFunction
from ..core.keys import DpfKey
from ..core.value_types import Int, XorWrapper
from ..utils import faultinject
from ..utils import telemetry as _tm
from ..utils.envflags import (
    env_bool as _env_bool,
    env_int as _env_int,
    env_opt_bool as _env_opt_bool,
)
from ..utils.errors import InvalidArgumentError
from . import aes_jax, backend_jax, value_codec
from . import pipeline as _pl

# ---------------------------------------------------------------------------
# Host-side key batch preparation
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class KeyBatch:
    """Correction-word arrays for K same-parameter keys of one party."""

    seeds: np.ndarray  # uint32[K, 4]
    party: int
    cw_seeds: np.ndarray  # uint32[K, L, 4]
    cw_left: np.ndarray  # bool[K, L]
    cw_right: np.ndarray  # bool[K, L]
    value_corrections: np.ndarray  # uint32[K, epb, 4] (zeros for tuple types)
    num_levels: int
    # Spec-driven codec form: per component c, uint32[K, epb, lpe_c].
    spec: Optional[value_codec.ValueSpec] = None
    codec_corrections: Optional[Tuple[np.ndarray, ...]] = None

    @classmethod
    def from_keys(
        cls, dpf: DistributedPointFunction, keys: Sequence[DpfKey], hierarchy_level: int = -1
    ) -> "KeyBatch":
        v = dpf.validator
        if hierarchy_level < 0:
            hierarchy_level = v.num_hierarchy_levels - 1
        stop_level = v.hierarchy_to_tree[hierarchy_level]
        k = len(keys)
        party = keys[0].party
        seeds = np.zeros((k, 4), dtype=np.uint32)
        cw_seeds = np.zeros((k, stop_level, 4), dtype=np.uint32)
        cw_left = np.zeros((k, stop_level), dtype=bool)
        cw_right = np.zeros((k, stop_level), dtype=bool)
        value_type = v.parameters[hierarchy_level].value_type
        epb = value_type.elements_per_block()
        spec = value_codec.build_spec(value_type, v.blocks_needed[hierarchy_level])
        vc = np.zeros((k, epb, 4), dtype=np.uint32)
        codec_vc = tuple(
            np.zeros((k, spec.epb, comp.lpe), dtype=np.uint32)
            for comp in spec.components
        )
        for i, key in enumerate(keys):
            if key.party != party:
                raise InvalidArgumentError(
                    "all keys in a batch must belong to one party"
                )
            v.validate_key(key)
            seeds[i] = uint128.to_limbs(key.seed)
            for l in range(stop_level):
                cw = key.correction_words[l]
                cw_seeds[i, l] = uint128.to_limbs(cw.seed)
                cw_left[i, l] = cw.control_left
                cw_right[i, l] = cw.control_right
            if hierarchy_level == v.num_hierarchy_levels - 1:
                corrections = key.last_level_value_correction
            else:
                corrections = key.correction_words[stop_level].value_correction
            per_comp = value_codec.correction_limbs(spec, corrections)
            for c, arr in enumerate(per_comp):
                codec_vc[c][i] = arr
            if not spec.is_tuple:
                for j, cval in enumerate(corrections):
                    vc[i, j] = uint128.to_limbs(int(cval))
        return cls(
            seeds=seeds,
            party=party,
            cw_seeds=cw_seeds,
            cw_left=cw_left,
            cw_right=cw_right,
            value_corrections=vc,
            num_levels=stop_level,
            spec=spec,
            codec_corrections=codec_vc,
        )

    def take(self, idx: np.ndarray) -> "KeyBatch":
        """Row-selects every per-key array (padding/chunking helper)."""
        return KeyBatch(
            seeds=self.seeds[idx],
            party=self.party,
            cw_seeds=self.cw_seeds[idx],
            cw_left=self.cw_left[idx],
            cw_right=self.cw_right[idx],
            value_corrections=self.value_corrections[idx],
            num_levels=self.num_levels,
            spec=self.spec,
            codec_corrections=(
                None
                if self.codec_corrections is None
                else tuple(a[idx] for a in self.codec_corrections)
            ),
        )

    def device_cw_arrays(self, from_level: int = 0):
        """(cw_planes uint32[K,L,128], ccl uint32[K,L], ccr uint32[K,L]) for
        tree levels >= from_level, vectorized over the key axis."""
        k = self.seeds.shape[0]
        if self.num_levels <= from_level:
            z = np.zeros((k, 0), np.uint32)
            return np.zeros((k, 0, 128), np.uint32), z, z
        return (
            backend_jax.cw_seed_planes(self.cw_seeds[:, from_level:]),
            backend_jax.control_masks(self.cw_left[:, from_level:]),
            backend_jax.control_masks(self.cw_right[:, from_level:]),
        )


# ---------------------------------------------------------------------------
# Value extraction / correction in u32 limbs (device)
# ---------------------------------------------------------------------------


def _split_elements(limbs: jnp.ndarray, bits: int) -> jnp.ndarray:
    """uint32[..., 4] 128-bit blocks -> uint32[..., epb, limbs_per_element].

    Element j of a block occupies bits [j*bits, (j+1)*bits) of the
    little-endian uint128, mirroring ConvertBytesToArrayOf
    (/root/reference/dpf/internal/value_type_helpers.h:506-520).
    """
    if bits >= 32:
        lpe = bits // 32
        return limbs.reshape(limbs.shape[:-1] + (128 // bits, lpe))
    per_limb = 32 // bits
    mask = jnp.uint32((1 << bits) - 1)
    shifts = jnp.arange(per_limb, dtype=jnp.uint32) * jnp.uint32(bits)
    vals = (limbs[..., :, None] >> shifts) & mask  # [..., 4, per_limb]
    return vals.reshape(limbs.shape[:-1] + (128 // bits, 1))


def _limb_add(a: jnp.ndarray, b: jnp.ndarray, bits: int) -> jnp.ndarray:
    """Element-wise addition mod 2^bits on uint32[..., lpe] limb arrays."""
    if bits <= 32:
        mask = jnp.uint32((1 << bits) - 1) if bits < 32 else jnp.uint32(0xFFFFFFFF)
        return (a + b) & mask
    out = []
    carry = jnp.zeros_like(a[..., 0])
    for l in range(bits // 32):
        t = a[..., l] + b[..., l]
        c1 = (t < a[..., l]).astype(jnp.uint32)
        s = t + carry
        c2 = (s < t).astype(jnp.uint32)
        carry = c1 | c2
        out.append(s)
    return jnp.stack(out, axis=-1)


def _limb_neg(a: jnp.ndarray, bits: int) -> jnp.ndarray:
    """Two's-complement negation mod 2^bits on uint32[..., lpe] limbs."""
    if bits <= 32:
        mask = jnp.uint32((1 << bits) - 1) if bits < 32 else jnp.uint32(0xFFFFFFFF)
        return (jnp.uint32(0) - a) & mask
    out = []
    carry = jnp.uint32(1)  # ~a + 1
    for l in range(bits // 32):
        s = (~a[..., l]) + carry
        carry = jnp.where((s == 0) & (carry == 1), jnp.uint32(1), jnp.uint32(0))
        out.append(s)
    return jnp.stack(out, axis=-1)


def _correct_values(
    hashed: jnp.ndarray,  # uint32[..., 4] value-hash blocks
    control: jnp.ndarray,  # bool/uint32[...] control bits (1 = corrected)
    corrections: jnp.ndarray,  # uint32[epb, lpe] per-element correction limbs
    bits: int,
    party: int,
    xor_group: bool,
) -> jnp.ndarray:
    """value = hash_element (+ correction if control) (negated if party 1).

    Mirrors the correction loop in EvaluateUntil
    (/root/reference/dpf/distributed_point_function.h:776-808).
    Returns uint32[..., epb, lpe].
    """
    elems = _split_elements(hashed, bits)  # [..., epb, lpe]
    ctrl = control.astype(jnp.uint32)[..., None, None]
    if xor_group:
        return elems ^ (corrections * ctrl)
    corr = corrections * ctrl  # zero where control unset
    out = _limb_add(elems, corr, bits)
    if party == 1:
        out = _limb_neg(out, bits)
    return out


# ---------------------------------------------------------------------------
# Lane-order bookkeeping for the doubling expansion
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# Host pre-expansion (vectorized numpy, per-key correction words)
# ---------------------------------------------------------------------------


def _host_expand(
    seeds: np.ndarray,  # uint32[K, 4]
    control: np.ndarray,  # bool[K]
    batch: KeyBatch,
    levels: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """Expands each key `levels` levels on the host -> ([K, 2^levels, 4],
    [K, 2^levels]) in leaf order, vectorized numpy over the native AES
    engine. Two consumers with very different envelopes: the device
    evaluators fill only the first packed word (5 levels) before the TPU
    takes over, while core/host_eval.py drives ALL levels through it for
    the CPU-only engine."""
    k = seeds.shape[0]
    seeds = seeds[:, None, :]  # [K, M, 4]
    control = control[:, None]
    for level in range(levels):
        m = seeds.shape[1]
        flat = seeds.reshape(k * m, 4)
        left = backend_numpy._PRG_LEFT.evaluate_limbs(flat).reshape(k, m, 4)
        right = backend_numpy._PRG_RIGHT.evaluate_limbs(flat).reshape(k, m, 4)
        corr = np.where(
            control[:, :, None], batch.cw_seeds[:, level][:, None, :], 0
        ).astype(np.uint32)
        left ^= corr
        right ^= corr
        # interleave children in leaf order
        children = np.stack([left, right], axis=2).reshape(k, 2 * m, 4)
        child_control = (children[:, :, 0] & 1).astype(bool)
        children[:, :, 0] &= np.uint32(0xFFFFFFFE)
        cc = np.stack(
            [
                control & batch.cw_left[:, level][:, None],
                control & batch.cw_right[:, level][:, None],
            ],
            axis=2,
        ).reshape(k, 2 * m)
        control = child_control ^ cc
        seeds = children
    return seeds, control


# ---------------------------------------------------------------------------
# Fused device programs
# ---------------------------------------------------------------------------


@jax.jit
def _pack_batch_jit(seeds, control_mask):
    """uint32[K, M, 4] seeds -> uint32[K, 128, M//32] planes (+ control)."""
    return jax.vmap(aes_jax.pack_to_planes)(seeds), control_mask


@jax.jit
def _expand_level_batch_jit(planes, control, cw_plane, ccl, ccr):
    """One doubling level over the whole key batch; one traced AES circuit.

    Dispatched per level from the host (arrays stay on device) so each XLA
    program stays small — compile time scales with the number of *distinct
    widths*, not with a single giant unrolled program.
    """
    return jax.vmap(backend_jax.expand_one_level)(planes, control, cw_plane, ccl, ccr)


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _expand_level_batch_donated_jit(planes, control, cw_plane, ccl, ccr):
    """`_expand_level_batch_jit` with the plane/control carry DONATED: the
    parent planes are dead the moment the children exist, and at serving
    widths they are the 100+ MB buffer whose per-level reallocation walks
    HBM toward the RESOURCE_EXHAUSTED cliff (PERF.md). Selected by
    `_expand_level_batch` on backends that implement donation."""
    return jax.vmap(backend_jax.expand_one_level)(planes, control, cw_plane, ccl, ccr)


def _expand_level_batch(planes, control, cw_plane, ccl, ccr):
    """One doubling level, donating the carried plane state where the
    backend supports it (DPF_TPU_DONATE / TPU default — XLA:CPU ignores
    donation and would warn per program). Every caller rebinds planes and
    control to the result, so donation never aliases a live buffer."""
    if _pl.donate_default():
        return _expand_level_batch_donated_jit(planes, control, cw_plane, ccl, ccr)
    return _expand_level_batch_jit(planes, control, cw_plane, ccl, ccr)


@jax.jit
def _split_levels_jit(cw_all, ccl_all, ccr_all):
    """Splits the stacked per-level corrections into per-level arrays in
    ONE program. Eagerly slicing `cw_all[:, level]` in the per-level loop
    dispatched 3 extra device programs per level — pure latency through a
    66 ms-dispatch link (r4 dispatch audit) — while slicing inside the
    expand program itself would widen its jit cache key from (planes
    width) to (planes width, total levels). This keeps both properties:
    one dispatch, and the expand programs stay keyed by width alone."""
    L = cw_all.shape[1]
    return (
        tuple(cw_all[:, l] for l in range(L)),
        tuple(ccl_all[:, l] for l in range(L)),
        tuple(ccr_all[:, l] for l in range(L)),
    )


@functools.partial(
    jax.jit,
    static_argnames=("bits", "party", "xor_group", "keep_per_block", "reorder"),
)
def _finalize_batch_jit(
    planes, control, corrections, order, bits, party, xor_group, keep_per_block,
    reorder=True,
):
    """Value hash + unpack + correction + leaf-order restore for a key batch.

    `keep_per_block` slices each block to corrected_elements_per_block
    (1 << (log_domain_size - tree_level)) before flattening, mirroring
    /root/reference/dpf/distributed_point_function.h:786-808 — blocks carry
    elements_per_block values but only the first 2^(lds - level) are
    addressable when an earlier hierarchy level forces the tree deeper.

    `reorder=False` skips the leaf-order gather and returns values in lane
    (expansion) order — for consumers that pre-permute their data into lane
    order once (e.g. a PIR database) instead of paying a full-size gather
    per evaluation.
    """
    hashed = jax.vmap(backend_jax.hash_value_planes)(planes)
    blocks = jax.vmap(aes_jax.unpack_from_planes)(hashed)
    ctrl = jax.vmap(backend_jax.unpack_mask_device)(control)
    fn = functools.partial(
        _correct_values, bits=bits, party=party, xor_group=xor_group
    )
    values = jax.vmap(fn)(blocks, ctrl, corrections)  # [K, lanes, epb, lpe]
    if reorder:
        values = values[:, order]  # leaf order
    values = values[:, :, :keep_per_block]
    k, n_blocks, kept, lpe = values.shape
    return values.reshape(k, n_blocks * kept, lpe)


@functools.partial(
    jax.jit, static_argnames=("spec", "party", "keep_per_block", "reorder")
)
def _finalize_batch_codec_jit(
    planes, control, corrections, order, spec, party, keep_per_block,
    reorder=True,
):
    """Spec-driven finalize for IntModN / Tuple outputs (see _finalize_batch_jit
    for the scalar fast path). Returns a tuple of per-component limb arrays
    uint32[K, n_blocks * keep_per_block, lpe_c]. reorder=False keeps lane
    (expansion) order, as in _finalize_batch_jit."""

    def one(p, c, corrs):
        stream = backend_jax.hash_value_stream(p, spec.blocks_needed)
        ctrl = backend_jax.unpack_mask_device(c)
        return value_codec.correct_values(stream, ctrl, corrs, spec, party)

    vals = jax.vmap(one)(planes, control, corrections)
    outs = []
    for v in vals:  # [K, lanes, epb, lpe_c]
        k, n_blocks, epb, lpe = v.shape
        if epb == 1:
            # IntModN / sampled components (epb == 1, so keep_per_block is
            # 1 and the epb slice is a no-op): fold lpe into the lane
            # dimension IMMEDIATELY, so the gather temporary is
            # [K, lanes*lpe] (one large trailing dim) instead of
            # [K, lanes, 1, lpe] — whose (1, lpe) trailing dims pad ~2.5x
            # against the 8x128 tiles (PERF.md IntModN-finalize open item;
            # pinned by tests via value_codec.tile_padded_bytes). The
            # element-limb order interleaves each lane's limbs in place,
            # so the final reshape back to [K, lanes, lpe] is a view.
            vf = v.reshape(k, n_blocks * lpe)
            if reorder:
                # The gather may SELECT a subset (padded parents emit
                # garbage lanes the leaf order skips), so the lane count
                # after it is len(order), not n_blocks.
                o2 = (order[:, None] * lpe + jnp.arange(lpe)).reshape(-1)
                vf = vf[:, o2]
            outs.append(vf.reshape(k, -1, lpe))
            continue
        if reorder:
            v = v[:, order]
        v = v[:, :, :keep_per_block]
        k, n_blocks, kept, lpe = v.shape
        outs.append(v.reshape(k, n_blocks * kept, lpe))
    return tuple(outs)


def _expand_level(planes, control, cw, ccl, ccr, use_pallas: bool):
    """One doubling level, on the Mosaic row kernel when enabled and the
    width fills at least one (8, 128) vreg tile region (256 lane words);
    narrow early levels and non-TPU platforms use the XLA bitslice."""
    if use_pallas and planes.shape[2] >= 256:
        from . import aes_pallas

        return aes_pallas.expand_one_level_pallas_batched(
            planes, control, cw, ccl, ccr
        )
    return _expand_level_batch_jit(planes, control, cw, ccl, ccr)


@functools.partial(
    jax.jit,
    static_argnames=(
        "levels", "bits", "party", "xor_group", "keep_per_block", "reorder",
        "spec", "use_pallas",
    ),
)
def _fused_chunk_jit(
    seeds,  # uint32[K, M, 4]
    control_mask,  # uint32[K, M//32]
    cw_planes,  # uint32[K, L, 128]
    ccl,  # uint32[K, L]
    ccr,  # uint32[K, L]
    corrections,  # uint32[K, epb, lpe], or a tuple of per-component arrays
    order,  # int[M << levels] leaf-order gather
    levels: int,
    party: int,
    keep_per_block: int,
    reorder: bool = True,
    bits: int = 0,  # scalar fast path (spec=None)
    xor_group: bool = False,
    spec=None,  # codec path (IntModN / Tuple) when set
    use_pallas: bool = False,
):
    """ONE program per chunk: pack -> all doubling levels -> value hash ->
    correction (-> optional leaf-order restore). The fewest-dispatches shape:
    through a high-dispatch-latency device link (~66 ms/dispatch measured on
    this image's tunnel, PERF.md) per-level dispatch costs more than the
    whole chunk's arithmetic."""
    planes, control = _pack_batch_jit(seeds, control_mask)
    for level in range(levels):
        planes, control = _expand_level(
            planes, control, cw_planes[:, level], ccl[:, level], ccr[:, level],
            use_pallas,
        )
    if spec is None:
        return _finalize_batch_jit(
            planes, control, corrections, order,
            bits=bits, party=party, xor_group=xor_group,
            keep_per_block=keep_per_block, reorder=reorder,
        )
    return _finalize_batch_codec_jit(
        planes, control, corrections, order,
        spec=spec, party=party, keep_per_block=keep_per_block, reorder=reorder,
    )


@functools.lru_cache(maxsize=8)  # each entry pins ~MBs on device — keep few
def _order_on_device(m_order: int, lanes: int, levels: int):
    """DEVICE-resident leaf-order gather for one (host lanes, padded
    lanes, device levels) shape: the index array is ~MBs at serving
    sizes, and re-uploading it per call would put the host link
    (megabytes/s through this image's tunnel) on the hot path — notably
    on PreparedKeyBatch replays, whose whole point is upload-once.
    (expansion_output_order itself is lru_cached host-side.)"""
    return jnp.asarray(
        backend_jax.expansion_output_order(m_order, lanes, levels)
    )


@functools.lru_cache(maxsize=2)  # O(L * 2^L) bytes per entry — keep few
def _walk_path_masks(num_levels: int) -> np.ndarray:
    """Packed per-level path masks for a full-domain walk: lane i follows the
    root-to-leaf path of leaf i (level l reads bit num_levels-1-l of i).

    Built word-wise without a [L, 2^L] bool intermediate: for leaf-bit
    positions >= 5 all 32 lanes of a word agree (word = 0 / ~0 by the word
    index bit), below 5 every word carries one constant 32-lane pattern.
    Returns uint32[num_levels, max(32, 2^num_levels) // 32].
    """
    lanes = max(32, 1 << num_levels)
    n_words = lanes // 32
    masks = np.empty((num_levels, n_words), np.uint32)
    widx = np.arange(n_words, dtype=np.uint64)
    for l in range(num_levels):
        b = num_levels - 1 - l
        if b >= 5:
            masks[l] = np.where(
                (widx >> np.uint64(b - 5)) & np.uint64(1), _FULL32, 0
            ).astype(np.uint32)
        else:
            masks[l] = np.uint32(
                sum(1 << i for i in range(32) if (i >> b) & 1)
            )
    return masks


_FULL32 = np.uint32(0xFFFFFFFF)


def _walk_one_key(seed, path_masks, control0, cw, l, r):
    """Shared walk preamble of the walk-mode kernels: replicated-seed planes
    (plane b = bit b of the seed broadcast over every lane word — no pack
    shuffle needed) walked down every leaf path at once. Returns
    (planes uint32[128, W], control uint32[W])."""
    w = path_masks.shape[1]
    seed_bits = (
        (seed[:, None] >> jnp.arange(32, dtype=jnp.uint32)) & jnp.uint32(1)
    ).reshape(128)
    planes = jnp.broadcast_to(
        (seed_bits * jnp.uint32(0xFFFFFFFF))[:, None], (128, w)
    )
    return backend_jax.evaluate_seeds_planes(
        planes, control0, path_masks, cw, l, r
    )


@functools.partial(
    jax.jit,
    static_argnames=("bits", "party", "xor_group", "keep"),
)
def _walk_chunk_jit(
    seeds,  # uint32[K, 4] root seeds
    path_masks,  # uint32[L, W] shared across keys
    cw_planes,  # uint32[K, L, 128]
    ccl,  # uint32[K, L]
    ccr,  # uint32[K, L]
    corrections,  # uint32[K, epb, lpe]
    bits: int,
    party: int,
    xor_group: bool,
    keep: int,
):
    """Full-domain evaluation, ONE program per key chunk: every leaf lane
    walks its own root-to-leaf path via the `evaluate_seeds_planes` scan.

    ~num_levels/2 x the AES work of the doubling expansion, but a single
    dispatch with a near-constant trace size, and lane i IS leaf i — no
    leaf-order gather exists at all. Returns uint32[K, lanes * keep, lpe]
    in leaf order (trim to the domain on the caller side)."""
    control0 = jnp.full(path_masks.shape[1], _FULL32 if party else 0, jnp.uint32)

    def one(seed, cw, l, r, corr):
        planes, control = _walk_one_key(seed, path_masks, control0, cw, l, r)
        hashed = backend_jax.hash_value_planes(planes)
        blocks = aes_jax.unpack_from_planes(hashed)
        ctrl = backend_jax.unpack_mask_device(control)
        vals = _correct_values(
            blocks, ctrl, corr, bits, party, xor_group
        )  # [lanes, epb, lpe]
        lanes, _epb, lpe = vals.shape
        return vals[:, :keep].reshape(lanes * keep, lpe)

    return jax.vmap(one)(seeds, cw_planes, ccl, ccr, corrections)


@functools.partial(
    jax.jit,
    static_argnames=(
        "levels", "bits", "party", "xor_group", "keep", "use_pallas",
        "fuse_last_hash",
    ),
)
def _fused_fold_chunk_jit(
    seeds,  # uint32[K, M, 4]
    control_mask,  # uint32[K, M//32]
    cw_planes,  # uint32[K, L, 128]
    ccl,  # uint32[K, L]
    ccr,  # uint32[K, L]
    corrections,  # uint32[K, epb, lpe]
    db,  # uint32[lanes * keep, lpe] FLAT lane-order database, or None
    levels: int,
    bits: int,
    party: int,
    xor_group: bool,
    keep: int,
    use_pallas: bool = False,
    fuse_last_hash: bool = False,
):
    """Fused expansion with an IN-PROGRAM consumer: every value is
    materialized in HBM (optimization_barrier below forces the buffer) and
    XOR-folded — against a lane-order database when `db` is given (the PIR
    inner product), plain otherwise — so the program's OUTPUT is a tiny
    [K, lpe]. On this image's tunnel, programs whose *output* exceeds
    ~117 MB miscompute while multi-GB *internal* buffers compute correctly
    (PERF.md 2026-07-31 fold-in-program finding), making this the shape
    that both verifies and scales: 63.8 M evals/s host-verified at 128-key
    chunks (vs 58.2 M for the out-of-program fold at its 14-key output
    cap) with no output-size limit at any domain."""
    planes, control = _pack_batch_jit(seeds, control_mask)
    # Same width gate as the separate-hash path: the Mosaic kernels want
    # >= 256 lane words (the fused kernel's input width is the LAST
    # level's input, i.e. half the output width the hash gate sees).
    fuse_last = (
        fuse_last_hash
        and use_pallas
        and levels >= 1
        and (planes.shape[2] << (levels - 1)) >= 128
    )
    expand_levels = levels - 1 if fuse_last else levels
    for level in range(expand_levels):
        planes, control = _expand_level(
            planes, control, cw_planes[:, level], ccl[:, level], ccr[:, level],
            use_pallas,
        )
    if fuse_last:
        # Final level + value hash in ONE Mosaic kernel: the last level's
        # child planes (half of all lanes) never round-trip through HBM
        # (opt-in via DPF_TPU_FUSE_LAST_HASH; fold mode discards the
        # expansion state, so only hashed planes + control are needed).
        from . import aes_pallas

        hashed, control = aes_pallas.expand_and_hash_last_level_pallas_batched(
            planes, control,
            cw_planes[:, levels - 1], ccl[:, levels - 1], ccr[:, levels - 1],
        )
    elif use_pallas and planes.shape[2] >= 256:
        from . import aes_pallas

        hashed = aes_pallas.hash_value_planes_pallas_batched(planes)
    else:
        hashed = jax.vmap(backend_jax.hash_value_planes)(planes)
    blocks = jax.vmap(aes_jax.unpack_from_planes)(hashed)
    ctrl = jax.vmap(backend_jax.unpack_mask_device)(control)
    fn = functools.partial(
        _correct_values, bits=bits, party=party, xor_group=xor_group
    )
    values = jax.vmap(fn)(blocks, ctrl, corrections)  # [K, lanes, epb, lpe]
    values = values[:, :, :keep]
    # The consumer reads a real HBM buffer, not a fused-away expression:
    # the measured semantics stay "materialize every output + consume".
    values = jax.lax.optimization_barrier(values)
    values = values.reshape(values.shape[0], -1, values.shape[-1])
    if db is not None:
        # db is the flat lane-order database [lanes * keep, lpe]
        # (prepare_pir_database order="lane"): padded positions hold zeros,
        # so garbage lanes cannot contribute to the inner product.
        values = values & db[None, :, :]
    return jnp.bitwise_xor.reduce(values, axis=1)


def full_domain_fold_chunks(
    dpf: DistributedPointFunction,
    keys,
    hierarchy_level: int = -1,
    key_chunk: Optional[int] = None,  # None = 128 (prepared: its own)
    host_levels: Optional[int] = None,
    db_lane=None,
    use_pallas: Optional[bool] = None,
    pipeline: Optional[bool] = None,
    mode: Optional[str] = None,  # None = DPF_TPU_MEGAKERNEL env -> "fold"
):
    """Full-domain evaluation with the consumer fused INTO each program.

    Yields (num_valid_keys, fold) where fold is uint32[key_chunk, lpe]: the
    XOR fold of every (lane-order) domain value of each key — AND-masked
    against `db_lane` first when given (the two-server-PIR inner product).
    One dispatch per key chunk, output bytes ~nothing: both the fastest
    shape through a high-dispatch-latency link and the only one whose
    per-program output stays small at any domain/chunk size (PERF.md
    "fold-in-program"). Values never leave the device; use
    `full_domain_evaluate_chunks` when the caller needs them.

    mode selects the execution strategy (None = "megakernel" when the
    DPF_TPU_MEGAKERNEL env is truthy, else "fold" — the A/B knob):

    * "fold" — the shipped shape: per-level doubling expansion (Mosaic row
      kernels / XLA bitslice per `use_pallas`), values materialized in HBM
      behind an optimization_barrier and folded in-program. `db_lane` is
      the FLAT uint32[positions, lpe] lane-order array from
      `prepare_pir_database(order="lane").lane_db`.
    * "megakernel" — the slab megakernel (aes_pallas.
      megakernel_fold_pallas_batched): ONE pallas_call expands every
      device level inside VMEM slabs, applies the value hash + correction
      in-kernel and accumulates the fold/inner product directly — no
      per-level HBM round trips and no value buffer at all; the program
      output is exactly [key_chunk, lpe]. `db_lane` is then the streaming
      row layout from `prepare_pir_database(order="megakernel")` /
      `megakernel_db_rows`, and it MUST be built under the same
      MegakernelPlan this call resolves (same host_levels and
      DPF_TPU_MEGAKERNEL_VMEM): the row permutation encodes the plan's
      slab geometry, and the shape check below cannot distinguish plans
      that agree on total width (e.g. differing only in host_levels) —
      `pir_query_batch_chunked` enforces plan equality on the prepared
      database and is the recommended PIR entry point. Requires a
      real-TPU or interpret-capable backend (the kernel runs interpreted
      off-TPU), scalar value widths that are 32-bit multiples, and at
      least one device level.

    `keys` may be a `PreparedKeyBatch` (packed + uploaded once; the
    prepared `key_chunk`/`host_levels` then apply — both modes consume the
    same prepared chunks). `pipeline` (None = DPF_TPU_PIPELINE env /
    platform default, see ops/pipeline.py) runs chunk N+1's host pack +
    upload + dispatch while the consumer still holds chunk N — the
    double-buffered executor behind the recorded "async chunk overlap"
    headline (PERF.md §Pallas).

    Scalar Int/XorWrapper value types only (the XOR fold of mod-N limb
    shares has no protocol meaning).
    """
    v = dpf.validator
    if hierarchy_level < 0:
        hierarchy_level = v.num_hierarchy_levels - 1
    backend_jax.log_backend_once()
    if mode is None:
        # The env-driven A/B default yields to an explicit use_pallas=False:
        # a caller qualifying the XLA engine (CHECK_PALLAS=0) must not
        # silently get the Mosaic megakernel — the mirror of the r3
        # explicit-use_pallas=True rule (same policy as _resolve_walk_mode).
        if use_pallas is False:
            mode, mode_source = "fold", "pinned-xla"
        else:
            mode, mode_source = _fold_mode_default(), "env-default"
    else:
        mode_source = "explicit"
    if mode not in ("fold", "megakernel"):
        raise InvalidArgumentError(
            f"mode must be 'fold' or 'megakernel', got {mode!r}"
        )
    _tm.decision("full_domain_fold_chunks", mode, mode_source)
    if use_pallas is None:
        use_pallas = _pallas_default()
    if mode == "megakernel":
        # The megakernel IS a Mosaic program regardless of the use_pallas
        # knob: keep the fault-injection scoping (_fi_backend ->
        # "pallas" for _inject_batch_faults and the executor hooks)
        # consistent with pir_query_batch_chunked's.
        use_pallas = True
    pipe = _pl.resolve(pipeline)

    prepared: Optional[PreparedKeyBatch] = None
    if isinstance(keys, PreparedKeyBatch):
        prepared = keys
        prepared._check_call(
            dpf, hierarchy_level, key_chunk, host_levels,
            "full_domain_fold_chunks",
        )
        if not prepared.scalar_fast:
            raise NotImplementedError(
                "full_domain_fold_chunks supports scalar Int/XorWrapper "
                "value types; evaluate IntModN/Tuple outputs via "
                "full_domain_evaluate_chunks"
            )
        if prepared.host_levels < 5:
            raise InvalidArgumentError(
                "full_domain_fold_chunks requires a PreparedKeyBatch with "
                "host_levels >= 5 (a tree of depth >= 5)"
            )
        bits, xor_group = prepared.bits, prepared.xor_group
        party = prepared.party
        keep = prepared.keep_per_block
        device_levels = prepared.device_levels
        chunks = prepared.chunks
    else:
        value_type = v.parameters[hierarchy_level].value_type
        batch = KeyBatch.from_keys(dpf, keys, hierarchy_level)
        spec = batch.spec
        if not (spec.is_scalar_direct and spec.blocks_needed == 1):
            raise NotImplementedError(
                "full_domain_fold_chunks supports scalar Int/XorWrapper value "
                "types; evaluate IntModN/Tuple outputs via "
                "full_domain_evaluate_chunks"
            )
        bits, xor_group = _value_kind(value_type)
        party = batch.party
        stop_level = batch.num_levels
        if stop_level < 5:
            # Below one packed word the expansion pads lanes whose garbage a
            # plain fold would absorb; domains this small have no use for the
            # bulk fold path anyway.
            raise NotImplementedError(
                "full_domain_fold_chunks requires a tree of depth >= 5; use "
                "full_domain_evaluate for small domains"
            )
        lds = v.parameters[hierarchy_level].log_domain_size
        keep = 1 << (lds - stop_level)
        num_keys = len(keys)
        if key_chunk is None:
            key_chunk = 128
        if host_levels is None:
            host_levels = 5
        elif host_levels < 5:
            # A silent clamp would desynchronize this generator from a
            # lane_order_map/PIR database the caller built at the smaller
            # host_levels (mismatched lane counts surface as opaque broadcast
            # errors inside the jit).
            raise InvalidArgumentError(
                f"full_domain_fold_chunks requires host_levels >= 5 (one full "
                f"packed word), got {host_levels}"
            )
        host_levels = min(host_levels, stop_level)
        device_levels = stop_level - host_levels
        _inject_batch_faults(batch, use_pallas)
        chunks = None  # prepared lazily, chunk by chunk, inside the thunks

    db_dev = None
    if db_lane is not None:
        db_dev = jnp.asarray(db_lane)

    fuse_last_hash = _env_bool("DPF_TPU_FUSE_LAST_HASH", default=False)

    mk_plan = None
    mk_interpret = False
    if mode == "megakernel":
        if bits % 32:
            raise NotImplementedError(
                f"megakernel value correction handles 32-bit-multiple "
                f"widths (Int/XorWrapper 32/64/128), got {bits}-bit values; "
                "use mode='fold'"
            )
        hl = prepared.host_levels if prepared is not None else host_levels
        mk_plan = plan_megakernel(dpf, hierarchy_level, host_levels=hl)
        # Off-TPU the Mosaic kernel runs through the Pallas interpreter —
        # bit-exact (tests/test_megakernel.py), minus the performance.
        mk_interpret = jax.default_backend() != "tpu"
        if db_dev is not None:
            expect = (
                keep * (bits // 32) * 32,
                mk_plan.num_slabs * mk_plan.final_words,
            )
            if tuple(db_dev.shape) != expect:
                raise InvalidArgumentError(
                    f"mode='megakernel' needs the streaming DB row layout "
                    f"{expect} (prepare_pir_database(order='megakernel') / "
                    f"megakernel_db_rows), got {tuple(db_dev.shape)}"
                )

    def _dispatch(ch: _PreparedChunk):
        if mk_plan is not None:
            return ch.valid, _megakernel_fold_chunk_jit(
                ch.seeds,
                ch.control_mask,
                ch.cw,
                ch.ccl,
                ch.ccr,
                ch.corr,
                db_dev,
                plan=mk_plan,
                bits=bits,
                party=party,
                xor_group=xor_group,
                keep=keep,
                interpret=mk_interpret,
            )
        return ch.valid, _fused_fold_chunk_jit(
            ch.seeds,
            ch.control_mask,
            ch.cw,
            ch.ccl,
            ch.ccr,
            ch.corr,
            db_dev,
            levels=device_levels,
            bits=bits,
            party=party,
            xor_group=xor_group,
            keep=keep,
            use_pallas=use_pallas,
            fuse_last_hash=fuse_last_hash,
        )

    def _thunks():
        if chunks is not None:  # PreparedKeyBatch: stage 1 already paid
            for ch in chunks:
                yield functools.partial(_dispatch, ch)
            return
        for kb, valid in _key_chunks(batch, num_keys, key_chunk):
            yield functools.partial(
                lambda kb, valid: _dispatch(
                    _prepare_chunk(kb, valid, host_levels, True, bits)
                ),
                kb,
                valid,
            )

    yield from _pl.prefetch_thunks(
        _thunks(), pipe, backend=_fi_backend(use_pallas),
        op="full_domain_fold_chunks",
    )


@functools.partial(jax.jit, static_argnames=("spec", "party", "keep"))
def _walk_chunk_codec_jit(
    seeds, path_masks, cw_planes, ccl, ccr, corrections, spec, party, keep,
):
    """Codec (IntModN / Tuple) variant of `_walk_chunk_jit`."""
    control0 = jnp.full(path_masks.shape[1], _FULL32 if party else 0, jnp.uint32)

    def one(seed, cw, l, r, corrs):
        planes, control = _walk_one_key(seed, path_masks, control0, cw, l, r)
        stream = backend_jax.hash_value_stream(planes, spec.blocks_needed)
        ctrl = backend_jax.unpack_mask_device(control)
        vals = value_codec.correct_values(stream, ctrl, corrs, spec, party)
        outs = []
        for v in vals:  # [lanes, epb, lpe_c]
            lanes, _epb, lpe = v.shape
            outs.append(v[:, :keep].reshape(lanes * keep, lpe))
        return tuple(outs)

    return jax.vmap(one)(seeds, cw_planes, ccl, ccr, corrections)


def _pallas_default() -> bool:
    """Resolves the Mosaic-kernel default: DPF_TPU_PALLAS when set
    (1/true/yes/on vs 0/false/no/off), else ON exactly for real TPU
    backends (PERF.md "Pallas vs XLA bitslice" — ~12x; CPU/interpret
    platforms keep the XLA path)."""
    env = _env_opt_bool("DPF_TPU_PALLAS")
    if env is not None:
        return env
    return jax.default_backend() == "tpu"


def _fi_backend(use_pallas: bool) -> str:
    """Fault-injection backend level of a device call (ops/degrade.py
    chain names): the Mosaic kernels are "pallas", XLA bitslice is "jax"."""
    return "pallas" if use_pallas else "jax"


def _inject_batch_faults(batch: KeyBatch, use_pallas: bool) -> None:
    """Applies armed seed/correction-word fault plans to the prepared
    device batch (utils/faultinject.py). No-op — one truthiness check —
    when no plan is armed. Deliberately NOT called by the host oracle
    (core/host_eval.py builds its own KeyBatch): injected faults model
    device-side corruption, so the oracle and the numpy fallback level
    always see clean data."""
    if not faultinject.is_active():
        return
    backend = _fi_backend(use_pallas)
    batch.seeds = faultinject.corrupt_seeds(batch.seeds, backend=backend)
    batch.cw_seeds = faultinject.corrupt_cw(batch.cw_seeds, backend=backend)


def _key_chunks(batch: KeyBatch, num_keys: int, key_chunk: int):
    """Yields (key_batch, num_valid_keys) in key_chunk-sized chunks, padding
    the last chunk with key 0 so every chunk compiles to one shape (no pad
    when the whole batch is smaller than key_chunk — smaller programs
    compile on their own). Padded rows are trimmed by the caller."""
    for idx, valid in _pl.chunk_indices(num_keys, key_chunk):
        yield batch.take(idx), valid


@dataclasses.dataclass
class _PreparedChunk:
    """One key chunk's device-resident evaluation inputs: host-expanded
    seeds, packed control mask, correction-word tables, and value
    corrections, uploaded once. The unit both the pipelined executor's
    launch stage and `PreparedKeyBatch` traffic in."""

    valid: int  # real (non-padded) keys in this chunk
    seeds: jnp.ndarray  # uint32[K, M, 4] host-expanded, lane-padded
    control_mask: jnp.ndarray  # uint32[K, M // 32]
    cw: jnp.ndarray  # uint32[K, L, 128]
    ccl: jnp.ndarray  # uint32[K, L]
    ccr: jnp.ndarray  # uint32[K, L]
    corr: object  # uint32[K, epb, lpe] (scalar) or tuple of codec arrays
    m: int  # real host lanes before the 32-lane pad


def _prepare_chunk_host(
    kb: KeyBatch, host_levels: int, scalar_fast: bool, bits: int
):
    """Host-side stage-1 pack for one chunk: host pre-expansion (numpy
    over the native AES engine), lane pad to one packed word,
    control-mask pack, correction tables. Returns
    (seeds, control_mask, cw, ccl, ccr, corr, m) in HOST form —
    `_prepare_chunk` wraps it with the device uploads; the lane-slab path
    keeps the host forms so pieces slice before uploading."""
    k = kb.seeds.shape[0]
    control0 = np.full(k, bool(kb.party), dtype=bool)
    seeds_h, control_h = _host_expand(kb.seeds, control0, kb, host_levels)
    m = seeds_h.shape[1]
    if m < 32:  # pad lanes to one packed word
        lane_pad = 32 - m
        seeds_h = np.concatenate(
            [seeds_h, np.zeros((k, lane_pad, 4), np.uint32)], axis=1
        )
        control_h = np.concatenate(
            [control_h, np.zeros((k, lane_pad), bool)], axis=1
        )
    control_mask = aes_jax.pack_bit_mask(control_h)
    cw, ccl, ccr = kb.device_cw_arrays(host_levels)
    if scalar_fast:
        corr = _correction_limbs(kb.value_corrections, bits)
    else:
        corr = kb.codec_corrections
    return seeds_h, control_mask, cw, ccl, ccr, corr, m


def _prepare_chunk(
    kb: KeyBatch, valid: int, host_levels: int, scalar_fast: bool, bits: int
) -> _PreparedChunk:
    """Stage-1 work for one chunk: `_prepare_chunk_host` plus the
    `jnp.asarray` uploads. Runs on the main thread — under the pipelined
    executor this overlaps the previous chunk's device program and the
    chunk before that's D2H pull."""
    seeds_h, control_mask, cw, ccl, ccr, corr, m = _prepare_chunk_host(
        kb, host_levels, scalar_fast, bits
    )
    if _tm.enabled():
        _tm.counter(
            "bytes.h2d",
            _tm.nbytes_of([seeds_h, control_mask, cw, ccl, ccr, corr]),
        )
    return _PreparedChunk(
        valid=valid,
        seeds=jnp.asarray(seeds_h),
        control_mask=jnp.asarray(control_mask),
        cw=jnp.asarray(cw),
        ccl=jnp.asarray(ccl),
        ccr=jnp.asarray(ccr),
        corr=(
            jnp.asarray(corr)
            if scalar_fast
            else tuple(jnp.asarray(a) for a in corr)
        ),
        m=m,
    )


class PreparedKeyBatch:
    """Key material packed and uploaded ONCE, reusable across bulk calls —
    the flat-path analog of `PreparedLevelsPlan` (ops/hierarchical.py).

    `full_domain_fold_chunks` and `full_domain_evaluate_chunks` (modes
    "levels"/"fused", leaf or lane order, no lane_slab) accept an instance
    in place of `keys` and skip the per-call host pre-expansion AND the
    re-upload of the correction-word/seed tables over the host link — at
    serving shapes those tables are ~MBs per call through a ~5 MB/s tunnel
    (PERF.md), pure setup cost for a key batch that does not change
    between calls (e.g. the benchmark loop, or a heavy-hitters server
    re-expanding one key batch against several databases). `key_chunk` and
    `host_levels` are fixed at prepare time; a consuming call passing a
    conflicting explicit value raises InvalidArgumentError (leave them at
    their None defaults to inherit the prepared choice).

    Armed fault-injection plans (seeds/cw) apply at *prepare* time — the
    prepared material models what actually sits in device memory — and
    are scoped by the prepare-time backend; the consuming call's
    `use_pallas` still selects the execution engine (the uploaded tables
    are engine-independent).
    """

    def __init__(self, dpf, keys: Sequence[DpfKey], hierarchy_level: int = -1,
                 key_chunk: int = 128, host_levels: Optional[int] = None,
                 use_pallas: Optional[bool] = None):
        v = dpf.validator
        if hierarchy_level < 0:
            hierarchy_level = v.num_hierarchy_levels - 1
        self.dpf = dpf
        self.hierarchy_level = hierarchy_level
        self.key_chunk = key_chunk
        self.num_keys = len(keys)
        batch = KeyBatch.from_keys(dpf, keys, hierarchy_level)
        if use_pallas is None:
            use_pallas = _pallas_default()
        _inject_batch_faults(batch, use_pallas)
        self.party = batch.party
        self.spec = batch.spec
        self.scalar_fast = (
            batch.spec.is_scalar_direct and batch.spec.blocks_needed == 1
        )
        value_type = v.parameters[hierarchy_level].value_type
        self.bits, self.xor_group = (
            _value_kind(value_type) if self.scalar_fast else (0, False)
        )
        stop_level = batch.num_levels
        lds = v.parameters[hierarchy_level].log_domain_size
        self.keep_per_block = 1 << (lds - stop_level)
        self.domain = 1 << lds
        if host_levels is None:
            host_levels = min(5, stop_level)
        elif host_levels < 5 and stop_level >= 5:
            raise InvalidArgumentError(
                f"PreparedKeyBatch requires host_levels >= 5 (one full "
                f"packed word), got {host_levels}"
            )
        host_levels = min(host_levels, stop_level)
        self.host_levels = host_levels
        self.device_levels = stop_level - host_levels
        self.chunks = [
            _prepare_chunk(kb, valid, host_levels, self.scalar_fast, self.bits)
            for kb, valid in _key_chunks(batch, self.num_keys, key_chunk)
        ]

    def _check_call(self, dpf, hierarchy_level: int, key_chunk, host_levels,
                    context: str) -> None:
        """The prepared tables encode one (parameter set, chunking, split)
        choice; silently accepting conflicting per-call knobs would run a
        different program against the wrong tables (or a different chunk
        grouping than the caller sized its consumers for)."""
        v = dpf.validator
        if hierarchy_level < 0:
            hierarchy_level = v.num_hierarchy_levels - 1
        if dpf is not self.dpf or hierarchy_level != self.hierarchy_level:
            raise InvalidArgumentError(
                f"{context}: PreparedKeyBatch was built for a different DPF "
                "instance or hierarchy level"
            )
        if key_chunk is not None and key_chunk != self.key_chunk:
            raise InvalidArgumentError(
                f"{context}: PreparedKeyBatch was prepared at key_chunk="
                f"{self.key_chunk}, call requested {key_chunk}"
            )
        if host_levels is not None and host_levels != self.host_levels:
            raise InvalidArgumentError(
                f"{context}: PreparedKeyBatch was prepared at host_levels="
                f"{self.host_levels}, call requested {host_levels}"
            )


def full_domain_evaluate_chunks(
    dpf: DistributedPointFunction,
    keys,
    hierarchy_level: int = -1,
    key_chunk: Optional[int] = None,  # None = 32 (prepared: its own)
    host_levels: Optional[int] = None,
    leaf_order: bool = True,
    mode: str = "levels",
    lane_slab: Optional[int] = None,
    use_pallas: Optional[bool] = None,
    pipeline: Optional[bool] = None,
):
    """Full-domain evaluation, yielding *device-resident* results per chunk.

    Yields (num_valid_keys, values) where values is a jax uint32 array
    [key_chunk, domain_size, lpe] (or a tuple of per-component arrays for
    Tuple outputs); only the first num_valid_keys rows are real keys. Nothing
    is transferred to the host — on a TPU behind a slow host link, pulling
    full-domain outputs costs orders of magnitude more than computing them,
    so on-device consumers (PIR reductions, histogram aggregation) should
    use this generator and reduce on device.

    leaf_order=False skips the per-evaluation leaf-order gather and yields
    values in expansion (lane) order: consumers can instead permute their
    static data once with `lane_order_map` at setup time.

    mode="levels" (default) runs the host-driven per-level doubling
    expansion (one small XLA program per level). mode="fused" runs the same
    doubling expansion as ONE XLA program per chunk (pack + every level +
    value hash + correction in a single dispatch): the winning shape when
    per-dispatch latency is high (~66 ms through this image's TPU tunnel,
    PERF.md) at the cost of one large program compile per chunk shape.
    mode="walk" runs ONE program per chunk in which every leaf lane walks
    its own root-to-leaf path (`lax.scan` over levels at full width):
    ~num_levels/2 x the AES arithmetic, but no per-level dispatch and —
    because lane i IS leaf i — no leaf-order gather at all: output is
    always leaf order, and passing leaf_order=False or host_levels raises
    ValueError (neither knob can apply). Walk-mode plane state is
    ~16 B x 2^tree_level per key held live for the whole program — size
    key_chunk to the device memory (e.g. 2^24-leaf domains want
    key_chunk <= 8 on a 16 GB chip). Which wins is platform-dependent; see
    tools/tpu_variants.py for the measured comparison.
    `lane_slab` (mode="fused", leaf_order=True only) splits each key
    chunk's expansion into multiple dispatches of `lane_slab` host lanes
    each, yielding ceil(M / lane_slab) leaf-contiguous pieces per chunk in
    leaf order — piece j of a chunk covers domain indices
    [j * lane_slab * 2^device_levels * keep, ...). Required when one
    program's output would exceed a platform's safe size (this image's
    tunnel miscomputes programs materializing >= ~16M leaves, PERF.md);
    see `plan_slabs` for sizing. Must be a multiple of 32 (packed-word
    granularity).

    Opt-in auto-slabbing: when the DPF_TPU_MAX_PROGRAM_BYTES env var is
    set (> 0) and mode="fused" with leaf_order=True and neither lane_slab
    nor host_levels given, oversized programs are auto-slabbed via
    `plan_slabs` under that budget (112 << 20 is the verified side of this
    image's tunnel threshold). Deliberately NOT on by default: slabbing
    changes the yield structure (several pieces per key chunk), which
    one-yield-per-chunk consumers must opt into knowingly.

    `keys` may be a `PreparedKeyBatch` (modes "levels"/"fused" without
    lane_slab: packed + uploaded once, reused across calls; the prepared
    `key_chunk`/`host_levels` apply). `pipeline` (None = DPF_TPU_PIPELINE
    env / platform default, ops/pipeline.py) launches the next chunk's
    host pack + upload + dispatch while the consumer holds the current
    one — one chunk ahead here (depth 1), because each in-flight chunk
    pins a full [key_chunk, domain, lpe] value buffer in device memory.
    """
    if mode not in ("levels", "fused", "walk"):
        raise InvalidArgumentError(
            f"mode must be 'levels', 'fused' or 'walk', got {mode!r}"
        )
    if lane_slab is not None:
        if mode != "fused" or not leaf_order:
            raise InvalidArgumentError(
                "lane_slab requires mode='fused' with leaf_order=True "
                "(lane-order consumers cannot model the slab structure)"
            )
        if lane_slab % 32 or lane_slab <= 0:
            raise InvalidArgumentError(
                f"lane_slab must be a positive multiple of 32, got {lane_slab}"
            )
    if mode == "walk" and (not leaf_order or host_levels is not None):
        # Silent acceptance would corrupt lane-order consumers: walk output
        # is always leaf order, so a caller that permuted its static data
        # with lane_order_map would reduce against wrong domain indices.
        raise InvalidArgumentError(
            "mode='walk' always yields leaf order and does no host "
            "pre-expansion; leaf_order=False / host_levels are not "
            "compatible with it"
        )
    v = dpf.validator
    if hierarchy_level < 0:
        hierarchy_level = v.num_hierarchy_levels - 1
    value_type = v.parameters[hierarchy_level].value_type
    backend_jax.log_backend_once()
    if use_pallas is None:
        use_pallas = _pallas_default()
    pipe = _pl.resolve(pipeline)
    fib = _fi_backend(use_pallas)

    prepared: Optional[PreparedKeyBatch] = None
    batch = None
    if isinstance(keys, PreparedKeyBatch):
        prepared = keys
        if mode == "walk" or lane_slab is not None:
            raise InvalidArgumentError(
                "PreparedKeyBatch supports mode='levels'/'fused' without "
                "lane_slab (walk mode and slabbing re-derive their inputs "
                "per call)"
            )
        prepared._check_call(
            dpf, hierarchy_level, key_chunk, host_levels,
            "full_domain_evaluate_chunks",
        )
        spec = prepared.spec
        scalar_fast = prepared.scalar_fast
        if scalar_fast:
            bits, xor_group = prepared.bits, prepared.xor_group
        party = prepared.party
        keep_per_block = prepared.keep_per_block
        domain = prepared.domain
        host_levels = prepared.host_levels
        device_levels = prepared.device_levels
        num_keys = prepared.num_keys
    else:
        if key_chunk is None:
            key_chunk = 32
        batch = KeyBatch.from_keys(dpf, keys, hierarchy_level)
        spec = batch.spec
        scalar_fast = spec.is_scalar_direct and spec.blocks_needed == 1
        if scalar_fast:
            bits, xor_group = _value_kind(value_type)
        party = batch.party
        stop_level = batch.num_levels
        # Only the first 2^(lds - tree_level) elements of each block are
        # addressable; fewer than elements_per_block when an earlier hierarchy
        # level forces the tree deeper (distributed_point_function.h:786-808).
        lds = v.parameters[hierarchy_level].log_domain_size
        keep_per_block = 1 << (lds - stop_level)
        assert keep_per_block <= value_type.elements_per_block()
        domain = 1 << lds

        # Opt-in auto-slabbing (see docstring). Only in full-auto sizing: an
        # explicit host_levels may be too shallow for a >= 32-lane slab, so
        # user-pinned splits keep user control. Sized by the ACTUAL program
        # key count: chunks() does not pad when the batch is smaller than
        # key_chunk.
        budget = _env_int("DPF_TPU_MAX_PROGRAM_BYTES", 0)
        if (
            budget > 0
            and mode == "fused"
            and leaf_order
            and lane_slab is None
            and host_levels is None
        ):
            auto_h, auto_slab = plan_slabs(
                dpf,
                max(1, min(key_chunk, len(keys))),
                hierarchy_level,
                max_out_bytes=budget,
            )
            if auto_slab is not None:
                host_levels, lane_slab = auto_h, auto_slab

        num_keys = len(keys)
        _inject_batch_faults(batch, use_pallas)
    def _trim(out):
        # Trim to the actual domain size (block packing may overshoot) and
        # unwrap single-component codec outputs. Only valid in leaf order —
        # lane order keeps padded lanes for the consumer's one-time permute.
        if leaf_order:
            if isinstance(out, tuple):
                out = tuple(o[:, :domain] for o in out)
            else:
                out = out[:, :domain]
        if isinstance(out, tuple) and not spec.is_tuple:
            out = out[0]
        return out

    def chunks():
        return _key_chunks(batch, num_keys, key_chunk)

    if mode == "walk":
        path_masks = jnp.asarray(_walk_path_masks(stop_level))

        def _walk_thunk(kb, valid):
            cw_dev, ccl, ccr = kb.device_cw_arrays(0)
            if scalar_fast:
                out = _walk_chunk_jit(
                    jnp.asarray(kb.seeds),
                    path_masks,
                    jnp.asarray(cw_dev),
                    jnp.asarray(ccl),
                    jnp.asarray(ccr),
                    jnp.asarray(_correction_limbs(kb.value_corrections, bits)),
                    bits=bits,
                    party=party,
                    xor_group=xor_group,
                    keep=keep_per_block,
                )
            else:
                out = _walk_chunk_codec_jit(
                    jnp.asarray(kb.seeds),
                    path_masks,
                    jnp.asarray(cw_dev),
                    jnp.asarray(ccl),
                    jnp.asarray(ccr),
                    tuple(jnp.asarray(a) for a in kb.codec_corrections),
                    spec=spec,
                    party=party,
                    keep=keep_per_block,
                )
            return valid, _trim(out)

        yield from _pl.prefetch_thunks(
            (
                functools.partial(_walk_thunk, kb, valid)
                for kb, valid in chunks()
            ),
            pipe,
            depth=1,
            backend=fib,
            op="full_domain_evaluate_chunks",
        )
        return

    # Host expands until one packed word (32 lanes) is full.
    if prepared is None:
        if host_levels is None:
            host_levels = min(5, stop_level)
        host_levels = min(host_levels, stop_level)
        device_levels = stop_level - host_levels

    def _prepared_chunks():
        if prepared is not None:
            yield from prepared.chunks
            return
        for kb, valid in chunks():
            yield _prepare_chunk(
                kb, valid, host_levels, scalar_fast,
                bits if scalar_fast else 0,
            )

    if mode == "fused" and lane_slab:
        # Slab path: pieces slice the HOST-side expansion (slicing a
        # device-resident _PreparedChunk would dispatch a program per
        # piece), so it keeps its own stage-1 prep. PreparedKeyBatch is
        # excluded above.
        def _slab_thunks():
            for kb, valid in chunks():
                seeds_p, control_mask, cw_dev, ccl, ccr, corr_h, m = (
                    _prepare_chunk_host(
                        kb, host_levels, scalar_fast,
                        bits if scalar_fast else 0,
                    )
                )
                cw_dev = jnp.asarray(cw_dev)
                ccl = jnp.asarray(ccl)
                ccr = jnp.asarray(ccr)
                if scalar_fast:
                    corr = jnp.asarray(corr_h)
                    kind = dict(bits=bits, xor_group=xor_group)
                else:
                    corr = tuple(jnp.asarray(a) for a in corr_h)
                    kind = dict(spec=spec)
                m_lanes = seeds_p.shape[1]
                slab = min(lane_slab, m_lanes)
                if m < 32:
                    # Host expansion below one packed word was lane-padded
                    # to 32; slicing padded lanes into pieces would emit
                    # garbage pieces. A single full piece is valid slabbing
                    # (every dispatch stays under any size bound a 32-lane
                    # program could violate), so clamp rather than reject
                    # (r3 review).
                    slab = m_lanes
                if slab < m_lanes:
                    # Multi-piece slabbing relies on pieces partitioning the
                    # domain EXACTLY: _trim's per-piece [:, :domain] cannot
                    # repair an overshooting piece (it would silently corrupt
                    # downstream offsets, e.g. the PIR natural-order advance).
                    # With the pad clamp above, m_lanes * 2^device_levels *
                    # keep_per_block == 2^lds holds by construction; raise
                    # (not assert: -O must not revert to silent corruption)
                    # if a future config breaks it.
                    if m_lanes * (1 << device_levels) * keep_per_block != domain:
                        raise InvalidArgumentError(
                            "lane_slab pieces would not partition the domain "
                            f"exactly (lanes={m_lanes}, device_levels="
                            f"{device_levels}, keep={keep_per_block}, "
                            f"domain={domain})"
                        )

                def _piece(lo, s, seeds_p=seeds_p, control_mask=control_mask,
                           cw_dev=cw_dev, ccl=ccl, ccr=ccr, corr=corr,
                           kind=kind, m=m, m_lanes=m_lanes, valid=valid):
                    if s == m_lanes:
                        seeds_s, mask_s = seeds_p, control_mask
                        order_s = _order_on_device(m, m_lanes, device_levels)
                    else:
                        seeds_s = seeds_p[:, lo : lo + s]
                        mask_s = control_mask[:, lo // 32 : (lo + s) // 32]
                        order_s = _order_on_device(s, s, device_levels)
                    out = _fused_chunk_jit(
                        jnp.asarray(seeds_s), jnp.asarray(mask_s),
                        cw_dev, ccl, ccr, corr, order_s,
                        levels=device_levels, party=party,
                        keep_per_block=keep_per_block, reorder=leaf_order,
                        use_pallas=use_pallas, **kind,
                    )
                    return valid, _trim(out)

                for lo in range(0, m_lanes, slab):
                    yield functools.partial(
                        _piece, lo, min(slab, m_lanes - lo)
                    )

        yield from _pl.prefetch_thunks(
            _slab_thunks(), pipe, depth=1, backend=fib,
            op="full_domain_evaluate_chunks",
        )
        return

    if mode == "fused":
        kind = (
            dict(bits=bits, xor_group=xor_group)
            if scalar_fast
            else dict(spec=spec)
        )

        def _fused_thunk(ch: _PreparedChunk):
            order_dev = _order_on_device(ch.m, ch.seeds.shape[1], device_levels)
            out = _fused_chunk_jit(
                ch.seeds, ch.control_mask, ch.cw, ch.ccl, ch.ccr, ch.corr,
                order_dev,
                levels=device_levels, party=party,
                keep_per_block=keep_per_block, reorder=leaf_order,
                use_pallas=use_pallas, **kind,
            )
            return ch.valid, _trim(out)

        yield from _pl.prefetch_thunks(
            (
                functools.partial(_fused_thunk, ch)
                for ch in _prepared_chunks()
            ),
            pipe,
            depth=1,
            backend=fib,
            op="full_domain_evaluate_chunks",
        )
        return

    def _levels_thunk(ch: _PreparedChunk):
        planes, control = _pack_batch_jit(ch.seeds, ch.control_mask)
        cw_l, ccl_l, ccr_l = _split_levels_jit(ch.cw, ch.ccl, ch.ccr)
        for level in range(device_levels):
            planes, control = _expand_level_batch(
                planes, control, cw_l[level], ccl_l[level], ccr_l[level]
            )
        order_dev = _order_on_device(ch.m, ch.seeds.shape[1], device_levels)
        if scalar_fast:
            out = _finalize_batch_jit(
                planes,
                control,
                ch.corr,
                order_dev,
                bits=bits,
                party=party,
                xor_group=xor_group,
                keep_per_block=keep_per_block,
                reorder=leaf_order,
            )
        else:
            out = _finalize_batch_codec_jit(
                planes,
                control,
                ch.corr,
                order_dev,
                spec=spec,
                party=party,
                keep_per_block=keep_per_block,
                reorder=leaf_order,
            )
        return ch.valid, _trim(out)

    yield from _pl.prefetch_thunks(
        (functools.partial(_levels_thunk, ch) for ch in _prepared_chunks()),
        pipe,
        depth=1,
        backend=fib,
        op="full_domain_evaluate_chunks",
    )


def plan_slabs(
    dpf: DistributedPointFunction,
    key_chunk: int,
    hierarchy_level: int = -1,
    max_out_bytes: int = 112 << 20,
    min_host_levels: int = 5,
) -> Tuple[int, Optional[int]]:
    """Sizes (host_levels, lane_slab) so one fused dispatch materializes at
    most `max_out_bytes` of output for a key_chunk-key program.

    The default budget is the verified side of this image's tunnel
    miscompute threshold (~117 MB computes bit-exactly, ~134 MB corrupts —
    PERF.md "2026-07-31"); programs under it need no slabbing and get
    (min_host_levels, None). Pass the result into
    `full_domain_evaluate_chunks(..., mode="fused", host_levels=h,
    lane_slab=s)`.
    """
    v = dpf.validator
    if hierarchy_level < 0:
        hierarchy_level = v.num_hierarchy_levels - 1
    stop_level = v.hierarchy_to_tree[hierarchy_level]
    value_type = v.parameters[hierarchy_level].value_type
    spec = value_codec.build_spec(value_type, v.blocks_needed[hierarchy_level])
    lds = v.parameters[hierarchy_level].log_domain_size
    keep = 1 << (lds - stop_level)
    bytes_per_leaf = keep * 4 * sum(c.lpe for c in spec.components)
    budget_leaves = max(1, max_out_bytes // (bytes_per_leaf * key_chunk))
    if (1 << stop_level) <= budget_leaves:
        return min(min_host_levels, stop_level), None
    # Host-expand until one 32-lane slab fits the budget, then take as many
    # whole 32-lane groups per dispatch as fit.
    h = min(min_host_levels, stop_level)
    while h < stop_level and (32 << (stop_level - h)) > budget_leaves:
        h += 1
    leaves_per_lane = 1 << (stop_level - h)
    slab = max(32, (budget_leaves // leaves_per_lane) // 32 * 32)
    return h, slab


# ---------------------------------------------------------------------------
# Megakernel strategy: VMEM-resident tree slabs with in-kernel consumers
# ---------------------------------------------------------------------------


class MegakernelPlan(NamedTuple):
    """Static shape plan for the slab megakernel (aes_pallas.
    megakernel_fold_pallas_batched): hashable, used as a jit static arg.
    All widths are in packed 32-lane words; every field is a power of two.

      entry_words  width of the level-host_levels seed tile (2^(h-5))
      levels_a     in-kernel levels from entry to the mid state
      mid_words    mid-state width, parked in VMEM scratch (= entry <<
                   levels_a = num_slabs * slab_words)
      num_slabs    domain slabs per key (the second grid axis)
      slab_words   slab slice width at the mid level
      levels_b     in-kernel levels from slab slice to leaves
      final_words  leaf-level slab width (slab_words << levels_b)
      fold_words   width the in-kernel fold reduces to (<= 128), i.e. the
                   per-key output is [lpe, fold_words] regardless of domain
    """

    host_levels: int
    levels_a: int
    levels_b: int
    entry_words: int
    mid_words: int
    slab_words: int
    final_words: int
    fold_words: int
    num_slabs: int


def _floor_pow2(x: int) -> int:
    return 1 << max(0, int(x).bit_length() - 1)


def _fold_mode_default() -> str:
    """Resolves the fold-strategy default: "megakernel" when
    DPF_TPU_MEGAKERNEL is truthy, else the shipped "fold" shape — the A/B
    knob bench.py / tools/tpu_measure.sh flip without code changes."""
    return "megakernel" if _env_bool("DPF_TPU_MEGAKERNEL", default=False) else "fold"


def plan_megakernel(
    dpf: DistributedPointFunction,
    hierarchy_level: int = -1,
    host_levels: Optional[int] = None,
    vmem_budget: Optional[int] = None,
    domain_shards: int = 1,
) -> MegakernelPlan:
    """Sizes the megakernel's slab geometry from a VMEM budget, analogous
    to `plan_slabs` sizing HBM output slabs.

    The budget (DPF_TPU_MEGAKERNEL_VMEM env, default 8 MB of the v5e's
    ~16 MB/core) splits between the leaf-level working slab (128 plane
    rows x final_words x 4 B, plus AES temporaries — slack 4x) and the
    mid-state scratch (129 rows x mid_words x 4 B). The kernel's OUTPUT is
    [K, lpe, fold_words <= 128] no matter what this chooses: unlike
    `plan_slabs`, there is no output-size wall to plan around — the
    >= 16M-leaf materialization threshold is structurally unreachable
    (pinned by tests/test_megakernel.py).

    `domain_shards` > 1 sizes the PER-SHARD plan for the mesh-sharded PIR
    path (parallel/sharded.build_sharded_megakernel_step): each 'domain'
    shard owns a contiguous 1/domain_shards slice of the level-host_levels
    entry tile — entry lane index IS the tree node id at that level, and
    the doubling expansion is data-independent of node id, so the shard's
    kernel on its entry slice computes exactly the leaves of its contiguous
    domain slice. Both entry_words and total_words divide by the shard
    count; the VMEM budget stays naturally per-chip, so DB capacity scales
    linearly with domain shards at a constant per-chip footprint. The
    kernel body is UNCHANGED — a shard plan is just a smaller plan."""
    v = dpf.validator
    if hierarchy_level < 0:
        hierarchy_level = v.num_hierarchy_levels - 1
    stop = v.hierarchy_to_tree[hierarchy_level]
    if host_levels is None:
        host_levels = 5
    if host_levels < 5:
        raise InvalidArgumentError(
            f"megakernel requires host_levels >= 5 (one packed word), got "
            f"{host_levels}"
        )
    if stop < host_levels + 1:
        raise InvalidArgumentError(
            f"megakernel needs at least one device level (tree depth {stop} "
            f"<= host_levels {host_levels}); use mode='fold' for tiny domains"
        )
    if vmem_budget is None:
        vmem_budget = _env_int("DPF_TPU_MEGAKERNEL_VMEM", 8 << 20)
    # Leaf-level slab: 128 rows x w_f x 4 B, ~4x live temporaries in the
    # traced AES circuit; mid scratch: 129 rows x w_v x 4 B.
    w_f_max = _floor_pow2(max(1, (vmem_budget // 2) // (128 * 4 * 4)))
    w_v_max = _floor_pow2(max(1, (vmem_budget // 4) // (129 * 4)))
    entry_words = 1 << (host_levels - 5)
    total_words = 1 << (stop - 5)
    if domain_shards != 1:
        if domain_shards < 1 or domain_shards & (domain_shards - 1):
            raise InvalidArgumentError(
                f"domain_shards must be a power of two, got {domain_shards}"
            )
        if entry_words % domain_shards:
            raise InvalidArgumentError(
                f"sharded megakernel needs host_levels >= 5 + "
                f"log2(domain_shards): the {entry_words}-word entry tile at "
                f"host_levels {host_levels} does not split across "
                f"{domain_shards} domain shards (each shard owns whole "
                "packed entry words)"
            )
        entry_words //= domain_shards
        total_words //= domain_shards
    final_words = min(total_words, w_f_max)
    num_slabs = total_words // final_words
    if num_slabs > (1 << 20):
        raise InvalidArgumentError(
            f"megakernel plan would need {num_slabs} slabs at tree depth "
            f"{stop}; raise DPF_TPU_MEGAKERNEL_VMEM or use mode='fold'"
        )
    slab_words = min(final_words, max(1, _floor_pow2(w_v_max // num_slabs)))
    if num_slabs * slab_words < entry_words:
        slab_words = entry_words // num_slabs if num_slabs <= entry_words else 1
    mid_words = num_slabs * slab_words
    levels_a = (mid_words // entry_words).bit_length() - 1
    levels_b = (final_words // slab_words).bit_length() - 1
    assert levels_a + levels_b == stop - host_levels, (
        levels_a, levels_b, stop, host_levels,
    )
    return MegakernelPlan(
        host_levels=host_levels,
        levels_a=levels_a,
        levels_b=levels_b,
        entry_words=entry_words,
        mid_words=mid_words,
        slab_words=slab_words,
        final_words=final_words,
        fold_words=min(128, final_words),
        num_slabs=num_slabs,
    )


@functools.lru_cache(maxsize=8)
def _megakernel_block_leaves(plan: MegakernelPlan) -> np.ndarray:
    """int64[total_blocks]: tree-leaf index of the megakernel's output
    block at global position g = slab * final_words * 32 + local_lane —
    the host replay of the kernel's two block-concat recursions (phase A
    over the whole row, phase B within each slab slice). Element e of
    block g is domain index leaves[g] * keep + e."""
    prefix = np.arange(plan.entry_words * 32, dtype=np.int64)
    for _ in range(plan.levels_a):
        prefix = np.concatenate([2 * prefix, 2 * prefix + 1])
    swl = plan.slab_words * 32
    fwl = plan.final_words * 32
    out = np.empty(plan.num_slabs * fwl, dtype=np.int64)
    for j in range(plan.num_slabs):
        base = prefix[j * swl : (j + 1) * swl]
        for _ in range(plan.levels_b):
            base = np.concatenate([2 * base, 2 * base + 1])
        out[j * fwl : (j + 1) * fwl] = base
    return out


def megakernel_order_map(
    dpf: DistributedPointFunction,
    hierarchy_level: int = -1,
    host_levels: Optional[int] = None,
    plan: Optional[MegakernelPlan] = None,
) -> np.ndarray:
    """int64[domain]: domain index of each megakernel output position
    (position g * keep + e is the value at domain index map[g*keep+e]) —
    the megakernel analog of `lane_order_map`, exact (no -1 padding: the
    kernel's lane set is the domain). The XOR fold itself is
    order-invariant; this map exists for the PIR database permutation
    (parallel/sharded.prepare_pir_database(order="megakernel"))."""
    v = dpf.validator
    if hierarchy_level < 0:
        hierarchy_level = v.num_hierarchy_levels - 1
    if plan is None:
        plan = plan_megakernel(dpf, hierarchy_level, host_levels)
    stop = v.hierarchy_to_tree[hierarchy_level]
    lds = v.parameters[hierarchy_level].log_domain_size
    keep = 1 << (lds - stop)
    leaves = _megakernel_block_leaves(plan)
    return (leaves[:, None] * keep + np.arange(keep, dtype=np.int64)).reshape(-1)


def megakernel_db_rows(
    dpf: DistributedPointFunction,
    db_limbs: np.ndarray,  # uint32[domain, lpe]
    plan: MegakernelPlan,
    hierarchy_level: int = -1,
) -> np.ndarray:
    """Permutes a natural-order PIR database into the megakernel's
    streaming row layout uint32[keep*lpe*32, total_words]: row
    (e*lpe + l)*32 + i at word w holds limb l of the database value for
    element e of the block the kernel computes at lane 32w+i — exactly
    what the kernel ANDs against after its in-register unpack. The slab-j
    tile is columns [j*final_words, (j+1)*final_words): contiguous, so the
    BlockSpec index map streams it with double-buffered DMA."""
    v = dpf.validator
    if hierarchy_level < 0:
        hierarchy_level = v.num_hierarchy_levels - 1
    stop = v.hierarchy_to_tree[hierarchy_level]
    lds = v.parameters[hierarchy_level].log_domain_size
    keep = 1 << (lds - stop)
    db_limbs = np.asarray(db_limbs)
    lpe = db_limbs.shape[1]
    leaves = _megakernel_block_leaves(plan)
    blocks = leaves.reshape(-1, 32)  # [W_total, 32]
    out = np.empty((keep * lpe * 32, blocks.shape[0]), dtype=np.uint32)
    for e in range(keep):
        rows = blocks * keep + e  # [W_total, 32] domain indices
        for l in range(lpe):
            out[(e * lpe + l) * 32 : (e * lpe + l + 1) * 32, :] = db_limbs[
                rows, l
            ].T
    return out


@functools.partial(
    jax.jit,
    static_argnames=("plan", "bits", "party", "xor_group", "keep", "interpret"),
)
def _megakernel_fold_chunk_jit(
    seeds,  # uint32[K, M, 4]
    control_mask,  # uint32[K, M//32]
    cw_planes,  # uint32[K, L, 128]
    ccl,  # uint32[K, L]
    ccr,  # uint32[K, L]
    corrections,  # uint32[K, epb, lpe]
    db_rows,  # uint32[keep*lpe*32, total_words] or None
    plan: MegakernelPlan,
    bits: int,
    party: int,
    xor_group: bool,
    keep: int,
    interpret: bool = False,
):
    """ONE program per chunk, megakernel edition: pack + the slab
    megakernel (every device level, value hash, correction and the
    fold/PIR accumulate all inside one pallas_call, leaves never in HBM) +
    a trivial cross-word XOR of the [K, lpe, fold_w] partials. The
    program's output is [K, lpe] — there is no domain-sized buffer
    anywhere, internal or output, so neither the ~117 MB output-miscompute
    threshold nor the RESOURCE_EXHAUSTED cliff can bind at any domain."""
    from . import aes_pallas

    planes, control = _pack_batch_jit(seeds, control_mask)
    folds = aes_pallas.megakernel_fold_pallas_batched(
        planes,
        control,
        cw_planes,
        ccl,
        ccr,
        corrections,
        db_rows,
        plan=plan,
        bits=bits,
        party=party,
        xor_group=xor_group,
        keep=keep,
        interpret=interpret,
    )
    return jnp.bitwise_xor.reduce(folds, axis=2)


# ---------------------------------------------------------------------------
# Walk-megakernel strategy: single-program in-register point walks
# ---------------------------------------------------------------------------


class WalkkernelPlan(NamedTuple):
    """Static shape plan for the walk megakernel (aes_pallas.
    walk_megakernel_pallas_batched): hashable, used as a jit static arg —
    the `plan_megakernel` analog for the point-walk paths.

      levels        tree levels walked in-kernel (= the whole tree: the
                    walk paths do no host pre-expansion)
      tile_words    point-tile width in packed 32-lane words (the second
                    grid axis steps tiles of 32 * tile_words points)
      num_tiles     point tiles per key
      padded_words  num_tiles * tile_words — the kernel's lane-word width;
                    callers pad points up to padded_words * 32 and trim
    """

    levels: int
    tile_words: int
    num_tiles: int
    padded_words: int


def _walk_mode_default() -> str:
    """Resolves the point-walk strategy default: "walkkernel" when
    DPF_TPU_WALKKERNEL is truthy, else the shipped per-level "walk" shape
    — the A/B knob bench scripts / tools/tpu_measure.sh flip without code
    changes (the DPF_TPU_MEGAKERNEL analog for EvaluateAt/DCF/MIC)."""
    return (
        "walkkernel"
        if _env_bool("DPF_TPU_WALKKERNEL", default=False)
        else "walk"
    )


def _resolve_walk_mode(
    mode: Optional[str], scalar_fast: bool, bits: int, levels: int,
    use_pallas: Optional[bool] = None,
    op: str = "evaluate_at_batch",
) -> str:
    """Resolves the point-walk strategy for one call — ONE policy shared
    by `evaluate_at_batch` and `dcf.batch.batch_evaluate` so it cannot
    drift. An explicit mode wins (configs the walk megakernel cannot
    handle raise); the env-driven default quietly keeps "walk" for them —
    DPF_TPU_WALKKERNEL is a process-wide A/B knob and must never turn a
    previously working call into an error. `bits` is only read when
    `scalar_fast` is set. `use_pallas` is the caller's RAW knob (pre
    platform-default resolution): an explicit False also pins the env
    default to "walk" — a call qualifying the XLA engine (CHECK_PALLAS=0)
    must not silently get a Mosaic kernel, the mirror of the r3
    explicit-True rule.

    Every resolution emits exactly one telemetry decision record under
    `op` (ISSUE 6): source "explicit" | "env-default" | "pinned-xla" |
    "downgrade" (with the reason), so an A/B harness can tell "kernel
    lost" from "kernel never ran" without parsing logs."""
    explicit = mode is not None
    source, reason = "explicit", ""
    if mode is None:
        if use_pallas is False:
            _tm.decision(op, "walk", "pinned-xla", reason="use_pallas=False")
            return "walk"
        mode = _walk_mode_default()
        source = "env-default"
    if mode not in ("walk", "walkkernel"):
        raise InvalidArgumentError(
            f"mode must be 'walk' or 'walkkernel', got {mode!r}"
        )
    if mode == "walkkernel":
        if not (scalar_fast and bits % 32 == 0):
            if explicit:
                raise NotImplementedError(
                    "mode='walkkernel' handles scalar Int/XorWrapper values "
                    "with 32-bit-multiple widths; use mode='walk' for codec "
                    "(IntModN/Tuple) or sub-word outputs"
                )
            mode, source = "walk", "downgrade"
            reason = "codec or sub-word value type"
        elif levels < 1:
            if explicit:
                raise InvalidArgumentError(
                    "mode='walkkernel' needs at least one tree level (got "
                    f"{levels}); use mode='walk' for trivial domains"
                )
            mode, source = "walk", "downgrade"
            reason = "trivial domain (no tree levels)"
    _tm.decision(op, mode, source, reason=reason)
    return mode


def plan_walkkernel(
    num_points: int,
    levels: int,
    lpe: int,
    captures: bool = False,
    vmem_budget: Optional[int] = None,
) -> WalkkernelPlan:
    """Sizes the walk megakernel's point-tile width from a VMEM budget —
    the `plan_megakernel` analog for the walk paths.

    The budget (DPF_TPU_WALKKERNEL_VMEM env, default 8 MB of the v5e's
    ~16 MB/core) covers, per lane word: the 128 seed-plane rows with ~4x
    live AES temporaries, the lpe*32 value rows (doubled-plus-one when a
    DCF accumulator is carried across depths), and the per-level path
    rows. The resulting tile is a power of two >= 128 words for multi-tile
    plans — 1024+ words at the default budget, so every row fills whole
    (8, 128) vregs; point counts below one tile round up to 8-word
    (sublane) granularity instead of paying a full tile of padding."""
    if levels < 1:
        raise InvalidArgumentError(
            f"walk megakernel needs at least one tree level, got {levels}"
        )
    if vmem_budget is None:
        vmem_budget = _env_int("DPF_TPU_WALKKERNEL_VMEM", 8 << 20)
    w = -(-max(1, num_points) // 32)
    per_word = 4 * (128 * 4 + 32 * max(1, lpe) * (3 if captures else 2) + levels)
    cap = _floor_pow2(max(128, vmem_budget // per_word))
    if w <= cap:
        tile = max(8, -(-w // 8) * 8)
        return WalkkernelPlan(levels, tile, 1, tile)
    num_tiles = -(-w // cap)
    return WalkkernelPlan(levels, cap, num_tiles, num_tiles * cap)


# ---------------------------------------------------------------------------
# Hierarchical-megakernel strategy: single-program prefix-window advances
# ---------------------------------------------------------------------------


class HierkernelPlan(NamedTuple):
    """Static shape plan for the hierarchical megakernel (aes_pallas.
    hier_megakernel_pallas_batched): hashable, used as a jit static arg —
    the `plan_walkkernel` analog for the heavy-hitters prefix windows.

      levels        tree levels the window walks in-kernel (the window's
                    cumulative advance depth)
      tile_words    lane-tile width in packed 32-lane words (the second
                    grid axis steps tiles of 32 * tile_words lanes)
      num_tiles     lane tiles per key
      padded_words  num_tiles * tile_words — the kernel's lane-word width;
                    the plan composition pads the window's lane set (one
                    lane per (hierarchy level, expanded tree node) pair)
                    up to padded_words * 32
    """

    levels: int
    tile_words: int
    num_tiles: int
    padded_words: int


def _hier_mode_default() -> str:
    """Resolves the hierarchical-advance strategy default: "hierkernel"
    when DPF_TPU_HIERKERNEL is truthy, else the shipped grouped "fused"
    shape — the A/B knob bench_heavy_hitters / tools/tpu_measure.sh flip
    without code changes (the DPF_TPU_WALKKERNEL analog for
    evaluate_levels_fused)."""
    return (
        "hierkernel"
        if _env_bool("DPF_TPU_HIERKERNEL", default=False)
        else "fused"
    )


def plan_hierkernel(
    num_lanes: int,
    levels: int,
    n_rows: int,
    lpe: int,
    keep: int = 1,
    vmem_budget: Optional[int] = None,
) -> HierkernelPlan:
    """Sizes the hierarchical megakernel's lane-tile width from a VMEM
    budget — the `plan_walkkernel` analog for the prefix windows.

    The budget (DPF_TPU_HIERKERNEL_VMEM env, default 8 MB of the v5e's
    ~16 MB/core) covers, per lane word: the 128 seed-plane rows with ~4x
    live AES temporaries plus the exit-state write, the keep*lpe*32 value
    accumulator rows (doubled: capture temporaries + the placement
    accumulator), the per-level path rows and the n_rows select-mask
    rows. Tile geometry follows plan_walkkernel's: a power of two >= 128
    words for multi-tile plans, 8-word (sublane) granularity below one
    tile. `num_lanes` is the window's (padded-uniform) lane count — the
    plan composition passes the max across the plan's windows so equal-
    depth windows share one compiled config."""
    if levels < 1:
        raise InvalidArgumentError(
            f"hier megakernel needs at least one tree level per window, "
            f"got {levels}"
        )
    if vmem_budget is None:
        vmem_budget = _env_int("DPF_TPU_HIERKERNEL_VMEM", 8 << 20)
    w = -(-max(1, num_lanes) // 32)
    per_word = 4 * (
        128 * 5 + 32 * max(1, lpe) * max(1, keep) * 2 + levels + n_rows + 8
    )
    cap = _floor_pow2(max(128, vmem_budget // per_word))
    if w <= cap:
        tile = max(8, -(-w // 8) * 8)
        return HierkernelPlan(levels, tile, 1, tile)
    num_tiles = -(-w // cap)
    return HierkernelPlan(levels, cap, num_tiles, num_tiles * cap)


@functools.partial(
    jax.jit,
    static_argnames=(
        "plan", "bits", "party", "xor_group", "keep", "captures", "interpret",
    ),
)
def _walk_megakernel_chunk_jit(
    seed_planes,  # uint32[K, 128] root-seed plane masks
    path_masks,  # uint32[L, Wp]
    cw_planes,  # uint32[K, L, 128]
    ccl,  # uint32[K, L]
    ccr,  # uint32[K, L]
    corrections,  # uint32[K, n_rows, lpe]
    sel_bits,  # uint32[n_rows, Wp]
    plan: WalkkernelPlan,
    bits: int,
    party: int,
    xor_group: bool,
    keep: int,
    captures=None,
    interpret: bool = False,
):
    """ONE program per chunk, walk-megakernel edition: the single
    pallas_call walking every tree level in-register (EvaluateAt's leaf
    capture or DCF's per-depth capture/accumulate in-kernel) plus the
    trivial value-row transpose back to [K, P_pad, lpe]. No per-level
    dispatch, no per-level [K, P] seed-plane HBM round trip."""
    from . import aes_pallas

    out = aes_pallas.walk_megakernel_pallas_batched(
        seed_planes,
        path_masks,
        cw_planes,
        ccl,
        ccr,
        corrections,
        sel_bits,
        plan=plan,
        bits=bits,
        party=party,
        xor_group=xor_group,
        keep=keep,
        captures=captures,
        interpret=interpret,
    )
    k = out.shape[0]
    lpe = bits // 32
    # Value rows -> [K, P_pad, lpe]: row l*32+i word w = limb l of point
    # 32w+i, so the point axis factors as (word, bit-in-word).
    return (
        out.reshape(k, lpe, 32, plan.padded_words)
        .transpose(0, 3, 2, 1)
        .reshape(k, plan.padded_words * 32, lpe)
    )


def _walk_megakernel_thunks(
    batch: KeyBatch,
    num_keys: int,
    key_chunk: int,
    corr_rows: np.ndarray,  # uint32[K, n_rows, lpe] per-key correction rows
    path_masks_dev,  # uint32[L, Wp] device-resident, point-shared
    sel_dev,  # uint32[n_sel, Wp] device-resident, point-shared
    plan: WalkkernelPlan,
    bits: int,
    party: int,
    xor_group: bool,
    keep: int,
    captures,
    interpret: bool,
):
    """Shared chunk-thunk driver for the walk-megakernel entry points
    (evaluate_at_batch and dcf.batch._batch_evaluate_walkkernel): yields
    one thunk per key chunk — whole-batch calls skip the identity
    fancy-index copies; per-chunk key tables upload inside the thunk so
    the pipelined executor overlaps them — returning (valid, out) with
    out uint32[K, P_pad, lpe]. The two call sites differ only in the
    capture-table inputs (corr_rows/sel/captures/keep), so the executor
    scaffolding lives once here."""

    def _thunk(idx, valid):
        whole = valid == num_keys and idx.shape[0] == num_keys
        kb = batch if whole else batch.take(idx)
        corr_c = corr_rows if whole else corr_rows[idx]
        cw_planes, ccl, ccr = kb.device_cw_arrays()
        out = _walk_megakernel_chunk_jit(
            jnp.asarray(backend_jax.cw_seed_planes(kb.seeds)),
            path_masks_dev,
            jnp.asarray(cw_planes),
            jnp.asarray(ccl),
            jnp.asarray(ccr),
            jnp.asarray(corr_c),
            sel_dev,
            plan=plan,
            bits=bits,
            party=party,
            xor_group=xor_group,
            keep=keep,
            captures=captures,
            interpret=interpret,
        )
        return valid, out

    return (
        functools.partial(_thunk, idx, valid)
        for idx, valid in _pl.chunk_indices(num_keys, key_chunk)
    )


@_tm.traced("full_domain_evaluate")
def full_domain_evaluate(
    dpf: DistributedPointFunction,
    keys: Sequence[DpfKey],
    hierarchy_level: int = -1,
    key_chunk: int = 32,
    host_levels: Optional[int] = None,
    use_pallas: Optional[bool] = None,
    integrity: Optional[bool] = None,
    pipeline: Optional[bool] = None,
) -> np.ndarray:
    """Full-domain evaluation of a key batch, results on the host.

    For Int/XorWrapper outputs returns uint32[K, domain_size, lpe] limb
    values (lpe = max(bits//32, 1)); use `values_to_numpy` for a numpy
    integer view. For IntModN returns uint32[K, domain_size, lpe] mod-N limb
    values; for Tuple outputs returns a tuple of such per-component arrays
    (struct of arrays) — `value_codec.values_to_host` converts either back to
    host values. Keys are processed in chunks of `key_chunk` to bound HBM
    use. For on-device consumption use `full_domain_evaluate_chunks`.

    `integrity` enables sentinel-key verification (None = the
    DPF_TPU_INTEGRITY env default): one library-generated probe key rides
    the batch through the same programs at the same shape, and its output
    is checked against the host oracle — a mismatch raises
    DataCorruptionError carrying the corrupted lane pattern
    (utils/integrity.py; PERF.md "Platform findings"). Costs one extra key
    per batch: free when the final chunk has a padding slot for it, but
    when len(keys) is an exact multiple of `key_chunk` the probe spills
    into one extra dispatch of its own (PERF.md "sentinel overhead").
    Scalar Int/XorWrapper outputs only; codec value types evaluate
    unverified with an "integrity-skip" event.

    `pipeline` (None = DPF_TPU_PIPELINE env / platform default,
    ops/pipeline.py) keeps three stages in flight: chunk N+1's host pack +
    upload + dispatch (main thread), chunk N's device program, and chunk
    N-1's D2H pull (worker thread).
    """
    from ..utils import integrity as _integrity

    if use_pallas is None:
        use_pallas = _pallas_default()
    pipe = _pl.resolve(pipeline)
    keys, probe = _integrity.setup_probe(
        dpf, hierarchy_level, keys, integrity, "full_domain_evaluate",
        backend=_fi_backend(use_pallas),
    )

    def _pull(item):
        valid, out = item
        if isinstance(out, tuple):
            return tuple(np.asarray(o)[:valid] for o in out)
        return np.asarray(out)[:valid]

    outs = list(
        _pl.consume(
            full_domain_evaluate_chunks(
                dpf, keys, hierarchy_level, key_chunk, host_levels,
                use_pallas=use_pallas, pipeline=pipeline,
            ),
            _pull,
            pipe,
            # depth 1, matching the generator's own launch window: every
            # un-pulled item pins a full [key_chunk, domain, lpe] value
            # buffer in device memory, so the default depth would pin ~4
            # chunks of values and walk HBM toward the eviction cliff the
            # executor exists to avoid (PERF.md).
            depth=1,
            backend=_fi_backend(use_pallas),
            op="full_domain_evaluate_chunks",
        )
    )
    is_tuple = isinstance(outs[0], tuple) if outs else False
    if is_tuple:
        return tuple(
            np.concatenate([o[c] for o in outs], axis=0)
            for c in range(len(outs[0]))
        )
    out = np.concatenate(outs, axis=0)
    out = faultinject.corrupt_output(out, backend=_fi_backend(use_pallas))
    if probe is not None:
        _integrity.verify_probe_values(
            probe, out[-1], context="full_domain_evaluate",
            key_index=out.shape[0] - 1,
        )
        out = out[:-1]
    return out


def lane_order_map(
    dpf: DistributedPointFunction,
    hierarchy_level: int = -1,
    host_levels: Optional[int] = None,
) -> np.ndarray:
    """Maps lane-order output positions to domain indices (-1 = padding).

    For `full_domain_evaluate_chunks(..., leaf_order=False)`: output element
    at position p is the DPF value at domain index `lane_order_map(...)[p]`.
    Static data (e.g. a PIR database) can be permuted once with this map at
    setup time, after which every evaluation skips its full-size leaf-order
    gather.
    """
    v = dpf.validator
    if hierarchy_level < 0:
        hierarchy_level = v.num_hierarchy_levels - 1
    stop_level = v.hierarchy_to_tree[hierarchy_level]
    lds = v.parameters[hierarchy_level].log_domain_size
    keep = 1 << (lds - stop_level)
    host_levels = min(5 if host_levels is None else host_levels, stop_level)
    device_levels = stop_level - host_levels
    m = 1 << host_levels
    padded = max(m, 32)
    order = backend_jax.expansion_output_order(m, padded, device_levels)
    n_lanes = padded << device_levels
    inv = np.full(n_lanes, -1, dtype=np.int64)
    inv[order] = np.arange(order.shape[0], dtype=np.int64)
    out = np.full(n_lanes * keep, -1, dtype=np.int64)
    for i in range(keep):
        valid = inv >= 0
        leaf_elem = inv * keep + i
        pos = np.arange(n_lanes, dtype=np.int64) * keep + i
        out[pos[valid]] = leaf_elem[valid]
    out[out >= (1 << lds)] = -1  # block packing overshoot
    return out


def _value_kind(value_type) -> Tuple[int, bool]:
    if isinstance(value_type, Int):
        return value_type.bitsize, False
    if isinstance(value_type, XorWrapper):
        return value_type.bitsize, True
    raise NotImplementedError(
        f"device evaluator supports Int/XorWrapper outputs, got {value_type}; "
        "use the host path (DistributedPointFunction.evaluate_*) instead"
    )


def _payload_kind(value_type) -> Tuple[int, bool, int]:
    """(bits, xor_group, n_elems) for scalar OR uniform packed tuples.

    Vector payloads are restricted to `TupleType` over identical 32/64/128-
    bit Int/XorWrapper elements: whole-limb widths dividing the block, so
    elements pack densely into ceil(n_elems * bits / 128) value-hash blocks
    (128 // bits per block, reference byte layout) and never straddle a
    block boundary — the capture tail splits blocks with the same
    `_split_elements` codec the scalar epb path uses.
    """
    from ..core.value_types import TupleType

    if isinstance(value_type, TupleType):
        elems = value_type.elements
        first = elems[0]
        if not all(e == first for e in elems[1:]):
            raise NotImplementedError(
                "batched evaluator supports uniform tuple payloads only, "
                f"got {value_type}"
            )
        bits, xor_group = _value_kind(first)
        if bits not in (32, 64, 128):
            raise NotImplementedError(
                "batched evaluator supports tuples of 32/64/128-bit "
                f"elements only (whole-limb block packing), got {value_type}"
            )
        return bits, xor_group, len(elems)
    bits, xor_group = _value_kind(value_type)
    return bits, xor_group, 1


def _correction_limbs(vc: np.ndarray, bits: int) -> np.ndarray:
    """uint32[K, epb, 4] full-block limbs -> uint32[K, epb, lpe]."""
    if bits >= 32:
        return vc[:, :, : bits // 32]
    return vc[:, :, :1] & np.uint32((1 << bits) - 1)


def values_to_numpy(values: np.ndarray, bits: int) -> np.ndarray:
    """uint32[..., lpe] limb values -> numpy uint array (object for 128)."""
    values = np.asarray(values)
    if bits <= 32:
        return values[..., 0].astype(f"uint{max(bits, 8)}" if bits != 32 else "uint32")
    if bits == 64:
        return values[..., 0].astype(np.uint64) | (
            values[..., 1].astype(np.uint64) << np.uint64(32)
        )
    out = np.zeros(values.shape[:-1], dtype=object)
    for l in range(values.shape[-1]):
        out |= values[..., l].astype(object) << (32 * l)
    return out


# ---------------------------------------------------------------------------
# Batched point evaluation (keys x points)
# ---------------------------------------------------------------------------


def _evaluate_points_one_key(
    seeds,  # uint32[P, 4] root seed broadcast
    control,  # uint32[W]
    path_masks,  # uint32[L, W] (shared across keys)
    cw_planes,  # uint32[L, 128]
    ccl,
    ccr,  # uint32[L]
    corrections,  # uint32[epb, lpe]
    block_sel,  # int32[P] block index of each point
    bits: int,
    party: int,
    xor_group: bool,
):
    planes = aes_jax.pack_to_planes(seeds)
    planes, control = backend_jax.evaluate_seeds_planes(
        planes, control, path_masks, cw_planes, ccl, ccr
    )
    hashed = backend_jax.hash_value_planes(planes)
    blocks = aes_jax.unpack_from_planes(hashed)
    ctrl_bits = backend_jax.unpack_mask_device(control)
    values = _correct_values(
        blocks, ctrl_bits, corrections, bits, party, xor_group
    )  # [P_pad, epb, lpe]
    p = block_sel.shape[0]
    return values[jnp.arange(p), block_sel]  # [P, lpe]


@functools.partial(
    jax.jit, static_argnames=("bits", "party", "xor_group", "use_pallas")
)
def _evaluate_points_jit(
    seeds, control, path_masks, cw_planes, ccl, ccr, corrections, block_sel,
    bits, party, xor_group, use_pallas=False,
):
    if use_pallas:
        from . import aes_pallas

        planes = jax.vmap(aes_jax.pack_to_planes)(seeds)
        ctrl0 = jnp.broadcast_to(
            control[None], (seeds.shape[0],) + control.shape
        )
        planes, ctrl = aes_pallas.walk_levels_pallas_batched(
            planes, ctrl0, path_masks, cw_planes, ccl, ccr
        )
        if planes.shape[2] >= 256:
            hashed = aes_pallas.hash_value_planes_pallas_batched(planes)
        else:
            hashed = jax.vmap(backend_jax.hash_value_planes)(planes)
        blocks = jax.vmap(aes_jax.unpack_from_planes)(hashed)
        ctrl_bits = jax.vmap(backend_jax.unpack_mask_device)(ctrl)
        fn = functools.partial(
            _correct_values, bits=bits, party=party, xor_group=xor_group
        )
        values = jax.vmap(fn)(blocks, ctrl_bits, corrections)
        p = block_sel.shape[0]
        return values[:, jnp.arange(p), block_sel]
    fn = functools.partial(
        _evaluate_points_one_key, bits=bits, party=party, xor_group=xor_group
    )
    return jax.vmap(fn, in_axes=(0, None, None, 0, 0, 0, 0, None))(
        seeds, control, path_masks, cw_planes, ccl, ccr, corrections, block_sel
    )


def _evaluate_points_one_key_codec(
    seeds, control, path_masks, cw_planes, ccl, ccr, corrections, block_sel,
    spec, party,
):
    planes = aes_jax.pack_to_planes(seeds)
    planes, control = backend_jax.evaluate_seeds_planes(
        planes, control, path_masks, cw_planes, ccl, ccr
    )
    stream = backend_jax.hash_value_stream(planes, spec.blocks_needed)
    ctrl_bits = backend_jax.unpack_mask_device(control)
    vals = value_codec.correct_values(stream, ctrl_bits, corrections, spec, party)
    p = block_sel.shape[0]
    return tuple(v[jnp.arange(p), block_sel] for v in vals)


@functools.partial(jax.jit, static_argnames=("spec", "party"))
def _evaluate_points_codec_jit(
    seeds, control, path_masks, cw_planes, ccl, ccr, corrections, block_sel,
    spec, party,
):
    fn = functools.partial(_evaluate_points_one_key_codec, spec=spec, party=party)
    return jax.vmap(fn, in_axes=(0, None, None, 0, 0, 0, 0, None))(
        seeds, control, path_masks, cw_planes, ccl, ccr, corrections, block_sel
    )


@_tm.traced("evaluate_at_batch")
def evaluate_at_batch(
    dpf: DistributedPointFunction,
    keys: Sequence[DpfKey],
    points: Sequence[int],
    hierarchy_level: int = -1,
    device_output: bool = False,
    use_pallas: Optional[bool] = None,
    integrity: Optional[bool] = None,
    key_chunk: Optional[int] = None,
    pipeline: Optional[bool] = None,
    mode: Optional[str] = None,
):
    """Evaluates every key at every point on device.

    Batched-device equivalent of EvaluateAt
    (/root/reference/dpf/distributed_point_function.h:331-360) — the
    reference evaluates one key at a time; here keys are vmapped and points
    are packed lanes. Returns uint32[K, P, lpe] limb values for scalar
    outputs, or a tuple of per-component arrays for Tuple outputs — numpy
    by default, device-resident jax arrays with device_output=True (for
    on-device consumers; see PERF.md on the host-link cost).

    `integrity` (None = DPF_TPU_INTEGRITY env default) appends a sentinel
    probe key verified at these exact points against the host oracle —
    see `full_domain_evaluate`.

    `key_chunk` (None = the whole batch in ONE program, the historical
    shape) splits the key axis into chunks driven through the pipelined
    executor (ops/pipeline.py): chunk N+1's correction-word upload and
    dispatch overlap chunk N's program and chunk N-1's D2H pull.
    `pipeline` (None = DPF_TPU_PIPELINE env / platform default) selects
    the executor mode; with a single chunk it is a pass-through.

    `mode` selects the walk strategy (None = "walkkernel" when the
    DPF_TPU_WALKKERNEL env is truthy, else "walk" — the A/B knob):

    * "walk" — the shipped shape: one program per chunk whose tree walk
      runs the per-level engines (`lax.scan` over the XLA bitslice, or
      `aes_pallas.walk_levels_pallas_batched` one pallas_call per level
      under `use_pallas`).
    * "walkkernel" — the walk megakernel
      (aes_pallas.walk_megakernel_pallas_batched): ONE pallas_call per
      chunk, grid (keys, point tiles), walking ALL tree levels
      in-register — no per-level kernel boundary and no per-level [K, P]
      seed-plane HBM round trip (PERF.md "Walk megakernel"); the leaf
      capture (value hash + correction + block-element select) runs
      in-kernel too. Point tiles come from `plan_walkkernel`
      (DPF_TPU_WALKKERNEL_VMEM). Scalar Int/XorWrapper widths that are
      32-bit multiples only; an explicit mode="walkkernel" on other value
      types raises, the env default quietly keeps "walk". Off-TPU the
      kernel runs through the Pallas interpreter (correctness only).
    """
    from ..utils import integrity as _integrity

    v = dpf.validator
    if hierarchy_level < 0:
        hierarchy_level = v.num_hierarchy_levels - 1
    use_pallas_raw = use_pallas
    if use_pallas is None:
        use_pallas = _pallas_default()
    pipe = _pl.resolve(pipeline)

    # Resolve the walk strategy BEFORE the probe/fault setup: the walk
    # megakernel IS a Mosaic program regardless of the use_pallas knob, so
    # the integrity probe and any armed fault plans must be scoped to the
    # engine that will actually execute (the full_domain_fold_chunks
    # discipline — it forces use_pallas=True before any probe runs).
    # Everything the validity check needs is derivable from the validator,
    # no key batch required.
    value_type = v.parameters[hierarchy_level].value_type
    spec = value_codec.build_spec(value_type, v.blocks_needed[hierarchy_level])
    scalar_fast = spec.is_scalar_direct and spec.blocks_needed == 1
    if scalar_fast:
        bits, xor_group = _value_kind(value_type)
    mode = _resolve_walk_mode(
        mode, scalar_fast, bits if scalar_fast else 0,
        v.hierarchy_to_tree[hierarchy_level], use_pallas_raw,
        op="evaluate_at_batch",
    )
    fib = "pallas" if mode == "walkkernel" else _fi_backend(use_pallas)

    keys, probe = _integrity.setup_probe(
        dpf, hierarchy_level, keys, integrity, "evaluate_at_batch",
        backend=fib,
    )
    backend_jax.log_backend_once()
    batch = KeyBatch.from_keys(dpf, keys, hierarchy_level)
    _inject_batch_faults(batch, use_pallas or mode == "walkkernel")
    num_levels = batch.num_levels
    k = batch.seeds.shape[0]
    p = len(points)

    tree_indices = np.array(
        [v.domain_to_tree_index(int(pt), hierarchy_level) for pt in points],
        dtype=object,
    )
    block_sel = np.array(
        [v.domain_to_block_index(int(pt), hierarchy_level) for pt in points],
        dtype=np.int32,
    )
    paths = uint128.array_to_limbs([int(t) for t in tree_indices])
    ck = k if key_chunk is None else max(1, key_chunk)

    if mode == "walkkernel":
        lds = v.parameters[hierarchy_level].log_domain_size
        keep = 1 << (lds - num_levels)
        plan = plan_walkkernel(p, num_levels, bits // 32)
        p_pad = plan.padded_words * 32
        path_masks = backend_jax._path_bit_masks(paths, num_levels, p_pad)
        # Select mask per block element: bit j of row e = [point j's
        # addressed element is e]; padded points select nothing.
        sel_bool = np.zeros((keep, p_pad), dtype=bool)
        sel_bool[block_sel, np.arange(p)] = True
        # Off-TPU the Mosaic kernel runs through the Pallas interpreter —
        # bit-exact (tests/test_walkkernel.py), minus the performance.
        thunks = _walk_megakernel_thunks(
            batch, k, ck,
            _correction_limbs(batch.value_corrections, bits),
            jnp.asarray(path_masks),
            jnp.asarray(aes_jax.pack_bit_mask(sel_bool)),
            plan, bits, batch.party, xor_group, keep,
            captures=None,
            interpret=jax.default_backend() != "tpu",
        )
    else:
        p_pad = -(-p // 32) * 32
        path_masks = backend_jax._path_bit_masks(paths, num_levels, p_pad)

        # Point-shared tables upload once; per-chunk key material uploads
        # (and overlaps) inside each thunk.
        path_masks_dev = jnp.asarray(path_masks)
        block_sel_dev = jnp.asarray(block_sel)
        control0_dev = jnp.asarray(
            aes_jax.pack_bit_mask(np.full(p_pad, bool(batch.party), dtype=bool))
        )

        def _chunk_thunk(idx, valid):
            # Single chunk covering the whole batch (the historical
            # default key_chunk=None): skip the identity fancy-index copy
            # of every per-key table.
            kb = batch if valid == k and idx.shape[0] == k else batch.take(idx)
            kk = kb.seeds.shape[0]
            cw_planes, ccl, ccr = kb.device_cw_arrays()
            seeds = np.broadcast_to(kb.seeds[:, None, :], (kk, p_pad, 4)).copy()
            if scalar_fast:
                out = _evaluate_points_jit(
                    jnp.asarray(seeds),
                    control0_dev,
                    path_masks_dev,
                    jnp.asarray(cw_planes),
                    jnp.asarray(ccl),
                    jnp.asarray(ccr),
                    jnp.asarray(_correction_limbs(kb.value_corrections, bits)),
                    block_sel_dev,
                    bits=bits,
                    party=batch.party,
                    xor_group=xor_group,
                    use_pallas=use_pallas,
                )
            else:
                out = _evaluate_points_codec_jit(
                    jnp.asarray(seeds),
                    control0_dev,
                    path_masks_dev,
                    jnp.asarray(cw_planes),
                    jnp.asarray(ccl),
                    jnp.asarray(ccr),
                    tuple(jnp.asarray(a) for a in kb.codec_corrections),
                    block_sel_dev,
                    spec=spec,
                    party=batch.party,
                )
            return valid, out

        thunks = (
            functools.partial(_chunk_thunk, idx, valid)
            for idx, valid in _pl.chunk_indices(k, ck)
        )

    if device_output:
        pieces = list(
            _pl.prefetch_thunks(thunks, pipe, backend=fib, op="evaluate_at_batch")
        )
        if scalar_fast:
            outs = [o[:valid, :p] for valid, o in pieces]
            out = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)
            if probe is not None:
                _integrity.verify_probe_at_points(
                    probe, points, np.asarray(out[-1]),
                    key_index=out.shape[0] - 1,
                )
                out = out[:-1]
            return out
        n_comp = len(pieces[0][1])
        out = tuple(
            (
                pieces[0][1][c][: pieces[0][0], :p]
                if len(pieces) == 1
                else jnp.concatenate(
                    [o[c][:valid, :p] for valid, o in pieces], axis=0
                )
            )
            for c in range(n_comp)
        )
        return out if spec.is_tuple else out[0]

    def _pull(item):
        valid, out = item
        if isinstance(out, tuple):
            return tuple(np.asarray(o)[:valid, :p] for o in out)
        return np.asarray(out)[:valid, :p]

    pieces = list(
        _pl.consume(
            _pl.prefetch_thunks(thunks, pipe, backend=fib, op="evaluate_at_batch"),
            _pull,
            pipe,
            backend=fib,
            op="evaluate_at_batch",
        )
    )
    if scalar_fast:
        out = np.concatenate(pieces, axis=0)
        out = faultinject.corrupt_output(out, backend=fib)
        if probe is not None:
            _integrity.verify_probe_at_points(
                probe, points, out[-1], key_index=out.shape[0] - 1,
            )
            out = out[:-1]
        return out
    n_comp = len(pieces[0])
    out = tuple(
        np.concatenate([piece[c] for piece in pieces], axis=0)
        for c in range(n_comp)
    )
    return out if spec.is_tuple else out[0]
