"""Batched hierarchical evaluation with prefix sets — the device analog of
EvaluateUntil/EvaluateNext over an EvaluationContext.

The host path (core/dpf.py:evaluate_until) replicates the reference's control
flow one value at a time — fine for small expansions, far too slow for the
experiments workload (2^20 nonzero prefixes, millions of outputs per level).
This module is the bulk path: a `BatchedContext` holds, per key batch, the
previous level's expansion (sorted prefix array + device seeds/control
bits), and `evaluate_until_batch` advances it:

  1. unique sorted prefixes -> positions into the stored prefix array
     (vectorized np.searchsorted — replaces the btree walk in
     ComputePartialEvaluations, /root/reference/dpf/distributed_point_function.cc:351-453),
  2. doubling expansion of the selected seeds on device
     (ExpandSeeds, .cc:271-349) across all keys at once,
  3. value hash + correction through the value codec (HashExpandedSeeds,
     .cc:500-524 + the correction loop in .h:776-836).

Outputs are leaf-ordered per prefix — for unique sorted `prefixes` this
equals the reference's output order. The context round-trips to/from the
wire-format EvaluationContext via to_evaluation_contexts / from the key list
(checkpoint/resume, SURVEY.md §5).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core import uint128
from ..core.dpf import DistributedPointFunction
from ..core.keys import DpfKey, EvaluationContext, PartialEvaluation
from ..utils import faultinject, integrity
from ..utils import telemetry as _tm
from ..utils.errors import InvalidArgumentError
from . import aes_jax, backend_jax, evaluator, value_codec
from . import pipeline as _pl


@dataclasses.dataclass
class BatchedContext:
    """Evaluation state of K same-parameter keys of one party."""

    dpf: DistributedPointFunction
    keys: List[DpfKey]
    previous_hierarchy_level: int = -1
    # Expansion state at previous_hierarchy_level (None before first call).
    # The stored prefix set is always "every parent's full child block", so
    # it is represented implicitly: sorted parent tree indices + the number
    # of levels each was expanded. Child prefix (p << child_levels) + leaf
    # lives at row position(p) * 2^child_levels + leaf of seeds/control —
    # positions are arithmetic, no materialized 2^L-times-larger array.
    parent_tree: Optional[np.ndarray] = None  # uint64/U128[Np] sorted unique
    child_levels: int = 0
    seeds: Optional[jnp.ndarray] = None  # uint32[K, Np << L, 4] leaf-ordered
    control: Optional[jnp.ndarray] = None  # uint32[K, Np << L] 0/1

    def _child_prefixes(self) -> Optional[list]:
        """Materialized child tree indices (python ints) — serialization."""
        if self.parent_tree is None:
            return None
        parents = (
            uint128.u128_to_ints(self.parent_tree)
            if self.parent_tree.dtype == uint128.U128
            else [int(p) for p in self.parent_tree]
        )
        n = 1 << self.child_levels
        return [(p << self.child_levels) + leaf for p in parents for leaf in range(n)]

    @classmethod
    def create(
        cls, dpf: DistributedPointFunction, keys: Sequence[DpfKey]
    ) -> "BatchedContext":
        party = keys[0].party
        for key in keys:
            dpf.validator.validate_key(key)
            if key.party != party:
                raise InvalidArgumentError(
                    "all keys in a batch must belong to one party"
                )
        return cls(dpf=dpf, keys=list(keys))

    def to_evaluation_contexts(self) -> List[EvaluationContext]:
        """Serializable per-key EvaluationContexts (checkpoint/resume)."""
        v = self.dpf.validator
        out = []
        seeds_np = None if self.seeds is None else np.asarray(self.seeds)
        prefix_ints_all = self._child_prefixes()
        for i, key in enumerate(self.keys):
            partials = []
            prefix_ints = prefix_ints_all
            if prefix_ints is not None:
                control_bits = np.asarray(self.control[i]).astype(bool)
                seed_ints = uint128.limbs_to_array(
                    seeds_np[i][: len(prefix_ints)]
                )
                for j, prefix in enumerate(prefix_ints):
                    partials.append(
                        PartialEvaluation(
                            prefix=int(prefix),
                            seed=int(seed_ints[j]),
                            control_bit=bool(control_bits[j]),
                        )
                    )
            out.append(
                EvaluationContext(
                    parameters=list(v.parameters),
                    key=key,
                    previous_hierarchy_level=self.previous_hierarchy_level,
                    partial_evaluations=partials,
                    partial_evaluations_level=self.previous_hierarchy_level,
                )
            )
        return out


@functools.partial(jax.jit, static_argnames=("pad",))
def _pad_pack_entry_jit(seeds0, control0, pad):
    """Entry-state preparation for _expand_batch in one program: pad the
    parent axis to the packed width, pack control lanes to bit masks, and
    transpose seeds to bit planes."""
    k = seeds0.shape[0]
    # Cast inside the program: an eager .astype at the call site was one
    # extra device dispatch on the first advance (bool -> uint32 entry
    # state; round-5 program-level audit).
    control0 = control0.astype(jnp.uint32)
    if pad:
        seeds0 = jnp.concatenate(
            [seeds0, jnp.zeros((k, pad, 4), jnp.uint32)], axis=1
        )
        control0 = jnp.concatenate(
            [control0, jnp.zeros((k, pad), control0.dtype)], axis=1
        )
    control_mask = _pack_mask_device(control0)  # inlines under jit
    return jax.vmap(aes_jax.pack_to_planes)(seeds0), control_mask


@jax.jit
def _pack_mask_device(bits: jnp.ndarray) -> jnp.ndarray:
    """uint32 0/1 [..., n] (n % 32 == 0) -> packed lane masks [..., n // 32]."""
    b = bits.reshape(bits.shape[:-1] + (-1, 32))
    shifts = jnp.arange(32, dtype=jnp.uint32)
    return jnp.sum(b << shifts, axis=-1, dtype=jnp.uint32)


def _as_prefix_array(prefixes: Sequence[int], log_domain: int) -> np.ndarray:
    """Unique sorted prefix array; uint64 below 64-bit domains, vectorized
    U128 (hi/lo structured, numerically ordered) at and above — python-int
    object arrays are 30-100x too slow for 2^20-prefix bookkeeping."""
    if log_domain < 64:
        if isinstance(prefixes, np.ndarray) and prefixes.dtype == uint128.U128:
            if prefixes["hi"].any():
                raise InvalidArgumentError(
                    f"Prefix out of range for a {log_domain}-bit domain"
                )
            arr = prefixes["lo"].copy()
        else:
            arr = np.asarray(prefixes, dtype=np.uint64)
    else:
        arr = uint128.u128_array(prefixes)
    # Already-strictly-sorted input (the common bulk case: callers pass the
    # previous level's np.unique output) skips the O(n log n) sort.
    sorted_strict = (
        uint128.u128_gt(arr[1:], arr[:-1]) if arr.dtype == uint128.U128
        else arr[1:] > arr[:-1]
    )
    if arr.shape[0] and bool(np.all(sorted_strict)):
        return arr
    uniq = np.unique(arr)
    if uniq.shape[0] != arr.shape[0]:
        raise InvalidArgumentError(
            "`prefixes` must be unique for the batched hierarchical path"
        )
    return uniq


@jax.jit
def _select_block_outputs_jit(outs, sel):
    """Per-prefix block selection (outs[:, sel]) as ONE device program.

    Accepts a single array or a tuple of per-component arrays (Tuple value
    types): jit flattens the pytree, so all component gathers fuse into one
    program instead of one dispatch per component."""
    return jax.tree.map(lambda o: o[:, sel], outs)


@jax.jit
def _gather_seeds_jit(seeds, control_unpacked, positions):
    sel = seeds[:, positions]  # [K, Np_pad, 4]
    ctrl = control_unpacked[:, positions]
    return sel, ctrl


def evaluate_until_batch(
    ctx: BatchedContext,
    hierarchy_level: int,
    prefixes: Sequence[int] = (),
    device_output: bool = False,
    mesh=None,
    engine: str = "device",
) -> Union[np.ndarray, Tuple[np.ndarray, ...], tuple]:
    """Advances all keys to `hierarchy_level`, expanding under `prefixes`.

    prefixes are domain indices at ctx.previous_hierarchy_level (empty iff
    first call), unique and treated as sorted. Returns values for the full
    expansion of every prefix, ordered by sorted prefix then leaf:
    uint32[K, num_outputs, lpe] limb values (tuple of per-component arrays
    for Tuple types). device_output=True returns jax arrays without host
    transfer.

    With a (keys, domain) `mesh`, the sorted parent-prefix axis shards over
    'domain' and keys over 'keys' — the domain-sharded EvaluateUntil: each
    device expands its contiguous slice of the prefix set, and the
    concatenated per-shard leaf orders form the global output with zero
    cross-shard communication.

    engine="host" runs the expansion on the native AES-NI host engine
    (core/host_eval.py) instead of the device — scalar Int/XorWrapper types
    only, and outputs come back host-format at the native element width:
    uint32[K, num_outputs] for bits <= 32, uint64[...] for 64-bit types,
    uint32[K, num_outputs, 4] limb rows for 128-bit types.
    """
    dpf, v = ctx.dpf, ctx.dpf.validator
    if hierarchy_level <= ctx.previous_hierarchy_level:
        raise InvalidArgumentError(
            "`hierarchy_level` must be greater than `ctx.previous_hierarchy_level`"
        )
    if hierarchy_level >= v.num_hierarchy_levels:
        raise InvalidArgumentError(
            "`hierarchy_level` must be less than the number of hierarchy levels"
        )
    if (ctx.previous_hierarchy_level < 0) != (len(prefixes) == 0):
        raise InvalidArgumentError(
            "`prefixes` must be empty if and only if this is the first call"
        )
    prev_lds_guard = (
        0
        if ctx.previous_hierarchy_level < 0
        else v.parameters[ctx.previous_hierarchy_level].log_domain_size
    )
    if v.parameters[hierarchy_level].log_domain_size - prev_lds_guard > 62:
        # Same bound as EvaluateUntil
        # (/root/reference/dpf/distributed_point_function.h:692-696).
        raise InvalidArgumentError(
            "Output size would be larger than 2**62. Please evaluate fewer "
            "hierarchy levels at once."
        )
    k = len(ctx.keys)
    value_type = v.parameters[hierarchy_level].value_type
    spec = value_codec.build_spec(value_type, v.blocks_needed[hierarchy_level])
    stop_level = v.hierarchy_to_tree[hierarchy_level]
    lds = v.parameters[hierarchy_level].log_domain_size
    keep_per_block = 1 << (lds - stop_level)

    batch = evaluator.KeyBatch.from_keys(dpf, ctx.keys, hierarchy_level)

    if ctx.previous_hierarchy_level < 0:
        start_level = 0
        prev_lds = 0
        tree_prefixes = None
        seeds0 = np.broadcast_to(batch.seeds[:, None, :], (k, 1, 4))
        control0 = np.full((k, 1), bool(batch.party))
        num_parents = 1
    else:
        start_level = v.hierarchy_to_tree[ctx.previous_hierarchy_level]
        prev_lds = v.parameters[ctx.previous_hierarchy_level].log_domain_size
        prefix_arr = _as_prefix_array(prefixes, prev_lds)
        positions, tree, tree_pos_of_prefix = _positions_for_prefixes(
            ctx.parent_tree, ctx.child_levels, prev_lds, start_level,
            prefix_arr, hierarchy_level,
        )
        tree_prefixes = tree
        num_parents = len(tree)
        if engine == "host":
            pos = positions.astype(np.int64)
            seeds0 = np.asarray(ctx.seeds)[:, pos]
            control0 = np.asarray(ctx.control)[:, pos]
        else:
            seeds0, control0 = _gather_seeds_jit(
                ctx.seeds, ctx.control, jnp.asarray(positions.astype(np.int64))
            )

    levels = stop_level - start_level
    if engine == "host":
        # Expansion state is only needed when a further hierarchy level
        # will resume from it; the final level can take the fused native
        # tail (no seed/control materialization at the leaf level).
        need_state = hierarchy_level < v.num_hierarchy_levels - 1
        outs, new_seeds, new_control = _expand_batch_host(
            batch, np.asarray(seeds0), np.asarray(control0), start_level,
            levels, keep_per_block, value_type, need_state=need_state,
        )
    elif mesh is not None:
        # Raw entry state on purpose: the callee passes host arrays to its
        # jit uncommitted (placement is call-setup transfer, not an eager
        # reshard program); committing via jnp.asarray here would undo it.
        outs, new_seeds, new_control = _expand_batch_sharded(
            batch, seeds0, control0,
            start_level, levels, spec, keep_per_block, mesh,
        )
    else:
        # Pad parents to whole packed words (32 lanes each).
        pad_to = max(32, -(-num_parents // 32) * 32)
        outs, new_seeds, new_control = _expand_batch(
            batch, seeds0, control0, start_level, levels, pad_to, spec,
            keep_per_block,
        )

    # When the previous level's domain index carries block bits (epb > 1),
    # distinct prefixes can share one tree index; each selects the slice
    # [block_index * outputs_per_prefix, ...) of its tree expansion —
    # mirroring the prefix_map reassembly in EvaluateUntil
    # (/root/reference/dpf/distributed_point_function.h:822-835).
    if ctx.previous_hierarchy_level >= 0:
        shift = prev_lds - start_level
        if shift:
            opp = 1 << (lds - prev_lds)  # outputs per prefix
            etp = 1 << (lds - start_level)  # elements per tree prefix
            block_index = (
                uint128.u128_and_low(prefix_arr, shift)
                if prefix_arr.dtype == uint128.U128
                else prefix_arr & np.uint64((1 << shift) - 1)
            )
            starts = tree_pos_of_prefix.astype(np.int64) * etp + block_index.astype(
                np.int64
            ) * opp
            sel = (
                starts[:, None] + np.arange(opp, dtype=np.int64)
            ).reshape(-1)
            if engine == "host":
                if isinstance(outs, tuple):
                    outs = tuple(o[:, sel] for o in outs)
                else:
                    outs = outs[:, sel]
            else:
                # Jitted gather: the eager fancy-index ran as ~7 separate
                # device programs per advance (bounds ops + gather +
                # broadcasts), found by the round-5 program-level dispatch
                # audit — ~0.5 s of pure dispatch latency per advance on
                # the 66 ms/dispatch tunnel.
                outs = _select_block_outputs_jit(outs, jnp.asarray(sel))

    # Update context state: new prefixes are (tree_prefix << levels) + leaf,
    # only when a further hierarchy level exists.
    if hierarchy_level < v.num_hierarchy_levels - 1:
        if tree_prefixes is None:
            tree_prefixes = np.zeros(1, dtype=np.uint64)
        ctx.parent_tree = tree_prefixes
        ctx.child_levels = levels
        ctx.seeds = new_seeds
        ctx.control = new_control
    else:
        ctx.parent_tree = None
        ctx.child_levels = 0
        ctx.seeds = None
        ctx.control = None
    ctx.previous_hierarchy_level = hierarchy_level

    if device_output:
        return outs
    if isinstance(outs, tuple):
        return tuple(np.asarray(o) for o in outs)
    return np.asarray(outs)


# ---------------------------------------------------------------------------
# Fused multi-level advance (heavy-hitters access pattern)
# ---------------------------------------------------------------------------


def _positions_for_prefixes(
    parent_tree, child_levels, prev_lds, start_level, prefix_arr,
    hierarchy_level,
):
    """Leaf-coordinate gather positions of `prefix_arr` (sorted unique domain
    prefixes at the previous hierarchy level) into the stored expansion
    state, plus the (tree_prefixes, tree_pos_of_prefix) bookkeeping.

    Stored state holds full child blocks of `parent_tree`: the row of child
    c is pos(c >> L) * 2^L + (c & (2^L - 1)) — one search over the
    2^L-times-smaller parent array instead of the child set. Shared by
    evaluate_until_batch and evaluate_levels_fused."""
    shift = prev_lds - start_level
    if shift:
        if prefix_arr.dtype == uint128.U128:
            shifted = uint128.u128_rshift(prefix_arr, shift)
        else:
            shifted = prefix_arr >> np.uint64(shift)
        # inverse maps each prefix to its tree position — reused by the
        # caller for the per-prefix block selection. `shifted` is sorted
        # (prefix_arr is), so unique is a linear neighbor-compare.
        if shifted.shape[0]:
            is_new = np.empty(shifted.shape[0], dtype=bool)
            is_new[0] = True
            is_new[1:] = shifted[1:] != shifted[:-1]
            tree = shifted[is_new]
            tree_pos_of_prefix = np.cumsum(is_new) - 1
        else:
            tree, tree_pos_of_prefix = np.unique(shifted, return_inverse=True)
    else:
        tree = prefix_arr
        tree_pos_of_prefix = None
    L = child_levels
    if tree.dtype == uint128.U128:
        tp = uint128.u128_rshift(tree, L)
        leaf = uint128.u128_and_low(tree, min(L, 64)).astype(np.int64)
        if parent_tree.dtype == uint128.U128:
            ppos = uint128.u128_searchsorted(parent_tree, tp)
            found = parent_tree[np.minimum(ppos, len(parent_tree) - 1)] == tp
        else:
            # uint64 parents, U128 tree: hi must be zero or the prefix
            # cannot be present (low-word equality alone would alias).
            tp64 = tp["lo"]
            ppos = np.searchsorted(parent_tree, tp64).astype(np.int64)
            found = (
                parent_tree[np.minimum(ppos, len(parent_tree) - 1)] == tp64
            ) & (tp["hi"] == 0)
    else:
        tp = tree >> np.uint64(L)
        leaf = (tree & np.uint64((1 << L) - 1)).astype(np.int64)
        ppos = np.searchsorted(parent_tree, tp).astype(np.int64)
        found = parent_tree[np.minimum(ppos, len(parent_tree) - 1)] == tp
    if (ppos >= len(parent_tree)).any() or not found.all():
        raise InvalidArgumentError(
            "Prefix not present in ctx.partial_evaluations at hierarchy "
            f"level {hierarchy_level}"
        )
    positions = ppos * (1 << L) + leaf
    return positions, tree, tree_pos_of_prefix


def _level_value_corrections(keys, v, hierarchy_level, bits):
    """uint32[K, epb, lpe] value-correction limbs at one hierarchy level."""
    stop = v.hierarchy_to_tree[hierarchy_level]
    epb = v.parameters[hierarchy_level].value_type.elements_per_block()
    k = len(keys)
    vc = np.zeros((k, epb, 4), dtype=np.uint32)
    for i, key in enumerate(keys):
        if hierarchy_level == v.num_hierarchy_levels - 1:
            corrections = key.last_level_value_correction
        else:
            corrections = key.correction_words[stop].value_correction
        for j, c in enumerate(corrections):
            vc[i, j] = uint128.to_limbs(int(c))
    return evaluator._correction_limbs(vc, bits)


def _advance_one_step(
    seeds, control, pos, cw, ccl, ccr, vc, gsel,
    levels: int, bits: int, party: int, xor_group: bool, use_pallas: bool,
):
    """ONE hierarchy-level advance — the trace-time building block shared
    by the unrolled and scan executors (they must stay numerically
    identical): gather the selected lanes, expand `levels` tree levels,
    value-hash, correct, and emit the leaf-ordered outputs through the
    precomposed `gsel` gather. Returns (out, seeds', control') with the
    state in expansion (lane) order."""
    if use_pallas:
        from . import aes_pallas

    k = seeds.shape[0]
    s = seeds[:, pos]  # [K, Np_pad, 4]
    c = control[:, pos]
    mask = _pack_mask_device(c)
    planes = jax.vmap(aes_jax.pack_to_planes)(s)
    for l in range(levels):
        if use_pallas and planes.shape[2] >= 8:
            planes, mask = aes_pallas.expand_one_level_pallas_batched(
                planes, mask, cw[:, l], ccl[:, l], ccr[:, l]
            )
        else:
            planes, mask = jax.vmap(backend_jax.expand_one_level)(
                planes, mask, cw[:, l], ccl[:, l], ccr[:, l]
            )
    if use_pallas and planes.shape[2] >= 256:
        hashed = aes_pallas.hash_value_planes_pallas_batched(planes)
    else:
        hashed = jax.vmap(backend_jax.hash_value_planes)(planes)
    blocks = jax.vmap(aes_jax.unpack_from_planes)(hashed)
    ctrlb = jax.vmap(backend_jax.unpack_mask_device)(mask)
    fn = functools.partial(
        evaluator._correct_values,
        bits=bits, party=party, xor_group=xor_group,
    )
    vals = jax.vmap(fn)(blocks, ctrlb, vc)  # [K, lanes, epb, lpe]
    flat = vals.reshape(k, -1, vals.shape[-1])
    out = flat[:, gsel]
    new_seeds = jax.vmap(aes_jax.unpack_from_planes)(planes)
    new_control = jax.vmap(backend_jax.unpack_mask_device)(mask)
    return out, new_seeds, new_control


@functools.partial(
    jax.jit,
    static_argnames=(
        "meta", "bits", "party", "xor_group", "use_pallas", "emit_state",
    ),
)
def _fused_advance_jit(
    seeds,  # uint32[K, lanes0, 4] entry state (leaf order)
    control,  # uint32[K, lanes0] 0/1
    step_args,  # per step: (pos, cw, ccl, ccr, vc, gsel)
    state_order,  # int64[final lanes] leaf-order gather, or None
    meta: tuple,  # per step: tree levels to expand (static)
    bits: int,
    party: int,
    xor_group: bool,
    use_pallas: bool,
    emit_state: bool,
):
    """G hierarchy-level advances in ONE program: per step, gather the
    selected lanes, expand `meta[d]` tree levels, value-hash, correct, and
    emit the leaf-ordered outputs through a single precomposed gather —
    the multi-level fusion of evaluate_until_batch's device path. All
    index tables (lane gathers `pos`, output gathers `gsel`) are computed
    on the host with lane-order composition, so the program contains no
    reorder dispatches at all; intermediate state stays in expansion (lane)
    order and only the exit state is leaf-ordered (for the resumable
    BatchedContext)."""
    outs = []
    for d, (pos, cw, ccl, ccr, vc, gsel) in enumerate(step_args):
        out, seeds, control = _advance_one_step(
            seeds, control, pos, cw, ccl, ccr, vc, gsel,
            meta[d], bits, party, xor_group, use_pallas,
        )
        outs.append(out)
    if emit_state:
        # Exit state leaf-ordered (the resumable BatchedContext contract).
        seeds = seeds[:, state_order]
        control = control[:, state_order]
    # Non-final groups return lane-order state: the next group's first
    # gather is precomposed with this group's lane order on the host.
    return tuple(outs), seeds, control


@functools.partial(
    jax.jit,
    static_argnames=(
        "levels", "bits", "party", "xor_group", "use_pallas", "emit_state",
        "out_lens",
    ),
)
def _fused_advance_scan_jit(
    seeds,  # uint32[K, L_in, 4] entry state
    control,  # uint32[K, L_in] 0/1
    pos,  # int64[G, pad_to] per-step gather positions (padded)
    cw,  # uint32[G, K, levels, 128]
    ccl,  # uint32[G, K, levels]
    ccr,  # uint32[G, K, levels]
    vc,  # uint32[G, K, epb, lpe]
    gsel,  # int64[G, out_max] output gathers (padded with 0)
    state_order,  # int64[...] leaf-order exit gather, or None
    levels: int,
    bits: int,
    party: int,
    xor_group: bool,
    use_pallas: bool,
    emit_state: bool,
    out_lens: tuple,
):
    """Scan form of `_fused_advance_jit` for G steps that all expand the
    SAME number of tree levels at the SAME padded width: the per-step AES
    circuits trace once (via the shared `_advance_one_step`) and
    `lax.scan` drives them, so a 127-step heavy-hitters plan compiles ~G
    smaller circuits instead of ~2G per group. The scan carry is the
    lane-order state at exactly the chunk's expansion width
    (pad_to << levels); an entry state of a different width is handled
    outside the scan — padded up when narrower, or consumed by running
    step 0 unrolled when wider (so a shrinking prefix set doesn't drag
    the wide state through every iteration)."""
    k = seeds.shape[0]
    pad_to = pos.shape[1]
    exp_w = pad_to << levels

    def body(carry, xs):
        seeds, control = carry
        pos_d, cw_d, ccl_d, ccr_d, vc_d, gsel_d = xs
        out, new_seeds, new_control = _advance_one_step(
            seeds, control, pos_d, cw_d, ccl_d, ccr_d, vc_d, gsel_d,
            levels, bits, party, xor_group, use_pallas,
        )
        return (new_seeds, new_control), out

    out0 = None
    if seeds.shape[1] > exp_w:
        # Wide entry state: run step 0 unrolled; the carry then starts at
        # the chunk's own width.
        out0, seeds, control = _advance_one_step(
            seeds, control, pos[0], cw[0], ccl[0], ccr[0], vc[0], gsel[0],
            levels, bits, party, xor_group, use_pallas,
        )
        pos, cw, ccl, ccr, vc, gsel = (
            a[1:] for a in (pos, cw, ccl, ccr, vc, gsel)
        )
    elif seeds.shape[1] < exp_w:
        seeds = jnp.concatenate(
            [seeds, jnp.zeros((k, exp_w - seeds.shape[1], 4), jnp.uint32)],
            axis=1,
        )
        control = jnp.concatenate(
            [control, jnp.zeros((k, exp_w - control.shape[1]), jnp.uint32)],
            axis=1,
        )

    (seeds, control), outs = jax.lax.scan(
        body, (seeds, control), (pos, cw, ccl, ccr, vc, gsel)
    )
    if out0 is not None:
        outs = jnp.concatenate([out0[None], outs], axis=0)
    # Per-step trims INSIDE the program: each step's real output length is
    # static, and doing the slicing here costs nothing, whereas slicing
    # the returned stack outside the jit dispatches ~2 device programs
    # per step — ~8 s of pure latency for a 127-step plan through a
    # 66 ms-dispatch link (r4 profile).
    trimmed = tuple(outs[i, :, :n] for i, n in enumerate(out_lens))
    if emit_state:
        seeds = seeds[:, state_order]
        control = control[:, state_order]
    return trimmed, seeds, control


@dataclasses.dataclass
class PreparedLevelsPlan:
    """Key-independent compilation of an `evaluate_levels_fused` plan.

    The virtual context walk, the scan/unroll chunk grouping, and every
    gather/selection table are composed once and held DEVICE-RESIDENT for
    reuse across key batches — the aggregation-server shape (one global
    prefix plan, many client key batches; the reference's analog walks its
    per-key btree inside EvaluateUntil each time,
    /root/reference/dpf/distributed_point_function.cc:351-453). Profiled on
    the 128-level heavy-hitters plan the table work is ~0.3 s/call of host
    time, and through a high-latency link the re-upload of ~tens of MB of
    index tables dominates; both are paid once here.

    Only valid for contexts whose state matches the one captured at
    preparation (`evaluate_levels_fused` verifies); value corrections and
    correction words stay per-call (they are key material).
    """

    parameters: tuple  # validator parameter list captured for compat check
    plan_levels: tuple  # hierarchy level per step (vc / cw slicing)
    bits: int
    xor_group: bool
    final_level: int
    emit_state: bool
    # Expected entry state.
    start_prev_level: int
    start_parent_tree: Optional[np.ndarray]
    start_child_levels: int
    # Virtual exit state (becomes the context state after execution).
    end_parent_tree: Optional[np.ndarray]
    end_child_levels: int
    # Per-step key-independent tables: (pos_pad_dev, levels_d, gsel_dev,
    # start_level).
    steps: list
    # (kind, [step indices], scan_extras) — scan_extras is
    # (pos_stack_dev, gsel_pad_dev, out_lens, levels_d) for "scan" chunks,
    # None for "unroll" chunks.
    chunks: list
    final_order_dev: Optional[jnp.ndarray]  # state reorder for emit
    # Execution strategy the plan was composed for: "fused" (the grouped
    # scan/unroll chunks above) or "hierkernel" (the single-program prefix
    # windows below; `steps`/`chunks` are then empty).
    mode: str = "fused"
    hier_windows: Optional[list] = None  # list[_HierWindow]
    hier_keep: int = 1  # uniform per-slot element count across windows


def bitwise_hierarchy_plan(levels: int, finals) -> list:
    """`evaluate_levels_fused` plan for the heavy-hitters access pattern:
    one hierarchy level per bit, entry i evaluating the unique i-bit
    prefixes of the final-level leaf set `finals` (python ints) —
    [(0, []), (1, P_1), ..., (levels-1, P_{levels-1})] with P_i the
    sorted unique `{f >> (levels - i)}`. Prefix arrays go u128 above the
    63-bit bookkeeping boundary. ONE implementation for the bench-shaped
    plans the device check (utils/integrity), tools/check_device.py and
    the test suites all build — the plan convention (prefixes at the
    PREVIOUS entry's domain) must not drift between them."""
    finals = sorted({int(f) for f in finals})
    plan = [(0, [])]
    for i in range(1, levels):
        p = sorted({f >> (levels - i) for f in finals})
        if i >= 64:
            # p is already sorted-unique; u128_array preserves order
            # (U128's (hi, lo) field order sorts numerically).
            plan.append((i, uint128.u128_array(p)))
        else:
            plan.append((i, np.array(p, dtype=np.uint64)))
    return plan


def candidate_children(
    prefixes, prev_log_domain: int, log_domain: int
) -> np.ndarray:
    """Domain indices of every child candidate an advance from
    `prev_log_domain` to `log_domain` expands, in the exact column order
    `evaluate_until_batch` emits its outputs (sorted prefix, then leaf) —
    candidate i of the advance's [K, n] output array is domain value
    ``candidate_children(...)[i]``. An empty prefix set (the first
    advance) covers the whole level-`log_domain` domain. This is the one
    shared candidate↔output mapping for the heavy-hitters pruning loop
    (the batch demo and the streaming window manager, ISSUE 15); uint64
    bookkeeping only, so domains stay below the 63-bit prefix boundary.
    """
    if log_domain > 62:
        raise InvalidArgumentError(
            "candidate_children covers uint64 bookkeeping domains only "
            f"(log_domain {log_domain} > 62)"
        )
    if prev_log_domain >= log_domain:
        raise InvalidArgumentError(
            "`log_domain` must exceed `prev_log_domain` (an advance "
            "always descends)"
        )
    prefixes = np.asarray(sorted(int(p) for p in prefixes), dtype=np.uint64)
    if prefixes.size == 0:
        return np.arange(1 << log_domain, dtype=np.uint64)
    d = log_domain - prev_log_domain
    base = np.repeat(prefixes, 1 << d)
    child = np.tile(np.arange(1 << d, dtype=np.uint64), prefixes.size)
    return (base << np.uint64(d)) + child


def draw_random_finals(levels: int, n: int, rng) -> list:
    """`n` uniform `levels`-bit leaf indices (python ints) for a
    heavy-hitters workload — composed from 32-bit words above the int64
    range, so the device check and the test suites draw the same leaf
    distribution at any depth (feeds `bitwise_hierarchy_plan`)."""
    if levels <= 63:
        return [int(x) for x in rng.integers(0, 1 << levels, size=n)]
    nwords = -(-levels // 32)
    words = rng.integers(0, 1 << 32, size=(n, nwords), dtype=np.uint64)
    mask = (1 << levels) - 1
    return [
        sum(int(w) << (32 * j) for j, w in enumerate(row)) & mask
        for row in words
    ]


# ---------------------------------------------------------------------------
# Hierarchical megakernel windows (mode="hierkernel", ISSUE 5)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _HierWindow:
    """One prefix window of a hierkernel plan: the key-independent tables
    of ONE pallas_call (aes_pallas.hier_megakernel_pallas_batched),
    composed on the host and held device-resident by the prepared plan.

    Lane layout: the window's plan steps become consecutive SEGMENTS; the
    segment of step t holds one lane per tree node of that step's full
    child-block expansion, in leaf (sorted tree index) order — so the
    last segment IS the resumable context state and the next window's
    entry gather indexes it directly. Each lane carries its window-entry
    ancestor position (`entry_pos`, gathered outside the kernel in the
    same jit) and its packed path bits from that ancestor; each step's
    value capture is gated by the pre-ANDed one-hot select-mask rows."""

    plan: "evaluator.HierkernelPlan"
    captures: tuple  # [depth + 1] capture-slot index per depth / -1
    depth: int  # tree levels this window walks
    start_level: int  # absolute tree level of the window entry state
    entry_pos_dev: jnp.ndarray  # int64[Wp * 32] entry-state lane gather
    path_dev: jnp.ndarray  # uint32[depth, Wp] packed per-lane path bits
    sel_dev: jnp.ndarray  # uint32[n_rows, Wp] packed slot-lane bits
    gsels_dev: tuple  # per step: int64[n_outputs] output gathers
    slot_steps: tuple  # per slot: global plan-step index (vc lookup)
    slot_keeps: tuple  # per slot: that level's elements per block
    state_base: int  # exit-state lane offset (last segment)
    state_len: int  # REAL exit-state lane count (the context width)
    state_cap: int  # uniform exit-slice width — every window of a plan
    #                 emits [K, state_cap, 4] so equal-shape windows share
    #                 ONE compiled program even when prefix counts drift


def _compose_hier_windows(raw, group: int, bits: int, entry_width: int):
    """Partitions the raw virtual-walk steps into prefix windows of up to
    `group` consecutive advances and composes each window's kernel
    tables. Raises NotImplementedError for plan shapes the hierkernel
    cannot express (the env-default caller falls back to "fused" with an
    engine-downgrade event; an explicit mode="hierkernel" propagates)."""
    lpe = bits // 32
    keep_g = max(r[4] for r in raw)
    if keep_g * lpe > 4:
        raise NotImplementedError(
            "hierkernel capture rows exceed one 128-bit block "
            f"(keep={keep_g} x lpe={lpe})"
        )
    idx_windows = [
        list(range(i, min(i + group, len(raw))))
        for i in range(0, len(raw), group)
    ]
    # Pass A — per-window lane bookkeeping: chain each step's leaf-order
    # expansion back to its window-entry ancestor + relative path bits.
    win_host = []
    for idx in idx_windows:
        depth = sum(raw[t][2] for t in idx)
        if depth < 1:
            raise NotImplementedError(
                "hierkernel window advances zero tree levels (hierarchy "
                "levels sharing one tree depth); use mode='fused'"
            )
        if depth > 62:
            raise NotImplementedError(
                f"hierkernel window depth {depth} exceeds 62 relative path "
                "bits; lower `group`"
            )
        prev = None
        cum_d = 0
        base = 0
        segs = []  # (base, n_t, D_t, entry_pos, rel_path, step index)
        for s, t in enumerate(idx):
            positions, num_parents, levels_d, _sel, _keep, _epb, _start, _h = raw[t]
            if levels_d == 0 and s > 0:
                raise NotImplementedError(
                    "hierkernel requires every advance after a window's "
                    "first to deepen the tree (two hierarchy levels share "
                    "a capture depth); use mode='fused'"
                )
            if prev is None:
                par_entry = positions.astype(np.int64)
                par_path = np.zeros(num_parents, dtype=np.uint64)
            else:
                pe, pp = prev
                par_entry = pe[positions]
                par_path = pp[positions]
            cum_d += levels_d
            nleaf = 1 << levels_d
            ent = np.repeat(par_entry, nleaf)
            pth = (np.repeat(par_path, nleaf) << np.uint64(levels_d)) | np.tile(
                np.arange(nleaf, dtype=np.uint64), num_parents
            )
            n_t = num_parents * nleaf
            segs.append((base, n_t, cum_d, ent, pth, t))
            base += n_t
            prev = (ent, pth)
        win_host.append((idx, depth, segs, base))
    # Uniform widths across windows: equal-shape windows then share ONE
    # compiled kernel config (the compile-budget discipline the walk
    # megakernel established) — early windows pay padded lanes, which
    # compute garbage on entry lane 0 and are never selected. The exit
    # state is emitted at one uniform `state_cap` width for the same
    # reason (real prefix counts drift per level; the executor pads the
    # plan's entry state up to state_cap on the host, and the resumable
    # context tolerates trailing pad lanes — every consumer indexes
    # through parent_tree, which stays exact).
    state_cap = max(
        [entry_width] + [wh[2][-1][1] for wh in win_host]
    )
    max_lanes = max(
        max(wh[3], wh[2][-1][0] + state_cap) for wh in win_host
    )
    windows = []
    for (idx, depth, segs, n_win) in win_host:
        n_rows = len(idx) * keep_g
        kplan = evaluator.plan_hierkernel(max_lanes, depth, n_rows, lpe, keep_g)
        wl = kplan.padded_words * 32
        entry_pos = np.zeros(wl, dtype=np.int64)
        rel_path = np.zeros(wl, dtype=np.uint64)
        lane_depth = np.zeros(wl, dtype=np.int64)
        captures = [-1] * (depth + 1)
        sel_bool = np.zeros((n_rows, wl), dtype=bool)
        gsels = []
        for s, (b, n_t, d_t, ent, pth, t) in enumerate(segs):
            entry_pos[b : b + n_t] = ent
            rel_path[b : b + n_t] = pth
            lane_depth[b : b + n_t] = d_t
            assert captures[d_t] == -1, (captures, d_t)
            captures[d_t] = s
            keep_t = raw[t][4]
            sel_bool[s * keep_g : s * keep_g + keep_t, b : b + n_t] = True
            sel = raw[t][3]
            gsels.append(
                jnp.asarray((b + sel // keep_t) * keep_g + sel % keep_t)
            )
        path_bits = np.zeros((depth, wl), dtype=bool)
        for lvl in range(depth):
            sh = lane_depth - 1 - lvl
            valid = sh >= 0
            path_bits[lvl, valid] = (
                (rel_path[valid] >> sh[valid].astype(np.uint64)) & 1
            ).astype(bool)
        last_b, last_n = segs[-1][0], segs[-1][1]
        windows.append(
            _HierWindow(
                plan=kplan,
                captures=tuple(captures),
                depth=depth,
                start_level=raw[idx[0]][6],
                entry_pos_dev=jnp.asarray(entry_pos),
                path_dev=jnp.asarray(aes_jax.pack_bit_mask(path_bits)),
                sel_dev=jnp.asarray(aes_jax.pack_bit_mask(sel_bool)),
                gsels_dev=tuple(gsels),
                slot_steps=tuple(idx),
                slot_keeps=tuple(raw[t][4] for t in idx),
                state_base=int(last_b),
                state_len=int(last_n),
                state_cap=int(state_cap),
            )
        )
    return windows, keep_g


@functools.partial(
    jax.jit,
    static_argnames=(
        "plan", "bits", "party", "xor_group", "keep", "captures",
        "state_base", "state_cap", "interpret",
    ),
)
def _hier_window_jit(
    seeds,  # uint32[K, M, 4] window-entry state (leaf order)
    control,  # uint32[K, M] 0/1
    entry_pos,  # int64[Wp * 32] per-lane ancestor gather (pad -> 0)
    path_masks,  # uint32[depth, Wp]
    cw,  # uint32[K, depth, 128]
    ccl,  # uint32[K, depth]
    ccr,  # uint32[K, depth]
    corr,  # uint32[K, n_rows, lpe]
    sel_bits,  # uint32[n_rows, Wp]
    gsels,  # tuple of int64[n_outputs] per plan step
    plan,
    bits: int,
    party: int,
    xor_group: bool,
    keep: int,
    captures,
    state_base: int,
    state_cap: int,
    interpret: bool,
):
    """ONE program per (key chunk x prefix window): the entry-ancestor
    gather + plane pack, the hier megakernel pallas_call (every level of
    the window walked in-register, every level's values captured through
    the select-mask rows), the value-row transpose, the per-step output
    gathers, and the leaf-ordered exit-state unpack — no per-level
    dispatch, no per-level HBM round trip of the prefix state."""
    from . import aes_pallas

    k = seeds.shape[0]
    lpe = bits // 32
    s = seeds.astype(jnp.uint32)[:, entry_pos]  # [K, Wp*32, 4]
    c = control.astype(jnp.uint32)[:, entry_pos]
    planes = jax.vmap(aes_jax.pack_to_planes)(s)
    mask = _pack_mask_device(c)
    vals, xplanes, xctrl = aes_pallas.hier_megakernel_pallas_batched(
        planes,
        mask,
        path_masks,
        cw,
        ccl,
        ccr,
        corr,
        sel_bits,
        plan=plan,
        bits=bits,
        party=party,
        xor_group=xor_group,
        keep=keep,
        captures=captures,
        interpret=interpret,
    )
    wp = plan.padded_words
    # Value rows -> flat [K, Wp*32*keep, lpe]: row (e*lpe+l)*32+i word w
    # holds limb l of element e of lane 32w+i, so the flat element index
    # factors as lane * keep + e — the space the gsel tables index.
    flat = (
        vals.reshape(k, keep, lpe, 32, wp)
        .transpose(0, 4, 3, 1, 2)
        .reshape(k, wp * 32 * keep, lpe)
    )
    outs = tuple(flat[:, g] for g in gsels)
    # Exit state at the plan-uniform state_cap width (trailing pad lanes
    # are garbage the next gather / the context never indexes): ALWAYS
    # emitted, so the final window shares the middle windows' compiled
    # program instead of tracing its own state-free variant.
    xseeds = jax.vmap(aes_jax.unpack_from_planes)(xplanes)[
        :, state_base : state_base + state_cap
    ]
    xc = jax.vmap(backend_jax.unpack_mask_device)(xctrl)[
        :, state_base : state_base + state_cap
    ]
    return outs, xseeds, xc


def _hier_corr_rows(win: _HierWindow, vcs, k: int, keep_g: int, lpe: int):
    """uint32[K, n_rows, lpe] per-(slot, element) correction limbs of one
    window — the per-call key material next to the prepared tables."""
    n_rows = len(win.slot_steps) * keep_g
    corr = np.zeros((k, n_rows, lpe), dtype=np.uint32)
    for s, (t, keep_t) in enumerate(zip(win.slot_steps, win.slot_keeps)):
        corr[:, s * keep_g : s * keep_g + keep_t] = vcs[t][:, :keep_t]
    return corr


def _emit_hier_downgrade(frm: str, to: str, reason: str, **data) -> None:
    """Structured engine-downgrade event for the hierarchical path's
    silent fallbacks (hierkernel -> fused, the fused path's narrow-width
    pallas -> XLA) — the dcf narrow-batch pattern: device A/B runs must
    be able to tell "kernel lost" from "kernel never ran"."""
    integrity.emit_event(
        "engine-downgrade",
        f"hierarchical.evaluate_levels_fused: {frm} -> {to}: {reason}",
        "pallas",  # every edge here downgrades away from a Pallas engine
        path="hierarchical",
        reason=reason,
        downgraded_to=to,
        **{"from": frm},
        **data,
    )


def _resolve_hier_prepare(ctx, plan, group, mode, mesh, use_pallas):
    """Resolves the hierarchical-advance strategy for one call and builds
    the prepared plan — an explicit mode wins (configs the hierkernel
    cannot handle raise); the DPF_TPU_HIERKERNEL env default quietly
    keeps "fused" for them with an engine-downgrade event, because a
    process-wide A/B knob must never turn a previously working call into
    an error (the _resolve_walk_mode contract)."""
    explicit = mode is not None
    if mode is None:
        mode = evaluator._hier_mode_default()
    if mode not in ("fused", "hierkernel"):
        raise InvalidArgumentError(
            f"mode must be 'fused' or 'hierkernel', got {mode!r}"
        )
    if mode == "hierkernel":
        reason, source = None, "downgrade"
        if mesh is not None:
            if explicit:
                raise InvalidArgumentError(
                    "mode='hierkernel' does not support mesh sharding; "
                    "use mode='fused'"
                )
            reason = "mesh sharding is fused-only"
        elif use_pallas is False and not explicit:
            # The env A/B default yields to an explicit engine knob (a
            # call qualifying the XLA engine must not silently get a
            # Mosaic kernel); an EXPLICIT mode still wins over it. The
            # decision source matches _resolve_walk_mode's taxonomy for
            # the identical situation: a caller-pinned engine, not a
            # capability downgrade.
            reason, source = "use_pallas=False pins the XLA engine", "pinned-xla"
        if reason is None:
            try:
                prepared = prepare_levels_fused(
                    ctx, plan, group, mode="hierkernel"
                )
            except NotImplementedError as e:
                if explicit:
                    raise
                reason = str(e)
            else:
                _tm.decision(
                    "evaluate_levels_fused", "hierkernel",
                    "explicit" if explicit else "env-default",
                )
                return "hierkernel", prepared
        _emit_hier_downgrade(
            "hierkernel", "fused", reason, plan_steps=len(plan)
        )
        _tm.decision(
            "evaluate_levels_fused", "fused", source, reason=reason
        )
    else:
        _tm.decision(
            "evaluate_levels_fused", "fused",
            "explicit" if explicit else "env-default",
        )
    return "fused", prepare_levels_fused(ctx, plan, group)


def prepare_levels_fused(
    ctx: BatchedContext,
    plan: Sequence[Tuple[int, Sequence[int]]],
    group: int = 16,
    mode: Optional[str] = None,
) -> PreparedLevelsPlan:
    """Builds the key-independent part of `evaluate_levels_fused` for
    `plan` against ctx's CURRENT state (the context is not advanced).
    The returned plan replays against any context of the same DPF
    parameters in the same state — pass it to `evaluate_levels_fused` in
    place of `plan`.

    `mode` selects the execution strategy the plan is composed for:
    "fused" (default — the grouped scan/unroll advance chunks) or
    "hierkernel" (the single-program prefix windows of the hierarchical
    megakernel, ISSUE 5: up to `group` consecutive advances per
    pallas_call; raises NotImplementedError for plan shapes the kernel
    cannot express — sub-32-bit value widths, hierarchy levels sharing
    one tree depth past a window's first step, window depths over 62)."""
    from ..core.value_types import Int, XorWrapper

    v = ctx.dpf.validator
    if mode is None:
        mode = "fused"
    if mode not in ("fused", "hierkernel"):
        raise InvalidArgumentError(
            f"mode must be 'fused' or 'hierkernel', got {mode!r}"
        )
    if group < 1:
        # group feeds the greedy chunking loop below; 0 would make it spin
        # forever (BENCH_HH_GROUP / CHECK_HH_GROUP env vars reach here).
        raise InvalidArgumentError("`group` must be >= 1")
    if not plan:
        raise InvalidArgumentError("`plan` must be non-empty")
    for (h, _) in plan:
        if not (0 <= h < v.num_hierarchy_levels):
            raise InvalidArgumentError(
                "`hierarchy_level` must be less than the number of "
                "hierarchy levels"
            )
        vt = v.parameters[h].value_type
        if not isinstance(vt, (Int, XorWrapper)) or v.blocks_needed[h] != 1:
            raise InvalidArgumentError(
                "evaluate_levels_fused supports scalar Int/XorWrapper "
                "outputs; use evaluate_until_batch for codec value types"
            )
    bits, xor_group = evaluator._value_kind(v.parameters[plan[-1][0]].value_type)
    if mode == "hierkernel" and bits % 32:
        # Decidable before the O(levels x prefixes) pass-1 walk: the
        # env-default fallback path must not pay the whole bookkeeping
        # twice for the common sub-word-value case.
        raise NotImplementedError(
            "hierkernel handles 32-bit-multiple value widths, got "
            f"{bits}; use mode='fused' for sub-word outputs"
        )

    # Pass 1 — virtual context walk (host): raw per-step tables, BEFORE
    # lane-order composition (which depends on each step's padded width,
    # chosen by the grouping pass below).
    start_prev_level = ctx.previous_hierarchy_level
    start_parent_tree = ctx.parent_tree
    start_child_levels = ctx.child_levels
    prev_level = start_prev_level
    parent_tree = start_parent_tree
    child_levels = start_child_levels
    raw = []  # (positions, num_parents, levels_d, sel, keep, epb, start, h)
    for (h, prefixes) in plan:
        if h <= prev_level:
            raise InvalidArgumentError(
                "`plan` hierarchy levels must be strictly increasing"
            )
        if (prev_level < 0) != (len(prefixes) == 0):
            raise InvalidArgumentError(
                "`prefixes` must be empty iff advancing a fresh context"
            )
        stop_level = v.hierarchy_to_tree[h]
        lds = v.parameters[h].log_domain_size
        keep = 1 << (lds - stop_level)
        b_h, xg_h = evaluator._value_kind(v.parameters[h].value_type)
        if (b_h, xg_h) != (bits, xor_group):
            raise InvalidArgumentError(
                "evaluate_levels_fused requires one value kind across the "
                "plan's hierarchy levels"
            )
        if prev_level < 0:
            start_level = 0
            positions = np.zeros(1, dtype=np.int64)
            tree = None
            tree_pos_of_prefix = None
            prefix_arr = None
            prev_lds = 0
        else:
            start_level = v.hierarchy_to_tree[prev_level]
            prev_lds = v.parameters[prev_level].log_domain_size
            prefix_arr = _as_prefix_array(prefixes, prev_lds)
            positions, tree, tree_pos_of_prefix = _positions_for_prefixes(
                parent_tree, child_levels, prev_lds, start_level,
                prefix_arr, h,
            )
        levels_d = stop_level - start_level
        if lds - (prev_lds if prev_level >= 0 else 0) > 62:
            raise InvalidArgumentError(
                "Output size would be larger than 2**62. Please evaluate "
                "fewer hierarchy levels at once."
            )
        num_parents = positions.shape[0]
        epb = v.parameters[h].value_type.elements_per_block()
        # Output selection in this level's element space (block-bit
        # sharing across tree prefixes); composed with the lane order in
        # pass 2: element E -> lane order_d[E // keep], flat = lane * epb
        # + E % keep.
        if prev_level >= 0 and (prev_lds - start_level):
            shift = prev_lds - start_level
            opp = 1 << (lds - prev_lds)
            etp = 1 << (lds - start_level)
            block_index = (
                uint128.u128_and_low(prefix_arr, shift)
                if prefix_arr.dtype == uint128.U128
                else prefix_arr & np.uint64((1 << shift) - 1)
            )
            starts = tree_pos_of_prefix.astype(np.int64) * etp + (
                block_index.astype(np.int64) * opp
            )
            sel = (starts[:, None] + np.arange(opp, dtype=np.int64)).reshape(-1)
        else:
            sel = np.arange((num_parents << levels_d) * keep, dtype=np.int64)
        raw.append(
            (positions, num_parents, levels_d, sel, keep, epb, start_level, h)
        )
        # Advance the virtual context.
        prev_level = h
        parent_tree = (
            tree if tree is not None else np.zeros(1, dtype=np.uint64)
        )
        child_levels = levels_d

    if mode == "hierkernel":
        final_level = plan[-1][0]
        emit_state = final_level < v.num_hierarchy_levels - 1
        entry_width = (
            1
            if start_parent_tree is None
            else len(start_parent_tree) << start_child_levels
        )
        windows, keep_g = _compose_hier_windows(raw, group, bits, entry_width)
        return PreparedLevelsPlan(
            parameters=tuple(v.parameters),
            plan_levels=tuple(h for (*_, h) in raw),
            bits=bits,
            xor_group=xor_group,
            final_level=final_level,
            emit_state=emit_state,
            start_prev_level=start_prev_level,
            start_parent_tree=start_parent_tree,
            start_child_levels=start_child_levels,
            end_parent_tree=parent_tree if emit_state else None,
            end_child_levels=child_levels if emit_state else 0,
            steps=[],
            chunks=[],
            final_order_dev=None,
            mode="hierkernel",
            hier_windows=windows,
            hier_keep=keep_g,
        )

    # Grouping: greedy runs capped at `group`. A run of >= 4 steps with one
    # common levels_d becomes a SCAN chunk — padded to one width so the AES
    # circuits trace ONCE per chunk via lax.scan instead of once per level
    # (compile time is the practical bound on deep hierarchies; the
    # heavy-hitters plan is ~127 consecutive 1-level advances).
    chunks = []  # (kind, [step indices], pad_to or None)
    i = 0
    while i < len(raw):
        lv = raw[i][2]
        j = i
        while (
            j < len(raw) and raw[j][2] == lv and j - i < group
        ):
            j += 1
        idx = list(range(i, j))
        if len(idx) >= 4:
            pad_to = max(
                max(32, -(-raw[t][1] // 32) * 32) for t in idx
            )
            chunks.append(("scan", idx, pad_to))
        else:
            chunks.append(("unroll", idx, None))
        i = j
    # Merge adjacent unroll chunks up to `group` (runs shorter than the
    # scan threshold should still share a program).
    merged_chunks = []
    for kind, idx, pad in chunks:
        if (
            kind == "unroll"
            and merged_chunks
            and merged_chunks[-1][0] == "unroll"
            and len(merged_chunks[-1][1]) + len(idx) <= group
        ):
            merged_chunks[-1] = ("unroll", merged_chunks[-1][1] + idx, None)
        else:
            merged_chunks.append((kind, idx, pad))
    chunks = merged_chunks

    # Pass 2 — compose gather positions with each previous step's lane
    # order and build the padded tables (host arrays here; the device
    # upload happens once per chunk below).
    prev_order = None
    steps_host = []  # (pos_pad, levels_d, gsel, start_level)
    pad_by_step = {}
    for kind, idx, pad in chunks:
        for t in idx:
            pad_by_step[t] = pad
    for t, (positions, num_parents, levels_d, sel, keep, epb, start, h) in (
        enumerate(raw)
    ):
        if prev_order is not None:
            positions = prev_order[positions]
        pad_to = pad_by_step[t] or max(32, -(-num_parents // 32) * 32)
        pos_pad = np.zeros(pad_to, dtype=np.int64)
        pos_pad[:num_parents] = positions
        order_d = backend_jax.expansion_output_order(
            num_parents, pad_to, levels_d
        )
        gsel = order_d[sel // keep] * epb + (sel % keep)
        steps_host.append((pos_pad, levels_d, gsel, start))
        prev_order = order_d

    final_level = plan[-1][0]
    emit_state = final_level < v.num_hierarchy_levels - 1
    # Device upload, once: scan chunks hold stacked tables; unroll steps
    # hold per-step tables. Steps inside scan chunks keep host metadata
    # only (their tables live in the stack).
    steps = []
    scan_steps = set()
    for kind, idx, pad in chunks:
        if kind == "scan":
            scan_steps.update(idx)
    for t, (pos_pad, levels_d, gsel, start) in enumerate(steps_host):
        if t in scan_steps:
            steps.append((None, levels_d, None, start))
        else:
            steps.append(
                (jnp.asarray(pos_pad), levels_d, jnp.asarray(gsel), start)
            )
    dev_chunks = []
    for kind, idx, pad in chunks:
        if kind == "scan":
            lv = steps_host[idx[0]][1]
            out_lens = [int(steps_host[t][2].shape[0]) for t in idx]
            out_max = max(out_lens)
            gsel_pad = np.zeros((len(idx), out_max), dtype=np.int64)
            for gi, t in enumerate(idx):
                gsel_pad[gi, : out_lens[gi]] = steps_host[t][2]
            pos_stack = np.stack([steps_host[t][0] for t in idx])
            dev_chunks.append(
                (
                    kind,
                    idx,
                    (
                        jnp.asarray(pos_stack),
                        jnp.asarray(gsel_pad),
                        out_lens,
                        lv,
                    ),
                )
            )
        else:
            dev_chunks.append((kind, idx, None))

    return PreparedLevelsPlan(
        parameters=tuple(v.parameters),
        plan_levels=tuple(h for (*_, h) in raw),
        bits=bits,
        xor_group=xor_group,
        final_level=final_level,
        emit_state=emit_state,
        start_prev_level=start_prev_level,
        start_parent_tree=start_parent_tree,
        start_child_levels=start_child_levels,
        end_parent_tree=parent_tree if emit_state else None,
        end_child_levels=child_levels if emit_state else 0,
        steps=steps,
        chunks=dev_chunks,
        final_order_dev=(
            jnp.asarray(prev_order) if emit_state else None
        ),
    )


def _evaluate_hierkernel(
    ctx: BatchedContext,
    prepared: PreparedLevelsPlan,
    device_output: bool,
    key_chunk: Optional[int],
    pipeline: Optional[bool],
) -> list:
    """Executes a hierkernel-mode prepared plan: per key chunk, ONE
    program per prefix window (`_hier_window_jit` — the entry gather,
    the hier megakernel pallas_call and every per-level output selection
    fused), windows chained through the leaf-ordered exit state, chunks
    driven through the pipelined executor (ops/pipeline.py) so chunk
    N+1's key-table pack/upload overlaps chunk N's windows."""
    import jax

    dpf, v = ctx.dpf, ctx.dpf.validator
    k = len(ctx.keys)
    bits, xor_group = prepared.bits, prepared.xor_group
    lpe = bits // 32
    keep_g = prepared.hier_keep
    windows = prepared.hier_windows
    emit_state = prepared.emit_state
    n_steps = len(prepared.plan_levels)
    batch = evaluator.KeyBatch.from_keys(dpf, ctx.keys, prepared.final_level)
    cw_all, ccl_all, ccr_all = batch.device_cw_arrays(0)
    vcs = [
        _level_value_corrections(ctx.keys, v, h, bits)
        for h in prepared.plan_levels
    ]
    corrs = [_hier_corr_rows(win, vcs, k, keep_g, lpe) for win in windows]
    interpret = jax.default_backend() != "tpu"

    # Entry state (the evaluate_levels_fused convention), padded on the
    # HOST up to the plan's uniform state_cap width so every window —
    # including the first — runs the same compiled program shape.
    if ctx.previous_hierarchy_level < 0:
        seeds0 = np.broadcast_to(batch.seeds[:, None, :], (k, 1, 4)).copy()
        control0 = np.full((k, 1), np.uint32(1 if batch.party else 0))
    else:
        seeds0 = ctx.seeds
        control0 = ctx.control
    s_cap = windows[0].state_cap
    if seeds0.shape[1] < s_cap:
        seeds0 = np.asarray(seeds0)
        control0 = np.asarray(control0).astype(np.uint32)
        pad = s_cap - seeds0.shape[1]
        seeds0 = np.concatenate(
            [seeds0, np.zeros((k, pad, 4), np.uint32)], axis=1
        )
        control0 = np.concatenate(
            [control0, np.zeros((k, pad), np.uint32)], axis=1
        )

    chunk = k if key_chunk is None else max(1, int(key_chunk))
    multi = chunk < k
    pipe = _pl.resolve(pipeline)
    if multi:
        # Chunk slicing happens on the host (an eager device fancy-index
        # would dispatch extra programs per chunk).
        seeds0 = np.asarray(seeds0)
        control0 = np.asarray(control0)

    def make_thunk(idx, valid):
        def thunk():
            whole = valid == k and idx.shape[0] == k
            if whole:
                s0 = jnp.asarray(seeds0).astype(jnp.uint32)
                c0 = jnp.asarray(control0).astype(jnp.uint32)
                cw_c, ccl_c, ccr_c = cw_all, ccl_all, ccr_all
                corrs_c = corrs
            else:
                s0 = jnp.asarray(
                    np.ascontiguousarray(seeds0[idx]).astype(np.uint32)
                )
                c0 = jnp.asarray(
                    np.ascontiguousarray(control0[idx]).astype(np.uint32)
                )
                cw_c, ccl_c, ccr_c = cw_all[idx], ccl_all[idx], ccr_all[idx]
                corrs_c = [c[idx] for c in corrs]
            outs_steps = []
            seeds_c, control_c = s0, c0
            for w, win in enumerate(windows):
                lo, hi = win.start_level, win.start_level + win.depth
                outs, seeds_c, control_c = _hier_window_jit(
                    seeds_c,
                    control_c,
                    win.entry_pos_dev,
                    win.path_dev,
                    jnp.asarray(np.ascontiguousarray(cw_c[:, lo:hi])),
                    jnp.asarray(np.ascontiguousarray(ccl_c[:, lo:hi])),
                    jnp.asarray(np.ascontiguousarray(ccr_c[:, lo:hi])),
                    jnp.asarray(corrs_c[w]),
                    win.sel_dev,
                    win.gsels_dev,
                    plan=win.plan,
                    bits=bits,
                    party=batch.party,
                    xor_group=xor_group,
                    keep=keep_g,
                    captures=win.captures,
                    state_base=win.state_base,
                    state_cap=win.state_cap,
                    interpret=interpret,
                )
                outs_steps.extend(outs)
            return valid, outs_steps, seeds_c, control_c

        return thunk

    keep_device = device_output and not multi
    def finalize(item):
        valid, outs_steps, xs, xc = item
        if keep_device:
            return item
        return (
            valid,
            [np.asarray(o)[:valid] for o in outs_steps],
            np.asarray(xs)[:valid] if emit_state else None,
            np.asarray(xc)[:valid] if emit_state else None,
        )

    thunks = (
        make_thunk(idx, valid)
        for idx, valid in _pl.chunk_indices(k, chunk)
    )
    per_chunk = list(
        _pl.map_chunks(thunks, finalize, pipe, op="evaluate_levels_fused")
    )

    if keep_device:
        _, outs_final, xs, xc = per_chunk[0]
        outs_final = list(outs_final)
    else:
        outs_final = [
            np.concatenate([pc[1][i] for pc in per_chunk], axis=0)
            for i in range(n_steps)
        ]
        xs = xc = None
        if emit_state:
            xs = np.concatenate([pc[2] for pc in per_chunk], axis=0)
            xc = np.concatenate([pc[3] for pc in per_chunk], axis=0)

    # Context update (same contract as the fused path; the hierkernel's
    # exit state is inherently leaf-ordered — the last segment of the
    # last window IS the final level's full child-block expansion).
    if emit_state:
        ctx.parent_tree = prepared.end_parent_tree
        ctx.child_levels = prepared.end_child_levels
        ctx.seeds = xs
        ctx.control = xc
    else:
        ctx.parent_tree = None
        ctx.child_levels = 0
        ctx.seeds = None
        ctx.control = None
    ctx.previous_hierarchy_level = prepared.final_level
    return outs_final


@_tm.traced("evaluate_levels_fused")
def evaluate_levels_fused(
    ctx: BatchedContext,
    plan,
    group: int = 16,
    device_output: bool = False,
    use_pallas: Optional[bool] = None,
    mesh=None,
    mode: Optional[str] = None,
    key_chunk: Optional[int] = None,
    pipeline: Optional[bool] = None,
) -> list:
    """Advances through MANY hierarchy levels with the per-level prefix sets
    known upfront — the heavy-hitters / experiments access pattern
    (BM_HeavyHitters, /root/reference/dpf/distributed_point_function_benchmark.cc:308-340) —
    fusing `group` level-advances into each device program. Per-level
    dispatch cost (the measured dominator of the 128-level hierarchy on a
    high-latency link, PERF.md) drops by ~4*group: the per-level gather,
    expansion, value hash + correction, and reorder all run inside one
    program per group, with every index table precomposed on the host.

    `plan` is a list of (hierarchy_level, prefixes) pairs, hierarchy levels
    strictly increasing, prefixes at the PREVIOUS entry's level (empty iff
    the context is fresh, first entry only) — the same contract as calling
    evaluate_until_batch once per entry, and the context ends in the same
    resumable state — or a `PreparedLevelsPlan` from `prepare_levels_fused`
    (the aggregation-server shape: tables composed and uploaded once,
    replayed across key batches; `group` is then ignored). Scalar
    Int/XorWrapper value types only.

    With a (keys, domain) `mesh`, the KEY axis shards over the mesh's
    'keys' axis (data-parallel: the fused per-group programs are
    elementwise over keys, so XLA propagates the sharding from the entry
    state with zero collectives; gather tables replicate). The key count
    must divide evenly over the 'keys' axis.

    `mode` selects the execution strategy: "fused" (the grouped
    scan/unroll chunks) or "hierkernel" (the hierarchical megakernel,
    ISSUE 5: ONE pallas_call per key chunk per `group`-advance prefix
    window). None resolves the DPF_TPU_HIERKERNEL env default, which
    quietly keeps "fused" for configurations the kernel cannot express
    (with a structured engine-downgrade event) — an explicit
    mode="hierkernel" raises instead. `key_chunk`/`pipeline` are
    hierkernel-mode execution knobs (keys per kernel chunk, the
    pipelined chunk executor); the fused path evaluates the whole batch
    in one pass and ignores them.

    Returns the per-entry value arrays: uint32[K, n_outputs, lpe] each
    (numpy unless device_output; hierkernel mode with an explicit
    key_chunk below the batch size assembles outputs on the host and
    returns numpy regardless).
    """
    dpf, v = ctx.dpf, ctx.dpf.validator
    k = len(ctx.keys)
    if mesh is not None and k % mesh.shape["keys"]:
        # Decidable up front — don't burn the host table-construction
        # passes on a call that cannot run.
        raise InvalidArgumentError(
            "evaluate_levels_fused with a mesh requires the key count "
            f"({k}) to divide evenly over the 'keys' axis "
            f"({mesh.shape['keys']})"
        )
    if isinstance(plan, PreparedLevelsPlan):
        prepared = plan
        if tuple(v.parameters) != prepared.parameters:
            raise InvalidArgumentError(
                "prepared plan was built for a different DPF parameter list"
            )
        same_tree = (
            (prepared.start_parent_tree is None) == (ctx.parent_tree is None)
        ) and (
            prepared.start_parent_tree is None
            or np.array_equal(prepared.start_parent_tree, ctx.parent_tree)
        )
        if (
            prepared.start_prev_level != ctx.previous_hierarchy_level
            or prepared.start_child_levels != ctx.child_levels
            or not same_tree
        ):
            raise InvalidArgumentError(
                "prepared plan does not match the context state (it was "
                "prepared at previous_hierarchy_level="
                f"{prepared.start_prev_level}, the context is at "
                f"{ctx.previous_hierarchy_level})"
            )
        if mode is not None and mode != prepared.mode:
            raise InvalidArgumentError(
                f"prepared plan was composed for mode={prepared.mode!r}; "
                f"it cannot execute as mode={mode!r} — re-prepare"
            )
        mode = prepared.mode
    else:
        if not plan:
            return []
        mode, prepared = _resolve_hier_prepare(
            ctx, plan, group, mode, mesh, use_pallas
        )
    if mode == "hierkernel":
        if mesh is not None:
            raise InvalidArgumentError(
                "mode='hierkernel' does not support mesh sharding; use "
                "mode='fused'"
            )
        outs = _evaluate_hierkernel(
            ctx, prepared, device_output, key_chunk, pipeline
        )
        return outs if device_output else _corrupt_outs(outs, "pallas")
    if use_pallas is None:
        use_pallas = evaluator._pallas_default()
    if use_pallas:
        # The per-step Pallas row kernels silently keep the XLA bitslice
        # below one vreg row of lanes (planes.shape[2] < 8 in
        # _advance_one_step) — surface the downgrade structurally so an
        # A/B run can tell "kernel lost" from "kernel never ran" (the
        # dcf narrow-batch pattern). Checked AFTER the platform-default
        # resolution, like dcf: on a real TPU the default is Pallas, and
        # that is exactly the measurement path that must not read as a
        # kernel record when the kernel never ran.
        # A step is flagged only when EVERY one of its expansion levels
        # runs under one vreg row (the widest level is the entry width
        # doubled levels-1 times) — multi-level steps whose later levels
        # reach kernel width keep the Pallas engine for most of their
        # work and must not read as "kernel never ran". Zero-level steps
        # expand nothing and are skipped.
        def _fully_narrow(entry_lanes, lv):
            return lv > 0 and (entry_lanes << (lv - 1)) < 256

        narrow = [
            t
            for t, (pos, lv, _gsel, _start) in enumerate(prepared.steps)
            if pos is not None and _fully_narrow(pos.shape[0], lv)
        ]
        for kind, idx, extras in prepared.chunks:
            if kind == "scan" and _fully_narrow(
                extras[0].shape[1], extras[3]
            ):
                narrow.extend(idx)
        if narrow:
            _emit_hier_downgrade(
                "fused-pallas",
                "fused-xla",
                f"{len(narrow)}/{len(prepared.steps)} advance steps stay "
                "under one vreg row (256 lanes) at every expansion level; "
                "they run the XLA bitslice",
                narrow_steps=len(narrow),
                plan_steps=len(prepared.steps),
            )

    bits, xor_group = prepared.bits, prepared.xor_group
    batch = evaluator.KeyBatch.from_keys(dpf, ctx.keys, prepared.final_level)
    cw_all, ccl_all, ccr_all = batch.device_cw_arrays(0)
    # Per-call key material: value corrections per step.
    vcs = [
        _level_value_corrections(ctx.keys, v, h, bits)
        for h in prepared.plan_levels
    ]

    # Shard-aware uploads (round-5 program audit): with a mesh, host arrays
    # go straight onto their key shards — uploading single-device and
    # letting jit/shard_map reshard cost one eager _multi_slice program
    # PER ARGUMENT per chunk. put_k: key-leading [K, ...]; put_sk:
    # step-major stacks [S, K, ...] (key axis second).
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        key_sharding = NamedSharding(mesh, PartitionSpec("keys"))
        _sk_sharding = NamedSharding(mesh, PartitionSpec(None, "keys"))

        def put_k(a):
            return jax.device_put(np.ascontiguousarray(a), key_sharding)

        def put_sk(a):
            return jax.device_put(np.ascontiguousarray(a), _sk_sharding)
    else:
        put_k = put_sk = jnp.asarray

    # Entry state.
    if ctx.previous_hierarchy_level < 0:
        seeds0 = put_k(
            np.broadcast_to(batch.seeds[:, None, :], (k, 1, 4)).copy()
        )
        control0 = put_k(np.full((k, 1), np.uint32(1 if batch.party else 0)))
    else:
        # Continuation state comes out of the previous fused program with
        # its sharding already propagated; the device_put is a no-op then.
        seeds0 = jnp.asarray(ctx.seeds).astype(jnp.uint32)
        control0 = jnp.asarray(ctx.control).astype(jnp.uint32)
        if mesh is not None:
            seeds0 = jax.device_put(seeds0, key_sharding)
            control0 = jax.device_put(control0, key_sharding)

    emit_state = prepared.emit_state
    outs_all = []
    seeds, control = seeds0, control0
    for ci, (kind, idx, scan_extras) in enumerate(prepared.chunks):
        chunk = [prepared.steps[t] for t in idx]
        last_in_run = ci == len(prepared.chunks) - 1
        emit = emit_state and last_in_run
        so = prepared.final_order_dev if emit else None
        if kind == "scan":
            pos_stack_dev, gsel_pad_dev, out_lens, lv = scan_extras
            outs, seeds, control = _fused_advance_scan_jit(
                seeds,
                control,
                pos_stack_dev,
                put_sk(
                    np.stack(
                        [cw_all[:, s : s + lv] for (_, _, _, s) in chunk]
                    )
                ),
                put_sk(
                    np.stack(
                        [ccl_all[:, s : s + lv] for (_, _, _, s) in chunk]
                    )
                ),
                put_sk(
                    np.stack(
                        [ccr_all[:, s : s + lv] for (_, _, _, s) in chunk]
                    )
                ),
                put_sk(np.stack([vcs[t] for t in idx])),
                gsel_pad_dev,
                so,
                levels=lv,
                bits=bits,
                party=batch.party,
                xor_group=xor_group,
                use_pallas=use_pallas,
                emit_state=emit,
                out_lens=tuple(out_lens),
            )
            outs_all.extend(outs)
            continue
        step_args = tuple(
            (
                pos_dev,
                put_k(cw_all[:, start : start + lv]),
                put_k(ccl_all[:, start : start + lv]),
                put_k(ccr_all[:, start : start + lv]),
                put_k(vcs[t]),
                gsel_dev,
            )
            for t, (pos_dev, lv, gsel_dev, start) in zip(idx, chunk)
        )
        meta = tuple(lv for (_, lv, _, _) in chunk)
        outs, seeds, control = _fused_advance_jit(
            seeds,
            control,
            step_args,
            so,
            meta=meta,
            bits=bits,
            party=batch.party,
            xor_group=xor_group,
            use_pallas=use_pallas,
            emit_state=emit,
        )
        outs_all.extend(outs)

    # Context update (same contract as evaluate_until_batch).
    if emit_state:
        ctx.parent_tree = prepared.end_parent_tree
        ctx.child_levels = prepared.end_child_levels
        ctx.seeds = seeds
        ctx.control = control
    else:
        ctx.parent_tree = None
        ctx.child_levels = 0
        ctx.seeds = None
        ctx.control = None
    ctx.previous_hierarchy_level = prepared.final_level

    if device_output:
        return list(outs_all)
    return _corrupt_outs(
        [np.asarray(o) for o in outs_all],
        evaluator._fi_backend(use_pallas),
    )


def _corrupt_outs(outs: list, backend: str) -> list:
    """Output-corruption seam for the runtime integrity layer (ISSUE 7):
    the hierarchical path has no sentinel-probe hook, so the supervisor's
    host-oracle spot check (ops/supervisor.evaluate_levels_fused_robust)
    is what detects device-side corruption — this is where the chaos
    harness injects it. No-op (one truthiness check) unarmed."""
    if not faultinject.is_active():
        return outs
    return [
        faultinject.corrupt_output(o, backend=backend)
        if isinstance(o, np.ndarray)
        else o  # tuple-typed outputs are outside the scalar probe scope
        for o in outs
    ]


def _expand_batch_host(
    batch: evaluator.KeyBatch,
    seeds0: np.ndarray,  # uint32[K, Np, 4]
    control0: np.ndarray,  # bool[K, Np]
    start_level: int,
    levels: int,
    keep_per_block: int,
    value_type,
    need_state: bool = True,
):
    """Host-engine counterpart of _expand_batch: the doubling expansion runs
    in the native AES-NI library (one call per key), value hash + correction
    vectorized in numpy (core/host_eval.correct_scalar_blocks) — or, when
    `need_state` is False (final hierarchy level: nothing resumes from the
    leaf seeds), the fully fused native forest pass (expansion tail + value
    hash + correction in one stream; see PERF.md "Host side"). Scalar
    Int/XorWrapper only; outputs are host format (uint64 / uint32 limb rows)
    in the same leaf order as the device path."""
    from .. import native
    from ..core import backend_numpy, host_eval
    from ..core.value_types import Int, XorWrapper

    if not isinstance(value_type, (Int, XorWrapper)):
        raise InvalidArgumentError(
            "engine='host' supports Int/XorWrapper outputs; use the device "
            "engine for other value types"
        )
    bits = value_type.bitsize
    xor_group = isinstance(value_type, XorWrapper)
    k, num_parents = seeds0.shape[0], seeds0.shape[1]
    n_out = num_parents << levels
    if not need_state and native.available():
        rkl = np.asarray(backend_numpy._PRG_LEFT._round_keys, dtype=np.uint8)
        rkr = np.asarray(backend_numpy._PRG_RIGHT._round_keys, dtype=np.uint8)
        rkv = np.asarray(backend_numpy._PRG_VALUE._round_keys, dtype=np.uint8)
        vc_wide = host_eval.pack_vc_wide(batch.value_corrections)
        n_vals = n_out * keep_per_block
        if bits == 128:
            outs = np.empty((k, n_vals, 4), dtype=np.uint32)
        elif bits == 64:
            outs = np.empty((k, n_vals), dtype=np.uint64)
        else:
            outs = np.empty((k, n_vals), dtype=np.uint32)
        for j in range(k):
            host_eval.fused_forest_values_into(
                outs[j], rkl, rkr, rkv,
                seeds0[j], control0[j].astype(np.uint8),
                batch.cw_seeds[j, start_level : start_level + levels],
                batch.cw_left[j, start_level : start_level + levels],
                batch.cw_right[j, start_level : start_level + levels],
                batch.party, levels, vc_wide[j], bits, xor_group,
                keep_per_block,
            )
        return outs, None, None
    new_seeds = np.empty((k, n_out, 4), dtype=np.uint32)
    new_control = np.empty((k, n_out), dtype=bool)
    for j in range(k):
        s, c = backend_numpy.expand_seeds(
            seeds0[j],
            control0[j].astype(bool),
            batch.cw_seeds[j, start_level : start_level + levels],
            batch.cw_left[j, start_level : start_level + levels],
            batch.cw_right[j, start_level : start_level + levels],
        )
        new_seeds[j] = s
        new_control[j] = c
    hashed = backend_numpy.hash_expanded_seeds(
        new_seeds.reshape(k * n_out, 4), 1
    ).reshape(k, n_out, 4)
    outs = host_eval.correct_scalar_blocks(
        hashed,
        new_control,
        batch.value_corrections,
        bits,
        xor_group,
        batch.party,
        keep_per_block,
    )
    return outs, new_seeds, new_control


def _expand_batch(
    batch: evaluator.KeyBatch,
    seeds0,  # [K, Np, 4] (numpy or jax)
    control0,  # [K, Np] bools or uint32 0/1
    start_level: int,
    levels: int,
    pad_to: int,
    spec,
    keep_per_block: int,
):
    """Doubling expansion + finalize; returns (values, seeds, control_mask).

    values: leaf-ordered [K, Np * 2^levels * keep, lpe] (or tuple);
    seeds/control are the *leaf-ordered* expansion state for context updates.
    """
    num_parents = seeds0.shape[1]
    pad = pad_to - num_parents
    # Pad + mask-pack + plane-pack in ONE program: the eager concatenates
    # and the un-jitted vmap'd pack dispatched ~30 tiny programs per call
    # (r4 dispatch audit; pure latency through a 66 ms link).
    planes, control_mask = _pad_pack_entry_jit(
        jnp.asarray(seeds0, dtype=jnp.uint32),
        jnp.asarray(control0),
        pad=pad,
    )

    cw_dev, ccl, ccr = batch.device_cw_arrays(start_level)
    cw_dev = jnp.asarray(cw_dev[:, :levels])
    ccl = jnp.asarray(ccl[:, :levels])
    ccr = jnp.asarray(ccr[:, :levels])
    cw_l, ccl_l, ccr_l = evaluator._split_levels_jit(cw_dev, ccl, ccr)
    for level in range(levels):
        # Donating dispatcher: the parent planes die as the children are
        # born, and at serving widths they are the 100+ MB recurring
        # buffer (ops/pipeline.donate_default gates by backend).
        planes, control_mask = evaluator._expand_level_batch(
            planes, control_mask, cw_l[level], ccl_l[level], ccr_l[level]
        )
    order = backend_jax.expansion_output_order(num_parents, pad_to, levels)
    outs = evaluator._finalize_batch_codec_jit(
        planes,
        control_mask,
        tuple(jnp.asarray(a) for a in batch.codec_corrections),
        jnp.asarray(order),
        spec=spec,
        party=batch.party,
        keep_per_block=keep_per_block,
    )
    if not spec.is_tuple:
        outs = outs[0]
    # Leaf-ordered seeds/control for the context update.
    new_seeds, new_control = _reorder_state_jit(
        planes, control_mask, jnp.asarray(order)
    )
    return outs, new_seeds, new_control


@jax.jit
def _reorder_state_jit(planes, control_mask, order):
    seeds = jax.vmap(aes_jax.unpack_from_planes)(planes)[:, order]
    ctrl = jax.vmap(backend_jax.unpack_mask_device)(control_mask)[:, order]
    return seeds, ctrl


# ---------------------------------------------------------------------------
# Domain-sharded expansion (prefix axis sharded over a mesh)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _build_sharded_parent_expand(
    mesh_key,  # the Mesh (hashable)
    levels: int,
    party: int,
    spec,
    keep_per_block: int,
    local_parents: int,
):
    """Compiles the sharded analog of _expand_batch: each 'domain' shard owns
    a contiguous slice of the (padded, sorted) parent prefixes and expands
    them fully — the concatenation of per-shard leaf orders IS the global
    leaf order, so no cross-shard communication exists at all."""
    import jax
    from jax.sharding import PartitionSpec as P

    mesh = mesh_key
    order = backend_jax.expansion_output_order(
        local_parents, local_parents, levels
    )

    def device_fn(seeds, control, cw_planes, ccl, ccr, corrections):
        # seeds [Kl, Pl, 4]; control [Kl, Pl]; cw_* [Kl, L, ...] replicated
        # over 'domain'; corrections pytree [Kl, epb, lpe_c].
        control_mask = _pack_mask_device(control.astype(jnp.uint32))
        planes = jax.vmap(aes_jax.pack_to_planes)(seeds)
        for level in range(levels):
            planes, control_mask = jax.vmap(backend_jax.expand_one_level)(
                planes, control_mask, cw_planes[:, level], ccl[:, level],
                ccr[:, level],
            )
        outs = evaluator._finalize_batch_codec_jit.__wrapped__(
            planes,
            control_mask,
            corrections,
            jnp.asarray(order),
            spec=spec,
            party=party,
            keep_per_block=keep_per_block,
        )
        new_seeds, new_control = _reorder_state_jit.__wrapped__(
            planes, control_mask, jnp.asarray(order)
        )
        return outs, new_seeds, new_control

    step = backend_jax.shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(
            P("keys", "domain"),  # seeds
            P("keys", "domain"),  # control
            P("keys"),  # cw_planes
            P("keys"),  # ccl
            P("keys"),  # ccr
            tuple(P("keys") for _ in spec.components),
        ),
        out_specs=(
            tuple(P("keys", "domain") for _ in spec.components),
            P("keys", "domain"),
            P("keys", "domain"),
        ),
    )
    return jax.jit(step)


@functools.partial(jax.jit, static_argnames=("k", "n_out", "n_state"))
def _sharded_trim_jit(outs, new_seeds, new_control, k, n_out, n_state):
    """Key-pad + parent-pad trims of a sharded expansion, one program."""
    outs = jax.tree.map(lambda o: o[:k, :n_out], outs)
    return outs, new_seeds[:k, :n_state], new_control[:k, :n_state]


@functools.lru_cache(maxsize=None)
def _sharded_entry_pad_for(mesh, pad):
    """Jitted sharded-expansion entry prep, out-sharded to the step's
    (keys, domain) layout so the shard_map call needs no eager reshard."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    kd = NamedSharding(mesh, P("keys", "domain"))

    @functools.partial(jax.jit, out_shardings=(kd, kd))
    def entry_pad(seeds0, control0, idx):
        seeds0 = seeds0.astype(jnp.uint32)
        control0 = control0.astype(jnp.uint32)
        if idx is not None:
            seeds0 = seeds0[idx]
            control0 = control0[idx]
        if pad:
            kp = seeds0.shape[0]
            seeds0 = jnp.concatenate(
                [seeds0, jnp.zeros((kp, pad, 4), jnp.uint32)], axis=1
            )
            control0 = jnp.concatenate(
                [control0, jnp.zeros((kp, pad), jnp.uint32)], axis=1
            )
        return seeds0, control0

    return entry_pad


def _expand_batch_sharded(
    batch: evaluator.KeyBatch,
    seeds0,
    control0,
    start_level: int,
    levels: int,
    spec,
    keep_per_block: int,
    mesh,
):
    """Mesh-sharded counterpart of _expand_batch. Pads the parent axis to a
    multiple of 32 * n_domain and the key axis to n_keys shards."""
    k = seeds0.shape[0]
    num_parents = seeds0.shape[1]
    n_domain = mesh.shape["domain"]
    key_shards = mesh.shape["keys"]
    key_pad = (-k) % key_shards
    if key_pad:
        # Repeat key 0 to make the key axis shardable; trimmed below.
        idx = np.concatenate(
            [np.arange(k), np.zeros(key_pad, dtype=np.int64)]
        )
        batch = batch.take(idx)
    else:
        idx = None
    pad_to = -(-num_parents // (32 * n_domain)) * (32 * n_domain)
    pad = pad_to - num_parents
    # Key-pad gather + parent pad + casts in ONE program whose outputs are
    # ALREADY (keys, domain)-sharded: run eagerly these were ~5 separate
    # dispatches per sharded advance, and the shard_map call then resharded
    # every input with further eager _multi_slice programs (round-5 program
    # audit; same storm class _pad_pack_entry_jit cures on the dense path).
    # Entry state passes to the jit as-is — numpy on the first advance,
    # device arrays (prior trim/gather outputs) afterwards. The jit places
    # uncommitted host arrays onto the mesh at call setup (a transfer);
    # pre-committing via jnp.asarray would cost an eager reshard program.
    seeds0, control0 = _sharded_entry_pad_for(mesh, pad)(seeds0, control0, idx)
    cw_dev, ccl, ccr = batch.device_cw_arrays(start_level)
    step = _build_sharded_parent_expand(
        mesh, levels, batch.party, spec, keep_per_block, pad_to // n_domain
    )
    # The correction-word inputs are host arrays: device_put them straight
    # onto their key shards (a transfer, not a device program) instead of
    # uploading replicated and letting shard_map reshard eagerly.
    from jax.sharding import NamedSharding, PartitionSpec as P

    krep = NamedSharding(mesh, P("keys"))
    outs, new_seeds, new_control = step(
        seeds0,
        control0,
        jax.device_put(np.ascontiguousarray(cw_dev[:, :levels]), krep),
        jax.device_put(np.ascontiguousarray(ccl[:, :levels]), krep),
        jax.device_put(np.ascontiguousarray(ccr[:, :levels]), krep),
        tuple(
            jax.device_put(np.asarray(a), krep)
            for a in batch.codec_corrections
        ),
    )
    # Shards own contiguous parent slices and each emits its leaf order, so
    # the concatenation IS global leaf order: global element base of parent
    # p is p * etp. Padding lanes are all appended after the real parents,
    # hence land in the trailing shards — trimming is a plain slice. All
    # three trims ride ONE jitted program: eagerly, each slice of a
    # sharded array lowered to ~7 separate dispatches (gather + broadcast
    # + convert chains; round-5 program audit found 21/advance here).
    etp = (1 << levels) * keep_per_block  # elements per parent
    outs, new_seeds, new_control = _sharded_trim_jit(
        outs,
        new_seeds,
        new_control,
        k=k,
        n_out=num_parents * etp,
        n_state=num_parents * (1 << levels),
    )
    if not spec.is_tuple:
        outs = outs[0]
    return outs, new_seeds, new_control
