"""Batched two-party key generation on the batched AES kernels.

Key generation was CPU-only by the paper's north star — fine until a
serving system is keygen-bound: the dealer in every gate scenario (BGI
2018/707's preprocessing model is *pure* keygen), Poplar-style streaming
ingestion, and the keygen-offload wire op are all bottlenecked on one
host core seeding trees. ``GenerateKeysIncremental``'s per-level PRG +
correction-word circuit is the same circuit the evaluator kernels
already run — just party-pairwise — so this module ports it onto the
existing batched PRG row circuits.

Three execution modes behind one entry point (``DPF_TPU_KEYGEN`` env
default, "numpy" until a hardware window verifies the device modes):

* ``"numpy"`` — the host batched path (core/keygen.py): one vectorized
  numpy AES call per tree level over all 2K seeds. The production
  default, ~10x the scalar per-key loop at 1024 keys (PERF.md
  "Device-side keygen").
* ``"jax"`` — the per-level expansion through the plane-space XLA
  bitslice (ops/aes_jax): all 2K parent seeds pack into bit-planes on a
  doubled key axis and ONE jitted program computes H_left, H_right (and,
  on blocks_needed==1 output levels, H_value) of every seed — one device
  program per tree level plus one final value hash.
* ``"pallas"`` — the same loop with the expansion running through the
  hardware-proven Mosaic row kernels, REUSED VERBATIM:
  ``expand_one_level_pallas_batched`` with zeroed correction inputs IS
  the keygen expansion (raw child hashes with the control bit split
  out), and ``hash_value_planes_pallas_batched`` is the value PRG. No
  new kernel body, no new Mosaic risk surface (dpflint's op-surface pins
  are untouched). Staged-for-tunnel like every kernel since round 5.

Every mode feeds the SAME level-step algebra (core/keygen.py's
``KeygenPrg`` seam / ``batch_level_step``), so the assembled
``DpfKey`` pairs are byte-identical across modes by construction —
pinned by serialized-bytes tests against the scalar oracle.

The correction-word computation between AES calls is vectorized
numpy/XLA with no per-key Python loops (the host-prep waste class
PERF.md's eval-prep record documents); key-object assembly is the only
remaining per-key work.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..core import keygen as core_keygen
from ..utils import envflags, faultinject
from ..utils import telemetry as _tm
from ..utils.errors import InvalidArgumentError

#: Execution modes of the batched keygen entry points.
KEYGEN_MODES = ("numpy", "jax", "pallas")

#: The degradation ladder, fastest rung first —
#: ops/supervisor.keygen_chain slices its rungs from here, so a new mode
#: must take a position in BOTH tuples (a mode missing from the ladder
#: fails loudly at chain build, never silently runs a different rung).
KEYGEN_RUNG_ORDER = ("pallas", "jax", "numpy")


def _keygen_mode_default() -> str:
    """DPF_TPU_KEYGEN env resolution ("numpy" unset — the host batched
    path is the production default until a hardware window verifies the
    device modes, the same gating every staged kernel follows)."""
    mode = envflags.env_str("DPF_TPU_KEYGEN", None)
    if mode is None:
        return "numpy"
    if mode not in KEYGEN_MODES:
        raise InvalidArgumentError(
            f"DPF_TPU_KEYGEN must be one of {KEYGEN_MODES}, got {mode!r}"
        )
    return mode


#: Lane floor of the pallas expansion: pad the doubled seed axis to full
#: [*, 128, 32]-word planes. Near-width-1 lane blocks are a pathological
#: grid for the row kernels (the _block_plan caveat — a W=1 interpret
#: config ran 100x slower than W=32 on this container), and W=32 at
#: block_w=32 is exactly the per-level kernel config the repo already
#: compiles, so small keygen batches share it instead of adding one.
_PALLAS_LANE_FLOOR = 1024


def _pad_rows(flat: np.ndarray, mult: int) -> Tuple[np.ndarray, int]:
    """Pads uint32[N, 4] seed rows to a multiple of `mult` (32 = the
    plane-packing granularity; the pallas path pads to the lane floor);
    returns (padded, original N)."""
    n = flat.shape[0]
    pad = (-n) % mult
    if pad == 0:
        return np.ascontiguousarray(flat), n
    return np.concatenate(
        [flat, np.zeros((pad, 4), dtype=np.uint32)], axis=0
    ), n


# ---------------------------------------------------------------------------
# JAX (plane-space XLA) expansion programs
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _jax_expand_jit(want_value: bool):
    """ONE program per level: pack 2K parent seeds to planes, hash under
    the left/right (and optionally value) PRG keys, unpack to limb rows.
    Shapes are level-independent, so a whole keygen pass reuses one
    compiled program per `want_value` variant."""
    import jax
    import jax.numpy as jnp

    from ..core import constants
    from . import aes_jax

    rkl = aes_jax.round_key_planes(constants.PRG_KEY_LEFT)
    rkr = aes_jax.round_key_planes(constants.PRG_KEY_RIGHT)
    rkv = aes_jax.round_key_planes(constants.PRG_KEY_VALUE)

    @jax.jit
    def run(flat):
        planes = aes_jax.pack_to_planes(flat)
        out = [
            aes_jax.unpack_from_planes(
                aes_jax.hash_planes(planes, jnp.asarray(rkl))
            ),
            aes_jax.unpack_from_planes(
                aes_jax.hash_planes(planes, jnp.asarray(rkr))
            ),
        ]
        if want_value:
            out.append(
                aes_jax.unpack_from_planes(
                    aes_jax.hash_planes(planes, jnp.asarray(rkv))
                )
            )
        return tuple(out)

    return run


@functools.lru_cache(maxsize=None)
def _jax_value_hash_jit():
    import jax
    import jax.numpy as jnp

    from ..core import constants
    from . import aes_jax

    rkv = aes_jax.round_key_planes(constants.PRG_KEY_VALUE)

    @jax.jit
    def run(flat):
        planes = aes_jax.pack_to_planes(flat)
        return aes_jax.unpack_from_planes(
            aes_jax.hash_planes(planes, jnp.asarray(rkv))
        )

    return run


# ---------------------------------------------------------------------------
# Pallas (Mosaic row kernel) expansion programs — existing entries, reused
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _pack_planes_jit():
    import jax

    from . import aes_jax

    return jax.jit(aes_jax.pack_to_planes)


@functools.lru_cache(maxsize=None)
def _unpack_planes_jit():
    import jax

    from . import aes_jax

    return jax.jit(aes_jax.unpack_from_planes)


def _restore_bit0_np(limbs, control_words: np.ndarray) -> np.ndarray:
    """The kernel zeroes plane 0 and returns it as control lane masks
    (bit i of word w = seed row 32w+i, the pack_bit_mask order); OR-ing
    the bit back into limb 0 reconstructs the raw hash output."""
    bits = (
        (np.asarray(control_words)[:, None] >> np.arange(32, dtype=np.uint32))
        & 1
    ).reshape(-1)
    out = np.array(limbs)
    out[:, 0] |= bits.astype(np.uint32)
    return out


def _pallas_expand(
    flat: np.ndarray, want_value: bool, block_w: int, interpret: bool
):
    """The pallas twin of :func:`_jax_expand_jit`: the keygen expansion
    through ``expand_one_level_pallas_batched`` run as ONE "key" whose W
    lane words are the 2K parent seeds. With zeroed control/correction
    inputs the kernel computes exactly the raw child hashes — output
    planes carry the hash with bit 0 cleared and the control row IS that
    bit (:func:`_restore_bit0_np`). The pallas entries are their own
    jitted programs (nesting an interpret-mode pallas_call inside an
    enclosing jit re-traces the kernel emulation into the outer graph —
    a 100x compile cliff found while staging this path), so the keygen
    shapes here match the per-level kernel configs the repo already
    compiles."""
    from . import aes_pallas

    planes = _pack_planes_jit()(flat)[None]  # [1, 128, W]
    w = planes.shape[2]
    zero_control = np.zeros((1, w), np.uint32)
    zero_cw = np.zeros((1, 128), np.uint32)
    zero_cc = np.zeros((1,), np.uint32)
    out, control = aes_pallas.expand_one_level_pallas_batched(
        planes, zero_control, zero_cw, zero_cc, zero_cc,
        block_w=block_w, interpret=interpret,
    )
    unpack = _unpack_planes_jit()
    control = np.asarray(control)
    left = _restore_bit0_np(unpack(out[0, :, :w]), control[0, :w])
    right = _restore_bit0_np(unpack(out[0, :, w:]), control[0, w:])
    outs = [left, right]
    if want_value:
        hashed = aes_pallas.hash_value_planes_pallas_batched(
            planes, block_w=block_w, interpret=interpret
        )
        outs.append(np.asarray(unpack(hashed[0])))
    return tuple(outs)


def _pallas_value_hash(
    flat: np.ndarray, block_w: int, interpret: bool
) -> np.ndarray:
    from . import aes_pallas

    planes = _pack_planes_jit()(flat)[None]
    hashed = aes_pallas.hash_value_planes_pallas_batched(
        planes, block_w=block_w, interpret=interpret
    )
    return np.asarray(_unpack_planes_jit()(hashed[0]))


class DeviceKeygenPrg(core_keygen.KeygenPrg):
    """A :class:`core.keygen.KeygenPrg` provider whose three fixed-key
    hashes run on the batched device circuits (backend "jax" = plane-
    space XLA, "pallas" = the Mosaic row kernels). Everything outside the
    provider — validation, level-step algebra, correction typing, key
    assembly — is the shared core path, so keys are byte-identical to
    the host provider's by construction."""

    def __init__(
        self, backend: str, block_w: int = 32, interpret: bool = False
    ):
        if backend not in ("jax", "pallas"):
            raise InvalidArgumentError(
                f"DeviceKeygenPrg backend must be 'jax' or 'pallas', "
                f"got {backend!r}"
            )
        self.backend = backend
        self.block_w = block_w
        self.interpret = interpret
        self._row_mult = 32 if backend == "jax" else _PALLAS_LANE_FLOOR

    def expand(self, flat: np.ndarray, want_value: bool):
        padded, n = _pad_rows(flat, self._row_mult)
        if self.backend == "jax":
            outs = _jax_expand_jit(want_value)(padded)
        else:
            outs = _pallas_expand(
                padded, want_value, self.block_w, self.interpret
            )
        left = np.asarray(outs[0])[:n]
        right = np.asarray(outs[1])[:n]
        value = np.asarray(outs[2])[:n] if want_value else None
        # Chaos seam (utils/faultinject "device_output"): a corrupted
        # expansion produces wrong correction words, which the robust
        # wrapper's serialized spot check must catch and degrade around.
        left = faultinject.corrupt_output(left, backend=self.backend)
        return left, right, value

    def value_hash(self, inputs: np.ndarray) -> np.ndarray:
        padded, n = _pad_rows(inputs, self._row_mult)
        if self.backend == "jax":
            out = _jax_value_hash_jit()(padded)
        else:
            out = _pallas_value_hash(padded, self.block_w, self.interpret)
        return np.asarray(out)[:n]


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------


def validated_mode(mode: Optional[str]) -> str:
    """Explicit mode wins; None falls back to the DPF_TPU_KEYGEN env
    default. THE membership check — the chain builder and the telemetry
    resolver both go through here."""
    resolved = mode if mode is not None else _keygen_mode_default()
    if resolved not in KEYGEN_MODES:
        raise InvalidArgumentError(
            f"keygen mode must be one of {KEYGEN_MODES}, got {resolved!r}"
        )
    return resolved


def resolve_mode(mode: Optional[str], op: str = "keygen") -> str:
    """:func:`validated_mode` plus the engine-decision telemetry record
    every entry-point resolution in this repo carries (the robust
    chain's per-rung attempts bypass this — a rung is the CHAIN's
    choice, recorded by its decision(source="degrade") stream)."""
    resolved = validated_mode(mode)
    _tm.decision(
        op, resolved, "explicit" if mode is not None else "env-default"
    )
    return resolved


def make_prg(
    mode: str, block_w: int = 32, interpret: bool = False
) -> Optional[core_keygen.KeygenPrg]:
    """The PRG provider for a resolved mode (None = the core host
    default)."""
    if mode == "numpy":
        return None
    return DeviceKeygenPrg(mode, block_w=block_w, interpret=interpret)


def generate_keys_batch(
    dpf,
    alphas: Sequence[int],
    betas: Sequence,
    mode: Optional[str] = None,
    seeds: Optional[np.ndarray] = None,
    block_w: int = 32,
    interpret: bool = False,
) -> Tuple[List, List]:
    """K DPF key pairs at once on the selected engine.

    Args/semantics match ``DistributedPointFunction.generate_keys_batch``
    (alphas: K points; betas: per hierarchy level, scalar or length-K;
    seeds: optional uint32[K, 2, 4] CSPRNG override) plus:

    * ``mode`` — "numpy" / "jax" / "pallas" (None = DPF_TPU_KEYGEN env,
      default "numpy"). All modes produce byte-identical keys.
    * ``block_w`` / ``interpret`` — pallas lane-block width and the
      interpret-mode escape hatch (tests; real hardware compiles Mosaic).

    Returns (keys of party 0, keys of party 1), each length K.
    """
    resolved = resolve_mode(mode)
    prg = make_prg(resolved, block_w=block_w, interpret=interpret)
    return dpf.generate_keys_batch(alphas, betas, seeds=seeds, prg=prg)


def generate_key_batches(
    dpf,
    alphas: Sequence[int],
    betas: Sequence,
    hierarchy_level: int = -1,
    **kwargs,
):
    """The evaluator-facing form: generates K key pairs and packs each
    party's keys into an ``ops.evaluator.KeyBatch`` ready for the batched
    evaluation entry points (correction-word arrays packed once, the
    PreparedKeyBatch upload shape). Returns (KeyBatch party 0, KeyBatch
    party 1, keys_0, keys_1)."""
    from .evaluator import KeyBatch

    keys_0, keys_1 = generate_keys_batch(dpf, alphas, betas, **kwargs)
    return (
        KeyBatch.from_keys(dpf, keys_0, hierarchy_level),
        KeyBatch.from_keys(dpf, keys_1, hierarchy_level),
        keys_0,
        keys_1,
    )
