"""Batched two-party key generation on the batched AES kernels.

Key generation was CPU-only by the paper's north star — fine until a
serving system is keygen-bound: the dealer in every gate scenario (BGI
2018/707's preprocessing model is *pure* keygen), Poplar-style streaming
ingestion, and the keygen-offload wire op are all bottlenecked on one
host core seeding trees. ``GenerateKeysIncremental``'s per-level PRG +
correction-word circuit is the same circuit the evaluator kernels
already run — just party-pairwise — so this module ports it onto the
existing batched PRG row circuits.

Five execution modes behind one entry point (``DPF_TPU_KEYGEN`` env
default, "numpy-threaded" — the device modes stay gated until a
hardware window verifies them):

* ``"numpy"`` — the single-thread host batched path (core/keygen.py):
  one vectorized numpy AES call per tree level over all 2K seeds. ~28x
  the scalar per-key loop at 1024 keys / depth 128 (PERF.md
  "Device-side keygen").
* ``"numpy-threaded"`` — the production default: the same host batched
  path sharded across a worker pool (``DPF_TPU_KEYGEN_THREADS``, 0 =
  all cores, unset = ``roofline.host_threads_default``). Keys in a
  batch are independent and all CSPRNG seeds are drawn ONCE before the
  pool fans out, so assembled keys are byte-identical to the
  single-thread run at any thread count.
* ``"jax"`` — the per-level expansion through the plane-space XLA
  bitslice (ops/aes_jax): all 2K parent seeds pack into bit-planes on a
  doubled key axis and ONE jitted program computes H_left, H_right (and,
  on blocks_needed==1 output levels, H_value) of every seed — one device
  program per tree level plus one final value hash.
* ``"pallas"`` — the same loop with the expansion running through the
  hardware-proven Mosaic row kernels, REUSED VERBATIM:
  ``expand_one_level_pallas_batched`` with zeroed correction inputs IS
  the keygen expansion (raw child hashes with the control bit split
  out), and ``hash_value_planes_pallas_batched`` is the value PRG. No
  new kernel body, no new Mosaic risk surface (dpflint's op-surface pins
  are untouched). Staged-for-tunnel like every kernel since round 5.
* ``"megakernel"`` — ONE ``pallas_call`` per key batch
  (``aes_pallas.keygen_megakernel_pallas_batched``): the whole level
  loop resident in VMEM, correction-word algebra in-kernel, erasing the
  per-level dispatch floor the jax/pallas modes pay. Staged-for-tunnel;
  gated behind ``router.UNVERIFIED_MODES`` like every device mode.

Every mode feeds the SAME level-step algebra (core/keygen.py's
``KeygenPrg`` seam / ``batch_level_step``), so the assembled
``DpfKey`` pairs are byte-identical across modes by construction —
pinned by serialized-bytes tests against the scalar oracle.

The correction-word computation between AES calls is vectorized
numpy/XLA with no per-key Python loops (the host-prep waste class
PERF.md's eval-prep record documents); key-object assembly is the only
remaining per-key work.
"""

from __future__ import annotations

import concurrent.futures
import functools
import os
import secrets
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..core import keygen as core_keygen
from ..core import uint128
from ..utils import envflags, faultinject
from ..utils import telemetry as _tm
from ..utils.errors import InvalidArgumentError

#: Execution modes of the batched keygen entry points.
KEYGEN_MODES = ("numpy", "numpy-threaded", "jax", "pallas", "megakernel")

#: The degradation ladder, fastest rung first —
#: ops/supervisor.keygen_chain slices its rungs from here, so a new mode
#: must take a position in BOTH tuples (a mode missing from the ladder
#: fails loudly at chain build, never silently runs a different rung —
#: the supervisor asserts set-equality of the two at import).
KEYGEN_RUNG_ORDER = ("megakernel", "pallas", "jax", "numpy-threaded", "numpy")

# Import-time agreement check (ISSUE 19 fix): a mode in one tuple but not
# the other would either crash `order.index(resolved)` late or silently
# start chains at the wrong rung — fail the import instead.
assert set(KEYGEN_RUNG_ORDER) == set(KEYGEN_MODES), (
    f"KEYGEN_RUNG_ORDER {KEYGEN_RUNG_ORDER} must be a permutation of "
    f"KEYGEN_MODES {KEYGEN_MODES}"
)


def _keygen_mode_default() -> str:
    """DPF_TPU_KEYGEN env resolution ("numpy-threaded" unset — the
    threaded host batched path is the production default until a
    hardware window verifies the device modes, the same gating every
    staged kernel follows)."""
    mode = envflags.env_str("DPF_TPU_KEYGEN", None)
    if mode is None:
        return "numpy-threaded"
    if mode not in KEYGEN_MODES:
        raise InvalidArgumentError(
            f"DPF_TPU_KEYGEN must be one of {KEYGEN_MODES}, got {mode!r}"
        )
    return mode


def keygen_threads() -> int:
    """Worker count of the threaded host dealer.

    ``DPF_TPU_KEYGEN_THREADS``: a positive count is taken literally, 0
    means all cores, unset falls back to the fleet-wide host sizing knob
    (``roofline.host_threads_default`` — DPF_TPU_THREADS, default 1) so
    a host sized for threaded evaluation threads its dealer the same
    way without a second flag."""
    n = envflags.env_int("DPF_TPU_KEYGEN_THREADS", -1)
    if n == -1:
        from ..utils import roofline

        return roofline.host_threads_default()
    if n < 0:
        raise InvalidArgumentError(
            f"DPF_TPU_KEYGEN_THREADS must be >= 0 (0 = all cores), got {n}"
        )
    if n == 0:
        return os.cpu_count() or 1
    return n


def host_generate_keys_batch(
    dpf,
    alphas: Sequence[int],
    betas: Sequence,
    seeds: Optional[np.ndarray] = None,
    threads: Optional[int] = None,
) -> Tuple[List, List]:
    """The threaded host dealer: ``dpf.generate_keys_batch`` sharded
    over contiguous key slices on a thread pool (keys in a batch are
    independent — the level-major numpy AES calls release the GIL, so
    slices overlap on a multi-core host).

    ALL CSPRNG seeds are drawn up front (one ``secrets`` draw, exactly
    the single-thread path's stream) and sliced to workers, so the
    assembled keys are byte-identical to a single-thread run of the same
    batch at ANY thread count — the PR 13 contract, pinned by the
    serialized-bytes tests. Import-light: no jax at any thread count
    (the dcf fast path and the serving host engine route here).

    Emits one `keygen.worker` span per slice and the dealer-plane
    `keygen.keys_per_sec` gauge."""
    k = len(alphas)
    n = keygen_threads() if threads is None else int(threads)
    if n < 1:
        raise InvalidArgumentError(
            f"keygen thread count must be >= 1, got {n}"
        )
    n = max(1, min(n, k))
    if seeds is None:
        raw = secrets.token_bytes(16 * 2 * k)
        seeds = np.frombuffer(raw, dtype=np.uint32).reshape(k, 2, 4).copy()
    else:
        seeds = np.array(seeds, dtype=np.uint32).reshape(k, 2, 4)
    start = time.perf_counter()
    if n == 1:
        out = dpf.generate_keys_batch(alphas, betas, seeds=seeds)
    else:
        beta_cols = core_keygen.normalize_beta_cols(
            betas, k, dpf.validator.num_hierarchy_levels
        )
        parent = _tm.current_span_id()
        bounds = [i * k // n for i in range(n + 1)]
        spans = [
            (bounds[i], bounds[i + 1])
            for i in range(n)
            if bounds[i + 1] > bounds[i]
        ]

        def run_slice(ab):
            a, b = ab
            with _tm.span(
                "keygen.worker", parent=parent, lo=a, hi=b, keys=b - a
            ):
                return dpf.generate_keys_batch(
                    alphas[a:b],
                    [col[a:b] for col in beta_cols],
                    seeds=seeds[a:b],
                )
        with concurrent.futures.ThreadPoolExecutor(max_workers=n) as pool:
            parts = list(pool.map(run_slice, spans))
        keys_0: List = []
        keys_1: List = []
        for p0, p1 in parts:
            keys_0 += p0
            keys_1 += p1
        out = (keys_0, keys_1)
    elapsed = time.perf_counter() - start
    if k and elapsed > 0:
        _tm.gauge("keygen.keys_per_sec", k / elapsed, op="keygen")
    return out


#: Lane floor of the pallas expansion: pad the doubled seed axis to full
#: [*, 128, 32]-word planes. Near-width-1 lane blocks are a pathological
#: grid for the row kernels (the _block_plan caveat — a W=1 interpret
#: config ran 100x slower than W=32 on this container), and W=32 at
#: block_w=32 is exactly the per-level kernel config the repo already
#: compiles, so small keygen batches share it instead of adding one.
_PALLAS_LANE_FLOOR = 1024


def _pad_rows(flat: np.ndarray, mult: int) -> Tuple[np.ndarray, int]:
    """Pads uint32[N, 4] seed rows to a multiple of `mult` (32 = the
    plane-packing granularity; the pallas path pads to the lane floor);
    returns (padded, original N)."""
    n = flat.shape[0]
    pad = (-n) % mult
    if pad == 0:
        return np.ascontiguousarray(flat), n
    return np.concatenate(
        [flat, np.zeros((pad, 4), dtype=np.uint32)], axis=0
    ), n


# ---------------------------------------------------------------------------
# JAX (plane-space XLA) expansion programs
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _jax_expand_jit(want_value: bool):
    """ONE program per level: pack 2K parent seeds to planes, hash under
    the left/right (and optionally value) PRG keys, unpack to limb rows.
    Shapes are level-independent, so a whole keygen pass reuses one
    compiled program per `want_value` variant."""
    import jax
    import jax.numpy as jnp

    from ..core import constants
    from . import aes_jax

    rkl = aes_jax.round_key_planes(constants.PRG_KEY_LEFT)
    rkr = aes_jax.round_key_planes(constants.PRG_KEY_RIGHT)
    rkv = aes_jax.round_key_planes(constants.PRG_KEY_VALUE)

    @jax.jit
    def run(flat):
        planes = aes_jax.pack_to_planes(flat)
        out = [
            aes_jax.unpack_from_planes(
                aes_jax.hash_planes(planes, jnp.asarray(rkl))
            ),
            aes_jax.unpack_from_planes(
                aes_jax.hash_planes(planes, jnp.asarray(rkr))
            ),
        ]
        if want_value:
            out.append(
                aes_jax.unpack_from_planes(
                    aes_jax.hash_planes(planes, jnp.asarray(rkv))
                )
            )
        return tuple(out)

    return run


@functools.lru_cache(maxsize=None)
def _jax_value_hash_jit():
    import jax
    import jax.numpy as jnp

    from ..core import constants
    from . import aes_jax

    rkv = aes_jax.round_key_planes(constants.PRG_KEY_VALUE)

    @jax.jit
    def run(flat):
        planes = aes_jax.pack_to_planes(flat)
        return aes_jax.unpack_from_planes(
            aes_jax.hash_planes(planes, jnp.asarray(rkv))
        )

    return run


# ---------------------------------------------------------------------------
# Pallas (Mosaic row kernel) expansion programs — existing entries, reused
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _pack_planes_jit():
    import jax

    from . import aes_jax

    return jax.jit(aes_jax.pack_to_planes)


@functools.lru_cache(maxsize=None)
def _unpack_planes_jit():
    import jax

    from . import aes_jax

    return jax.jit(aes_jax.unpack_from_planes)


def _restore_bit0_np(limbs, control_words: np.ndarray) -> np.ndarray:
    """The kernel zeroes plane 0 and returns it as control lane masks
    (bit i of word w = seed row 32w+i, the pack_bit_mask order); OR-ing
    the bit back into limb 0 reconstructs the raw hash output."""
    bits = (
        (np.asarray(control_words)[:, None] >> np.arange(32, dtype=np.uint32))
        & 1
    ).reshape(-1)
    out = np.array(limbs)
    out[:, 0] |= bits.astype(np.uint32)
    return out


def _pallas_expand(
    flat: np.ndarray, want_value: bool, block_w: int, interpret: bool
):
    """The pallas twin of :func:`_jax_expand_jit`: the keygen expansion
    through ``expand_one_level_pallas_batched`` run as ONE "key" whose W
    lane words are the 2K parent seeds. With zeroed control/correction
    inputs the kernel computes exactly the raw child hashes — output
    planes carry the hash with bit 0 cleared and the control row IS that
    bit (:func:`_restore_bit0_np`). The pallas entries are their own
    jitted programs (nesting an interpret-mode pallas_call inside an
    enclosing jit re-traces the kernel emulation into the outer graph —
    a 100x compile cliff found while staging this path), so the keygen
    shapes here match the per-level kernel configs the repo already
    compiles."""
    from . import aes_pallas

    planes = _pack_planes_jit()(flat)[None]  # [1, 128, W]
    w = planes.shape[2]
    zero_control = np.zeros((1, w), np.uint32)
    zero_cw = np.zeros((1, 128), np.uint32)
    zero_cc = np.zeros((1,), np.uint32)
    out, control = aes_pallas.expand_one_level_pallas_batched(
        planes, zero_control, zero_cw, zero_cc, zero_cc,
        block_w=block_w, interpret=interpret,
    )
    unpack = _unpack_planes_jit()
    control = np.asarray(control)
    left = _restore_bit0_np(unpack(out[0, :, :w]), control[0, :w])
    right = _restore_bit0_np(unpack(out[0, :, w:]), control[0, w:])
    outs = [left, right]
    if want_value:
        hashed = aes_pallas.hash_value_planes_pallas_batched(
            planes, block_w=block_w, interpret=interpret
        )
        outs.append(np.asarray(unpack(hashed[0])))
    return tuple(outs)


def _pallas_value_hash(
    flat: np.ndarray, block_w: int, interpret: bool
) -> np.ndarray:
    from . import aes_pallas

    planes = _pack_planes_jit()(flat)[None]
    hashed = aes_pallas.hash_value_planes_pallas_batched(
        planes, block_w=block_w, interpret=interpret
    )
    return np.asarray(_unpack_planes_jit()(hashed[0]))


class DeviceKeygenPrg(core_keygen.KeygenPrg):
    """A :class:`core.keygen.KeygenPrg` provider whose three fixed-key
    hashes run on the batched device circuits (backend "jax" = plane-
    space XLA, "pallas" = the Mosaic row kernels). Everything outside the
    provider — validation, level-step algebra, correction typing, key
    assembly — is the shared core path, so keys are byte-identical to
    the host provider's by construction."""

    def __init__(
        self, backend: str, block_w: int = 32, interpret: bool = False
    ):
        if backend not in ("jax", "pallas"):
            raise InvalidArgumentError(
                f"DeviceKeygenPrg backend must be 'jax' or 'pallas', "
                f"got {backend!r}"
            )
        self.backend = backend
        self.block_w = block_w
        self.interpret = interpret
        self._row_mult = 32 if backend == "jax" else _PALLAS_LANE_FLOOR

    def expand(self, flat: np.ndarray, want_value: bool):
        padded, n = _pad_rows(flat, self._row_mult)
        if self.backend == "jax":
            outs = _jax_expand_jit(want_value)(padded)
        else:
            outs = _pallas_expand(
                padded, want_value, self.block_w, self.interpret
            )
        left = np.asarray(outs[0])[:n]
        right = np.asarray(outs[1])[:n]
        value = np.asarray(outs[2])[:n] if want_value else None
        # Chaos seam (utils/faultinject "device_output"): a corrupted
        # expansion produces wrong correction words, which the robust
        # wrapper's serialized spot check must catch and degrade around.
        left = faultinject.corrupt_output(left, backend=self.backend)
        return left, right, value

    def value_hash(self, inputs: np.ndarray) -> np.ndarray:
        padded, n = _pad_rows(inputs, self._row_mult)
        if self.backend == "jax":
            out = _jax_value_hash_jit()(padded)
        else:
            out = _pallas_value_hash(padded, self.block_w, self.interpret)
        return np.asarray(out)[:n]


# ---------------------------------------------------------------------------
# Keygen megakernel host path: pack, ONE program, unpack, assemble
# ---------------------------------------------------------------------------


def _pack_planes_np(flat: np.ndarray) -> np.ndarray:
    """Numpy twin of ``aes_jax.pack_to_planes``: uint32[N, 4] block rows
    -> uint32[128, N//32] bit planes (plane p word w bit i = bit p of
    block 32w+i). The megakernel host path packs/unpacks on the host so
    the jitted program is EXACTLY the pallas_call — the 1-program pin."""
    n = flat.shape[0]
    assert n % 32 == 0, n
    w = n // 32
    bits = np.unpackbits(
        np.ascontiguousarray(flat).view(np.uint8).reshape(n, 16),
        axis=1,
        bitorder="little",
    )  # [N, 128]
    b = bits.reshape(w, 32, 128).astype(np.uint32)
    planes = (b << np.arange(32, dtype=np.uint32)[None, :, None]).sum(
        axis=1, dtype=np.uint32
    )  # [w, 128]
    return np.ascontiguousarray(planes.T)


def _unpack_planes_np(planes: np.ndarray) -> np.ndarray:
    """Inverse of :func:`_pack_planes_np`: uint32[128, W] -> uint32[32W, 4]."""
    w = planes.shape[1]
    bits = (
        (planes[:, :, None] >> np.arange(32, dtype=np.uint32)) & 1
    ).astype(np.uint8)  # [128, W, 32]
    rows = bits.transpose(1, 2, 0).reshape(w * 32, 128)
    packed = np.ascontiguousarray(
        np.packbits(rows, axis=1, bitorder="little")
    )
    return packed.view(np.uint32).reshape(-1, 4).copy()


def _unpack_lane_bits_np(row: np.ndarray, k: int) -> np.ndarray:
    """Packed lane-mask row (bit i of word w = key 32w+i) -> bool[k]."""
    bits = (
        (np.asarray(row)[:, None] >> np.arange(32, dtype=np.uint32)) & 1
    ).reshape(-1)
    return bits[:k].astype(bool)


@functools.lru_cache(maxsize=None)
def _keygen_megakernel_jit(
    levels: int, captures, block_w: int, interpret: bool
):
    """The megakernel's ONE compiled program per (levels, captures,
    tile) config: jit strictly around the pallas_call (pack/unpack stay
    host-side numpy), so a warm batch is a single dispatch — the
    dispatch-audit pin. Interpret mode traces the kernel emulation into
    the jit (fine with the cheap test rows; the real circuit compiles on
    hardware only, like every staged kernel)."""
    import jax

    from . import aes_pallas

    def run(planes0, planes1, path_masks):
        return aes_pallas.keygen_megakernel_pallas_batched(
            planes0,
            planes1,
            path_masks,
            captures=captures,
            block_w=block_w,
            interpret=interpret,
        )

    return jax.jit(run)


def _megakernel_generate(
    dpf,
    alphas: Sequence[int],
    betas: Sequence,
    seeds: Optional[np.ndarray] = None,
    block_w: int = 32,
    interpret: bool = False,
    reference: bool = False,
) -> Tuple[List, List]:
    """Batched keygen through the single-program megakernel.

    Host side: draw seeds, pack both parties' seed planes and the
    per-level alpha bits (keys in lanes), run ONE device program
    (`aes_pallas.keygen_megakernel_pallas_batched`), unpack the
    correction-word / control-correction / value-hash planes, apply the
    typed beta algebra (`_value_corrections_from_hashed` — value typing
    stays host-side), and feed the SAME level-record stream the numpy
    dealer feeds `core_keygen.assemble_batch_keys` — wire keys are
    byte-identical by construction.

    ``reference=True`` replays through
    `keygen_megakernel_reference_rows` (no pallas_call): the eager
    real-circuit oracle-identity test and the interpret plumbing tests
    share this exact host prep/assembly."""
    from ..ops import degrade

    v = dpf.validator
    levels = v.tree_levels_needed - 1
    if levels < 1:
        raise degrade.RungUnsupported(
            "keygen megakernel needs at least one tree level"
        )
    if any(b != 1 for b in v.blocks_needed):
        raise degrade.RungUnsupported(
            "keygen megakernel requires blocks_needed == 1 at every "
            "output level (wide-value input offsets are host-only)"
        )
    hier_in_loop = [
        v.tree_to_hierarchy[d] for d in range(levels) if d in v.tree_to_hierarchy
    ]
    if hier_in_loop != list(range(v.num_hierarchy_levels - 1)):
        raise degrade.RungUnsupported(
            "keygen megakernel requires one capture depth per hierarchy "
            f"level, got {hier_in_loop} of {v.num_hierarchy_levels}"
        )
    captures = tuple(d in v.tree_to_hierarchy for d in range(levels)) + (
        True,
    )

    k = len(alphas)
    if k == 0:
        return [], []
    beta_cols = core_keygen.normalize_beta_cols(
        betas, k, v.num_hierarchy_levels
    )
    for level, col in enumerate(beta_cols):
        for val in col:
            v.validate_value(val, level)
    last_log = v.parameters[-1].log_domain_size
    alphas = [int(a) for a in alphas]
    for alpha in alphas:
        if alpha < 0 or (last_log < 128 and alpha >= (1 << last_log)):
            raise InvalidArgumentError(
                "`alpha` must be smaller than the output domain size"
            )
    if seeds is None:
        raw = secrets.token_bytes(16 * 2 * k)
        seeds_l = np.frombuffer(raw, dtype=np.uint32).reshape(k, 2, 4).copy()
    else:
        seeds_l = np.array(seeds, dtype=np.uint32).reshape(k, 2, 4)

    # Keys in lanes: pad to whole words of whole tiles.
    wp = -(-(-(-k // 32)) // block_w) * block_w  # ceil(ceil(k/32)/bw)*bw
    kp = wp * 32
    pad = np.zeros((kp - k, 4), dtype=np.uint32)
    planes0 = _pack_planes_np(np.concatenate([seeds_l[:, 0, :], pad]))
    planes1 = _pack_planes_np(np.concatenate([seeds_l[:, 1, :], pad]))

    alpha_limbs = uint128.u128_to_limb_rows(uint128.u128_array(alphas))
    path_bits = np.zeros((levels, kp), dtype=np.uint32)
    for d in range(levels):
        bit_index = last_log - (d + 1)
        if 0 <= bit_index < 128:
            path_bits[d, :k] = (
                alpha_limbs[:, bit_index // 32] >> (bit_index % 32)
            ) & 1
    path_masks = (
        path_bits.reshape(levels, wp, 32)
        << np.arange(32, dtype=np.uint32)[None, None, :]
    ).sum(axis=2, dtype=np.uint32)

    if reference:
        from . import aes_pallas

        outs = aes_pallas.keygen_megakernel_reference_rows(
            planes0, planes1, path_masks, captures=captures
        )
    else:
        outs = _keygen_megakernel_jit(levels, captures, block_w, interpret)(
            planes0, planes1, path_masks
        )
    cw, cc, vh, ctrl = (np.asarray(o) for o in outs)

    seed_ints = uint128.limb_rows_to_ints(seeds_l.reshape(-1, 4))
    out_keys: Tuple[List, List] = (
        [
            core_keygen.DpfKey(
                seed=seed_ints[2 * i], correction_words=[], party=0
            )
            for i in range(k)
        ],
        [
            core_keygen.DpfKey(
                seed=seed_ints[2 * i + 1], correction_words=[], party=1
            )
            for i in range(k)
        ],
    )

    def typed_corrections(slot: int, hierarchy_level: int):
        base = slot * 256
        hashed = np.stack(
            [
                _unpack_planes_np(vh[base : base + 128])[:k],
                _unpack_planes_np(vh[base + 128 : base + 256])[:k],
            ],
            axis=1,
        )[:, :, None, :]  # [K, 2, 1, 4]
        control = np.zeros((k, 2), dtype=bool)
        control[:, 1] = _unpack_lane_bits_np(ctrl[slot], k)
        return dpf._keygen._value_corrections_from_hashed(
            hierarchy_level,
            hashed,
            control,
            alphas,
            beta_cols[hierarchy_level],
        )

    level_records = []
    slot = 0
    for d in range(levels):
        value_corrections = None
        if captures[d]:
            value_corrections = typed_corrections(slot, v.tree_to_hierarchy[d])
            slot += 1
        seed_correction = _unpack_planes_np(cw[d * 128 : (d + 1) * 128])[:k]
        cc_pair = np.stack(
            [
                _unpack_lane_bits_np(cc[2 * d], k),
                _unpack_lane_bits_np(cc[2 * d + 1], k),
            ],
            axis=1,
        )
        level_records.append((seed_correction, cc_pair, value_corrections))
    last_cw = typed_corrections(slot, v.num_hierarchy_levels - 1)
    core_keygen.assemble_batch_keys(out_keys, level_records, last_cw)
    return out_keys


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------


def validated_mode(mode: Optional[str]) -> str:
    """Explicit mode wins; None falls back to the DPF_TPU_KEYGEN env
    default. THE membership check — the chain builder and the telemetry
    resolver both go through here."""
    resolved = mode if mode is not None else _keygen_mode_default()
    if resolved not in KEYGEN_MODES:
        raise InvalidArgumentError(
            f"keygen mode must be one of {KEYGEN_MODES}, got {resolved!r}"
        )
    return resolved


def resolve_mode(mode: Optional[str], op: str = "keygen") -> str:
    """:func:`validated_mode` plus the engine-decision telemetry record
    every entry-point resolution in this repo carries (the robust
    chain's per-rung attempts bypass this — a rung is the CHAIN's
    choice, recorded by its decision(source="degrade") stream)."""
    resolved = validated_mode(mode)
    _tm.decision(
        op, resolved, "explicit" if mode is not None else "env-default"
    )
    return resolved


def make_prg(
    mode: str, block_w: int = 32, interpret: bool = False
) -> Optional[core_keygen.KeygenPrg]:
    """The PRG provider for a per-level resolved mode (None = the core
    host default). Only the per-level modes have a provider form —
    "numpy-threaded" and "megakernel" restructure the loop itself and
    dispatch through :func:`run_resolved`."""
    if mode in ("numpy", "numpy-threaded"):
        return None
    if mode == "megakernel":
        raise InvalidArgumentError(
            "the megakernel keygen mode has no per-level PRG provider; "
            "dispatch through run_resolved/generate_keys_batch"
        )
    return DeviceKeygenPrg(mode, block_w=block_w, interpret=interpret)


def run_resolved(
    dpf,
    resolved: str,
    alphas: Sequence[int],
    betas: Sequence,
    seeds: Optional[np.ndarray] = None,
    block_w: int = 32,
    interpret: bool = False,
    threads: Optional[int] = None,
) -> Tuple[List, List]:
    """Dispatches an ALREADY-RESOLVED mode to its engine, with no
    telemetry decision of its own — the seam the robust chain's rungs
    call (a rung is the CHAIN's choice, recorded by its
    decision(source="degrade") stream) and the tail of
    :func:`generate_keys_batch`."""
    if resolved == "numpy":
        return dpf.generate_keys_batch(alphas, betas, seeds=seeds)
    if resolved == "numpy-threaded":
        return host_generate_keys_batch(
            dpf, alphas, betas, seeds=seeds, threads=threads
        )
    if resolved == "megakernel":
        return _megakernel_generate(
            dpf, alphas, betas, seeds=seeds, block_w=block_w,
            interpret=interpret,
        )
    prg = DeviceKeygenPrg(resolved, block_w=block_w, interpret=interpret)
    return dpf.generate_keys_batch(alphas, betas, seeds=seeds, prg=prg)


def generate_keys_batch(
    dpf,
    alphas: Sequence[int],
    betas: Sequence,
    mode: Optional[str] = None,
    seeds: Optional[np.ndarray] = None,
    block_w: int = 32,
    interpret: bool = False,
    threads: Optional[int] = None,
) -> Tuple[List, List]:
    """K DPF key pairs at once on the selected engine.

    Args/semantics match ``DistributedPointFunction.generate_keys_batch``
    (alphas: K points; betas: per hierarchy level, scalar or length-K;
    seeds: optional uint32[K, 2, 4] CSPRNG override) plus:

    * ``mode`` — "numpy" / "numpy-threaded" / "jax" / "pallas" /
      "megakernel" (None = DPF_TPU_KEYGEN env, default
      "numpy-threaded"). All modes produce byte-identical keys.
    * ``block_w`` / ``interpret`` — pallas lane-block width and the
      interpret-mode escape hatch (tests; real hardware compiles Mosaic).
    * ``threads`` — threaded-mode worker override (None =
      DPF_TPU_KEYGEN_THREADS / roofline.host_threads_default).

    Returns (keys of party 0, keys of party 1), each length K.
    """
    resolved = resolve_mode(mode)
    return run_resolved(
        dpf, resolved, alphas, betas, seeds=seeds, block_w=block_w,
        interpret=interpret, threads=threads,
    )


def generate_key_batches(
    dpf,
    alphas: Sequence[int],
    betas: Sequence,
    hierarchy_level: int = -1,
    **kwargs,
):
    """The evaluator-facing form: generates K key pairs and packs each
    party's keys into an ``ops.evaluator.KeyBatch`` ready for the batched
    evaluation entry points (correction-word arrays packed once, the
    PreparedKeyBatch upload shape). Returns (KeyBatch party 0, KeyBatch
    party 1, keys_0, keys_1)."""
    from .evaluator import KeyBatch

    keys_0, keys_1 = generate_keys_batch(dpf, alphas, betas, **kwargs)
    return (
        KeyBatch.from_keys(dpf, keys_0, hierarchy_level),
        KeyBatch.from_keys(dpf, keys_1, hierarchy_level),
        keys_0,
        keys_1,
    )
