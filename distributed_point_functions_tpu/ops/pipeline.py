"""Pipelined chunk executor: overlap pack/upload, compute, and pull/verify.

PERF.md establishes that the device paths are latency- and transfer-bound,
not compute-bound (~66 ms per dispatch through this image's tunnel, a
~5 MB/s host link), yet the bulk entry points historically ran strictly
synchronously: chunk N's host key pack, H2D upload, device program, and
D2H pull all completed before chunk N+1 started. This module promotes the
bench-side hand-rolled "async chunk overlap" (PERF.md §Pallas) into a
library capability with three stages in flight:

  1. **launch** (main thread) — host-side key pack + ``device_put`` of
     chunk N+1's correction-word/seed material plus the *async* dispatch
     of its device program. JAX dispatch returns immediately, so up to
     ``depth`` chunks queue on the device while…
  2. **compute** (device) — chunk N's program runs, and…
  3. **finalize** (worker thread) — chunk N-1's D2H pull, sentinel
     verification, and consumer fold happen concurrently. Host pulls
     block the calling thread, hence the single worker; one worker keeps
     chunk completion strictly ordered.

The same code drives the serial mode (``pipeline=False``): launch and
finalize run inline on one thread with identical per-chunk fault hooks,
so a pipeline-on/off A/B (bench.py's ``pipeline_overlap`` field, the
overlap proxy in tests/test_pipeline.py) compares like for like.

Failure semantics: when any stage raises (e.g. ``DataCorruptionError``
from sentinel verification at stage 3, or an injected ``chunk_launch``
fault), every in-flight finalize is **drained** — awaited, not abandoned —
before the exception propagates. A degradation rerun (ops/degrade.py)
therefore never races a background pull, and results already yielded to
the consumer stay valid (completed chunks are not lost). The drain wait
is bounded (``DPF_TPU_DRAIN_TIMEOUT``, default 60 s) and an expiry is
surfaced — a structured "drain-timeout" IntegrityEvent plus a
``pipeline.drain_timeout`` counter — instead of silently proceeding
(ISSUE 7). With a dispatch deadline armed (``DPF_TPU_DEADLINE`` or a
DegradationPolicy ``deadline_seconds``, ops/supervisor.py), every
per-chunk launch and finalize wait is watchdog-bounded and an expiry
raises ``UnavailableError`` — a *hung* device call enters the
retry→degrade path instead of wedging the executor forever.

Enabled per-call via the ``pipeline=`` keyword on every bulk entry point
or process-wide via ``DPF_TPU_PIPELINE`` (strict boolean). Default: ON
for device backends, OFF on XLA:CPU (whose compute runs on the same cores
the stages would overlap on) and never for the numpy host oracle (which
has no device queue at all). ``DPF_TPU_PIPELINE_DEPTH`` sizes the launch
window (default 2 chunks ahead).

``DPF_TPU_DONATE`` governs input-buffer donation on the large per-chunk
fold programs (parallel/sharded.py) and the per-level expansion programs:
default ON for TPU backends — the 100+ MB value buffers are reused by XLA
instead of accumulating toward the RESOURCE_EXHAUSTED cliff — and OFF on
CPU, where XLA does not implement donation and would warn per program.
"""

from __future__ import annotations

import functools
import itertools
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import wait as _futures_wait
from typing import Callable, Iterable, Iterator, Optional, TypeVar

import numpy as np

from ..utils import faultinject
from ..utils import telemetry as _tm
from ..utils import envflags as _envflags
from ..utils.errors import InvalidArgumentError

T = TypeVar("T")
R = TypeVar("R")


def _sv():
    """ops.supervisor, imported lazily: it sits above this module in the
    dependency order (supervisor -> degrade -> utils; nothing back here),
    but a module-level import would still couple the executor's import
    cost to the whole resilience layer for callers that never arm it."""
    from . import supervisor

    return supervisor


def pipeline_default() -> bool:
    """Resolves the executor default: DPF_TPU_PIPELINE when set, else ON
    exactly for non-CPU JAX backends. XLA:CPU computes on the very cores
    the launch/finalize stages would overlap on, so pipelining there buys
    nothing and costs a thread; tests opt in explicitly."""
    env = _envflags.env_opt_bool("DPF_TPU_PIPELINE")
    if env is not None:
        return env
    import jax

    return jax.default_backend() != "cpu"


def resolve(pipeline: Optional[bool]) -> bool:
    """Explicit keyword wins; None = the platform/env default."""
    return pipeline_default() if pipeline is None else bool(pipeline)


def depth_default() -> int:
    """Launch-ahead window (chunks in flight beyond the one the consumer
    holds). DPF_TPU_PIPELINE_DEPTH, floor 1, default 2 (double buffering:
    one uploading/computing, one computed awaiting pull)."""
    try:
        depth = _envflags.env_int("DPF_TPU_PIPELINE_DEPTH", 2)
    except InvalidArgumentError:
        depth = 2
    return max(1, depth)


def donate_default() -> bool:
    """Input-buffer donation default: DPF_TPU_DONATE when set, else ON for
    real TPU backends only (XLA:CPU does not implement donation and warns
    once per donated program)."""
    env = _envflags.env_opt_bool("DPF_TPU_DONATE")
    if env is not None:
        return env
    import jax

    return jax.default_backend() == "tpu"


def chunk_indices(num_items: int, chunk: int) -> Iterator[tuple]:
    """Yields (idx int64[chunk or fewer], num_valid) index blocks with the
    shared padding rule of evaluator._key_chunks: the last block pads with
    row 0 so every dispatched program keeps one shape — except when the
    whole batch is smaller than `chunk` (small programs compile on their
    own). Padded rows are trimmed by the caller via num_valid."""
    for start in range(0, num_items, chunk):
        idx = np.arange(start, min(start + chunk, num_items))
        valid = idx.shape[0]
        pad = chunk - valid if num_items > chunk else 0
        if pad:
            idx = np.concatenate([idx, np.zeros(pad, dtype=np.int64)])
        if _tm.enabled():
            _tm.observe("chunk.items", valid)
        yield idx, valid


def prefetch_thunks(
    thunks: Iterable[Callable[[], T]],
    pipeline: bool,
    depth: Optional[int] = None,
    backend: Optional[str] = None,
    op: Optional[str] = None,
) -> Iterator[T]:
    """Stage-1/2 driver. Each thunk performs ONE chunk's host pack +
    upload + async device-program dispatch and returns the chunk's
    device-resident result. Pipelined, up to `depth` chunks launch ahead
    of the one the consumer holds, so chunk N+1's pack/upload overlaps
    chunk N's device program and the consumer's pull of chunk N-1; serial
    mode launches and yields strictly one at a time. Results always yield
    in input order.

    Per chunk, before its launch, the fault-injection hooks fire:
    ``maybe_raise("chunk_launch")`` (a per-chunk injected failure — how
    tests corrupt a pipeline mid-flight) and ``chunk_delay("launch")``
    (the artificial dispatch-latency knob behind the CPU overlap proxy).
    Both are armed-plan no-ops in production.

    `op` labels this entry point's telemetry (ISSUE 6): with the bus
    enabled, every chunk launch emits a ``pipeline.launch`` span (the
    injected launch delay counts as dispatch latency and is inside it),
    a ``pipeline.chunks_launched`` counter tick and a
    ``pipeline.queue_depth`` gauge (chunks in flight). Disabled, the
    per-chunk cost is one boolean check — pinned by
    tests/test_telemetry.py.
    """
    if depth is None:
        depth = depth_default()
    window: deque = deque()
    idx = 0
    for thunk in thunks:
        faultinject.maybe_raise("chunk_launch", backend=backend)

        def _launch(thunk=thunk):
            # Inside the supervisor's deadline watchdog (when armed): the
            # injected hang and the real dispatch wait are both bounded.
            faultinject.chunk_delay("launch", backend=backend)
            faultinject.device_hang("launch", backend=backend)
            _sv().check_abandoned()
            return thunk()

        if _tm.enabled():
            with _tm.span("pipeline.launch", op=op, chunk=idx):
                result = _sv().deadline_call(
                    _launch, "pipeline.launch", op=op, backend=backend
                )
            _tm.counter("pipeline.chunks_launched", op=op)
            _tm.gauge("pipeline.queue_depth", len(window) + 1, op=op)
        else:
            result = _sv().deadline_call(
                _launch, "pipeline.launch", op=op, backend=backend
            )
        window.append(result)
        idx += 1
        if not pipeline or len(window) > depth:
            yield window.popleft()
    while window:
        yield window.popleft()


def consume(
    results: Iterable[T],
    finalize: Callable[[T], R],
    pipeline: bool,
    depth: Optional[int] = None,
    backend: Optional[str] = None,
    op: Optional[str] = None,
) -> Iterator[R]:
    """Stage-3 driver. Pulls each upstream chunk through `finalize` (the
    blocking D2H transfer + sentinel verification + host-side fold) — on a
    single worker thread when pipelined, so the pulls overlap the main
    thread's pack/dispatch of later chunks; inline when serial. One worker
    by construction: chunk results yield strictly in order either way.

    On any failure (a finalize raising — e.g. sentinel verification
    detecting a corrupted chunk — or the upstream iterable raising), every
    in-flight finalize is drained before the exception propagates: the
    caller can immediately rerun on a fallback backend (ops/degrade.py)
    without racing a background pull, and chunks already yielded remain
    valid.

    Telemetry (ISSUE 6): each finalize emits a ``pipeline.finalize`` span
    whose parent is the span active when `consume` was CALLED (captured
    on the main thread), so the span tree is identical whether finalize
    runs inline or on the worker thread; its duration is the measured
    dispatch latency (blocking wait + pull), and the pulled host bytes
    tick the ``bytes.d2h`` counter."""
    if depth is None:
        depth = depth_default()
    parent = _tm.current_span_id() if _tm.enabled() else None
    seq = itertools.count()

    def _finalize(item: T) -> R:
        if not _tm.enabled():
            faultinject.chunk_delay("finalize", backend=backend)
            faultinject.device_hang("finalize", backend=backend)
            _sv().check_abandoned()
            return finalize(item)
        with _tm.span(
            "pipeline.finalize", parent=parent, op=op, chunk=next(seq)
        ):
            faultinject.chunk_delay("finalize", backend=backend)
            faultinject.device_hang("finalize", backend=backend)
            _sv().check_abandoned()
            out = finalize(item)
        _tm.counter("pipeline.chunks_finalized", op=op)
        _tm.counter("bytes.d2h", _tm.nbytes_of(out), op=op)
        return out

    if not pipeline:
        sv = _sv()
        for item in results:
            # Serial finalize runs inline: the deadline watchdog (when
            # armed) hosts the blocking pull on its own thread so a hang
            # converts to UnavailableError instead of wedging the caller.
            yield sv.deadline_call(
                functools.partial(_finalize, item),
                "pipeline.finalize",
                op=op,
                backend=backend,
            )
        return

    pool = ThreadPoolExecutor(max_workers=1, thread_name_prefix="dpf-pipeline")
    pending: deque = deque()
    sv = _sv()
    try:
        try:
            for item in results:
                pending.append(pool.submit(_finalize, item))
                while len(pending) > depth:
                    yield sv.deadline_result(
                        pending.popleft(), "pipeline.finalize",
                        op=op, backend=backend,
                    )
            while pending:
                yield sv.deadline_result(
                    pending.popleft(), "pipeline.finalize",
                    op=op, backend=backend,
                )
        except BaseException:
            drain(pending, backend=backend, op=op)
            raise
    finally:
        # Normal exhaustion leaves nothing pending; after drain() the
        # worker is idle — never block teardown on a wait here.
        pool.shutdown(wait=False)


def drain_timeout_default() -> float:
    """Bound on the drain-on-error wait (seconds): DPF_TPU_DRAIN_TIMEOUT,
    default 60 — the pre-ISSUE-7 hardcoded constant, now a knob."""
    try:
        return _envflags.env_float("DPF_TPU_DRAIN_TIMEOUT", 60.0)
    except InvalidArgumentError:
        return 60.0


def drain(pending, backend: Optional[str] = None, op: Optional[str] = None) -> None:
    """Cancels what has not started and awaits what has: after drain, no
    background thread touches device buffers. Bounded wait — a wedged
    device pull must not hang the error path forever (the exception being
    propagated is the primary signal). A timeout is no longer silent
    (ISSUE 7): chunks still in flight when the wait expires mean a
    background thread MAY still touch device buffers — a DataLossError-
    kind fact the degradation rerun needs to know, surfaced as a
    structured "drain-timeout" IntegrityEvent plus a
    ``pipeline.drain_timeout`` counter."""
    for f in pending:
        f.cancel()
    if not pending:
        return
    timeout = drain_timeout_default()
    _done, not_done = _futures_wait(list(pending), timeout=timeout)
    if not_done:
        from ..utils import integrity as _integrity

        _integrity.emit_event(
            "drain-timeout",
            f"pipeline drain: {len(not_done)} in-flight finalize(s) still "
            f"running after {timeout:g}s — a wedged device pull may still "
            "touch device buffers behind the degradation rerun "
            "(DataLossError-kind; raise DPF_TPU_DRAIN_TIMEOUT or arm "
            "DPF_TPU_DEADLINE to convert hangs earlier)",
            backend or "",
            op=op,
            error="DataLossError",
            pending=len(not_done),
            timeout_seconds=timeout,
        )
        _tm.counter("pipeline.drain_timeout", op=op)


def map_chunks(
    thunks: Iterable[Callable[[], T]],
    finalize: Callable[[T], R],
    pipeline: bool,
    depth: Optional[int] = None,
    backend: Optional[str] = None,
    op: Optional[str] = None,
) -> Iterator[R]:
    """prefetch_thunks + consume composed: the full three-stage executor
    for entry points that own both the dispatch and the pull. `op` labels
    both stages' telemetry."""
    return consume(
        prefetch_thunks(thunks, pipeline, depth, backend, op=op),
        finalize,
        pipeline,
        depth,
        backend,
        op=op,
    )
